"""Replication v1 — synchronous WAL/manifest shipping to a standby.

The first availability axis (VERDICT r4 #7): the reference keeps data
alive through erasure/mirror blob groups and re-placement
(`blobstorage_grouptype.cpp`, DSProxy `base/blobstorage.h:884`, Hive
`hive_impl.h:158`); the v1 analog here is a MIRROR of the durable
store's mutation stream. Every Store write (WAL appends, manifest/json
replacements, portion blobs, compaction rewrites, drops) ships
SYNCHRONOUSLY to a standby before the write is acknowledged — a commit
the client saw is on both sides, so killing the primary loses nothing:
an engine booted from the standby root recovers to the last committed
plan step through the ordinary crash-recovery path (`storage/persist.py
load()` — the standby IS a crash image that happens to be remote).

Transports: `DirSink` mirrors into a local directory (tests, same-host
standby); `GrpcSink` ships to a `StandbyServer` in another process
(JSON ops, blob payloads base64 — the DCN seam). Apply is idempotent
(appends re-framed by record, json/blob replaces, missing-ok deletes).
"""

from __future__ import annotations

import base64
import json
import os
from typing import Optional

SERVICE = "ydb_tpu.Replica"


def apply_op(root: str, op: dict) -> None:
    """Apply one shipped mutation under the standby root."""
    from ydb_tpu.storage import blobfile as B
    from ydb_tpu.storage.persist import _atomic_json

    kind = op["op"]
    rel = op.get("path", "")
    if os.path.isabs(rel) or ".." in rel.split(os.sep):
        raise ValueError(f"bad replica path {rel!r}")
    path = os.path.join(root, rel)
    if kind in ("json", "wal_append", "wal_rewrite", "put_b64"):
        os.makedirs(os.path.dirname(path), exist_ok=True)
    if kind == "json":
        _atomic_json(path, op["data"])
    elif kind == "wal_append":
        B.wal_append(path, op["data"], sync=op.get("sync", True))
    elif kind == "wal_rewrite":
        B.wal_rewrite(path, op["data"])
    elif kind == "put_b64":
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(base64.b64decode(op["data"]))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    elif kind == "jsonl_append":
        # JSON-lines append (the 2PC decision-log mirror, cluster/dtx.py):
        # one fsynced line per shipped record. A re-shipped record after a
        # crash-before-ack duplicates a line; the dtx folds are per-gtx
        # last-record-wins, so duplicates are harmless.
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "ab") as f:
            f.write(json.dumps(op["data"]).encode() + b"\n")
            f.flush()
            os.fsync(f.fileno())
    elif kind == "unlink":
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
    elif kind == "rmtree":
        import shutil
        shutil.rmtree(path, ignore_errors=True)
    else:
        raise ValueError(f"unknown replica op {kind!r}")


class DirSink:
    """Standby on a local directory (same-host mirror / tests)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def ship(self, op: dict) -> None:
        apply_op(self.root, op)

    def has_catalog(self) -> bool:
        return os.path.exists(os.path.join(self.root, "catalog.json"))


class GrpcSink:
    """Standby in another process, over its Replica gRPC front."""

    def __init__(self, endpoint: str, token: str = ""):
        import grpc
        self.endpoint = endpoint
        self.token = token
        self._channel = grpc.insecure_channel(endpoint, options=[
            ("grpc.max_send_message_length", 256 << 20),
            ("grpc.max_receive_message_length", 256 << 20)])
        self._apply = self._channel.unary_unary(
            f"/{SERVICE}/Apply",
            request_serializer=lambda o: json.dumps(o).encode(),
            response_deserializer=lambda b: json.loads(b.decode()))

    def ship(self, op: dict) -> None:
        resp = self._apply({**op, "token": self.token})
        if "error" in resp:
            raise RuntimeError(f"replica apply failed: {resp['error']}")

    def has_catalog(self) -> bool:
        resp = self._apply({"op": "probe", "path": "catalog.json",
                            "token": self.token})
        if "error" in resp:
            raise RuntimeError(f"replica probe failed: {resp['error']}")
        return bool(resp.get("exists"))


class StandbyServer:
    """Receives the primary's mutation stream into a local root. Promote
    by booting `QueryEngine(data_dir=root)` — ordinary crash recovery."""

    def __init__(self, root: str, port: int = 0, token: str = ""):
        import hmac

        from concurrent import futures

        import grpc
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.applied = 0
        tok = token

        def handle_apply(request, context):
            try:
                if tok and not hmac.compare_digest(
                        str(request.get("token", "")), tok):
                    return {"error": "Unauthenticated"}
                if request.get("op") == "probe":
                    rel = request.get("path", "")
                    if os.path.isabs(rel) or ".." in rel.split(os.sep):
                        return {"error": "bad probe path"}
                    return {"ok": True, "exists": os.path.exists(
                        os.path.join(self.root, rel))}
                apply_op(self.root, request)
                self.applied += 1
                return {"ok": True}
            except Exception as e:           # noqa: BLE001 — wire boundary
                return {"error": f"{type(e).__name__}: {e}"}

        handlers = {
            "Apply": grpc.unary_unary_rpc_method_handler(
                handle_apply,
                request_deserializer=lambda b: json.loads(b.decode()),
                response_serializer=lambda o: json.dumps(o).encode()),
        }
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=4),
            options=[("grpc.max_receive_message_length", 256 << 20)])
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=None)


def make_sink(replica) -> Optional[object]:
    """Engine-facing factory: sink object | 'host:port' | directory."""
    if replica is None or hasattr(replica, "ship"):
        return replica
    if isinstance(replica, str):
        if ":" in replica and not os.sep in replica:
            return GrpcSink(replica)
        return DirSink(replica)
    raise TypeError(f"bad replica target {replica!r}")
