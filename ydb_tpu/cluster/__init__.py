from ydb_tpu.cluster.router import ShardedCluster  # noqa: F401
