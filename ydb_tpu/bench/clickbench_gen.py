"""ClickBench-style `hits` table generator (BASELINE config #5).

The reference ships the 43-query ClickBench suite with canonical results
(`ydb/public/lib/ydb_cli/commands/click_bench_queries.sql`,
`click_bench_canonical/`). The public dataset is a 100M-row web-analytics
log; this generator produces a statistically similar table (the column
subset the query suite touches): high-cardinality ids, skewed categorical
ids, zipfian search phrases/URLs, timestamps over a month.

Deterministic (seeded) — oracle results are reproducible.
"""

from __future__ import annotations

import numpy as np

from ydb_tpu.core import dtypes as dt
from ydb_tpu.core.schema import Column, Schema

HITS_SCHEMA = Schema([
    Column("WatchID", dt.DType(dt.Kind.INT64, False)),
    Column("JavaEnable", dt.DType(dt.Kind.INT64, False)),
    Column("EventTime", dt.DType(dt.Kind.INT64, False)),   # unix seconds
    Column("EventDate", dt.DType(dt.Kind.DATE32, False)),
    Column("CounterID", dt.DType(dt.Kind.INT64, False)),
    Column("ClientIP", dt.DType(dt.Kind.INT64, False)),
    Column("RegionID", dt.DType(dt.Kind.INT64, False)),
    Column("UserID", dt.DType(dt.Kind.INT64, False)),
    Column("OS", dt.DType(dt.Kind.INT64, False)),
    Column("AdvEngineID", dt.DType(dt.Kind.INT64, False)),
    Column("IsRefresh", dt.DType(dt.Kind.INT64, False)),
    Column("ResolutionWidth", dt.DType(dt.Kind.INT64, False)),
    Column("IsLink", dt.DType(dt.Kind.INT64, False)),
    Column("IsDownload", dt.DType(dt.Kind.INT64, False)),
    Column("SearchEngineID", dt.DType(dt.Kind.INT64, False)),
    Column("SearchPhrase", dt.DType(dt.Kind.STRING, False)),
    Column("MobilePhone", dt.DType(dt.Kind.INT64, False)),
    Column("MobilePhoneModel", dt.DType(dt.Kind.STRING, False)),
    Column("URL", dt.DType(dt.Kind.STRING, False)),
    Column("Title", dt.DType(dt.Kind.STRING, False)),
    Column("Referer", dt.DType(dt.Kind.STRING, False)),
    Column("UserAgent", dt.DType(dt.Kind.INT64, False)),
    Column("TraficSourceID", dt.DType(dt.Kind.INT64, False)),
    Column("DontCountHits", dt.DType(dt.Kind.INT64, False)),
    Column("URLHash", dt.DType(dt.Kind.INT64, False)),
    Column("RefererHash", dt.DType(dt.Kind.INT64, False)),
    Column("WindowClientWidth", dt.DType(dt.Kind.INT64, False)),
    Column("WindowClientHeight", dt.DType(dt.Kind.INT64, False)),
])

_WORDS = np.array(["google", "yandex", "weather", "news", "cars", "phones",
                   "games", "music", "maps", "cinema", "travel", "recipes",
                   "football", "crypto", "python", "shoes", "hotels", ""])
_MODELS = np.array(["", "", "", "iPhone", "Galaxy", "Pixel", "Nokia"])
_REF_HOSTS = np.array(["google.com", "www.yandex.ru", "news.site",
                       "example.com", "forum.example.org", "blog.io"])


def content_hash(s: str) -> int:
    """Deterministic content-addressed 63-bit string hash (URLHash /
    RefererHash columns — the real dataset carries precomputed sipHash-like
    url hashes; content addressing keeps query constants stable)."""
    import hashlib
    return int.from_bytes(hashlib.sha1(s.encode()).digest()[:8],
                          "little") >> 1


def gen_hits(n_rows: int, seed: int = 20260729,
             url_cardinality: int = 0) -> dict:
    """`url_cardinality` > 0: URL and Referer gain random path suffixes
    drawn from that many values (distinct combinations multiply with the
    word pools) — the real dataset's URL column is near-unique, the
    dictionary-degeneracy case the string lane must survive (VERDICT r3
    item 6)."""
    rng = np.random.default_rng(seed)
    n = n_rows
    zipf = lambda k, size: np.minimum(  # noqa: E731
        rng.zipf(1.5, size), k) - 1
    day0 = 19530                       # 2023-06-22
    date = day0 + rng.integers(0, 31, n)
    phrase_ix = zipf(len(_WORDS), n)
    # ~60% empty search phrases, like the real data
    phrase_ix = np.where(rng.random(n) < 0.6, len(_WORDS) - 1, phrase_ix)
    phrases = _WORDS[phrase_ix]
    two = _WORDS[zipf(len(_WORDS) - 1, n)]
    phrases = np.where(
        (phrases != "") & (rng.random(n) < 0.4),
        np.char.add(np.char.add(phrases.astype(str), " "), two.astype(str)),
        phrases)
    urls = np.char.add("http://example.com/",
                       _WORDS[zipf(len(_WORDS) - 1, n)].astype(str))
    if url_cardinality:
        suffix = (rng.integers(0, url_cardinality, n)).astype("U10")
        urls = np.char.add(np.char.add(urls, "/p"), suffix)
    titles = np.char.add(np.char.capitalize(
        _WORDS[zipf(len(_WORDS) - 1, n)].astype(str)), " page")
    ref_host = _REF_HOSTS[zipf(len(_REF_HOSTS), n)]
    ref_path = _WORDS[zipf(len(_WORDS) - 1, n)]
    referers = np.char.add(np.char.add(np.char.add(
        "https://", ref_host.astype(str)), "/"), ref_path.astype(str))
    if url_cardinality:
        rsuf = (rng.integers(0, url_cardinality, n)).astype("U10")
        referers = np.char.add(np.char.add(referers, "/r"), rsuf)
    referers = np.where(rng.random(n) < 0.4, "", referers)
    def _hashes(arr):
        uniq, inv = np.unique(arr, return_inverse=True)
        return np.array([content_hash(u) for u in uniq],
                        dtype=np.int64)[inv]
    url_hashes = _hashes(urls)
    ref_hashes = _hashes(referers)
    return {
        "WatchID": rng.integers(1, 1 << 60, n),
        "JavaEnable": rng.integers(0, 2, n),
        "EventTime": (date.astype(np.int64) * 86400
                      + rng.integers(0, 86400, n)),
        "EventDate": date.astype(np.int32),
        "CounterID": zipf(8000, n) + 1,
        "ClientIP": rng.integers(0, 1 << 31, n),
        "RegionID": zipf(5000, n) + 1,
        "UserID": rng.integers(1, n // 3 + 2, n),
        "OS": zipf(80, n),
        "AdvEngineID": np.where(rng.random(n) < 0.95, 0, zipf(60, n) + 1),
        "IsRefresh": (rng.random(n) < 0.13).astype(np.int64),
        "ResolutionWidth": rng.choice(
            [0, 1024, 1280, 1366, 1440, 1536, 1600, 1920, 2560], n),
        "IsLink": (rng.random(n) < 0.07).astype(np.int64),
        "IsDownload": (rng.random(n) < 0.02).astype(np.int64),
        "SearchEngineID": np.where(phrases == "", 0, zipf(90, n) + 1),
        "SearchPhrase": phrases.astype(object),
        "MobilePhone": zipf(9, n),
        "MobilePhoneModel": _MODELS[zipf(len(_MODELS), n)].astype(object),
        "URL": urls.astype(object),
        "Title": titles.astype(object),
        "Referer": referers.astype(object),
        "UserAgent": zipf(80, n) + 1,
        "TraficSourceID": rng.integers(-1, 10, n),
        "DontCountHits": (rng.random(n) < 0.05).astype(np.int64),
        "URLHash": url_hashes,
        "RefererHash": ref_hashes,
        "WindowClientWidth": rng.choice(
            [0, 1024, 1280, 1366, 1440, 1920], n),
        "WindowClientHeight": rng.choice([0, 600, 720, 768, 900, 1080], n),
    }


def load_hits(catalog, n_rows: int = 100_000, shards: int = 1,
              portion_rows: int = 1 << 20, seed: int = 20260729,
              url_cardinality: int = 0) -> dict:
    """Create and fill the `hits` table; returns the raw numpy arrays."""
    import pandas as pd

    from ydb_tpu.storage.mvcc import WriteVersion
    raw = gen_hits(n_rows, seed, url_cardinality=url_cardinality)
    table = catalog.create_table("hits", HITS_SCHEMA, ["WatchID"],
                                 shards=shards, portion_rows=portion_rows)
    table.bulk_upsert(pd.DataFrame(raw), WriteVersion(1, 1))
    return raw
