"""Deterministic TPC-H data generator (numpy, scale-factor parametrized).

Plays the role of the reference's `ydb workload tpch init --scale N` data
population (`ydb/public/lib/ydb_cli/commands/tpch.h:9-66`,
`ydb/library/workload/tpch/`): all eight tables with the standard row-count
scaling, spec-shaped value domains (dates 1992-01-01..1998-12-01, the Q1
returnflag/linestatus alphabet, per-column distributions close enough that
the benchmark queries exercise the same selectivities), and referential
integrity between keys. Decimals are Double, matching the reference's own
TPC-H schema choice (`tpch_schema.sql:4`).

Not a bit-exact dbgen: query *results* are validated against a pandas
oracle over the same generated data, and canonical-result pinning happens
at that layer (analog of `click_bench_canonical/`).
"""

from __future__ import annotations

import numpy as np

from ydb_tpu.core import dtypes as dt
from ydb_tpu.core.schema import Column, Schema

EPOCH_1992 = 8035     # days from 1970-01-01 to 1992-01-01
EPOCH_1998_08 = 10439  # days to 1998-08-01
DATE_SPAN = 2526      # 1992-01-01 .. 1998-12-01


def date32(y: int, m: int, d: int) -> int:
    """Civil date → days since epoch (host-side mirror of ops/kernels _civil)."""
    yy = y - (1 if m <= 2 else 0)
    era = (yy if yy >= 0 else yy - 399) // 400
    yoe = yy - era * 400
    mp = m + (9 if m <= 2 else -3)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


_S = lambda: dt.DType(dt.Kind.STRING, nullable=False)  # noqa: E731
_I64 = dt.DType(dt.Kind.INT64, nullable=False)
_I32 = dt.DType(dt.Kind.INT32, nullable=False)
_F64 = dt.DType(dt.Kind.FLOAT64, nullable=False)
_D32 = dt.DType(dt.Kind.DATE32, nullable=False)


TPCH_SCHEMAS: dict[str, tuple[Schema, list[str]]] = {
    "lineitem": (Schema([
        Column("l_orderkey", _I64), Column("l_partkey", _I64),
        Column("l_suppkey", _I64), Column("l_linenumber", _I32),
        Column("l_quantity", _F64), Column("l_extendedprice", _F64),
        Column("l_discount", _F64), Column("l_tax", _F64),
        Column("l_returnflag", _S()), Column("l_linestatus", _S()),
        Column("l_shipdate", _D32), Column("l_commitdate", _D32),
        Column("l_receiptdate", _D32), Column("l_shipinstruct", _S()),
        Column("l_shipmode", _S()), Column("l_comment", _S()),
    ]), ["l_orderkey", "l_linenumber"]),
    "orders": (Schema([
        Column("o_orderkey", _I64), Column("o_custkey", _I64),
        Column("o_orderstatus", _S()), Column("o_totalprice", _F64),
        Column("o_orderdate", _D32), Column("o_orderpriority", _S()),
        Column("o_clerk", _S()), Column("o_shippriority", _I32),
        Column("o_comment", _S()),
    ]), ["o_orderkey"]),
    "customer": (Schema([
        Column("c_custkey", _I64), Column("c_name", _S()),
        Column("c_address", _S()), Column("c_nationkey", _I64),
        Column("c_phone", _S()), Column("c_acctbal", _F64),
        Column("c_mktsegment", _S()), Column("c_comment", _S()),
    ]), ["c_custkey"]),
    "part": (Schema([
        Column("p_partkey", _I64), Column("p_name", _S()),
        Column("p_mfgr", _S()), Column("p_brand", _S()),
        Column("p_type", _S()), Column("p_size", _I32),
        Column("p_container", _S()), Column("p_retailprice", _F64),
        Column("p_comment", _S()),
    ]), ["p_partkey"]),
    "supplier": (Schema([
        Column("s_suppkey", _I64), Column("s_name", _S()),
        Column("s_address", _S()), Column("s_nationkey", _I64),
        Column("s_phone", _S()), Column("s_acctbal", _F64),
        Column("s_comment", _S()),
    ]), ["s_suppkey"]),
    "partsupp": (Schema([
        Column("ps_partkey", _I64), Column("ps_suppkey", _I64),
        Column("ps_availqty", _I32), Column("ps_supplycost", _F64),
        Column("ps_comment", _S()),
    ]), ["ps_partkey", "ps_suppkey"]),
    "nation": (Schema([
        Column("n_nationkey", _I64), Column("n_name", _S()),
        Column("n_regionkey", _I64), Column("n_comment", _S()),
    ]), ["n_nationkey"]),
    "region": (Schema([
        Column("r_regionkey", _I64), Column("r_name", _S()),
        Column("r_comment", _S()),
    ]), ["r_regionkey"]),
}

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
CONTAINERS = [f"{a} {b}" for a in ["SM", "LG", "MED", "JUMBO", "WRAP"]
              for b in ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]]
TYPE_1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
P_NAME_WORDS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
                "black", "blanched", "blue", "blush", "brown", "burlywood",
                "burnished", "chartreuse", "chiffon", "chocolate", "coral",
                "cornflower", "cornsilk", "cream", "cyan", "dark", "deep",
                "dim", "dodger", "drab", "firebrick", "floral", "forest",
                "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey",
                "honeydew", "hot", "hotpink", "indian", "ivory", "khaki",
                "lace", "lavender", "lawn", "lemon", "light", "lime", "linen"]
COMMENT_WORDS = np.array([
    "carefully", "quickly", "furiously", "slyly", "blithely", "ironic",
    "final", "pending", "regular", "express", "special", "bold", "even",
    "silent", "unusual", "deposits", "requests", "packages", "accounts",
    "instructions", "theodolites", "pinto", "beans", "foxes", "ideas",
    "platelets", "dependencies", "excuses", "asymptotes"], dtype=object)


class TpchData:
    """Generated tables as dicts of numpy arrays (strings = object arrays).

    `fast_strings` (auto-on at sf >= 0.5): per-row Python string building
    is replaced by indexing into pre-built pools (comments, clerks, part
    names) and vectorized np.char construction (phones, entity names) —
    the difference between minutes and hours at SF10+. Value domains and
    query selectivities keep the same shape; oracles recompute over the
    same data either way."""

    def __init__(self, sf: float, seed: int = 19920101,
                 fast_strings: bool | None = None):
        self.sf = sf
        self.fast = (sf >= 0.5) if fast_strings is None else fast_strings
        self.rng = np.random.default_rng(seed)
        self.tables: dict[str, dict[str, np.ndarray]] = {}
        self._generate()

    # -- helpers -----------------------------------------------------------

    def _comment_exact(self, n: int, lo: int, hi: int) -> np.ndarray:
        k = self.rng.integers(lo, hi, n)
        idx = self.rng.integers(0, len(COMMENT_WORDS), (n, hi))
        words = COMMENT_WORDS[idx]
        return np.array([" ".join(words[i, :k[i]]) for i in range(n)], dtype=object)

    def _comment(self, n: int, lo: int = 2, hi: int = 6) -> np.ndarray:
        if not self.fast or n <= 4096:
            return self._comment_exact(n, lo, hi)
        pool = self._comment_exact(4096, lo, hi)
        return pool[self.rng.integers(0, len(pool), n)]

    def _choice(self, options: list[str], n: int) -> np.ndarray:
        return np.array(options, dtype=object)[self.rng.integers(0, len(options), n)]

    def _phone(self, nk: np.ndarray) -> np.ndarray:
        r = self.rng
        a = r.integers(100, 1000, len(nk))
        b = r.integers(100, 1000, len(nk))
        c = r.integers(1000, 10000, len(nk))
        if self.fast:
            parts = [(10 + nk).astype("U2"), a.astype("U3"),
                     b.astype("U3"), c.astype("U4")]
            out = parts[0]
            for p in parts[1:]:
                out = np.char.add(np.char.add(out, "-"), p)
            return out.astype(object)
        return np.array([f"{10 + k}-{x}-{y}-{z}"
                         for k, x, y, z in zip(nk, a, b, c)], dtype=object)

    def _numbered(self, prefix: str, ids: np.ndarray) -> np.ndarray:
        """'Prefix#000000001'-style names, vectorized in fast mode."""
        if self.fast:
            digits = np.char.zfill(ids.astype(np.int64).astype("U10"), 9)
            return np.char.add(prefix + "#", digits).astype(object)
        return np.array([f"{prefix}#{i:09d}" for i in ids], dtype=object)

    # -- generation --------------------------------------------------------

    def _generate(self):
        sf, rng = self.sf, self.rng
        n_part = max(1, int(200_000 * sf))
        n_supp = max(1, int(10_000 * sf))
        n_cust = max(1, int(150_000 * sf))
        n_ord = max(1, int(1_500_000 * sf))

        # region / nation
        self.tables["region"] = {
            "r_regionkey": np.arange(5, dtype=np.int64),
            "r_name": np.array(REGIONS, dtype=object),
            "r_comment": self._comment(5),
        }
        nk = np.arange(len(NATIONS), dtype=np.int64)
        self.tables["nation"] = {
            "n_nationkey": nk,
            "n_name": np.array([n for n, _ in NATIONS], dtype=object),
            "n_regionkey": np.array([r for _, r in NATIONS], dtype=np.int64),
            "n_comment": self._comment(len(NATIONS)),
        }

        # supplier
        s_nation = rng.integers(0, len(NATIONS), n_supp).astype(np.int64)
        self.tables["supplier"] = {
            "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
            "s_name": self._numbered("Supplier",
                                     np.arange(1, n_supp + 1)),
            "s_address": self._comment(n_supp, 1, 3),
            "s_nationkey": s_nation,
            "s_phone": self._phone(s_nation),
            "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_supp), 2),
            "s_comment": self._comment(n_supp),
        }

        # part
        pn1 = self._choice(TYPE_1, n_part)
        pn2 = self._choice(TYPE_2, n_part)
        pn3 = self._choice(TYPE_3, n_part)
        p_type = np.array([f"{a} {b} {c}" for a, b, c in zip(pn1, pn2, pn3)],
                          dtype=object)
        brand_m = rng.integers(1, 6, n_part)
        brand_n = rng.integers(1, 6, n_part)
        if self.fast and n_part > (1 << 16):
            pool_idx = rng.integers(0, len(P_NAME_WORDS), (1 << 16, 5))
            pool = np.array(
                [" ".join(P_NAME_WORDS[j] for j in pool_idx[i])
                 for i in range(1 << 16)], dtype=object)
            p_name = pool[rng.integers(0, len(pool), n_part)]
        else:
            name_idx = rng.integers(0, len(P_NAME_WORDS), (n_part, 5))
            p_name = np.array(
                [" ".join(P_NAME_WORDS[j] for j in name_idx[i])
                 for i in range(n_part)], dtype=object)
        self.tables["part"] = {
            "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
            "p_name": p_name,
            "p_mfgr": np.array([f"Manufacturer#{m}" for m in brand_m], dtype=object),
            "p_brand": np.array([f"Brand#{m}{n}" for m, n in zip(brand_m, brand_n)],
                                dtype=object),
            "p_type": p_type,
            "p_size": rng.integers(1, 51, n_part).astype(np.int32),
            "p_container": self._choice(CONTAINERS, n_part),
            "p_retailprice": np.round(
                900 + (np.arange(1, n_part + 1) % 1000) / 10
                + 100 * (np.arange(1, n_part + 1) % 10), 2),
            "p_comment": self._comment(n_part, 1, 3),
        }

        # partsupp: 4 suppliers per part
        ps_part = np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 4)
        ps_supp = np.empty(4 * n_part, dtype=np.int64)
        for j in range(4):
            ps_supp[j::4] = 1 + (np.arange(n_part) + j * (n_supp // 4 + 1)) % n_supp
        self.tables["partsupp"] = {
            "ps_partkey": ps_part,
            "ps_suppkey": ps_supp,
            "ps_availqty": rng.integers(1, 10_000, 4 * n_part).astype(np.int32),
            "ps_supplycost": np.round(rng.uniform(1.0, 1000.0, 4 * n_part), 2),
            "ps_comment": self._comment(4 * n_part),
        }

        # customer
        c_nation = rng.integers(0, len(NATIONS), n_cust).astype(np.int64)
        self.tables["customer"] = {
            "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
            "c_name": self._numbered("Customer",
                                     np.arange(1, n_cust + 1)),
            "c_address": self._comment(n_cust, 1, 3),
            "c_nationkey": c_nation,
            "c_phone": self._phone(c_nation),
            "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, n_cust), 2),
            "c_mktsegment": self._choice(SEGMENTS, n_cust),
            "c_comment": self._comment(n_cust),
        }

        # orders (1/3 of customers have no orders, per spec)
        cust_pool = np.arange(1, n_cust + 1, dtype=np.int64)
        cust_pool = cust_pool[cust_pool % 3 != 0] if n_cust >= 3 else cust_pool
        o_cust = cust_pool[rng.integers(0, len(cust_pool), n_ord)]
        o_date = (EPOCH_1992 + rng.integers(0, DATE_SPAN - 151, n_ord)).astype(np.int32)
        self.tables["orders"] = {
            "o_orderkey": np.arange(1, n_ord + 1, dtype=np.int64),
            "o_custkey": o_cust,
            "o_orderstatus": np.full(n_ord, "O", dtype=object),  # fixed below
            "o_totalprice": np.zeros(n_ord),                     # fixed below
            "o_orderdate": o_date,
            "o_orderpriority": self._choice(PRIORITIES, n_ord),
            "o_clerk": self._numbered(
                "Clerk", rng.integers(1, max(2, int(1000 * sf)), n_ord)),
            "o_shippriority": np.zeros(n_ord, dtype=np.int32),
            "o_comment": self._comment(n_ord),
        }

        # lineitem: 1-7 lines per order
        lines_per = rng.integers(1, 8, n_ord)
        n_li = int(lines_per.sum())
        l_order = np.repeat(self.tables["orders"]["o_orderkey"], lines_per)
        l_odate = np.repeat(o_date, lines_per)
        starts = np.concatenate([[0], np.cumsum(lines_per)[:-1]])
        l_lineno = (np.arange(n_li) - np.repeat(starts, lines_per) + 1).astype(np.int32)

        l_part = rng.integers(1, n_part + 1, n_li).astype(np.int64)
        # supplier chosen among the 4 for the part (referential integrity)
        which = rng.integers(0, 4, n_li)
        l_supp = 1 + ((l_part - 1) + which * (n_supp // 4 + 1)) % n_supp
        qty = rng.integers(1, 51, n_li).astype(np.float64)
        retail = self.tables["part"]["p_retailprice"][l_part - 1]
        eprice = np.round(qty * retail, 2)
        disc = rng.integers(0, 11, n_li) / 100.0
        tax = rng.integers(0, 9, n_li) / 100.0
        ship = (l_odate + rng.integers(1, 122, n_li)).astype(np.int32)
        commit = (l_odate + rng.integers(30, 91, n_li)).astype(np.int32)
        receipt = (ship + rng.integers(1, 31, n_li)).astype(np.int32)
        cutoff = date32(1995, 6, 17)
        rflag = np.where(receipt <= cutoff,
                         np.where(rng.random(n_li) < 0.5, "R", "A"), "N").astype(object)
        lstatus = np.where(ship > cutoff, "O", "F").astype(object)
        self.tables["lineitem"] = {
            "l_orderkey": l_order,
            "l_partkey": l_part,
            "l_suppkey": l_supp,
            "l_linenumber": l_lineno,
            "l_quantity": qty,
            "l_extendedprice": eprice,
            "l_discount": disc,
            "l_tax": tax,
            "l_returnflag": rflag,
            "l_linestatus": lstatus,
            "l_shipdate": ship,
            "l_commitdate": commit,
            "l_receiptdate": receipt,
            "l_shipinstruct": self._choice(INSTRUCTS, n_li),
            "l_shipmode": self._choice(SHIPMODES, n_li),
            "l_comment": self._comment(n_li),
        }

        # back-fill order status/totalprice from lineitems
        gross = eprice * (1 - disc) * (1 + tax)
        totals = np.zeros(n_ord + 1)
        np.add.at(totals, l_order, gross)
        self.tables["orders"]["o_totalprice"] = np.round(totals[1:], 2)
        all_f = np.ones(n_ord + 1, dtype=bool)
        any_f = np.zeros(n_ord + 1, dtype=bool)
        lf = lstatus == "F"
        np.logical_and.at(all_f, l_order, lf)
        np.logical_or.at(any_f, l_order, lf)
        st = np.where(all_f[1:], "F", np.where(any_f[1:], "P", "O")).astype(object)
        self.tables["orders"]["o_orderstatus"] = st


def load_tpch(catalog, sf: float = 0.01, shards: int = 1, seed: int = 19920101,
              portion_rows: int = 1 << 20):
    """Generate TPC-H data and load it into a catalog of ColumnTables."""
    from ydb_tpu.core.block import HostBlock
    from ydb_tpu.storage.mvcc import WriteVersion

    data = TpchData(sf, seed)
    for tname, (schema, keys) in TPCH_SCHEMAS.items():
        small = tname in ("nation", "region")
        table = catalog.create_table(
            tname, schema, keys, shards=1 if small else shards,
            portion_rows=portion_rows)
        arrays = data.tables[tname]
        n = len(arrays[schema.names[0]])
        enc = {}
        for c in schema:
            a = arrays[c.name]
            if c.dtype.is_string:
                enc[c.name] = table.dictionaries[c.name].encode_bulk(
                    np.asarray(a, dtype=object))
            else:
                enc[c.name] = np.asarray(a, dtype=c.dtype.np)
        block = HostBlock.from_arrays(schema, enc,
                                      dictionaries=dict(table.dictionaries))
        writes = table.write(block)
        table.commit(writes, WriteVersion(1, 1))
        table.indexate()
    return data
