"""TPC-DS table subset generator (BASELINE config #4).

The reference carries the full 99-query TPC-DS templates
(`ydb/library/benchmarks/queries/tpcds/`). This generator produces the
retail-star subset that the supported query shapes touch — store_sales
(fact), date_dim, item, customer, store — with TPC-DS-like domains
(brands/categories/manufacturers, a 5-year calendar). Deterministic.
"""

from __future__ import annotations

import numpy as np

from ydb_tpu.core import dtypes as dt
from ydb_tpu.core.schema import Column, Schema


def _i64(name):
    return Column(name, dt.DType(dt.Kind.INT64, False))


def _f64(name):
    return Column(name, dt.DType(dt.Kind.FLOAT64, False))


def _s(name):
    return Column(name, dt.DType(dt.Kind.STRING, False))


SCHEMAS = {
    "date_dim": (Schema([_i64("d_date_sk"), _i64("d_year"), _i64("d_moy"),
                         _i64("d_dom"), _i64("d_week_seq"),
                         _i64("d_qoy"), _i64("d_dow"),
                         _s("d_day_name")]), ["d_date_sk"]),
    "item": (Schema([_i64("i_item_sk"), _s("i_item_id"),
                     _i64("i_brand_id"), _s("i_brand"),
                     _i64("i_class_id"), _s("i_class"),
                     _i64("i_category_id"), _s("i_category"),
                     _i64("i_manufact_id"), _s("i_manufact"),
                     _i64("i_manager_id"),
                     _f64("i_current_price")]), ["i_item_sk"]),
    "store": (Schema([_i64("s_store_sk"), _s("s_store_name"),
                      _s("s_state"), _i64("s_zip_num")]), ["s_store_sk"]),
    "customer": (Schema([_i64("c_customer_sk"), _i64("c_current_addr_sk"),
                         _i64("c_current_cdemo_sk"),
                         _s("c_first_name"), _s("c_last_name"),
                         _i64("c_birth_year")]), ["c_customer_sk"]),
    "customer_address": (Schema([_i64("ca_address_sk"), _s("ca_state"),
                                 _i64("ca_zip_num")]), ["ca_address_sk"]),
    "customer_demographics": (Schema([_i64("cd_demo_sk"), _s("cd_gender"),
                                      _s("cd_marital_status"),
                                      _s("cd_education_status")]),
                              ["cd_demo_sk"]),
    "household_demographics": (Schema([_i64("hd_demo_sk"),
                                       _i64("hd_dep_count"),
                                       _i64("hd_vehicle_count")]),
                               ["hd_demo_sk"]),
    "time_dim": (Schema([_i64("t_time_sk"), _i64("t_hour"),
                         _i64("t_minute")]), ["t_time_sk"]),
    "promotion": (Schema([_i64("p_promo_sk"), _s("p_channel_email"),
                          _s("p_channel_event")]), ["p_promo_sk"]),
    "store_sales": (Schema([_i64("ss_ticket_sk"), _i64("ss_sold_date_sk"),
                            _i64("ss_sold_time_sk"),
                            _i64("ss_item_sk"), _i64("ss_customer_sk"),
                            _i64("ss_cdemo_sk"), _i64("ss_hdemo_sk"),
                            _i64("ss_promo_sk"),
                            _i64("ss_store_sk"), _i64("ss_quantity"),
                            _f64("ss_sales_price"), _f64("ss_list_price"),
                            _f64("ss_coupon_amt"),
                            _f64("ss_ext_sales_price"),
                            _f64("ss_ext_discount_amt"),
                            _f64("ss_ext_wholesale_cost"),
                            _f64("ss_net_profit")]), ["ss_ticket_sk"]),
    "web_sales": (Schema([_i64("ws_order_sk"), _i64("ws_sold_date_sk"),
                          _i64("ws_sold_time_sk"),
                          _i64("ws_ship_date_sk"),
                          _i64("ws_item_sk"),
                          _i64("ws_bill_customer_sk"),
                          _i64("ws_bill_addr_sk"),
                          _i64("ws_ship_hdemo_sk"),
                          _i64("ws_warehouse_sk"), _i64("ws_promo_sk"),
                          _i64("ws_quantity"),
                          _f64("ws_sales_price"), _f64("ws_list_price"),
                          _f64("ws_ext_sales_price"),
                          _f64("ws_ext_discount_amt"),
                          _f64("ws_net_profit")]), ["ws_order_sk"]),
    "catalog_sales": (Schema([_i64("cs_order_sk"), _i64("cs_sold_date_sk"),
                              _i64("cs_sold_time_sk"),
                              _i64("cs_ship_date_sk"),
                              _i64("cs_item_sk"),
                              _i64("cs_bill_customer_sk"),
                              _i64("cs_bill_cdemo_sk"),
                              _i64("cs_promo_sk"),
                              _i64("cs_warehouse_sk"),
                              _i64("cs_quantity"),
                              _f64("cs_sales_price"),
                              _f64("cs_list_price"),
                              _f64("cs_coupon_amt"),
                              _f64("cs_ext_sales_price"),
                              _f64("cs_ext_discount_amt"),
                              _f64("cs_net_profit")]), ["cs_order_sk"]),
    "store_returns": (Schema([_i64("sr_return_sk"),
                              _i64("sr_returned_date_sk"),
                              _i64("sr_item_sk"), _i64("sr_customer_sk"),
                              _i64("sr_cdemo_sk"),
                              _i64("sr_ticket_sk"),
                              _i64("sr_return_quantity"),
                              _f64("sr_return_amt"),
                              _f64("sr_net_loss")]), ["sr_return_sk"]),
    "web_returns": (Schema([_i64("wr_return_sk"),
                            _i64("wr_returned_date_sk"),
                            _i64("wr_item_sk"), _i64("wr_order_sk"),
                            _i64("wr_returning_customer_sk"),
                            _i64("wr_refunded_cdemo_sk"),
                            _i64("wr_return_quantity"),
                            _f64("wr_return_amt"),
                            _f64("wr_fee")]), ["wr_return_sk"]),
    "warehouse": (Schema([_i64("w_warehouse_sk"), _s("w_warehouse_name"),
                          _s("w_state")]), ["w_warehouse_sk"]),
    "inventory": (Schema([_i64("inv_row_sk"), _i64("inv_date_sk"),
                          _i64("inv_item_sk"), _i64("inv_warehouse_sk"),
                          _i64("inv_quantity_on_hand")]), ["inv_row_sk"]),
}

_CATS = np.array(["Books", "Home", "Electronics", "Jewelry", "Sports",
                  "Music", "Women", "Men", "Children", "Shoes"])
_DAYS = np.array(["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
                  "Friday", "Saturday"])
_STATES = np.array(["TN", "CA", "TX", "OH", "GA", "WA", "NY"])


def gen_tpcds(sf: float = 0.01, seed: int = 20260730) -> dict:
    rng = np.random.default_rng(seed)
    tables: dict = {}

    n_dates = 365 * 5
    d_sk = np.arange(1, n_dates + 1)
    yr = 1998 + (d_sk - 1) // 365
    doy = (d_sk - 1) % 365
    moy = doy // 31 + 1
    tables["date_dim"] = {
        "d_date_sk": d_sk, "d_year": yr, "d_moy": moy,
        "d_dom": doy % 31 + 1, "d_week_seq": (d_sk - 1) // 7 + 1,
        "d_qoy": (moy - 1) // 3 + 1, "d_dow": d_sk % 7,
        "d_day_name": _DAYS[d_sk % 7].astype(object)}

    n_item = max(200, int(1800 * sf * 10))
    i_sk = np.arange(1, n_item + 1)
    brand_id = rng.integers(1, 100, n_item) * 100 + rng.integers(1, 10,
                                                                 n_item)
    cat_ix = rng.integers(0, len(_CATS), n_item)
    manu = rng.integers(1, 100, n_item)
    class_id = rng.integers(1, 17, n_item)
    tables["item"] = {
        "i_item_sk": i_sk,
        "i_item_id": np.array([f"AAAA{k:012d}" for k in i_sk], object),
        "i_brand_id": brand_id,
        "i_brand": np.array([f"brand#{b}" for b in brand_id], object),
        "i_class_id": class_id,
        "i_class": np.array([f"class#{c}" for c in class_id], object),
        "i_category_id": cat_ix + 1,
        "i_category": _CATS[cat_ix].astype(object),
        "i_manufact_id": manu,
        "i_manufact": np.array([f"manu#{m}" for m in manu], object),
        "i_manager_id": rng.integers(1, 100, n_item),
        "i_current_price": (rng.random(n_item) * 100).round(2)}

    n_store = 12
    tables["store"] = {
        "s_store_sk": np.arange(1, n_store + 1),
        "s_store_name": np.array([f"store_{i}" for i in range(n_store)],
                                 object),
        "s_state": _STATES[rng.integers(0, len(_STATES), n_store)]
        .astype(object),
        "s_zip_num": rng.integers(10000, 10040, n_store)}

    n_addr = max(300, int(50_000 * sf))
    tables["customer_address"] = {
        "ca_address_sk": np.arange(1, n_addr + 1),
        "ca_state": _STATES[rng.integers(0, len(_STATES), n_addr)]
        .astype(object),
        "ca_zip_num": rng.integers(10000, 10040, n_addr)}

    n_cdemo = 7 * 6 * 4          # gender x marital x education grid
    n_cust = max(500, int(100_000 * sf))
    tables["customer"] = {
        "c_customer_sk": np.arange(1, n_cust + 1),
        "c_current_addr_sk": rng.integers(1, n_addr + 1, n_cust),
        "c_current_cdemo_sk": rng.integers(1, n_cdemo + 1, n_cust),
        "c_first_name": np.array([f"fn{i % 997}" for i in range(n_cust)],
                                 object),
        "c_last_name": np.array([f"ln{i % 499}" for i in range(n_cust)],
                                object),
        "c_birth_year": rng.integers(1930, 2005, n_cust)}

    # cross-joined demographic/time/promotion dimensions (TPC-DS keeps
    # these small and dense)

    genders = np.array(["M", "F"])
    marital = np.array(["S", "M", "D", "W", "U"])
    edu = np.array(["Primary", "Secondary", "College", "2 yr Degree",
                    "4 yr Degree", "Advanced Degree", "Unknown"])
    cd_sk = np.arange(1, n_cdemo + 1)
    tables["customer_demographics"] = {
        "cd_demo_sk": cd_sk,
        "cd_gender": genders[cd_sk % 2].astype(object),
        "cd_marital_status": marital[cd_sk % 5].astype(object),
        "cd_education_status": edu[cd_sk % 7].astype(object)}

    n_hdemo = 40
    hd_sk = np.arange(1, n_hdemo + 1)
    tables["household_demographics"] = {
        "hd_demo_sk": hd_sk, "hd_dep_count": hd_sk % 10,
        "hd_vehicle_count": hd_sk % 5}

    n_time = 24 * 60
    t_sk = np.arange(1, n_time + 1)
    tables["time_dim"] = {
        "t_time_sk": t_sk, "t_hour": (t_sk - 1) // 60,
        "t_minute": (t_sk - 1) % 60}

    n_promo = 30
    p_sk = np.arange(1, n_promo + 1)
    yn = np.array(["Y", "N"])
    tables["promotion"] = {
        "p_promo_sk": p_sk,
        "p_channel_email": yn[p_sk % 2].astype(object),
        "p_channel_event": yn[(p_sk // 2) % 2].astype(object)}

    n_ss = max(2000, int(2_880_000 * sf))
    tables["store_sales"] = {
        "ss_ticket_sk": np.arange(1, n_ss + 1),
        "ss_sold_date_sk": rng.integers(1, n_dates + 1, n_ss),
        "ss_sold_time_sk": rng.integers(1, n_time + 1, n_ss),
        "ss_item_sk": rng.integers(1, n_item + 1, n_ss),
        "ss_customer_sk": rng.integers(1, n_cust + 1, n_ss),
        "ss_cdemo_sk": rng.integers(1, n_cdemo + 1, n_ss),
        "ss_hdemo_sk": rng.integers(1, n_hdemo + 1, n_ss),
        "ss_promo_sk": rng.integers(1, n_promo + 1, n_ss),
        "ss_store_sk": rng.integers(1, n_store + 1, n_ss),
        "ss_quantity": rng.integers(1, 100, n_ss),
        "ss_sales_price": (rng.random(n_ss) * 200).round(2),
        "ss_list_price": (rng.random(n_ss) * 250).round(2),
        "ss_coupon_amt": (rng.random(n_ss) * 50).round(2),
        "ss_ext_sales_price": (rng.random(n_ss) * 2000).round(2),
        "ss_ext_discount_amt": (rng.random(n_ss) * 120).round(2),
        "ss_ext_wholesale_cost": (rng.random(n_ss) * 900).round(2),
        "ss_net_profit": ((rng.random(n_ss) - 0.3) * 1000).round(2)}

    n_wh = 6
    tables["warehouse"] = {
        "w_warehouse_sk": np.arange(1, n_wh + 1),
        "w_warehouse_name": np.array([f"wh_{i}" for i in range(n_wh)],
                                     object),
        "w_state": _STATES[rng.integers(0, len(_STATES), n_wh)]
        .astype(object)}

    n_ws = max(800, int(720_000 * sf))
    ws_sold = rng.integers(1, n_dates + 1, n_ws)
    tables["web_sales"] = {
        "ws_order_sk": np.arange(1, n_ws + 1),
        "ws_sold_date_sk": ws_sold,
        "ws_sold_time_sk": rng.integers(1, n_time + 1, n_ws),
        "ws_ship_date_sk": np.minimum(ws_sold + rng.integers(1, 120, n_ws),
                                      n_dates),
        "ws_item_sk": rng.integers(1, n_item + 1, n_ws),
        "ws_bill_customer_sk": rng.integers(1, n_cust + 1, n_ws),
        "ws_bill_addr_sk": rng.integers(1, n_addr + 1, n_ws),
        "ws_ship_hdemo_sk": rng.integers(1, n_hdemo + 1, n_ws),
        "ws_warehouse_sk": rng.integers(1, n_wh + 1, n_ws),
        "ws_promo_sk": rng.integers(1, n_promo + 1, n_ws),
        "ws_quantity": rng.integers(1, 100, n_ws),
        "ws_sales_price": (rng.random(n_ws) * 200).round(2),
        "ws_list_price": (rng.random(n_ws) * 250).round(2),
        "ws_ext_sales_price": (rng.random(n_ws) * 2000).round(2),
        "ws_ext_discount_amt": (rng.random(n_ws) * 120).round(2),
        "ws_net_profit": ((rng.random(n_ws) - 0.3) * 1000).round(2)}

    n_cs = max(1200, int(1_440_000 * sf))
    cs_sold = rng.integers(1, n_dates + 1, n_cs)
    tables["catalog_sales"] = {
        "cs_order_sk": np.arange(1, n_cs + 1),
        "cs_sold_date_sk": cs_sold,
        "cs_sold_time_sk": rng.integers(1, n_time + 1, n_cs),
        "cs_ship_date_sk": np.minimum(cs_sold + rng.integers(1, 120, n_cs),
                                      n_dates),
        "cs_item_sk": rng.integers(1, n_item + 1, n_cs),
        "cs_bill_customer_sk": rng.integers(1, n_cust + 1, n_cs),
        "cs_bill_cdemo_sk": rng.integers(1, n_cdemo + 1, n_cs),
        "cs_promo_sk": rng.integers(1, n_promo + 1, n_cs),
        "cs_warehouse_sk": rng.integers(1, n_wh + 1, n_cs),
        "cs_quantity": rng.integers(1, 100, n_cs),
        "cs_sales_price": (rng.random(n_cs) * 200).round(2),
        "cs_list_price": (rng.random(n_cs) * 250).round(2),
        "cs_coupon_amt": (rng.random(n_cs) * 50).round(2),
        "cs_ext_sales_price": (rng.random(n_cs) * 2000).round(2),
        "cs_ext_discount_amt": (rng.random(n_cs) * 120).round(2),
        "cs_net_profit": ((rng.random(n_cs) - 0.3) * 1000).round(2)}

    # ~10% of store tickets return (sr_ticket_sk + sr_item_sk link back)
    n_sr = max(200, n_ss // 10)
    sr_pick = rng.choice(n_ss, n_sr, replace=False)
    tables["store_returns"] = {
        "sr_return_sk": np.arange(1, n_sr + 1),
        "sr_returned_date_sk": np.minimum(
            tables["store_sales"]["ss_sold_date_sk"][sr_pick]
            + rng.integers(1, 90, n_sr), n_dates),
        "sr_item_sk": tables["store_sales"]["ss_item_sk"][sr_pick],
        "sr_customer_sk": tables["store_sales"]["ss_customer_sk"][sr_pick],
        "sr_cdemo_sk": rng.integers(1, n_cdemo + 1, n_sr),
        "sr_ticket_sk": tables["store_sales"]["ss_ticket_sk"][sr_pick],
        "sr_return_quantity": rng.integers(1, 50, n_sr),
        "sr_return_amt": (rng.random(n_sr) * 500).round(2),
        "sr_net_loss": (rng.random(n_sr) * 300).round(2)}

    n_wr = max(80, n_ws // 10)
    wr_pick = rng.choice(n_ws, n_wr, replace=False)
    tables["web_returns"] = {
        "wr_return_sk": np.arange(1, n_wr + 1),
        "wr_returned_date_sk": np.minimum(
            ws_sold[wr_pick] + rng.integers(1, 90, n_wr), n_dates),
        "wr_item_sk": tables["web_sales"]["ws_item_sk"][wr_pick],
        "wr_order_sk": tables["web_sales"]["ws_order_sk"][wr_pick],
        "wr_returning_customer_sk":
            tables["web_sales"]["ws_bill_customer_sk"][wr_pick],
        "wr_refunded_cdemo_sk": rng.integers(1, n_cdemo + 1, n_wr),
        "wr_return_quantity": rng.integers(1, 50, n_wr),
        "wr_return_amt": (rng.random(n_wr) * 500).round(2),
        "wr_fee": (rng.random(n_wr) * 40).round(2)}

    # weekly inventory snapshots per (item, warehouse)
    inv_dates = np.arange(1, n_dates + 1, 7)
    n_inv_items = min(n_item, 400)
    grid = np.array(np.meshgrid(inv_dates,
                                np.arange(1, n_inv_items + 1),
                                np.arange(1, n_wh + 1),
                                indexing="ij")).reshape(3, -1)
    n_inv = grid.shape[1]
    tables["inventory"] = {
        "inv_row_sk": np.arange(1, n_inv + 1),
        "inv_date_sk": grid[0],
        "inv_item_sk": grid[1],
        "inv_warehouse_sk": grid[2],
        "inv_quantity_on_hand": rng.integers(0, 1000, n_inv)}
    return tables


def load_tpcds(catalog, sf: float = 0.01, shards: int = 1,
               portion_rows: int = 1 << 20, seed: int = 20260730) -> dict:
    import pandas as pd

    from ydb_tpu.storage.mvcc import WriteVersion
    tables = gen_tpcds(sf, seed)
    for name, (schema, pk) in SCHEMAS.items():
        t = catalog.create_table(name, schema, pk, shards=shards,
                                 portion_rows=portion_rows)
        t.bulk_upsert(pd.DataFrame(tables[name]), WriteVersion(1, 1))
    return tables
