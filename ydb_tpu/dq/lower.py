"""SELECT AST + shard topology → StageGraph (the planner lowering pass).

This subsumes the router's former per-shape rewrites — scatter/merge
aggregation, two-level distinct, order/limit scatter scans and the
sharded×sharded shuffle join each used to be a bespoke code path in
`cluster/router.py`; they are now *lowerings* producing one StageGraph
executed by one runner (`dq/runner.py`), the way the reference builds
every distributed plan through `dq_tasks_graph.h` stage builders:

  * no sharded table      → single task on one worker, result collected
                            (replicated copies must not double-count);
  * one sharded table     → partial stage per worker —union_all→ router
                            merge stage (sum→sum, count→sum, avg→sum+
                            count; two-level COUNT(DISTINCT));
  * two sharded tables    → scan stage per side —hash_shuffle(key)→
                            co-partitioned join+partial stage —union_all→
                            router merge (the ShuffleJoin connection);
  * non-aggregating       → limit-pushdown scan stage —merge→ router
                            order/limit tail.

The same aggregate decomposition (`AggCollector`) serves every shape.
"""

from __future__ import annotations

import dataclasses
import uuid
from dataclasses import dataclass, field

from ydb_tpu.dq.graph import (DQ_TMP_PREFIX, HASH_SHUFFLE, INPUT_TABLE,
                              MERGE, PLANE_ICI, UNION_ALL, Channel,
                              Stage, StageGraph)
from ydb_tpu.sql import ast, render

AGGS = ("sum", "count", "min", "max", "avg")

# aggregates whose inputs tolerate bounded per-value error (a final
# reduction absorbs it — the EQuARX stance): their argument columns may
# block-quantize on the ICI plane. COUNT/MIN/MAX do NOT qualify: count
# ignores values but min/max REPORT one, and a quantized extremum would
# surface verbatim in the result
TOLERANT_AGGS = ("sum", "avg")


def plane_mode() -> str:
    """The `YDB_TPU_DQ_PLANE` lever: `auto` (ICI where both endpoints
    share a mesh), `host` (force gRPC frames everywhere — the byte-equal
    escape hatch), `ici` (refuse to lower rather than fall back)."""
    import os
    mode = (os.environ.get("YDB_TPU_DQ_PLANE", "auto").strip().lower()
            or "auto")
    if mode not in ("auto", "host", "ici"):
        raise DqLowerError(f"YDB_TPU_DQ_PLANE={mode!r} — expected "
                           "auto | host | ici")
    return mode


class DqLowerError(Exception):
    """Statement shape not lowerable to a distributed stage graph."""


@dataclass
class DqTopology:
    """What the lowering needs to know about the cluster. With a Hive
    attached (`from_hive`), the worker count comes from the CURRENT
    placement — alive, non-stale shard owners — instead of a static
    endpoint list, and the graph is stamped with the placement epoch it
    was lowered against (a failed run re-lowers against the next one)."""
    n_workers: int
    replicated: set = field(default_factory=set)
    key_columns: dict = field(default_factory=dict)  # sharded: table -> pk
    placement_epoch: int = 0
    # devices of ONE JAX mesh the runner can drive directly (0 = workers
    # are separate OS processes — no shared mesh, host plane only). Set
    # by the router when every worker is in-process and the process
    # exposes at least n_workers devices: that is the "both endpoints on
    # the same mesh" condition the ICI plane needs.
    ici_devices: int = 0

    @property
    def ici_capable(self) -> bool:
        return 2 <= self.n_workers <= self.ici_devices

    @classmethod
    def from_hive(cls, hive, replicated=(), key_columns=None,
                  ici_devices: int = 0) -> "DqTopology":
        orphans = hive.orphaned_shards()
        if orphans:
            # refusing beats silently returning a partial scan: these
            # shards' rows are unreachable until a re-placement (sweep
            # retries the image replay) or an operator intervenes
            raise DqLowerError(
                f"shard(s) {orphans} have no live owner — re-placement "
                "pending or failed; refusing a silently-partial scan")
        eps = hive.query_endpoints()
        if not eps:
            raise DqLowerError(
                "no alive shard-owning workers in the Hive placement — "
                "the cluster has no queryable topology")
        return cls(n_workers=len(eps), replicated=set(replicated),
                   key_columns=dict(key_columns or {}),
                   placement_epoch=hive.epoch,
                   ici_devices=int(ici_devices))


# -- AST helpers (moved from cluster/router.py — shared by lowerings) ------


class AggCollector:
    """Collect distinct aggregate calls in an expression tree and the
    substitution from each call to its merge-side expression."""

    def __init__(self):
        self.partial_items: list = []     # [(alias, ast expr)]
        self.merge_map: dict = {}         # FuncCall -> merge expr (ast)
        self.has_distinct = False         # seen a DISTINCT aggregate
        self._n = 0

    def _alias(self) -> str:
        self._n += 1
        return f"__a{self._n}"

    def visit(self, e):
        if isinstance(e, ast.FuncCall) and e.name in AGGS:
            if e in self.merge_map:
                return
            if e.distinct:
                # recorded, not raised: detection passes (has_agg) walk
                # the same tree; only actual decomposition refuses
                self.has_distinct = True
                return
            if e.name == "avg":
                a_s, a_c = self._alias(), self._alias()
                self.partial_items.append(
                    (a_s, ast.FuncCall("sum", e.args)))
                self.partial_items.append(
                    (a_c, ast.FuncCall("count", e.args)))
                self.merge_map[e] = ast.BinOp(
                    "/",
                    ast.FuncCall("sum", (ast.Name((a_s,)),)),
                    ast.FuncCall("sum", (ast.Name((a_c,)),)))
                return
            a = self._alias()
            self.partial_items.append((a, e))
            merge_fn = {"sum": "sum", "count": "sum",
                        "min": "min", "max": "max"}[e.name]
            self.merge_map[e] = ast.FuncCall(merge_fn, (ast.Name((a,)),))
            return
        for f in getattr(e, "__dataclass_fields__", ()):
            v = getattr(e, f)
            if isinstance(v, tuple):
                for x in v:
                    if hasattr(x, "__dataclass_fields__"):
                        self.visit(x)
            elif hasattr(v, "__dataclass_fields__"):
                self.visit(v)


def substitute(e, mapping: dict):
    """Replace subtrees by the mapping (dataclass equality), recursively."""
    if e in mapping:
        return mapping[e]
    if not hasattr(e, "__dataclass_fields__"):
        return e

    def rw(v):
        if isinstance(v, tuple):
            return tuple(rw(x) for x in v)
        if hasattr(v, "__dataclass_fields__"):
            return substitute(v, mapping)
        return v
    try:
        return dataclasses.replace(
            e, **{f: rw(getattr(e, f)) for f in e.__dataclass_fields__})
    except TypeError:
        return e


def has_agg(sel: ast.Select) -> bool:
    c = AggCollector()
    for it in sel.items:
        c.visit(it.expr)
    if sel.having is not None:
        c.visit(sel.having)
    return bool(c.merge_map) or c.has_distinct or bool(sel.group_by)


def contains_subquery(node) -> bool:
    """Any nested SELECT (CTE, derived table, IN/EXISTS/scalar subquery):
    shipping those verbatim would compute their aggregates shard-locally
    — silently wrong — so the lowering refuses them."""
    if isinstance(node, (ast.InSubquery, ast.Exists, ast.ScalarSubquery,
                         ast.SubqueryRef)):
        return True
    if isinstance(node, ast.Select) and node.ctes:
        return True
    for fname in getattr(node, "__dataclass_fields__", ()):
        v = getattr(node, fname)
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if isinstance(x, tuple):
                if any(contains_subquery(y) for y in x
                       if hasattr(y, "__dataclass_fields__")):
                    return True
            elif hasattr(x, "__dataclass_fields__") \
                    and contains_subquery(x):
                return True
    return False


def table_names(rel) -> list:
    if isinstance(rel, ast.TableRef):
        return [rel.name]
    if isinstance(rel, ast.Join):
        return table_names(rel.left) + table_names(rel.right)
    return []


def has_outer_join(rel) -> bool:
    if isinstance(rel, ast.Join):
        return (rel.kind not in ("inner", "cross")
                or has_outer_join(rel.left) or has_outer_join(rel.right))
    return False


def relation_binds(rel) -> dict:
    """FROM bindings: {bind name (alias or table): table name}."""
    out: dict = {}
    if isinstance(rel, ast.TableRef):
        out[rel.alias or rel.name] = rel.name
    elif isinstance(rel, ast.Join):
        out.update(relation_binds(rel.left))
        out.update(relation_binds(rel.right))
    return out


def collect_names(node, out=None) -> list:
    if out is None:
        out = []
    if isinstance(node, ast.Name):
        out.append(node.parts)
        return out
    for f in getattr(node, "__dataclass_fields__", ()):
        v = getattr(node, f)
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if isinstance(x, tuple):
                for y in x:
                    if hasattr(y, "__dataclass_fields__"):
                        collect_names(y, out)
            elif hasattr(x, "__dataclass_fields__"):
                collect_names(x, out)
    return out


def attribute(parts: tuple, binds: dict, table_cols: dict):
    """Which TABLE a column reference binds to (None = unresolvable)."""
    if len(parts) == 2:
        return binds.get(parts[0])
    hits = [t for t in set(binds.values())
            if parts[-1] in table_cols.get(t, ())]
    if len(hits) == 1:
        return hits[0]
    if len(hits) > 1:
        raise DqLowerError(f"ambiguous column {parts[-1]!r} across "
                           f"{sorted(hits)} — qualify it")
    return None


def conjuncts(e) -> list:
    if e is None:
        return []
    if isinstance(e, ast.BinOp) and e.op == "and":
        return conjuncts(e.left) + conjuncts(e.right)
    return [e]


def join_ons(rel) -> list:
    if isinstance(rel, ast.Join):
        return (conjuncts(rel.on) + join_ons(rel.left)
                + join_ons(rel.right))
    return []


def expr_tables(e, binds: dict, table_cols: dict) -> set:
    out = set()
    for parts in collect_names(e):
        t = attribute(parts, binds, table_cols)
        if t is not None:
            out.add(t)
    return out


def only_tables(e, allowed: set, binds: dict, table_cols: dict) -> bool:
    ts = expr_tables(e, binds, table_cols)
    return bool(ts) and ts <= allowed


def cross_equality(e, a: str, b: str, binds: dict, table_cols: dict):
    """`A.x = B.y` (either orientation) → (x, y); else None."""
    if not (isinstance(e, ast.BinOp) and e.op == "="
            and isinstance(e.left, ast.Name)
            and isinstance(e.right, ast.Name)):
        return None
    lt = attribute(e.left.parts, binds, table_cols)
    rt = attribute(e.right.parts, binds, table_cols)
    if lt == a and rt == b:
        return (e.left.parts[-1], e.right.parts[-1])
    if lt == b and rt == a:
        return (e.right.parts[-1], e.left.parts[-1])
    return None


def split_name_contexts(node, exact: list, tolerant: list,
                        in_tolerant: bool = False) -> None:
    """Collect column references by usage context: inside a SUM/AVG
    argument (`tolerant` — a final reduction absorbs bounded per-value
    error) vs anywhere else (`exact` — keys, group-bys, filters,
    COUNT/MIN/MAX args, ORDER BY). The quantization planner only trusts
    a column that NEVER appears in an exact context."""
    if isinstance(node, ast.Name):
        (tolerant if in_tolerant else exact).append(node.parts)
        return
    if isinstance(node, ast.FuncCall) and node.name in AGGS:
        inner = node.name in TOLERANT_AGGS and not node.distinct
        for a in node.args:
            if hasattr(a, "__dataclass_fields__"):
                split_name_contexts(a, exact, tolerant, inner)
        return
    for f in getattr(node, "__dataclass_fields__", ()):
        v = getattr(node, f)
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if isinstance(x, tuple):
                for y in x:
                    if hasattr(y, "__dataclass_fields__"):
                        split_name_contexts(y, exact, tolerant,
                                            in_tolerant)
            elif hasattr(x, "__dataclass_fields__"):
                split_name_contexts(x, exact, tolerant, in_tolerant)


def rewrite_relation(rel, temp_of: dict):
    """Swap sharded TableRefs for their shuffle-temp names, keeping the
    original bind name as the alias so every column reference resolves
    unchanged."""
    if isinstance(rel, ast.TableRef):
        if rel.name in temp_of:
            return ast.TableRef(temp_of[rel.name],
                                rel.alias or rel.name)
        return rel
    if isinstance(rel, ast.Join):
        return dataclasses.replace(
            rel, left=rewrite_relation(rel.left, temp_of),
            right=rewrite_relation(rel.right, temp_of))
    return rel


# -- lowering --------------------------------------------------------------


class _Builder:
    def __init__(self, tag: str):
        self.tag = tag
        self.stages: list = []
        self.channels: dict = {}
        self._n = 0

    def channel(self, kind: str, src: str, dst: str = "", key: str = "",
                columns=None, table: str = "") -> Channel:
        self._n += 1
        ch = Channel(id=f"dqc_{self.tag}_{self._n}", kind=kind,
                     src_stage=src, dst_stage=dst, key=key,
                     columns=list(columns or []), table=table)
        self.channels[ch.id] = ch
        return ch

    def graph(self) -> StageGraph:
        g = StageGraph(stages=self.stages, channels=self.channels,
                       tag=self.tag)
        g.validate()
        return g


def lower_select(sel: ast.Select, topo: DqTopology,
                 table_cols) -> StageGraph:
    """Lower one SELECT to a StageGraph. `table_cols(table)` resolves a
    table's column names (catalog schemas in-process, an RPC schema probe
    on the router)."""
    from ydb_tpu.query.window import has_window
    if not isinstance(sel, ast.Select):
        raise DqLowerError("only SELECT lowers to a stage graph")
    if has_window(sel):
        raise DqLowerError("window functions are not distributable over "
                           "shards yet (per-shard windows would be "
                           "silently wrong)")
    if contains_subquery(sel):
        raise DqLowerError("CTEs/subqueries are not distributable over "
                           "shards yet (their aggregates would compute "
                           "shard-locally)")
    b = _Builder(uuid.uuid4().hex[:10])
    tables = set(table_names(sel.relation))
    unknown = sorted(t for t in tables if t not in topo.replicated
                     and t not in topo.key_columns)
    if unknown:
        # ambiguous distribution must refuse, not guess: assuming
        # replicated would run one worker's shard (missing rows);
        # assuming sharded would N-fold overcount a replicated copy
        raise DqLowerError(
            f"unknown distribution for table(s) {unknown} — register "
            "them in key_columns (sharded) or replicated before "
            "distributing")
    sharded = sorted({n for n in tables
                      if n not in topo.replicated
                      and n in topo.key_columns})
    if len(sharded) > 2:
        raise DqLowerError(
            f"joining {len(sharded)} sharded tables ({sharded}) is not "
            "supported yet — at most two shuffle; create dimensions with "
            "replicated=True")
    if len(sharded) == 2:
        final_sel, scan_channels = _lower_shuffle_scans(b, sel, sharded,
                                                        table_cols)
        _lower_two_phase(b, final_sel, inputs=scan_channels)
    elif not sharded:
        # every referenced table is replicated: run the whole statement
        # as ONE task on one worker — scattering over N full copies would
        # double-count every aggregate N times
        s = Stage(id=f"s{len(b.stages)}", sql=render.select(sel),
                  on="worker0")
        ch = b.channel(UNION_ALL, src=s.id)
        s.outputs = [ch.id]
        b.stages.append(s)
        b.stages.append(Stage(id="merge", inputs=[ch.id], on="router"))
    else:
        _lower_two_phase(b, sel, inputs=[])
    g = b.graph()
    g.placement_epoch = topo.placement_epoch
    _assign_planes(g, topo)
    return g


def _assign_planes(g: StageGraph, topo: DqTopology) -> None:
    """Pick each channel's data plane. Worker-bound edges (both
    endpoints are worker tasks) go device-resident when the topology
    says every worker sits on one JAX mesh; router-bound edges always
    collect over the host plane. `YDB_TPU_DQ_PLANE` overrides."""
    mode = plane_mode()
    if mode == "host":
        return                         # default plane on every channel
    if mode == "ici" and not topo.ici_capable:
        raise DqLowerError(
            f"YDB_TPU_DQ_PLANE=ici but the topology is not "
            f"device-colocated ({topo.n_workers} worker(s), "
            f"{topo.ici_devices} mesh device(s)) — the ICI plane needs "
            "every worker on one JAX mesh")
    if not topo.ici_capable:
        return
    for ch in g.channels.values():
        if not ch.router_bound:
            ch.plane = PLANE_ICI


def _lower_two_phase(b: _Builder, sel: ast.Select, inputs: list) -> None:
    if has_agg(sel):
        if _lower_count_distinct(b, sel, inputs):
            return
        _lower_agg(b, sel, inputs)
    else:
        _lower_scan(b, sel, inputs)


def _label(it: ast.SelectItem, i: int) -> str:
    if it.alias:
        return it.alias
    if isinstance(it.expr, ast.Name):          # single-node naming
        return it.expr.parts[-1]
    return f"column{i}"


def _lower_agg(b: _Builder, sel: ast.Select, inputs: list) -> None:
    """Partial/merge aggregation split (sum→sum, count→sum, avg→sum+count,
    min/max→min/max) — the BlockCombineHashed → BlockMergeFinalizeHashed
    boundary expressed as a UnionAll edge."""
    if sel.distinct or sel.ctes:
        raise DqLowerError("DISTINCT/CTE SELECTs are not distributable "
                           "over shards yet")
    col = AggCollector()
    for it in sel.items:
        col.visit(it.expr)
    if sel.having is not None:
        col.visit(sel.having)
    for o in sel.order_by:
        col.visit(o.expr)
    if col.has_distinct:
        # the distinct-only shape was handled by _lower_count_distinct;
        # mixtures of DISTINCT and plain aggregates need a per-agg plan
        raise DqLowerError(
            "mixing DISTINCT aggregates with other aggregates is not "
            "distributable over shards yet")

    gmap = {}
    gitems = []
    for i, g in enumerate(sel.group_by):
        a = f"__g{i}"
        gmap[g] = ast.Name((a,))
        gitems.append(ast.SelectItem(g, a))
    items = gitems + [ast.SelectItem(e, a)
                      for (a, e) in col.partial_items]
    worker_sel = ast.Select(
        items=items, relation=sel.relation, where=sel.where,
        group_by=list(sel.group_by), ctes=list(sel.ctes))

    sub = {**col.merge_map, **gmap}
    mitems = [ast.SelectItem(substitute(it.expr, sub), _label(it, i))
              for i, it in enumerate(sel.items)]
    morder = [dataclasses.replace(o, expr=substitute(o.expr, sub))
              for o in sel.order_by]
    mhaving = substitute(sel.having, sub) \
        if sel.having is not None else None
    merge_sel = ast.Select(
        items=mitems, relation=ast.TableRef(INPUT_TABLE),
        group_by=[gmap[g] for g in sel.group_by], having=mhaving,
        order_by=morder, limit=sel.limit, offset=sel.offset)

    s = Stage(id=f"s{len(b.stages)}", sql=render.select(worker_sel),
              inputs=list(inputs))
    for cid in inputs:
        b.channels[cid].dst_stage = s.id
    ch = b.channel(UNION_ALL, src=s.id)
    s.outputs = [ch.id]
    b.stages.append(s)
    # the merge GROUP BY re-plans through the router engine and therefore
    # rides the same tiled/late-materialized sorted group-by as every
    # statement (its key domains come from the landed temp table's
    # dictionaries) — marked so the runner counts it on /counters
    b.stages.append(Stage(id="merge", inputs=[ch.id], on="router",
                          merge_sel=merge_sel,
                          groupby_merge=bool(sel.group_by)))


def _lower_count_distinct(b: _Builder, sel: ast.Select,
                          inputs: list) -> bool:
    """COUNT(DISTINCT x) distribution (the two-level distinct shuffle):
    supported when every aggregate is a distinct count — workers emit
    SELECT DISTINCT keys+args, the merge dedups and counts. Returns False
    when the shape doesn't apply."""
    aggs = []
    for it in sel.items:
        if isinstance(it.expr, ast.FuncCall) and it.expr.name in AGGS:
            if not (it.expr.name == "count" and it.expr.distinct):
                return False
            aggs.append(it)
        elif it.expr not in sel.group_by:
            return False
    if not aggs:
        return False
    gitems = [ast.SelectItem(g, f"__g{i}")
              for i, g in enumerate(sel.group_by)]
    ditems = [ast.SelectItem(a.expr.args[0], f"__d{k}")
              for k, a in enumerate(aggs)]
    worker_sel = ast.Select(items=gitems + ditems, relation=sel.relation,
                            where=sel.where, distinct=True)
    gmap = {g: ast.Name((f"__g{i}",))
            for i, g in enumerate(sel.group_by)}
    mitems, k = [], 0
    for i, it in enumerate(sel.items):
        if it in aggs:
            e = ast.FuncCall("count", (ast.Name((f"__d{k}",)),),
                             distinct=True)
            k += 1
        else:
            e = substitute(it.expr, gmap)
        mitems.append(ast.SelectItem(e, _label(it, i)))
    morder = [dataclasses.replace(o, expr=substitute(o.expr, gmap))
              for o in sel.order_by]
    merge_sel = ast.Select(
        items=mitems, relation=ast.TableRef(INPUT_TABLE),
        group_by=[gmap[g] for g in sel.group_by], order_by=morder,
        limit=sel.limit, offset=sel.offset)

    s = Stage(id=f"s{len(b.stages)}", sql=render.select(worker_sel),
              inputs=list(inputs))
    for cid in inputs:
        b.channels[cid].dst_stage = s.id
    ch = b.channel(UNION_ALL, src=s.id)
    s.outputs = [ch.id]
    b.stages.append(s)
    # cross-shard duplicate rows shrink before the merge aggregation;
    # the distinct-count merge is a group-by merge like _lower_agg's
    b.stages.append(Stage(id="merge", inputs=[ch.id], on="router",
                          merge_sel=merge_sel, dedup_input=True,
                          groupby_merge=True))
    return True


def _lower_scan(b: _Builder, sel: ast.Select, inputs: list) -> None:
    """Non-aggregating scatter: limit+offset push down per worker; the
    router stage re-sorts the union and applies the final slice."""
    lim = None if sel.limit is None else sel.limit + (sel.offset or 0)
    worker_sel = dataclasses.replace(sel, limit=lim, offset=None)
    # ORDER BY the pre-alias expression: rewrite to the output alias
    # (the router merge sorts the gathered frame by column name)
    alias_of = {it.expr: it.alias for it in sel.items if it.alias}
    order = [dataclasses.replace(o, expr=ast.Name((alias_of[o.expr],)))
             if o.expr in alias_of else o for o in sel.order_by]

    s = Stage(id=f"s{len(b.stages)}", sql=render.select(worker_sel),
              inputs=list(inputs))
    for cid in inputs:
        b.channels[cid].dst_stage = s.id
    ch = b.channel(MERGE if sel.order_by else UNION_ALL, src=s.id)
    # bounds lattice: the pushed-down LIMIT bounds every producer's
    # output rows on this channel
    if lim is not None:
        ch.out_bound = int(lim)
    s.outputs = [ch.id]
    b.stages.append(s)
    b.stages.append(Stage(
        id="merge", inputs=[ch.id], on="router",
        post={"distinct": sel.distinct, "order": order,
              "limit": sel.limit, "offset": sel.offset}))


def _lower_shuffle_scans(b: _Builder, sel: ast.Select, sharded: list,
                         table_cols):
    """Two sharded tables: emit one projection/scan stage per side whose
    output hash-shuffles on the join key, so the downstream stage joins
    co-partitioned rows worker-locally (`dq_opt_join.cpp` ShuffleJoin —
    neither worker ever holds the other's shard set). Returns the
    relation-rewritten SELECT for the downstream stage plus the two
    shuffle channel ids."""
    if any(isinstance(it.expr, ast.Star) for it in sel.items):
        raise DqLowerError("SELECT * is not supported in a shuffle join "
                           "— name the columns")
    if has_outer_join(sel.relation):
        # the shuffle drops NULL join keys (inner semantics); a LEFT/FULL
        # join would silently lose its NULL-extended rows
        raise DqLowerError("outer joins between two sharded tables are "
                           "not supported yet (inner only)")
    binds = relation_binds(sel.relation)          # bind name -> table
    cols = {t: table_cols(t) for t in set(binds.values())}
    refs = collect_names(sel)
    used: dict = {t: set() for t in binds.values()}
    for parts in refs:
        t = attribute(parts, binds, cols)
        if t is not None:
            used[t].add(parts[-1])

    # join key: the first WHERE/ON equality linking the two sharded
    # tables (additional equalities stay as local filters — rows
    # co-partitioned by the first key still satisfy them locally)
    conjs = conjuncts(sel.where) + join_ons(sel.relation)
    a, bt = sharded
    key_a = key_b = None
    for c in conjs:
        pair = cross_equality(c, a, bt, binds, cols)
        if pair is not None:
            key_a, key_b = pair
            break
    if key_a is None:
        raise DqLowerError(
            f"no equality join condition between sharded tables {a!r} "
            f"and {bt!r} — a cross join cannot shuffle")
    used[a].add(key_a)
    used[bt].add(key_b)

    # quantization proof: a shipped column is aggregation-tolerant iff
    # EVERY reference to it sits inside a SUM/AVG argument — those feed
    # a final reduction that absorbs the per-value quant error. Keys,
    # group-bys, filters and COUNT/MIN/MAX inputs must cross exact.
    exact_refs: list = []
    tol_refs: list = []
    split_name_contexts(sel, exact_refs, tol_refs)
    exact_cols: dict = {t: set() for t in binds.values()}
    tol_cols: dict = {t: set() for t in binds.values()}
    for refs, bucket in ((exact_refs, exact_cols), (tol_refs, tol_cols)):
        for parts in refs:
            t = attribute(parts, binds, cols)
            if t is not None:
                bucket[t].add(parts[-1])

    temp_of = {t: f"{DQ_TMP_PREFIX}{b.tag}_{t}" for t in sharded}
    channels = []
    for t, key in ((a, key_a), (bt, key_b)):
        alias = next(al for al, tbl in binds.items() if tbl == t)
        local = [c for c in conjuncts(sel.where)
                 if only_tables(c, {t}, binds, cols)]
        where = None
        for c in local:
            where = c if where is None else ast.BinOp("and", where, c)
        items = [ast.SelectItem(ast.Name((alias, col)), col)
                 for col in sorted(used[t])]
        stage_sel = ast.Select(items=items,
                               relation=ast.TableRef(t, alias),
                               where=where)
        s = Stage(id=f"s{len(b.stages)}", sql=render.select(stage_sel))
        ch = b.channel(HASH_SHUFFLE, src=s.id, dst="join", key=key,
                       columns=sorted(used[t]), table=temp_of[t])
        ch.quant_cols = sorted(
            ((tol_cols[t] - exact_cols[t]) & used[t]) - {key})
        s.outputs = [ch.id]
        b.stages.append(s)
        channels.append(ch.id)
    # channels' dst_stage is stamped when the consumer stage is built
    final_sel = dataclasses.replace(
        sel, relation=rewrite_relation(sel.relation, temp_of))
    return final_sel, channels
