"""DQ task runner — the executer actor over a StageGraph.

Walks the graph in topological order; each worker stage runs as one task
per worker (the reference's one-compute-actor-per-(stage, partition)),
tracked through a pending → running → finished/failed state machine.
Channel failures retry at STAGE granularity: the stage's output channels
are dropped everywhere reachable and every task re-runs with the SAME
frame src — stage programs are deterministic, so a timed-out first
attempt that is still running ships frames with identical (src, seq)
identities AND identical payloads (headers may differ: they carry the
attempt's trace span), and the receiver's (src, seq)-keyed dedup absorbs
whichever attempt lands second (a worker that stays dead turns into a
clean error naming it — never a hang, never a torn result).

`LocalWorker` adapts an in-process `QueryEngine` to the same worker
surface the gRPC `server.Client` exposes, so a 1-worker graph is the
degenerate case of the exact distributed code path (pinned byte-equal to
the fused in-process path by `tests/test_dq.py`).
"""

from __future__ import annotations

import dataclasses
import os
import time

import pandas as pd

from ydb_tpu.dq.graph import StageGraph
from ydb_tpu.sql import ast, render
from ydb_tpu.utils.metrics import GLOBAL


class DqError(Exception):
    pass


class DqWorkerLost(DqError):
    """A task's worker is gone at the TRANSPORT level (connection
    refused/reset, RPC deadline): its shard cannot re-run anywhere
    without re-placement, so the runner surfaces the loss immediately
    instead of burning `stage_retries` into timeouts against a corpse.
    The router's Hive failover (`cluster/router.py`) catches this,
    re-places the dead worker's shards, and re-lowers the statement
    onto the surviving placement."""

    def __init__(self, msg: str, endpoints=()):
        super().__init__(msg)
        self.endpoints = sorted(endpoints)


def _is_transport_error(e) -> bool:
    """Transport-level failure (the worker process/link, not the query):
    gRPC channel errors and socket-level exceptions. In-band worker
    errors arrive as RuntimeError from the Client wrapper and are NOT
    transport — they retry on the same worker like before."""
    try:
        import grpc
        if isinstance(e, grpc.RpcError):
            return True
    except ImportError:
        pass
    return isinstance(e, (ConnectionError, TimeoutError, OSError))


def _transport_kind(e) -> str:
    """'timeout' (hang-shaped: the worker may still answer ping, which
    is exactly why the router's probe must trust this hint) vs
    'unavailable' (connection-level: the probe can verify it)."""
    if isinstance(e, TimeoutError):
        return "timeout"
    try:
        import grpc
        if isinstance(e, grpc.RpcError) and \
                getattr(e, "code", lambda: None)() == \
                grpc.StatusCode.DEADLINE_EXCEEDED:
            return "timeout"
    except ImportError:
        pass
    return "unavailable"


# -- cross-worker clock alignment -------------------------------------------
#
# Worker span timestamps are worker-local monotonic clocks (each tracer
# counts from its own process start): comparing them across workers —
# DQ stage overlap, channel send→recv gaps — needs every span on ONE
# timebase. The DqRunTask RPC boundary gives a free NTP-style estimator:
# the runner stamps send/recv on the router clock, the worker stamps
# receive/respond on its clock (`resp["profile"]["clock"]`), and the
# midpoint difference is the router-minus-worker offset with ±RTT/2
# uncertainty. EWMA-smoothed per worker HANDLE (Client / LocalWorker
# objects persist across the per-query runners), observed on every task
# RPC — sampled or not — so the estimate is warm by the first profiled
# query.

_CLOCK_ALPHA = 0.3


def observe_clock(worker, t_send: float, t_recv: float,
                  w_recv: float, w_send: float):
    """One offset sample at an RPC boundary, folded into the worker's
    EWMA. All times in ms on their respective tracer clocks. Returns
    (offset_ms, err_ms): router_time ≈ worker_time + offset_ms."""
    sample = ((t_send + t_recv) / 2.0) - ((w_recv + w_send) / 2.0)
    err = max(0.0, ((t_recv - t_send) - (w_send - w_recv)) / 2.0)
    prev = getattr(worker, "_clock_ewma", None)
    if prev is None:
        off = (sample, err)
    else:
        off = (_CLOCK_ALPHA * sample + (1 - _CLOCK_ALPHA) * prev[0],
               _CLOCK_ALPHA * err + (1 - _CLOCK_ALPHA) * prev[1])
    worker._clock_ewma = off
    return off


def worker_clock_offset(worker):
    """The smoothed (offset_ms, err_ms) for a worker handle, or None
    before its first observed RPC."""
    return getattr(worker, "_clock_ewma", None)


class DqTaskRunner:
    def __init__(self, workers: list, engine, counters=None,
                 stage_retries: int = 1, rpc_timeout: float = None):
        self.workers = list(workers)
        self.engine = engine                 # router-side merge engine
        self.counters = counters or GLOBAL
        self.stage_retries = stage_retries
        self.rpc_timeout = rpc_timeout if rpc_timeout is not None else \
            float(os.environ.get("YDB_TPU_DQ_RPC_TIMEOUT", 600.0))
        self.task_log: list = []             # observability + tests
        # per-(stage, worker) execution stats for THIS graph run — one
        # row per task attempt set, pushed into the engine's
        # `dq_stage_stats` ring (`.sys/dq_stage_stats`) after the run
        self.stage_stats: list = []
        self._input_waits: dict = {}         # (stage id, widx) -> ms
        # per-stage device-plane wire accounting (filled by the ICI
        # exchanges): stage id -> {"ici_bytes", "ici_frames",
        # "quant_bytes_saved"} — attributed into the stage-stats rows
        self._ici_stage_stats: dict = {}
        # endpoints whose last RPC died at the transport level: later
        # attempts/stages skip them (reroute single-task stages, raise
        # DqWorkerLost for per-shard ones) instead of re-timing-out —
        # the router reads this (with per-endpoint failure kinds) to
        # drive Hive failover
        self.transport_failed: set = set()
        self.transport_kinds: dict = {}      # endpoint -> timeout|unavailable
        # closed resource-ledger summary of the last run() — the router
        # joins it into the profile record so critical-path extraction
        # can cost padded/transferred bytes next to the milliseconds
        self.mem_summary: dict = None
        for w in self.workers:
            if hasattr(w, "bind_peers"):
                try:
                    w.bind_peers(self.workers)
                except Exception as e:       # noqa: BLE001 — a worker
                    # already dead at bind time is an early transport
                    # failure, surfaced when its first task runs
                    if _is_transport_error(e):
                        self.transport_failed.add(w.endpoint)
                    else:
                        raise

    # -- tracing helpers ----------------------------------------------------

    @property
    def tracer(self):
        return getattr(self.engine, "tracer", None)

    def _span(self, name: str, **attrs):
        from contextlib import nullcontext
        t = self.tracer
        return t.span(name, **attrs) if t is not None else nullcontext()

    @staticmethod
    def _trace_ctx(base_ctx, parent_span) -> dict:
        """Propagation context for a task RPC: the router trace's id,
        the task span to parent under, and the sampling bit. `base_ctx`
        MUST be captured on the trace-owning thread (`tracer.current()`
        is thread-local) — task RPCs fire from pool threads."""
        if base_ctx is None:
            return None
        if parent_span is not None:
            return dict(base_ctx, parent_span_id=parent_span.span_id)
        return dict(base_ctx)

    # -- public -------------------------------------------------------------

    def run(self, graph: StageGraph) -> pd.DataFrame:
        graph.validate()
        self._dtypes: dict = {}              # channel id -> {col: dtype}
        self._collected: dict = {}           # channel id -> {widx: frame}
        # resource ledger for the whole graph run: a router-driven DQ
        # query never passes through engine.execute() at this level, so
        # the runner owns the statement ledger — the nested router-merge
        # statement then contributes to it instead of opening its own
        from ydb_tpu.utils import memledger
        led = memledger.open_statement()
        try:
            for stage in graph.stages:
                if stage.on == "router":
                    return self._run_router_stage(graph, stage)
                self._run_worker_stage(graph, stage)
            raise DqError("stage graph ended without a router stage")
        finally:
            if led is not None:
                memledger.close_statement(led)
                self.mem_summary = led.summary()
                rm = getattr(self.engine, "_record_memory", None)
                if rm is not None:
                    rm(f"dq-graph:{graph.tag}", "dq", led)
            self._cleanup(graph)
            ring = getattr(self.engine, "dq_stage_stats", None)
            if ring is not None:
                ring.extend(self.stage_stats)

    # -- worker stages ------------------------------------------------------

    def _task_workers(self, stage) -> list:
        """Workers to task for a stage, honoring transport-dead skips.
        A single-task stage (`worker0`: replicated-only data, every
        worker holds a full copy) REROUTES onto the first live worker —
        the one correctness-preserving reroute without re-placement. A
        per-shard stage must task every worker; a dead one among them is
        a worker-lost condition, not a reroute."""
        if stage.on == "worker0":
            for (i, w) in enumerate(self.workers):
                if w.endpoint not in self.transport_failed:
                    return [(i, w)]
            raise DqWorkerLost(
                f"stage {stage.id}: no live worker for single-task "
                f"stage (all {len(self.workers)} transport-failed)",
                endpoints=self.transport_failed)
        return list(enumerate(self.workers))

    def _run_worker_stage(self, graph, stage) -> None:
        from ydb_tpu.utils.metrics import GLOBAL_HIST
        self.counters.inc("dq/stages")
        if self._stage_ici_channels(graph, stage) \
                and not all(hasattr(w, "ici_land") for w in self.workers):
            # defense in depth: the lowering promised a shared mesh the
            # runner's worker set cannot honor (e.g. a gRPC endpoint
            # joined after lowering) — the host plane is always correct
            self._flip_to_host(graph, stage,
                               "workers are not mesh-colocated")
        t_stage = time.perf_counter()
        with self._span("dq-stage", stage=stage.id,
                        tasks=len(self._task_workers(stage))):
            self._materialize_inputs(graph, stage)
            results, tasks = self._run_stage_attempts(
                graph, stage, self._output_specs(graph, stage))
            ici_chs = self._stage_ici_channels(graph, stage)
            if ici_chs:
                try:
                    self._run_ici_exchanges(graph, stage, ici_chs,
                                            results)
                except Exception as e:       # noqa: BLE001 — ANY failed
                    # device exchange (mid-collective worker death,
                    # codec refusal, mesh gone) falls back to re-running
                    # the edge on the host plane: same stage programs,
                    # fresh host frames, the receivers' (src, seq) dedup
                    # guards the overlap
                    self._flip_to_host(graph, stage,
                                       f"{type(e).__name__}: {e}")
                    self._drop_outputs(graph, stage)
                    results, tasks = self._run_stage_attempts(
                        graph, stage, self._output_specs(graph, stage))
        # success-only, matching the router stage and query/latency_ms:
        # a timed-out stage would inject an rpc-timeout artifact
        GLOBAL_HIST.observe("dq/stage_ms",
                            (time.perf_counter() - t_stage) * 1000.0)

        for (i, resp, _e) in results:
            for cid in stage.outputs:
                ch = graph.channels[cid]
                self._dtypes.setdefault(cid, {}).update(
                    resp.get("dtypes") or {})
                if ch.router_bound:
                    frame = self._collected_frame(resp)
                    if frame is not None:
                        self._collected.setdefault(cid, {})[i] = frame
            self.counters.inc("dq/channel_bytes",
                              resp.get("bytes_shipped", 0))
            self.counters.inc("dq/frames", resp.get("frames_shipped", 0))
            self._note_task_stats(graph, stage, tasks[i], resp, i)

    # -- channel planes ------------------------------------------------------

    def _output_specs(self, graph, stage) -> list:
        specs = []
        for cid in stage.outputs:
            ch = graph.channels[cid]
            spec = {"channel": ch.id, "kind": ch.kind, "key": ch.key,
                    "n_peers": len(self.workers),
                    "peers": [w.endpoint for w in self.workers]}
            if ch.plane == "ici":
                spec["plane"] = "ici"
            specs.append(spec)
        return specs

    @staticmethod
    def _stage_ici_channels(graph, stage) -> list:
        return [graph.channels[cid] for cid in stage.outputs
                if graph.channels[cid].plane == "ici"]

    def _flip_to_host(self, graph, stage, reason: str) -> None:
        """Re-lower this stage's ICI edges onto the host plane (the
        always-available data plane) — counted so operators see every
        edge that did NOT go device-resident as planned."""
        for ch in self._stage_ici_channels(graph, stage):
            ch.plane = "host"
            self.counters.inc("dq/ici_fallbacks")
        self._ici_stage_stats.pop(stage.id, None)

    def _run_ici_exchanges(self, graph, stage, ici_chs, results) -> None:
        """Execute the stage's device-resident edges: ONE collective per
        channel over every producer's stage output (`dq/ici.py`), the
        per-consumer partitions landing straight in each worker's
        exchange buffer — no npz, no gRPC. Bytes count on `dq/ici_bytes`
        (`dq/channel_bytes` stays untouched for these edges)."""
        from ydb_tpu.dq import ici
        by_idx = {i: resp for (i, resp, _e) in results}
        blocks = []
        for i in range(len(self.workers)):
            resp = by_idx.get(i)
            if resp is None or "ici_block" not in resp:
                raise ici.IciPlaneError(
                    f"stage {stage.id}: task w{i} shipped no device "
                    "frame")
            blocks.append(resp["ici_block"])
        planned = ici.planned_enabled()
        dfs = hint = None
        if not planned:
            # YDB_TPU_DQ_PLANNED=0 comparison lane: the legacy exchange
            # routes pandas, so materialize each producer ONCE here —
            # honestly booked as in-plan host-sync debt (the exact tax
            # the planned path retires) — and overwrite the schema
            # dtype hints with the exact pandas dtypes
            from ydb_tpu.utils import memledger
            dfs, hint = [], {}
            for i, b in enumerate(blocks):
                # lint: transfer-ok(lever-off legacy lane — booked on to_pandas_in_plan below)
                df = b.to_pandas()
                memledger.record_transfer(
                    "dq/runner.py::legacy_ici_to_pandas",
                    int(df.memory_usage(index=False).sum()),
                    to_pandas_in_plan=True)
                dts = {c: str(df[c].dtype) for c in df.columns}
                by_idx[i]["dtypes"] = dts
                hint.update(dts)
                dfs.append(df)
        agg = self._ici_stage_stats.setdefault(
            stage.id, {"ici_bytes": 0, "ici_frames": 0,
                       "quant_bytes_saved": 0,
                       "pad_live_bytes": 0, "pad_padded_bytes": 0,
                       "count_exchange_bytes": 0})
        kkinds = {}
        for ch in ici_chs:
            kkind = None
            for resp in by_idx.values():
                kkind = (resp.get("ici_key_kinds") or {}).get(ch.id) \
                    or kkind
            kkinds[ch.id] = kkind
        batched = None
        if planned and len(ici_chs) > 1:
            # a multi-edge stage ships ALL its sizing counts as ONE
            # fused program + one exchanged matrix instead of one host
            # round trip per channel (`dq/count_exchange_batched`)
            with self._span("ici-exchange-batched", stage=stage.id,
                            channels=len(ici_chs)):
                batched = ici.exchange_blocks_batched(
                    ici_chs, blocks,
                    key_kinds=[kkinds[ch.id] for ch in ici_chs],
                    counters=self.counters)
        for ci, ch in enumerate(ici_chs):
            kkind = kkinds[ch.id]
            with self._span("ici-exchange", channel=ch.id, kind=ch.kind):
                if batched is not None:
                    out_parts, stats = batched[ci]
                elif planned:
                    out_parts, stats = ici.exchange_blocks(
                        ch, blocks, key_kind=kkind,
                        counters=self.counters)
                else:
                    out_parts, stats = ici.exchange(
                        ch, dfs, key_kind=kkind, dtypes_hint=hint,
                        counters=self.counters)
            share = max(1, stats["ici_bytes"] // len(self.workers))
            for i, w in enumerate(self.workers):
                w.ici_land(ch.id, out_parts[i], share,
                           src=f"ici.{ch.id}", seq=i)
            self.counters.inc("dq/ici_bytes", stats["ici_bytes"])
            self.counters.inc("dq/ici_frames", stats["ici_frames"])
            if stats["quant_bytes_saved"] > 0:
                self.counters.inc("dq/quant_bytes_saved",
                                  stats["quant_bytes_saved"])
            for k in ("ici_bytes", "ici_frames", "quant_bytes_saved",
                      "pad_live_bytes", "pad_padded_bytes",
                      "count_exchange_bytes"):
                agg[k] += max(0, stats.get(k) or 0)
            # per-CHANNEL pad accounting row (`.sys/dq_stage_stats`,
            # state='channel', worker='' so the load signal skips it):
            # the planned exchange's padded/live is a per-edge property —
            # the task-row aggregate hides which edge pays the tax
            live = int(stats.get("pad_live_bytes") or 0)
            padded = int(stats.get("pad_padded_bytes") or 0)
            self.stage_stats.append(self._stage_row(
                graph, stage, "", "channel", 1, channel=ch.id,
                plane="ici", ici_bytes=int(stats["ici_bytes"]),
                pad_live_bytes=live, pad_padded_bytes=padded,
                pad_efficiency=round(live / padded, 3) if padded
                else 0.0))

    def _run_stage_attempts(self, graph, stage, specs):
        """The pending → running → finished/failed attempt loop. Every
        ATTEMPT of every task gets its own span in the router's tree
        (`attach_span` — the span object lives on the trace-owning
        thread, pool threads stamp duration/outcome), and a finishing
        task's worker-recorded spans ingest under its attempt span.
        Returns (results, tasks). The worker set is re-resolved per
        attempt: a transport-dead worker is skipped (a single-task stage
        reroutes onto a live one, counted `dq/retry_rerouted`)."""
        from concurrent.futures import ThreadPoolExecutor
        tracer = self.tracer
        # propagation context captured HERE, on the trace-owning thread
        # (the pool threads below have no thread-local trace open)
        base_ctx = tracer.current() if tracer is not None else None
        tasks: dict = {}
        prev_eps = None
        for attempt in range(self.stage_retries + 1):
            tws = self._task_workers(stage)
            eps = {w.endpoint for (_i, w) in tws}
            if (prev_eps is not None and eps - prev_eps) or \
                    (attempt == 0 and stage.on == "worker0"
                     and tws[0][0] != 0):
                # this attempt runs on workers the last one would not
                # have — the single-task stage rerouted off a dead
                # worker (mid-stage, or pre-marked at bind time)
                self.counters.inc("dq/retry_rerouted",
                                  max(1, len(eps - (prev_eps or set()))))
            prev_eps = eps
            for (i, w) in tws:
                if i not in tasks:
                    # attempts counts THIS task's own runs (a task
                    # created by a mid-stage reroute starts at 0, not
                    # at the stage's attempt index — its stats must not
                    # blame retries on the healthy worker)
                    tasks[i] = {"task": f"{graph.tag}.{stage.id}.w{i}",
                                "stage": stage.id, "worker": w.endpoint,
                                "state": "pending", "attempts": 0}
                    self.task_log.append(tasks[i])
            task_spans = {}
            if tracer is not None:
                for (i, w) in tws:
                    task_spans[i] = tracer.attach_span(
                        "dq-task", task=tasks[i]["task"],
                        worker=w.endpoint, attempt=attempt + 1)

            clock_offsets: dict = {}     # widx -> (offset_ms, err_ms)

            def one(iw):
                i, w = iw
                t = tasks[i]
                t["state"] = "running"
                t["attempts"] = t.get("attempts", 0) + 1
                self.counters.inc("dq/tasks")
                sp = task_spans.get(i)
                t_send = tracer._now() if tracer is not None else None
                t0 = time.perf_counter()
                try:
                    # src is attempt-INDEPENDENT on purpose: the stage
                    # program is deterministic (same inputs, same frame
                    # boundaries, same seq order), so a timed-out first
                    # attempt still running concurrently with the retry
                    # ships frames with the same (src, seq) identities
                    # and payloads (headers differ — per-attempt trace
                    # span) — the receiver's (src, seq)-keyed dedup
                    # drops them instead of double-landing rows
                    resp = w.dq_run_task(
                        task_id=t["task"], stage=stage.id, sql=stage.sql,
                        outputs=specs, src=t["task"],
                        timeout=self.rpc_timeout,
                        trace=self._trace_ctx(base_ctx, sp))
                    t["state"] = "finished"
                    clk = (resp.get("profile") or {}).get("clock")
                    if tracer is not None and clk is not None:
                        # clock alignment: fold this RPC's boundary
                        # stamps into the worker's EWMA offset; the
                        # ingest below rebases the worker's spans with
                        # it, and the offset + uncertainty land on the
                        # trace (the attempt's task span)
                        off, cerr = observe_clock(
                            w, t_send, tracer._now(),
                            float(clk["recv_ms"]),
                            float(clk["send_ms"]))
                        clock_offsets[i] = (off, cerr)
                        if sp is not None:
                            sp.attrs["clock_offset_ms"] = round(off, 3)
                            sp.attrs["clock_err_ms"] = round(cerr, 3)
                    if sp is not None:
                        sp.dur_ms = (time.perf_counter() - t0) * 1000.0
                        sp.attrs["state"] = "finished"
                    return (i, resp, None)
                except Exception as e:       # noqa: BLE001 — per-task
                    t["state"] = "failed"
                    t["error"] = f"{type(e).__name__}: {e}"
                    if sp is not None:
                        sp.dur_ms = (time.perf_counter() - t0) * 1000.0
                        sp.attrs["state"] = "failed"
                        sp.attrs["error"] = f"{type(e).__name__}"
                    return (i, None, e)

            with ThreadPoolExecutor(max_workers=len(tws)) as pool:
                results = list(pool.map(one, tws))
            if tracer is not None:
                # worker-recorded spans join the tree under their
                # attempt's task span (ids collide-free: span ids are
                # pid-salted), rebased onto the ROUTER timebase by the
                # worker's smoothed clock offset — the assembled
                # cross-worker profile with honest overlap/gaps
                for (i, resp, _e) in results:
                    spans = ((resp or {}).get("profile") or {}) \
                        .get("spans")
                    if spans:
                        sp = task_spans.get(i)
                        off = clock_offsets.get(i)
                        tracer.ingest(
                            spans, parent_id=sp.span_id
                            if sp is not None else None,
                            offset_ms=off[0] if off is not None
                            else None)
            failed = [(i, e) for (i, _r, e) in results if e is not None]
            if not failed:
                return results, tasks
            transport = [(i, e) for (i, e) in failed
                         if _is_transport_error(e)]
            for (i, e) in transport:
                self.transport_failed.add(tasks[i]["worker"])
                self.transport_kinds[tasks[i]["worker"]] = \
                    _transport_kind(e)
            if transport and stage.on != "worker0":
                # a per-shard stage lost a worker: its shard cannot
                # re-run elsewhere without re-placement — surface the
                # loss NOW (Hive failover re-lowers onto survivors)
                # instead of resending into the corpse every attempt
                names = ", ".join(f"{tasks[i]['worker']} "
                                  f"({tasks[i].get('error', '?')[:120]})"
                                  for (i, _e) in transport)
                raise DqWorkerLost(
                    f"stage {stage.id} failed after {attempt + 1} "
                    f"attempt(s) on: {names} — worker lost (transport); "
                    f"needs re-placement",
                    endpoints=self.transport_failed)
            # stage-level retry: drop the half-delivered output channels
            # everywhere reachable, then re-run every task of the stage
            # under a new attempt id
            if attempt < self.stage_retries:
                self.counters.inc("dq/tasks_retried", len(tws))
                self._drop_outputs(graph, stage)
                time.sleep(0.1)
                continue
            names = ", ".join(f"{tasks[i]['worker']} "
                              f"({tasks[i].get('error', '?')[:120]})"
                              for (i, _e) in failed)
            raise DqError(
                f"stage {stage.id} failed after "
                f"{self.stage_retries + 1} attempt(s) on: {names}")
        raise AssertionError("unreachable: the attempt loop returns on "
                             "success or raises on exhausted retries")

    def _stage_row(self, graph, stage, worker: str, state: str,
                   attempts: int, **stats) -> dict:
        """The `.sys/dq_stage_stats` row shape — ONE literal for worker
        tasks and the router stage (sysview.py mirrors these keys)."""
        ctx = self.tracer.current() if self.tracer is not None else None
        row = {"trace_id": (ctx or {}).get("trace_id", 0) or 0,
               "graph": graph.tag, "stage": stage.id, "worker": worker,
               "state": state, "attempts": int(attempts),
               "channel": "",
               "rows": 0, "bytes": 0, "frames": 0,
               "plane": "host", "ici_bytes": 0,
               "pad_live_bytes": 0, "pad_padded_bytes": 0,
               "pad_efficiency": 0.0,
               "exec_ms": 0.0, "flush_ms": 0.0,
               "input_wait_ms": 0.0, "backpressure_wait_ms": 0.0}
        row.update(stats)
        return row

    def _note_task_stats(self, graph, stage, task, resp, widx) -> None:
        """One `.sys/dq_stage_stats` row per finished task."""
        prof = resp.get("profile") or {}
        chans = prof.get("channels") or []
        ici = self._ici_stage_stats.get(stage.id)
        self.stage_stats.append(self._stage_row(
            graph, stage, task["worker"], task["state"],
            task["attempts"],
            rows=int(resp.get("rows_in", 0)),
            bytes=int(resp.get("bytes_shipped", 0)),
            frames=int(resp.get("frames_shipped", 0)),
            plane="ici" if ici else
                  ("host" if stage.outputs else "-"),
            ici_bytes=int(ici["ici_bytes"] // len(self.workers))
            if ici else 0,
            pad_live_bytes=int(ici["pad_live_bytes"]
                               // len(self.workers)) if ici else 0,
            pad_padded_bytes=int(ici["pad_padded_bytes"]
                                 // len(self.workers)) if ici else 0,
            pad_efficiency=round(ici["pad_live_bytes"]
                                 / ici["pad_padded_bytes"], 3)
            if ici and ici["pad_padded_bytes"] else 0.0,
            exec_ms=float(prof.get("exec_ms", 0.0)),
            flush_ms=float(prof.get("flush_ms", 0.0)),
            input_wait_ms=float(
                self._input_waits.get((stage.id, widx), 0.0)),
            backpressure_wait_ms=float(
                sum(c.get("backpressure_wait_ms", 0.0) for c in chans))))

    def _materialize_inputs(self, graph, stage) -> None:
        """Stage barrier, consumer side: every producer task finished (the
        runner only reaches this stage afterwards), so drain each input
        channel into its typed transient table on every task worker.
        Each open's {rows, bytes, wait_ms} reply becomes an `input-wait`
        span and accrues into the consuming task's stage-stats row."""
        from concurrent.futures import ThreadPoolExecutor

        from ydb_tpu.utils.metrics import GLOBAL_HIST
        for cid in stage.inputs:
            ch = graph.channels[cid]
            dtypes = self._dtypes.get(cid, {})
            cols = [(c, dtypes.get(c, "float64")) for c in ch.columns]
            tws = self._task_workers(stage)

            def open_one(iw, _ch=ch, _cols=cols):
                i, w = iw
                try:
                    return (i, w.endpoint,
                            w.channel_open(_ch.id, _ch.table,
                                           columns=_cols,
                                           timeout=self.rpc_timeout))
                except Exception as e:       # noqa: BLE001 — one surface:
                    # a worker lost at the barrier must raise DqError so
                    # the router maps it to ClusterError like every other
                    # failure mode; transport-level loss marks the worker
                    # for Hive failover like a task failure would
                    msg = (f"channel {_ch.id} barrier failed on "
                           f"{w.endpoint}: {type(e).__name__}: "
                           f"{str(e)[:200]}")
                    if _is_transport_error(e):
                        self.transport_failed.add(w.endpoint)
                        self.transport_kinds[w.endpoint] = \
                            _transport_kind(e)
                        raise DqWorkerLost(
                            msg, endpoints=self.transport_failed) from e
                    raise DqError(msg) from e
            with ThreadPoolExecutor(max_workers=len(tws)) as pool:
                opens = list(pool.map(open_one, tws))
            for (i, endpoint, resp) in opens:
                wait = float(resp.get("wait_ms", 0.0) or 0.0)
                key = (stage.id, i)
                self._input_waits[key] = self._input_waits.get(key, 0.0) \
                    + wait
                if wait:
                    GLOBAL_HIST.observe("dq/channel_wait_ms", wait)
                sp = self.tracer.attach_span(
                    "input-wait", channel=ch.id, worker=endpoint,
                    rows=int(resp.get("rows", 0)),
                    bytes=int(resp.get("bytes", 0))) \
                    if self.tracer is not None else None
                if sp is not None:
                    # the wait already HAPPENED — rewind start so the
                    # span occupies its true interval instead of
                    # overlapping the upcoming task execution
                    sp.start_ms = round(sp.start_ms - wait, 3)
                    sp.dur_ms = wait

    def _drop_outputs(self, graph, stage) -> None:
        chans = list(stage.outputs)
        for cid in chans:
            self._collected.pop(cid, None)
        for w in self.workers:
            try:
                w.channel_close(channels=chans, timeout=self.rpc_timeout)
            except Exception:                # noqa: BLE001 — best effort
                pass

    @staticmethod
    def _collected_frame(resp):
        if "collected_df" in resp:
            return resp["collected_df"]
        c = resp.get("collected")
        if c is None:
            return None
        return pd.DataFrame(c["rows"], columns=c["columns"])

    # -- router (merge) stage ----------------------------------------------

    def _run_router_stage(self, graph, stage) -> pd.DataFrame:
        from ydb_tpu.utils.metrics import GLOBAL_HIST
        t_stage = time.perf_counter()
        ok = False
        try:
            with self._span("dq-stage", stage=stage.id, on="router"):
                out = self._router_stage_body(graph, stage)
            ok = True
            return out
        finally:
            ms = (time.perf_counter() - t_stage) * 1000.0
            if ok:
                # success-only, like the worker stages above
                GLOBAL_HIST.observe("dq/stage_ms", ms)
            self.stage_stats.append(self._stage_row(
                graph, stage, "router",
                "finished" if ok else "failed", 1,
                plane="-",
                rows=sum(len(f) for got in
                         (self._collected.get(cid, {})
                          for cid in stage.inputs)
                         for f in got.values()),
                exec_ms=round(ms, 3)))

    def _router_stage_body(self, graph, stage) -> pd.DataFrame:
        from ydb_tpu.query.window import apply_order_limit
        self.counters.inc("dq/stages")
        if getattr(stage, "groupby_merge", False):
            # partial-agg merges ride the tiled sorted group-by through
            # the engine below; count them so /counters shows DQ's share
            self.counters.inc("dq/merge_groupby_stages")
        frames = []
        for cid in stage.inputs:
            got = self._collected.get(cid, {})
            frames.extend(f for (_i, f) in sorted(got.items()))
        if not frames:
            raise DqError(f"router stage {stage.id} collected no frames")
        df = pd.concat(frames, ignore_index=True) if len(frames) > 1 \
            else frames[0].reset_index(drop=True)
        if stage.dedup_input:
            df = df.drop_duplicates(ignore_index=True)
        if stage.merge_sel is not None:
            return self._merge_over_temp(stage.merge_sel, df)
        if stage.post is not None:
            if stage.post.get("distinct"):
                # per-worker DISTINCT leaves cross-worker duplicates
                df = df.drop_duplicates(ignore_index=True)
            try:
                return apply_order_limit(df, stage.post.get("order") or [],
                                         stage.post.get("limit"),
                                         stage.post.get("offset"))
            except ValueError as e:
                raise DqError(str(e)) from e
        return df

    def _merge_over_temp(self, merge_sel: ast.Select,
                         df: pd.DataFrame) -> pd.DataFrame:
        from ydb_tpu.core.block import HostBlock
        eng = self.engine
        temps: list = []
        try:
            tname = eng._register_temp(HostBlock.from_pandas(df), temps)
            final = dataclasses.replace(merge_sel,
                                        relation=ast.TableRef(tname))
            try:
                return eng.query(render.select(final))
            except Exception as e:           # noqa: BLE001 — one surface
                raise DqError(f"router merge stage failed: "
                              f"{type(e).__name__}: {e}") from e
        finally:
            for tn in temps:
                if eng.catalog.has(tn):
                    eng.catalog.drop_table(tn)

    # -- cleanup ------------------------------------------------------------

    def _cleanup(self, graph) -> None:
        tables = [ch.table for ch in graph.channels.values() if ch.table]
        chans = list(graph.channels)
        if not tables and not chans:
            return
        for w in self.workers:
            try:
                w.channel_close(tables=tables, channels=chans,
                                timeout=self.rpc_timeout)
            except Exception:                # noqa: BLE001 — best effort
                pass


class LocalWorker:
    """In-process worker: the same control surface `server.Client` gives
    the runner (execute / dq_run_task / channel_open / channel_close /
    counters), driving a local QueryEngine directly with an in-process
    exchange buffer — the 1-worker degenerate case, and N-engine
    single-process clusters in tests."""

    def __init__(self, engine, name: str = ""):
        import threading
        from ydb_tpu.cluster.exchange import ExchangeBuffer
        from ydb_tpu.utils.metrics import Counters
        self.engine = engine
        self.endpoint = f"local:{name or hex(id(engine))[2:]}"
        self.exchange = ExchangeBuffer()
        # device-resident channel landings (planned ICI exchange): the
        # exchange buffer speaks pandas frames, so blocks that stay on
        # the accelerator land here instead — channel → DeviceStageBlock,
        # with the same (src, seq) idempotency the frame path gets from
        # ExchangeBuffer.put
        self._device_landed: dict = {}
        self._device_seen: set = set()
        self._peers = [self]
        # task table: mutated by the runner's pool threads while
        # dq_tasks() snapshots it — same discipline as the servicer's
        # _lock around its _dq_tasks table
        self._tasks_mu = threading.Lock()
        self.tasks: dict = {}            # guarded-by: _tasks_mu
        # worker-side task counters go to a private sink: runner and
        # worker share GLOBAL in-process, so counting on both sides
        # would report 2x the real dq/tasks|frames|channel_bytes
        self.task_counters = Counters()

    def bind_peers(self, peers: list) -> None:
        self._peers = list(peers)

    # -- data plane ---------------------------------------------------------

    def _land(self, frame: bytes) -> None:
        from ydb_tpu.cluster.exchange import unpack_frame
        header, df = unpack_frame(frame)
        self.exchange.put(header["channel"], df, len(frame),
                          src=header.get("src", ""),
                          seq=header.get("seq"))

    # -- worker surface -----------------------------------------------------

    def execute(self, sql: str) -> dict:
        from ydb_tpu.server.service import _result_payload
        block = self.engine.execute(sql)
        return _result_payload(block, getattr(self.engine, "last_stats",
                                              None))

    def dq_run_task(self, task_id: str, stage: str, sql: str,
                    outputs: list, src: str, timeout=None,
                    trace=None) -> dict:
        from ydb_tpu.dq import task as dq_task
        with self._tasks_mu:
            rec = self.tasks.setdefault(task_id, {"stage": stage,
                                                  "attempts": 0})
            rec["state"] = "running"
            rec["attempts"] += 1
        try:
            resp = dq_task.run_task(
                self.engine, sql, outputs, src,
                send=lambda _o, p, frame: self._peers[p]._land(frame),
                counters=self.task_counters, trace=trace)
            with self._tasks_mu:
                rec["state"] = "finished"
            return resp
        except Exception as e:
            with self._tasks_mu:
                rec["state"], rec["error"] = "failed", str(e)
            raise

    def ici_land(self, channel: str, df, nbytes: int,
                 src: str = "ici", seq=None) -> None:
        """Land one ICI-exchanged partition — the device plane's
        replacement for an ExchangePut frame (same (src, seq)
        idempotency discipline, no npz, no gRPC). A pandas frame (the
        legacy exchange) goes into the exchange buffer; a block (the
        planned exchange — a `DeviceStageBlock` still on the
        accelerator) lands by REFERENCE in the device store, counted as
        a device→device handoff, never a host transfer."""
        from ydb_tpu.core.block import HostBlock
        if isinstance(df, HostBlock):
            key = (channel, src, seq)
            if seq is not None and key in self._device_seen:
                return
            self._device_seen.add(key)
            self._device_landed[channel] = df
            from ydb_tpu.utils import memledger
            memledger.record_device_handoff(
                "dq/runner.py::ici_land",
                df.live_nbytes() if hasattr(df, "live_nbytes")
                else int(nbytes))
            return
        self.exchange.put(channel, df, int(nbytes), src=src, seq=seq)

    def channel_open(self, channel: str, table: str, columns=None,
                     timeout=None) -> dict:
        from ydb_tpu.dq.task import (materialize_channel,
                                     materialize_device_channel)
        blk = self._device_landed.get(channel)
        if blk is not None:
            # kept (not popped) until channel_close: a consumer-stage
            # retry re-opens the channel and must find the landing again
            stats = materialize_device_channel(self.engine, blk, table)
        else:
            stats = materialize_channel(self.engine, self.exchange,
                                        channel, table, columns)
        return {"ok": True, **stats}

    def channel_close(self, tables=(), channels=(), timeout=None) -> dict:
        for name in tables:
            if self.engine.catalog.has(name) and \
                    getattr(self.engine.catalog.table(name), "transient",
                            False):
                self.engine.catalog.drop_table(name)
        for ch in channels:
            self.exchange.drop(ch)
            self._device_landed.pop(ch, None)
            self._device_seen = {k for k in self._device_seen
                                 if k[0] != ch}
        return {"ok": True}

    def dq_tasks(self, timeout=None) -> dict:
        """Task-table snapshot — the DqTasks RPC surface, in-process
        (per-record copies UNDER the lock, same as the servicer, so a
        caller can't observe a record mid-mutation from a running task
        thread)."""
        with self._tasks_mu:
            return {k: dict(v) for k, v in self.tasks.items()}

    def counters(self) -> dict:
        return self.engine.counters()

    def health(self) -> dict:
        """The Health RPC surface, in-process: the shared engine-level
        payload (`server.service.health_snapshot` — one body, two
        transports). No session table here — LocalWorker clusters
        drive engines directly."""
        from ydb_tpu.server.service import health_snapshot
        return {**health_snapshot(self.engine), "sessions": 0}

    def hive_adopt_shard(self, root: str, tables=None,
                         timeout=None) -> dict:
        """Replay a dead peer's shard image into this worker's tables
        (the HiveAdoptShard RPC surface, in-process)."""
        from ydb_tpu.hive.adopt import adopt_shard
        return {"ok": True,
                "copied": adopt_shard(self.engine, root, tables)}

    def ping(self, timeout=None) -> bool:
        return True
