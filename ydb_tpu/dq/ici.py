"""The DQ channel ICI data plane — device-resident redistribution.

A host-plane channel serializes every partition to an npz frame and
round-trips it through gRPC (`cluster/exchange.py ChannelWriter` →
ExchangePut), so shuffle bandwidth between chips on the SAME mesh is
gRPC-bound. When the lowering marks an edge `plane="ici"` (both
endpoints' tasks run on devices of one JAX mesh — `dq/lower.py
_assign_planes`), the runner executes the redistribution here instead:

  hash_shuffle   bucketize + `lax.all_to_all` + compact — the portable
                 collective shuffle of `parallel/shuffle.py` (arxiv
                 2112.01075), over the SAME per-row buckets the host
                 plane would compute (`cluster/exchange.key_buckets`),
                 so a key routes to the same consumer on either plane
                 and the two sides of a join agree even if their edges
                 lowered differently;
  broadcast      all-gather of every producer's rows to every consumer.

On top, optional EQuARX-style block quantization (arxiv 2506.17615):
columns the lowering PROVED aggregation-tolerant (`Channel.quant_cols`
— pure SUM/AVG inputs behind a final reduction) cross the wire as int8
codes + per-block float32 scales (~1/8 the bytes) when
`YDB_TPU_DQ_QUANT=1`; keys, group-bys and every other exact-context
column always ship verbatim. A quant request the runtime cannot honor
(non-float column) is REFUSED loudly — counted on `dq/quant_refused`,
shipped exact — never silently lossy.

Anything this plane cannot express (exotic dtypes, mixed object
columns, a mesh that went away) raises `IciPlaneError`; the runner
catches it and re-runs the edge on the host plane — correctness never
depends on the fast path.
"""

from __future__ import annotations

import os

import numpy as np
import pandas as pd

from ydb_tpu.parallel.collective import (QUANT_BLOCK, bucket_segments,
                                         compact_segments,
                                         dequantize_blocked,
                                         exchange_segments, gather_all,
                                         quantize_blocked)

AXIS = "shards"


class IciPlaneError(Exception):
    """This edge cannot (or could not) run device-resident; the runner
    falls back to the host plane."""


def quant_enabled() -> bool:  # lint: tuning-provider
    """`YDB_TPU_DQ_QUANT` lever: 0/unset = off (byte-equal frames)."""
    return os.environ.get("YDB_TPU_DQ_QUANT", "0").strip() == "1"


def planned_enabled() -> bool:  # lint: tuning-provider
    """`YDB_TPU_DQ_PLANNED` lever: 1/unset = planned redistribution
    (`exchange_blocks` — device blocks by reference, count-exchange
    segment sizing on the fine ladder); 0 = the legacy pandas exchange
    with 2x power-of-two segments and the device overflow probe."""
    return os.environ.get("YDB_TPU_DQ_PLANNED", "1").strip() != "0"


# -- mesh + compiled-exchange caches ---------------------------------------

_MESHES: dict = {}
_FNS: dict = {}


def _mesh(ndev: int):
    import jax
    from jax.sharding import Mesh
    m = _MESHES.get(ndev)
    if m is None:
        devs = jax.devices()
        if len(devs) < ndev:
            raise IciPlaneError(
                f"ICI plane needs {ndev} mesh devices, platform has "
                f"{len(devs)}")
        m = _MESHES[ndev] = Mesh(np.array(devs[:ndev]), (AXIS,))
    return m


# -- column codecs ---------------------------------------------------------
#
# Every landed column must be indistinguishable from the host plane's
# npz round trip: plain numeric dtypes pass through; object columns
# (how `to_pandas` renders NULL-bearing numerics and strings) ride as
# typed arrays + valid masks (+ a shared dictionary for strings) and
# decode back to object-with-None.

_NUM = "num"
_MASK_INT = "maskint"
_MASK_FLOAT = "maskfloat"
_DICT = "dict"


def _classify(series_per_dev: list, col: str, hint: str):
    """One codec per column, decided over ALL producers (the same
    column can be int64 on a NULL-free shard and object on another)."""
    dts = {str(s.dtype) for s in series_per_dev if len(s)}
    if not dts:
        dts = {hint or "float64"}
    objish = {"object", "str", "string"}
    if not (dts & objish):
        if len(dts) > 1:
            raise IciPlaneError(f"column {col!r}: producers disagree on "
                                f"dtype ({sorted(dts)})")
        np_dt = np.dtype(next(iter(dts)))
        if np_dt.kind not in "iufb":
            raise IciPlaneError(f"column {col!r}: dtype {np_dt} is not "
                                "ICI-encodable")
        return (_NUM, np_dt)
    vals = [v for s in series_per_dev for v in s.dropna().tolist()]
    if all(isinstance(v, (int, np.integer)) and not isinstance(v, bool)
           for v in vals):
        return (_MASK_INT, np.dtype(np.int64))
    if all(isinstance(v, (int, float, np.integer, np.floating))
           and not isinstance(v, bool) for v in vals):
        return (_MASK_FLOAT, np.dtype(np.float64))
    if all(isinstance(v, str) for v in vals):
        # shared dictionary across every producer: codes agree on all
        # devices, values ship once host-side (metadata, not row bytes)
        values = sorted(set(vals))
        return (_DICT, np.dtype(np.int32), values)
    raise IciPlaneError(f"column {col!r}: mixed object values are not "
                        "ICI-encodable")


def _encode(series: pd.Series, spec, cap: int):
    """→ (data[cap], valid[cap]) numpy arrays for one producer."""
    n = len(series)
    valid = np.ones(cap, np.bool_)
    valid[n:] = False
    if spec[0] == _NUM:
        data = np.zeros(cap, spec[1])
        data[:n] = series.to_numpy(dtype=spec[1], copy=False)
        return data, valid
    notna = series.notna().to_numpy() if n else np.zeros(0, np.bool_)
    valid[:n] = notna
    data = np.zeros(cap, spec[1])
    if spec[0] == _DICT:
        code_of = {v: i for i, v in enumerate(spec[2])}
        vals = series.to_numpy()
        data[:n] = [code_of[v] if m else 0
                    for v, m in zip(vals, notna)]
    elif n:
        if series.dtype != object:        # NULL-free numeric producer
            data[:n] = series.to_numpy(dtype=spec[1], copy=False)
        else:
            vals = series.to_numpy()
            data[:n] = [spec[1].type(v) if m else 0
                        for v, m in zip(vals, notna)]
    return data, valid


def _decode(spec, data: np.ndarray, valid: np.ndarray):
    """Per-consumer column: device output rows (already transferred —
    the caller batches every column through ONE jax.device_get) → the
    pandas column the host plane's npz round trip would have landed."""
    if spec[0] == _NUM:
        return data.astype(spec[1], copy=False)
    if spec[0] == _DICT:
        # lint: transfer-ok(string pool is host metadata, never a device value)
        pool = np.asarray(spec[2], dtype=object)
        out = np.array(
            pool[np.clip(data.astype(np.int64), 0,
                         max(len(pool) - 1, 0))]
            if len(pool) else np.zeros(len(data), object),
            dtype=object)
    else:
        out = data.astype(spec[1], copy=False).astype(object)
    out[~valid] = None
    return out


# -- the exchange ----------------------------------------------------------


def _wire_bytes_per_row(spec, quantized: bool) -> float:
    """Bytes one row of this column occupies on the interconnect (data
    + valid mask; quantized columns ride int8 codes + amortized
    per-block scale)."""
    if quantized:
        return 1 + 4.0 / QUANT_BLOCK + 1
    return spec[1].itemsize + 1


def _build_shuffle_fn(mesh, ndev, cap, seg, names, dtypes, quant_names):
    """Compile the shard-mapped bucketize → (quantize) → all_to_all →
    (dequantize) → compact program for one signature. `seg` is the
    per-target segment capacity: smaller than `cap` cuts wire bytes
    proportionally (uniform hashing puts ~rows/ndev in each target);
    the returned overflow flag tells the host to rerun with full
    segments when a target bucket didn't fit (the DQ channel spilling
    analog, same discipline as `DistributedAgg.run`)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ydb_tpu.parallel._compat import shard_map

    def per_device(arrays, valids, bucket, length):
        env = {n: (arrays[n][0], valids[n][0]) for n in names}
        stacked_d, stacked_v, cnts, ovf = bucket_segments(
            env, bucket[0], length[0], cap, seg, ndev, names)
        scales = {}
        for n in quant_names:
            stacked_d[n], scales[n] = quantize_blocked(stacked_d[n])
        recv_d, recv_v, recv_c = exchange_segments(
            stacked_d, stacked_v, cnts, names, axis=AXIS)
        recv_s = {n: jax.lax.all_to_all(scales[n], AXIS, 0, 0,
                                        tiled=False)
                  for n in quant_names}
        for n in quant_names:
            recv_d[n] = dequantize_blocked(recv_d[n], recv_s[n],
                                           dtypes[n])
        env2, tot = compact_segments(recv_d, recv_v, recv_c, seg, ndev,
                                     names)
        out_d = {n: env2[n][0] for n in names}
        out_v = {n: (env2[n][1] if env2[n][1] is not None
                     else jnp.ones_like(out_d[n], dtype=jnp.bool_))
                 for n in names}
        return out_d, out_v, tot, ovf

    def wrapper(arrays, valids, bucket, length):
        out_d, out_v, tot, ovf = per_device(arrays, valids, bucket,
                                            length)
        return ({n: x[None] for n, x in out_d.items()},
                {n: x[None] for n, x in out_v.items()}, tot[None],
                ovf[None])

    pspec_in = ({n: P(AXIS, None) for n in names},
                {n: P(AXIS, None) for n in names},
                P(AXIS, None), P(AXIS))
    return jax.jit(shard_map(
        wrapper, mesh=mesh, in_specs=pspec_in,
        out_specs=(P(AXIS, None), P(AXIS, None), P(AXIS), P(AXIS)),
        check_vma=False))


def _build_broadcast_fn(mesh, ndev, cap, names):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ydb_tpu.parallel._compat import shard_map

    def wrapper(arrays, valids, length):
        d = {n: arrays[n][0] for n in names}
        v = {n: valids[n][0] for n in names}
        env2, tot = gather_all(d, v, length[0], cap, ndev, names,
                               axis=AXIS)
        out_d = {n: env2[n][0] for n in names}
        out_v = {n: (env2[n][1] if env2[n][1] is not None
                     else jnp.ones_like(out_d[n], dtype=jnp.bool_))
                 for n in names}
        return ({n: x[None] for n, x in out_d.items()},
                {n: x[None] for n, x in out_v.items()}, tot[None])

    pspec_in = ({n: P(AXIS, None) for n in names},
                {n: P(AXIS, None) for n in names},
                P(AXIS))
    return jax.jit(shard_map(
        wrapper, mesh=mesh, in_specs=pspec_in,
        out_specs=(P(AXIS, None), P(AXIS, None), P(AXIS)),
        check_vma=False))


def exchange(ch, dfs: list, key_kind: str = None,
             dtypes_hint: dict = None, counters=None) -> tuple:
    """Execute one ICI-plane channel over its producers' stage outputs.

    `dfs[d]` is mesh device d's stage output (one per worker, worker
    order). Returns `(out_dfs, stats)`: the per-consumer landed frames
    and `{"ici_bytes", "ici_frames", "quant_bytes_saved", "quant_cols",
    "quant_refused"}`. Raises `IciPlaneError` when the edge cannot run
    device-resident (the caller falls back to the host plane)."""
    import jax

    from ydb_tpu.dq.graph import BROADCAST, HASH_SHUFFLE
    from ydb_tpu.ops.device import bucket_capacity
    from ydb_tpu.utils import memledger

    ndev = len(dfs)
    if ndev < 2:
        raise IciPlaneError("ICI plane needs at least 2 producers")
    mesh = _mesh(ndev)
    if ch.kind not in (HASH_SHUFFLE, BROADCAST):
        raise IciPlaneError(f"channel kind {ch.kind!r} has no ICI form")

    columns = None
    for df in dfs:
        if list(df.columns):
            columns = list(df.columns)
            break
    if columns is None:
        columns = list(ch.columns)
    if not columns:
        raise IciPlaneError(f"channel {ch.id}: no columns to exchange")

    if ch.kind == HASH_SHUFFLE:
        from ydb_tpu.cluster.exchange import key_buckets
        # host-plane parity: NULL join keys drop (inner semantics), and
        # the bucket per row is the SAME hash the host plane routes by
        dropped = []
        buckets = []
        for df in dfs:
            keep = df[ch.key].notna()
            df = df[keep] if not keep.all() else df
            dropped.append(df)
            try:
                buckets.append(
                    key_buckets(df[ch.key].to_numpy(), ndev, key_kind)
                    if len(df) else np.zeros(0, np.int64))
            except ValueError as e:
                raise IciPlaneError(f"channel {ch.id} key {ch.key!r}: "
                                    f"{e}") from e
        dfs = dropped

    hints = dtypes_hint or {}
    specs = {c: _classify([df[c] for df in dfs], c, hints.get(c))
             for c in columns}

    # quantization: only lowering-proven columns, only plain floats,
    # only with the lever on. A declared column the runtime cannot
    # quantize is refused LOUDLY and shipped exact.
    quant_names: list = []
    refused: list = []
    if quant_enabled():
        for c in ch.quant_cols:
            spec = specs.get(c)
            if spec is not None and spec[0] == _NUM \
                    and spec[1].kind == "f":
                quant_names.append(c)
            elif spec is not None:
                refused.append(c)
        if refused and counters is not None:
            counters.inc("dq/quant_refused", len(refused))

    cap = bucket_capacity(max(max((len(df) for df in dfs), default=0),
                              1), minimum=QUANT_BLOCK)
    arrays = {}
    valids = {}
    for c in columns:
        enc = [_encode(df[c] if c in df.columns
                       else pd.Series(np.zeros(0, specs[c][1])),
                       specs[c], cap) for df in dfs]
        arrays[c] = np.stack([d for (d, _v) in enc])
        valids[c] = np.stack([v for (_d, v) in enc])
    lengths = np.array([len(df) for df in dfs], np.int32)

    names = tuple(columns)
    dt_sig = tuple((c, specs[c][0], str(specs[c][1])) for c in names)
    if ch.kind == HASH_SHUFFLE:
        bucket = np.zeros((ndev, cap), np.int32)
        for d, b in enumerate(buckets):
            bucket[d, :len(b)] = b.astype(np.int32)
        # segment sizing: uniform hashing sends ~rows/ndev to each
        # target, so 2× that (power-of-two) usually fits and cuts wire
        # bytes vs full-capacity segments; a skewed edge overflows on
        # device and reruns ONCE with seg = cap, which cannot overflow
        # (a target receives at most one producer's full row count)
        max_rows = max((len(df) for df in dfs), default=0)
        seg = min(cap, bucket_capacity(
            max(1, (2 * max_rows + ndev - 1) // ndev),
            minimum=QUANT_BLOCK))
        # (Channel.out_bound is NOT consulted on THIS legacy path:
        # `cap` above is already sized from the producers' MEASURED
        # rows — this exchange routes materialized frames, so a static
        # bound can never be tighter. The planned path
        # (`exchange_blocks`) is the bound's consumer: it caps the
        # count-exchange segment sizing with it.)
        while True:
            sig = ("shuffle", ndev, cap, seg, dt_sig,
                   tuple(quant_names))
            # lint: allow-cache-key(the quant lever rides in quant_names above — flipping YDB_TPU_DQ_QUANT changes the tuple, never serves a stale program)
            fn = _FNS.get(sig)
            if fn is None:
                dtypes = {c: specs[c][1] for c in names}
                fn = _FNS[sig] = _build_shuffle_fn(
                    mesh, ndev, cap, seg, names, dtypes,
                    tuple(quant_names))
            out_d, out_v, lens, ovf = fn(arrays, valids, bucket,
                                         lengths)
            # the blessed batched escape for the overflow verdict (was
            # a per-device np.asarray sync — a baselined host-sync debt)
            if not jax.device_get(ovf).any():
                break
            assert seg < cap, "full-capacity segments cannot overflow"
            seg = cap
    else:
        seg = cap                      # broadcast gathers full buffers
        sig = ("broadcast", ndev, cap, dt_sig)
        # lint: allow-cache-key(broadcast edges never quantize — quant_cols apply only to hash-shuffle segments)
        fn = _FNS.get(sig)
        if fn is None:
            fn = _FNS[sig] = _build_broadcast_fn(mesh, ndev, cap, names)
        out_d, out_v, lens = fn(arrays, valids, lengths)

    # ONE batched device→host transfer for every (column, device)
    # segment — 2·cols·ndev separate blocking np.asarray round trips
    # before this was batched (the to_host discipline, ops/device.py)
    host_d, host_v, lens = jax.device_get((out_d, out_v, lens))
    memledger.record_transfer(
        "dq/ici.py::exchange",
        memledger.deep_nbytes((host_d, host_v)))
    out_dfs = []
    for d in range(ndev):
        n = int(lens[d])
        cols = {c: _decode(specs[c], host_d[c][d][:n], host_v[c][d][:n])
                for c in columns}
        out_dfs.append(pd.DataFrame(cols, columns=columns))

    # wire accounting: what the collective actually moved — every
    # (src, dst) pair carries one seg-row segment per column (payload +
    # valid mask; broadcast replicates each producer's full cap-row
    # buffer to every device), plus the per-segment row counts
    per_row = sum(_wire_bytes_per_row(specs[c], c in quant_names)
                  for c in columns)
    exact_row = sum(_wire_bytes_per_row(specs[c], False)
                    for c in columns)
    segs = ndev * ndev
    # padding-waste account: the live rows that actually crossed (the
    # per-consumer landed totals) vs the capacity-padded segment frames
    # the collective shipped — the MULTICHIP_r06 ~3.5× waste, measured
    # per channel instead of estimated
    live_rows = int(sum(int(lens[d]) for d in range(ndev)))
    padded_rows = segs * seg
    padded_wire = int(segs * seg * per_row + segs * 4)
    live_wire = int(live_rows * per_row)
    memledger.record_alloc("collective", memledger.deep_nbytes(
        (arrays, valids)))
    memledger.record_pad("ici_frames", live_rows, padded_rows,
                         live_wire, padded_wire)
    stats = {
        "ici_bytes": padded_wire,
        "ici_frames": segs,
        "quant_bytes_saved": int(segs * seg * (exact_row - per_row)),
        "quant_cols": list(quant_names),
        "quant_refused": list(refused),
        "pad_live_bytes": live_wire,
        "pad_padded_bytes": padded_wire,
        "pad_efficiency": round(live_wire / padded_wire, 3)
        if padded_wire else None,
    }
    return out_dfs, stats


# -- planned redistribution (device blocks by reference) -------------------


def _build_counts_fn(ndev: int, cap: int):
    """Compile the planned path's count exchange: per (producer, target)
    live-row counts from the bucket plane. The [ndev, ndev] int32 result
    is the ONE small sizing message the host reads before any row moves
    — dropped/NULL rows already carry bucket -1, so a plain equality
    reduction is the whole program."""
    import jax
    import jax.numpy as jnp

    def counts(bucket):
        return jnp.stack(
            [jnp.sum(bucket == d, axis=1) for d in range(ndev)],
            axis=1).astype(jnp.int32)

    return jax.jit(counts)


def _build_counts_batched_fn(ndev: int, nch: int, cap: int):
    """The stage-level twin of `_build_counts_fn`: ONE fused program
    over EVERY hash-shuffle edge's bucket plane (`[nch, ndev, cap]`,
    planes padded to the widest capacity with -1 — pad rows route
    nowhere), so a stage with several outgoing edges pays ONE host
    round trip for all its sizing messages instead of one per channel
    (ROADMAP 1c)."""
    import jax
    import jax.numpy as jnp

    def counts(buckets):                     # [nch, ndev, cap]
        return jnp.stack(
            [jnp.sum(buckets == d, axis=2) for d in range(ndev)],
            axis=2).astype(jnp.int32)        # [nch, ndev, ndev]

    return jax.jit(counts)


def _device_specs(ch, blocks, columns):
    """One (codec_tag, numpy dtype) per column, decided over every
    producer SCHEMA (no pandas, no sync) — the planned twin of
    `_classify`. Strings ride as int32 dictionary codes (`_DICT`),
    everything else as its schema dtype (`_NUM`); validity always rides
    as a mask plane next to the data."""
    specs = {}
    for c in columns:
        dts, is_str = set(), False
        for b in blocks:
            if b.schema.has(c):
                dt = b.schema.dtype(c)
                is_str = is_str or dt.is_string
                dts.add(np.dtype(dt.np).str)
        if not dts:
            raise IciPlaneError(f"channel {ch.id}: column {c!r} missing "
                                "from every producer")
        if len(dts) > 1:
            raise IciPlaneError(f"column {c!r}: producers disagree on "
                                f"dtype ({sorted(dts)})")
        np_dt = np.dtype(next(iter(dts)))
        if np_dt.kind not in "iufb":
            raise IciPlaneError(f"column {c!r}: dtype {np_dt} is not "
                                "ICI-encodable")
        specs[c] = (_DICT, np.dtype(np.int32)) if is_str \
            else (_NUM, np_dt)
    return specs


def _union_dictionaries(ch, columns, specs, devs):
    """Shared consumer dictionaries for string columns: one union
    `Dictionary` per column over every producer's values (host METADATA
    — never a device readback), plus per-producer code-remap LUTs
    (old code → union code) applied device-side via `jnp.take`."""
    from ydb_tpu.core.dictionary import Dictionary
    unions, luts = {}, {}
    for c in columns:
        if specs[c][0] != _DICT:
            continue
        u = Dictionary()
        per = []
        for (dev, n) in devs:
            d = dev.dictionaries.get(c)
            if d is None:
                if n > 0 and c in dev.arrays:
                    raise IciPlaneError(
                        f"channel {ch.id}: string column {c!r} has rows "
                        "but no dictionary on a producer")
                per.append(None)
                continue
            vals = d.values_array()
            per.append(u.encode_bulk(vals).astype(np.int32) if len(vals)
                       else np.zeros(0, np.int32))
        unions[c] = u
        luts[c] = per
    return unions, luts


def exchange_blocks(ch, blocks: list, key_kind: str = None,
                    counters=None) -> tuple:
    """Planned device-resident redistribution — the stage spine's data
    plane. Producers and consumers speak device blocks BY REFERENCE:
    `blocks[d]` is mesh device d's stage output (a `DeviceStageBlock`
    stays on the accelerator; a plain `HostBlock` from a non-fused
    stage is uploaded once), and the landed per-consumer partitions
    come back as `DeviceStageBlock`s — no pandas, no npz, no host sync
    on the row plane.

    Segment sizing is PLANNED instead of guessed: a compiled count
    exchange ships the per-(producer, target) live-row counts ([ndev,
    ndev] int32 — the one small sizing message), and the collective's
    segment size is the measured max bucketed UP onto the fine quarter-
    octave ladder (`progstore/buckets.bucket_segment`, overshoot
    <= 1.25x) so the compiled-program cache stays a handful of rungs —
    retiring the legacy 2x power-of-two padding tax. `Channel.out_bound`
    (the planner's bounds lattice) caps the sizing; a bound that
    undercuts the measured counts trips the overflow escape hatch — ONE
    rerun at full capacity, which cannot overflow. The device overflow
    flag is NEVER fetched: sizing is host-known before dispatch.

    Returns `(out_blocks, stats)`; raises `IciPlaneError` when the edge
    cannot run device-resident (the runner falls back to the host
    plane)."""
    st = _prepare_exchange(ch, blocks, key_kind, counters)
    counts_host, ce_bytes = None, 0
    if st["bucket"] is not None:
        counts_host = _exchange_counts(st)
        ce_bytes = st["ndev"] * st["ndev"] * 4
    return _finish_exchange(st, counts_host, ce_bytes, counters)


def exchange_blocks_batched(chans: list, blocks: list, key_kinds=None,
                            counters=None) -> list:
    """Stage-level batched count exchange (ROADMAP 1c): prepare EVERY
    outgoing ICI edge of the stage, ship ALL their sizing counts as ONE
    fused program + ONE `[nch, ndev, ndev]` device_get — one host round
    trip per STAGE instead of one per channel — then finish each
    collective with its own counts slice. Bucket planes pad to the
    widest channel's capacity with -1, and pad rows route nowhere, so
    each slice equals the channel's solo counts exactly. Broadcast
    edges need no counts and ride along untouched; a stage with at most
    one shuffle edge degenerates to the solo exchange. Any preparation
    failure raises `IciPlaneError` for the WHOLE stage (the runner's
    host-plane fallback re-runs every edge).

    Returns `[(out_blocks, stats)]` in channel order."""
    import jax
    import jax.numpy as jnp

    from ydb_tpu.utils import memledger

    kks = list(key_kinds) if key_kinds is not None \
        else [None] * len(chans)
    sts = [_prepare_exchange(ch, blocks, kk, counters)
           for ch, kk in zip(chans, kks)]
    shuf = [st for st in sts if st["bucket"] is not None]
    if len(shuf) > 1:
        ndev = shuf[0]["ndev"]
        capmax = max(st["cap"] for st in shuf)
        planes = [st["bucket"] if st["cap"] == capmax else jnp.pad(
            st["bucket"], ((0, 0), (0, capmax - st["cap"])),
            constant_values=-1) for st in shuf]
        csig = ("counts_batched", ndev, len(shuf), capmax)
        # lint: allow-cache-key(batched counts depend only on the geometry (ndev, nch, cap) — no tuning lever feeds them)
        cfn = _FNS.get(csig)
        if cfn is None:
            cfn = _FNS[csig] = _build_counts_batched_fn(
                ndev, len(shuf), capmax)
        all_counts = jax.device_get(cfn(jnp.stack(planes)))
        memledger.record_transfer(
            "dq/ici.py::count_exchange_batched",
            len(shuf) * ndev * ndev * 4, boundary=True)
        if counters is not None:
            counters.inc("dq/count_exchange_batched")
        for st, cm in zip(shuf, all_counts):
            st["_counts"] = cm           # already host numpy (device_get)
    elif shuf:
        shuf[0]["_counts"] = _exchange_counts(shuf[0])
    out = []
    for st in sts:
        ce = st["ndev"] * st["ndev"] * 4 \
            if st["bucket"] is not None else 0
        out.append(_finish_exchange(st, st.pop("_counts", None), ce,
                                    counters))
    return out


def _prepare_exchange(ch, blocks: list, key_kind: str = None,
                      counters=None) -> dict:
    """Upload/align every producer's buffers and compute the hash-
    shuffle bucket plane — everything `exchange_blocks` does BEFORE the
    count exchange. Split out so the stage-level batched count exchange
    (`exchange_blocks_batched`) prepares every edge once and the SAME
    code computes both the solo and the batched routing — the two can
    never drift."""
    import jax.numpy as jnp

    from ydb_tpu.dq.graph import BROADCAST, HASH_SHUFFLE
    from ydb_tpu.ops.device import DeviceStageBlock, to_device
    from ydb_tpu.progstore.buckets import bucket_segment
    from ydb_tpu.utils.hashing import splitmix64

    ndev = len(blocks)
    if ndev < 2:
        raise IciPlaneError("ICI plane needs at least 2 producers")
    mesh = _mesh(ndev)
    if ch.kind not in (HASH_SHUFFLE, BROADCAST):
        raise IciPlaneError(f"channel kind {ch.kind!r} has no ICI form")

    columns = None
    for b in blocks:
        if list(b.schema.names):
            columns = list(b.schema.names)
            break
    if columns is None:
        columns = list(ch.columns)
    if not columns:
        raise IciPlaneError(f"channel {ch.id}: no columns to exchange")
    specs = _device_specs(ch, blocks, columns)

    # quantization: same contract as the legacy path — only lowering-
    # proven columns, only plain (mask-free) floats, lever-gated;
    # refusals are loud, never silently lossy
    quant_names: list = []
    refused: list = []

    # producer buffer capacity on the fine ladder (not the legacy pow2)
    max_len = max(max((b.length for b in blocks), default=0), 1)
    cap = bucket_segment(max_len, minimum=1)

    devs = []                           # (DeviceBlock view, host length)
    for b in blocks:
        if isinstance(b, DeviceStageBlock) and not b.materialized:
            devs.append((b.device, b.length))
        else:
            devs.append((to_device(b, capacity=max(cap, b.length)),
                         b.length))

    def _masked(c):
        return any(c in dev.valids for (dev, _n) in devs)

    if quant_enabled():
        for c in ch.quant_cols:
            spec = specs.get(c)
            if spec is not None and spec[0] == _NUM \
                    and spec[1].kind == "f" and not _masked(c):
                quant_names.append(c)
            elif spec is not None:
                refused.append(c)
        if refused and counters is not None:
            counters.inc("dq/quant_refused", len(refused))
    if quant_names:
        cap = -(-cap // QUANT_BLOCK) * QUANT_BLOCK

    unions, luts = _union_dictionaries(ch, columns, specs, devs)

    def _fit(a, want, fill=None):
        m = int(a.shape[0])
        if m == want:
            return a
        if m > want:
            return a[:want]
        pad = jnp.zeros((want - m,), a.dtype) if fill is None \
            else jnp.full((want - m,), fill, a.dtype)
        return jnp.concatenate([a, pad])

    lengths = np.array([n for (_dev, n) in devs], np.int32)
    lengths_col = jnp.asarray(lengths)[:, None]
    idx_row = jnp.arange(cap, dtype=jnp.int32)[None, :]
    arrays, valids = {}, {}
    for c in columns:
        want_dt = specs[c][1]
        per_d, per_v = [], []
        for di, (dev, n) in enumerate(devs):
            if c not in dev.arrays:
                raise IciPlaneError(f"channel {ch.id}: column {c!r} "
                                    f"missing on producer {di}")
            a = dev.arrays[c]
            if specs[c][0] == _DICT:
                lut_np = luts[c][di]
                if lut_np is not None and len(lut_np):
                    lut = jnp.asarray(lut_np)
                    a = jnp.take(lut, jnp.clip(a.astype(jnp.int32), 0,
                                               len(lut_np) - 1))
            if a.dtype != want_dt:
                a = a.astype(want_dt)
            per_d.append(_fit(a, cap))
            v = dev.valids.get(c)
            per_v.append(jnp.ones((cap,), jnp.bool_) if v is None
                         else _fit(v, cap))
        arrays[c] = jnp.stack(per_d)
        valids[c] = jnp.stack(per_v)
    for c in quant_names:
        # zero the inactive tail: capture-time pad rows may hold garbage
        # whose magnitude would poison the per-block quant scales
        arrays[c] = jnp.where(idx_row < lengths_col, arrays[c], 0)

    names = tuple(columns)
    dt_sig = tuple((c, specs[c][0], str(specs[c][1])) for c in names)
    bucket = None
    if ch.kind == HASH_SHUFFLE:
        key = ch.key
        if not key or key not in columns:
            raise IciPlaneError(f"channel {ch.id}: shuffle key {key!r} "
                                "is not an exchanged column")
        kspec = specs[key]
        kind = key_kind or ("string" if kspec[0] == _DICT
                            else "float" if kspec[1].kind == "f"
                            else "int")
        if kind == "float":
            raise IciPlaneError(
                f"channel {ch.id} key {key!r}: float join keys are not "
                "hash-partitionable")
        # the bucket plane: the SAME per-row route the host plane's
        # `key_buckets` computes — splitmix64 for ints (x64 bit parity),
        # a host crc32 LUT over the union values for strings — with
        # NULL/pad rows at -1 (dropped: inner-shuffle semantics)
        if kind == "string":
            import zlib
            uvals = unions[key].values_array()
            blut_np = np.array(
                [int(np.uint64(zlib.crc32(str(v).encode())) %
                     np.uint64(ndev)) for v in uvals],
                np.int32) if len(uvals) else np.zeros(1, np.int32)
            blut = jnp.asarray(blut_np)
            bucket = jnp.take(blut, jnp.clip(
                arrays[key].astype(jnp.int32), 0, len(blut_np) - 1))
        else:
            h = splitmix64(jnp, arrays[key].astype(jnp.int64))
            bucket = (h % jnp.uint64(ndev)).astype(jnp.int32)
        active = (idx_row < lengths_col) & valids[key]
        bucket = jnp.where(active, bucket, jnp.int32(-1))

    return {
        "ch": ch, "blocks": blocks, "mesh": mesh, "ndev": ndev,
        "columns": columns, "specs": specs, "quant_names": quant_names,
        "refused": refused, "cap": cap, "lengths": lengths,
        "arrays": arrays, "valids": valids, "unions": unions,
        "names": names, "dt_sig": dt_sig, "bucket": bucket,
        "masked": {c: _masked(c) for c in columns},
    }


def _exchange_counts(st: dict):
    """The solo count exchange for ONE prepared hash-shuffle channel:
    the planned path's single host round trip — ndev^2 int32, counted
    as the blessed sizing message (the legacy row-plane device_get
    disappears entirely)."""
    import jax

    from ydb_tpu.utils import memledger

    ndev, cap = st["ndev"], st["cap"]
    csig = ("counts", ndev, cap)
    # lint: allow-cache-key(the counts program depends only on (ndev, cap) — no tuning lever feeds it)
    cfn = _FNS.get(csig)
    if cfn is None:
        cfn = _FNS[csig] = _build_counts_fn(ndev, cap)
    counts_host = jax.device_get(cfn(st["bucket"]))
    memledger.record_transfer("dq/ici.py::count_exchange",
                              ndev * ndev * 4, boundary=True)
    return counts_host


def _finish_exchange(st: dict, counts_host, ce_bytes: int,
                     counters=None) -> tuple:
    """Size, compile and run the collective from prepared state plus
    the already-exchanged sizing counts, then build the landed consumer
    blocks and the wire/padding account. `counts_host` is None exactly
    for broadcast edges (they gather full buffers — no sizing
    message)."""
    from ydb_tpu.core.schema import Column, Schema
    from ydb_tpu.ops.device import DeviceBlock, DeviceStageBlock
    from ydb_tpu.progstore.buckets import bucket_segment
    from ydb_tpu.utils import memledger

    ch, blocks, mesh = st["ch"], st["blocks"], st["mesh"]
    ndev, cap = st["ndev"], st["cap"]
    columns, specs, names = st["columns"], st["specs"], st["names"]
    dt_sig, quant_names = st["dt_sig"], st["quant_names"]
    arrays, valids = st["arrays"], st["valids"]
    lengths, unions = st["lengths"], st["unions"]
    if st["bucket"] is not None:
        max_pair = int(counts_host.max()) if counts_host.size else 0
        seg = bucket_segment(max(max_pair, 1), minimum=1)
        bound = getattr(ch, "out_bound", None)
        if bound:
            bseg = bucket_segment(int(bound), minimum=1)
            if bseg < seg:
                seg = bseg
        if max_pair > seg:
            # an unsound (or forged) bound undercut the measured counts:
            # the overflow escape hatch — ONE rerun at full capacity,
            # which cannot overflow (a target receives at most one
            # producer's full row count)
            if counters is not None:
                counters.inc("dq/planned_overflow_reruns")
            seg = cap
        if quant_names:
            seg = -(-seg // QUANT_BLOCK) * QUANT_BLOCK
        seg = min(seg, cap)

        sig = ("shuffle", ndev, cap, seg, dt_sig, tuple(quant_names))
        # lint: allow-cache-key(the quant lever rides in quant_names above — flipping YDB_TPU_DQ_QUANT changes the tuple, never serves a stale program)
        fn = _FNS.get(sig)
        if fn is None:
            dtypes = {c: specs[c][1] for c in names}
            fn = _FNS[sig] = _build_shuffle_fn(
                mesh, ndev, cap, seg, names, dtypes, tuple(quant_names))
        out_d, out_v, _lens, _ovf = fn(arrays, valids, st["bucket"],
                                       lengths)
        # _lens/_ovf are NEVER fetched: the landed totals and the
        # no-overflow verdict are host-known from the count exchange
        landed = [int(counts_host[:, d].sum()) for d in range(ndev)]
        out_cap = ndev * seg
    else:
        seg = cap                       # broadcast gathers full buffers
        sig = ("broadcast", ndev, cap, dt_sig)
        # lint: allow-cache-key(broadcast edges never quantize — quant_cols apply only to hash-shuffle segments)
        fn = _FNS.get(sig)
        if fn is None:
            fn = _FNS[sig] = _build_broadcast_fn(mesh, ndev, cap, names)
        out_d, out_v, _lens = fn(arrays, valids, lengths)
        landed = [int(lengths.sum())] * ndev
        out_cap = ndev * cap

    # landed per-consumer blocks: array REFERENCES into the collective's
    # output, wrapped with host-known lengths — the consumer stage's
    # fused scan stacks them without any readback
    out_cols, out_dicts = [], {}
    for c in columns:
        sdt = next(b.schema.dtype(c) for b in blocks if b.schema.has(c))
        out_cols.append(Column(c, sdt))
        if c in unions:
            out_dicts[c] = unions[c]
    out_schema = Schema(out_cols)
    masked = st["masked"]
    out_blocks = []
    for d in range(ndev):
        dev = DeviceBlock(
            out_schema, {c: out_d[c][d] for c in columns},
            {c: out_v[c][d] for c in columns if masked[c]},
            landed[d], out_cap, dict(out_dicts))
        out_blocks.append(DeviceStageBlock(dev, landed[d]))

    # wire + padding account: planned segments on the ladder vs the live
    # rows that actually crossed, plus the sizing messages (per-segment
    # counts and the count exchange itself)
    per_row = sum(_wire_bytes_per_row(specs[c], c in quant_names)
                  for c in columns)
    exact_row = sum(_wire_bytes_per_row(specs[c], False)
                    for c in columns)
    segs = ndev * ndev
    live_rows = int(sum(landed))
    padded_rows = segs * seg
    padded_wire = int(segs * seg * per_row + segs * 4 + ce_bytes)
    live_wire = int(live_rows * per_row)
    memledger.record_alloc("collective", memledger.deep_nbytes(
        (arrays, valids)))
    memledger.record_pad("ici_frames", live_rows, padded_rows,
                         live_wire, padded_wire)
    stats = {
        "ici_bytes": padded_wire,
        "ici_frames": segs,
        "quant_bytes_saved": int(segs * seg * (exact_row - per_row)),
        "quant_cols": list(quant_names),
        "quant_refused": list(st["refused"]),
        "pad_live_bytes": live_wire,
        "pad_padded_bytes": padded_wire,
        "pad_efficiency": round(live_wire / padded_wire, 3)
        if padded_wire else None,
        "planned": True,
        "seg": int(seg),
        "count_exchange_bytes": ce_bytes,
    }
    return out_blocks, stats
