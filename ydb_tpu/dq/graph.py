"""Stage graph — the physical distribution plan.

The analog of the reference's `TDqTasksGraph` (`dq_tasks_graph.h:43-165`):
a query lowers to *stages* (each owning one program — here a rendered
stage SQL the worker engine compiles to its `ir.Program` pipelines, or a
router-side merge select) connected by typed *channels*:

  hash_shuffle  every producer routes each row to hash(key) % n_workers
                (the HashShuffle connection — co-partitions join sides);
  broadcast     every producer ships its full output to every consumer
                (the Broadcast connection — replicated build sides);
  union_all     producers ship everything to the single consumer, order
                irrelevant (the UnionAll connection — partial-agg gather);
  merge         union_all whose producers emit sorted streams; the
                consumer restores the total order (Merge connection).

union_all / merge channels with an empty dst are *router-bound*: their
frames return in the task response and the final router stage merges
them locally. Worker-bound channels land in each consumer's exchange
buffer and materialize as transient `__xj_*` tables before the consumer
stage runs (the stage barrier).

Every channel additionally carries a *data plane* — which wire its rows
actually cross:

  host   npz frames over the workers' gRPC front (`cluster/exchange.py`
         ChannelWriter → ExchangePut), the DCN seam; always available;
  ici    device-resident redistribution over the JAX mesh
         (`ydb_tpu/dq/ici.py`: bucketize + `lax.all_to_all` + compact,
         broadcast as all-gather), chosen at lowering time when BOTH
         endpoints' tasks run on devices of the same mesh — no npz, no
         gRPC, bytes counted on `dq/ici_bytes` instead of
         `dq/channel_bytes`. A failed ICI exchange falls back to
         re-running the edge on the host plane.

`quant_cols` lists the columns the planner PROVED aggregation-tolerant
(pure SUM/AVG inputs behind a final reduction — EQuARX, arxiv
2506.17615): the ICI plane may block-quantize exactly these (int8 +
per-block scale) under `YDB_TPU_DQ_QUANT=1`; keys and group-by columns
are never listed, so they always cross exact.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

HASH_SHUFFLE = "hash_shuffle"
BROADCAST = "broadcast"
UNION_ALL = "union_all"
MERGE = "merge"

CHANNEL_KINDS = (HASH_SHUFFLE, BROADCAST, UNION_ALL, MERGE)

PLANE_HOST = "host"
PLANE_ICI = "ici"
CHANNEL_PLANES = (PLANE_HOST, PLANE_ICI)

# consumer-side temp tables must live inside the shuffle-temp namespace
# the channel RPCs enforce (`server/service.py` SHUFFLE_TMP_PREFIX)
DQ_TMP_PREFIX = "__xj_dq"


@dataclass
class Channel:
    id: str
    kind: str                       # one of CHANNEL_KINDS
    src_stage: str
    dst_stage: str = ""             # "" = router-bound (collected)
    key: str = ""                   # hash_shuffle: routing column
    columns: list = field(default_factory=list)   # produced column names
    table: str = ""                 # consumer-side temp table name
    plane: str = PLANE_HOST         # host (gRPC frames) | ici (mesh)
    # columns proven aggregation-tolerant by the lowering — the ONLY
    # candidates for block quantization on the ICI plane
    quant_cols: list = field(default_factory=list)
    # bounds lattice: proven upper bound on rows any ONE producer ships
    # over this channel (0 = unknown). Stamped by the lowering (LIMIT
    # pushdown today). Planned redistribution (`dq/ici.exchange_blocks`)
    # consumes it: the bound caps the count-exchange segment sizing, so
    # a proven-small channel never compiles a full-capacity collective
    # even before the exchanged counts arrive. The legacy 2x exchange
    # routes materialized frames and still ignores it.
    out_bound: int = 0

    @property
    def router_bound(self) -> bool:
        return not self.dst_stage


@dataclass
class Stage:
    """One stage: the same program runs as one task per worker (or a
    single task for `on="worker0"`), or router-locally for the final
    merge stage (`on="router"`)."""
    id: str
    sql: str = ""                   # worker stage program (rendered SQL)
    inputs: list = field(default_factory=list)    # channel ids consumed
    outputs: list = field(default_factory=list)   # channel ids produced
    on: str = "workers"             # workers | worker0 | router
    # router merge stage: SELECT over the gathered frame registered as a
    # temp table — relation is TableRef(INPUT_TABLE), swapped at run time
    merge_sel: Optional[object] = None
    # router stage host-side tail: {"distinct", "order", "limit",
    # "offset"} applied via apply_order_limit (scan-shape merges whose
    # ORDER BY refers to output columns)
    post: Optional[dict] = None
    dedup_input: bool = False       # drop cross-worker duplicate rows
    # partial-aggregate merge stage: its merge_sel GROUP BY re-plans
    # through the engine and rides the tiled sorted group-by like any
    # statement (counted as dq/merge_groupby_stages)
    groupby_merge: bool = False

INPUT_TABLE = "__dq_partial__"      # merge_sel relation placeholder


@dataclass
class StageGraph:
    """Stages in topological order (lowering emits producers first) +
    the channel table. Exactly one router stage, last, produces the
    statement result."""
    stages: list = field(default_factory=list)
    channels: dict = field(default_factory=dict)
    tag: str = ""
    # Hive placement epoch this graph was lowered against (0 = static
    # topology) — a failover re-lowers, so a rerun graph carries the
    # epoch whose worker set it actually tasks
    placement_epoch: int = 0

    def stage(self, sid: str) -> Stage:
        for s in self.stages:
            if s.id == sid:
                return s
        raise KeyError(sid)

    def validate(self) -> None:
        seen: set = set()
        routers = [s for s in self.stages if s.on == "router"]
        if len(routers) != 1 or self.stages[-1].on != "router":
            raise ValueError("StageGraph needs exactly one router stage, "
                             "last")
        for ch in self.channels.values():
            if ch.kind not in CHANNEL_KINDS:
                raise ValueError(f"bad channel kind {ch.kind!r}")
            if ch.kind in (HASH_SHUFFLE, BROADCAST) and ch.router_bound:
                raise ValueError(f"{ch.kind} channel {ch.id} cannot be "
                                 "router-bound")
            if ch.plane not in CHANNEL_PLANES:
                raise ValueError(f"bad channel plane {ch.plane!r}")
            if ch.plane == PLANE_ICI and ch.router_bound:
                # router-bound channels collect in the task response;
                # there is no device edge to ride
                raise ValueError(f"channel {ch.id} cannot be ICI-plane "
                                 "and router-bound")
            if not ch.router_bound and not ch.table.startswith("__xj_"):
                raise ValueError(f"channel temp {ch.table!r} outside the "
                                 "__xj_* namespace")
        for s in self.stages:
            for cid in s.inputs:
                ch = self.channels[cid]
                if ch.src_stage not in seen:
                    raise ValueError(
                        f"stage {s.id} consumes {cid} before its producer "
                        f"{ch.src_stage} (not topological)")
            seen.add(s.id)

    def explain(self) -> str:
        lines = []
        for s in self.stages:
            outs = ", ".join(
                f"{c}:{self.channels[c].kind}"
                + (f"({self.channels[c].key})"
                   if self.channels[c].key else "")
                + (f" plane={self.channels[c].plane}"
                   if self.channels[c].plane != PLANE_HOST else "")
                for c in s.outputs)
            lines.append(f"stage {s.id} on={s.on}"
                         + (f" inputs={s.inputs}" if s.inputs else "")
                         + (f" -> {outs}" if outs else " -> result"))
            if s.sql:
                lines.append(f"  {s.sql}")
        return "\n".join(lines)
