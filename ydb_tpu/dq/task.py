"""Worker-side task execution — the DQ compute-actor seat.

One *task* = (stage, worker): run the stage program through the local
engine, then route the result over the stage's output channels —
hash-partitioned to peers, broadcast to every peer, or collected back to
the runner for a router-bound channel. Shared verbatim by the gRPC
servicer (`server/service.py` DqRunTask) and the in-process
`LocalWorker` (`dq/runner.py`), so the 1-worker degenerate case runs the
exact code the cluster runs.
"""

from __future__ import annotations

from ydb_tpu.utils.metrics import GLOBAL


def run_task(engine, sql: str, outputs: list, src: str, send,
             token: str = "", counters=None) -> dict:
    """Execute one task. `outputs`: [{"channel", "kind", "key", "n_peers"}]
    specs; `send(out, peer_idx, frame_bytes)` is the transport for
    worker-bound channels. Returns {"ok", "rows_in", "dtypes",
    "bytes_shipped", "frames_shipped"[, "collected_df"]} — the caller
    serializes `collected_df` for the wire."""
    from ydb_tpu.cluster.exchange import ChannelWriter, hash_partition
    counters = counters or GLOBAL
    executor = engine.executor
    executor.dq_stage_depth += 1
    try:
        block = engine.execute(sql)
    finally:
        executor.dq_stage_depth -= 1
    df = block.to_pandas()
    resp = {"ok": True, "rows_in": len(df),
            "dtypes": {c: str(df[c].dtype) for c in df.columns}}
    total_bytes = total_frames = 0
    for out in outputs:
        kind = out["kind"]
        if kind in ("union_all", "merge"):
            resp["collected_df"] = df
            continue
        n_peers = int(out["n_peers"])
        if kind == "hash_shuffle":
            key = out["key"]
            # the key's hash route comes from the SCHEMA, not the pandas
            # dtype: nullable int keys widen to object dtype in pandas
            # and would otherwise string-hash on this producer while a
            # NOT NULL producer int-hashes — the same key landing on two
            # consumers silently drops sharded-join matches
            kkind = None
            if block.schema.has(key):
                dt = block.schema.dtype(key)
                kkind = ("string" if dt.is_string
                         else "float" if dt.is_float else "int")
            parts = hash_partition(df, key, n_peers, kind=kkind)
        elif kind == "broadcast":
            parts = [df] * n_peers
        else:
            raise ValueError(f"bad output channel kind {kind!r}")
        writer = ChannelWriter(
            out["channel"], src,
            lambda p, frame, _o=out: send(_o, p, frame),
            n_peers, token=token, counters=counters)
        try:
            for p in range(n_peers):
                writer.ship(p, parts[p])
        finally:
            writer.close()
        total_bytes += writer.bytes_sent
        total_frames += writer.frames_sent
    resp["bytes_shipped"] = total_bytes
    resp["frames_shipped"] = total_frames
    counters.inc("dq/tasks")
    if total_frames:
        counters.inc("dq/frames", total_frames)
        counters.inc("dq/channel_bytes", total_bytes)
    return resp


def materialize_channel(engine, exchange, channel: str, table: str,
                        columns=None) -> int:
    """Drain a channel's frames into a transient local table — the stage
    barrier's consumer side (ChannelOpen). `columns`: [(name, dtype)] so
    a worker that received no partitions still registers a typed temp.
    Namespace/auth policy stays with the caller (the servicer)."""
    from ydb_tpu.core.block import HostBlock
    from ydb_tpu.storage.mvcc import WriteVersion
    df = exchange.take(channel)
    if df.empty and columns:
        df = empty_typed_frame(columns)
    block = HostBlock.from_pandas(df)
    if engine.catalog.has(table):
        # drop-and-recreate only ever replaces a transient temp: a
        # durable table that happens to sit in the namespace is not ours
        # to clobber
        old = engine.catalog.table(table)
        if not getattr(old, "transient", False):
            raise ValueError(f"refusing to replace non-transient table "
                             f"{table!r}")
        engine.catalog.drop_table(table)
    t = engine.catalog.create_table(
        table, block.schema, [block.schema.names[0]], transient=True)
    # the block's dictionaries BECOME the table's: the binder reads
    # table-level dictionaries for group-by domains and rank LUTs —
    # leaving the fresh empty ones in place makes every string key
    # decode to code 0
    t.dictionaries = {n: cd.dictionary
                      for n, cd in block.columns.items()
                      if cd.dictionary is not None}
    t.commit(t.write(block), WriteVersion(1, 1))
    t.indexate()
    return block.length


def empty_typed_frame(columns):
    """Zero-row frame with the stage schema's dtypes — a worker whose
    channel received no partitions still registers a typed temp table."""
    import numpy as np
    import pandas as pd
    cols = {}
    for (name, dtype) in columns:
        if dtype in ("object", "str"):
            cols[name] = np.empty(0, dtype=object)
        else:
            cols[name] = np.empty(0, dtype=np.dtype(dtype))
    return pd.DataFrame(cols)
