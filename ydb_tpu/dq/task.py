"""Worker-side task execution — the DQ compute-actor seat.

One *task* = (stage, worker): run the stage program through the local
engine, then route the result over the stage's output channels —
hash-partitioned to peers, broadcast to every peer, or collected back to
the runner for a router-bound channel. Shared verbatim by the gRPC
servicer (`server/service.py` DqRunTask) and the in-process
`LocalWorker` (`dq/runner.py`), so the 1-worker degenerate case runs the
exact code the cluster runs.

Profiling: a task that arrives with a trace context ({trace_id,
parent_span_id, sampled} — the NWilson::TTraceId analog riding the
DqRunTask RPC) adopts it on the worker engine's tracer, records its
exec / output-flush spans (with the engine's own statement + device
sub-spans nested inside), and ships the finished span list back in the
response (`resp["profile"]["spans"]`) for the runner to `ingest()` into
the router's tree. Per-channel producer stats (frames/rows/bytes/
backpressure wait) ship back unconditionally — they cost nothing and
feed `.sys/dq_stage_stats` even for unsampled queries.
"""

from __future__ import annotations

from ydb_tpu.utils.metrics import GLOBAL


def run_task(engine, sql: str, outputs: list, src: str, send,
             token: str = "", counters=None, trace=None) -> dict:
    """Execute one task. `outputs`: [{"channel", "kind", "key", "n_peers"}]
    specs; `send(out, peer_idx, frame_bytes)` is the transport for
    worker-bound channels; `trace`: the propagated context (or None).
    Returns {"ok", "rows_in", "dtypes", "bytes_shipped", "frames_shipped",
    "profile"[, "collected_df"]} — the caller serializes `collected_df`
    for the wire."""
    counters = counters or GLOBAL
    executor = engine.executor
    tracer = getattr(engine, "tracer", None)
    # clock-alignment stamps (this worker's tracer clock at RPC receive
    # and response build): the runner pairs them with its own send/recv
    # timestamps to estimate this worker's clock offset (NTP-style
    # midpoint) and rebase every ingested span onto the router timebase.
    # Shipped UNCONDITIONALLY — unsampled traffic keeps the EWMA warm.
    w_recv = tracer._now() if tracer is not None else None
    adopt = trace is not None and tracer is not None
    sampled = bool(adopt and trace.get("sampled"))
    if adopt:
        # adopt the ROUTER's decision either way: an UNSAMPLED context
        # still opens an (unsampled) trace so the stage statement runs
        # nested — otherwise the worker engine would treat internal
        # stage SQL as an outermost user statement, re-sample it, drain
        # the deterministic sampling accumulator, and push uuid-named
        # stage programs into the worker's query-profiles ring
        tracer.begin_trace(sampled=sampled,
                           trace_id=trace.get("trace_id"),
                           parent_id=trace.get("parent_span_id"))
    spans = []
    try:
        resp = _run_task_body(engine, executor, sql, outputs, src, send,
                              token, counters, tracer, sampled, trace)
    finally:
        if adopt:
            # end_trace force-closes anything a raising path left open,
            # so the worker tracer never leaks state into its next task
            spans = tracer.end_trace()
    if sampled:
        resp["profile"]["spans"] = [s.to_dict() for s in spans]
    if w_recv is not None:
        resp.setdefault("profile", {})["clock"] = {
            "recv_ms": round(w_recv, 3),
            "send_ms": round(tracer._now(), 3)}
    return resp


def _run_task_body(engine, executor, sql, outputs, src, send, token,
                   counters, tracer, sampled, trace):
    import time
    from contextlib import nullcontext

    from ydb_tpu.cluster.exchange import ChannelWriter, hash_partition

    def span(name, **attrs):
        return tracer.span(name, **attrs) if sampled else nullcontext()

    channel_stats: list = []
    t0 = time.perf_counter()
    with span("task-exec", src=src):
        executor.dq_stage_depth += 1
        executor.dq_device_capture = True
        try:
            block = engine.execute(sql)
        finally:
            executor.dq_stage_depth -= 1
            executor.dq_device_capture = False
    exec_ms = (time.perf_counter() - t0) * 1000.0
    # the device-resident stage spine: the block stays wherever the
    # engine produced it (a `DeviceStageBlock` for fused plans — still
    # on the accelerator). Pandas materializes LAZILY below: only a
    # host-plane egress lane pays the readback, and only the
    # hash_shuffle/broadcast escape hatch books it as in-plan host-sync
    # debt (`hostsync/to_pandas_in_plan` — the counter the spine gate
    # pins to zero). ICI edges ship the block BY REFERENCE.
    resp = {"ok": True, "rows_in": int(block.length),
            "dtypes": _schema_dtypes(block)}
    df_box: list = []
    debt_box: list = []

    def df_for(debt: bool):
        """Materialize pandas ONCE for host-plane egress. `debt=True`
        lanes (the hash_shuffle/broadcast escape hatch) book the
        readback on `hostsync/to_pandas_in_plan`; the router-bound
        collection is the worker's result egress — the one blessed
        boundary — and stays debt-free."""
        if not df_box:
            # lint: transfer-ok(host-plane egress — the block records its own boundary readback; escape-hatch lanes book in-plan debt below)
            df = block.to_pandas()
            resp["dtypes"] = {c: str(df[c].dtype) for c in df.columns}
            df_box.append(df)
        if debt and not debt_box:
            debt_box.append(True)
            from ydb_tpu.utils import memledger
            memledger.record_transfer(
                "dq/task.py::host_plane_to_pandas",
                int(df_box[0].memory_usage(index=False).sum()),
                to_pandas_in_plan=True)
        return df_box[0]

    total_bytes = total_frames = 0
    t0 = time.perf_counter()
    with span("output-flush", channels=len(outputs),
              channel_ids=",".join(str(o["channel"]) for o in outputs)):
        for out in outputs:
            kind = out["kind"]
            if kind in ("union_all", "merge"):
                resp["collected_df"] = df_for(debt=False)
                channel_stats.append({
                    "channel": out["channel"], "frames": 0,
                    "rows": int(block.length), "bytes": 0,
                    "backpressure_wait_ms": 0.0})
                continue
            if out.get("plane") == "ici":
                # device-resident edge: NO frames leave this task — the
                # runner (which owns the mesh) collects every producer's
                # stage output and executes the redistribution as ONE
                # collective (`dq/ici.py`). Ship the BLOCK by reference
                # (ICI edges only lower between in-process mesh
                # workers) plus the schema's hash-kind verdict for the
                # routing key, the same signal the host plane feeds
                # `hash_partition`.
                resp["ici_block"] = block
                kkinds = resp.setdefault("ici_key_kinds", {})
                key = out.get("key", "")
                if key and block.schema.has(key):
                    dt = block.schema.dtype(key)
                    kkinds[out["channel"]] = (
                        "string" if dt.is_string
                        else "float" if dt.is_float else "int")
                channel_stats.append({
                    "channel": out["channel"], "frames": 0,
                    "rows": int(block.length), "bytes": 0, "plane": "ici",
                    "backpressure_wait_ms": 0.0})
                continue
            n_peers = int(out["n_peers"])
            if kind == "hash_shuffle":
                key = out["key"]
                # the key's hash route comes from the SCHEMA, not the
                # pandas dtype: nullable int keys widen to object
                # dtype in pandas and would otherwise string-hash on
                # this producer while a NOT NULL producer int-hashes
                # — the same key landing on two consumers silently
                # drops sharded-join matches
                kkind = None
                if block.schema.has(key):
                    dt = block.schema.dtype(key)
                    kkind = ("string" if dt.is_string
                             else "float" if dt.is_float else "int")
                parts = hash_partition(df_for(debt=True), key, n_peers,
                                       kind=kkind)
            elif kind == "broadcast":
                parts = [df_for(debt=True)] * n_peers
            else:
                raise ValueError(f"bad output channel kind {kind!r}")
            writer = ChannelWriter(
                out["channel"], src,
                lambda p, frame, _o=out: send(_o, p, frame),
                n_peers, token=token, counters=counters, trace=trace)
            try:
                for p in range(n_peers):
                    writer.ship(p, parts[p])
            finally:
                writer.close()
            total_bytes += writer.bytes_sent
            total_frames += writer.frames_sent
            channel_stats.append(writer.stats())
    flush_ms = (time.perf_counter() - t0) * 1000.0
    resp["bytes_shipped"] = total_bytes
    resp["frames_shipped"] = total_frames
    resp["profile"] = {
        "exec_ms": round(exec_ms, 3),
        "flush_ms": round(flush_ms, 3),
        "channels": channel_stats,
    }
    wait = sum(c["backpressure_wait_ms"] for c in channel_stats)
    if wait:
        from ydb_tpu.utils.metrics import GLOBAL_HIST
        GLOBAL_HIST.observe("dq/channel_wait_ms", wait)
    counters.inc("dq/tasks")
    if total_frames:
        counters.inc("dq/frames", total_frames)
        counters.inc("dq/channel_bytes", total_bytes)
    return resp


def _schema_dtypes(block) -> dict:
    """The pandas dtype `to_pandas` WOULD render, derived from the
    schema WITHOUT materializing host arrays: strings and NULL-bearing
    columns widen to object, everything else keeps its numpy dtype
    name. For a device-resident block a still-on-device validity mask
    reads as nullable (collapsing an all-valid mask to None is host
    knowledge the spine refuses to sync for); every host-plane egress
    lane overwrites these hints with exact pandas dtypes."""
    import numpy as np
    dev = getattr(block, "device", None)
    use_dev = dev is not None and not block.materialized
    out = {}
    for c in block.schema:
        masked = (c.name in dev.valids) if use_dev \
            else (block.columns[c.name].valid is not None)
        out[c.name] = "object" if (c.dtype.is_string or masked) \
            else np.dtype(c.dtype.np).name
    return out


def materialize_device_channel(engine, block, table: str) -> dict:
    """ChannelOpen, device-resident: register a landed
    `DeviceStageBlock` as the transient channel table WITHOUT
    materializing host arrays — the consumer stage's fused scan stacks
    the device columns directly (`storage/device_cache.py` superblock
    fast path), so a multi-stage plan never leaves the accelerator
    between stages. `indexate()` is deliberately skipped: portion
    min/max stats are host readbacks, and a committed-but-unindexed
    insert entry is a first-class scan source."""
    import time

    from ydb_tpu.storage.mvcc import WriteVersion
    t0 = time.perf_counter()
    if engine.catalog.has(table):
        old = engine.catalog.table(table)
        if not getattr(old, "transient", False):
            raise ValueError(f"refusing to replace non-transient table "
                             f"{table!r}")
        engine.catalog.drop_table(table)
    t = engine.catalog.create_table(
        table, block.schema, [block.schema.names[0]], transient=True)
    # the landed block's dictionaries BECOME the table's (same contract
    # as the host-plane materialize below)
    t.dictionaries = dict(block.device.dictionaries)
    t.commit(t.write(block), WriteVersion(1, 1))
    return {"rows": block.length, "bytes": block.live_nbytes(),
            "wait_ms": round((time.perf_counter() - t0) * 1000.0, 3)}


def materialize_channel(engine, exchange, channel: str, table: str,
                        columns=None) -> dict:
    """Drain a channel's frames into a transient local table — the stage
    barrier's consumer side (ChannelOpen). `columns`: [(name, dtype)] so
    a worker that received no partitions still registers a typed temp.
    Namespace/auth policy stays with the caller (the servicer).
    Returns {"rows", "bytes", "wait_ms"} — the consumer-side channel
    stat (input drain + table build time) the runner attributes as the
    stage's input-wait."""
    import time

    from ydb_tpu.core.block import HostBlock
    from ydb_tpu.storage.mvcc import WriteVersion
    t0 = time.perf_counter()
    df, nbytes = exchange.take2(channel)
    if df.empty and columns:
        df = empty_typed_frame(columns)
    block = HostBlock.from_pandas(df)
    if engine.catalog.has(table):
        # drop-and-recreate only ever replaces a transient temp: a
        # durable table that happens to sit in the namespace is not ours
        # to clobber
        old = engine.catalog.table(table)
        if not getattr(old, "transient", False):
            raise ValueError(f"refusing to replace non-transient table "
                             f"{table!r}")
        engine.catalog.drop_table(table)
    t = engine.catalog.create_table(
        table, block.schema, [block.schema.names[0]], transient=True)
    # the block's dictionaries BECOME the table's: the binder reads
    # table-level dictionaries for group-by domains and rank LUTs —
    # leaving the fresh empty ones in place makes every string key
    # decode to code 0
    t.dictionaries = {n: cd.dictionary
                      for n, cd in block.columns.items()
                      if cd.dictionary is not None}
    t.commit(t.write(block), WriteVersion(1, 1))
    t.indexate()
    return {"rows": block.length, "bytes": int(nbytes),
            "wait_ms": round((time.perf_counter() - t0) * 1000.0, 3)}


def empty_typed_frame(columns):
    """Zero-row frame with the stage schema's dtypes — a worker whose
    channel received no partitions still registers a typed temp table."""
    import numpy as np
    import pandas as pd
    cols = {}
    for (name, dtype) in columns:
        if dtype in ("object", "str"):
            cols[name] = np.empty(0, dtype=object)
        else:
            cols[name] = np.empty(0, dtype=np.dtype(dtype))
    return pd.DataFrame(cols)
