"""DQ task-graph runtime — the generic stage/task/channel execution layer.

The reference distributes every query through one abstraction: a task
graph of *stages* connected by *channels* (`dq_tasks_graph.h:43-165`),
executed as one task per (stage, partition) with data streamed over
output channels (`dq_output_channel.cpp:31`). This package is that
abstraction for the cluster seam:

  * `graph`  — StageGraph / Stage / Channel dataclasses (UnionAll,
    HashShuffle, Broadcast, Merge edges);
  * `lower`  — SELECT AST + shard topology → StageGraph (the planner's
    lowering pass; subsumes the router's per-shape rewrites);
  * `task`   — worker-side task execution: run the stage program, route
    the output over its channels (hash/broadcast/collect);
  * `runner` — the control plane: one task per (stage, worker), a
    pending→running→finished/failed state machine, stage-level retry on
    channel failure, and the router-side merge stage. `LocalWorker`
    makes the in-process engine the 1-worker degenerate case.
"""

from ydb_tpu.dq.graph import Channel, Stage, StageGraph  # noqa: F401
from ydb_tpu.dq.lower import DqLowerError, DqTopology, lower_select  # noqa: F401
from ydb_tpu.dq.runner import DqError, DqTaskRunner, LocalWorker  # noqa: F401
