"""Shape-bucketed polymorphism for scan source counts.

A table that grows by one portion at a time would mint a new program
shape per portion count — the `.sys/compiled_programs` inventory of a
steadily loaded table shows exactly that churn. Quantizing the
superblock row count K to a geometric ladder (ratio ~1.41: 1, 2, 3, 4,
6, 8, 12, 16, 24, 32, ...) caps the shapes a growing table can visit
at O(log n); the superblock pads the extra rows with zero-length
sources, which the fused kernels already mask out via the per-row
length vector, so padded execution is byte-equal to exact-K execution.

`bucket_sources` is the single tuning provider every bucketed cache
key must flow through: the bucketed K lands IN the superblock cache
key and IN the fused/batched program keys, so flipping
`YDB_TPU_SHAPE_BUCKETS` can never alias a padded program with an exact
one. `YDB_TPU_SHAPE_BUCKETS=0` disables bucketing (exact K, byte-equal
legacy shapes); any other value is the ladder ceiling above which K
passes through unbucketed (default 4096 — a table that large has
outgrown the single-superblock fused path anyway).
"""

from __future__ import annotations

import os

_DEFAULT_CEILING = 4096


def bucket_ceiling() -> int:
    """`YDB_TPU_SHAPE_BUCKETS` lever: 0 disables, else the largest K
    the ladder covers (default 4096)."""
    raw = os.environ.get("YDB_TPU_SHAPE_BUCKETS", "").strip()
    if raw == "0":
        return 0
    try:
        v = int(raw)
    except ValueError:
        v = 0
    return v if v > 0 else _DEFAULT_CEILING


def enabled() -> bool:
    return bucket_ceiling() > 0


def ladder(limit: int) -> tuple:
    """The geometric bucket ladder up to and including `limit`:
    1, 2, 3, 4, 6, 8, 12, 16, 24, 32, ... — the union of 2^e and
    3*2^e, ratio ~1.41, so a growing table visits O(log n) shapes."""
    vals = {1}
    e = 0
    while 2 ** e <= limit:
        vals.add(2 ** e)
        if 3 * 2 ** e <= limit:
            vals.add(3 * 2 ** e)
        e += 1
    return tuple(sorted(vals))


def bucket_sources(k: int) -> int:  # lint: tuning-provider
    """Quantize a scan source count UP to its ladder bucket. Identity
    when bucketing is off, K is degenerate, or K exceeds the ladder
    ceiling."""
    ceiling = bucket_ceiling()
    if ceiling <= 0 or k <= 1 or k > ceiling:
        return k
    for b in ladder(ceiling):
        if b >= k:
            return b
    return k


def segment_ladder(limit: int) -> tuple:
    """The FINE quarter-octave ladder for collective segment sizes:
    {m * 2^e : m in {4, 5, 6, 7}} — 4, 5, 6, 7, 8, 10, 12, 14, 16,
    20, ... — ratio <= 1.25. Segment padding is wire bytes shipped
    ndev^2 times, so the coarse ~1.41-ratio source ladder (up to 1.5x
    overshoot) would blow the <= 1.3x wire/live budget on its own;
    this ladder caps the per-segment overshoot at 25% while keeping
    the program-shape count O(log n)."""
    vals = {v for v in (1, 2, 3) if v <= limit}
    e = 0
    while 4 * 2 ** e <= limit:
        for m in (4, 5, 6, 7):
            if m * 2 ** e <= limit:
                vals.add(m * 2 ** e)
        e += 1
    return tuple(sorted(vals))


def bucket_segment(n: int, minimum: int = 1) -> int:  # lint: tuning-provider
    """Quantize a collective segment size UP to its fine-ladder rung
    (at least `minimum`, itself rounded up to a rung). Unlike
    `bucket_sources` this is NOT gated by `YDB_TPU_SHAPE_BUCKETS`:
    planned redistribution always buckets its segments — the ladder IS
    the shape-stability mechanism, not an optional compression of an
    exact shape."""
    n = max(int(n), int(minimum), 1)
    if n <= 4:
        return n                      # 1, 2, 3, 4 are their own rungs
    e = 0
    while 7 * 2 ** e < n:
        e += 1
    for m in (4, 5, 6, 7):
        if m * 2 ** e >= n:
            return m * 2 ** e
    return 8 * 2 ** e
