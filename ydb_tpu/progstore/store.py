"""Persistent compiled-program store — XLA AOT executables that
outlive the process.

The reference YDB runs a compile service so query programs survive
session churn; here the equivalent is a content-addressed directory.
Every fresh AOT capture (`utils/progstats.capture`) serializes its
`jax.stages.Compiled` via `jax.experimental.serialize_executable` and
writes it under `YDB_TPU_PROGSTORE=<dir>`; a restarted process (or a
failover adoptee pointed at the same data dir) consults the store
before compiling and dispatches the deserialized executable —
`prog/store_hits` with `compile_ms ~= 0`.

Layout (one directory, human-inspectable):

    <dir>/manifest.jsonl      append-only index, latest line per key
                              wins; `"obj": null` lines are tombstones
    <dir>/objects/<digest>.bin pickled {payload, in_tree, out_tree,
                              extra}; <digest> = blake2s of the bytes,
                              re-verified at every load

A manifest line carries the store FORMAT version, an environment
fingerprint (jax + jaxlib versions — a serialized executable is not
portable across XLA revisions) and a device fingerprint (platform +
device kind + device count). The failure ladder is deliberate:

  * unknown key                → `prog/store_misses`, plain cold miss
  * format/env version skew,
    bad checksum, unpicklable,
    undeserializable           → `prog/store_corrupt`: the object is
                                 DELETED from disk, a tombstone is
                                 appended, and the caller sees a cold
                                 miss — never a crash, never a
                                 wrong-program dispatch
  * device fingerprint
    mismatch                   → `prog/store_refused`: the entry is
                                 refused but KEPT (a data dir copied
                                 from a CPU warmer is still valid back
                                 on CPU); the caller compiles fresh
  * any I/O error              → `prog/store_errors`, treated as miss

Cache keys are big tuples of fingerprints, signatures, frozensets and
numpy dtypes whose `repr` is not stable across processes (hash
randomization reorders set/dict iteration), so the store key is a
blake2s digest of a CANONICAL encoding (`canon_bytes`) that sorts
unordered collections and normalizes numpy/enum scalars.

`YDB_TPU_PROGSTORE` unset/empty/`0` disables everything: no directory
is created, no files are written, loads return None — byte-equal to
the pre-store engine. `YDB_TPU_PROGSTORE_DEVICE` overrides the device
fingerprint (the fault-injection hook the mismatch regression test
uses to simulate a foreign-backend store).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import threading
import time

import numpy as np

from ydb_tpu.utils.metrics import GLOBAL

# bump whenever the object body layout or the manifest schema changes —
# old entries then read as version skew and are evicted as corrupt
FORMAT_VERSION = 1

_MU = threading.Lock()
_STORES: dict = {}                     # guarded-by: _MU — root -> ProgramStore


def store_dir():
    """The `YDB_TPU_PROGSTORE` lever: a directory path enables the
    store, unset/empty/`0` disables it (no files, byte-equal)."""
    raw = os.environ.get("YDB_TPU_PROGSTORE", "").strip()
    if raw in ("", "0"):
        return None
    return raw


def enabled() -> bool:
    return store_dir() is not None


def env_fingerprint() -> str:
    """jax + jaxlib versions: the XLA revision pair a serialized
    executable is pinned to."""
    import jax
    import jaxlib
    return f"jax={jax.__version__};jaxlib={jaxlib.__version__}"


def device_fingerprint() -> str:
    """platform : device kind : local device count — what the
    executable was compiled FOR. `YDB_TPU_PROGSTORE_DEVICE` overrides
    (test hook for the copied-data-dir mismatch guard)."""
    spoof = os.environ.get("YDB_TPU_PROGSTORE_DEVICE", "").strip()
    if spoof:
        return spoof
    try:
        import jax
        devs = jax.local_devices()
        kind = str(getattr(devs[0], "device_kind", "unknown"))
        return f"{jax.default_backend()}:{kind}:{len(devs)}"
    except Exception:                  # noqa: BLE001 — fingerprint only
        return "unknown:unknown:0"


# --------------------------------------------------------------------------
# canonical key encoding
# --------------------------------------------------------------------------


def _canon(x, out: list) -> None:
    """Append a canonical token stream for `x`. Unordered collections
    are sorted by their own canonical encoding; numpy scalars/dtypes
    and enums normalize to stable primitives; anything unknown falls
    back to repr (cache keys in this repo are built from canonical
    primitives, so the fallback is a safety net, not a path)."""
    if isinstance(x, bool) or x is None:
        out.append(f"b:{x};")
    elif isinstance(x, int):
        out.append(f"i:{x};")
    elif isinstance(x, float):
        out.append(f"f:{x!r};")
    elif isinstance(x, str):
        out.append(f"s:{len(x)}:{x};")
    elif isinstance(x, bytes):
        out.append(f"y:{x.hex()};")
    elif isinstance(x, (tuple, list)):
        out.append(f"t:{len(x)}[")
        for item in x:
            _canon(item, out)
        out.append("]")
    elif isinstance(x, (set, frozenset)):
        parts = []
        for item in x:
            sub: list = []
            _canon(item, sub)
            parts.append("".join(sub))
        out.append(f"u:{len(x)}[" + "".join(sorted(parts)) + "]")
    elif isinstance(x, dict):
        items = []
        for k, v in x.items():
            sub = []
            _canon(k, sub)
            _canon(v, sub)
            items.append("".join(sub))
        out.append(f"d:{len(x)}[" + "".join(sorted(items)) + "]")
    elif isinstance(x, np.dtype):
        out.append(f"n:{x.str};")
    elif isinstance(x, np.generic):
        _canon(x.item(), out)
    elif hasattr(x, "value") and hasattr(type(x), "__members__"):
        # Enum member: class name + value, import-order independent
        out.append(f"e:{type(x).__name__}:{x.value!r};")
    else:
        out.append(f"r:{x!r};")


def canon_bytes(key) -> bytes:
    out: list = []
    _canon(key, out)
    return "".join(out).encode()


def key_digest(kind: str, key) -> str:
    h = hashlib.blake2s(digest_size=16)
    h.update(kind.encode())
    h.update(b"\x00")
    h.update(canon_bytes(key))
    return h.hexdigest()


def _body_digest(body: bytes) -> str:
    return hashlib.blake2s(body, digest_size=16).hexdigest()


# --------------------------------------------------------------------------
# the store proper
# --------------------------------------------------------------------------


class ProgramStore:
    """One on-disk store rooted at `root`. The manifest is read once at
    open and maintained in memory; writes append (manifest lines are
    one JSON object per line, latest per key wins). Thread-safe; the
    sequential-process restart story (gate: run, kill -9, rerun) needs
    no cross-process locking because objects are content-addressed and
    the manifest is append-only."""

    def __init__(self, root: str):
        self.root = root
        self._mu = threading.Lock()
        self._index: dict = {}         # key digest -> manifest line dict
        self._loads = 0
        self._saves = 0
        os.makedirs(os.path.join(root, "objects"), exist_ok=True)
        self._read_manifest()

    # -- manifest ----------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.root, "manifest.jsonl")

    def _read_manifest(self) -> None:
        try:
            with open(self._manifest_path(), "r", encoding="utf-8") as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        ent = json.loads(ln)
                    except ValueError:
                        continue       # torn tail line from a kill -9
                    k = ent.get("key")
                    if not k:
                        continue
                    if ent.get("obj") is None:
                        self._index.pop(k, None)   # tombstone
                    else:
                        self._index[k] = ent
        except FileNotFoundError:
            pass
        except OSError:
            GLOBAL.inc("prog/store_errors")

    def _append_manifest(self, ent: dict) -> None:
        line = json.dumps(ent, sort_keys=True) + "\n"
        with open(self._manifest_path(), "a", encoding="utf-8") as f:
            f.write(line)

    # -- corruption handling -----------------------------------------------

    def _evict_corrupt(self, kd: str, ent: dict) -> None:
        """Satellite contract: a corrupt/skewed entry is counted,
        DELETED from disk and tombstoned — the next process never
        retries it."""
        GLOBAL.inc("prog/store_corrupt")
        obj = ent.get("obj")
        with self._mu:
            self._index.pop(kd, None)
            try:
                if obj:
                    try:
                        os.unlink(self._obj_path(obj))
                    except FileNotFoundError:
                        pass
                self._append_manifest({"v": FORMAT_VERSION, "key": kd,
                                       "obj": None, "ts": time.time()})
            except OSError:
                GLOBAL.inc("prog/store_errors")

    def _obj_path(self, digest: str) -> str:
        return os.path.join(self.root, "objects", f"{digest}.bin")

    # -- load / save -------------------------------------------------------

    def load(self, kind: str, key):
        """Deserialize the stored executable for (kind, key), or None.

        Returns `{"compiled", "extra"}` on a hit. Every non-hit path is
        a counted cold miss for the caller; this method never raises."""
        kd = key_digest(kind, key)
        with self._mu:
            ent = self._index.get(kd)
        if ent is None:
            GLOBAL.inc("prog/store_misses")
            return None
        if ent.get("v") != FORMAT_VERSION or \
                ent.get("env") != env_fingerprint():
            self._evict_corrupt(kd, ent)           # version skew
            return None
        if ent.get("device") != device_fingerprint():
            # a foreign-backend store must not dispatch here — refuse
            # loudly but keep the entry (it is valid on ITS device)
            GLOBAL.inc("prog/store_refused")
            return None
        try:
            with open(self._obj_path(ent["obj"]), "rb") as f:
                body = f.read()
        except FileNotFoundError:
            self._evict_corrupt(kd, ent)
            return None
        except OSError:
            GLOBAL.inc("prog/store_errors")
            return None
        if _body_digest(body) != ent["obj"]:
            self._evict_corrupt(kd, ent)           # truncated / garbage
            return None
        try:
            rec = pickle.loads(body)
            from jax.experimental import serialize_executable
            compiled = serialize_executable.deserialize_and_load(
                rec["payload"], rec["in_tree"], rec["out_tree"])
        except Exception:              # noqa: BLE001 — corrupt payload
            self._evict_corrupt(kd, ent)
            return None
        GLOBAL.inc("prog/store_hits")
        with self._mu:
            self._loads += 1
        return {"compiled": compiled, "extra": rec.get("extra") or {}}

    def save(self, kind: str, key, compiled, extra=None) -> bool:
        """Serialize a freshly compiled executable. Idempotent per key
        (an entry already indexed for this env/device is kept); any
        failure counts `prog/store_errors` and is swallowed — a broken
        disk must not fail the query that just compiled fine."""
        kd = key_digest(kind, key)
        with self._mu:
            ent = self._index.get(kd)
        if ent is not None and ent.get("v") == FORMAT_VERSION and \
                ent.get("env") == env_fingerprint() and \
                ent.get("device") == device_fingerprint():
            return True
        try:
            from jax.experimental import serialize_executable
            payload, in_tree, out_tree = \
                serialize_executable.serialize(compiled)
            # round-trip validation BEFORE publishing: an executable
            # that XLA itself loaded from its compilation cache can
            # serialize to a payload with dangling symbol references
            # ("Symbols not found" at deserialize) — such a payload
            # must never reach the manifest, where every future restart
            # would evict it as corrupt and recompile anyway
            serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
            buf = io.BytesIO()
            pickle.dump({"payload": payload, "in_tree": in_tree,
                         "out_tree": out_tree, "extra": extra or {}},
                        buf, protocol=pickle.HIGHEST_PROTOCOL)
            body = buf.getvalue()
            digest = _body_digest(body)
            path = self._obj_path(digest)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(body)
            os.replace(tmp, path)      # atomic: no torn objects
            line = {"v": FORMAT_VERSION, "key": kd, "obj": digest,
                    "kind": kind, "env": env_fingerprint(),
                    "device": device_fingerprint(), "ts": time.time()}
            with self._mu:
                self._append_manifest(line)
                self._index[kd] = line
                self._saves += 1
        except Exception:              # noqa: BLE001 — never fail the query
            GLOBAL.inc("prog/store_errors")
            return False
        GLOBAL.inc("prog/store_writes")
        return True

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        """The `.sys/progstore` / ProgStoreStats payload for THIS
        store: index size, on-disk bytes, process load/save activity,
        plus the global counters (cumulative across stores)."""
        with self._mu:
            entries = len(self._index)
            kinds: dict = {}
            for ent in self._index.values():
                k = ent.get("kind", "?")
                kinds[k] = kinds.get(k, 0) + 1
            loads, saves = self._loads, self._saves
        obj_bytes = 0
        obj_count = 0
        try:
            objdir = os.path.join(self.root, "objects")
            for name in os.listdir(objdir):
                if name.endswith(".bin"):
                    obj_count += 1
                    obj_bytes += os.path.getsize(os.path.join(objdir, name))
        except OSError:
            pass
        return {
            "root": self.root, "entries": entries, "objects": obj_count,
            "object_bytes": obj_bytes, "kinds": kinds,
            "loads": loads, "saves": saves,
            "env": env_fingerprint(), "device": device_fingerprint(),
            "hits": GLOBAL.get("prog/store_hits"),
            "misses": GLOBAL.get("prog/store_misses"),
            "writes": GLOBAL.get("prog/store_writes"),
            "corrupt": GLOBAL.get("prog/store_corrupt"),
            "refused": GLOBAL.get("prog/store_refused"),
            "errors": GLOBAL.get("prog/store_errors"),
        }


def get_store():
    """The process-wide store for the current `YDB_TPU_PROGSTORE`
    directory, or None when the lever is off. Instances are cached per
    root so tests flipping the env get fresh isolated stores."""
    root = store_dir()
    if root is None:
        return None
    root = os.path.abspath(root)
    with _MU:
        st = _STORES.get(root)
        if st is None:
            try:
                st = ProgramStore(root)
            except OSError:
                GLOBAL.inc("prog/store_errors")
                return None
            _STORES[root] = st
        return st


def stats():
    """Stats for the active store, or a disabled stub (the sysview and
    the RPC never fabricate a store that is not there)."""
    st = get_store()
    if st is None:
        return {"root": "", "entries": 0, "objects": 0, "object_bytes": 0,
                "kinds": {}, "loads": 0, "saves": 0,
                "env": env_fingerprint(), "device": device_fingerprint(),
                "hits": GLOBAL.get("prog/store_hits"),
                "misses": GLOBAL.get("prog/store_misses"),
                "writes": GLOBAL.get("prog/store_writes"),
                "corrupt": GLOBAL.get("prog/store_corrupt"),
                "refused": GLOBAL.get("prog/store_refused"),
                "errors": GLOBAL.get("prog/store_errors")}
    return st.stats()


def reset_for_tests() -> None:
    """Drop cached store instances (test isolation: a re-created tmp
    dir must re-read its manifest, not reuse a stale index)."""
    with _MU:
        _STORES.clear()
