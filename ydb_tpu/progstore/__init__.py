"""Zero-compile serving: the persistent compiled-program subsystem.

Three lanes, each an independent lever:

  store.py          on-disk content-addressed AOT executable store
                    (`YDB_TPU_PROGSTORE=<dir>`): a fresh compile is
                    serialized once and every later process with the
                    same cache key, jax/jaxlib version and device
                    fingerprint deserializes it instead of compiling —
                    `prog/store_hits` with `compile_ms ~= 0`.
  buckets.py        shape-bucketed polymorphism
                    (`YDB_TPU_SHAPE_BUCKETS`): scan source counts
                    quantize to a geometric ladder so a growing table
                    migrates between O(log n) program shapes.
  compile_ahead.py  the compile-ahead lane (`YDB_TPU_COMPILE_AHEAD`):
                    novel (key, bucket) pairs compile in the background
                    overlapped with the admission-queue wait, with
                    single-flight dedup so a client storm on a fresh
                    shape compiles once.

All three default as documented in their modules and are byte-equal
escape hatches when disabled: `YDB_TPU_PROGSTORE=0` leaves no files,
`YDB_TPU_SHAPE_BUCKETS=0` restores exact per-count shapes, and
`YDB_TPU_COMPILE_AHEAD=0` restores strictly synchronous compiles.
"""

from ydb_tpu.progstore import buckets, compile_ahead, store  # noqa: F401
