"""The compile-ahead lane: overlap fresh compiles with the admission
wait, and never compile the same shape twice concurrently.

The engine knows the physical plan (and therefore the fused program
key) BEFORE the statement sits down in the memory-admission queue; a
novel (key, bucket) pair can start its AOT compile on a background
thread during that wait instead of serializing behind it. The store
lane compounds: a compile-ahead of a shape that is already on disk is
a deserialize, near-free.

`SingleFlight` is the dedup primitive for BOTH lanes: the synchronous
dispatch path and the background lane route every fused/batched
compile through `run(key, thunk)`, so a 64-client storm on a fresh
shape compiles exactly once — 1 leader compiles, 63 followers block on
the leader's future and share the result (`prog/compile_ahead_dedup`).
A leader's exception propagates to every waiter and clears the slot,
so the next request retries fresh rather than caching a poisoned
future.

`YDB_TPU_COMPILE_AHEAD=0` disables the background lane (compiles run
strictly synchronously, byte-equal); single-flight dedup stays on —
it has no observable result effect, only fewer duplicate compiles.
"""

from __future__ import annotations

import concurrent.futures as cf
import os
import threading

from ydb_tpu.utils.metrics import GLOBAL

_MU = threading.Lock()
_POOL = None                           # guarded-by: _MU — lazy worker pool


def enabled() -> bool:
    """`YDB_TPU_COMPILE_AHEAD` lever: 0 = no background lane."""
    return os.environ.get("YDB_TPU_COMPILE_AHEAD", "1").strip() != "0"


def _workers() -> int:
    return max(1, int(os.environ.get("YDB_TPU_COMPILE_AHEAD_THREADS",
                                     "2")))


def _pool():
    global _POOL
    with _MU:
        if _POOL is None:
            _POOL = cf.ThreadPoolExecutor(
                max_workers=_workers(),
                thread_name_prefix="ydb-tpu-compile-ahead")
        return _POOL


class SingleFlight:
    """Per-key concurrent dedup. The first caller of `run(key, thunk)`
    becomes the leader and executes; concurrent callers with the same
    key block on the leader's future and share its result (or its
    exception). The slot clears when the leader finishes — a failed
    compile is retried by the NEXT request, never cached."""

    __slots__ = ("_mu", "_inflight")

    def __init__(self):
        self._mu = threading.Lock()
        self._inflight: dict = {}

    def run(self, key, thunk):
        with self._mu:
            fut = self._inflight.get(key)
            if fut is None:
                fut = self._inflight[key] = cf.Future()
                leader = True
            else:
                leader = False
        if not leader:
            GLOBAL.inc("prog/compile_ahead_dedup")
            return fut.result()
        try:
            res = thunk()
        except BaseException as exc:
            fut.set_exception(exc)
            with self._mu:
                self._inflight.pop(key, None)
            raise
        fut.set_result(res)
        with self._mu:
            self._inflight.pop(key, None)
        return res

    def launch(self, key, thunk) -> bool:
        """Kick `thunk` for `key` on the background pool unless that
        key is already in flight (then the eventual synchronous caller
        will dedup onto it anyway). Fire-and-forget: errors are counted
        (`prog/compile_ahead_errors`) and swallowed — the synchronous
        path will hit the real error with full context."""
        if not enabled():
            return False
        with self._mu:
            if key in self._inflight:
                return False
        GLOBAL.inc("prog/compile_ahead_launches")

        def _bg():
            try:
                self.run(key, thunk)
            except BaseException:      # noqa: BLE001 — sync path re-raises
                GLOBAL.inc("prog/compile_ahead_errors")

        try:
            _pool().submit(_bg)
        except RuntimeError:           # interpreter shutdown
            return False
        return True

    def inflight(self) -> int:
        with self._mu:
            return len(self._inflight)


def reset_for_tests() -> None:
    """Drain the background pool so a test's compile-ahead work cannot
    leak into the next test's counters."""
    global _POOL
    with _MU:
        pool, _POOL = _POOL, None
    if pool is not None:
        pool.shutdown(wait=True)
