"""Query-scoped resource ledger — bytes for the profiler's milliseconds.

PR 7 gave statements *time* attribution; this module gives them *bytes*:

  * **device-memory accounting** — every instrumented allocation on the
    execution spine (superblock upload, DeviceBlock upload, fused
    dispatch outputs, DQ collective staging) records its shape×dtype
    bytes into the statement's ledger, whose running total and peak
    become `QueryStats.memory`, the EXPLAIN ANALYZE `-- memory:` line,
    the `.sys/query_memory` sysview and the `mem/*` counters. Where the
    platform exposes real HBM telemetry (`device_memory_stats`),
    `device_memory_snapshot()` reports it; the shape arithmetic is the
    portable floor that works on every backend.
  * **padding-waste accounting** — every padded structure (power-of-two
    capacity buckets, 2× shuffle segments, batch-lane axis buckets, ICI
    frames) reports `live_rows/padded_rows` and `live_bytes/
    padded_bytes`, so "capacity-padded segments ship ~3.5× the live
    bytes" (MULTICHIP_r06) is a counter, not an estimate — the gauge
    ROADMAP item 1's "wire bytes ≤1.3× live bytes" gate reads.
  * **host-transfer flight recorder** — the runtime counterpart of
    graftlint's static host-sync pass: every known device→host readback
    site calls `record_transfer(site, nbytes)`, with `boundary=True`
    where the site carries a `# lint: transfer-ok(reason)` pragma (the
    ONE suppression vocabulary the static pass honors too). Counters
    (`hostsync/*`), a ring of recent transfers (`.sys/
    device_transfers`), and the `to_pandas`-inside-a-plan pin ROADMAP
    item 1 will gate to zero.

`YDB_TPU_MEMLEDGER=0` disables every record call (results byte-equal —
the ledger only ever *observes*; nothing here touches device values or
forces a sync: `.nbytes` is shape arithmetic, and the one place a
transfer size is measured the bytes are already host-side).

Attribution is thread-local like the tracer: the engine (or the DQ
runner) opens one ledger per outermost statement on the executing
thread; nested statements (EXPLAIN ANALYZE's inner run, the DQ router
merge) contribute to the enclosing ledger.
"""

from __future__ import annotations

import os
import threading
from collections import deque

from ydb_tpu.utils.metrics import GLOBAL, GLOBAL_HIST

_TLS = threading.local()

# flight recorder: last-N device→host transfers, process-wide (worker
# threads serving DQ tasks record here even when no statement ledger is
# open on their thread) — the `.sys/device_transfers` source
TRANSFER_RING_LEN = int(os.environ.get("YDB_TPU_TRANSFER_RING", "256"))
_RING: deque = deque(maxlen=TRANSFER_RING_LEN)   # guarded-by: _RING_MU
_RING_MU = threading.Lock()
_RING_SEQ = [0]                                  # guarded-by: _RING_MU


def enabled() -> bool:
    """`YDB_TPU_MEMLEDGER` lever: 0 = every record call is a no-op
    (byte-equal — the ledger never influences execution either way)."""
    return os.environ.get("YDB_TPU_MEMLEDGER", "1").strip() != "0"


class MemLedger:
    """One statement's resource account. Thread-safe increments (the
    batched lane and DQ exchanges may record from the owning thread
    while channel stats arrive from task callbacks)."""

    __slots__ = ("cur_bytes", "peak_bytes", "alloc_bytes", "freed_bytes",
                 "by_category", "pad_kinds", "transfers", "transfer_bytes",
                 "boundary_transfers", "to_pandas_in_plan", "sites",
                 "admission_est_bytes", "_mu")

    def __init__(self):
        self.cur_bytes = 0
        self.peak_bytes = 0
        self.alloc_bytes = 0
        self.freed_bytes = 0
        self.by_category: dict = {}
        # kind -> [live_rows, padded_rows, live_bytes, padded_bytes]
        self.pad_kinds: dict = {}
        self.transfers = 0
        self.transfer_bytes = 0
        self.boundary_transfers = 0
        self.to_pandas_in_plan = 0
        self.sites: dict = {}          # site -> [count, bytes]
        self.admission_est_bytes = None
        self._mu = threading.Lock()

    # -- recording ---------------------------------------------------------

    def alloc(self, category: str, nbytes: int) -> None:
        with self._mu:
            self.cur_bytes += nbytes
            self.peak_bytes = max(self.peak_bytes, self.cur_bytes)
            self.alloc_bytes += nbytes
            self.by_category[category] = \
                self.by_category.get(category, 0) + nbytes

    def free(self, category: str, nbytes: int) -> None:
        with self._mu:
            self.cur_bytes = max(0, self.cur_bytes - nbytes)
            self.freed_bytes += nbytes

    def pad(self, kind: str, live_rows: int, padded_rows: int,
            live_bytes: int, padded_bytes: int) -> None:
        with self._mu:
            acc = self.pad_kinds.setdefault(kind, [0, 0, 0, 0])
            acc[0] += live_rows
            acc[1] += padded_rows
            acc[2] += live_bytes
            acc[3] += padded_bytes

    def transfer(self, site: str, nbytes: int, count: int,
                 boundary: bool, to_pandas_in_plan: bool) -> None:
        with self._mu:
            self.transfers += count
            self.transfer_bytes += nbytes
            if boundary:
                self.boundary_transfers += count
            if to_pandas_in_plan:
                self.to_pandas_in_plan += count
            acc = self.sites.setdefault(site, [0, 0])
            acc[0] += count
            acc[1] += nbytes

    # -- rollup ------------------------------------------------------------

    def summary(self) -> dict:
        with self._mu:
            live = sum(a[2] for a in self.pad_kinds.values())
            padded = sum(a[3] for a in self.pad_kinds.values())
            est = self.admission_est_bytes
            err = None
            if est is not None and self.peak_bytes > 0:
                err = round(abs(est - self.peak_bytes)
                            / self.peak_bytes * 100.0, 1)
            return {
                "peak_bytes": int(self.peak_bytes),
                "alloc_bytes": int(self.alloc_bytes),
                "freed_bytes": int(self.freed_bytes),
                "by_category": dict(self.by_category),
                "pad": {k: {"live_rows": a[0], "padded_rows": a[1],
                            "live_bytes": a[2], "padded_bytes": a[3]}
                        for k, a in self.pad_kinds.items()},
                "live_bytes": int(live),
                "padded_bytes": int(padded),
                "waste_bytes": int(max(0, padded - live)),
                "pad_efficiency": round(live / padded, 3) if padded else
                None,
                "transfers": int(self.transfers),
                "transfer_bytes": int(self.transfer_bytes),
                "boundary_transfers": int(self.boundary_transfers),
                "to_pandas_in_plan": int(self.to_pandas_in_plan),
                "sites": {s: {"count": a[0], "bytes": a[1]}
                          for s, a in self.sites.items()},
                "admission_est_bytes": est,
                "est_error_pct": err,
            }


# -- the thread-local statement stack --------------------------------------


def current():
    """The ledger of the innermost open statement on this thread, or
    None (disabled, or no statement open — e.g. a DQ task pool
    thread)."""
    return getattr(_TLS, "ledger", None)


def open_statement():
    """Open a ledger for an outermost statement. Returns the NEW ledger
    when this call owns it (caller must `close_statement` it), or None
    when disabled or a statement is already open on this thread (the
    nested statement contributes to the enclosing ledger)."""
    if not enabled() or getattr(_TLS, "ledger", None) is not None:
        return None
    led = MemLedger()
    _TLS.ledger = led
    return led


def close_statement(led) -> None:
    """Close an owned ledger: pop it and roll its totals into the
    global counter families (`mem/*` peaks + the peak-HBM histogram,
    the admission-calibration histogram)."""
    if getattr(_TLS, "ledger", None) is led:
        _TLS.ledger = None
    GLOBAL.inc("mem/ledgers")
    if led.peak_bytes > 0:
        GLOBAL.set_max("mem/peak_bytes", led.peak_bytes)
        GLOBAL_HIST.observe("mem/peak_mb", led.peak_bytes / (1 << 20))
    est = led.admission_est_bytes
    if est is not None and led.peak_bytes > 0:
        GLOBAL.inc("admission/calibrated")
        GLOBAL_HIST.observe(
            "admission/est_error_pct",
            abs(est - led.peak_bytes) / led.peak_bytes * 100.0)


def note_admission(est_bytes: int) -> None:
    """Stamp the admission reservation estimate onto the open ledger —
    the `estimate vs measured peak` calibration input."""
    led = current()
    if led is not None and led.admission_est_bytes is None:
        led.admission_est_bytes = int(est_bytes)


# -- module-level record API (cheap no-ops when disabled) ------------------


def record_alloc(category: str, nbytes: int) -> None:
    led = current()
    if led is None:
        return
    nbytes = int(nbytes)
    led.alloc(category, nbytes)
    GLOBAL.inc("mem/alloc_bytes", nbytes)


def record_free(category: str, nbytes: int) -> None:
    led = current()
    if led is None:
        return
    nbytes = int(nbytes)
    led.free(category, nbytes)
    GLOBAL.inc("mem/freed_bytes", nbytes)


def record_pad(kind: str, live_rows: int, padded_rows: int,
               live_bytes: int, padded_bytes: int) -> None:
    """One padded structure's live-vs-padded account. Counted globally
    even without an open ledger (DQ task pool threads report the
    padding their stage buffers carry)."""
    if not enabled():
        return
    live_bytes, padded_bytes = int(live_bytes), int(padded_bytes)
    GLOBAL.inc("pad/live_bytes", live_bytes)
    GLOBAL.inc("pad/padded_bytes", padded_bytes)
    GLOBAL.inc("pad/waste_bytes", max(0, padded_bytes - live_bytes))
    led = current()
    if led is not None:
        led.pad(kind, int(live_rows), int(padded_rows), live_bytes,
                padded_bytes)


def record_transfer(site: str, nbytes: int, count: int = 1,
                    boundary: bool = False,
                    to_pandas_in_plan: bool = False) -> None:
    """Flight-record one device→host readback. `boundary`: the site is
    an excused client/upload boundary (it carries the
    `# lint: transfer-ok(reason)` pragma the static host-sync pass
    honors); everything else is plan-interior debt — the population
    ROADMAP item 1 drives to zero."""
    if not enabled():
        return
    nbytes, count = int(nbytes), int(count)
    GLOBAL.inc("hostsync/transfers", count)
    GLOBAL.inc("hostsync/bytes", nbytes)
    if boundary:
        GLOBAL.inc("hostsync/boundary_transfers", count)
    if to_pandas_in_plan:
        GLOBAL.inc("hostsync/to_pandas_in_plan", count)
    with _RING_MU:
        _RING_SEQ[0] += 1
        _RING.append({"seq": _RING_SEQ[0], "site": site,
                      "bytes": nbytes, "count": count,
                      "boundary": bool(boundary),
                      "to_pandas_in_plan": bool(to_pandas_in_plan)})
    led = current()
    if led is not None:
        led.transfer(site, nbytes, count, boundary, to_pandas_in_plan)


def record_device_handoff(site: str, nbytes: int, count: int = 1) -> None:
    """Flight-record one device→device stage handoff (the stage spine's
    block-by-reference seam: fused capture, planned-exchange landing,
    channel-table device write). These are NOT host transfers — the
    bytes never cross the link — so they count under `devlink/*`, ride
    the same ring for `.sys/device_transfers` visibility (tagged
    `device_to_device`), and leave every `hostsync/*` counter flat. The
    classification is the regression surface: a handoff site that
    mistakenly calls `record_transfer` would re-open ROADMAP item 1's
    zero-to_pandas gate from the accounting side."""
    if not enabled():
        return
    nbytes, count = int(nbytes), int(count)
    GLOBAL.inc("devlink/handoffs", count)
    GLOBAL.inc("devlink/bytes", nbytes)
    with _RING_MU:
        _RING_SEQ[0] += 1
        _RING.append({"seq": _RING_SEQ[0], "site": site,
                      "bytes": nbytes, "count": count,
                      "boundary": False, "to_pandas_in_plan": False,
                      "device_to_device": True})


def transfer_ring() -> list:
    """Snapshot of the recent-transfer ring (newest last) — the
    `.sys/device_transfers` payload."""
    with _RING_MU:
        return [dict(r) for r in _RING]


# -- byte helpers (shape arithmetic only — never a device sync) ------------


def deep_nbytes(obj) -> int:
    """Sum `.nbytes` over a pytree-ish structure of arrays (dict / list /
    tuple / array / None). `.nbytes` on a jax array is shape×itemsize —
    metadata, no transfer."""
    if obj is None:
        return 0
    nb = getattr(obj, "nbytes", None)
    if nb is not None:
        return int(nb)
    if isinstance(obj, dict):
        return sum(deep_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(deep_nbytes(v) for v in obj)
    return 0


def record_padded_buffers(kind: str, category: str, live_rows: int,
                          padded_rows: int, *buffer_trees) -> None:
    """Combined alloc + pad record for a padded device buffer set: the
    buffers' full (padded) bytes are allocated to `category`, and the
    live share is prorated by row count."""
    if not enabled() or padded_rows <= 0:
        return
    padded_bytes = sum(deep_nbytes(t) for t in buffer_trees)
    if padded_bytes <= 0:
        return
    live_bytes = int(padded_bytes * min(live_rows, padded_rows)
                     / padded_rows)
    record_alloc(category, padded_bytes)
    record_pad(kind, live_rows, padded_rows, live_bytes, padded_bytes)


def device_memory_snapshot() -> dict:
    """Real HBM telemetry where the backend exposes it
    (`Device.memory_stats()` — TPU/GPU runtimes), else {}. The portable
    shape-arithmetic ledger never depends on this; it is surfaced for
    operators whose platform can corroborate the ledger's numbers."""
    try:
        import jax
        stats = {}
        for d in jax.local_devices():
            ms = getattr(d, "memory_stats", None)
            ms = ms() if callable(ms) else None
            if ms:
                stats[str(d)] = {
                    "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                    "peak_bytes_in_use":
                        int(ms.get("peak_bytes_in_use", 0)),
                }
        return stats
    except Exception:                  # noqa: BLE001 — telemetry only
        return {}
