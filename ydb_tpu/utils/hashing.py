"""Shared hash functions.

One definition for every consumer (shard routing, device shuffle
partitioning, join hashing) so host and device agree bit-for-bit —
the role `ydb/core/formats/arrow/hash/calcer.cpp` plays in the reference.
"""

from __future__ import annotations

import numpy as np

_C1 = 0xBF58476D1CE4E5B9
_C2 = 0x94D049BB133111EB
_GOLDEN = 0x9E3779B97F4A7C15


def splitmix64(xp, x):
    """splitmix64 finalizer; xp is numpy or jax.numpy.

    Input is converted to uint64 bits (numpy path uses a view to avoid
    value conversion of negatives; jax wraps via astype).
    """
    if xp is np:
        u = np.ascontiguousarray(x.astype(np.int64)).view(np.uint64).copy()
    else:
        u = x.astype(xp.int64).astype(xp.uint64)
    u = (u ^ (u >> np.uint64(30))) * np.uint64(_C1)
    u = (u ^ (u >> np.uint64(27))) * np.uint64(_C2)
    return u ^ (u >> np.uint64(31))


def hash_combine(xp, h, x):
    return h ^ (x + np.uint64(_GOLDEN) + (h << np.uint64(6)) + (h >> np.uint64(2)))
