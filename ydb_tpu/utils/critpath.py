"""Critical-path extraction over assembled span trees — *which chain of
work actually bounds this query's wall clock*.

PR 7 gave every query an assembled cross-worker span tree and PR 11 gave
it a byte ledger; both stop at *attribution* (how much time/bytes each
phase consumed, summed). This module answers the scheduling question
instead: starting from the query's end, walk backwards through the span
DAG (parent/child nesting plus channel send→recv edges, with clock-
rebased cross-worker timestamps — `Tracer.ingest(offset_ms=...)`) and
keep only the chain of segments that was actually *blocking* at each
instant. A channel wait fully hidden under a longer device execution
never appears; two parallel stages contribute only the longer one; a
failed task attempt (state=failed) is excluded outright — its retry is
the blocking chain.

Every critical-path segment is classified into one of `CLASSES`:

  device_execute  on-chip program execution (incl. dispatch enqueue)
  compile         fresh-shape XLA compile (split out of dispatch spans
                  by their `compile_ms` attr)
  host_transfer   D2H/H2D movement (upload, readout, future drain)
  host_lane       host-side CPU work (parse/plan/builds/pandas lanes —
                  the q13 class)
  channel_wait    DQ channel production/drain + ICI exchanges
  admission_wait  queueing behind the memory-admission budget
  scheduler_gap   structural self-time nothing below accounts for

and the per-class milliseconds become EXPLAIN ANALYZE `-- critical
path:` lines, `QueryStats.critical_path`, the `.sys/query_critical_path`
ring and the `crit/*` counters — the machine-generated worklist ROADMAP
items 1–2 rank their work by. `YDB_TPU_CRITPATH=0` disables extraction
and export entirely (byte-equal results, counters frozen), matching the
MEMLEDGER / TRACE_SAMPLE lever convention.
"""

from __future__ import annotations

import os

from ydb_tpu.utils.tracing import Span, span_from_dict

CLASSES = ("device_execute", "compile", "host_transfer", "host_lane",
           "channel_wait", "admission_wait", "scheduler_gap")

# leaf-span classification; spans not listed here fall back by shape:
# STRUCTURAL self-time is a scheduler gap, any other unknown leaf is
# host work (conservative: unclassified time must not masquerade as
# device time — the whole point is ranking the NON-device share)
CLASS_BY_NAME = {
    "device-execute": "device_execute",
    "tiled-scan": "device_execute",
    "shuffle-join": "device_execute",
    "spill-merge": "device_execute",
    "compile": "compile",
    "superblock-upload": "host_transfer",
    "readout-transfer": "host_transfer",
    # the engine's drain phase: on the fused path its device-execute /
    # readout-transfer children carry the time (self ~0); on the
    # portioned path the self-time IS the host-driven per-portion
    # streaming loop — host work, not transfer
    "readout": "host_lane",
    "parse": "host_lane",
    "plan": "host_lane",
    "join-builds": "host_lane",
    "task-exec": "host_lane",
    "window-device": "device_execute",
    "window-host-lane": "host_lane",
    "setop-host-lane": "host_lane",
    "input-wait": "channel_wait",
    "output-flush": "channel_wait",
    "ici-exchange": "channel_wait",
    "admission-wait": "admission_wait",
}

# spans whose self-time is pure orchestration/waiting (their children
# are the work): gaps on the critical path inside these classify
# scheduler_gap. Engine-side spans (statement/execute/fused-attempt)
# are NOT here: their self-time is real host CPU work — binder, temp
# materialization, pandas conversions — i.e. the q13 host-lane class,
# and it must rank as host_lane, not hide as a gap.
STRUCTURAL = {"dq-query", "dq-stage", "dq-task", "query"}

# dispatch spans absorb a fresh shape's XLA compile; the `compile_ms`
# attr marks how much of the span's front is compile, split out below
_DISPATCH = ("device-dispatch", "device-dispatch-batched")

_EPS = 5e-4          # ms — timestamps round to 3 decimals


def enabled() -> bool:
    """`YDB_TPU_CRITPATH` lever: 0 = extraction and export disabled
    (results byte-equal; `crit/*` counters frozen)."""
    return os.environ.get("YDB_TPU_CRITPATH", "1").strip() != "0"


def _as_spans(spans) -> list:
    return [span_from_dict(s) if isinstance(s, dict) else s
            for s in (spans or [])]


def _drop_failed_subtrees(spans: list) -> list:
    """A failed task attempt must not extend the path — its *retry* is
    the blocking chain. Remove every span whose `state` attr is
    `failed`, plus all descendants."""
    failed = {s.span_id for s in spans
              if s.attrs.get("state") == "failed"}
    if not failed:
        return spans
    by_parent: dict = {}
    for s in spans:
        by_parent.setdefault(s.parent_id, []).append(s)
    frontier = list(failed)
    while frontier:
        pid = frontier.pop()
        for c in by_parent.get(pid, ()):
            if c.span_id not in failed:
                failed.add(c.span_id)
                frontier.append(c.span_id)
    return [s for s in spans if s.span_id not in failed]


def _classify(span: Span) -> str:
    cls = CLASS_BY_NAME.get(span.name)
    if cls is not None:
        return cls
    if span.name in _DISPATCH:
        return "device_execute"
    if span.name in STRUCTURAL:
        return "scheduler_gap"
    return "host_lane"


def lane_of(span: Span, by_id: dict, memo: dict) -> str:
    """Worker lane: the `worker` attr of the nearest enclosing dq-task
    span, else 'router' — the ONE lane-resolution rule, shared with the
    timeline exporter (`utils/chrometrace.py`) so Perfetto tracks and
    critical-path segment workers can never disagree."""
    sid = span.span_id
    got = memo.get(sid)
    if got is not None:
        return got
    if span.name == "dq-task" and span.attrs.get("worker"):
        lane = str(span.attrs["worker"])
    else:
        p = by_id.get(span.parent_id)
        lane = lane_of(p, by_id, memo) if p is not None else "router"
    memo[sid] = lane
    return lane


def _pieces(span: Span, a: float, b: float) -> list:
    """Class pieces of the self-time interval [a, b] of `span`. A
    dispatch span's `compile_ms` front is split out as `compile`."""
    if span.name in _DISPATCH:
        c = float(span.attrs.get("compile_ms") or 0.0)
        if c > _EPS:
            cut = min(span.start_ms + c, b)
            out = []
            if cut - a > _EPS:
                out.append(("compile", a, min(cut, b)))
            if b - cut > _EPS:
                out.append(("device_execute", max(cut, a), b))
            return out or [("device_execute", a, b)]
    return [(_classify(span), a, b)]


def extract(spans, memory: dict = None) -> dict:
    """Extract the critical path of one assembled trace.

    `spans`: Span objects or their `to_dict()` payloads — the full tree
    (a statement window without its root also works; a virtual root is
    synthesized over the forest). `memory`: the statement's closed
    MemLedger summary (PR 11) — its transfer/padding bytes ride along so
    padded bytes on the critical path are costed next to the
    milliseconds.

    Returns {classes, pct, segments, wall_ms, total_ms, coverage,
    connected, non_device_ms, dominant_*, top_spans, memory} — segments
    chronological, each labeled with one of `CLASSES`."""
    spans = _drop_failed_subtrees(_as_spans(spans))
    spans = [s for s in spans if s.dur_ms >= 0.0]
    if not spans:
        return {"classes": {}, "pct": {}, "segments": [],
                "wall_ms": 0.0, "total_ms": 0.0, "coverage": 0.0,
                "connected": True, "non_device_ms": 0.0,
                "dominant_span": "", "dominant_class": "",
                "dominant_ms": 0.0, "top_spans": {},
                "memory": _memory_join(memory)}
    by_id = {s.span_id: s for s in spans}
    t_lo = min(s.start_ms for s in spans)
    t_hi = max(s.start_ms + s.dur_ms for s in spans)
    # virtual root over the forest: a statement window (no root span)
    # and a full tree (one root) walk the same code path, and any gap
    # between top-level spans becomes honest scheduler_gap self-time
    root = Span("query", spans[0].trace_id, -1, None, t_lo,
                max(0.0, t_hi - t_lo))
    children: dict = {-1: []}
    for s in spans:
        pid = s.parent_id if s.parent_id in by_id else -1
        children.setdefault(pid, []).append(s)
        children.setdefault(s.span_id, [])
    by_id[-1] = root
    lane_memo: dict = {-1: "router"}

    def end_of(s: Span) -> float:
        return s.start_ms + s.dur_ms

    segments: list = []

    def emit(span: Span, a: float, b: float) -> None:
        if b - a <= _EPS:
            return
        for (cls, pa, pb) in _pieces(span, a, b):
            segments.append({
                "span": span.name, "span_id": span.span_id,
                "class": cls,
                "worker": lane_of(span, by_id, lane_memo),
                "start_ms": round(pa, 3), "end_ms": round(pb, 3),
                "ms": round(pb - pa, 3)})

    def walk(span: Span, hi: float, lo: float = None) -> None:
        # `lo` clamps this subtree into its ancestors' window: a clock-
        # rebased child may nominally start a hair before its parent,
        # and letting it cover time the grandparent also fills would
        # double-count (overlapping, "disconnected-looking" segments)
        lo = span.start_ms if lo is None else max(span.start_ms, lo)
        t = hi
        kids = children.get(span.span_id, ())
        while t - lo > _EPS:
            best = None
            for c in kids:
                ce = end_of(c)
                # the blocking child at instant t: finished by t, not
                # already fully before the window floor, and actually
                # OCCUPYING time strictly below t — zero-duration spans
                # (rounded-away sub-µs work, 0ms input-waits) and spans
                # starting at t cannot be blocking, and skipping them
                # guarantees every iteration moves t strictly down
                # (choosing one would leave t unchanged and spin this
                # loop forever)
                if ce <= t + _EPS and ce - lo > _EPS \
                        and ce - c.start_ms > _EPS \
                        and c.start_ms < t - _EPS:
                    if best is None or ce > end_of(best):
                        best = c
            if best is None:
                emit(span, lo, t)
                return
            ce = min(end_of(best), t)
            if t - ce > _EPS:
                emit(span, ce, t)          # parent self-time gap
            walk(best, ce, lo)
            t = min(t, max(best.start_ms, lo))

    walk(root, end_of(root))
    # the walk runs backwards in time (and a split dispatch emits its
    # pieces forwards): chronological order by sort, not reversal
    segments.sort(key=lambda s: (s["start_ms"], s["end_ms"]))

    classes: dict = {}
    top_spans: dict = {}
    for seg in segments:
        classes[seg["class"]] = classes.get(seg["class"], 0.0) + seg["ms"]
        if seg["span"] != "query":
            top_spans[seg["span"]] = \
                top_spans.get(seg["span"], 0.0) + seg["ms"]
    classes = {k: round(v, 3) for k, v in classes.items()}
    total = round(sum(classes.values()), 3)
    wall = round(max(0.0, t_hi - t_lo), 3)
    connected = all(
        segments[i + 1]["start_ms"] - segments[i]["end_ms"] <= 0.01
        for i in range(len(segments) - 1))
    dom = max((s for s in segments if s["class"] != "scheduler_gap"),
              key=lambda s: s["ms"], default=None)
    # compile is host-side work: it counts as non-device time (the gap
    # classes a 10× target has to eliminate), so only device_execute
    # subtracts
    non_device = round(total - classes.get("device_execute", 0.0), 3)
    return {
        "classes": classes,
        "pct": {k: round(100.0 * v / wall, 1) if wall else 0.0
                for k, v in classes.items()},
        "segments": segments,
        "wall_ms": wall,
        "total_ms": total,
        "coverage": round(total / wall, 4) if wall else 0.0,
        "connected": connected,
        "non_device_ms": max(0.0, non_device),
        "dominant_span": dom["span"] if dom else "",
        "dominant_class": dom["class"] if dom else "",
        "dominant_ms": round(top_spans.get(dom["span"], 0.0), 3)
        if dom else 0.0,
        "top_spans": {k: round(v, 3) for k, v in sorted(
            top_spans.items(), key=lambda kv: -kv[1])},
        "memory": _memory_join(memory),
    }


def _memory_join(memory) -> dict:
    """The PR 11 byte companions of the critical-path milliseconds:
    host-transfer traffic and the padding tax of the same statement."""
    if not memory:
        return {}
    return {
        "transfer_bytes": int(memory.get("transfer_bytes", 0)),
        "transfers": int(memory.get("transfers", 0)),
        "waste_bytes": int(memory.get("waste_bytes", 0)),
        "pad_efficiency": memory.get("pad_efficiency"),
        "to_pandas_in_plan": int(memory.get("to_pandas_in_plan", 0)),
    }


def summarize(cp: dict) -> dict:
    """The compact per-statement form (`QueryStats.critical_path`,
    bench records): everything except the segment list."""
    return {k: v for k, v in cp.items() if k != "segments"}


def record_counters(cp: dict) -> None:
    """Roll one extraction into the `crit/*` counter families. Guarded
    by the caller on `enabled()` — with the lever off these counters
    stay frozen (the differential test pins that)."""
    from ydb_tpu.utils.metrics import GLOBAL, GLOBAL_HIST
    GLOBAL.inc("crit/extractions")
    if not cp["connected"]:
        GLOBAL.inc("crit/disconnected")
    GLOBAL.inc("crit/non_device_ms", cp["non_device_ms"])
    for cls, ms in cp["classes"].items():
        GLOBAL.inc(f"crit/{cls}_ms", ms)
    GLOBAL_HIST.observe("crit/coverage_pct", 100.0 * cp["coverage"])


def render_lines(cp: dict) -> list:
    """The EXPLAIN ANALYZE `-- critical path:` lines: per-class % of
    wall, then the dominant span."""
    if not cp or not cp.get("classes"):
        return []
    parts = " | ".join(
        f"{cls} {cp['pct'].get(cls, 0.0):.1f}%"
        for cls in CLASSES if cls in cp["classes"])
    lines = [f"-- critical path: {parts}"]
    lines.append(
        f"-- critical path: coverage {100.0 * cp['coverage']:.1f}% of "
        f"{cp['wall_ms']:.1f}ms wall"
        + ("" if cp["connected"] else " [DISCONNECTED]")
        + (f" | dominant {cp['dominant_span']} "
           f"({cp['dominant_class']}, {cp['dominant_ms']:.1f}ms)"
           if cp.get("dominant_span") else ""))
    mem = cp.get("memory") or {}
    if mem.get("transfer_bytes") or mem.get("waste_bytes"):
        lines.append(
            f"-- critical path: host transfers "
            f"{mem.get('transfer_bytes', 0) / (1 << 20):.2f}MB"
            + (f" | padded waste "
               f"{mem.get('waste_bytes', 0) / (1 << 20):.2f}MB "
               f"(pad eff {mem['pad_efficiency']:.2f})"
               if mem.get("pad_efficiency") is not None else ""))
    return lines
