"""Virtual-mesh self-provisioning for CPU proxies of multi-chip runs.

The bench host exposes ONE real chip, so every multi-device leg
(`__graft_entry__.dryrun_multichip`, `scripts/ici_gate.py`,
`bench.py --multichip`) re-executes itself in a subprocess with an
N-device virtual CPU platform. The flag merge lives HERE once: the
child must force `JAX_PLATFORMS=cpu` (the TPU plugin's sitecustomize
beats the env var, so children also pin `jax.config`) and add
`--xla_force_host_platform_device_count=N` without clobbering any
XLA_FLAGS the operator already set.
"""

from __future__ import annotations

import os


def virtual_mesh_env(ndev: int, base: dict = None) -> dict:
    """Environment for a subprocess that must see an `ndev`-device
    virtual CPU mesh. Existing XLA_FLAGS are preserved; an explicit
    device-count flag already present wins."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={ndev}"
        ).strip()
    return env
