"""Counters and per-query statistics — the observability floor.

The reference hangs monlib dynamic counter trees off every component
(`library/cpp/monlib`, aggregated per tablet type by
`tablet_counters_aggregator.cpp`, served at `/counters`) and fills
per-task/per-channel stats protos that roll up into the query plan
(`dq_tasks_runner.h:73` TDqTaskRunnerStatsView, `kqp_executer_stats.cpp`,
`kqp_query_plan.cpp` — surfaced as EXPLAIN ANALYZE and `.sys` views).

Here: a process-wide hierarchical counter registry (plain dict, sampled on
read) and a QueryStats record the engine fills per statement — the inputs
to `EXPLAIN ANALYZE`, `engine.counters()`, and the server's /counters
endpoint.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional


class Counters:
    """Hierarchical monotonic counters: `inc("engine/queries")`.
    Thread-safe — concurrent sessions increment from their own threads."""

    def __init__(self):
        import threading
        self._c: dict[str, float] = {}
        self._mu = threading.Lock()

    def inc(self, name: str, by: float = 1) -> None:
        with self._mu:
            self._c[name] = self._c.get(name, 0) + by

    def set(self, name: str, value: float) -> None:
        with self._mu:
            self._c[name] = value

    def set_max(self, name: str, value: float) -> None:
        """High-watermark gauge: keep the largest value ever reported
        (e.g. `dq/channel_inflight_peak_bytes` from the channel writers)."""
        with self._mu:
            if value > self._c.get(name, float("-inf")):
                self._c[name] = value

    def get(self, name: str) -> float:
        return self._c.get(name, 0)

    def snapshot(self) -> dict:
        with self._mu:
            return dict(sorted(self._c.items()))


GLOBAL = Counters()

# DQ task-graph runtime counters (`ydb_tpu/dq/`), one namespace on the
# existing /counters surface — router side counts stages/tasks/retries,
# worker side counts local stage executions and channel traffic:
#   dq/stages                     stages executed (runner)
#   dq/tasks                      tasks launched (runner + worker)
#   dq/tasks_retried              tasks re-run by a stage-level retry
#   dq/channel_bytes              frame bytes shipped over channels
#   dq/frames                     frames shipped over channels
#   dq/local_stage_execs          statements run as DQ stage programs
#   dq/channel_inflight_peak_bytes  flow-control high watermark


@dataclass
class QueryStats:
    """Per-statement execution breakdown (TDqTaskRunnerStatsView analog)."""
    sql: str = ""
    kind: str = ""                 # select | insert | update | ddl | ...
    parse_ms: float = 0.0
    plan_ms: float = 0.0
    execute_ms: float = 0.0
    total_ms: float = 0.0
    rows_out: int = 0
    plan_cache_hit: bool = False
    fused: bool = False            # whole-query single-dispatch path
    distributed: bool = False      # mesh hash-shuffle path
    tables: list = field(default_factory=list)

    def render(self) -> str:
        path = ("mesh-distributed" if self.distributed
                else "fused single-dispatch" if self.fused
                else "portioned")
        return (f"-- stats: total {self.total_ms:.1f}ms "
                f"(parse {self.parse_ms:.1f}, plan {self.plan_ms:.1f}"
                f"{' [cache hit]' if self.plan_cache_hit else ''}, "
                f"execute {self.execute_ms:.1f}) | "
                f"rows out {self.rows_out} | path {path}")


class Timer:
    def __init__(self):
        self.t0 = time.perf_counter()

    def ms(self) -> float:
        return (time.perf_counter() - self.t0) * 1000.0

    def lap(self) -> float:
        now = time.perf_counter()
        out = (now - self.t0) * 1000.0
        self.t0 = now
        return out
