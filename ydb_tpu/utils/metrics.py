"""Counters and per-query statistics — the observability floor.

The reference hangs monlib dynamic counter trees off every component
(`library/cpp/monlib`, aggregated per tablet type by
`tablet_counters_aggregator.cpp`, served at `/counters`) and fills
per-task/per-channel stats protos that roll up into the query plan
(`dq_tasks_runner.h:73` TDqTaskRunnerStatsView, `kqp_executer_stats.cpp`,
`kqp_query_plan.cpp` — surfaced as EXPLAIN ANALYZE and `.sys` views).

Here: a process-wide hierarchical counter registry (plain dict, sampled on
read) and a QueryStats record the engine fills per statement — the inputs
to `EXPLAIN ANALYZE`, `engine.counters()`, and the server's /counters
endpoint.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Optional


class Counters:
    """Hierarchical monotonic counters: `inc("engine/queries")`.
    Thread-safe — concurrent sessions increment from their own threads."""

    def __init__(self):
        import threading
        self._c: dict[str, float] = {}
        self._mu = threading.Lock()

    def inc(self, name: str, by: float = 1) -> None:
        with self._mu:
            self._c[name] = self._c.get(name, 0) + by

    def set(self, name: str, value: float) -> None:
        with self._mu:
            self._c[name] = value

    def set_max(self, name: str, value: float) -> None:
        """High-watermark gauge: keep the largest value ever reported
        (e.g. `dq/channel_inflight_peak_bytes` from the channel writers)."""
        with self._mu:
            if value > self._c.get(name, float("-inf")):
                self._c[name] = value

    def get(self, name: str) -> float:
        return self._c.get(name, 0)

    def snapshot(self) -> dict:
        with self._mu:
            return dict(sorted(self._c.items()))


GLOBAL = Counters()


class Histogram:
    """Log-bucketed latency histogram (the monlib NHistogram exponential
    bucket family): bucket i covers [BASE·G^(i-1), BASE·G^i), G=2,
    BASE=0.05 ms — 32 buckets span 50 µs … ~30 h (0.05·2^31 ms),
    everything above lands in one overflow bucket. Quantiles
    interpolate geometrically inside the winning bucket and clamp to
    the exact observed min/max, so a single sample reports itself at
    every quantile."""

    BASE = 0.05
    GROWTH = 2.0
    N_BUCKETS = 32                    # + 1 overflow

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts = [0] * (self.N_BUCKETS + 1)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def _bucket(self, v: float) -> int:
        if v < self.BASE:
            return 0
        i = int(math.log(v / self.BASE, self.GROWTH)) + 1
        return min(i, self.N_BUCKETS)      # N_BUCKETS = overflow

    def record(self, v: float) -> None:
        v = max(0.0, float(v))
        self.counts[self._bucket(v)] += 1
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    def quantile(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        rank = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if acc + c >= rank:
                if i >= self.N_BUCKETS:
                    # overflow bucket is unbounded above — the exact
                    # observed max is the only honest answer
                    return self.max
                lo = self.BASE * self.GROWTH ** (i - 1) if i > 0 else 0.0
                hi = self.BASE * self.GROWTH ** i
                frac = (rank - acc) / c
                est = lo + (hi - lo) * frac
                return min(max(est, self.min), self.max)
            acc += c
        return self.max

    def snapshot(self) -> dict:
        if self.count == 0:
            return {"count": 0, "p50": 0.0, "p95": 0.0, "p99": 0.0,
                    "max": 0.0}
        return {"count": self.count,
                "p50": round(self.quantile(0.50), 3),
                "p95": round(self.quantile(0.95), 3),
                "p99": round(self.quantile(0.99), 3),
                "max": round(self.max, 3)}

    def cumulative(self) -> list:
        """[(upper_bound, cumulative_count)] in bucket order, ending
        with (inf, count) — the OpenMetrics histogram `_bucket{le=}`
        series (cumulative by spec; the overflow bucket maps to
        le=\"+Inf\")."""
        out, acc = [], 0
        for i in range(self.N_BUCKETS):
            acc += self.counts[i]
            out.append((self.BASE * self.GROWTH ** i, acc))
        out.append((math.inf, self.count))
        return out


class HistogramRegistry:
    """Named histograms with the Counters locking discipline; surfaced
    on /counters as `hist/<name>/{count,p50,p95,p99,max}`."""

    def __init__(self):
        import threading
        self._h: dict[str, Histogram] = {}
        self._mu = threading.Lock()

    def observe(self, name: str, value_ms: float) -> None:
        with self._mu:
            h = self._h.get(name)
            if h is None:
                h = self._h[name] = Histogram()
            h.record(value_ms)

    def get(self, name: str) -> Optional[Histogram]:
        with self._mu:
            return self._h.get(name)

    def snapshot(self) -> dict:
        """Flat /counters payload: hist/<name>/p50 etc. Per-histogram
        snapshots are taken UNDER the lock — quantile() walks counts[]
        against self.count, and a concurrent record() between the two
        would hand back a torn view."""
        out = {}
        with self._mu:
            for name, h in self._h.items():
                for k, v in h.snapshot().items():
                    out[f"hist/{name}/{k}"] = v
        return out

    def families(self) -> dict:
        """Consistent per-histogram export payload (taken under the
        lock, same torn-view discipline as snapshot()):
        name -> {"buckets": [(le, cum)], "sum", "count"}."""
        out = {}
        with self._mu:
            for name, h in self._h.items():
                out[name] = {"buckets": h.cumulative(),
                             "sum": h.sum, "count": h.count}
        return out


GLOBAL_HIST = HistogramRegistry()

# --------------------------------------------------------------------------
# THE counter registry — every name the process may emit, with its doc.
#
# This is load-bearing, not a comment: `graftlint`'s counters pass
# (ydb_tpu/analysis/passes/counters.py) fails CI when code increments a
# name that is not here (typo'd names feed dashboards nobody reads) or
# when an entry here is emitted nowhere (stale doc). Doc-string
# conventions the tooling understands:
#
#   "[viz] ..."   always-visible on /counters (zero before first emit)
#   "[hist] ..."  a GLOBAL_HIST family, surfaced as hist/<name>/{q}
#   "(dynamic)"   emitted through a variable name (the call site
#                 carries a `# lint: allow-counters(...)` pragma)
#   "(derived)"   computed in QueryEngine.counters(), never emitted
#                 through Counters methods
#
# Wildcard entries end with "/*" and admit an open-ended family.
# --------------------------------------------------------------------------

COUNTER_REGISTRY = {
    # -- statement latency histograms (end-to-end + per phase) -------------
    "query/latency_ms": "[hist] statement wall end-to-end",
    "query/parse_ms": "[hist] statement parse phase",
    "query/plan_ms": "[hist] statement plan phase",
    "query/execute_ms": "[hist] statement execute phase",
    # -- engine -------------------------------------------------------------
    "engine/queries": "SELECTs executed",
    "engine/statements": "statements executed (all kinds)",
    "engine/rows_out": "result rows returned",
    "engine/plan_cache_hits": "text-keyed plan cache hits",
    "engine/plan_cache_misses": "text-keyed plan cache misses",
    "engine/plan_cache_size": "(derived) live plan-cache entries",
    "engine/throttled": "statements rejected by the quoter",
    "engine/ttl_evicted": "rows dropped by TTL sweeps",
    "engine/shard_splits": "shard split operations",
    "engine/window_device_pushdown": "window queries on the device lane",
    "engine/window_device_rows": "rows through the device window lane",
    "engine/window_device_errors": "device window lane fallbacks",
    "engine/host_lane/*": "host-lane residency by statement shape",
    # -- executor -----------------------------------------------------------
    "executor/fused_plans": "(derived) live fused-plan cache entries",
    "executor/tiled_queries": "queries run through the tiled path",
    "executor/shuffle_joins": "mesh shuffle-join executions",
    "executor/spilled_rows": "rows spilled by the partition store",
    "executor/spilled_bytes": "bytes spilled by the partition store",
    # -- concurrent pipeline ------------------------------------------------
    "pipeline/dispatched": "[viz] queries dispatched async",
    "pipeline/in_flight": "[viz] dispatched-undrained gauge",
    "pipeline/overlap_hits": "[viz] entries that found another in flight",
    "pipeline/readout_ms": "[viz] cumulative readout wall",
    "pipeline/window_timeouts": "admissions that outwaited the window",
    "pipeline/window": "(derived) configured pipeline window",
    # -- batched dispatch lane ---------------------------------------------
    "batch/batches": "[viz] stacked executions dispatched",
    "batch/coalesced_queries": "[viz] member queries across batches",
    "batch/max_size": "[viz] largest batch ever sealed",
    "batch/singles": "[viz] solo members run per-query",
    "batch/fallbacks": "[viz] sealed batches that fell back per-member",
    "batch/declined": "[viz] lane-ineligible statements",
    "batch/trace_errors": "[viz] stacked-trace failures",
    "batch/reservations": "[viz] single admission reservations taken",
    "batch/window_timeouts": "[viz] members that outwaited the seal",
    "batch/lift_hits": "[viz] plans with every literal lifted",
    "batch/lift_misses": "[viz] plans the lift pass skipped",
    "batch/window_ms": "(derived) configured batch window",
    # -- admission ----------------------------------------------------------
    "admission/active_queries": "admitted-statement gauge",
    "admission/in_flight_bytes": "reserved working-set gauge",
    "admission/waits": "admissions that had to queue",
    "admission/timeouts": "admissions that hit the deadline",
    "admission/wait_ms": "[hist] admission queue wait",
    "admission/calibrated":
        "[viz] queries with both an estimate and a measured peak",
    "admission/est_error_pct":
        "[hist] admission estimate vs measured peak (|est-peak|/peak %)",
    # -- resource ledger (utils/memledger.py): per-query device bytes ------
    "mem/ledgers": "[viz] statements that closed a resource ledger",
    "mem/alloc_bytes": "[viz] ledger: device bytes allocated (cumulative)",
    "mem/freed_bytes": "[viz] ledger: device bytes released (cumulative)",
    "mem/peak_bytes":
        "[viz] high-watermark of any single query's device working set",
    "mem/peak_mb": "[hist] per-query peak device working set (MB)",
    # -- padding-waste accounting (live vs padded structure bytes) ---------
    "pad/live_bytes": "[viz] live-row bytes through padded structures",
    "pad/padded_bytes": "[viz] allocated/shipped bytes of those structures",
    "pad/waste_bytes": "[viz] padded minus live — the padding tax",
    # -- host-transfer flight recorder (device→host readbacks) -------------
    "hostsync/transfers": "[viz] device→host transfers (flight recorder)",
    "hostsync/bytes": "[viz] bytes those transfers moved",
    "hostsync/boundary_transfers":
        "[viz] the transfer-ok-excused boundary subset (client egress)",
    "hostsync/to_pandas_in_plan":
        "[viz] to_pandas materializations INSIDE a multi-stage plan",
    "devlink/handoffs":
        "[viz] device→device block handoffs (stage spine, no host sync)",
    "devlink/bytes": "[viz] live bytes those handoffs kept on device",
    # -- DQ task-graph runtime ---------------------------------------------
    "dq/stages": "stages executed (runner)",
    "dq/tasks": "tasks launched (runner + worker)",
    "dq/tasks_retried": "tasks re-run by a stage-level retry",
    "dq/channel_bytes": "frame bytes shipped over host-plane channels",
    "dq/frames": "frames shipped over host-plane channels",
    "dq/local_stage_execs": "statements run as DQ stage programs",
    "dq/channel_inflight_peak_bytes": "flow-control high watermark",
    "dq/merge_groupby_stages":
        "[viz] merge stages that are partial-agg merges",
    "dq/retry_rerouted":
        "[viz] tasks/statements re-routed off a transport-dead worker",
    "dq/stage_ms": "[hist] per-stage wall",
    "dq/channel_wait_ms":
        "[hist] channel wait (input drain + writer backpressure)",
    # -- DQ ICI plane (device-resident edges; dq/channel_bytes stays 0) ----
    "dq/ici_bytes": "[viz] interconnect bytes moved by collectives",
    "dq/ici_frames": "[viz] (src, dst) segments exchanged",
    "dq/ici_fallbacks": "[viz] ICI edges re-run on the host plane",
    "dq/quant_bytes_saved":
        "[viz] wire bytes saved by EQuARX block quantization",
    "dq/quant_refused":
        "[viz] declared quant columns refused (shipped exact)",
    "dq/planned_overflow_reruns":
        "[viz] planned exchanges whose counts beat the sized segment "
        "(full-capacity rerun)",
    "dq/count_exchange_batched":
        "[viz] stage-level batched count exchanges (one fused counts "
        "program + one device_get for ALL outgoing edges)",
    # -- Hive control plane -------------------------------------------------
    "hive/registered": "[viz] workers registered (first time)",
    "hive/heartbeats": "[viz] lease renewals (push agents or pulse)",
    "hive/worker_dead": "[viz] alive→dead transitions",
    "hive/lease_expired": "[viz] the expiry subset of worker_dead",
    "hive/workers_alive": "[viz] gauge: currently alive workers",
    "hive/shards_replaced": "[viz] shards moved off dead workers",
    "hive/shards_adopted": "shard images replayed INTO this node",
    "hive/adopted_rows": "rows absorbed by those replays",
    "hive/adopt_failed": "[viz] re-placements whose image replay raised",
    "hive/rejoin_stale": "dead workers that re-registered re-placed",
    "hive/failover_holds": "[viz] queries held at the placement barrier",
    "hive/placement_epoch": "[viz] gauge: placement map version",
    "hive/elections_won": "lease-election wins (pending→leader)",
    "hive/leadership_lost": "leaders fenced by a lost lease",
    "hive/standby_promotions": "engines booted from a standby root",
    # -- sorted group-by trace counters (accrued at TRACE time; deltas
    # visible only for freshly compiled shapes — the CI gather gate
    # relies on that; emitted via _t_inc/_t_max in ops/xla_exec.py) ---------
    "groupby/traces": "[viz] (dynamic) sorted group-by lowerings traced",
    "groupby/tiles": "[viz] (dynamic) tiles across those traces",
    "groupby/gather_ops":
        "[viz] (dynamic) gathers above the tile-row budget",
    "groupby/gather_ops_total": "[viz] (dynamic) every traced gather",
    "groupby/batched_gathers":
        "[viz] (dynamic) per-dtype multi-column 2-D gathers",
    "groupby/scatter_ops": "[viz] (dynamic) scatter-reduces (legacy path)",
    "groupby/sort_rows_max": "[viz] (dynamic) group-by sort row watermark",
    "groupby/value_gather_rows_max":
        "[viz] (dynamic) value-column gather row watermark",
    # -- bounds lattice (query/bounds.py, YDB_TPU_BOUNDS) ------------------
    "bounds/plans": "[viz] plans annotated by the bounds lattice",
    "bounds/finite_plans": "[viz] plans whose result bound is finite",
    "bounds/proven_rows":
        "[viz] (dynamic) per-group rows allocated at the proven bound",
    "bounds/capacity_rows":
        "[viz] (dynamic) rows capacity sizing would have allocated",
    "bounds/bounded_groupbys":
        "[viz] (dynamic) group-by traces with a finite group bound",
    "bounds/carried_keys":
        "[viz] (dynamic) grouping columns carried out of sort identity",
    "bounds/carry_rewrites": "[viz] executor carry-key plan rewrites",
    "bounds/eager_agg_rewrites":
        "[viz] LEFT JOIN builds pre-aggregated below the join",
    "bounds/fd_checks": "functional-dependency verifications attempted",
    "bounds/fd_verified": "functional-dependency verifications proven",
    "bounds/admission_capped_bytes":
        "admission estimate bytes removed by proven build bounds",
    "bounds/seg_bounded_shuffles":
        "mesh shuffle merges with bound-sized segments",
    "groupby/join_bounded_plans":
        "[viz] plans whose group count a join build side bounded",
    # -- late materialization (query/latemat.py, YDB_TPU_LATE_MAT) ---------
    "latemat/deferred_cols":
        "[viz] columns carried as row-ids per fused dispatch "
        "(scan deferrals + late join payloads)",
    "latemat/compact_plans":
        "[viz] fused dispatches carrying a bound-sized ir.Compact",
    "latemat/compact_capacity_rows":
        "ladder-quantized compact capacities allocated (rows)",
    "latemat/compact_live_rows":
        "measured live rows at the compact seam (rows)",
    "latemat/compact_overflow_reruns":
        "[viz] compacts whose live count beat the sized bound "
        "(full-capacity rerun — loud, never a truncation)",
    "sort/rows_max": "[viz] (dynamic) lax.sort row watermark",
    "sort/operands_max": "[viz] (dynamic) lax.sort operand watermark",
    # -- program / device caches -------------------------------------------
    "program_cache/compiles": "[viz] fresh XLA compiles (timed shim)",
    "program_cache/compile_ms": "[viz] cumulative compile wall",
    "program_cache/hits": "(derived) ProgramCache hits",
    "program_cache/misses": "(derived) ProgramCache misses",
    # -- compiled-program observatory (utils/progstats.py): XLA cost-model
    # roofline accounting per compiled executable ---------------------------
    "prog/registered":
        "[viz] programs captured with compile-time cost/memory analysis",
    "prog/compile_ms": "[viz] cumulative AOT lower+compile wall",
    "prog/executions":
        "[viz] measured device executions joined to a program",
    "prog/device_ms": "[viz] cumulative measured device-execute wall",
    "prog/evicted": "[viz] inventory entries marked evicted (LRU)",
    "prog/recompiled":
        "[viz] evicted keys compiled again (a MISS, never a hit)",
    "prog/cost_unavailable":
        "[viz] programs whose backend withheld cost analysis",
    "prog/aot_errors":
        "[viz] AOT captures that failed (the legacy jit path ran)",
    "prog/aot_fallbacks":
        "[viz] AOT calls re-dispatched via jit (aval/device drift)",
    "prog/utilization_pct":
        "[hist] per-execution roofline utilization (% of peak)",
    # -- persistent program store + compile-ahead lane (ydb_tpu/progstore):
    # executables that outlive the process, shape buckets, background
    # compiles overlapped with the admission wait ---------------------------
    "prog/store_hits":
        "[viz] executables deserialized from the on-disk store "
        "(compile_ms ~= 0 — the zero-compile restart path)",
    "prog/store_misses": "[viz] store lookups that found no entry",
    "prog/store_writes": "[viz] fresh executables serialized to disk",
    "prog/store_corrupt":
        "[viz] corrupt/truncated/version-skewed entries evicted from "
        "disk and treated as cold misses",
    "prog/store_refused":
        "[viz] entries refused on device-fingerprint mismatch (a "
        "copied data dir must not dispatch a foreign executable)",
    "prog/store_errors":
        "[viz] store I/O failures swallowed as misses (a broken disk "
        "never fails the query)",
    "prog/compile_ahead_launches":
        "[viz] background fused-program fills kicked before admission",
    "prog/compile_ahead_hits":
        "[viz] programs the background lane made ready before their "
        "first dispatch",
    "prog/compile_ahead_dedup":
        "[viz] concurrent fills that deduped onto an in-flight "
        "compile (the storm-compiles-once guarantee)",
    "prog/compile_ahead_errors":
        "[viz] background fills that failed (the synchronous path "
        "re-raises with full context)",
    "device_cache/hits": "(derived) HBM column cache hits",
    "device_cache/misses": "(derived) HBM column cache misses",
    "device_cache/bytes": "(derived) HBM column cache residency",
    # -- critical-path analysis (utils/critpath.py): the blocking-chain
    # decomposition of query wall — crit/<class>_ms accumulate via the
    # wildcard family below --------------------------------------------------
    "crit/extractions": "[viz] critical paths extracted",
    "crit/disconnected": "[viz] extractions whose chain had gaps",
    "crit/non_device_ms":
        "[viz] cumulative critical-path wall NOT spent executing on "
        "device — the speed-gap ledger's raw material",
    "crit/coverage_pct":
        "[hist] critical-path coverage of the query wall (%)",
    "crit/*": "critical-path milliseconds by segment class "
              "(device_execute/compile/host_transfer/host_lane/"
              "channel_wait/admission_wait/scheduler_gap)",
    # -- tracing / slow queries --------------------------------------------
    "trace/forced_slow": "[viz] statements force-sampled as offenders",
    "trace/sample_rate": "(derived) configured sample rate",
    "trace/profiles_held": "(derived) profile ring occupancy",
    "slow_query/count": "[viz] over-threshold statements",
    "slow_query/worst_ms": "worst statement wall seen",
    "slow_query/*": "over-threshold statements by kind",
    # -- materialized views (ydb_tpu/views/): continuous queries folding
    # CDC deltas into device-maintained aggregate state ----------------------
    "view/registered": "(dynamic) materialized views currently defined",
    "view/applied_deltas":
        "[viz] changefeed messages folded into view state",
    "view/delta_rows":
        "[viz] signed delta rows (old/new images) through fold programs",
    "view/fold_ms":
        "[hist] one delta-batch fold wall (delta block -> row program "
        "-> partial group-by -> state apply) — flat in delta size, "
        "never O(table)",
    "view/rebuilds":
        "[viz] full-recompute escapes (bound exceeded / pre-image-less "
        "mutation / missing host mirror)",
    "view/lag_versions":
        "(dynamic) coordinator steps the laggiest fold is behind",
    "view/reads_state":
        "[viz] view reads served from folded state at the watermark",
    "view/reads_fallback":
        "[viz] view reads that fell back to the base query (snapshot "
        "behind state, or degraded view)",
    # -- servers ------------------------------------------------------------
    "server/http_queries": "HTTP front statements",
    "server/rpc_in_flight": "(dynamic) gRPC handler gauge",
    "coordinator/plan_step": "(derived) last 2PC plan step",
}

# the fixed histogram families (always-visible keys on /counters — see
# QueryEngine.counters): derived from the registry's [hist] marks
HIST_FAMILIES = tuple(sorted(
    n for n, doc in COUNTER_REGISTRY.items() if doc.startswith("[hist]")))

# counters QueryEngine.counters() zero-fills so dashboards/probes never
# see missing keys — the registry's [viz] marks
ALWAYS_VISIBLE = tuple(sorted(
    n for n, doc in COUNTER_REGISTRY.items() if doc.startswith("[viz]")))

@dataclass
class QueryStats:
    """Per-statement execution breakdown (TDqTaskRunnerStatsView analog)."""
    sql: str = ""
    kind: str = ""                 # select | insert | update | ddl | ...
    parse_ms: float = 0.0
    plan_ms: float = 0.0
    execute_ms: float = 0.0
    total_ms: float = 0.0
    rows_out: int = 0
    plan_cache_hit: bool = False
    fused: bool = False            # whole-query single-dispatch path
    distributed: bool = False      # mesh hash-shuffle path
    tables: list = field(default_factory=list)
    # sorted group-by trace breakdown (tiles/gather_ops/…, the
    # `xla_exec.groupby_trace_delta` window for this statement) —
    # non-empty only when it compiled a fresh group-by shape
    groupby: dict = field(default_factory=dict)
    # bounds-lattice trace breakdown (`query/bounds.py`): proven vs
    # capacity per-group rows this statement's fresh group-by shapes
    # allocated, carried-key counts — the `-- bounds:` line's source
    bounds: dict = field(default_factory=dict)
    # batched dispatch lane (`query/batch_lane.py`): how this statement
    # rode a coalesced batch — {"coalesced": B, "leader": bool,
    # "batched": bool} (batched=False → the lane fell back to per-member
    # execution); empty when the lane is off or the shape was ineligible
    batching: dict = field(default_factory=dict)
    # device-timeline attribution (`utils/tracing.phase_breakdown` over
    # this statement's spans): {build_ms, upload_ms, dispatch_ms,
    # device_ms, readout_ms, compile_ms} — empty when the statement was
    # unsampled or never touched the device
    phases: dict = field(default_factory=dict)
    # resource-ledger rollup (`utils/memledger.MemLedger.summary`):
    # peak/alloc device bytes, padding live-vs-padded account, host
    # transfers, admission calibration — empty when YDB_TPU_MEMLEDGER=0
    memory: dict = field(default_factory=dict)
    # critical-path rollup (`utils/critpath.summarize`): per-class ms +
    # % of wall, coverage, the dominant span — the blocking chain, not
    # another aggregate. Empty when unsampled or YDB_TPU_CRITPATH=0.
    critical_path: dict = field(default_factory=dict)
    # compiled-program roofline rollup (`utils/progstats.py`): the
    # programs this statement executed with their measured device ms
    # joined to the XLA cost model — {n, device_ms, utilization_pct,
    # bound_class, programs: [...]}. Empty when no instrumented program
    # ran or YDB_TPU_PROGSTATS=0.
    programs: dict = field(default_factory=dict)
    # materialized-view serving decisions (`views/manager.py`): one
    # {view, mode, watermark} per view this read referenced — mode
    # "state" served the folded aggregate state at the watermark,
    # "fallback"/"degraded" re-ran the defining query at the snapshot
    view_serving: list = field(default_factory=list)

    def render(self) -> str:
        path = ("mesh-distributed" if self.distributed
                else "fused single-dispatch" if self.fused
                else "portioned")
        out = (f"-- stats: total {self.total_ms:.1f}ms "
               f"(parse {self.parse_ms:.1f}, plan {self.plan_ms:.1f}"
               f"{' [cache hit]' if self.plan_cache_hit else ''}, "
               f"execute {self.execute_ms:.1f}) | "
               f"rows out {self.rows_out} | path {path}")
        if self.groupby:
            g = self.groupby
            out += (f"\n-- groupby trace: tiles {g.get('tiles', 0)} | "
                    f"gathers {g.get('gather_ops_total', 0)} "
                    f"({g.get('gather_ops', 0)} over tile budget, "
                    f"{g.get('batched_gathers', 0)} batched) | "
                    f"sort rows max {g.get('sort_rows_max', 0)} | "
                    f"value gather rows max "
                    f"{g.get('value_gather_rows_max', 0)}")
        if self.bounds:
            bd = self.bounds
            proven = bd.get("proven_rows", 0)
            cap = bd.get("capacity_rows", 0)
            line = (f"\n-- bounds: proven {proven} rows vs capacity "
                    f"{cap}")
            if cap:
                line += f" ({proven / cap:.3f}x tightening)"
            if bd.get("carried_keys"):
                line += f" | {bd['carried_keys']} carried key(s)"
            if bd.get("bounded_groupbys"):
                line += (f" | {bd['bounded_groupbys']} bounded "
                         "group-by(s)")
            out += line
        if self.batching:
            b = self.batching
            out += (f"\n-- batching: coalesced {b.get('coalesced', 0)} "
                    f"queries | leader "
                    f"{str(b.get('leader', False)).lower()} | "
                    f"{'stacked dispatch' if b.get('batched') else 'per-member fallback'}")
        if self.phases:
            p = self.phases
            out += ("\n-- phases: " + " | ".join(
                f"{k.removesuffix('_ms')} {p[k]:.1f}ms"
                for k in ("compile_ms", "build_ms", "upload_ms",
                          "dispatch_ms", "device_ms", "readout_ms")
                if k in p))
        if self.memory and (self.memory.get("peak_bytes")
                            or self.memory.get("transfers")):
            m = self.memory
            mb = 1 << 20
            line = f"\n-- memory: peak {m.get('peak_bytes', 0) / mb:.2f}MB"
            if m.get("admission_est_bytes") is not None:
                line += (f" (admitted {m['admission_est_bytes'] / mb:.2f}"
                         f"MB")
                if m.get("est_error_pct") is not None:
                    line += f", err {m['est_error_pct']:.0f}%"
                line += ")"
            if m.get("pad_efficiency") is not None:
                line += (f" | pad eff {m['pad_efficiency']:.2f} "
                         f"(live {m.get('live_bytes', 0) / mb:.2f}MB / "
                         f"padded {m.get('padded_bytes', 0) / mb:.2f}MB)")
            line += (f" | host transfers {m.get('transfers', 0)} "
                     f"({m.get('transfer_bytes', 0) / mb:.2f}MB")
            if m.get("to_pandas_in_plan"):
                line += f", {m['to_pandas_in_plan']} to_pandas-in-plan"
            line += ")"
            out += line
        for v in self.view_serving:
            if v.get("mode") == "state":
                out += (f"\n-- view {v['view']}: state @ plan_step "
                        f"{v['watermark']}")
            else:
                out += (f"\n-- view {v['view']}: base-query fallback "
                        f"({v.get('mode', 'fallback')}, watermark "
                        f"plan_step {v['watermark']})")
        if self.programs and self.programs.get("programs"):
            p = self.programs
            head = (f"\n-- programs: {p['n']} | "
                    f"device {p['device_ms']:.2f}ms")
            if p.get("utilization_pct") is not None:
                head += f" | utilization {p['utilization_pct']:.1f}%"
            if p.get("bound_class"):
                head += f" | {p['bound_class']}"
            out += head
            for pr in p["programs"][:6]:
                # provenance tag: [fresh] = compiled inside this
                # statement; [store]/[compile-ahead] = the compile was
                # skipped (persistent store hit / background lane)
                src = pr.get("source", "fresh")
                tag = (" [fresh]" if pr.get("fresh")
                       else f" [{src.replace('_', '-')}]"
                       if src != "fresh" else "")
                line = f"\n--   {pr['key']}{tag}: "
                if pr.get("bound_class") == "unavailable" \
                        or pr.get("flops") is None:
                    line += ("cost unavailable (backend withheld "
                             "analysis)")
                else:
                    line += (f"flops {pr['flops']:.4g} "
                             f"bytes {pr['bytes_accessed']:.4g}")
                    if pr.get("intensity") is not None:
                        line += f" (intensity {pr['intensity']:.2f})"
                line += f" | device {pr['device_ms']:.2f}ms"
                if pr.get("achieved_gflops") is not None:
                    line += (f" -> {pr['achieved_gflops']:.2f} GFLOP/s, "
                             f"{pr['achieved_gbps']:.2f} GB/s")
                if pr.get("utilization_pct") is not None:
                    line += f" | {pr['utilization_pct']:.1f}% of peak"
                if pr.get("bound_class") \
                        and pr["bound_class"] != "unavailable":
                    line += f" | {pr['bound_class']}"
                out += line
        if self.critical_path:
            from ydb_tpu.utils.critpath import render_lines
            lines = render_lines(self.critical_path)
            if lines:
                out += "\n" + "\n".join(lines)
        return out


class Timer:
    def __init__(self):
        self.t0 = time.perf_counter()

    def ms(self) -> float:
        return (time.perf_counter() - self.t0) * 1000.0

    def lap(self) -> float:
        now = time.perf_counter()
        out = (now - self.t0) * 1000.0
        self.t0 = now
        return out


# --------------------------------------------------------------------------
# OpenMetrics text exposition (the server's GET /metrics payload) — the
# registry finally pays rent outside lint: every # HELP line is the
# COUNTER_REGISTRY doc, histograms export as cumulative buckets per the
# OpenMetrics spec, and any Prometheus can scrape the process.
# --------------------------------------------------------------------------

_OM_SANITIZE = None     # compiled lazily (re import stays off the hot path)


def _om_name(name: str) -> str:
    """Counter name → OpenMetrics metric name: `mem/peak_bytes` →
    `ydbtpu_mem_peak_bytes` (slashes/dashes are label-illegal)."""
    global _OM_SANITIZE
    if _OM_SANITIZE is None:
        import re
        _OM_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")
    return "ydbtpu_" + _OM_SANITIZE.sub("_", name)


def _om_help(name: str) -> Optional[str]:
    """Registry doc for a counter (exact entry, or its wildcard
    family), with the [viz]/[hist] tooling marks stripped."""
    doc = COUNTER_REGISTRY.get(name)
    if doc is None:
        for entry, d in COUNTER_REGISTRY.items():
            if entry.endswith("/*") and name.startswith(entry[:-1]):
                doc = f"{d} ({entry})"
                break
    if doc is None:
        return None
    for mark in ("[viz] ", "[hist] "):
        if doc.startswith(mark):
            doc = doc[len(mark):]
    return doc.replace("\\", "\\\\").replace("\n", " ")


def _om_value(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_openmetrics(counters: dict, hist_registry=None) -> str:
    """OpenMetrics 1.0 text exposition of a counter snapshot plus the
    process histograms. `counters`: the /counters payload (flattened
    `hist/<name>/<q>` quantile keys are skipped — histograms export
    properly as cumulative buckets from `hist_registry` instead).
    Scalar counters export as gauges (several are gauges or
    high-watermarks; OpenMetrics counters would forbid decreases)."""
    hist_registry = hist_registry if hist_registry is not None \
        else GLOBAL_HIST
    lines: list = []
    for name in sorted(counters):
        if name.startswith("hist/"):
            continue
        om = _om_name(name)
        doc = _om_help(name)
        lines.append(f"# TYPE {om} gauge")
        if doc:
            lines.append(f"# HELP {om} {doc}")
        lines.append(f"{om} {_om_value(counters[name])}")
    for name, fam in sorted(hist_registry.families().items()):
        om = _om_name(name)
        doc = _om_help(name)
        lines.append(f"# TYPE {om} histogram")
        if doc:
            lines.append(f"# HELP {om} {doc}")
        for (le, cum) in fam["buckets"]:
            le_s = "+Inf" if math.isinf(le) else repr(round(le, 6))
            lines.append(f'{om}_bucket{{le="{le_s}"}} {int(cum)}')
        lines.append(f"{om}_sum {_om_value(fam['sum'])}")
        lines.append(f"{om}_count {int(fam['count'])}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"
