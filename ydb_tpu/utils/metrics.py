"""Counters and per-query statistics — the observability floor.

The reference hangs monlib dynamic counter trees off every component
(`library/cpp/monlib`, aggregated per tablet type by
`tablet_counters_aggregator.cpp`, served at `/counters`) and fills
per-task/per-channel stats protos that roll up into the query plan
(`dq_tasks_runner.h:73` TDqTaskRunnerStatsView, `kqp_executer_stats.cpp`,
`kqp_query_plan.cpp` — surfaced as EXPLAIN ANALYZE and `.sys` views).

Here: a process-wide hierarchical counter registry (plain dict, sampled on
read) and a QueryStats record the engine fills per statement — the inputs
to `EXPLAIN ANALYZE`, `engine.counters()`, and the server's /counters
endpoint.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Optional


class Counters:
    """Hierarchical monotonic counters: `inc("engine/queries")`.
    Thread-safe — concurrent sessions increment from their own threads."""

    def __init__(self):
        import threading
        self._c: dict[str, float] = {}
        self._mu = threading.Lock()

    def inc(self, name: str, by: float = 1) -> None:
        with self._mu:
            self._c[name] = self._c.get(name, 0) + by

    def set(self, name: str, value: float) -> None:
        with self._mu:
            self._c[name] = value

    def set_max(self, name: str, value: float) -> None:
        """High-watermark gauge: keep the largest value ever reported
        (e.g. `dq/channel_inflight_peak_bytes` from the channel writers)."""
        with self._mu:
            if value > self._c.get(name, float("-inf")):
                self._c[name] = value

    def get(self, name: str) -> float:
        return self._c.get(name, 0)

    def snapshot(self) -> dict:
        with self._mu:
            return dict(sorted(self._c.items()))


GLOBAL = Counters()


class Histogram:
    """Log-bucketed latency histogram (the monlib NHistogram exponential
    bucket family): bucket i covers [BASE·G^(i-1), BASE·G^i), G=2,
    BASE=0.05 ms — 32 buckets span 50 µs … ~30 h (0.05·2^31 ms),
    everything above lands in one overflow bucket. Quantiles
    interpolate geometrically inside the winning bucket and clamp to
    the exact observed min/max, so a single sample reports itself at
    every quantile."""

    BASE = 0.05
    GROWTH = 2.0
    N_BUCKETS = 32                    # + 1 overflow

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self):
        self.counts = [0] * (self.N_BUCKETS + 1)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def _bucket(self, v: float) -> int:
        if v < self.BASE:
            return 0
        i = int(math.log(v / self.BASE, self.GROWTH)) + 1
        return min(i, self.N_BUCKETS)      # N_BUCKETS = overflow

    def record(self, v: float) -> None:
        v = max(0.0, float(v))
        self.counts[self._bucket(v)] += 1
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    def quantile(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        rank = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if acc + c >= rank:
                if i >= self.N_BUCKETS:
                    # overflow bucket is unbounded above — the exact
                    # observed max is the only honest answer
                    return self.max
                lo = self.BASE * self.GROWTH ** (i - 1) if i > 0 else 0.0
                hi = self.BASE * self.GROWTH ** i
                frac = (rank - acc) / c
                est = lo + (hi - lo) * frac
                return min(max(est, self.min), self.max)
            acc += c
        return self.max

    def snapshot(self) -> dict:
        if self.count == 0:
            return {"count": 0, "p50": 0.0, "p95": 0.0, "p99": 0.0,
                    "max": 0.0}
        return {"count": self.count,
                "p50": round(self.quantile(0.50), 3),
                "p95": round(self.quantile(0.95), 3),
                "p99": round(self.quantile(0.99), 3),
                "max": round(self.max, 3)}


class HistogramRegistry:
    """Named histograms with the Counters locking discipline; surfaced
    on /counters as `hist/<name>/{count,p50,p95,p99,max}`."""

    def __init__(self):
        import threading
        self._h: dict[str, Histogram] = {}
        self._mu = threading.Lock()

    def observe(self, name: str, value_ms: float) -> None:
        with self._mu:
            h = self._h.get(name)
            if h is None:
                h = self._h[name] = Histogram()
            h.record(value_ms)

    def get(self, name: str) -> Optional[Histogram]:
        with self._mu:
            return self._h.get(name)

    def snapshot(self) -> dict:
        """Flat /counters payload: hist/<name>/p50 etc. Per-histogram
        snapshots are taken UNDER the lock — quantile() walks counts[]
        against self.count, and a concurrent record() between the two
        would hand back a torn view."""
        out = {}
        with self._mu:
            for name, h in self._h.items():
                for k, v in h.snapshot().items():
                    out[f"hist/{name}/{k}"] = v
        return out


GLOBAL_HIST = HistogramRegistry()

# the fixed histogram families (always-visible keys on /counters — see
# QueryEngine.counters): end-to-end + per-phase statement latency,
# per-DQ-stage wall, channel wait (input drain + writer backpressure),
# and memory-admission queueing
HIST_FAMILIES = ("query/latency_ms", "query/parse_ms", "query/plan_ms",
                 "query/execute_ms", "dq/stage_ms", "dq/channel_wait_ms",
                 "admission/wait_ms")

# DQ task-graph runtime counters (`ydb_tpu/dq/`), one namespace on the
# existing /counters surface — router side counts stages/tasks/retries,
# worker side counts local stage executions and channel traffic:
#   dq/stages                     stages executed (runner)
#   dq/tasks                      tasks launched (runner + worker)
#   dq/tasks_retried              tasks re-run by a stage-level retry
#   dq/channel_bytes              frame bytes shipped over channels
#   dq/frames                     frames shipped over channels
#   dq/local_stage_execs          statements run as DQ stage programs
#   dq/channel_inflight_peak_bytes  flow-control high watermark
#   dq/merge_groupby_stages       router merge stages that are partial-agg
#                                 merges (ride the tiled sorted group-by)
#   dq/retry_rerouted             tasks/statements re-routed off a
#                                 transport-dead worker (single-task
#                                 stage reroute, or a router failover
#                                 round that re-lowered onto the
#                                 surviving Hive placement)
#
# DQ channel ICI plane (`ydb_tpu/dq/ici.py` — device-resident edges;
# `dq/channel_bytes` above stays at 0 for an edge that went ICI):
#   dq/ici_bytes                  interconnect bytes moved by device
#                                 collectives (all_to_all segments +
#                                 valid masks + row counts; all-gather
#                                 for broadcast edges)
#   dq/ici_frames                 (src, dst) segments exchanged
#   dq/ici_fallbacks              ICI edges re-run on the host plane
#                                 (mid-collective failure, codec
#                                 refusal, or a worker set with no
#                                 shared mesh)
#   dq/quant_bytes_saved          wire bytes saved by EQuARX block
#                                 quantization of planner-proven
#                                 aggregation-tolerant columns
#                                 (YDB_TPU_DQ_QUANT=1)
#   dq/quant_refused              declared quant columns the runtime
#                                 refused (non-float at execution time)
#                                 and shipped exact instead
#
# Hive control-plane counters (`ydb_tpu/hive/`, the cluster membership/
# placement/failover subsystem):
#   hive/registered               workers registered (first time)
#   hive/heartbeats               lease renewals (push agents or pull
#                                 pulse)
#   hive/worker_dead              alive→dead transitions (lease expiry
#                                 or observed transport failure)
#   hive/lease_expired            the expiry subset of worker_dead
#   hive/workers_alive            gauge: currently alive workers
#   hive/shards_replaced          shards moved off dead workers (adopt
#                                 hook succeeded)
#   hive/shards_adopted           shard images replayed INTO this node
#   hive/adopted_rows             rows absorbed by those replays
#   hive/adopt_failed             re-placements whose image replay
#                                 raised (shard stays orphaned, retried
#                                 each sweep)
#   hive/rejoin_stale             dead workers that re-registered after
#                                 their shards were re-placed (excluded
#                                 from sharded scans until re-imaged)
#   hive/failover_holds           queries held at the placement barrier
#                                 while a re-placement was in flight
#   hive/placement_epoch          gauge: placement map version
#   hive/elections_won            lease-election wins (pending→leader)
#   hive/leadership_lost          leaders fenced by a lost lease
#   hive/standby_promotions       engines booted from a standby root by
#                                 a won election
#
# Sorted group-by trace counters (`ops/xla_exec.py`, accrued at TRACE
# time — compile-cache hits re-trace nothing, so deltas show up only for
# freshly compiled shapes; the CI gather-budget gate relies on that):
#   groupby/traces                sorted group-by lowerings traced
#   groupby/tiles                 tiles across those traces (P per trace)
#   groupby/gather_ops            gathers ABOVE the tile-row budget — the
#                                 ~30 ms full-capacity ops the round-8
#                                 tiled path exists to eliminate
#   groupby/gather_ops_total      every traced gather
#   groupby/batched_gathers       per-dtype multi-column (2-D) gathers
#   groupby/scatter_ops           scatter-reduces (legacy path only; the
#                                 round-8 path is scatter-free)
#   groupby/sort_rows_max         high watermark of group-by sort rows
#   groupby/value_gather_rows_max high watermark of per-op value-column
#                                 gather rows (≤ tile budget when tiling)
#   groupby/join_bounded_plans    fused plans whose group count was
#                                 bounded by an inner-join build side
#   sort/rows_max, sort/operands_max  lax.sort compile-cliff axes across
#                                 all device sorts (group-by + ORDER BY)


@dataclass
class QueryStats:
    """Per-statement execution breakdown (TDqTaskRunnerStatsView analog)."""
    sql: str = ""
    kind: str = ""                 # select | insert | update | ddl | ...
    parse_ms: float = 0.0
    plan_ms: float = 0.0
    execute_ms: float = 0.0
    total_ms: float = 0.0
    rows_out: int = 0
    plan_cache_hit: bool = False
    fused: bool = False            # whole-query single-dispatch path
    distributed: bool = False      # mesh hash-shuffle path
    tables: list = field(default_factory=list)
    # sorted group-by trace breakdown (tiles/gather_ops/…, the
    # `xla_exec.groupby_trace_delta` window for this statement) —
    # non-empty only when it compiled a fresh group-by shape
    groupby: dict = field(default_factory=dict)
    # batched dispatch lane (`query/batch_lane.py`): how this statement
    # rode a coalesced batch — {"coalesced": B, "leader": bool,
    # "batched": bool} (batched=False → the lane fell back to per-member
    # execution); empty when the lane is off or the shape was ineligible
    batching: dict = field(default_factory=dict)
    # device-timeline attribution (`utils/tracing.phase_breakdown` over
    # this statement's spans): {build_ms, upload_ms, dispatch_ms,
    # device_ms, readout_ms, compile_ms} — empty when the statement was
    # unsampled or never touched the device
    phases: dict = field(default_factory=dict)

    def render(self) -> str:
        path = ("mesh-distributed" if self.distributed
                else "fused single-dispatch" if self.fused
                else "portioned")
        out = (f"-- stats: total {self.total_ms:.1f}ms "
               f"(parse {self.parse_ms:.1f}, plan {self.plan_ms:.1f}"
               f"{' [cache hit]' if self.plan_cache_hit else ''}, "
               f"execute {self.execute_ms:.1f}) | "
               f"rows out {self.rows_out} | path {path}")
        if self.groupby:
            g = self.groupby
            out += (f"\n-- groupby trace: tiles {g.get('tiles', 0)} | "
                    f"gathers {g.get('gather_ops_total', 0)} "
                    f"({g.get('gather_ops', 0)} over tile budget, "
                    f"{g.get('batched_gathers', 0)} batched) | "
                    f"sort rows max {g.get('sort_rows_max', 0)} | "
                    f"value gather rows max "
                    f"{g.get('value_gather_rows_max', 0)}")
        if self.batching:
            b = self.batching
            out += (f"\n-- batching: coalesced {b.get('coalesced', 0)} "
                    f"queries | leader "
                    f"{str(b.get('leader', False)).lower()} | "
                    f"{'stacked dispatch' if b.get('batched') else 'per-member fallback'}")
        if self.phases:
            p = self.phases
            out += ("\n-- phases: " + " | ".join(
                f"{k.removesuffix('_ms')} {p[k]:.1f}ms"
                for k in ("compile_ms", "build_ms", "upload_ms",
                          "dispatch_ms", "device_ms", "readout_ms")
                if k in p))
        return out


class Timer:
    def __init__(self):
        self.t0 = time.perf_counter()

    def ms(self) -> float:
        return (time.perf_counter() - self.t0) * 1000.0

    def lap(self) -> float:
        now = time.perf_counter()
        out = (now - self.t0) * 1000.0
        self.t0 = now
        return out
