"""Compiled-program observatory — the XLA cost model joined to measured
device time, per compiled executable.

PR 12's speed-gap ledger ranks every query by *non-device* blocking
milliseconds; this module answers the question that ledger leaves open
for the device time that remains: does this fused program achieve 2% or
80% of what the chip can do? XLA already computes the needed oracle at
compile time — `Compiled.cost_analysis()` (flops, transcendentals,
bytes accessed) and `Compiled.memory_analysis()` (argument/output/temp/
generated-code bytes, the compiler-reported HBM complement of PR 11's
shape-arithmetic ledger). Capturing both at the cache-fill sites
(`ops/xla_exec.ProgramCache`, the fused/batched dispatch lanes in
`query/executor.py`) and joining them to PR 7's measured device-execute
spans turns every compiled program into a roofline data point:

  achieved GFLOP/s   flops / measured device ms
  achieved GB/s      bytes accessed / measured device ms
  intensity          flops / bytes accessed
  utilization %      roofline-bound time / measured time (how close the
                     measured execution came to the peak-table ceiling)
  bound class        memory_bound | compute_bound | launch_bound
                     (sub-µs roofline work: dispatch overhead dominates)

The peak table comes from `YDB_TPU_PEAK_GFLOPS` / `YDB_TPU_PEAK_GBPS`
(always win), else a per-device-kind reference table for known TPUs,
else a one-shot micro-probe on CPU-class backends — the source is
stamped so a verdict can be audited.

Capture rides the compile itself: at a fresh cache fill the jitted
callable is AOT-compiled (`fn.lower(*args).compile()` — ONE trace + ONE
compile, the same work the lazy first call would have done) and the
returned `ProgramHandle` dispatches through the AOT executable, falling
back to the plain jit path on aval/device drift (a mesh path running
the cached program on another device pays exactly the per-device
compile jit itself would have paid). Cost analysis is BACKEND-DEPENDENT:
CPU may return sparse or absent keys — consumers degrade to explicit
`unavailable` rows, never fabricated zeros.

Surfaces: the `.sys/compiled_programs` inventory sysview (hit/miss/
eviction counts, compile_ms, cost+memory analysis, cumulative device
ms, utilization, bound class — evicted entries persist in the ring
marked `evicted`), the EXPLAIN ANALYZE `-- programs:` block +
`QueryStats.programs`, per-query `utilization`/`bound_class` in the
bench `speed_gap` section, and `prog/*` counters + the utilization
histogram on /counters and /metrics.

`YDB_TPU_PROGSTATS=0` disables everything byte-equal: fills return the
bare jitted callable (the legacy lazy-jit first call), every record is
a no-op, `prog/*` counters freeze and the sysview reports zero rows.
Attribution is thread-local like the tracer and the mem ledger: the
engine opens one statement accumulator per OUTERMOST statement; nested
statements contribute to the enclosing one.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

from ydb_tpu.utils.metrics import GLOBAL, GLOBAL_HIST

_MU = threading.RLock()
_INVENTORY: OrderedDict = OrderedDict()   # guarded-by: _MU — key_id -> entry
_PEAKS: dict = {}                         # guarded-by: _MU — probe/table cache
_TLS = threading.local()

# roofline work below this is dispatch/launch overhead territory — the
# program cannot meaningfully bound on compute or bandwidth
LAUNCH_BOUND_US = 1.0

BOUND_CLASSES = ("memory_bound", "compute_bound", "launch_bound",
                 "unavailable")

# reference ceilings per device kind (peak GFLOP/s, peak HBM GB/s) —
# marketed per-chip MXU/HBM numbers, order-of-magnitude honest for the
# "2% or 80% of peak" verdict this module exists to render; the env
# levers override for calibrated hardware. Longest prefix wins.
_DEVICE_PEAKS = (
    ("TPU v6", 918_000.0, 1_640.0),
    ("TPU v5p", 459_000.0, 2_765.0),
    ("TPU v5 lite", 197_000.0, 810.0),
    ("TPU v5e", 197_000.0, 810.0),
    ("TPU v5", 459_000.0, 2_765.0),
    ("TPU v4", 275_000.0, 1_228.0),
    ("TPU v3", 123_000.0, 900.0),
    ("TPU v2", 46_000.0, 700.0),
)


def enabled() -> bool:
    """`YDB_TPU_PROGSTATS` lever: 0 = no AOT capture, no records, no
    rows — results byte-equal, `prog/*` counters frozen."""
    return os.environ.get("YDB_TPU_PROGSTATS", "1").strip() != "0"


def ring_len() -> int:
    return max(16, int(os.environ.get("YDB_TPU_PROGSTATS_RING", "256")))


# --------------------------------------------------------------------------
# hardware peak table
# --------------------------------------------------------------------------


def _probe_cpu() -> tuple:
    """One-shot micro-probe for backends without a table entry (the CPU
    runner): a small timed matmul for GFLOP/s, a streaming add for
    GB/s. Runs once per process, at the first utilization computation —
    compile-time-adjacent, never in a per-row hot loop."""
    import jax
    import jax.numpy as jnp
    n, reps = 384, 4
    a = jnp.ones((n, n), jnp.float32)
    mm = jax.jit(lambda x: x @ x)
    mm(a).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(reps):
        r = mm(a)
    r.block_until_ready()
    gflops = reps * 2.0 * n ** 3 / (time.perf_counter() - t0) / 1e9
    m = jnp.ones((1 << 22,), jnp.float32)          # 16 MB
    st = jax.jit(lambda x: x + 1.0)
    st(m).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(reps):
        r = st(m)
    r.block_until_ready()
    gbps = reps * 2.0 * m.nbytes / (time.perf_counter() - t0) / 1e9
    return max(gflops, 0.1), max(gbps, 0.1)


def peaks() -> dict:
    """{gflops, gbps, source} — env levers win (re-read every call, so
    tests can flip them), else the device-kind table, else the one-shot
    probe (cached), else a conservative fallback."""
    env_gf = float(os.environ.get("YDB_TPU_PEAK_GFLOPS", "0") or 0)
    env_gb = float(os.environ.get("YDB_TPU_PEAK_GBPS", "0") or 0)
    if env_gf > 0 and env_gb > 0:
        return {"gflops": env_gf, "gbps": env_gb, "source": "env"}
    with _MU:
        cached = dict(_PEAKS)
    if not cached:
        try:
            import jax
            kind = str(getattr(jax.local_devices()[0], "device_kind", ""))
            hit = next(((gf, gb) for (p, gf, gb) in _DEVICE_PEAKS
                        if kind.startswith(p)), None)
            if hit is not None:
                cached = {"gflops": hit[0], "gbps": hit[1],
                          "source": "table"}
            else:
                gf, gb = _probe_cpu()
                cached = {"gflops": gf, "gbps": gb, "source": "probe"}
        except Exception:              # noqa: BLE001 — observability
            cached = {"gflops": 10.0, "gbps": 5.0, "source": "fallback"}
        with _MU:
            _PEAKS.update(cached)
    out = dict(cached)
    if env_gf > 0:
        out["gflops"], out["source"] = env_gf, "env+" + out["source"]
    if env_gb > 0:
        out["gbps"], out["source"] = env_gb, "env+" + cached["source"]
    return out


# --------------------------------------------------------------------------
# roofline math
# --------------------------------------------------------------------------


def roofline(flops, bytes_accessed, device_ms=None, pk=None) -> dict:
    """Classify one (flops, bytes, measured-ms) triple against the peak
    table. Absent/zero cost → the explicit `unavailable` verdict (a
    backend that withholds analysis must not read as a 0-flop program).
    `device_ms` None/0 → static classification only (no utilization)."""
    pk = pk or peaks()
    f = max(float(flops or 0), 0.0)
    b = max(float(bytes_accessed or 0), 0.0)
    if f <= 0 and b <= 0:
        return {"bound_class": "unavailable", "roofline_ms": None,
                "intensity": None, "utilization_pct": None,
                "achieved_gflops": None, "achieved_gbps": None}
    t_comp_ms = f / (pk["gflops"] * 1e6)
    t_mem_ms = b / (pk["gbps"] * 1e6)
    roof_ms = max(t_comp_ms, t_mem_ms)
    if roof_ms * 1000.0 < LAUNCH_BOUND_US:
        bound = "launch_bound"
    elif t_mem_ms >= t_comp_ms:
        bound = "memory_bound"
    else:
        bound = "compute_bound"
    out = {"bound_class": bound, "roofline_ms": round(roof_ms, 6),
           "intensity": round(f / b, 3) if b > 0 else None,
           "utilization_pct": None, "achieved_gflops": None,
           "achieved_gbps": None}
    if device_ms and device_ms >= roof_ms:
        # a measured delta BELOW the roofline floor is not a
        # measurement: the block_until_ready probe ran after the
        # program already finished (warm sub-ms programs drain their
        # future late), so the delta bounds nothing — a ">100%
        # utilization" would be fabricated. Stay unmeasured; the
        # static bound_class above still stands.
        out["achieved_gflops"] = round(f / (device_ms * 1e6), 3)
        out["achieved_gbps"] = round(b / (device_ms * 1e6), 3)
        out["utilization_pct"] = round(100.0 * roof_ms / device_ms, 2)
    return out


# --------------------------------------------------------------------------
# compile-time capture
# --------------------------------------------------------------------------


def key_id(kind: str, key) -> str:
    """Stable short inventory id for a cache key (the raw keys are big
    tuples of fingerprints/signatures — repr-hash them once)."""
    import hashlib
    h = hashlib.blake2s(repr(key).encode(), digest_size=6).hexdigest()
    return f"{kind}:{h}"


def _cost_dict(compiled):
    """Normalized cost analysis, or None when the backend withholds it
    (raises, empty, or all-zero — zeros would fabricate a free
    program)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:                  # noqa: BLE001 — backend-dependent
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    out = {
        "flops": float(ca.get("flops", 0) or 0),
        "transcendentals": float(ca.get("transcendentals", 0) or 0),
        "bytes_accessed": float(ca.get("bytes accessed", 0) or 0),
        "output_bytes": float(ca.get("bytes accessedout{}", 0) or 0),
    }
    if out["flops"] <= 0 and out["bytes_accessed"] <= 0:
        return None
    return out


def _memory_dict(compiled):
    """Compiler-reported executable memory stats, or None."""
    try:
        ms = compiled.memory_analysis()
        out = {
            "arg_bytes": int(getattr(ms, "argument_size_in_bytes", 0)),
            "out_bytes": int(getattr(ms, "output_size_in_bytes", 0)),
            "temp_bytes": int(getattr(ms, "temp_size_in_bytes", 0)),
            "code_bytes":
                int(getattr(ms, "generated_code_size_in_bytes", 0)),
        }
    except Exception:                  # noqa: BLE001 — backend-dependent
        return None
    if not any(out.values()):
        return None
    return out


_HLO_TEXT_CAP = 8 << 20               # skip op-counting monster modules


def _hlo_op_count(compiled) -> int:
    """HLO instruction count of the optimized module (0 when the text
    form is unavailable or too large to bother)."""
    try:
        txt = compiled.as_text()
        if not txt or len(txt) > _HLO_TEXT_CAP:
            return 0
        return sum(1 for ln in txt.splitlines() if " = " in ln)
    except Exception:                  # noqa: BLE001 — backend-dependent
        return 0


class LazyJit:
    """The jit-path stand-in for a store-loaded handle: the store hit
    skipped tracing entirely, so there is no jitted callable to fall
    back to until drift actually happens. `rebuild()` then produces it
    once (paying exactly the trace+compile the legacy path would have
    paid) and is memoized."""

    __slots__ = ("_rebuild", "_fn", "_mu")

    def __init__(self, rebuild):
        self._rebuild = rebuild
        self._fn = None
        self._mu = threading.Lock()

    def __call__(self, *args):
        with self._mu:
            if self._fn is None:
                self._fn = self._rebuild()
            fn = self._fn
        return fn(*args)

    def clear_cache(self) -> None:
        with self._mu:
            fn, self._fn = self._fn, None
        cc = getattr(fn, "clear_cache", None)
        if callable(cc):
            cc()


class ProgramHandle:
    """A cache entry wrapping the AOT-compiled executable. Calls
    dispatch through the `Compiled`; aval/device drift (a mesh path
    running this program for another placement) falls back to the plain
    jit path — which compiles per placement exactly as it would have
    without AOT. `clear_cache` drops the executable AND clears the jit
    cache, so the exec-cache release-on-evict lifecycle holds."""

    __slots__ = ("key_id", "compile_ms", "_jit", "_compiled")

    def __init__(self, kid: str, jit_fn, compiled, compile_ms: float):
        self.key_id = kid
        self.compile_ms = compile_ms
        self._jit = jit_fn
        self._compiled = compiled

    def __call__(self, *args):
        c = self._compiled
        if c is not None:
            try:
                return c(*args)
            except (TypeError, ValueError):
                GLOBAL.inc("prog/aot_fallbacks")
        return self._jit(*args)

    def clear_cache(self) -> None:
        self._compiled = None
        cc = getattr(self._jit, "clear_cache", None)
        if callable(cc):
            cc()


def _analysis_triple(compiled, extra=None):
    """(cost, memory, hlo_ops) for an executable — preferring the
    values the SAVING process persisted (a deserialized executable may
    withhold analysis the original compile reported)."""
    extra = extra or {}
    cost = extra.get("cost") or _cost_dict(compiled)
    mem = extra.get("memory") or _memory_dict(compiled)
    hlo = int(extra.get("hlo_ops") or 0) or _hlo_op_count(compiled)
    return cost, mem, hlo


def capture(kind: str, key, jit_fn, args, consult_store: bool = True,
            store_extra=None, source: str = "fresh"):
    """AOT-compile `jit_fn(*args)` at a fresh cache fill, recording the
    executable's cost/memory analysis into the inventory. Returns a
    `ProgramHandle` to cache in place of `jit_fn` — or `jit_fn`
    unchanged when disabled or when lower/compile raises (trace errors
    then surface at the normal jit call site, byte-identical to the
    legacy lazy path).

    With the program store enabled, the store is consulted FIRST: a hit
    deserializes the persisted executable and registers with
    `compile_ms = 0` and `source = "store"` (no trace, no compile). A
    fresh compile is serialized back into the store. `consult_store =
    False` skips the lookup for call sites that already consulted the
    store themselves (the fused lane, which needs the stored extra
    payload before it can even build `jit_fn`)."""
    if not enabled():
        return jit_fn
    kid = key_id(kind, key)
    pstore = _store() if consult_store else None
    if pstore is not None:
        rec = pstore.load(kind, key)
        if rec is not None:
            compiled = rec["compiled"]
            cost, mem, hlo = _analysis_triple(compiled, rec["extra"])
            _register(kid, kind, 0.0, cost, mem, hlo, source="store")
            return ProgramHandle(kid, jit_fn, compiled, 0.0)
    t0 = time.perf_counter()
    try:
        compiled = jit_fn.lower(*args).compile()
    except Exception:                  # noqa: BLE001 — the jit call site
        GLOBAL.inc("prog/aot_errors")  # re-raises the real error
        return jit_fn
    ms = (time.perf_counter() - t0) * 1000.0
    cost, mem, hlo = (_cost_dict(compiled), _memory_dict(compiled),
                      _hlo_op_count(compiled))
    _register(kid, kind, ms, cost, mem, hlo, source=source)
    pstore = _store()
    if pstore is not None:
        extra = dict(store_extra or {})
        extra.update({"cost": cost, "memory": mem, "hlo_ops": hlo})
        pstore.save(kind, key, compiled, extra=extra)
    return ProgramHandle(kid, jit_fn, compiled, round(ms, 3))


def store_load(kind: str, key, rebuild):
    """Fused-lane store lookup: deserialize the persisted executable
    for (kind, key) WITHOUT building or tracing anything. Returns
    `(handle, extra)` — `extra` carrying whatever the saving process
    persisted alongside (the fused lane needs `layout_box`/`out_schema`
    that only trace time would otherwise produce) — or None on any
    miss. `rebuild` lazily reconstructs the jitted callable for the
    drift-fallback path (memoized, never called on the hit path)."""
    if not enabled():
        return None
    pstore = _store()
    if pstore is None:
        return None
    rec = pstore.load(kind, key)
    if rec is None:
        return None
    kid = key_id(kind, key)
    compiled = rec["compiled"]
    cost, mem, hlo = _analysis_triple(compiled, rec["extra"])
    _register(kid, kind, 0.0, cost, mem, hlo, source="store")
    return ProgramHandle(kid, LazyJit(rebuild), compiled, 0.0), rec["extra"]


def store_save(kind: str, key, handle, extra=None) -> None:
    """Persist an already-captured handle's executable (the fused lane
    saves AFTER first successful dispatch, when `layout_box` is
    populated — a trace-time artifact the store hit must replay)."""
    pstore = _store()
    if pstore is None or not isinstance(handle, ProgramHandle):
        return
    compiled = handle._compiled
    if compiled is None:
        return
    ent = inventory_entry(handle.key_id) or {}
    full = {"cost": ent.get("cost"), "memory": ent.get("memory"),
            "hlo_ops": ent.get("hlo_ops", 0)}
    full.update(extra or {})
    pstore.save(kind, key, compiled, extra=full)


def _store():
    """The active program store, or None (lever off / open failure)."""
    try:
        from ydb_tpu.progstore import store as _ps
        return _ps.get_store()
    except Exception:                  # noqa: BLE001 — store is optional
        return None


def _register(kid: str, kind: str, compile_ms, cost, mem,
              hlo_ops: int, source: str = "fresh") -> None:
    GLOBAL.inc("prog/registered")
    if compile_ms:
        GLOBAL.inc("prog/compile_ms", compile_ms)
    if cost is None:
        GLOBAL.inc("prog/cost_unavailable")
    with _MU:
        ent = _INVENTORY.get(kid)
        if ent is None:
            ent = _INVENTORY[kid] = {
                "key": kid, "kind": kind, "state": "live",
                "hits": 0, "misses": 0, "evictions": 0, "compiles": 0,
                "compile_ms": 0.0, "cost": None, "memory": None,
                "hlo_ops": 0, "execs": 0, "device_ms": 0.0,
                "device_ms_max": 0.0, "source": source,
            }
        was_evicted = ent["state"] == "evicted"
        ent["state"] = "live"
        ent["misses"] += 1             # every register IS a cache miss
        ent["compiles"] += 1
        ent["compile_ms"] += float(compile_ms or 0.0)
        ent["cost"] = cost
        ent["memory"] = mem
        ent["hlo_ops"] = int(hlo_ops)
        ent["source"] = source
        _INVENTORY.move_to_end(kid)
        while len(_INVENTORY) > ring_len():
            _INVENTORY.popitem(last=False)
    if was_evicted:
        # the PR-4 companion invariant: a re-compile of an evicted key
        # is a MISS that re-records compile cost, never a silent hit
        GLOBAL.inc("prog/recompiled")


def record_hit(kid) -> None:
    """One cache hit for an inventoried program (the handle's `key_id`;
    None — a pre-lever or lever-off entry — is a no-op)."""
    if kid is None or not enabled():
        return
    with _MU:
        ent = _INVENTORY.get(kid)
        if ent is not None:
            ent["hits"] += 1


def mark_evicted(kind: str, key) -> None:
    """Exec-cache LRU eviction surfaced: the inventory entry persists in
    the ring marked `evicted` (the executable itself was released by
    `ops/exec_cache.release_executable`)."""
    if not enabled():
        return
    with _MU:
        ent = _INVENTORY.get(key_id(kind, key))
        if ent is None:
            return
        ent["state"] = "evicted"
        ent["evictions"] += 1
    GLOBAL.inc("prog/evicted")


def record_exec(kid, device_ms: float, fresh: bool = False) -> None:
    """Join one measured device-execute span (the block_until_ready
    delta of a fused/batched dispatch) to its program: cumulative
    device ms, the roofline utilization histogram, and the statement
    accumulator feeding `QueryStats.programs`."""
    if kid is None or not enabled():
        return
    device_ms = max(float(device_ms), 0.0)
    with _MU:
        ent = _INVENTORY.get(kid)
        if ent is None:
            return
        ent["execs"] += 1
        ent["device_ms"] += device_ms
        # the max delta is the best estimate of the program's full
        # device wall (a late-drained future measures only the tail)
        ent["device_ms_max"] = max(ent["device_ms_max"], device_ms)
        cost = dict(ent["cost"]) if ent["cost"] else None
        kind = ent["kind"]
        source = ent.get("source", "fresh")
    GLOBAL.inc("prog/executions")
    GLOBAL.inc("prog/device_ms", device_ms)
    rf = roofline(cost.get("flops") if cost else None,
                  cost.get("bytes_accessed") if cost else None,
                  device_ms)
    if rf["utilization_pct"] is not None:
        GLOBAL_HIST.observe("prog/utilization_pct", rf["utilization_pct"])
    st = current()
    if st is not None:
        st.add({"key": kid, "kind": kind, "source": source,
                "device_ms": round(device_ms, 3), "fresh": bool(fresh),
                "flops": cost.get("flops") if cost else None,
                "bytes_accessed":
                    cost.get("bytes_accessed") if cost else None,
                **rf})


# --------------------------------------------------------------------------
# per-statement attribution (the memledger thread-local discipline)
# --------------------------------------------------------------------------


class StatementPrograms:
    """One statement's program executions (thread-safe: the batched lane
    may record from the leader thread for members)."""

    __slots__ = ("events", "_mu")

    def __init__(self):
        self.events: list = []
        self._mu = threading.Lock()

    def add(self, ev: dict) -> None:
        with self._mu:
            self.events.append(ev)

    def summary(self) -> dict:
        """The `QueryStats.programs` payload: per-program rows (merged
        across repeat executions within the statement, sorted by device
        ms) plus a dominant-program rollup. Empty dict when the
        statement ran no instrumented program."""
        with self._mu:
            events = [dict(e) for e in self.events]
        if not events:
            return {}
        merged: OrderedDict = OrderedDict()
        for e in events:
            m = merged.get(e["key"])
            if m is None:
                merged[e["key"]] = m = dict(e)
                m["_best_ms"] = e["device_ms"]
            else:
                m["device_ms"] = round(m["device_ms"] + e["device_ms"], 3)
                m["fresh"] = m["fresh"] or e["fresh"]
                # keep the roofline verdict of the slower (fuller)
                # measurement — the honest utilization estimate
                if e["device_ms"] > m.get("_best_ms", 0.0):
                    for k in ("utilization_pct", "achieved_gflops",
                              "achieved_gbps", "bound_class"):
                        m[k] = e[k]
                    m["_best_ms"] = e["device_ms"]
        progs = sorted(merged.values(), key=lambda p: -p["device_ms"])
        for p in progs:
            p.pop("_best_ms", None)
        dom = progs[0]
        return {"n": len(progs),
                "device_ms": round(sum(p["device_ms"] for p in progs), 3),
                "utilization_pct": dom.get("utilization_pct"),
                "bound_class": dom.get("bound_class", ""),
                "programs": progs}


def current():
    return getattr(_TLS, "programs", None)


def open_statement():
    """Open the accumulator for an OUTERMOST statement on this thread;
    None when disabled or nested (nested statements contribute to the
    enclosing accumulator — the memledger rule)."""
    if not enabled() or getattr(_TLS, "programs", None) is not None:
        return None
    st = StatementPrograms()
    _TLS.programs = st
    return st


def close_statement(st) -> None:
    if getattr(_TLS, "programs", None) is st:
        _TLS.programs = None


# --------------------------------------------------------------------------
# inventory export (the `.sys/compiled_programs` payload)
# --------------------------------------------------------------------------


def inventory_rows() -> list:
    """One row per inventoried program, oldest first — live and evicted
    alike. Empty under YDB_TPU_PROGSTATS=0 (the lever freezes the view,
    not just the capture)."""
    if not enabled():
        return []
    with _MU:
        entries = [dict(e) for e in _INVENTORY.values()]
    pk = peaks() if entries else None
    rows = []
    for e in entries:
        cost = e["cost"] or {}
        mem = e["memory"] or {}
        rf = roofline(cost.get("flops"), cost.get("bytes_accessed"),
                      e["device_ms_max"] or None, pk=pk)
        rows.append({
            "program": e["key"], "kind": e["kind"], "state": e["state"],
            "source": e.get("source", "fresh"),
            "hits": e["hits"], "misses": e["misses"],
            "evictions": e["evictions"], "compiles": e["compiles"],
            "compile_ms": round(e["compile_ms"], 3),
            "cost": "ok" if e["cost"] else "unavailable",
            "flops": cost.get("flops", 0.0),
            "transcendentals": cost.get("transcendentals", 0.0),
            "bytes_accessed": cost.get("bytes_accessed", 0.0),
            "output_bytes": cost.get("output_bytes", 0.0),
            "hlo_ops": e["hlo_ops"],
            "arg_bytes": mem.get("arg_bytes", 0),
            "out_bytes": mem.get("out_bytes", 0),
            "temp_bytes": mem.get("temp_bytes", 0),
            "code_bytes": mem.get("code_bytes", 0),
            "execs": e["execs"],
            "device_ms": round(e["device_ms"], 3),
            "device_ms_max": round(e["device_ms_max"], 3),
            "achieved_gflops": rf["achieved_gflops"] or 0.0,
            "achieved_gbps": rf["achieved_gbps"] or 0.0,
            "intensity": rf["intensity"] or 0.0,
            "utilization_pct": rf["utilization_pct"] or 0.0,
            "bound_class": rf["bound_class"],
        })
    return rows


def inventory_entry(kid: str):
    """Test/tooling hook: the raw inventory entry for a key id."""
    with _MU:
        e = _INVENTORY.get(kid)
        return dict(e) if e is not None else None


def reset_for_tests() -> None:
    """Clear the process-global inventory (test isolation only —
    counters are NOT reset)."""
    with _MU:
        _INVENTORY.clear()
