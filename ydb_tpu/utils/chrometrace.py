"""Chrome trace-event export of assembled query profiles — scrub a
query in Perfetto.

Takes one `.sys/query_profiles` record (the span tree PR 7 assembles,
clock-rebased into the router timebase by `Tracer.ingest(offset_ms=…)`)
and renders the Chrome trace-event JSON Perfetto loads directly:

  * one process, one *track per worker/device lane* (router + each DQ
    worker, with a separate `…/device` thread for the device-timeline
    spans) via `thread_name` metadata events;
  * every span as a complete `X` event (`ts`/`dur` in µs, rebased
    non-negative), its attrs and critical-path class in `args`;
  * async *flow arrows* (`s`/`f` pairs) for every channel edge — the
    producer's output-flush / ici-exchange span points at each
    consumer's input-wait span, so cross-worker data movement is a
    drawn arrow, not an inference;
  * counter tracks from the PR 11 mem ledger (cumulative host-transfer
    bytes; channel rows at each drain).

Served as `GET /trace/<query_id>` (query_id = trace_id) on the HTTP
front and written per-query by `bench.py --trace-dir`. `validate()` is
the structural checker `scripts/critpath_gate.py` gates on: matched
event pairs, monotone non-negative timestamps, at least the declared
shape of every event kind.
"""

from __future__ import annotations

from ydb_tpu.utils.tracing import span_from_dict

_DEVICE_LANE = {"device-execute", "device-dispatch",
                "device-dispatch-batched", "superblock-upload",
                "readout-transfer"}


def _lanes(spans) -> dict:
    """span_id -> track name: `critpath.lane_of` (the one shared
    lane-resolution rule) plus a '<lane>/device' sub-track for the
    device-timeline spans."""
    from ydb_tpu.utils.critpath import lane_of
    by_id = {s.span_id: s for s in spans}
    memo: dict = {}
    out = {}
    for s in spans:
        lane = lane_of(s, by_id, memo)
        if s.name in _DEVICE_LANE:
            lane = f"{lane}/device"
        out[s.span_id] = lane
    return out


def render(profile: dict) -> dict:
    """One profile record → Chrome trace-event JSON (a dict ready for
    json.dump; Perfetto-loadable)."""
    spans = [span_from_dict(d) for d in (profile.get("spans") or [])]
    events: list = []
    if not spans:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    t0 = min(s.start_ms for s in spans)
    lanes = _lanes(spans)
    lane_tid = {}
    for lane in sorted(set(lanes.values())):
        lane_tid.setdefault(lane, len(lane_tid) + 1)
    pid = 1
    events.append({"ph": "M", "name": "process_name", "pid": pid,
                   "tid": 0, "args": {"name": f"query "
                                      f"{profile.get('trace_id', 0)}"}})
    for lane, tid in lane_tid.items():
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": lane}})

    def us(ms: float) -> float:
        return round(max(0.0, ms - t0) * 1000.0, 1)

    seg_class = {s["span_id"]: s["class"]
                 for s in (profile.get("critical_path") or {})
                 .get("segments", [])}
    for s in spans:
        args = {k: v for k, v in s.attrs.items()}
        args["span_id"] = s.span_id
        if s.span_id in seg_class:
            args["critical_path_class"] = seg_class[s.span_id]
        events.append({
            "ph": "X", "name": s.name, "cat": "span", "pid": pid,
            "tid": lane_tid[lanes[s.span_id]],
            "ts": us(s.start_ms), "dur": round(max(0.0, s.dur_ms)
                                               * 1000.0, 1),
            "args": args})

    # flow arrows: producer flush span -> each consumer's input-wait,
    # paired by channel id (output-flush carries `channel_ids`;
    # ici-exchange carries `channel`)
    producers: dict = {}
    for s in spans:
        if s.name == "output-flush" and s.attrs.get("channel_ids"):
            for cid in str(s.attrs["channel_ids"]).split(","):
                if cid:
                    producers.setdefault(cid, []).append(s)
        elif s.name == "ici-exchange" and s.attrs.get("channel"):
            producers.setdefault(str(s.attrs["channel"]), []).append(s)
    fid = 0
    for s in spans:
        if s.name != "input-wait" or not s.attrs.get("channel"):
            continue
        for prod in producers.get(str(s.attrs["channel"]), ()):
            fid += 1
            start_ts = us(prod.start_ms + prod.dur_ms)
            end_ts = max(us(s.start_ms), start_ts)   # monotone per flow
            events.append({
                "ph": "s", "id": fid, "name": f"ch:{s.attrs['channel']}",
                "cat": "channel", "pid": pid,
                "tid": lane_tid[lanes[prod.span_id]], "ts": start_ts})
            events.append({
                "ph": "f", "bp": "e", "id": fid,
                "name": f"ch:{s.attrs['channel']}", "cat": "channel",
                "pid": pid, "tid": lane_tid[lanes[s.span_id]],
                "ts": end_ts})

    # counter tracks from the mem ledger: cumulative channel rows at
    # each drain, and the statement's host-transfer bytes start→end
    rows_acc = 0
    for s in sorted(spans, key=lambda x: x.start_ms + x.dur_ms):
        if s.name == "input-wait" and s.attrs.get("rows") is not None:
            rows_acc += int(s.attrs["rows"])
            events.append({"ph": "C", "name": "channel_rows",
                           "pid": pid, "tid": 0,
                           "ts": us(s.start_ms + s.dur_ms),
                           "args": {"rows": rows_acc}})
    mem = (profile.get("critical_path") or {}).get("memory") or {}
    root_end = max(s.start_ms + s.dur_ms for s in spans)
    events.append({"ph": "C", "name": "hostsync_bytes", "pid": pid,
                   "tid": 0, "ts": 0.0, "args": {"bytes": 0}})
    events.append({"ph": "C", "name": "hostsync_bytes", "pid": pid,
                   "tid": 0, "ts": us(root_end),
                   "args": {"bytes": int(mem.get("transfer_bytes", 0))}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": profile.get("trace_id", 0),
                          "sql": profile.get("sql", ""),
                          "timebase": "router"}}


def validate(trace: dict) -> list:
    """Structural Perfetto-acceptability check; returns a list of
    problems (empty = valid). Pinned: events list present; every X/B/E
    event carries name/pid/tid and non-negative ts (X also a
    non-negative dur); B/E nest matched per (pid, tid); every flow `s`
    has a matching `f` with ts >= the start's."""
    errs: list = []
    events = trace.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    stacks: dict = {}
    flows: dict = {}
    for i, e in enumerate(events):
        ph = e.get("ph")
        if ph in ("X", "B", "E", "s", "f", "C"):
            if e.get("ts") is None or e["ts"] < 0:
                errs.append(f"event {i} ({ph}): negative/missing ts")
            if ph != "E" and not e.get("name"):
                errs.append(f"event {i} ({ph}): missing name")
            if e.get("pid") is None or e.get("tid") is None:
                errs.append(f"event {i} ({ph}): missing pid/tid")
        if ph == "X":
            if e.get("dur") is None or e["dur"] < 0:
                errs.append(f"event {i}: X without non-negative dur")
        elif ph == "B":
            stacks.setdefault((e.get("pid"), e.get("tid")),
                              []).append(e.get("name"))
        elif ph == "E":
            st = stacks.setdefault((e.get("pid"), e.get("tid")), [])
            if not st:
                errs.append(f"event {i}: E without matching B")
            else:
                st.pop()
        elif ph == "s":
            flows.setdefault(e.get("id"), []).append(("s", e["ts"]))
        elif ph == "f":
            flows.setdefault(e.get("id"), []).append(("f", e["ts"]))
    for (key, st) in stacks.items():
        if st:
            errs.append(f"unclosed B events on track {key}: {st}")
    for fid, legs in flows.items():
        kinds = [k for (k, _t) in legs]
        if kinds.count("s") != 1 or kinds.count("f") != 1:
            errs.append(f"flow {fid}: needs exactly one s and one f")
            continue
        ts = dict(legs)
        if ts["f"] < ts["s"]:
            errs.append(f"flow {fid}: finish before start")
    return errs


def flow_pairs(trace: dict) -> int:
    """Matched s/f flow-arrow pairs in the trace (the gate requires at
    least one for a DQ query's channel edges)."""
    ids_s = {e.get("id") for e in trace.get("traceEvents", [])
             if e.get("ph") == "s"}
    ids_f = {e.get("id") for e in trace.get("traceEvents", [])
             if e.get("ph") == "f"}
    return len(ids_s & ids_f)
