"""Rate limiting: named token buckets (the Kesus/quoter analog).

The reference meters work through a DRR quoter service backed by
Kesus-managed hierarchical token buckets
(`ydb/core/quoter/quoter_service.cpp`, `ydb/core/kesus/` — named
resources with rate/burst, consumers block or shed). Here: named
buckets with (rate/s, burst) refilled on a monotonic clock; the engine
consumes from the `queries` resource at statement admission and sheds
with a throttle error when the bucket is dry — the overload-protection
seat.
"""

from __future__ import annotations

import time
from typing import Callable, Optional


class TokenBucket:
    def __init__(self, rate: float, burst: float,
                 clock: Optional[Callable[[], float]] = None):
        import threading
        if rate <= 0 or burst <= 0:
            raise ValueError("rate and burst must be positive")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock or time.monotonic
        self._tokens = self.burst        # guarded-by: _mu
        self._last = self._clock()       # guarded-by: _mu
        self._mu = threading.Lock()   # admission runs on session threads

    def try_acquire(self, amount: float = 1.0) -> bool:
        with self._mu:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= amount:
                self._tokens -= amount
                return True
            return False


class Quoter:
    """Named resource registry: `set_quota("queries", rate, burst)` +
    `acquire("queries")` at admission points."""

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._clock = clock
        self._buckets: dict[str, TokenBucket] = {}

    def set_quota(self, resource: str, rate: float,
                  burst: Optional[float] = None) -> None:
        self._buckets[resource] = TokenBucket(
            rate, burst if burst is not None else rate,
            clock=self._clock)

    def drop_quota(self, resource: str) -> None:
        self._buckets.pop(resource, None)

    def acquire(self, resource: str, amount: float = 1.0) -> bool:
        """True when admitted: unknown resources are unlimited (the
        quoter only meters what an operator configured)."""
        b = self._buckets.get(resource)
        return True if b is None else b.try_acquire(amount)
