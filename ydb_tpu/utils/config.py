"""Config system: YAML with selector overrides + feature flags.

The reference boots from a YAML config language with selector/override
blocks resolved per node (`ydb/library/yaml_config` — `selector_config`
entries match node labels and patch the base config) and gates features
behind flags (`ydb/core/base/feature_flags.h`), distributed at runtime by
the Console tablet. Here: one YAML document, the same base + overrides
shape, resolved at engine construction; flags gate real execution paths
(fused single-dispatch, plan cache, background compaction).

    block_rows: 1048576
    grace_budget_bytes: 536870912
    feature_flags:
      enable_fused: true
      enable_plan_cache: true
      enable_auto_compaction: true
    overrides:
      - selector: {env: test}
        config:
          block_rows: 8192

Resolution: every override whose selector is a subset of the supplied
labels applies in order, last writer wins (the reference's rule).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

DEFAULT_FLAGS = {
    "enable_fused": True,           # whole-query single-dispatch path
    "enable_plan_cache": True,
    "enable_auto_compaction": True,  # background portion merging
    "enable_device_windows": True,   # window functions on device
}


@dataclass
class Config:
    block_rows: int = 1 << 20
    grace_budget_bytes: int = 1 << 29
    data_dir: Optional[str] = None
    server_port: int = 2136
    # host fallback lanes (window functions, set-op combine) refuse frames
    # above this many rows — a silent single-core pandas job over a huge
    # frame is a perf trap; raise the limit explicitly to accept it
    host_lane_max_rows: int = 8 << 20
    # frames at or above this many rows take the device window lane
    # (ops/window_dev.py); below it the fixed dispatch+readout cost
    # outweighs the pandas pass. 0 = always device when supported.
    window_device_min_rows: int = 1 << 16
    # auto-split threshold for column shards (rows); 0 = disabled
    shard_split_rows: int = 0
    # concurrent-query pipeline: max SELECTs dispatched but not yet
    # drained (device result buffers held in HBM). 1 = serialize
    # dispatch→readout (the pre-pipeline behavior, a debug lever).
    pipeline_window: int = 4
    feature_flags: dict = field(default_factory=lambda: dict(DEFAULT_FLAGS))

    def flag(self, name: str) -> bool:
        if name not in DEFAULT_FLAGS:
            raise KeyError(f"unknown feature flag {name!r} "
                           f"(have: {', '.join(sorted(DEFAULT_FLAGS))})")
        return bool(self.feature_flags.get(name, DEFAULT_FLAGS[name]))

    @staticmethod
    def from_dict(doc: dict, labels: Optional[dict] = None) -> "Config":
        doc = dict(doc or {})
        labels = labels or {}
        merged = {k: v for k, v in doc.items() if k != "overrides"}
        for ov in doc.get("overrides", []) or []:
            sel = ov.get("selector", {}) or {}
            if all(labels.get(k) == v for k, v in sel.items()):
                patch = ov.get("config", {}) or {}
                for k, v in patch.items():
                    if k == "feature_flags":
                        merged.setdefault("feature_flags", {})
                        merged["feature_flags"] = {
                            **merged.get("feature_flags", {}), **v}
                    else:
                        merged[k] = v
        flags = {**DEFAULT_FLAGS, **(merged.pop("feature_flags", {}) or {})}
        unknown = set(flags) - set(DEFAULT_FLAGS)
        if unknown:
            raise ValueError(f"unknown feature flags: {sorted(unknown)}")
        known = {"block_rows", "grace_budget_bytes", "data_dir",
                 "server_port", "host_lane_max_rows", "shard_split_rows",
                 "window_device_min_rows", "pipeline_window"}
        bad = set(merged) - known
        if bad:
            raise ValueError(f"unknown config keys: {sorted(bad)}")
        return Config(feature_flags=flags, **merged)

    @staticmethod
    def load(path: Optional[str] = None,
             labels: Optional[dict] = None) -> "Config":
        """Load from a YAML file (default: $YDB_TPU_CONFIG if set, else
        built-in defaults)."""
        import yaml
        path = path or os.environ.get("YDB_TPU_CONFIG")
        if path is None:
            return Config()
        with open(path) as f:
            return Config.from_dict(yaml.safe_load(f) or {}, labels)
