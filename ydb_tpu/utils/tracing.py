"""Distributed-tracing spans (the Wilson analog).

The reference threads `NWilson::TTraceId` through actor events and wraps
phases in `TSpan`s uploaded via OTLP (`ydb/library/actors/wilson/
wilson_span.h`, `wilson_uploader.cpp`), with per-request sampling decided
at admission (`ydb/core/jaeger_tracing/`). Here the span tree covers a
statement's phases (parse → plan → execute, with executor sub-spans for
build/upload/dispatch/readout); the engine keeps the last trace and can
publish finished traces into a topic — the OTLP-uploader seat — so a
consumer can drain them like any changefeed.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Optional

_ids = itertools.count(1)


@dataclass
class Span:
    name: str
    trace_id: int
    span_id: int
    parent_id: Optional[int]
    start_ms: float
    dur_ms: float = 0.0
    attrs: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start_ms": round(self.start_ms, 3),
                "dur_ms": round(self.dur_ms, 3), "attrs": self.attrs}


class Tracer:
    """Per-engine span recorder: a stack-scoped context-manager API.

    One trace per statement (`begin_trace`); `span(name)` nests under the
    innermost open span. Finished traces go to `sink` (a callable) when
    set — the engine wires this to a topic for export.

    Trace state is THREAD-LOCAL: concurrent sessions each build their own
    span tree (the reference threads TTraceId through per-request actor
    chains for the same reason)."""

    def __init__(self):
        import threading
        self._tls = threading.local()
        self._t0 = time.perf_counter()
        self.sink = None

    def _state(self):
        s = self._tls
        if not hasattr(s, "spans"):
            s.spans, s.stack, s.trace_id, s.depth = [], [], 0, 0
        return s

    @property
    def spans(self) -> list:
        return self._state().spans

    @property
    def _stack(self) -> list:
        return self._state().stack

    @property
    def _trace_id(self) -> int:
        return self._state().trace_id

    def _now(self) -> float:
        return (time.perf_counter() - self._t0) * 1000.0

    def begin_trace(self) -> int:
        s = self._state()
        s.depth += 1
        if s.depth == 1:
            s.trace_id = next(_ids)
            s.spans = []
            s.stack = []
        return s.trace_id

    def span(self, name: str, **attrs):
        return _SpanCtx(self, name, attrs)

    def end_trace(self) -> list[Span]:
        s = self._state()
        s.depth = max(0, s.depth - 1)
        if s.depth > 0:
            return s.spans
        out = s.spans
        if self.sink is not None and out:
            try:
                self.sink([sp.to_dict() for sp in out])
            except Exception:                    # noqa: BLE001 — export
                pass                             # must never fail a query
        return out

    def render(self) -> str:
        """Indented span tree (the EXPLAIN ANALYZE trace section)."""
        children: dict = {}
        roots = []
        for s in self.spans:
            if s.parent_id is None:
                roots.append(s)
            else:
                children.setdefault(s.parent_id, []).append(s)
        lines = []

        def walk(s: Span, depth: int):
            attrs = "".join(f" {k}={v}" for k, v in s.attrs.items())
            # still-open spans (EXPLAIN ANALYZE renders mid-statement)
            # show elapsed-so-far instead of a misleading 0.0
            dur = s.dur_ms if s not in self._stack \
                else self._now() - s.start_ms
            lines.append(f"{'  ' * depth}- {s.name}: "
                         f"{dur:.1f}ms{attrs}")
            for c in children.get(s.span_id, []):
                walk(c, depth + 1)
        for r in roots:
            walk(r, 0)
        return "\n".join(lines)


class _SpanCtx:
    def __init__(self, tracer: Tracer, name: str, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self) -> Span:
        t = self.tracer
        parent = t._stack[-1].span_id if t._stack else None
        self.s = Span(self.name, t._trace_id, next(_ids), parent,
                      t._now(), attrs=dict(self.attrs))
        t.spans.append(self.s)
        t._stack.append(self.s)
        return self.s

    def __exit__(self, *exc):
        self.s.dur_ms = self.tracer._now() - self.s.start_ms
        self.tracer._stack.pop()
        return False
