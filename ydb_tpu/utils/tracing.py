"""Distributed-tracing spans (the Wilson analog).

The reference threads `NWilson::TTraceId` through actor events and wraps
phases in `TSpan`s uploaded via OTLP (`ydb/library/actors/wilson/
wilson_span.h`, `wilson_uploader.cpp`), with per-request sampling decided
at admission (`ydb/core/jaeger_tracing/`). Here the span tree covers a
statement's phases (parse → plan → execute, with executor sub-spans for
build/upload/dispatch/device-execute/readout), and the SAME tree spans
processes: a DQ task runner forwards `(trace_id, parent_span_id,
sampled)` over the `DqRunTask` RPC, workers record their task spans
against the adopted trace id, and the runner `ingest()`s them back —
one assembled cross-worker span tree per query. The engine keeps the
last trace and can publish finished traces into a topic (the
OTLP-uploader seat) so a consumer can drain them like any changefeed.

Sampling is decided ONCE at statement admission (`begin_trace(sampled=
False)`): an unsampled statement records nothing — `span()` hands back
throwaway contexts, so the hot path costs one TLS read and one object
allocation per phase, and the output is byte-identical to tracing off.
"""

from __future__ import annotations

import itertools
import os
import time
from dataclasses import dataclass, field
from typing import Optional

# span/trace ids draw from one per-process counter salted per process:
# two worker processes contributing spans to the same assembled trace
# must never collide on span_id (both counting from 1 guaranteed they
# would). Layout keeps ids under 2^63 — they land in int64 sysview
# columns: high 30 bits = full pid (Linux pid_max caps at 2^22) + 8
# random bits (pid-reuse across worker restarts), low 33 bits = counter.
# (no |1 inside the salt: forcing the low bit would alias adjacent
# even/odd pids; pid >= 1 already guarantees a nonzero salt)
_ids = itertools.count(
    (((int.from_bytes(os.urandom(1), "big") << 22)
      | (os.getpid() & 0x3FFFFF)) << 33) | 1)


@dataclass
class Span:
    name: str
    trace_id: int
    span_id: int
    parent_id: Optional[int]
    start_ms: float
    dur_ms: float = 0.0
    attrs: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start_ms": round(self.start_ms, 3),
                "dur_ms": round(self.dur_ms, 3), "attrs": self.attrs}


def span_from_dict(d: dict) -> Span:
    return Span(d.get("name", "?"), int(d.get("trace_id", 0)),
                int(d.get("span_id", 0)), d.get("parent_id"),
                float(d.get("start_ms", 0.0)),
                float(d.get("dur_ms", 0.0)), dict(d.get("attrs") or {}))


# span names the per-phase breakdown rolls up (utils/metrics.QueryStats
# `.phases`, the bench artifact, `.sys/query_profiles` columns): every
# device-timeline segment of a fused/batched/DQ execution
PHASE_SPANS = {
    "join-builds": "build_ms",
    "superblock-upload": "upload_ms",
    "device-dispatch": "dispatch_ms",
    "device-dispatch-batched": "dispatch_ms",
    "device-execute": "device_ms",
    "readout-transfer": "readout_ms",
}


def phase_breakdown(spans) -> dict:
    """Sum the device-timeline spans of one trace into a flat
    {phase: ms} dict. Compile happens INSIDE the first dispatch of a
    fresh shape (the dispatch span's dur contains it, stamped as the
    `compile_ms` attr), so it is pulled OUT of dispatch_ms here —
    the phases are disjoint and safe to sum. A compile-ahead build runs
    on the lane's worker thread CONCURRENTLY with planning: the span
    then carries `compile_wait_ms` (the portion of the build the
    dispatch actually blocked on), and only that much is pulled out —
    subtracting the full off-thread build would eat the real enqueue
    time the span also covers."""
    out: dict = {}
    in_dispatch = 0.0
    for s in spans:
        key = PHASE_SPANS.get(s.name)
        if key is not None:
            out[key] = out.get(key, 0.0) + s.dur_ms
        c = s.attrs.get("compile_ms")
        if c:
            out["compile_ms"] = out.get("compile_ms", 0.0) + float(c)
            w = s.attrs.get("compile_wait_ms")
            in_dispatch += float(c) if w is None else float(w)
    if in_dispatch and out.get("dispatch_ms"):
        out["dispatch_ms"] = max(0.0, out["dispatch_ms"] - in_dispatch)
    return {k: round(v, 3) for k, v in out.items()}


class Tracer:
    """Per-engine span recorder: a stack-scoped context-manager API.

    One trace per statement (`begin_trace`); `span(name)` nests under the
    innermost open span. Finished traces go to `sink` (a callable) when
    set — the engine wires this to a topic for export.

    Trace state is THREAD-LOCAL: concurrent sessions each build their own
    span tree (the reference threads TTraceId through per-request actor
    chains for the same reason)."""

    def __init__(self):
        import threading
        self._tls = threading.local()
        self._t0 = time.perf_counter()
        self.sink = None

    def _state(self):
        s = self._tls
        if not hasattr(s, "spans"):
            s.spans, s.stack, s.trace_id, s.depth = [], [], 0, 0
            s.sampled, s.root_parent = True, None
        return s

    @property
    def spans(self) -> list:
        return self._state().spans

    @property
    def _stack(self) -> list:
        return self._state().stack

    @property
    def _trace_id(self) -> int:
        return self._state().trace_id

    @property
    def sampled(self) -> bool:
        """Whether the CURRENT thread's open trace records spans."""
        s = self._state()
        return bool(s.sampled) if s.depth > 0 else False

    def _now(self) -> float:
        return (time.perf_counter() - self._t0) * 1000.0

    def begin_trace(self, sampled: bool = True, trace_id: int = None,
                    parent_id: int = None) -> int:
        """Open (or nest into) the thread's trace. `trace_id`/`parent_id`
        adopt a REMOTE context (a DQ worker joining the router's trace:
        its root spans parent under the router's task span); `sampled` is
        the admission-time decision — nested begin_trace calls (internal
        statements) inherit the outer decision."""
        s = self._state()
        s.depth += 1
        if s.depth == 1:
            s.trace_id = trace_id if trace_id is not None else next(_ids)
            s.spans = []
            s.stack = []
            s.sampled = bool(sampled)
            s.root_parent = parent_id
        return s.trace_id

    def current(self):
        """Propagation context of the thread's open trace:
        {trace_id, parent_span_id, sampled} — what rides the DqRunTask
        RPC and channel frame headers. None when no trace is open."""
        s = self._state()
        if s.depth == 0:
            return None
        return {"trace_id": s.trace_id,
                "parent_span_id": (s.stack[-1].span_id if s.stack
                                   else s.root_parent),
                "sampled": bool(s.sampled)}

    def span(self, name: str, **attrs):
        s = self._state()
        if s.depth > 0 and not s.sampled:
            return _NullSpanCtx()
        return _SpanCtx(self, name, attrs)

    def attach_span(self, name: str, parent_id: int = None,
                    **attrs) -> Optional[Span]:
        """Attach a span to the thread's open trace WITHOUT making it the
        innermost context — for spans whose lifetime is tracked from
        other threads (the DQ runner's per-attempt task spans run on a
        pool; the span object is allocated on the trace-owning thread,
        and the worker thread stamps `dur_ms`/attrs when done). Returns
        None when no sampled trace is open."""
        s = self._state()
        if s.depth == 0 or not s.sampled:
            return None
        if parent_id is None:
            parent_id = s.stack[-1].span_id if s.stack else s.root_parent
        sp = Span(name, s.trace_id, next(_ids), parent_id, self._now(),
                  attrs=dict(attrs))
        s.spans.append(sp)
        return sp

    def ingest(self, span_dicts, parent_id: int = None,
               offset_ms: float = None) -> list:
        """Merge REMOTE spans (worker `to_dict()` payloads shipped back
        in a task result) into the thread's open trace. Spans keep their
        ids and internal parent links; any whose parent is unknown in
        the combined batch re-roots under `parent_id` (default: the
        innermost open span), so a worker subtree hangs off the router's
        task span even if the worker recorded against a stale root.

        `offset_ms`: the measured LOCAL-minus-REMOTE clock offset for
        the batch's source (the DQ runner's RPC-boundary estimate,
        EWMA-smoothed per worker) — every ingested start_ms rebases by
        it, so spans from N workers land on ONE timebase (this tracer's)
        and cross-worker overlap/gaps are real. Without it the legacy
        parent-alignment fallback shifts the batch so its earliest span
        starts at the parent (honest ordering, no cross-worker
        comparability)."""
        s = self._state()
        if s.depth == 0 or not s.sampled or not span_dicts:
            return []
        if parent_id is None:
            parent_id = s.stack[-1].span_id if s.stack else s.root_parent
        known = {sp.span_id for sp in s.spans}
        batch = [span_from_dict(d) for d in span_dicts]
        if offset_ms is not None:
            # clock-aligned rebase: worker timestamps carry their own
            # tracer's epoch; adding the measured local-minus-remote
            # offset moves every one of them onto THIS tracer's clock
            for sp in batch:
                sp.start_ms = round(sp.start_ms + offset_ms, 3)
        else:
            # rebase the batch's epoch: worker start_ms is relative to
            # the WORKER tracer's process start — without shifting onto
            # the local epoch, a child could "start" hours before its
            # parent and timeline consumers of the profile would see
            # nonsense (only dur_ms is cross-process comparable;
            # relative offsets within the batch are preserved)
            parent_sp = next((sp for sp in s.spans
                              if sp.span_id == parent_id), None)
            if parent_sp is not None and batch:
                delta = parent_sp.start_ms - min(sp.start_ms
                                                 for sp in batch)
                for sp in batch:
                    sp.start_ms = round(sp.start_ms + delta, 3)
        known |= {sp.span_id for sp in batch}
        for sp in batch:
            sp.trace_id = s.trace_id
            if sp.parent_id is None or sp.parent_id not in known:
                sp.parent_id = parent_id
            s.spans.append(sp)
        return batch

    def end_trace(self) -> list[Span]:
        s = self._state()
        s.depth = max(0, s.depth - 1)
        if s.depth > 0:
            return s.spans
        # exception safety: a statement that raised past an open span
        # (or a code path that entered a span ctx it never exited) must
        # not leak stack state into the NEXT statement — force-close
        # whatever is still open, stamping elapsed-so-far
        while s.stack:
            sp = s.stack.pop()
            if sp.dur_ms == 0.0:
                sp.dur_ms = self._now() - sp.start_ms
        out = s.spans
        s.spans = []
        s.trace_id, s.root_parent, s.sampled = 0, None, True
        if self.sink is not None and out:
            try:
                self.sink([sp.to_dict() for sp in out])
            except Exception:                    # noqa: BLE001 — export
                pass                             # must never fail a query
        return out

    def render(self, spans=None) -> str:
        """Indented span tree (the EXPLAIN ANALYZE trace section).
        `spans`: render a finished trace (e.g. engine.last_trace) instead
        of the thread's in-flight one."""
        live = spans is None
        spans = self.spans if live else spans
        known = {s.span_id for s in spans}
        children: dict = {}
        roots = []
        for s in spans:
            if s.parent_id is None or s.parent_id not in known:
                roots.append(s)
            else:
                children.setdefault(s.parent_id, []).append(s)
        lines = []

        def walk(s: Span, depth: int):
            attrs = "".join(f" {k}={v}" for k, v in s.attrs.items())
            # still-open spans (EXPLAIN ANALYZE renders mid-statement)
            # show elapsed-so-far instead of a misleading 0.0
            dur = s.dur_ms if not (live and s in self._stack) \
                else self._now() - s.start_ms
            lines.append(f"{'  ' * depth}- {s.name}: "
                         f"{dur:.1f}ms{attrs}")
            for c in children.get(s.span_id, []):
                walk(c, depth + 1)
        for r in roots:
            walk(r, 0)
        return "\n".join(lines)


class _SpanCtx:
    def __init__(self, tracer: Tracer, name: str, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self) -> Span:
        t = self.tracer
        st = t._state()
        parent = st.stack[-1].span_id if st.stack else st.root_parent
        self.s = Span(self.name, st.trace_id, next(_ids), parent,
                      t._now(), attrs=dict(self.attrs))
        st.spans.append(self.s)
        st.stack.append(self.s)
        return self.s

    def __exit__(self, exc_type, exc, _tb):
        self.s.dur_ms = self.tracer._now() - self.s.start_ms
        if exc_type is not None:
            self.s.attrs.setdefault("error", exc_type.__name__)
        stack = self.tracer._stack
        # remove THIS span wherever it sits: an inner span leaked open by
        # a raising code path must not make this pop corrupt the stack
        # for the rest of the statement. Leaked descendants removed here
        # still get their elapsed stamped — end_trace's force-close only
        # sees spans that are STILL on the stack.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self.s:
                for leaked in stack[i + 1:]:
                    if leaked.dur_ms == 0.0:
                        leaked.dur_ms = \
                            self.tracer._now() - leaked.start_ms
                del stack[i:]
                break
        return False


class _NullSpanCtx:
    """Unsampled statement: hand back a throwaway span so callers that
    set attrs on the yielded span keep working, record nothing."""

    __slots__ = ("s",)

    def __enter__(self) -> Span:
        self.s = Span("", 0, 0, None, 0.0)
        return self.s

    def __exit__(self, *exc):
        return False
