"""Table/block schemas.

Analog of the reference's `ydb/core/formats/arrow/arrow_helpers.h` schema
plumbing plus SchemeShard table descriptions (simplified)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ydb_tpu.core.dtypes import DType


@dataclass(frozen=True)
class Column:
    name: str
    dtype: DType


@dataclass
class Schema:
    columns: list[Column] = field(default_factory=list)

    def __post_init__(self):
        self._index = {c.name: i for i, c in enumerate(self.columns)}
        if len(self._index) != len(self.columns):
            raise ValueError("duplicate column names")

    @property
    def names(self) -> list[str]:
        return [c.name for c in self.columns]

    def has(self, name: str) -> bool:
        return name in self._index

    def col(self, name: str) -> Column:
        return self.columns[self._index[name]]

    def dtype(self, name: str) -> DType:
        return self.col(name).dtype

    def select(self, names: list[str]) -> "Schema":
        return Schema([self.col(n) for n in names])

    def extend(self, cols: list[Column]) -> "Schema":
        return Schema(self.columns + cols)

    def __len__(self) -> int:
        return len(self.columns)

    def __iter__(self):
        return iter(self.columns)
