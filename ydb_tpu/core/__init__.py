from ydb_tpu.core import dtypes
from ydb_tpu.core.block import HostBlock
from ydb_tpu.core.schema import Column, Schema

__all__ = ["dtypes", "HostBlock", "Column", "Schema"]
