"""Append-only string dictionaries.

The device data plane never sees raw bytes: string columns travel as int32
codes; the dictionary (codes → values) stays on the host. String predicates
(LIKE/eq/substr) are evaluated once over the dictionary on the host, producing
a boolean/typed lookup table the device gathers through — the TPU-native
counterpart of the reference's dictionary encoding
(`ydb/core/formats/arrow/dictionary/`) + hyperscan/re2 string UDFs
(`ydb/library/yql/udfs/common/`).
"""

from __future__ import annotations

import numpy as np


class Dictionary:
    """Append-only value dictionary: value <-> int32 code."""

    __slots__ = ("_map", "_values", "_ranks")

    def __init__(self):
        self._map: dict[str, int] = {}
        self._values: list[str] = []
        self._ranks = None          # (len, ranks) memo — see sort_ranks

    def __len__(self) -> int:
        return len(self._values)

    def encode(self, values) -> np.ndarray:
        """Encode an iterable of python strings (None → code -1)."""
        m = self._map
        vals = self._values
        out = np.empty(len(values), dtype=np.int32)
        for i, v in enumerate(values):
            if v is None:
                out[i] = -1
                continue
            code = m.get(v)
            if code is None:
                code = len(vals)
                m[v] = code
                vals.append(v)
            out[i] = code
        return out

    def encode_bulk(self, values: np.ndarray) -> np.ndarray:
        """Vectorized encode of an object array (None → -1): hash-factorize
        once (C speed), then encode only the distinct values through the
        Python-dict path. 60M rows cost one factorize + a take, not 60M
        dict lookups."""
        import pandas as pd
        codes, uniques = pd.factorize(values, use_na_sentinel=True)
        if hasattr(uniques, "to_numpy"):
            uniques = uniques.to_numpy(dtype=object)
        # str-coerce at the UNIQUES level (small): non-str objects (a
        # numeric-looking column pandas inferred as int) must enter the
        # dictionary as strings, or lookups/sorts break; equal-after-str
        # values collapse to one code via the encode map
        lut = self.encode([u if isinstance(u, str) else str(u)
                           for u in uniques])
        lut = np.concatenate([lut, np.array([-1], np.int32)])  # -1 slot
        return lut[codes].astype(np.int32)

    def encode_existing(self, value: str) -> int:
        """Code for a value, or -2 (never matches) if absent."""
        return self._map.get(value, -2)

    def decode(self, codes: np.ndarray) -> np.ndarray:
        values = self.values_array()
        out = np.empty(len(codes), dtype=object)
        ok = codes >= 0
        out[ok] = values[codes[ok]]
        out[~ok] = None
        return out

    def values_array(self) -> np.ndarray:
        # length captured first: a concurrent writer appending (append-only,
        # codes are stable) must not grow the list mid-conversion
        vals = self._values
        return np.asarray(vals[:len(vals)], dtype=object)

    def sort_ranks(self) -> np.ndarray:
        """code → lexicographic rank (int32), memoized per dictionary
        length: sort keys recompute this per query, and at URL-scale
        cardinality a fresh double-argsort over millions of strings costs
        seconds. Append-only dictionaries make the (len, ranks) memo
        exact."""
        vals = self._values
        n = len(vals)
        cached = self._ranks
        if cached is not None and cached[0] == n:
            return cached[1]
        if not n:
            ranks = np.zeros(1, np.int32)
        else:
            arr = np.asarray(vals[:n], dtype=object)
            ranks = np.argsort(np.argsort(arr, kind="stable")) \
                .astype(np.int32)
        self._ranks = (n, ranks)
        return ranks

    def lut(self, predicate) -> np.ndarray:
        """Evaluate `predicate(value) -> bool` over all dictionary entries.

        Returns a bool LUT of len(dict); the device evaluates the predicate
        on a code column as `lut[code]` (a gather).
        """
        vals = self._values
        n = len(vals)                 # stable under concurrent appends
        out = np.empty(n, dtype=np.bool_)
        for i in range(n):
            out[i] = predicate(vals[i])
        return out
