"""Append-only string dictionaries.

The device data plane never sees raw bytes: string columns travel as int32
codes; the dictionary (codes → values) stays on the host. String predicates
(LIKE/eq/substr) are evaluated once over the dictionary on the host, producing
a boolean/typed lookup table the device gathers through — the TPU-native
counterpart of the reference's dictionary encoding
(`ydb/core/formats/arrow/dictionary/`) + hyperscan/re2 string UDFs
(`ydb/library/yql/udfs/common/`).
"""

from __future__ import annotations

import numpy as np


class Dictionary:
    """Append-only value dictionary: value <-> int32 code."""

    __slots__ = ("_map", "_values")

    def __init__(self):
        self._map: dict[str, int] = {}
        self._values: list[str] = []

    def __len__(self) -> int:
        return len(self._values)

    def encode(self, values) -> np.ndarray:
        """Encode an iterable of python strings (None → code -1)."""
        m = self._map
        vals = self._values
        out = np.empty(len(values), dtype=np.int32)
        for i, v in enumerate(values):
            if v is None:
                out[i] = -1
                continue
            code = m.get(v)
            if code is None:
                code = len(vals)
                m[v] = code
                vals.append(v)
            out[i] = code
        return out

    def encode_bulk(self, values: np.ndarray) -> np.ndarray:
        """Vectorized encode of an object array (None → -1): hash-factorize
        once (C speed), then encode only the distinct values through the
        Python-dict path. 60M rows cost one factorize + a take, not 60M
        dict lookups."""
        import pandas as pd
        codes, uniques = pd.factorize(values, use_na_sentinel=True)
        if hasattr(uniques, "to_numpy"):
            uniques = uniques.to_numpy(dtype=object)
        lut = self.encode(list(uniques))
        lut = np.concatenate([lut, np.array([-1], np.int32)])  # -1 slot
        return lut[codes].astype(np.int32)

    def encode_existing(self, value: str) -> int:
        """Code for a value, or -2 (never matches) if absent."""
        return self._map.get(value, -2)

    def decode(self, codes: np.ndarray) -> np.ndarray:
        values = self.values_array()
        out = np.empty(len(codes), dtype=object)
        ok = codes >= 0
        out[ok] = values[codes[ok]]
        out[~ok] = None
        return out

    def values_array(self) -> np.ndarray:
        # length captured first: a concurrent writer appending (append-only,
        # codes are stable) must not grow the list mid-conversion
        vals = self._values
        return np.asarray(vals[:len(vals)], dtype=object)

    def lut(self, predicate) -> np.ndarray:
        """Evaluate `predicate(value) -> bool` over all dictionary entries.

        Returns a bool LUT of len(dict); the device evaluates the predicate
        on a code column as `lut[code]` (a gather).
        """
        vals = self._values
        n = len(vals)                 # stable under concurrent appends
        out = np.empty(n, dtype=np.bool_)
        for i in range(n):
            out[i] = predicate(vals[i])
        return out
