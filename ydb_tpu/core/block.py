"""Columnar blocks (host side).

A ``HostBlock`` is the unit of data flow between storage, channels, and the
device compute path: a set of equal-length numpy columns with optional
validity bitmaps — the analog of an Arrow RecordBatch in the reference's scan
protocol (`ydb/core/kqp/common/kqp_compute_events.h` TEvScanData ArrowBatch)
and of MiniKQL block values (`mkql_block_builder.cpp`).

Null representation: (data, valid) pairs; ``valid is None`` means
"no nulls". String columns carry int32 dictionary codes plus a reference to
their host-side ``Dictionary``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ydb_tpu.core.dictionary import Dictionary
from ydb_tpu.core.dtypes import DType, Kind
from ydb_tpu.core.schema import Column, Schema


@dataclass
class ColumnData:
    data: np.ndarray
    valid: Optional[np.ndarray] = None          # bool array or None (=all valid)
    dictionary: Optional[Dictionary] = None     # strings only


@dataclass
class HostBlock:
    schema: Schema
    columns: dict[str, ColumnData] = field(default_factory=dict)
    length: int = 0

    @staticmethod
    def from_arrays(
        schema: Schema,
        arrays: dict[str, np.ndarray],
        valids: Optional[dict[str, np.ndarray]] = None,
        dictionaries: Optional[dict[str, Dictionary]] = None,
    ) -> "HostBlock":
        valids = valids or {}
        dictionaries = dictionaries or {}
        cols = {}
        length = None
        for c in schema:
            a = np.asarray(arrays[c.name], dtype=c.dtype.np)
            if length is None:
                length = len(a)
            elif len(a) != length:
                raise ValueError("ragged block")
            cols[c.name] = ColumnData(a, valids.get(c.name), dictionaries.get(c.name))
        return HostBlock(schema, cols, length or 0)

    @staticmethod
    def from_pandas(df, schema: Optional[Schema] = None,
                    dictionaries: Optional[dict[str, Dictionary]] = None) -> "HostBlock":
        """Build a block from a pandas DataFrame (tests / ingestion)."""
        import pandas as pd  # noqa: F401
        from ydb_tpu.core import dtypes as dt

        dictionaries = dict(dictionaries or {})
        cols: dict[str, ColumnData] = {}
        columns: list[Column] = []
        for name in df.columns:
            s = df[name]
            valid = None
            if s.isna().any():
                valid = (~s.isna()).to_numpy()
            if schema is not None:
                dtype = schema.dtype(name)
            elif s.dtype == object or str(s.dtype) in ("string", "str"):
                # object dtype is how pandas renders NULL-bearing NUMERIC
                # columns too (to_pandas emits them that way) — classify
                # by the non-null values, not the container dtype
                nonnull = s.dropna()
                if len(nonnull) and all(
                        isinstance(v, (int, float, np.integer, np.floating))
                        and not isinstance(v, bool)
                        for v in nonnull.tolist()):
                    if all(isinstance(v, (int, np.integer))
                           for v in nonnull.tolist()):
                        dtype = dt.DType(dt.Kind.INT64, True)
                    else:
                        dtype = dt.DType(dt.Kind.FLOAT64, True)
                else:
                    dtype = dt.STRING
            else:
                dtype = dt.from_numpy(s.dtype)
            if dtype.is_string:
                d = dictionaries.setdefault(name, Dictionary())
                arr = s.to_numpy(dtype=object, copy=True)
                if valid is not None:
                    arr[~valid] = None
                data = d.encode_bulk(arr)   # factorize, not 1 lookup/row
                cols[name] = ColumnData(data, valid, d)
            else:
                data = s.to_numpy(dtype=dtype.np, na_value=0) if valid is not None \
                    else s.to_numpy(dtype=dtype.np)
                cols[name] = ColumnData(np.ascontiguousarray(data), valid)
            columns.append(Column(name, dtype))
        return HostBlock(schema or Schema(columns), cols, len(df))

    def to_pandas(self):
        import pandas as pd

        out = {}
        for c in self.schema:
            cd = self.columns[c.name]
            if c.dtype.is_string and cd.dictionary is not None:
                vals = cd.dictionary.decode(cd.data)
            else:
                vals = cd.data.astype(object) if cd.valid is not None else cd.data
            if cd.valid is not None:
                vals = np.array(vals, dtype=object)
                vals[~cd.valid] = None
            out[c.name] = vals
        return pd.DataFrame(out)

    def take(self, indices: np.ndarray) -> "HostBlock":
        cols = {}
        for name, cd in self.columns.items():
            cols[name] = ColumnData(
                cd.data[indices],
                cd.valid[indices] if cd.valid is not None else None,
                cd.dictionary,
            )
        return HostBlock(self.schema, cols, len(indices))

    def slice(self, start: int, stop: int) -> "HostBlock":
        cols = {}
        for name, cd in self.columns.items():
            cols[name] = ColumnData(
                cd.data[start:stop],
                cd.valid[start:stop] if cd.valid is not None else None,
                cd.dictionary,
            )
        return HostBlock(self.schema, cols, max(0, stop - start))

    def select(self, names: list[str]) -> "HostBlock":
        return HostBlock(self.schema.select(names),
                         {n: self.columns[n] for n in names}, self.length)

    @staticmethod
    def concat(blocks: list["HostBlock"]) -> "HostBlock":
        if not blocks:
            raise ValueError("empty concat")
        if len(blocks) == 1:
            return blocks[0]
        schema = blocks[0].schema
        cols = {}
        n = sum(b.length for b in blocks)
        for c in schema:
            datas = [b.columns[c.name].data for b in blocks]
            data = np.concatenate(datas)
            valid = None
            if any(b.columns[c.name].valid is not None for b in blocks):
                valid = np.concatenate([
                    b.columns[c.name].valid if b.columns[c.name].valid is not None
                    else np.ones(b.length, dtype=np.bool_)
                    for b in blocks
                ])
            dicts = {id(b.columns[c.name].dictionary) for b in blocks
                     if b.columns[c.name].dictionary is not None}
            if len(dicts) > 1:
                raise ValueError(f"concat across different dictionaries for {c.name}")
            d = next((b.columns[c.name].dictionary for b in blocks
                      if b.columns[c.name].dictionary is not None), None)
            cols[c.name] = ColumnData(data, valid, d)
        return HostBlock(schema, cols, n)
