"""Column data types.

The type lattice follows the reference's YQL primitive types as used by the
columnar path (`ydb/core/formats/arrow/switch/switch_type.h`,
`ydb/library/yql/public/udf/udf_data_type.h`): fixed-width integers, floats,
bool, date/timestamp, and strings. Strings are dictionary-encoded for the
device path (codes on TPU, dictionary on host) — the reference has the same
move in `ydb/core/formats/arrow/dictionary/`.

Decimal follows the reference's own TPC-H schema choice of Double
(`ydb/public/lib/ydb_cli/commands/tpch_schema.sql`).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np


class Kind(enum.Enum):
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    DATE32 = "date32"          # days since unix epoch, int32 storage
    TIMESTAMP = "timestamp"    # microseconds since epoch, int64 storage
    STRING = "string"          # dictionary-encoded: int32 codes + host dict


_NUMPY = {
    Kind.BOOL: np.bool_,
    Kind.INT8: np.int8,
    Kind.INT16: np.int16,
    Kind.INT32: np.int32,
    Kind.INT64: np.int64,
    Kind.UINT8: np.uint8,
    Kind.UINT16: np.uint16,
    Kind.UINT32: np.uint32,
    Kind.UINT64: np.uint64,
    Kind.FLOAT32: np.float32,
    Kind.FLOAT64: np.float64,
    Kind.DATE32: np.int32,
    Kind.TIMESTAMP: np.int64,
    Kind.STRING: np.int32,     # physical: dictionary codes
}

_INTS = {Kind.INT8, Kind.INT16, Kind.INT32, Kind.INT64,
         Kind.UINT8, Kind.UINT16, Kind.UINT32, Kind.UINT64}
_FLOATS = {Kind.FLOAT32, Kind.FLOAT64}


@dataclass(frozen=True)
class DType:
    kind: Kind
    nullable: bool = True

    @property
    def np(self) -> type:
        """Physical numpy storage dtype."""
        return _NUMPY[self.kind]

    @property
    def is_integer(self) -> bool:
        return self.kind in _INTS

    @property
    def is_float(self) -> bool:
        return self.kind in _FLOATS

    @property
    def is_numeric(self) -> bool:
        return self.is_integer or self.is_float

    @property
    def is_string(self) -> bool:
        return self.kind is Kind.STRING

    @property
    def is_temporal(self) -> bool:
        return self.kind in (Kind.DATE32, Kind.TIMESTAMP)

    def with_nullable(self, nullable: bool) -> "DType":
        return DType(self.kind, nullable)

    def __repr__(self) -> str:  # compact: Int64?, Float64
        return self.kind.value + ("?" if self.nullable else "")


# Convenience constructors
BOOL = DType(Kind.BOOL)
INT8 = DType(Kind.INT8)
INT16 = DType(Kind.INT16)
INT32 = DType(Kind.INT32)
INT64 = DType(Kind.INT64)
UINT8 = DType(Kind.UINT8)
UINT16 = DType(Kind.UINT16)
UINT32 = DType(Kind.UINT32)
UINT64 = DType(Kind.UINT64)
FLOAT32 = DType(Kind.FLOAT32)
FLOAT64 = DType(Kind.FLOAT64)
DATE32 = DType(Kind.DATE32)
TIMESTAMP = DType(Kind.TIMESTAMP)
STRING = DType(Kind.STRING)


def common_numeric(a: DType, b: DType) -> DType:
    """Binary-op result type promotion (YQL-style: float wins, wider wins)."""
    if not (a.is_numeric and b.is_numeric):
        if a.kind == b.kind:
            return DType(a.kind, a.nullable or b.nullable)
        raise TypeError(f"no common type for {a} and {b}")
    kind = Kind(np.promote_types(a.np, b.np).name)
    return DType(kind, a.nullable or b.nullable)


def from_numpy(dt: np.dtype) -> DType:
    return DType(Kind(np.dtype(dt).name))
