"""ydb_tpu — a TPU-native distributed SQL engine.

A from-scratch framework with the capability surface of YDB (reference:
waralex/ydb), redesigned TPU-first:

- the columnar execution substrate is a typed SSA-style op IR
  (``ydb_tpu.ops``) with a numpy oracle lowering and an XLA lowering
  (``jax.jit`` per program/shape-bucket) — the analog of the reference's
  ColumnShard SSA program (`ydb/core/protos/ssa.proto`) and MiniKQL block
  compute nodes (`ydb/library/yql/minikql/comp_nodes/mkql_block_*.cpp`);
- the storage layer is an embedded column store mirroring ColumnShard's
  InsertTable/portions/compaction model (`ydb/core/tx/columnshard/engines/`);
- distributed execution is a DQ-style stage/task/channel graph
  (`ydb/library/yql/dq/`) whose hash shuffles lower to XLA collectives over
  a `jax.sharding.Mesh` instead of Interconnect TCP channels.

Numeric policy: f64/i64 are first-class (TPU emulates f64 with adequate
precision for SQL aggregate semantics); therefore jax x64 mode is enabled
at package import.
"""

import os as _os

import jax as _jax

_jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: SQL engines compile one executable per
# (program, shape-bucket) and re-create the same shapes across processes
# (server restarts, CLI runs, benchmarks). On this platform a remote
# compile costs seconds-to-minutes; a cache hit costs ~0.1s. Opt out with
# YDB_TPU_JIT_CACHE=0, relocate with YDB_TPU_JIT_CACHE=/path.
_cache_dir = _os.environ.get("YDB_TPU_JIT_CACHE", "")
# forced-CPU processes (tests, virtual meshes) skip it BY DEFAULT: CPU
# compiles are fast, and XLA:CPU AOT entries warn about host-feature
# mismatches across processes (SIGILL risk) — the cache's value is the
# remote TPU compiler. An explicit YDB_TPU_JIT_CACHE path still wins.
if not _cache_dir and _os.environ.get("JAX_PLATFORMS", "") == "cpu":
    _cache_dir = "0"
if _cache_dir != "0":
    if not _cache_dir:
        _cache_dir = _os.path.join(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))), ".jax_cache")
    try:
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
    except Exception:                    # noqa: BLE001 — cache is optional
        pass

# pandas 3 defaults str columns/indexes to pyarrow-backed storage, and
# ArrowStringArray._from_sequence intermittently SEGFAULTS when a
# DataFrame is constructed on a non-main thread in this image (observed
# from the pgwire/gRPC server threads). numpy-backed str storage keeps
# the same dtype semantics without pyarrow on the construction path.
import pandas as _pd

_pd.set_option("mode.string_storage", "python")

__version__ = "0.1.0"
