"""Hive membership — lease-based worker liveness.

The reference's Hive tablet tracks node liveness through the node
broker / local services (`hive_impl.h:158` TNodeInfo, lease-style
`TEvLocal::TEvPing` round-trips); here a worker REGISTERS once and then
renews a lease with heartbeats. A lease that expires without renewal
marks the worker dead — the control plane never needs a worker's
cooperation to declare it gone (kill -9 is indistinguishable from a
network partition, and both must converge to `dead` within one lease).

Two renewal transports feed the same table:

  * push — workers run a `hive/agent.py` HeartbeatAgent against the
    HiveRegister/HiveHeartbeat RPCs of whichever server hosts the Hive
    (`server/service.py`, engine.hive attached);
  * pull — a router-side pulse loop pings plain gRPC workers and renews
    the lease of every responder (`hive/core.py` Hive.pulse), for
    deployments where workers predate the agent.

The clock is injectable so lease expiry is unit-testable without
sleeping; counters land in the `hive/*` namespace on /counters.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

ALIVE = "alive"
DEAD = "dead"


@dataclass
class NodeInfo:
    """One registered worker (the TNodeInfo seat)."""
    node_id: str
    endpoint: str
    capacity: float = 1.0
    shards: list = field(default_factory=list)   # shard ids it serves
    registered_at: float = 0.0
    lease_deadline: float = 0.0
    heartbeats: int = 0
    state: str = ALIVE
    load: float = 0.0               # worker-reported (stage wall ms)
    # set when the node re-registered AFTER its shards were re-placed:
    # its local store still holds the old shard's rows, so sharded scans
    # must skip it until an operator re-images it (double-count guard)
    stale: bool = False
    # ever owned a shard (placement sync sets it; never cleared) — a
    # dead rejoiner is stale only if it HAD shards that were re-placed
    had_shards: bool = False


class HiveMembership:
    """Worker registry with lease liveness. Thread-safe: heartbeats
    arrive from gRPC pool threads while the router sweeps."""

    def __init__(self, lease_s: float = 3.0, clock=time.monotonic,
                 counters=None):
        from ydb_tpu.utils.metrics import GLOBAL
        self.lease_s = float(lease_s)
        self.clock = clock
        self.counters = counters or GLOBAL
        self._mu = threading.Lock()
        # registration order is placement order (dict preserves it) —
        # the router's worker list must keep the operator's endpoint
        # order so pk-hash insert routing stays stable across restarts.
        # NodeInfo fields are part of this table's state: mutating them
        # (shards/stale/load/...) requires _mu too, which is why the
        # Hive's placement mirror goes through sync_shards below.
        self._nodes: dict[str, NodeInfo] = {}   # guarded-by: _mu

    # -- registration / renewal --------------------------------------------

    def register(self, endpoint: str, node_id: str = "",
                 capacity: float = 1.0, shards=()) -> dict:
        """Register (or revive) a worker; grants a fresh lease. Returns
        the accepted identity and lease so the agent can schedule
        renewals at lease/3."""
        nid = node_id or endpoint
        now = self.clock()
        with self._mu:
            n = self._nodes.get(nid)
            if n is None:
                n = self._nodes[nid] = NodeInfo(
                    node_id=nid, endpoint=endpoint,
                    capacity=float(capacity), shards=list(shards),
                    registered_at=now)
                self.counters.inc("hive/registered")
            else:
                # rejoin: a node whose shards were re-placed while it was
                # dead holds stale copies of them — it may serve again
                # only after re-imaging (its `shards` were zeroed by the
                # re-placement; an operator resets `stale` after wiping)
                if n.state == DEAD and n.had_shards and not n.shards:
                    n.stale = True
                    self.counters.inc("hive/rejoin_stale")
                n.endpoint = endpoint
                n.capacity = float(capacity)
                n.state = ALIVE
            n.lease_deadline = now + self.lease_s
            self._gauge_locked()
            return {"node_id": nid, "lease_s": self.lease_s,
                    "shards": list(n.shards), "stale": n.stale}

    def heartbeat(self, node_id: str, load: float = None) -> dict:
        """Renew a lease. Unknown node → the agent must re-register
        (the Hive restarted and lost volatile membership)."""
        with self._mu:
            n = self._nodes.get(node_id)
            if n is None or n.state == DEAD:
                return {"ok": False, "register": True}
            n.lease_deadline = self.clock() + self.lease_s
            n.heartbeats += 1
            if load is not None:
                n.load = float(load)
            self.counters.inc("hive/heartbeats")
            return {"ok": True, "lease_s": self.lease_s}

    # -- liveness -----------------------------------------------------------

    def sweep(self) -> list:
        """Expire overdue leases; returns the NEWLY dead nodes (the
        caller — `hive/core.py` — re-places their shards)."""
        now = self.clock()
        newly = []
        with self._mu:
            for n in self._nodes.values():
                if n.state == ALIVE and n.lease_deadline <= now:
                    n.state = DEAD
                    newly.append(n)
                    self.counters.inc("hive/lease_expired")
                    self.counters.inc("hive/worker_dead")
            if newly:
                self._gauge_locked()
        return newly

    def expire(self, endpoints) -> list:
        """Force-expire leases for observed-dead endpoints (the query
        path saw a transport error — no reason to wait out the lease).
        Returns the newly dead nodes, like sweep()."""
        eps = set(endpoints)
        newly = []
        with self._mu:
            for n in self._nodes.values():
                if n.state == ALIVE and n.endpoint in eps:
                    n.state = DEAD
                    newly.append(n)
                    self.counters.inc("hive/worker_dead")
            if newly:
                self._gauge_locked()
        return newly

    def sync_shards(self, owned: dict) -> None:
        """Mirror a placement map back onto NodeInfo.shards (the sysview
        and rejoin-staleness both read them). NodeInfo rows are THIS
        registry's state, so the mutation holds OUR lock — the Hive used
        to rewrite them under its placement lock only, which let a
        concurrent rows()/register() observe half-synced shard lists."""
        with self._mu:
            for n in self._nodes.values():
                n.shards = sorted(owned.get(n.node_id, ()), key=str)
                if n.shards:
                    n.had_shards = True

    def _gauge_locked(self) -> None:
        self.counters.set("hive/workers_alive",
                          sum(1 for n in self._nodes.values()
                              if n.state == ALIVE))

    # -- views --------------------------------------------------------------

    def get(self, node_id: str):
        with self._mu:
            return self._nodes.get(node_id)

    def by_endpoint(self, endpoint: str):
        with self._mu:
            for n in self._nodes.values():
                if n.endpoint == endpoint:
                    return n
        return None

    def alive(self) -> list:
        """Alive nodes in REGISTRATION order (placement order)."""
        with self._mu:
            return [n for n in self._nodes.values() if n.state == ALIVE]

    def nodes(self) -> list:
        with self._mu:
            return list(self._nodes.values())

    def rows(self) -> list:
        """`.sys/cluster_nodes` row payloads."""
        now = self.clock()
        with self._mu:
            return [{
                "node_id": n.node_id, "endpoint": n.endpoint,
                "state": n.state,
                "lease_ms_left": max(0.0, (n.lease_deadline - now)
                                     * 1000.0) if n.state == ALIVE else 0.0,
                "heartbeats": n.heartbeats,
                "capacity": n.capacity, "load": n.load,
                "shards": ",".join(str(s) for s in n.shards),
                "stale": n.stale,
            } for n in self._nodes.values()]
