"""Hive — the cluster control plane (membership, placement, failover).

The reference's Hive tablet + StateStorage seats (`hive_impl.h`,
`statestorage.cpp`), radically simplified into three cooperating parts:

  * membership   (`hive/membership.py`) — workers hold leases renewed by
                 heartbeats (push agents or router pull); expiry = dead;
  * placement    (`hive/placement.py`) — a deterministic capacity- and
                 load-aware shard→worker map, stable while owners live;
  * failover     (`hive/core.py` re-placement over `hive/adopt.py` image
                 replay; `hive/election.py` lease-elected router/standby
                 leadership).

Observability: `hive/*` counters on /counters and the
`.sys/cluster_nodes` sysview (`scheme/sysview.py`).
"""

from ydb_tpu.hive.adopt import adopt_shard
from ydb_tpu.hive.agent import HeartbeatAgent
from ydb_tpu.hive.core import Hive, HiveError
from ydb_tpu.hive.election import (LeaseElection, LeaseFile,
                                   promote_when_elected)
from ydb_tpu.hive.membership import ALIVE, DEAD, HiveMembership, NodeInfo
from ydb_tpu.hive.placement import (PlacementMap, rebalance,
                                    stage_load_signal)

__all__ = [
    "ALIVE", "DEAD", "HeartbeatAgent", "Hive", "HiveError",
    "HiveMembership", "LeaseElection", "LeaseFile", "NodeInfo",
    "PlacementMap", "adopt_shard", "promote_when_elected", "rebalance",
    "stage_load_signal",
]
