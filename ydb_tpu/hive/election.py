"""Lease-based leader election — the StateStorage seat.

The reference elects tablet leaders through StateStorage replicas
(`statestorage.cpp` generation+guard rounds); the analog here is a
LEASE RECORD on shared storage (the standby mirror's disk — the same
medium the data already rides): candidates race to acquire it, the
winner renews at lease/3, and a leader that stops renewing (crash,
partition) loses the lease to the next candidate after expiry. Exactly
one leader per lease interval, no operator in the loop.

This turns standby promotion (`cluster/replica.py` StandbyServer)
from operator-driven ("boot from the standby root by hand") into
election-driven: every router candidate runs `promote_when_elected` —
whoever wins the lease boots the engine from the standby root; the
losers keep waiting as warm spares and take over on lease expiry.

The acquire critical section is an atomic `os.mkdir` lock (POSIX mkdir
is atomic across processes on one filesystem — the shared-disk analog
of a StateStorage quorum round), with stale-lock breaking so a candidate
killed INSIDE the critical section cannot wedge the election forever.
"""

from __future__ import annotations

import json
import os
import threading
import time


class LeaseFile:
    """The durable lease record: {owner, deadline}."""

    LOCK_STALE_S = 5.0          # break a lock dir older than this

    def __init__(self, path: str, clock=time.time):
        self.path = path
        self.clock = clock
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def _locked(self, fn):
        lockdir = self.path + ".lock"
        tokenf = os.path.join(lockdir, "owner")
        token = f"{os.getpid()}.{time.time_ns()}"
        deadline = time.monotonic() + 10.0
        while True:
            try:
                os.mkdir(lockdir)
                with open(tokenf, "w") as f:
                    f.write(token)
                break
            except FileExistsError:
                try:
                    # wall clock on BOTH sides: getmtime is epoch
                    # seconds, so the staleness compare must be too
                    # (monotonic here would never fire and a candidate
                    # killed inside the critical section would wedge
                    # the election forever)
                    if time.time() - os.path.getmtime(lockdir) \
                            > self.LOCK_STALE_S:
                        try:
                            os.unlink(tokenf)
                        except OSError:
                            pass
                        os.rmdir(lockdir)
                        continue
                except OSError:
                    pass
                if time.monotonic() > deadline:
                    raise TimeoutError(f"lease lock wedged: {lockdir}")
                time.sleep(0.01)
        try:
            return fn()
        finally:
            # release ONLY a lock we still own: if a peer stale-broke
            # ours while we stalled, blindly rmdir'ing here would free
            # the peer's LIVE lock and let a third candidate into the
            # critical section alongside it
            try:
                with open(tokenf) as f:
                    mine = f.read() == token
            except OSError:
                mine = False
            if mine:
                try:
                    os.unlink(tokenf)
                    os.rmdir(lockdir)
                except OSError:
                    pass

    def read(self):
        try:
            with open(self.path) as f:
                return json.loads(f.read())
        except (OSError, ValueError):
            return None

    def _write(self, rec: dict) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(rec))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def try_acquire(self, owner: str, lease_s: float) -> bool:
        """Acquire or renew: succeeds when the record is absent, expired,
        or already ours. One winner per lease interval — the mkdir lock
        serializes the read-check-write."""
        def body():
            rec = self.read()
            now = self.clock()
            if rec is not None and rec.get("owner") != owner \
                    and float(rec.get("deadline", 0)) > now:
                return False
            self._write({"owner": owner, "deadline": now + lease_s})
            # confirm after write: if a peer stale-broke OUR lock while
            # we stalled and wrote between our read and write, the race
            # loser must see itself overwritten. Plain files have no
            # CAS, so a peer writing AFTER this re-read still wins a
            # window bounded by one renewal interval (step() then flips
            # the loser to not-leader); LOCK_STALE_S must exceed any
            # honest pause inside this critical section.
            rec = self.read()
            return rec is not None and rec.get("owner") == owner
        return self._locked(body)

    def release(self, owner: str) -> None:
        def body():
            rec = self.read()
            if rec is not None and rec.get("owner") == owner:
                try:
                    os.unlink(self.path)
                except FileNotFoundError:
                    pass
        self._locked(body)

    def holder(self):
        """Current live holder (None when absent or expired)."""
        rec = self.read()
        if rec is None or float(rec.get("deadline", 0)) <= self.clock():
            return None
        return rec.get("owner")


class LeaseElection:
    """One candidate's view of the race: step() tries to acquire/renew;
    start() runs the loop at lease/3 with an `on_win` callback fired on
    the pending→leader transition (at-most-once per tenure)."""

    def __init__(self, lease: LeaseFile, candidate_id: str,
                 lease_s: float = 2.0, on_win=None):
        from ydb_tpu.utils.metrics import GLOBAL
        self.lease = lease if isinstance(lease, LeaseFile) \
            else LeaseFile(lease)
        self.candidate_id = candidate_id
        self.lease_s = float(lease_s)
        self.on_win = on_win
        self.is_leader = False
        self.counters = GLOBAL
        self._stop = threading.Event()
        self._thread = None

    def step(self) -> bool:
        won = self.lease.try_acquire(self.candidate_id, self.lease_s)
        if won and not self.is_leader:
            self.counters.inc("hive/elections_won")
            if self.on_win is not None:
                self.on_win()
        elif not won and self.is_leader:
            # lost the lease (a renewal missed a whole interval): a
            # fenced ex-leader must stop acting, loudly
            self.counters.inc("hive/leadership_lost")
        self.is_leader = won
        return won

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.step()
                except Exception:            # noqa: BLE001 — keep racing
                    pass
                self._stop.wait(max(0.05, self.lease_s / 3.0))

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"elect-{self.candidate_id}")
        self._thread.start()

    def stop(self, release: bool = False) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if release and self.is_leader:
            self.lease.release(self.candidate_id)
            self.is_leader = False


def promote_when_elected(standby_root: str, lease_path: str,
                         candidate_id: str, lease_s: float = 2.0,
                         timeout_s: float = 30.0, clock=time.time,
                         **engine_kwargs):
    """Election-driven standby promote: block until this candidate wins
    the lease (or `timeout_s` passes — another candidate is the live
    leader), then boot the engine from the standby root through ordinary
    crash recovery. Returns (engine, election) — the election keeps
    renewing in the background as the leader's fence; losers get
    (None, election)."""
    from ydb_tpu.query import QueryEngine
    election = LeaseElection(LeaseFile(lease_path, clock=clock),
                             candidate_id, lease_s=lease_s)
    deadline = time.monotonic() + timeout_s
    while not election.step():
        if time.monotonic() > deadline:
            return None, election
        time.sleep(max(0.05, lease_s / 3.0))
    # start renewing BEFORE the boot: crash recovery of a large image
    # can outlast lease_s, and a lapsed lease mid-boot would let a
    # second candidate win and boot the same root (split-brain)
    election.start()                # keep renewing: leadership fence
    try:
        engine = QueryEngine(data_dir=standby_root, **engine_kwargs)
    except BaseException:
        election.stop(release=True)  # failed boot must not hold the
        raise                        # lease against other candidates
    from ydb_tpu.utils.metrics import GLOBAL
    GLOBAL.inc("hive/standby_promotions")
    return engine, election
