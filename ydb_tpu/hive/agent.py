"""Worker-side heartbeat agent — the push half of the lease protocol.

Runs on every worker process: registers with the Hive host's
HiveRegister RPC (retrying until the Hive is up — boot order must not
matter), then renews at lease/3 via HiveHeartbeat, carrying the
worker's load signal (mean DQ task wall from its own stage stats). A
`{register: true}` reply means the Hive restarted and lost volatile
membership — the agent re-registers and carries on. Loss of the Hive
endpoint is survivable noise: the agent keeps retrying, and the worker
keeps serving whatever traffic still reaches it.
"""

from __future__ import annotations

import threading


class HeartbeatAgent:
    def __init__(self, hive_endpoint: str, node_id: str, endpoint: str,
                 shards=(), capacity: float = 1.0, engine=None,
                 token: str = "", interval_s: float = None):
        self.hive_endpoint = hive_endpoint
        self.node_id = node_id
        self.endpoint = endpoint
        self.shards = list(shards)
        self.capacity = float(capacity)
        self.engine = engine             # load signal source (optional)
        self.token = token
        self.interval_s = interval_s     # None: lease/3 from register
        self._stop = threading.Event()
        self._thread = None
        self.registered = False

    def _client(self):
        from ydb_tpu.server import Client
        return Client(self.hive_endpoint, token=self.token)

    def _load(self):
        if self.engine is None:
            return None
        from ydb_tpu.hive.placement import stage_load_signal
        sig = stage_load_signal(self.engine)
        if sig:
            # a worker only knows its own wall; any recorded key is it
            return next(iter(sig.values()))
        # workers don't run the router-side DqTaskRunner, so their
        # dq_stage_stats ring stays empty — but every DQ stage program
        # executes through engine.execute, which feeds the process-wide
        # statement-latency histogram: its mean IS this worker's wall
        from ydb_tpu.utils.metrics import GLOBAL_HIST
        h = GLOBAL_HIST.get("query/latency_ms")
        if h is not None and h.count:
            return h.sum / h.count
        return None

    def _loop(self) -> None:
        client = None
        interval = self.interval_s or 1.0
        while not self._stop.is_set():
            try:
                if client is None:
                    client = self._client()
                if not self.registered:
                    resp = client.hive_register(
                        endpoint=self.endpoint, node_id=self.node_id,
                        capacity=self.capacity, shards=self.shards)
                    self.registered = True
                    if self.interval_s is None:
                        interval = max(0.2,
                                       float(resp.get("lease_s", 3.0))
                                       / 3.0)
                else:
                    resp = client.hive_heartbeat(self.node_id,
                                                 load=self._load())
                    if resp.get("register"):
                        self.registered = False
                        continue            # re-register immediately
            except Exception:                # noqa: BLE001 — hive may be
                client = None                # down/restarting; keep going
                self.registered = False
            self._stop.wait(interval)

    def start(self) -> "HeartbeatAgent":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"hive-agent-{self.node_id}")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
