"""Shard adoption — boot a dead worker's shard image on a survivor.

Every worker mirrors its durable store synchronously to a standby image
(`cluster/replica.py`: an ack'd commit is on both sides), so a dead
worker's rows are fully present in its mirror directory. Re-placement
replays that image into the adopting worker's OWN tables: boot a
QueryEngine from the image root (ordinary crash recovery — the standby
IS a crash image), read each sharded table, and commit the rows into
the survivor's catalog under a fresh plan step. After the replay the
survivor's local scan covers both its original shard and the adopted
one, so re-lowered DQ stage programs need no shard awareness at all.

The copy reserves the engine's memory admission for each table's
working set — an adoption racing live traffic queues like any big
query instead of blowing the device budget (kqp_rm_service stance).
"""

from __future__ import annotations

import os

import numpy as np


def adopt_shard(engine, image_root: str, tables=None) -> dict:
    """Replay the shard image at `image_root` into `engine`'s tables.
    `tables`: the sharded table names to absorb (replicated tables are
    already everywhere — copying them would double-count). Returns
    {table: rows_copied}."""
    from ydb_tpu.core.block import HostBlock
    from ydb_tpu.query import QueryEngine
    from ydb_tpu.utils.metrics import GLOBAL

    img = QueryEngine(block_rows=1 << 12, data_dir=image_root)
    copied: dict = {}
    # idempotency guard: tables commit one-by-one, so a partial failure
    # (or an RPC retry after a lost reply) re-enters here — tables that
    # already landed must NOT replay again (silent row duplication).
    # Per-process scope matches the retry path (the Hive re-asks the
    # same worker process); a survivor that crashes MID-adoption keeps
    # its partial rows durably and must be re-imaged, not re-adopted.
    done = engine.__dict__.setdefault("_hive_adopted", set())
    root_key = os.path.realpath(image_root)
    for name in tables or sorted(img.catalog.tables):
        if not img.catalog.has(name) or not engine.catalog.has(name):
            continue
        if (root_key, name) in done:
            copied[name] = 0
            continue
        df = img.query(f"select * from {name}")
        if not len(df):
            copied[name] = 0
            continue
        t = engine.catalog.table(name)
        enc = {}
        valids = {}
        est = 0
        for c in t.schema:
            a = df[c.name].to_numpy()
            if c.dtype.is_string:
                # encode under the DEST table's dictionaries — the image
                # engine's codes mean nothing here
                enc[c.name] = t.dictionaries[c.name].encode_bulk(
                    np.asarray(a, dtype=object))
            else:
                if a.dtype == object:
                    # nullable column decoded to objects: None → NaN/0
                    # with an explicit validity mask
                    valid = np.array([v is not None for v in a])
                    fill = np.where(valid, a, 0)
                    enc[c.name] = np.asarray(fill.tolist(),
                                             dtype=c.dtype.np)
                    valids[c.name] = valid
                else:
                    enc[c.name] = np.asarray(a, dtype=c.dtype.np)
            est += int(getattr(enc[c.name], "nbytes", 0))
        block = HostBlock.from_arrays(t.schema, enc,
                                      valids=valids or None,
                                      dictionaries=dict(t.dictionaries))
        # admission: the replay's upload/scan growth competes with live
        # queries — reserve like any statement would
        with engine.admission.admit(est):
            writes = t.write(block)
            t.commit(writes, engine._next_version())
            t.indexate()
        done.add((root_key, name))
        copied[name] = len(df)
        GLOBAL.inc("hive/adopted_rows", len(df))
    GLOBAL.inc("hive/shards_adopted")
    return copied
