"""Shard placement — the deterministic, capacity- and load-aware balancer.

The reference Hive owns the tablet→node map and rebalances it with a
scored boot-queue (`hive_impl.h` TBootQueue; `tablet_move_info.h`
usage-weighted moves). Here the map is shard→node, and the balancer is a
pure DETERMINISTIC function of (current map, shard set, alive nodes,
load signal): every router candidate computes the identical map from the
same inputs, so placement needs no consensus round — the election
(`hive/election.py`) picks who gets to ACT on it.

Load signal: PR 7's per-stage wall stats (`engine.dq_stage_stats`, the
`.sys/dq_stage_stats` ring filled by the DQ runner) aggregated per
worker — a worker whose tasks run long is loaded, whatever the cause
(bigger shard, slower host, noisy neighbor).

Stability discipline: shards stay where they are while their node is
alive (moving a shard means replaying its image — never free); leave
moves ONLY the dead node's shards; join moves nothing by default
(`move_on_join` opts in, for deployments whose adopt hook can re-image).
"""

from __future__ import annotations


def stage_load_signal(engine) -> dict:
    """Per-worker load from the DQ stage-stats ring: mean task exec_ms
    (the per-stage wall attribution PR 7 records). Empty dict until a
    distributed query has run."""
    totals: dict = {}
    counts: dict = {}
    for r in list(getattr(engine, "dq_stage_stats", []) or []):
        w = r.get("worker", "")
        if not w or w == "router":
            continue
        totals[w] = totals.get(w, 0.0) + float(r.get("exec_ms", 0.0))
        counts[w] = counts.get(w, 0) + 1
    return {w: totals[w] / counts[w] for w in totals}


def _score(node, assigned_load: dict) -> tuple:
    """Lower is better; deterministic tie-break on node_id."""
    cap = max(node.capacity, 1e-9)
    return (assigned_load.get(node.node_id, 0.0) / cap,
            (node.load or 0.0) / cap, node.node_id)


def rebalance(current: dict, shards, nodes: list,
              shard_load: dict = None, move_on_join: bool = False) -> dict:
    """Compute the new shard→node_id map.

    `current`: the existing map (may reference dead nodes); `shards`:
    every shard that must be placed; `nodes`: ALIVE candidate NodeInfos
    (stale rejoiners excluded by the caller); `shard_load`: optional
    per-shard weight (defaults 1.0). Deterministic: iteration orders are
    sorted, scores tie-break on node_id."""
    if not nodes:
        return {}
    by_id = {n.node_id: n for n in nodes}
    shard_load = shard_load or {}
    out: dict = {}
    assigned: dict = {}          # node_id -> placed load
    # 1. keep every shard whose owner is still alive (no free moves)
    for s in sorted(shards, key=str):
        owner = current.get(s)
        if owner in by_id:
            out[s] = owner
            assigned[owner] = assigned.get(owner, 0.0) \
                + shard_load.get(s, 1.0)
    # 2. orphans (dead/unknown owner) go to the best-scoring node —
    #    heaviest first so the greedy packing stays balanced
    orphans = sorted((s for s in shards if s not in out),
                     key=lambda s: (-shard_load.get(s, 1.0), str(s)))
    for s in orphans:
        best = min(nodes, key=lambda n: _score(n, assigned))
        out[s] = best.node_id
        assigned[best.node_id] = assigned.get(best.node_id, 0.0) \
            + shard_load.get(s, 1.0)
    # 3. optional join leveling: drain the most-loaded node toward empty
    #    joiners until shard counts are within one of each other
    if move_on_join:
        while True:
            counts = {n.node_id: 0 for n in nodes}
            for nid in out.values():
                counts[nid] += 1
            hi = max(counts, key=lambda k: (counts[k], k))
            lo = min(counts, key=lambda k: (counts[k], k))
            if counts[hi] - counts[lo] <= 1:
                break
            moved = min((s for s, nid in out.items() if nid == hi),
                        key=str)
            out[moved] = lo
    return out


class PlacementMap:
    """The versioned shard→node map (epoch bumps on every change, so
    lowered graphs and routers can detect a stale topology)."""

    def __init__(self):
        self.assign: dict = {}      # shard id -> node_id
        self.epoch = 0

    def apply(self, new: dict) -> list:
        """Install a computed map; returns the moves [(shard, old_node,
        new_node)] (old_node None for first placement)."""
        moves = [(s, self.assign.get(s), nid) for s, nid in new.items()
                 if self.assign.get(s) != nid]
        dropped = [s for s in self.assign if s not in new]
        if moves or dropped:
            self.assign = dict(new)
            self.epoch += 1
        return moves

    def shards_of(self, node_id: str) -> list:
        return sorted((s for s, nid in self.assign.items()
                       if nid == node_id), key=str)
