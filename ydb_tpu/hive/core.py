"""The Hive — cluster control plane: membership × placement × failover.

The analog of the reference's Hive tablet (`hive_impl.h`): it owns which
worker serves which shard, notices workers dying (lease expiry or an
observed transport error), and re-places the dead worker's shards onto
survivors. Re-placement is DATA movement here: every worker mirrors its
durable store synchronously to a standby image (`cluster/replica.py`),
so "move shard S to node V" = "replay S's image into V's tables" — the
`adopt` hook, typically `hive/adopt.py:adopt_shard` over the mirror
root (in-process) or the worker's HiveAdoptShard RPC (OS cluster).

The router consults `query_endpoints()` instead of a static endpoint
list (`cluster/router.py`), and the DQ lowering reads the same placement
through `DqTopology.from_hive` (`dq/lower.py`) — a graph is lowered
against an epoch, and a failed run re-lowers against the next one.
"""

from __future__ import annotations

import threading
import time

from ydb_tpu.hive.membership import ALIVE, HiveMembership
from ydb_tpu.hive.placement import PlacementMap, rebalance


class HiveError(Exception):
    pass


class Hive:
    def __init__(self, lease_s: float = 3.0, clock=time.monotonic,
                 adopt=None, counters=None, move_on_join: bool = False):
        """`adopt(shard_id, node: NodeInfo, old_node: NodeInfo|None) ->
        None`: make `node` serve `shard_id`'s rows by replaying the
        image of `old_node` — the owner AT DEATH, whose standby mirror
        is where the shard's rows (original or previously adopted)
        actually live. A raising hook REVERTS the move — a shard the
        survivor did not actually absorb must stay visibly orphaned
        (queries fail loudly) rather than silently losing its rows from
        every result."""
        from ydb_tpu.utils.metrics import GLOBAL
        self.membership = HiveMembership(lease_s=lease_s, clock=clock,
                                         counters=counters)
        self.placement = PlacementMap()
        self.adopt = adopt
        self.counters = counters or GLOBAL
        self.move_on_join = move_on_join
        self._mu = threading.Lock()          # placement transitions
        self._adopting: set = set()          # guarded-by: _mu
        # failed replays back off before the sweep retries them — a
        # persistently failing adopt hook must not re-run its
        # seconds-long image replay inline in EVERY query's sweep
        self.adopt_retry_s = max(2.0, float(lease_s))
        # shard -> earliest retry (read by the planning step under _mu,
        # so writes hold it too — concurrent sweep + fail_workers both
        # run _replace)
        self._adopt_backoff: dict = {}       # guarded-by: _mu
        self._pulse_thread = None
        self._pulse_stop = threading.Event()

    # -- worker lifecycle ---------------------------------------------------

    def register_worker(self, endpoint: str, node_id: str = "",
                        capacity: float = 1.0, shards=()) -> dict:
        """Register a worker and claim its declared shards (first claim
        wins; a re-placed shard is NOT handed back to a rejoiner — its
        rows now live on the adopter)."""
        resp = self.membership.register(endpoint, node_id=node_id,
                                        capacity=capacity, shards=shards)
        nid = resp["node_id"]
        with self._mu:
            changed = False
            for s in shards:
                if s not in self.placement.assign:
                    self.placement.assign[s] = nid
                    changed = True
            if changed:
                self.placement.epoch += 1
            self._sync_node_shards_locked()
        self.counters.set("hive/placement_epoch", self.placement.epoch)
        resp["shards"] = self.placement.shards_of(nid)
        return resp

    def heartbeat(self, node_id: str, load: float = None) -> dict:
        return self.membership.heartbeat(node_id, load=load)

    # -- liveness / failover ------------------------------------------------

    def sweep(self) -> list:
        """Lease-expiry pass: newly dead workers lose their shards to
        survivors (the failover path nothing has to trigger — a worker
        that silently wedges is re-placed within one lease)."""
        newly = self.membership.sweep()
        if newly or self._has_orphans():
            # orphans: shards whose adopt hook failed on a previous pass
            # stay pointed at their dead owner — every sweep retries them
            self._replace(newly)
        return newly

    def _has_orphans(self) -> bool:
        alive = {n.node_id for n in self.membership.alive()
                 if not n.stale}
        return any(nid not in alive
                   for nid in self.placement.assign.values())

    def fail_workers(self, endpoints) -> list:
        """Observed-dead fast path (the query saw a transport error):
        expire the lease NOW and re-place."""
        newly = self.membership.expire(endpoints)
        if newly:
            self._replace(newly)
        return newly

    def _replace(self, dead_nodes: list) -> list:
        """Move every dead node's shards onto surviving placement. The
        adopt hook runs OUTSIDE the lock (image replay takes seconds;
        heartbeats must keep landing), guarded by an in-flight set so
        concurrent sweeps never double-replay a shard."""
        candidates = [n for n in self.membership.alive() if not n.stale]
        now = self.membership.clock()
        with self._mu:
            shards = set(self.placement.assign)
            new = rebalance(self.placement.assign, shards, candidates,
                            move_on_join=self.move_on_join)
            live = {n.node_id for n in candidates}
            moves = [(s, self.placement.assign.get(s), nid)
                     for s, nid in new.items()
                     if self.placement.assign.get(s) != nid
                     and s not in self._adopting
                     and self._adopt_backoff.get(s, 0.0) <= now
                     # NEVER move a shard off a LIVE owner through the
                     # adoption path: mirrors are worker-granular and
                     # adoption never deletes from the source, so a
                     # leveling move would leave the rows counted on
                     # BOTH nodes (move_on_join leveling is advisory
                     # until shard-granular movement exists)
                     and self.placement.assign.get(s) not in live]
            # CO-LOCATE a dead owner's shards on one target: the shard
            # image is the OWNER's mirror (it holds every shard that
            # worker served, adopted ones included), so splitting its
            # shards across targets would replay overlapping images —
            # the same rows landing on two survivors
            target_of: dict = {}
            for (s, old, nid) in sorted(moves, key=lambda m: str(m[0])):
                target_of.setdefault(old, nid)
            planned = [(s, old, target_of[old]) for (s, old, _n) in moves]
            self._adopting.update(s for (s, _o, _n) in planned)
        done = []
        failed = []
        for (s, old, nid) in planned:
            node = self.membership.get(nid)
            try:
                if self.adopt is not None:
                    self.adopt(s, node,
                               self.membership.get(old)
                               if old is not None else None)
                done.append((s, old, nid))
                self.counters.inc("hive/shards_replaced")
            except Exception:                # noqa: BLE001 — keep orphan
                failed.append(s)
                self.counters.inc("hive/adopt_failed")
        retry_at = self.membership.clock() + self.adopt_retry_s
        with self._mu:
            # backoff updates under _mu: the planning step above reads
            # _adopt_backoff under the lock, and a concurrent _replace
            # (sweep vs fail_workers) must not interleave a torn view
            for (s, _old, nid) in done:
                self.placement.assign[s] = nid
                self._adopt_backoff.pop(s, None)
            for s in failed:
                self._adopt_backoff[s] = retry_at
            if done:
                self.placement.epoch += 1
            self._adopting.difference_update(
                s for (s, _o, _n) in planned)
            self._sync_node_shards_locked()
        self.counters.set("hive/placement_epoch", self.placement.epoch)
        return done

    def _sync_node_shards_locked(self) -> None:
        """Mirror the placement back onto NodeInfo.shards. `_locked`
        covers OUR lock (placement.assign is read under _mu); the
        NodeInfo mutation itself happens inside the membership under
        ITS lock (`sync_shards`) — rewriting peer-owned rows under the
        wrong lock is exactly what graftlint's locks pass flags."""
        owned: dict = {}
        for s, nid in self.placement.assign.items():
            owned.setdefault(nid, []).append(s)
        self.membership.sync_shards(owned)

    # -- router-facing views ------------------------------------------------

    @property
    def epoch(self) -> int:
        return self.placement.epoch

    def orphaned_shards(self) -> list:
        """Shards whose owner is not an alive, non-stale worker — their
        rows are unreachable until a re-placement succeeds. The lowering
        REFUSES to build a graph while any exist (a scan that silently
        drops a shard's rows is worse than an error)."""
        alive = {n.node_id for n in self.membership.alive()
                 if not n.stale}
        return sorted((s for s, nid in self.placement.assign.items()
                       if nid not in alive), key=str)

    def query_endpoints(self) -> list:
        """Endpoints a distributed query should task, in registration
        order: alive, non-stale workers owning at least one shard (a
        shard-less rejoiner still holds its OLD rows — tasking it would
        double-count them)."""
        return [n.endpoint for n in self.membership.alive()
                if not n.stale and n.shards]

    def rows(self) -> list:
        return self.membership.rows()

    # -- pull liveness (plain gRPC workers, no agent) -----------------------

    def pulse(self, ping) -> None:
        """One pull round: `ping(endpoint) -> bool`; responders get their
        lease renewed, non-responders expire naturally."""
        for n in self.membership.alive():
            ok = False
            try:
                ok = bool(ping(n.endpoint))
            except Exception:                # noqa: BLE001 — dead is dead
                ok = False
            if ok:
                self.membership.heartbeat(n.node_id)
        self.sweep()

    def start_pulse(self, ping, interval_s: float = None) -> None:
        """Background pull loop at lease/3 (stop with stop_pulse)."""
        if self._pulse_thread is not None:
            return
        interval = interval_s or max(0.2, self.membership.lease_s / 3.0)
        self._pulse_stop.clear()

        def loop():
            while not self._pulse_stop.wait(interval):
                try:
                    self.pulse(ping)
                except Exception:            # noqa: BLE001 — keep pulsing
                    pass

        self._pulse_thread = threading.Thread(target=loop, daemon=True,
                                              name="hive-pulse")
        self._pulse_thread.start()

    def stop_pulse(self) -> None:
        if self._pulse_thread is None:
            return
        self._pulse_stop.set()
        self._pulse_thread.join(timeout=10)
        self._pulse_thread = None
