"""shard_map across JAX API generations.

`jax.shard_map` (with `check_vma=`) only exists from jax 0.6; on 0.4.x
the same transform lives at `jax.experimental.shard_map.shard_map` and
the replication-check kwarg is spelled `check_rep`.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma)

else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
        return _exp_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma)
