"""Shared device-collective building blocks for every ICI exchange.

ONE implementation of the bucketize → segment → `lax.all_to_all` →
compact redistribution (and its broadcast sibling, all-gather +
compact), consumed by three call sites:

  * `parallel/shuffle.py`      — distributed two-phase aggregation;
  * `parallel/shuffle_join.py` — probe-row exchange of the shuffle join;
  * `dq/ici.py`                — the DQ channel ICI data plane.

The formulation follows the portable-collective shuffle of arxiv
2112.01075 (memory-efficient redistribution as fixed-capacity segments
over one all_to_all) — everything static-shape, row counts ride along,
overflow detected on device.

Also here: the EQuARX-style block quantizer (arxiv 2506.17615) for
collective payloads — per-block scale + int8 codes, so an
aggregation-tolerant float column crosses the interconnect at ~1/8 the
bytes. NaN is preserved through a reserved code (-128, outside the
symmetric [-127, 127] quant range).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ydb_tpu.utils.hashing import hash_combine, splitmix64

AXIS = "shards"

# EQuARX block granularity: one float32 scale per QUANT_BLOCK int8 codes
# (overhead 4/QUANT_BLOCK bytes/row on top of the 1-byte code)
QUANT_BLOCK = 128
_NAN_CODE = -128                     # outside the symmetric quant range


def bucket_of(env, key_names, ndev):
    """Hash-partition bucket id per row (device-side, same hash family
    as host shard routing — `ydb_tpu/utils/hashing.py`)."""
    h = None
    for k in key_names:
        d, v = env[k]
        # value-truncating int64 coercion for all key dtypes (float keys
        # hash by truncated value — bitcast encodings are unavailable
        # under TPU x64 emulation)
        x = splitmix64(jnp, d.astype(jnp.int64))
        if v is not None:
            x = jnp.where(v, x, jnp.uint64(0))
        h = x if h is None else hash_combine(jnp, h, x)
    if h is None:
        return None
    return (h % jnp.uint64(ndev)).astype(jnp.int32)


def bucket_segments(env, bucket, length, cap, seg, ndev, names):
    """Build the per-target send segments of one device's rows.

    `env[name] = (data[cap], valid[cap]|None)`; `bucket[cap]` is the
    target device per row. Returns `(stacked_d, stacked_v, counts,
    overflow)` — per-column `[ndev, seg]` segment stacks, per-target row
    counts `[ndev]` (clamped to `seg`), and the overflow flag (any
    target bucket held more than `seg` rows — caller reruns with
    full-capacity segments, which cannot overflow)."""
    from ydb_tpu.ops.xla_exec import compress
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = iota < length
    seg_d = {n: [] for n in names}
    seg_v = {n: [] for n in names}
    counts = []
    overflow = jnp.bool_(False)
    for d_t in range(ndev):
        mask = active & (bucket == d_t)
        env_c, cnt = compress(env, length, mask, cap)
        overflow = overflow | (cnt > seg)
        counts.append(jnp.minimum(cnt, seg))
        for n in names:
            seg_d[n].append(env_c[n][0][:seg])
            v = env_c[n][1]
            seg_v[n].append(v[:seg] if v is not None
                            else jnp.ones((seg,), jnp.bool_))
    stacked_d = {n: jnp.stack(seg_d[n]) for n in names}        # (D, S)
    stacked_v = {n: jnp.stack(seg_v[n]) for n in names}
    return stacked_d, stacked_v, jnp.stack(counts), overflow


def exchange_segments(stacked_d, stacked_v, cnts, names, axis=AXIS):
    """The ICI hop: segment d of device s → device d segment s, for
    every column's data + valid stacks plus the row counts."""
    recv_d = {n: jax.lax.all_to_all(stacked_d[n], axis, 0, 0,
                                    tiled=False) for n in names}
    recv_v = {n: jax.lax.all_to_all(stacked_v[n], axis, 0, 0,
                                    tiled=False) for n in names}
    recv_c = jax.lax.all_to_all(cnts[:, None], axis, 0, 0,
                                tiled=False)[:, 0]              # (D,)
    return recv_d, recv_v, recv_c


def compact_segments(recv_d, recv_v, recv_c, seg, ndev, names):
    """Flatten the received `[ndev, seg]` segment stacks and compact the
    live rows to the front. Returns `(env, total)` over `[ndev * seg]`
    buffers."""
    from ydb_tpu.ops.xla_exec import compress
    flat = ndev * seg
    jrow = jnp.arange(seg, dtype=jnp.int32)
    seg_mask = (jrow[None, :] < recv_c[:, None]).reshape(-1)
    env = {n: (recv_d[n].reshape(-1), recv_v[n].reshape(-1))
           for n in names}
    return compress(env, jnp.int32(flat), seg_mask, flat)


def gather_all(stacked_d, stacked_v, cnts, seg, ndev, names, axis=AXIS):
    """Broadcast sibling of the shuffle: every device receives EVERY
    device's `[seg]` buffer (all-gather over ICI) and compacts the live
    rows. Inputs are per-device `[seg]` buffers (not per-target stacks).
    Returns `(env, total)` over `[ndev * seg]`."""
    from ydb_tpu.ops.xla_exec import compress
    recv_d = {n: jax.lax.all_gather(stacked_d[n], axis) for n in names}
    recv_v = {n: jax.lax.all_gather(stacked_v[n], axis) for n in names}
    recv_c = jax.lax.all_gather(cnts, axis)                     # (D,)
    flat = ndev * seg
    jrow = jnp.arange(seg, dtype=jnp.int32)
    seg_mask = (jrow[None, :] < recv_c[:, None]).reshape(-1)
    env = {n: (recv_d[n].reshape(-1), recv_v[n].reshape(-1))
           for n in names}
    return compress(env, jnp.int32(flat), seg_mask, flat)


# -- padding-waste accounting ----------------------------------------------
#
# Every exchange built from these blocks ships FIXED-capacity segments
# (the arxiv 2112.01075 static-shape stance), so the wire carries
# padded_rows = ndev² · seg rows regardless of how many are live. The
# account below is the shared host-side arithmetic the three call sites
# (dq/ici.py, parallel/shuffle.py, parallel/shuffle_join.py) report into
# the resource ledger — the measured form of the "~3.5× the live bytes"
# MULTICHIP_r06 waste ROADMAP item 1 exists to delete.


def segment_pad_account(kind: str, ndev: int, seg: int, live_rows: int,
                        bytes_per_row: float) -> dict:
    """Ledger + return the live-vs-padded account of one fixed-capacity
    segment exchange: `ndev²` segments of `seg` rows each on the wire,
    `live_rows` of them real."""
    from ydb_tpu.utils import memledger
    padded_rows = ndev * ndev * seg
    live_bytes = int(live_rows * bytes_per_row)
    padded_bytes = int(padded_rows * bytes_per_row)
    memledger.record_pad(kind, live_rows, padded_rows, live_bytes,
                         padded_bytes)
    return {"live_rows": live_rows, "padded_rows": padded_rows,
            "live_bytes": live_bytes, "padded_bytes": padded_bytes,
            "efficiency": round(live_bytes / padded_bytes, 3)
            if padded_bytes else None}


# -- EQuARX block quantization (collective payload codec) ------------------


def quantize_blocked(x, block=QUANT_BLOCK):
    """Per-block symmetric int8 quantization of a float array whose last
    axis is a multiple of `block`. Returns `(codes int8, scales
    float32)` with `scales.shape = x.shape[:-1] + (last // block,)`.
    NaN encodes as the reserved code -128 and survives the round trip;
    a block's scale comes from its NaN-masked max-abs."""
    shape = x.shape
    xb = x.reshape(shape[:-1] + (shape[-1] // block, block))
    finite = ~jnp.isnan(xb)
    mag = jnp.max(jnp.where(finite, jnp.abs(xb), 0.0), axis=-1)
    scale = jnp.where(mag > 0, mag / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(xb / scale[..., None]), -127, 127)
    q = jnp.where(finite, q, jnp.full_like(xb, _NAN_CODE))
    return q.astype(jnp.int8).reshape(shape), scale


def dequantize_blocked(codes, scales, dtype, block=QUANT_BLOCK):
    """Inverse of `quantize_blocked`: int8 codes + per-block scales →
    float array of `dtype` (reserved code -128 → NaN)."""
    shape = codes.shape
    qb = codes.reshape(shape[:-1] + (shape[-1] // block, block))
    x = qb.astype(dtype) * scales[..., None].astype(dtype)
    x = jnp.where(qb == _NAN_CODE, jnp.nan, x)
    return x.reshape(shape)
