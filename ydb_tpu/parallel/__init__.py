from ydb_tpu.parallel.shuffle import (  # noqa: F401
    DistributedAgg, make_mesh,
)
