"""Device-mesh hash shuffle + distributed two-phase aggregation.

The TPU-native replacement for DQ's hash-shuffle channels
(`DqCnHashShuffle`, partitioner `dq_output_consumer.cpp:99`, channel data
events `dq_compute_actor_channels.h:90`): instead of packing rows into
TEvChannelData and pushing them over Interconnect TCP, every stage-boundary
repartition is a single `jax.lax.all_to_all` across the pod's ICI mesh:

  per device:  partial GroupBy (BlockCombineHashed analog)
               → bucket rows by key hash  (TDqOutputHashPartitionConsumer)
               → build D fixed-capacity segments
  all_to_all:  segment d of device s  →  device d segment s     (ICI)
  per device:  compact received segments → final GroupBy
               (BlockMergeFinalizeHashed analog)

Group keys are disjoint across devices after the shuffle, so the final
merge is local and the host only concatenates per-device results.

Everything is static-shape: segments have a fixed per-edge capacity and
carry a row count. Overflow is detected on device (a bool reduced across
segments); `run` then rebuilds with full-capacity segments — which cannot
overflow — and reruns the batch, the analog of DQ channel spilling
(`dq/actors/spilling/channel_storage.cpp`).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.dtypes import DType, Kind
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import ir
from ydb_tpu.ops.device import bucket_capacity
from ydb_tpu.ops.xla_exec import _trace_program, compress, groupby_tuning
from ydb_tpu.parallel._compat import shard_map
from ydb_tpu.parallel.collective import (AXIS, bucket_of, bucket_segments,
                                         compact_segments,
                                         exchange_segments)

# back-compat alias: callers historically imported the bucketizer from
# here; the one implementation lives in parallel/collective.py now
_bucket_of = bucket_of


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (AXIS,))


@partial(jax.jit, static_argnames=("caps", "pcap", "names"))
def _fuse_device_blocks(blocks, caps, pcap, names):
    """Concat + compact a device's partial blocks into one [pcap] buffer
    (runs on the device that owns the blocks — committed inputs pin the
    execution there)."""
    datas = {n: [] for n in names}
    vals = {n: [] for n in names}
    masks = []
    total = 0
    for (arrays, valids, length), cap in zip(blocks, caps):
        iota = jnp.arange(cap, dtype=jnp.int32)
        masks.append(iota < length)
        total += cap
        for n in names:
            datas[n].append(arrays[n])
            v = valids.get(n)
            vals[n].append(v if v is not None
                           else jnp.ones((cap,), jnp.bool_))
    env = {n: (jnp.concatenate(datas[n]), jnp.concatenate(vals[n]))
           for n in names}
    mask = jnp.concatenate(masks)
    env, cnt = compress(env, jnp.int32(total), mask, total)
    out_d, out_v = {}, {}
    for n in names:
        d, v = env[n]
        if total < pcap:
            d = jnp.pad(d, (0, pcap - total))
            v = jnp.pad(v, (0, pcap - total))
        else:
            d, v = d[:pcap], v[:pcap]
        out_d[n], out_v[n] = d, v
    return out_d, out_v, cnt


@dataclass
class DistributedAgg:
    """Compiled distributed two-phase aggregation over a device mesh."""

    partial: ir.Program
    final: ir.Program
    in_schema: Schema
    mesh: Mesh
    seg_rows: int = 0        # per-edge segment capacity (0: = capacity)

    def __post_init__(self):
        # sig -> (shard_fn, out-schema holder): alternating signatures
        # (capacity buckets, valid sets, param sets) each keep their
        # compiled fn instead of thrashing a single slot
        self._fns: dict = {}

    # -- compile ----------------------------------------------------------

    def _build(self, cap: int, valid_names: tuple, param_names: tuple):
        ndev = self.mesh.devices.size
        in_cols = list(self.in_schema.columns)
        partial_prog, final_prog = self.partial, self.final

        gb = next(c for c in partial_prog.commands
                  if isinstance(c, ir.GroupBy))
        key_names = list(gb.keys)

        def per_device(arrays, valids, length, params):
            env = {}
            for c in in_cols:
                env[c.name] = (arrays[c.name][0], valids.get(c.name))
            env = {k: (d, v[0] if v is not None else None)
                   for k, (d, v) in env.items()}
            env, glen, sel, schema = _trace_program(
                partial_prog, in_cols, cap, env, length[0], params)
            assert sel is None  # partial ends in GroupBy
            names = list(schema.names)
            # the scatter group-by path shrinks the working capacity
            pcap = next(iter(env.values()))[0].shape[0] if env else cap
            seg = min(self.seg_rows or pcap, pcap)

            if not key_names or ndev == 1:
                # global agg: no shuffle, merge via all_gather
                datas = {n: jax.lax.all_gather(env[n][0], AXIS) for n in names}
                valid_g = {n: jax.lax.all_gather(
                    env[n][1] if env[n][1] is not None
                    else jnp.ones((pcap,), jnp.bool_), AXIS) for n in names}
                lens = jax.lax.all_gather(glen, AXIS)
                iota = jnp.arange(pcap, dtype=jnp.int32)
                seg_mask = (iota[None, :] < lens[:, None]).reshape(-1)
                env2 = {n: (datas[n].reshape(-1), valid_g[n].reshape(-1))
                        for n in names}
                env2, tot = compress(env2, jnp.int32(ndev * pcap), seg_mask,
                                     ndev * pcap)
                fenv, flen, fsel, fschema = _trace_program(
                    final_prog, list(schema.columns), ndev * pcap, env2, tot,
                    params)
                if fsel is not None:
                    fcap = next(iter(fenv.values()))[0].shape[0] if fenv \
                        else ndev * pcap
                    fenv, flen = compress(fenv, flen, fsel, fcap)
                # merged result is identical on every device — report once
                flen = jnp.where(jax.lax.axis_index(AXIS) == 0, flen, 0)
                out_d = {n: fenv[n][0] for n in fschema.names}
                out_v = {n: (fenv[n][1] if fenv[n][1] is not None
                             else jnp.ones_like(out_d[n], dtype=jnp.bool_))
                         for n in fschema.names}
                return out_d, out_v, flen, jnp.bool_(False), tuple(
                    (c.name, c.dtype.kind.value, c.dtype.nullable)
                    for c in fschema.columns)

            # hash shuffle: build ndev segments of seg rows each, swap
            # them over ICI, compact (shared with shuffle_join + the DQ
            # ICI channel plane — parallel/collective.py)
            bucket = bucket_of(env, key_names, ndev)
            stacked_d, stacked_v, cnts, overflow = bucket_segments(
                env, bucket, glen, pcap, seg, ndev, names)
            recv_d, recv_v, recv_c = exchange_segments(
                stacked_d, stacked_v, cnts, names)
            flat = ndev * seg
            env2, tot = compact_segments(recv_d, recv_v, recv_c, seg,
                                         ndev, names)
            fenv, flen, fsel, fschema = _trace_program(
                final_prog, list(schema.columns), flat, env2, tot, params)
            if fsel is not None:
                fcap = next(iter(fenv.values()))[0].shape[0] if fenv else flat
                fenv, flen = compress(fenv, flen, fsel, fcap)
            out_d = {n: fenv[n][0] for n in fschema.names}
            out_v = {n: (fenv[n][1] if fenv[n][1] is not None
                         else jnp.ones_like(out_d[n], dtype=jnp.bool_))
                     for n in fschema.names}
            return out_d, out_v, flen, overflow, tuple(
                (c.name, c.dtype.kind.value, c.dtype.nullable)
                for c in fschema.columns)

        out_schema_holder = {}

        def wrapper(arrays, valids, lengths, params):
            out_d, out_v, flen, overflow, out_sig = per_device(
                arrays, valids, lengths, params)
            out_schema_holder["sig"] = out_sig
            return (
                {n: x[None] for n, x in out_d.items()},
                {n: x[None] for n, x in out_v.items()},
                flen[None],
                overflow[None],
            )

        pspec_in = (
            {c.name: P(AXIS, None) for c in in_cols},
            {n: P(AXIS, None) for n in valid_names},
            P(AXIS),
            {n: P() for n in param_names},
        )
        shard_fn = jax.jit(shard_map(
            wrapper, mesh=self.mesh, in_specs=pspec_in,
            out_specs=(P(AXIS, None), P(AXIS, None), P(AXIS), P(AXIS)),
            check_vma=False,
        ))
        return shard_fn, out_schema_holder

    # -- run ---------------------------------------------------------------

    def run(self, blocks_per_device: list, params: Optional[dict] = None
            ) -> HostBlock:
        """blocks_per_device: one HostBlock per mesh device (row partition)."""
        ndev = self.mesh.devices.size
        assert len(blocks_per_device) == ndev
        params = params or {}
        cap = bucket_capacity(max(max(b.length for b in blocks_per_device), 1))
        arrays, valids, lengths = {}, {}, []
        valid_names = []
        for c in self.in_schema:
            stk, vstk, any_valid = [], [], False
            for b in blocks_per_device:
                cd = b.columns[c.name]
                pad = cap - b.length
                stk.append(np.pad(cd.data, (0, pad)))
                if cd.valid is not None:
                    any_valid = True
                    vstk.append(np.pad(cd.valid, (0, pad)))
                else:
                    vstk.append(np.ones(cap, np.bool_))
            arrays[c.name] = np.stack(stk)
            if any_valid:
                valids[c.name] = np.stack(vstk)
                valid_names.append(c.name)
        lengths = np.array([b.length for b in blocks_per_device],
                           dtype=np.int32)

        # groupby_tuning is part of the identity: _build traces the
        # partial/final GroupBy under the env knobs live at trace time,
        # and this instance can outlive a knob flip (tests construct
        # DistributedAgg directly; the executor's outer cache already
        # keys on the tuning, this inner cache must agree)
        sig = (cap, tuple(sorted(valid_names)), tuple(sorted(params)),
               self.seg_rows, groupby_tuning())
        entry = self._fns.get(sig)
        if entry is None:
            entry = self._build(cap, tuple(sorted(valid_names)),
                                tuple(sorted(params)))
            self._fns[sig] = entry
        fn, holder = entry

        dev_params = {k: jnp.asarray(v) for k, v in params.items()}
        out_d, out_v, flens, overflow = fn(arrays, valids, lengths,
                                           dev_params)
        # ONE batched device_get for the overflow verdict (was a
        # per-flag np.asarray sync — a baselined host-sync debt)
        if jax.device_get(overflow).any():
            # overflowed rows were clamped on device, so that result is
            # partial — discard it, rebuild with full-capacity segments
            # (seg = pcap ≥ any per-bucket count: cannot overflow) and rerun
            assert self.seg_rows, "full-capacity segments cannot overflow"
            self.seg_rows = 0
            return self.run(blocks_per_device, params)
        self._holder = holder
        # padding-waste account of the shuffle's fixed-capacity segments
        from ydb_tpu.parallel.collective import segment_pad_account
        segment_pad_account(
            "shuffle_segments", ndev, min(self.seg_rows or cap, cap),
            int(lengths.sum()),
            sum(a.dtype.itemsize for a in arrays.values())
            + len(valids))
        dicts = {}
        for b in blocks_per_device:
            for name, cd in b.columns.items():
                if cd.dictionary is not None:
                    dicts[name] = cd.dictionary
        return self._finish(out_d, out_v, flens, dicts)

    def run_device_blocks(self, per_dev_blocks: list,
                          params: Optional[dict] = None) -> HostBlock:
        """Distributed merge over ALREADY device-resident partials.

        ``per_dev_blocks[d]`` is a list of DeviceBlocks committed to mesh
        device d (the per-portion partial-aggregation outputs of the SQL
        executor). Each device fuses its partials locally (concat +
        compress, jit'd on that device), the fused buffers are assembled
        into one globally-sharded array — no host round-trip — and the
        shard-mapped shuffle+merge runs over it.
        """
        ndev = self.mesh.devices.size
        assert len(per_dev_blocks) == ndev
        assert all(blks for blks in per_dev_blocks), \
            "every device needs at least one (possibly empty) partial block"
        params = params or {}
        names = tuple(self.in_schema.names)
        total_caps = [sum(b.capacity for b in blks)
                      for blks in per_dev_blocks]
        pcap = bucket_capacity(max(total_caps), minimum=128)
        fused = []
        for blks in per_dev_blocks:
            blocks_in = tuple((b.arrays, b.valids, b.length) for b in blks)
            caps = tuple(b.capacity for b in blks)
            fused.append(_fuse_device_blocks(blocks_in, caps, pcap, names))

        sh2 = NamedSharding(self.mesh, P(AXIS, None))
        sh1 = NamedSharding(self.mesh, P(AXIS))
        arrays = {n: jax.make_array_from_single_device_arrays(
            (ndev, pcap), sh2, [fused[d][0][n][None] for d in range(ndev)])
            for n in names}
        valids = {n: jax.make_array_from_single_device_arrays(
            (ndev, pcap), sh2, [fused[d][1][n][None] for d in range(ndev)])
            for n in names}
        lengths = jax.make_array_from_single_device_arrays(
            (ndev,), sh1, [fused[d][2][None] for d in range(ndev)])

        sig = (pcap, tuple(sorted(names)), tuple(sorted(params)),
               self.seg_rows, groupby_tuning())
        entry = self._fns.get(sig)
        if entry is None:
            entry = self._build(pcap, tuple(sorted(names)),
                                tuple(sorted(params)))
            self._fns[sig] = entry
        fn, self._holder = entry
        dev_params = {k: jnp.asarray(v) for k, v in params.items()}
        out_d, out_v, flens, overflow = fn(arrays, valids, lengths,
                                           dev_params)
        # seg_rows here is 0 (full capacity) or a PROVEN merge-GroupBy
        # bound (each producer's partial holds ≤ out_bound groups, so a
        # bound-bucket segment cannot overflow) — either way overflow is
        # impossible; keep the invariant checked LOUDLY (an understated
        # bound must crash, never silently clamp rows). Batched
        # device_get, not a per-flag np.asarray sync.
        assert not jax.device_get(overflow).any(), \
            "proven segment bound overflowed — bound source is wrong"
        # NO pad record here: the partials' live row counts are
        # device-resident scalars, and the ledger must never force a
        # sync to measure — the host-input `run` path carries the gauge
        dicts = {}
        for blks in per_dev_blocks:
            for b in blks:
                dicts.update(b.dictionaries)
        return self._finish(out_d, out_v, flens, dicts)

    def _finish(self, out_d, out_v, flens, dicts) -> HostBlock:
        """Per-device results → host concat (groups are disjoint)."""
        ndev = self.mesh.devices.size
        out_sig = self._holder["sig"]
        out_cols = [Column(n, DType(Kind(k), nullable))
                    for (n, k, nullable) in out_sig]
        schema = Schema(out_cols)
        # ONE batched device→host transfer for every (column, device) —
        # the to_host discipline (ops/device.py): each np.asarray on a
        # device array is its own blocking round trip, 2·cols·ndev of
        # them on a tunneled TPU before this was batched
        host_d, host_v, flens = jax.device_get(
            ({c.name: out_d[c.name] for c in out_cols},
             {c.name: out_v[c.name] for c in out_cols}, flens))
        from ydb_tpu.utils import memledger
        memledger.record_transfer(
            "parallel/shuffle.py::DistributedAgg._finish",
            memledger.deep_nbytes((host_d, host_v)))
        blocks = []
        for d in range(ndev):
            n = int(flens[d])
            cols = {}
            for c in out_cols:
                data = host_d[c.name][d][:n].astype(c.dtype.np)
                v = host_v[c.name][d][:n]
                cols[c.name] = ColumnData(
                    data, None if v.all() else v, dicts.get(c.name))
            blocks.append(HostBlock(schema, cols, n))
        return HostBlock.concat(blocks)
