"""Distributed shuffle join: partitioned build + probe-row exchange.

The reference's shuffle-join strategy (`dq_opt_join.cpp` EJoinAlgoType::
ShuffleJoin over `dq_tasks_graph.h:43` task stages): when a join's build
side is too large to broadcast to every node, BOTH sides hash-partition
by the join key — stage N builds its partition's hash table, stage N+1
routes each probe row to its key's owner over the interconnect.

TPU shape: the build is hash-partitioned host-side (splitmix64, the same
family as every other routing decision) with partition d committed to
mesh device d — no device holds the full build. Probe rows arrive as the
per-device stage-A outputs; ONE `shard_map` program buckets them by key,
exchanges segments via `jax.lax.all_to_all` over ICI, compacts, probes
the LOCAL build partition with a vectorized searchsorted, and runs the
rest of the pipeline (post-join programs + partial aggregation) without
leaving the device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ydb_tpu.core.block import HostBlock
from ydb_tpu.core.dtypes import DType, Kind
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import ir
from ydb_tpu.ops.device import DeviceBlock, bucket_capacity
from ydb_tpu.ops.join import _select_and_gather, build as build_table
from ydb_tpu.ops.xla_exec import _trace_program, compress, groupby_tuning
from ydb_tpu.parallel._compat import shard_map
from ydb_tpu.parallel.collective import (AXIS, bucket_of, bucket_segments,
                                         compact_segments,
                                         exchange_segments)
from ydb_tpu.parallel.shuffle import _fuse_device_blocks
from ydb_tpu.utils.hashing import splitmix64


def partition_build(built: HostBlock, key: str, payload: list, ndev: int):
    """Hash-partition a build side into ndev per-device BuildTables plus
    the padded/stacked arrays a shard_map consumes. Returns
    (stacked arrays dict, payload schema, dictionaries, max row count)."""
    from ydb_tpu.ops.join import _host_key

    enc, valid = _host_key(built, key)
    if valid is not None:
        keep = np.nonzero(valid)[0]       # NULL keys never match
        built = built.take(keep)
        enc = enc[keep]
    h = splitmix64(np, enc.astype(np.int64))
    part = (h % np.uint64(ndev)).astype(np.int64)
    tables = []
    for p in range(ndev):
        idx = np.nonzero(part == p)[0]
        tables.append(build_table(built.take(idx), key, list(payload)))
    cap = max(t.keys_sorted.shape[0] for t in tables)
    keys = np.full((ndev, cap), np.iinfo(np.int64).max, np.int64)
    ns = np.zeros(ndev, np.int32)
    payload_np: dict = {n: None for n in payload}
    pvalid_np: dict = {}
    # ONE batched device→host landing for every partition's keys/payload
    # (was 2·cols·ndev per-array np.asarray round trips — a baselined
    # host-sync debt); a partition already host-side passes through
    fetched = jax.device_get(
        [{"keys": t.keys_sorted, "payload": dict(t.payload),
          "pvalid": dict(t.payload_valid)} for t in tables])
    for p, (t, host) in enumerate(zip(tables, fetched)):
        kcap = host["keys"].shape[0]
        keys[p, :kcap] = host["keys"]
        ns[p] = t.n
        for n in payload:
            arr = host["payload"][n]
            if payload_np[n] is None:
                payload_np[n] = np.zeros((ndev, cap), arr.dtype)
            payload_np[n][p, :len(arr)] = arr
            pv = host["pvalid"].get(n)
            if pv is not None:
                pvalid_np.setdefault(
                    n, np.zeros((ndev, cap), np.bool_))
                pvalid_np[n][p, :len(pv)] = pv
    dicts = dict(tables[0].dictionaries) if tables else {}
    from ydb_tpu.utils import memledger
    memledger.record_padded_buffers(
        "shuffle_join_build", "build", int(ns.sum()), ndev * cap,
        keys, payload_np, pvalid_np)
    return ({"keys": keys, "ns": ns, "payload": payload_np,
             "pvalid": pvalid_np},
            tables[0].schema if tables else Schema([]), dicts, cap)


class ShuffleJoin:
    """Compiled probe-row exchange + local probe + post-join pipeline."""

    def __init__(self, mesh, in_schema: Schema, probe_key: str, kind: str,
                 payload_cols: list, mark_col: str, not_in: bool,
                 rest_programs: list, partial):
        self.mesh = mesh
        self.in_schema = in_schema
        self.probe_key = probe_key
        self.kind = kind
        self.payload_cols = payload_cols       # [Column] appended by probe
        self.mark_col = mark_col
        self.not_in = not_in
        self.rest_programs = rest_programs     # [ir.Program] after the join
        self.partial = partial                 # ir.Program | None
        self._fns: dict = {}

    def _build(self, pcap: int, bcap: int, payload_names: tuple,
               pvalid_names: tuple, param_names: tuple):
        ndev = self.mesh.devices.size
        in_cols = list(self.in_schema.columns)
        names = [c.name for c in in_cols]
        probe_key, kind, not_in = self.probe_key, self.kind, self.not_in
        payload_cols = self.payload_cols
        mark_col = self.mark_col
        rest = list(self.rest_programs)
        partial = self.partial

        def per_device(arrays, valids, length, bkeys, bns, bpay, bpv,
                       params):
            env = {n: (arrays[n][0], valids[n][0]) for n in names}
            glen = length[0]
            # --- route probe rows to their key's owner (ICI all_to_all;
            # shared segment machinery — parallel/collective.py).
            # seg = pcap: full-capacity segments cannot overflow
            bucket = bucket_of(env, [probe_key], ndev)
            stacked_d, stacked_v, cnts, _ovf = bucket_segments(
                env, bucket, glen, pcap, pcap, ndev, names)
            recv_d, recv_v, recv_c = exchange_segments(
                stacked_d, stacked_v, cnts, names)
            flat = ndev * pcap
            env2, tot = compact_segments(recv_d, recv_v, recv_c, pcap,
                                         ndev, names)

            # --- probe the LOCAL build partition (vectorized binsearch)
            d, v = env2[probe_key]
            enc = d.astype(jnp.int64)
            iota2 = jnp.arange(flat, dtype=jnp.int32)
            act2 = iota2 < tot
            matchable = act2 if v is None else (act2 & v)
            keys_local = bkeys[0]
            n_local = bns[0]
            pos = jnp.searchsorted(keys_local, enc).astype(jnp.int32)
            safe = jnp.clip(pos, 0, bcap - 1)
            found = (keys_local[safe] == enc) & matchable \
                & (safe < n_local)
            payload_local = {n: bpay[n][0] for n in payload_names}
            pvalid_local = {n: bpv[n][0] for n in pvalid_names}
            out_sel, gathered, gathered_valid = _select_and_gather(
                found, safe, act2, v, n_local, kind, not_in,
                payload_local, pvalid_local, payload_names)

            schema = Schema(list(in_cols))
            for c in payload_cols:
                if c.name == mark_col:
                    env2[c.name] = (found, None)
                elif c.name in gathered:
                    env2[c.name] = (gathered[c.name],
                                    gathered_valid[c.name])
                schema = Schema([x for x in schema.columns
                                 if x.name != c.name] + [c])
            if kind != "mark":
                env2, tot = compress(env2, tot, out_sel, flat)

            # --- rest of the pipeline + partial, all on-device
            cap2 = flat
            sel = None
            for prog in rest:
                env2, tot, sel, schema = _trace_program(
                    prog, schema.columns, cap2, env2, tot, params, sel=sel)
                if env2:
                    cap2 = next(iter(env2.values()))[0].shape[0]
            if partial is not None:
                env2, tot, sel, schema = _trace_program(
                    partial, schema.columns, cap2, env2, tot, params,
                    sel=sel)
                if env2:
                    cap2 = next(iter(env2.values()))[0].shape[0]
            if sel is not None:
                env2, tot = compress(env2, tot, sel, cap2)
            out_d = {n: env2[n][0] for n in schema.names}
            out_v = {n: (env2[n][1] if env2[n][1] is not None
                         else jnp.ones_like(out_d[n], dtype=jnp.bool_))
                     for n in schema.names}
            return out_d, out_v, tot, tuple(
                (c.name, c.dtype.kind.value, c.dtype.nullable)
                for c in schema.columns)

        holder = {}

        def wrapper(arrays, valids, lengths, bkeys, bns, bpay, bpv, params):
            out_d, out_v, tot, sig = per_device(
                arrays, valids, lengths, bkeys, bns, bpay, bpv, params)
            holder["sig"] = sig
            return ({n: x[None] for n, x in out_d.items()},
                    {n: x[None] for n, x in out_v.items()}, tot[None])

        pspec_in = (
            {n: P(AXIS, None) for n in names},
            {n: P(AXIS, None) for n in names},
            P(AXIS),
            P(AXIS, None),
            P(AXIS),
            {n: P(AXIS, None) for n in payload_names},
            {n: P(AXIS, None) for n in pvalid_names},
            {n: P() for n in param_names},
        )
        fn = jax.jit(shard_map(
            wrapper, mesh=self.mesh, in_specs=pspec_in,
            out_specs=(P(AXIS, None), P(AXIS, None), P(AXIS)),
            check_vma=False))
        return fn, holder

    def run(self, per_dev_blocks: list, build_arrays: dict, bcap: int,
            params: dict, dicts: dict) -> list:
        """per_dev_blocks[d]: stage-A DeviceBlocks on device d. Returns one
        post-join (post-partial) DeviceBlock per device."""
        ndev = self.mesh.devices.size
        names = tuple(self.in_schema.names)
        total_caps = [sum(b.capacity for b in blks)
                      for blks in per_dev_blocks]
        pcap = bucket_capacity(max(total_caps), minimum=128)
        fused = []
        for blks in per_dev_blocks:
            blocks_in = tuple((b.arrays, b.valids, b.length) for b in blks)
            caps = tuple(b.capacity for b in blks)
            fused.append(_fuse_device_blocks(blocks_in, caps, pcap, names))
        sh2 = NamedSharding(self.mesh, P(AXIS, None))
        sh1 = NamedSharding(self.mesh, P(AXIS))
        arrays = {n: jax.make_array_from_single_device_arrays(
            (ndev, pcap), sh2, [fused[d][0][n][None] for d in range(ndev)])
            for n in names}
        valids = {n: jax.make_array_from_single_device_arrays(
            (ndev, pcap), sh2, [fused[d][1][n][None] for d in range(ndev)])
            for n in names}
        lengths = jax.make_array_from_single_device_arrays(
            (ndev,), sh1, [fused[d][2][None] for d in range(ndev)])

        bkeys = jax.device_put(build_arrays["keys"], sh2)
        bns = jax.device_put(build_arrays["ns"], sh1)
        bpay = {n: jax.device_put(a, sh2)
                for n, a in build_arrays["payload"].items()}
        bpv = {n: jax.device_put(a, sh2)
               for n, a in build_arrays["pvalid"].items()}

        payload_names = tuple(sorted(build_arrays["payload"]))
        pvalid_names = tuple(sorted(build_arrays["pvalid"]))
        # groupby_tuning: _build traces rest_programs/partial (GroupBy
        # lowerings read the tile/batch/legacy knobs) — same identity
        # rule as every other compiled-program cache key
        key = (pcap, bcap, payload_names, pvalid_names,
               tuple(sorted(params)), groupby_tuning())
        entry = self._fns.get(key)
        if entry is None:
            entry = self._build(pcap, bcap, payload_names, pvalid_names,
                                tuple(sorted(params)))
            self._fns[key] = entry
        fn, holder = entry
        dev_params = {k: jnp.asarray(v) for k, v in params.items()}
        out_d, out_v, lens = fn(arrays, valids, lengths, bkeys, bns, bpay,
                                bpv, dev_params)
        out_cols = [Column(n, DType(Kind(k), nullable))
                    for (n, k, nullable) in holder["sig"]]
        schema = Schema(out_cols)
        out_cap = next(iter(out_d.values())).shape[1] if out_d else 0
        blocks = []
        for d in range(ndev):
            arrays_d = {c.name: out_d[c.name].addressable_shards[d].data[0]
                        for c in out_cols}
            valids_d = {c.name: out_v[c.name].addressable_shards[d].data[0]
                        for c in out_cols}
            len_d = lens.addressable_shards[d].data[0]
            blocks.append(DeviceBlock(
                schema, arrays_d, valids_d, len_d, out_cap,
                {n: dc for n, dc in dicts.items() if schema.has(n)}))
        return blocks
