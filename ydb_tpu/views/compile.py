"""Fold-program compiler: a view's defining SELECT → op-IR programs.

A materialized view's maintenance loop is three ordinary `ops/ir`
programs (arxiv 2603.09555's compiler-first constant-cost-update stance:
compile the update rule once, apply it per delta):

  * **row program** — per delta row (a CDC old/new image carrying a
    ``__sign`` of -1/+1): group-key assigns, sign-weighted aggregate
    inputs (``sum(x)`` folds as ``sign * coalesce(x, 0)`` plus a
    non-null counter, so DELETE is subtraction), then the WHERE filter.
  * **partial program** — the segment-reduce of one delta batch:
    GroupBy over the key columns summing the weighted inputs. Chained
    device-to-device after the row program.
  * **merge program** — per-partition partial state stacked and
    re-grouped (sum of partial sums, min of partition minima) — the
    same partial/final shape the DQ distributed aggregate uses, run at
    read time.

All three are plain programs through `ops/xla_exec.run_on_device`, so
they ride the ProgramCache, persist in the progstore (a restarted
worker folds with ``compile_ms == 0``) and land roofline rows in
`.sys/compiled_programs` like any other program.

Supported shapes (v1, checked here — anything else raises
`UnsupportedView` and the DDL is refused): single row-store table
source; WHERE over non-string columns; GROUP BY over scalar
expressions (string columns as bare keys only — delta batches encode
them through a batch-local dictionary, so no table-dictionary LUT can
go stale); aggregates count(*)/count/sum/min/max/avg with min/max over
bare non-string columns (exact under DELETE via per-group value
multisets, `manager.py`); or the non-grouped filter/project case.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ydb_tpu.core import dtypes as dt
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import ir
from ydb_tpu.query import binder as B
from ydb_tpu.sql import ast


class UnsupportedView(ValueError):
    """Definition shape the incremental maintainer cannot fold."""


_AGGS = ("count", "sum", "min", "max", "avg")
_UNSIGNED = (dt.Kind.UINT8, dt.Kind.UINT16, dt.Kind.UINT32, dt.Kind.UINT64)
_SUMMABLE = (dt.Kind.INT8, dt.Kind.INT16, dt.Kind.INT32, dt.Kind.INT64,
             dt.Kind.UINT8, dt.Kind.UINT16, dt.Kind.UINT32, dt.Kind.UINT64,
             dt.Kind.FLOAT32, dt.Kind.FLOAT64)


@dataclass
class KeySpec:
    out: str                       # served output column label
    col: str                       # internal key column (__k<i>)
    dtype: dt.DType                # served dtype (STRING kind for strings)
    source_col: Optional[str] = None   # bare string key's source column


@dataclass
class AggSpec:
    func: str                      # count_all | count | sum | min | max | avg
    out: str                       # served output column label
    dtype: dt.DType                # served dtype (engine agg_result_dtype)
    n_col: Optional[str] = None    # partial: signed non-null counter
    s_col: Optional[str] = None    # partial: signed sum
    s_dtype: Optional[dt.DType] = None
    m_col: Optional[str] = None    # partial: per-partition extreme (min/max)
    arg_col: Optional[str] = None  # min/max: bare source column


@dataclass
class PlainItem:
    out: str
    dtype: dt.DType
    col: Optional[str] = None      # row-program output column (__v<j>)
    source_col: Optional[str] = None   # string passthrough source column


class ViewProgram:
    """Compiled maintenance plan for one view (see module docstring)."""

    def __init__(self, name: str, source: str, kind: str, sql: str):
        self.name = name
        self.source = source
        self.kind = kind               # "agg" | "plain"
        self.sql = sql
        self.delta_schema: Schema = None
        self.string_cols: tuple = ()
        self.row_program: ir.Program = None
        self.row_schema: Schema = None
        self.keys: list = []
        self.aggs: list = []
        self.items: list = []          # ("key", KeySpec) | ("agg", AggSpec)
        self.partial_cols: list = []   # [(name, DType)] summed in partials
        self.minmax: list = []         # AggSpecs maintained via multisets
        self.plain_items: list = []    # PlainItems (kind == "plain")
        self.out_schema: Schema = None
        self.planned_bound = 0         # planner-proven group bound (0: none)
        self._partials: dict = {}      # out_bound -> GroupBy program
        self._merges: dict = {}

    def partial_program(self, out_bound: int) -> ir.Program:
        """Delta-batch segment-reduce (chained after the row program).
        ``out_bound`` is the delta block capacity — sound (every group
        holds >= 1 surviving row) and aligned with the ProgramCache's
        capacity bucketing, so the bound costs no extra compiles."""
        p = self._partials.get(out_bound)
        if p is None:
            aggs = [ir.Agg("__rows", "sum", "__sign")]
            aggs += [ir.Agg(n, "sum", n) for (n, _d) in self.partial_cols]
            p = ir.Program().group_by([k.col for k in self.keys], aggs,
                                      out_bound=out_bound)
            self._partials[out_bound] = p
        return p

    def merge_program(self, out_bound: int) -> ir.Program:
        """Read-time merge over stacked per-partition partial state —
        the DQ partial/final aggregate shape."""
        p = self._merges.get(out_bound)
        if p is None:
            aggs = [ir.Agg("__rows", "sum", "__rows")]
            aggs += [ir.Agg(n, "sum", n) for (n, _d) in self.partial_cols]
            aggs += [ir.Agg(m.m_col, "min" if m.func == "min" else "max",
                            m.m_col) for m in self.minmax]
            p = ir.Program().group_by([k.col for k in self.keys], aggs,
                                      out_bound=out_bound)
            self._merges[out_bound] = p
        return p

    @property
    def partial_schema(self) -> Schema:
        """Per-partition partial state block (the merge program's input)."""
        cols = [Column(k.col, k.dtype) for k in self.keys]
        cols.append(Column("__rows", dt.DType(dt.Kind.INT64, False)))
        cols += [Column(n, d) for (n, d) in self.partial_cols]
        cols += [Column(m.m_col, m.dtype.with_nullable(True))
                 for m in self.minmax]
        return Schema(cols)


# -- shape checks ----------------------------------------------------------


def _walk_fields(e):
    if e is None or not hasattr(e, "__dataclass_fields__"):
        return
    yield e
    for f in e.__dataclass_fields__:
        v = getattr(e, f)
        for x in (v if isinstance(v, tuple) else (v,)):
            yield from _walk_fields(x)


def _reject_strings(e, scope: B.Scope, ctx: str) -> None:
    """String columns fold only as bare group keys: any other use would
    evaluate through table-dictionary LUTs, and delta batches carry
    batch-local codes — a silent mismatch. Refuse at CREATE instead."""
    for node in _walk_fields(e):
        if isinstance(node, (ast.InSubquery, ast.Exists, ast.ScalarSubquery,
                             ast.WindowFunc)):
            raise UnsupportedView(
                f"{ctx}: subqueries/window functions are not foldable")
        if isinstance(node, ast.FuncCall) and node.name in B.AGG_NAMES:
            raise UnsupportedView(
                f"{ctx}: aggregates must be top-level select items")
        if isinstance(node, ast.Name):
            b = scope.try_resolve(node.parts)
            if b is not None and b.dtype.is_string:
                raise UnsupportedView(
                    f"{ctx}: string column {'.'.join(node.parts)!r} is "
                    "only supported as a bare GROUP BY key")


def _check_shape(select: ast.Select) -> None:
    for attr, what in (("ctes", "WITH"), ("having", "HAVING"),
                       ("order_by", "ORDER BY"), ("limit", "LIMIT"),
                       ("offset", "OFFSET")):
        if getattr(select, attr):
            raise UnsupportedView(f"{what} is not supported in a "
                                  "materialized view definition")
    if select.distinct:
        raise UnsupportedView("DISTINCT is not supported in a "
                              "materialized view definition")
    if not isinstance(select.relation, ast.TableRef):
        raise UnsupportedView("materialized views fold a single source "
                              "table (no joins/subqueries yet)")
    for it in select.items:
        if isinstance(it.expr, ast.Star):
            raise UnsupportedView("SELECT * is not supported; name the "
                                  "view's columns")


def _label(it: ast.SelectItem, idx: int, used: set) -> str:
    if it.alias:
        base = it.alias
    elif isinstance(it.expr, ast.Name):
        base = it.expr.parts[-1]
    elif isinstance(it.expr, ast.FuncCall):
        base = it.expr.name
    else:
        base = f"col{idx}"
    lbl, k = base, 2
    while lbl in used:
        lbl, k = f"{base}_{k}", k + 1
    used.add(lbl)
    return lbl


# -- aggregate compilation -------------------------------------------------


def _bind_sum_input(e: ast.FuncCall, out: str, j: int, eb: B.ExprBinder,
                    scope: B.Scope, delta_schema: Schema,
                    prog: ir.Program, partial_cols: list) -> AggSpec:
    arg = e.args[0]
    _reject_strings(arg, scope, f"{e.name}()")
    ax = eb.bind(arg)
    adt = ir.infer_expr(ax, delta_schema)
    if adt.kind not in _SUMMABLE:
        raise UnsupportedView(f"{e.name}() over {adt!r} is not foldable")
    # partial sums are SIGNED (DELETE subtracts), so unsigned inputs
    # promote to int64 — finalize restores the engine's uint64 result
    sx = ir.call("cast", ax, to=dt.Kind.INT64.value) \
        if adt.kind in _UNSIGNED else ax
    s_dtype = dt.FLOAT64 if adt.is_float else dt.DType(dt.Kind.INT64, False)
    zero = ir.Const(0.0 if adt.is_float else 0, s_dtype.with_nullable(False))
    n_col, s_col = f"__n{j}", f"__s{j}"
    prog.assign(n_col, ir.call("if", ir.call("is_not_null", ax),
                               ir.Col("__sign"),
                               ir.Const(0, dt.DType(dt.Kind.INT64, False))))
    prog.assign(s_col, ir.call("mul", ir.Col("__sign"),
                               ir.call("coalesce", sx, zero)))
    partial_cols.append((n_col, dt.DType(dt.Kind.INT64, False)))
    partial_cols.append((s_col, s_dtype))
    if e.name == "avg":
        final = dt.FLOAT64
    else:
        final = ir.agg_result_dtype("sum", adt).with_nullable(True)
    return AggSpec(e.name, out, final, n_col=n_col, s_col=s_col,
                   s_dtype=s_dtype)


def _compile_agg(e: ast.FuncCall, out: str, j: int, eb: B.ExprBinder,
                 scope: B.Scope, delta_schema: Schema, prog: ir.Program,
                 partial_cols: list) -> AggSpec:
    if e.distinct:
        raise UnsupportedView("DISTINCT aggregates are not foldable")
    if e.name == "count" and (e.star or not e.args):
        return AggSpec("count_all", out, dt.DType(dt.Kind.UINT64, False))
    if not e.args:
        raise UnsupportedView(f"{e.name}() needs an argument")
    if e.name == "count":
        arg = e.args[0]
        if isinstance(arg, ast.Name) \
                and scope.resolve(arg.parts).dtype.is_string:
            ax = ir.Col(scope.resolve(arg.parts).internal)
        else:
            _reject_strings(arg, scope, "count()")
            ax = eb.bind(arg)
        n_col = f"__n{j}"
        prog.assign(n_col, ir.call(
            "if", ir.call("is_not_null", ax), ir.Col("__sign"),
            ir.Const(0, dt.DType(dt.Kind.INT64, False))))
        partial_cols.append((n_col, dt.DType(dt.Kind.INT64, False)))
        return AggSpec("count", out, dt.DType(dt.Kind.UINT64, False),
                       n_col=n_col)
    if e.name in ("sum", "avg"):
        return _bind_sum_input(e, out, j, eb, scope, delta_schema, prog,
                               partial_cols)
    # min/max: exact under DELETE needs the per-group value multiset
    # (manager.py) — restricted to bare non-string columns so the
    # multiset updates straight from the row images
    arg = e.args[0]
    if not isinstance(arg, ast.Name):
        raise UnsupportedView(f"{e.name}() folds bare columns only")
    b = scope.resolve(arg.parts)
    if b.dtype.is_string:
        raise UnsupportedView(f"{e.name}() over string columns is not "
                              "foldable")
    return AggSpec(e.name, out, b.dtype.with_nullable(True),
                   m_col=f"__m{j}", arg_col=b.internal)


# -- entry -----------------------------------------------------------------


def compile_view(name: str, select: ast.Select, table, sql: str,
                 planner=None) -> ViewProgram:
    """Compile the defining SELECT against the source table's schema.
    `planner` (optional) contributes the bounds-lattice group bound the
    manager uses to size state capacity (rebuild escape when exceeded)."""
    _check_shape(select)
    rel = select.relation
    src_alias = rel.alias or rel.name
    schema = table.schema

    scope = B.Scope()
    for c in schema:
        scope.add(src_alias, c.name, B.ColumnBinding(c.name, c.dtype))
    pool = B.ParamPool("vp")
    eb = B.ExprBinder(scope, pool)

    has_agg = bool(select.group_by) or any(
        isinstance(i.expr, ast.FuncCall) and i.expr.name in _AGGS
        for i in select.items)

    vp = ViewProgram(name, rel.name, "agg" if has_agg else "plain", sql)
    vp.string_cols = tuple(c.name for c in schema if c.dtype.is_string)
    # delta rows: every source column (strings as batch-local int64
    # codes), a -1/+1 sign, and the event-order index (plain views fold
    # per event in order; agg folds are order-free)
    dcols = [Column(c.name, dt.DType(
        dt.Kind.INT64 if c.dtype.is_string else c.dtype.kind, True))
        for c in schema]
    dcols += [Column("__sign", dt.DType(dt.Kind.INT64, False)),
              Column("__idx", dt.DType(dt.Kind.INT64, False))]
    vp.delta_schema = Schema(dcols)

    prog = ir.Program()
    used: set = set()

    if has_agg:
        key_exprs = []
        for i, g in enumerate(select.group_by):
            if isinstance(g, ast.Name):
                b = scope.resolve(g.parts)
                if b.dtype.is_string:
                    vp.keys.append(KeySpec(g.parts[-1], f"__k{i}",
                                           dt.DType(dt.Kind.STRING, True),
                                           source_col=b.internal))
                    key_exprs.append(ir.Col(b.internal))
                    continue
            _reject_strings(g, scope, "GROUP BY")
            kx = eb.bind(g)
            vp.keys.append(KeySpec(
                f"k{i}", f"__k{i}",
                ir.infer_expr(kx, vp.delta_schema).with_nullable(True)))
            key_exprs.append(kx)
        for ks, kx in zip(vp.keys, key_exprs):
            prog.assign(ks.col, kx)

        for idx, it in enumerate(select.items):
            e = it.expr
            if isinstance(e, ast.FuncCall) and e.name in _AGGS:
                spec = _compile_agg(e, _label(it, idx, used), len(vp.aggs),
                                    eb, scope, vp.delta_schema, prog,
                                    vp.partial_cols)
                vp.aggs.append(spec)
                if spec.m_col is not None:
                    vp.minmax.append(spec)
                vp.items.append(("agg", spec))
                continue
            ki = next((i for i, g in enumerate(select.group_by) if e == g),
                      None)
            if ki is None:
                raise UnsupportedView(
                    "select items must be group keys or aggregates")
            vp.keys[ki].out = _label(it, idx, used)
            vp.items.append(("key", vp.keys[ki]))
    else:
        j = 0
        for idx, it in enumerate(select.items):
            e = it.expr
            lbl = _label(it, idx, used)
            if isinstance(e, ast.Name):
                b = scope.resolve(e.parts)
                if b.dtype.is_string:
                    # identity passthrough: served straight from the row
                    # image, no device column needed
                    vp.plain_items.append(PlainItem(
                        lbl, b.dtype, source_col=b.internal))
                    continue
            _reject_strings(e, scope, "select item")
            vx = eb.bind(e)
            col = f"__v{j}"
            j += 1
            prog.assign(col, vx)
            vp.plain_items.append(PlainItem(
                lbl, ir.infer_expr(vx, vp.delta_schema), col=col))
        if select.where is not None:
            _reject_strings(select.where, scope, "WHERE")
        prog.assign("__keep",
                    eb.bind(select.where) if select.where is not None
                    else ir.Const(True, dt.DType(dt.Kind.BOOL, False)))

    if has_agg and select.where is not None:
        _reject_strings(select.where, scope, "WHERE")
        prog.filter(eb.bind(select.where))

    if pool.values:
        # a bound LUT/param snapshots table-dictionary codes at compile
        # time — stale against every future delta batch; not foldable
        raise UnsupportedView(
            "definition needs runtime parameters (string LUTs) — not "
            "foldable")

    if has_agg:
        proj = [k.col for k in vp.keys] + ["__sign"]
        proj += [n for (n, _d) in vp.partial_cols]
        proj += [c for c in dict.fromkeys(m.arg_col for m in vp.minmax)
                 if c not in proj]
        prog.project(proj)
    else:
        prog.project(["__idx", "__sign", "__keep"]
                     + [p.col for p in vp.plain_items if p.col])
    vp.row_program = prog
    vp.row_schema = ir.infer_schema(prog, vp.delta_schema)

    if has_agg:
        vp.out_schema = Schema([Column(sp.out, sp.dtype)
                                for (_t, sp) in vp.items])
    else:
        vp.out_schema = Schema([Column(p.out, p.dtype)
                                for p in vp.plain_items])

    if planner is not None and has_agg:
        # bounds lattice: the planner's proven group bound for this query
        # shape sizes state capacity — the manager counts a rebuild
        # (view/rebuilds) and re-derives it when state outgrows it
        try:
            plan = planner.plan_select(select)
            bounds = [getattr(p, "out_bound", 0)
                      for p in getattr(plan, "pipelines", ())]
            bounds.append(getattr(plan, "out_bound", 0))
            vp.planned_bound = max((b for b in bounds if b), default=0)
        except Exception:              # noqa: BLE001 — advisory only
            vp.planned_bound = 0
    return vp
