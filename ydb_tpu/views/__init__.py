"""Incremental materialized views (continuous queries over CDC).

The reference publishes committed DataShard mutations into topics
precisely so downstream consumers can maintain derived state without
re-scanning the source (`ydb/core/change_exchange/`). This package is
that consumer surface: `CREATE MATERIALIZED VIEW v AS SELECT ...`
registers a continuous query whose aggregate state is folded forward
from the source table's changefeed — a view update costs O(delta), a
view read costs O(state), never O(table).

  * `compile.py`  — the fold compiler: the defining SELECT becomes a
    row program (key/weighted-input assigns + WHERE filter), a partial
    GroupBy (the segment-reduce of one delta batch) and a merge GroupBy
    (per-partition partial state → served groups, the DQ partial/final
    merge shape), all plain `ops/ir` programs executed through
    `ops/xla_exec` — so they ride the ProgramCache, the progstore
    (restart folds with compile_ms == 0) and the roofline observatory
    like any other program.
  * `manager.py`  — the view registry + maintainer: consumes the CDC
    topic per partition, folds deltas into keyed aggregate state,
    mirrors state to the host store for restart, and serves reads at
    the view's high-watermark WriteVersion (a read at a snapshot the
    state has run ahead of falls back to the base query).
"""

from ydb_tpu.views.manager import MatView, ViewManager
from ydb_tpu.views.compile import UnsupportedView, compile_view

__all__ = ["MatView", "ViewManager", "UnsupportedView", "compile_view"]
