"""Materialized-view registry + maintainer.

Each `MatView` is a continuous query: a consumer of its source table's
changefeed topic that folds committed deltas into persistent aggregate
state. The reference's change exchange ships committed DataShard effects
to topics exactly so a consumer like this can maintain derived state
without rescanning the source (`ydb/core/change_exchange/`); the fold
itself is the compiled-program discipline of the serving spine — one
row program + one partial GroupBy per delta batch, one merge GroupBy
per read (the DQ partial/final aggregate shape across topic
partitions), all through `ops/xla_exec` so the programs persist in the
progstore and a restarted worker folds with ``compile_ms == 0``.

Cost model: a fold is O(delta) (delta batch → device → per-key partial
applied to a host/device-mirrored state dict), a read is O(state)
(stack per-partition partials → merge program → finalize), never
O(table). min/max stay exact under DELETE via per-group value
multisets (a decrement-able extreme needs the survivors, not just the
current extreme).

Serving contract: a read drains the topic first, then serves from
state iff the view's high-watermark plan_step is at or below the read
snapshot — CDC emission happens inside apply *before* publish, so
after a drain every commit visible to the snapshot is already folded.
A snapshot the state has run ahead of (or a degraded view) falls back
to the base query. State pairs atomically with consumed offsets in a
host mirror (`<root>/__views/<name>.json`), so restart resumes
exactly-once without replaying folded history.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Optional

import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.dictionary import Dictionary
from ydb_tpu.core import dtypes as dt
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops.device import bucket_capacity, to_device, to_host
from ydb_tpu.ops.xla_exec import run_on_device
from ydb_tpu.utils.metrics import GLOBAL, GLOBAL_HIST
from ydb_tpu.views.compile import UnsupportedView, compile_view

_READ_CHUNK = 4096
_REBUILD_CHUNK = 4096


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _NeedRebuild(Exception):
    """Raised mid-drain when incremental folding cannot continue."""

    def __init__(self, reason: str, degrade: bool = False):
        super().__init__(reason)
        self.reason = reason
        self.degrade = degrade


class _PartState:
    """Per-topic-partition fold state (mirrors the partition's pk
    ordering: every mutation of one row lands in one partition)."""

    __slots__ = ("offset", "groups", "mmaps", "rows")

    def __init__(self):
        self.offset = 0          # next topic offset to consume
        self.groups: dict = {}   # key tuple -> [rows, partial sums...]
        self.mmaps: dict = {}    # minmax idx -> {key tuple: {value: count}}
        self.rows: dict = {}     # plain views: pk tuple -> value tuple


def _item(v):
    return v.item() if hasattr(v, "item") else v


class MatView:
    def __init__(self, mgr: "ViewManager", name: str, vp, topic_name: str,
                 auto_topic: bool):
        self.mgr = mgr
        self.name = name
        self.vp = vp
        self.topic_name = topic_name
        self.auto_topic = auto_topic
        self.watermark = 0       # plan_step the state is exact at
        self.degraded = False    # permanent base-query fallback
        self.folds = 0
        self.rebuilds = 0
        self._mu = threading.RLock()
        self._serve: Optional[HostBlock] = None
        self.parts = [_PartState() for _ in self.topic.partitions]
        # escape threshold: the planner's proven group bound sizes the
        # state (with headroom — dictionary growth legitimately outgrows
        # a plan-time bound), the env cap backstops unbounded keys
        cap = _env_int("YDB_TPU_VIEW_MAX_GROUPS", 1 << 20)
        if vp.planned_bound:
            cap = min(cap, max(vp.planned_bound * 8, 4096))
        self.max_groups = cap

    @property
    def topic(self):
        return self.mgr.engine.topics[self.topic_name]

    # -- lag --------------------------------------------------------------

    def lag_messages(self) -> int:
        t = self.topic
        return sum(max(0, t.partitions[p].end_offset - self.parts[p].offset)
                   for p in range(len(self.parts)))

    def lag_versions(self) -> int:
        return max(0, self.mgr.engine.coordinator.last_plan_step
                   - self.watermark)

    def group_count(self) -> int:
        if self.vp.kind == "plain":
            return sum(len(p.rows) for p in self.parts)
        return sum(len(p.groups) for p in self.parts)

    def state_bytes(self) -> int:
        """Rough host-mirror footprint (vectors + multisets)."""
        if self.vp.kind == "plain":
            width = len(self.vp.plain_items) + 1
            return sum(len(p.rows) for p in self.parts) * width * 8
        width = 1 + len(self.vp.partial_cols)
        n = sum(len(p.groups) for p in self.parts) * width * 8
        n += sum(len(m) * 16 for p in self.parts
                 for mm in p.mmaps.values() for m in mm.values())
        return n

    # -- fold -------------------------------------------------------------

    def drain(self) -> None:
        """Consume every pending changefeed message into state. Caller
        holds `_mu`."""
        if self.degraded:
            return
        before = self.folds
        try:
            t = self.topic
            for p, part in enumerate(self.parts):
                while True:
                    recs = t.partitions[p].read(part.offset, _READ_CHUNK)
                    if not recs:
                        break
                    self._fold_batch(part, [r["data"] for r in recs])
                    part.offset += len(recs)
            if self.group_count() > self.max_groups:
                raise _NeedRebuild(
                    f"group count {self.group_count()} exceeds planned "
                    f"bound {self.max_groups}", degrade=True)
        except _NeedRebuild as nr:
            self._rebuild(nr.reason, degrade=nr.degrade)
        else:
            if self.folds != before:
                self.save_mirror()
        GLOBAL.set("view/lag_versions", self.lag_versions())

    def _fold_batch(self, part: _PartState, events: list) -> None:
        vp = self.vp
        t0 = time.perf_counter()
        rows = []       # (image dict, sign, event position, is_new)
        steps = 0
        for pos, d in enumerate(events):
            if d.get("table") != vp.source:
                continue
            if "old" not in d or "new" not in d:
                # pre-image-less legacy message: can't subtract — escape
                # to a full recompute (counted)
                raise _NeedRebuild("changefeed message without row images")
            if d["old"] is not None:
                rows.append((d["old"], -1, pos, False))
            if d["new"] is not None:
                rows.append((d["new"], +1, pos, True))
            steps = max(steps, int(d.get("plan_step", 0)))
        if rows:
            block, strdicts = self._delta_block(rows)
            cap = bucket_capacity(block.length)
            dev = to_device(block, cap)
            out = run_on_device(vp.row_program, dev)
            if vp.kind == "plain":
                self._apply_plain(part, to_host(out), rows)
            else:
                if vp.minmax:
                    self._apply_minmax(part, to_host(out), strdicts)
                pout = run_on_device(vp.partial_program(cap), out)
                self._apply_partials(part, to_host(pout), strdicts)
        self.watermark = max(self.watermark, steps)
        self.folds += 1
        self._serve = None
        ms = (time.perf_counter() - t0) * 1000.0
        GLOBAL.inc("view/applied_deltas", len(events))
        GLOBAL.inc("view/delta_rows", len(rows))
        GLOBAL.inc("view/fold_ms", ms)
        GLOBAL_HIST.observe("view/fold_ms", ms)

    def _delta_block(self, rows: list):
        """Delta rows → HostBlock of the view's delta schema. String
        columns encode through a batch-local dictionary (codes live only
        for this fold: state keys are decoded python values, so no
        table-dictionary LUT can go stale between batches)."""
        vp = self.vp
        n = len(rows)
        arrays, valids = {}, {}
        strdicts = {}
        src = self.mgr.engine.catalog.table(vp.source)
        for c in src.schema:
            vals = [img.get(c.name) for (img, _s, _p, _n) in rows]
            if c.name in vp.string_cols:
                dic = strdicts[c.name] = Dictionary()
                codes = dic.encode(vals).astype(np.int64)
                valid = codes >= 0
                arrays[c.name] = np.where(valid, codes, 0)
                valids[c.name] = valid
            else:
                valid = np.array([v is not None for v in vals], dtype=bool)
                np_dt = vp.delta_schema.dtype(c.name).np
                arrays[c.name] = np.array(
                    [0 if v is None else v for v in vals], dtype=np_dt)
                valids[c.name] = valid
        arrays["__sign"] = np.array([s for (_i, s, _p, _n) in rows],
                                    dtype=np.int64)
        arrays["__idx"] = np.arange(n, dtype=np.int64)
        return HostBlock.from_arrays(vp.delta_schema, arrays,
                                     valids), strdicts

    def _decode_key(self, host: HostBlock, i: int, strdicts: dict):
        out = []
        for ks in self.vp.keys:
            cd = host.columns[ks.col]
            if cd.valid is not None and not cd.valid[i]:
                out.append(None)
            elif ks.source_col is not None:
                out.append(strdicts[ks.source_col]._values[int(cd.data[i])])
            else:
                out.append(_item(cd.data[i]))
        return tuple(out)

    def _apply_partials(self, part: _PartState, phost: HostBlock,
                        strdicts: dict) -> None:
        vp = self.vp
        width = 1 + len(vp.partial_cols)
        cols = [phost.columns["__rows"]] \
            + [phost.columns[n] for (n, _d) in vp.partial_cols]
        for i in range(phost.length):
            key = self._decode_key(phost, i, strdicts)
            cur = part.groups.get(key)
            if cur is None:
                cur = part.groups[key] = [0] * width
            for j, cd in enumerate(cols):
                cur[j] += _item(cd.data[i])
            if cur[0] == 0:
                # all inserts cancelled by deletes: the group is gone
                # (integer row counts — exact, no float dust here)
                del part.groups[key]
                for mm in part.mmaps.values():
                    mm.pop(key, None)

    def _apply_minmax(self, part: _PartState, rhost: HostBlock,
                      strdicts: dict) -> None:
        """Maintain per-group value multisets from the surviving
        (post-WHERE) delta rows — min/max stay exact under DELETE."""
        signs = rhost.columns["__sign"].data
        for j, sp in enumerate(self.vp.minmax):
            cd = rhost.columns[sp.arg_col]
            mm = part.mmaps.setdefault(j, {})
            for i in range(rhost.length):
                if cd.valid is not None and not cd.valid[i]:
                    continue       # NULL args never enter min/max
                key = self._decode_key(rhost, i, strdicts)
                val = _item(cd.data[i])
                m = mm.get(key)
                if m is None:
                    m = mm[key] = {}
                c = m.get(val, 0) + int(signs[i])
                if c:
                    m[val] = c
                else:
                    m.pop(val, None)
                    if not m:
                        del mm[key]

    def _apply_plain(self, part: _PartState, rhost: HostBlock,
                     rows: list) -> None:
        """Fold filter/project deltas in event order: old image retires
        the pk, new image lands iff it passes WHERE."""
        vp = self.vp
        src = self.mgr.engine.catalog.table(vp.source)
        keep_cd = rhost.columns["__keep"]
        for i, (img, _sign, _pos, is_new) in enumerate(rows):
            pk = tuple(img.get(k) for k in src.key_columns)
            if not is_new:
                part.rows.pop(pk, None)
                continue
            keep = bool(keep_cd.data[i]) and (
                keep_cd.valid is None or bool(keep_cd.valid[i]))
            if not keep:
                part.rows.pop(pk, None)
                continue
            vals = []
            for p in vp.plain_items:
                if p.source_col is not None:
                    vals.append(img.get(p.source_col))
                else:
                    cd = rhost.columns[p.col]
                    vals.append(None if cd.valid is not None
                                and not cd.valid[i] else _item(cd.data[i]))
            part.rows[pk] = tuple(vals)

    # -- rebuild escape ----------------------------------------------------

    def _rebuild(self, reason: str, degrade: bool = False,
                 count: bool = True) -> None:
        """Counted full-recompute escape: drop state, reposition the
        consumer, refold from a table snapshot (synthetic insert events
        routed exactly like the changefeed routes, so later deltas land
        on the same partition state). Caller holds `_mu`."""
        if count:
            GLOBAL.inc("view/rebuilds")
            self.rebuilds += 1
        self._serve = None
        eng = self.mgr.engine
        for part in self.parts:
            part.groups.clear()
            part.mmaps.clear()
            part.rows.clear()
        if degrade:
            self.degraded = True
            self.save_mirror()
            return
        with eng.lock:
            # writes serialize under the engine lock: (snapshot, topic
            # positions, row iteration) observe one consistent point
            snap = eng.snapshot()
            t = self.topic
            for p, part in enumerate(self.parts):
                recs = t.partitions[p].records
                idx = len(recs)
                while idx > 0 and int(recs[idx - 1]["data"].get(
                        "plan_step", 0)) > snap.plan_step:
                    idx -= 1
                part.offset = idx
            src = eng.catalog.table(self.vp.source)
            buckets = [[] for _ in self.parts]
            for _pk, chain in src.rows.items():
                vals = src._visible(chain, snap)
                if vals is None:
                    continue
                row = src._decode_row(vals)
                key = tuple(row.get(k) for k in src.key_columns)
                p = zlib.crc32(str(str(key)).encode()) % len(self.parts)
                buckets[p].append(
                    {"table": self.vp.source, "op": "insert", "row": row,
                     "old": None, "new": row,
                     "plan_step": snap.plan_step, "tx_id": 0})
        for p, events in enumerate(buckets):
            for i in range(0, len(events), _REBUILD_CHUNK):
                self._fold_batch(self.parts[p],
                                 events[i:i + _REBUILD_CHUNK])
        self.watermark = max(self.watermark, snap.plan_step)
        self.save_mirror()

    # -- serving -----------------------------------------------------------

    def serve(self, snap):
        """(block, mode): the served state block, or (None, mode) when
        the read must fall back to the base query."""
        with self._mu:
            if not self.degraded:
                self.drain()
            if self.degraded:
                GLOBAL.inc("view/reads_fallback")
                return None, "degraded"
            if self.watermark > snap.plan_step:
                # the state ran ahead of this snapshot (older snapshot,
                # or an unpublished commit's deltas already folded)
                GLOBAL.inc("view/reads_fallback")
                return None, "fallback"
            if self._serve is None:
                self._serve = self._build_serve()
            GLOBAL.inc("view/reads_state")
            return self._serve, "state"

    def peek_mode(self, snap) -> str:
        """EXPLAIN's serving-mode probe — no fold, no state touch."""
        if self.degraded:
            return "degraded"
        return "state" if self.watermark <= snap.plan_step else "fallback"

    def _build_serve(self) -> HostBlock:
        if self.vp.kind == "plain":
            return self._serve_plain()
        merged = self._merged_host()
        return self._finalize(merged)

    def _merged_host(self) -> HostBlock:
        """Stack per-partition partial state and merge. Grouped views run
        the merge GroupBy on device (the DQ partial/final shape over
        topic partitions); the global-aggregate case is a single vector
        add per partition, merged host-side."""
        vp = self.vp
        schema = vp.partial_schema
        if not vp.keys:
            width = 1 + len(vp.partial_cols)
            tot = [0] * width
            for part in self.parts:
                vec = part.groups.get(())
                if vec:
                    for j in range(width):
                        tot[j] += vec[j]
            arrays, valids = {}, {}
            cols = [("__rows", tot[0])] + [
                (n, tot[1 + j]) for j, (n, _d) in enumerate(vp.partial_cols)]
            for cname, v in cols:
                arrays[cname] = np.array([v], dtype=schema.dtype(cname).np)
            for j, sp in enumerate(vp.minmax):
                vals = [min(m) if sp.func == "min" else max(m)
                        for part in self.parts
                        for m in [part.mmaps.get(j, {}).get(())] if m]
                ext = None if not vals else (
                    min(vals) if sp.func == "min" else max(vals))
                arrays[sp.m_col] = np.array(
                    [0 if ext is None else ext], dtype=sp.dtype.np)
                valids[sp.m_col] = np.array([ext is not None])
            return HostBlock.from_arrays(schema, arrays, valids)

        keys, vecs, owners = [], [], []
        for part in self.parts:
            for key, vec in part.groups.items():
                keys.append(key)
                vecs.append(vec)
                owners.append(part)
        n = len(keys)
        arrays, valids, dicts = {}, {}, {}
        for i, ks in enumerate(vp.keys):
            kv = [k[i] for k in keys]
            if ks.dtype.is_string:
                dic = dicts[ks.col] = Dictionary()
                codes = dic.encode(kv).astype(np.int32)
                if not dic._values:
                    dic.encode([""])    # decode target for clamped NULLs
                valid = codes >= 0
                arrays[ks.col] = np.where(valid, codes, 0).astype(np.int32)
                valids[ks.col] = valid
            else:
                valid = np.array([v is not None for v in kv], dtype=bool)
                arrays[ks.col] = np.array(
                    [0 if v is None else v for v in kv], dtype=ks.dtype.np)
                valids[ks.col] = valid
        arrays["__rows"] = np.array([v[0] for v in vecs], dtype=np.int64)
        for j, (cname, cdt) in enumerate(vp.partial_cols):
            arrays[cname] = np.array([v[1 + j] for v in vecs], dtype=cdt.np)
            if cdt.nullable:
                valids[cname] = np.ones(n, dtype=bool)
        for j, sp in enumerate(vp.minmax):
            exts = []
            for key, part in zip(keys, owners):
                m = part.mmaps.get(j, {}).get(key)
                exts.append(None if not m else
                            (min(m) if sp.func == "min" else max(m)))
            valid = np.array([e is not None for e in exts], dtype=bool)
            arrays[sp.m_col] = np.array([0 if e is None else e for e in exts],
                                        dtype=sp.dtype.np)
            valids[sp.m_col] = valid
        stacked = HostBlock.from_arrays(schema, arrays, valids, dicts)
        if n == 0:
            return stacked
        cap = bucket_capacity(n)
        out = run_on_device(self.vp.merge_program(cap),
                            to_device(stacked, cap))
        return to_host(out)

    def _finalize(self, m: HostBlock) -> HostBlock:
        """Merged partials → the served block, with the group-by
        engine's exact null/dtype rules (differential-tested)."""
        vp = self.vp
        n = m.length
        arrays, valids, dicts = {}, {}, {}
        for tag, sp in vp.items:
            if tag == "key":
                cd = m.columns[sp.col]
                arrays[sp.out] = cd.data
                if cd.valid is not None:
                    valids[sp.out] = cd.valid
                if cd.dictionary is not None:
                    dicts[sp.out] = cd.dictionary
                continue
            if sp.func == "count_all":
                arrays[sp.out] = m.columns["__rows"].data.astype(np.uint64)
            elif sp.func == "count":
                arrays[sp.out] = m.columns[sp.n_col].data.astype(np.uint64)
            elif sp.func in ("sum", "avg"):
                nn = m.columns[sp.n_col].data.astype(np.int64)
                s = m.columns[sp.s_col].data
                live = nn > 0
                if sp.func == "avg":
                    out = np.divide(s.astype(np.float64),
                                    np.maximum(nn, 1).astype(np.float64))
                else:
                    out = np.where(live, s, 0).astype(sp.dtype.np)
                arrays[sp.out] = out
                valids[sp.out] = live
            else:                      # min / max from merged extremes
                cd = m.columns[sp.m_col]
                arrays[sp.out] = cd.data.astype(sp.dtype.np)
                valids[sp.out] = (np.ones(n, dtype=bool)
                                  if cd.valid is None else cd.valid)
        return HostBlock.from_arrays(vp.out_schema, arrays, valids, dicts)

    def _serve_plain(self) -> HostBlock:
        vp = self.vp
        rows = [v for part in self.parts for v in part.rows.values()]
        n = len(rows)
        arrays, valids, dicts = {}, {}, {}
        for i, p in enumerate(vp.plain_items):
            vals = [r[i] for r in rows]
            if p.dtype.is_string:
                dic = dicts[p.out] = Dictionary()
                codes = dic.encode(vals).astype(np.int32)
                if not dic._values:
                    dic.encode([""])    # decode target for clamped NULLs
                valid = codes >= 0
                arrays[p.out] = np.where(valid, codes, 0).astype(np.int32)
                valids[p.out] = valid
            else:
                valid = np.array([v is not None for v in vals], dtype=bool)
                arrays[p.out] = np.array([0 if v is None else v
                                          for v in vals], dtype=p.dtype.np)
                valids[p.out] = valid
        return HostBlock.from_arrays(vp.out_schema, arrays, valids, dicts)

    # -- host mirror -------------------------------------------------------

    def _mirror_path(self) -> Optional[str]:
        store = self.mgr.engine.catalog.store
        if store is None:
            return None
        return os.path.join(store.root, "__views", f"{self.name}.json")

    def save_mirror(self) -> None:
        path = self._mirror_path()
        if path is None:
            return
        from ydb_tpu.storage.persist import _atomic_json
        os.makedirs(os.path.dirname(path), exist_ok=True)
        parts = []
        for part in self.parts:
            parts.append({
                "offset": part.offset,
                "groups": [[list(k), list(v)]
                           for k, v in part.groups.items()],
                "mmaps": {str(j): [[list(k), [[v, c] for v, c in m.items()]]
                                   for k, m in mm.items()]
                          for j, mm in part.mmaps.items()},
                "rows": [[list(k), list(v)] for k, v in part.rows.items()],
            })
        _atomic_json(path, {
            "watermark": self.watermark, "degraded": self.degraded,
            "folds": self.folds, "rebuilds": self.rebuilds, "parts": parts})

    def load_mirror(self) -> bool:
        """Restore (state, offsets) atomically from the host mirror;
        False → caller rebuilds from a table snapshot."""
        path = self._mirror_path()
        if path is None or not os.path.exists(path):
            return False
        import json
        with open(path) as f:
            m = json.load(f)
        if len(m.get("parts", [])) != len(self.parts):
            return False               # partition layout changed
        self.watermark = int(m["watermark"])
        self.degraded = bool(m.get("degraded", False))
        self.folds = int(m.get("folds", 0))
        self.rebuilds = int(m.get("rebuilds", 0))
        for part, pm in zip(self.parts, m["parts"]):
            part.offset = int(pm["offset"])
            part.groups = {tuple(k): list(v) for k, v in pm["groups"]}
            part.mmaps = {
                int(j): {tuple(k): {_mm_key(v): c for v, c in pairs}
                         for k, pairs in entries}
                for j, entries in pm.get("mmaps", {}).items()}
            part.rows = {tuple(k): tuple(v) for k, v in pm.get("rows", [])}
        return True

    def free(self) -> None:
        """DROP: forget state and the mirror."""
        with self._mu:
            for part in self.parts:
                part.groups.clear()
                part.mmaps.clear()
                part.rows.clear()
            self._serve = None
            path = self._mirror_path()
            if path is not None and os.path.exists(path):
                os.remove(path)


def _mm_key(v):
    # JSON round-trips int-valued floats as-is; keys came from row
    # images, so the stored type is already the source type
    return v


class ViewManager:
    """The engine's view registry: DDL, commit-time fold scheduling,
    serving lookups, durability (views.json + per-view mirrors)."""

    def __init__(self, engine):
        self.engine = engine
        self.views: dict = {}            # name -> MatView
        self._by_source: dict = {}       # table -> [view names]
        self.fold_batch = _env_int("YDB_TPU_VIEW_FOLD_BATCH", 256)

    # -- registry ----------------------------------------------------------

    def has(self, name: str) -> bool:
        return name in self.views

    def get(self, name: str) -> Optional[MatView]:
        return self.views.get(name)

    def on_table(self, table: str) -> list:
        return [self.views[n] for n in self._by_source.get(table, ())
                if n in self.views]

    # -- DDL ---------------------------------------------------------------

    def create(self, name: str, select, sql: str) -> MatView:
        from ydb_tpu.query.engine import QueryError
        eng = self.engine
        if name in self.views:
            raise QueryError(f"materialized view {name!r} already exists")
        if eng.catalog.has(name):
            raise QueryError(f"{name!r} already names a table")
        rel = getattr(select, "relation", None)
        import ydb_tpu.sql.ast as ast
        if not isinstance(rel, ast.TableRef) or not eng.catalog.has(rel.name):
            raise UnsupportedView(
                "materialized views fold a single existing source table")
        src = eng.catalog.table(rel.name)
        if getattr(src, "store_kind", "column") != "row":
            raise UnsupportedView(
                "materialized views need a row-store source (CDC)")
        vp = compile_view(name, select, src, sql, planner=eng.planner)

        topic_name = eng._changefeeds.get(rel.name)
        auto = topic_name is None
        if auto:
            topic_name = f"__cdc_{rel.name}"
            if topic_name not in eng.topics:
                eng.create_topic(topic_name, partitions=2)
            eng.enable_changefeed(rel.name, topic_name)
        view = MatView(self, name, vp, topic_name, auto)
        with view._mu:
            # initial population is a load, not a counted escape
            view._rebuild("initial population", count=False)
        self.views[name] = view
        self._by_source.setdefault(rel.name, []).append(name)
        self._persist()
        GLOBAL.set("view/registered", len(self.views))
        return view

    def drop(self, name: str, if_exists: bool = False) -> bool:
        from ydb_tpu.query.engine import QueryError
        view = self.views.pop(name, None)
        if view is None:
            if if_exists:
                return False
            raise QueryError(f"unknown materialized view {name!r}")
        src = view.vp.source
        names = self._by_source.get(src, [])
        if name in names:
            names.remove(name)
        if not names:
            self._by_source.pop(src, None)
        view.free()
        eng = self.engine
        shared = any(v.topic_name == view.topic_name
                     for v in self.views.values())
        if view.auto_topic and not shared:
            # unsubscribe: unwire the changefeed we created, then drop
            # its topic (drop_topic refuses while the feed is wired)
            with eng.lock:
                if eng._changefeeds.get(src) == view.topic_name:
                    t = eng.catalog.table(src) if eng.catalog.has(src) \
                        else None
                    if t is not None:
                        t.changefeed = None
                    eng._changefeeds.pop(src, None)
                    eng._cdc_since.pop(src, None)
                    eng._save_topics()
                if view.topic_name in eng.topics:
                    eng.drop_topic(view.topic_name)
        self._persist()
        GLOBAL.set("view/registered", len(self.views))
        return True

    def drop_for_table(self, table: str) -> None:
        for v in list(self.on_table(table)):
            self.drop(v.name)

    # -- commit hook -------------------------------------------------------

    def on_commit(self, table: str) -> None:
        """Fold when a source's lag crosses the batch threshold, so the
        read path drains at most one small tail. Non-blocking: if a
        reader holds the view lock it is folding already."""
        names = self._by_source.get(table)
        if not names:
            return
        for n in list(names):
            v = self.views.get(n)
            if v is None or v.degraded:
                continue
            if v.lag_messages() >= self.fold_batch:
                if v._mu.acquire(blocking=False):
                    try:
                        v.drain()
                    finally:
                        v._mu.release()
            GLOBAL.set("view/lag_versions", v.lag_versions())

    # -- durability --------------------------------------------------------

    def _persist(self) -> None:
        store = self.engine.catalog.store
        if store is None:
            return
        from ydb_tpu.storage.persist import _atomic_json
        _atomic_json(
            os.path.join(store.root, "views.json"),
            {n: {"sql": v.vp.sql, "source": v.vp.source,
                 "topic": v.topic_name, "auto_topic": v.auto_topic}
             for n, v in self.views.items()})

    def load(self) -> None:
        """Restart: recompile each view from its defining SQL, restore
        (state, offsets) from the host mirror, drain what landed while
        down. Fold programs come back from the progstore — zero
        recompiles. Missing/stale mirror → counted rebuild."""
        store = self.engine.catalog.store
        if store is None:
            return
        path = os.path.join(store.root, "views.json")
        if not os.path.exists(path):
            return
        import json
        from ydb_tpu.sql.parser import parse
        with open(path) as f:
            meta = json.load(f)
        for name, vm in meta.items():
            src_name = vm["source"]
            if not self.engine.catalog.has(src_name) \
                    or vm["topic"] not in self.engine.topics:
                continue
            src = self.engine.catalog.table(src_name)
            try:
                vp = compile_view(name, parse(vm["sql"]), src, vm["sql"],
                                  planner=self.engine.planner)
            except UnsupportedView:
                continue
            view = MatView(self, name, vp, vm["topic"],
                           bool(vm.get("auto_topic")))
            with view._mu:
                if view.load_mirror():
                    view.drain()
                else:
                    view._rebuild("missing host mirror")
            self.views[name] = view
            self._by_source.setdefault(src_name, []).append(name)
        GLOBAL.set("view/registered", len(self.views))

    # -- observability -----------------------------------------------------

    def sysview_rows(self) -> list:
        out = []
        step = self.engine.coordinator.last_plan_step
        for name in sorted(self.views):
            v = self.views[name]
            out.append({
                "name": name, "source": v.vp.source, "kind": v.vp.kind,
                "topic": v.topic_name, "watermark_step": v.watermark,
                "lag_versions": max(0, step - v.watermark),
                "state_rows": v.group_count(),
                "state_bytes": v.state_bytes(),
                "folds": v.folds, "rebuilds": v.rebuilds,
                "degraded": v.degraded,
            })
        return out
