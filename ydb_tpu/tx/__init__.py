from ydb_tpu.tx.coordinator import Coordinator  # noqa: F401
from ydb_tpu.tx.session import Session, Transaction, TxAborted  # noqa: F401
