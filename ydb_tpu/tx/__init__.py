from ydb_tpu.tx.coordinator import Coordinator  # noqa: F401
from ydb_tpu.tx.session import (  # noqa: F401
    Session, Transaction, TxAborted, TxCommitTorn,
)
