"""Interactive transactions — BEGIN / COMMIT / ROLLBACK with optimistic
locks.

The reference's session actor holds per-session tx state
(`ydb/core/kqp/session_actor/kqp_session_actor.cpp`), acquires optimistic
locks during reads (`ydb/core/tx/locks/`), and commits through the
coordinator plan-step protocol with lock validation at commit time.

v0 semantics (snapshot isolation + table-granular optimistic locks):

  * BEGIN captures the coordinator's read snapshot; every statement in the
    tx reads at that snapshot PLUS the tx's own uncommitted writes
    (`Snapshot.tx_view`);
  * writes stage against storage tagged with the tx id — row tables get
    unstamped version-chain entries, column tables uncommitted insert-table
    writes — invisible to every other session;
  * each table READ records (uid, data_version-at-snapshot) in the lock
    set and validates TABLE-granular at commit (any foreign bump since
    BEGIN → TxAborted); own staged writes bump data_version, so the
    lock remembers how many bumps were self-inflicted;
  * tables only ever WRITTEN validate finer (the row/range-lock
    refinement of `ydb/core/tx/locks/`): row-store blind writes take
    pk-granular write locks — commit aborts only when a foreign commit
    newer than the snapshot touched one of OUR keys — and column-store
    blind inserts are commuting appends (no conflict possible without a
    read);
  * COMMIT validates every lock, then takes one coordinator plan step
    and stamps all staged writes at it — atomically visible, since
    readers order by plan step;
  * ROLLBACK (or abort) removes every staged write.

Reads stay table-granular (no predicate locks), which keeps the
protocol sound: serializable over row tables, snapshot-write isolation
over column tables.
"""

from __future__ import annotations

from typing import Optional

from ydb_tpu.storage.mvcc import Snapshot


class TxAborted(Exception):
    """Optimistic lock broken: a conflicting commit landed since BEGIN."""


class TxCommitTorn(Exception):
    """Internal error: a multi-table COMMIT failed mid-apply. Tables
    whose apply already landed keep their writes (stamped versions
    cannot be recalled); everything not yet applied was force-aborted
    and the session's transaction is cleared. Deliberately NOT a
    `TxAborted` subclass: the standard `except TxAborted: retry` idiom
    is only safe when nothing landed, and a torn commit re-run would
    double-apply the tables that did — clients must handle it
    explicitly (operator attention, not retry)."""


class Transaction:
    def __init__(self, tx_id: int, snapshot: Snapshot,
                 begin_versions: dict):
        self.tx_id = tx_id
        self.snapshot = Snapshot(snapshot.plan_step, snapshot.tx_id,
                                 tx_view=tx_id)
        # data_version of every table AS OF BEGIN — the lock baseline
        # (first-touch versions would miss commits landing between BEGIN
        # and the first read, which the tx's snapshot cannot see)
        self.begin_versions = begin_versions
        # uid -> [table, baseline version, self bumps since]
        self.locks: dict = {}
        # READ-locked tables validate table-granular; tables only ever
        # WRITTEN validate at pk granularity (row stores) or commute
        # (column inserts are pure appends) — concurrent blind upserts
        # to disjoint keys stop aborting spuriously (the row/range-lock
        # refinement of `ydb/core/tx/locks/`, point-write granularity)
        self.read_locked: set = set()
        self.write_pks: dict = {}      # uid -> set of pk tuples
        self.row_writes: list = []     # (table, ops) in apply order
        self.col_writes: list = []     # (table, [(shard, wid)])
        self.col_deletes: list = []    # (table, [delete-mark handles])

    def lock(self, table, read: bool = True) -> None:
        if read:
            self.read_locked.add(table.uid)
        if table.uid not in self.locks:
            seen = self.begin_versions.get(table.uid, table.data_version)
            self.locks[table.uid] = [table, seen, 0]

    def note_self_bump(self, table, n: int = 1,
                       write_pks=None) -> None:
        self.lock(table, read=False)
        self.locks[table.uid][2] += n
        if write_pks is not None:
            self.write_pks.setdefault(table.uid, set()).update(write_pks)

    def validate(self) -> None:
        for uid, (table, seen, self_bumps) in self.locks.items():
            if uid in self.read_locked:
                if table.data_version - self_bumps != seen:
                    raise TxAborted(
                        f"optimistic lock broken on table {table.name!r}")
                continue
            # write-only: point conflicts on the touched keys only
            pks = self.write_pks.get(uid)
            check = getattr(table, "max_committed_step", None)
            if pks and check is not None \
                    and check(pks) > self.snapshot.plan_step:
                raise TxAborted(
                    f"write-write conflict on table {table.name!r}")
            # write-only column-table appends commute: no check


class Session:
    """One interactive session over a shared engine (the session-actor
    analog). Sessions share catalog/executor/coordinator; each holds at
    most one open transaction."""

    def __init__(self, engine):
        import threading
        self.engine = engine
        self.tx: Optional[Transaction] = None
        # one statement at a time per session: a client pipelining e.g.
        # SELECT and COMMIT on the same session must not race on self.tx
        # (the reference rejects with SESSION_BUSY; here the second
        # statement queues). The engine-wide default session skips this —
        # anonymous autocommit reads are the concurrent path.
        self._mu = threading.RLock()

    # -- statement entry ---------------------------------------------------

    def execute(self, sql: str):
        return self.engine.execute(sql, session=self)

    def query(self, sql: str):
        return self.engine.execute(sql, session=self).to_pandas()

    # -- tx control --------------------------------------------------------

    def begin(self) -> None:
        if self.tx is not None:
            raise TxAborted("transaction already open")
        coord = self.engine.coordinator
        begin_versions = {t.uid: t.data_version
                          for t in self.engine.catalog.tables.values()}
        self.tx = Transaction(coord.begin_tx(), coord.read_snapshot(),
                              begin_versions)
        coord.pin_snapshot(self.tx.tx_id, self.tx.snapshot.plan_step)

    def commit(self) -> None:
        tx = self._require_tx()
        try:
            tx.validate()
        except TxAborted:
            self._abort(tx)
            raise
        coord = self.engine.coordinator
        version = coord.propose(tx.tx_id)
        # group column writes + delete marks PER TABLE: one commit call
        # carries both through one intent-journal record (an UPDATE's
        # deletes and re-inserts must survive a crash together)
        col_tables: dict = {}
        for table, writes in tx.col_writes:
            ent = col_tables.setdefault(id(table), [table, [], []])
            ent[1].extend(writes)
        for table, handles in tx.col_deletes:
            ent = col_tables.setdefault(id(table), [table, [], []])
            ent[2].extend(handles)
        # keys are id(table) for BOTH kinds (col_tables is keyed that
        # way too). A table is "landed" once its apply call returned;
        # the table whose apply call is IN FLIGHT when an exception hits
        # is in-doubt: stamp_tx stamps chains before its WAL append and
        # table.commit's durable record (store.commit_table) precedes
        # its dictionary/state saves — either may have landed, so the
        # poison path must never roll an in-doubt table back (a WAL
        # abort for committed wids would drop the rows at the next
        # replay — silent durable loss); un-landed staged writes heal
        # at boot.
        landed: set = set()
        in_doubt_key = None
        try:
            for table, ops in tx.row_writes:
                in_doubt_key = id(table)
                table.stamp_tx(tx.tx_id, version, ops_for_wal=ops)
                landed.add(id(table))
                in_doubt_key = None
            for key, (table, writes, handles) in col_tables.items():
                hits = [(shard, portion, mark.rows)
                        for (shard, portion, mark) in handles]
                for (_shard, portion, mark) in handles:
                    portion.drop_delete(mark)  # replaced by committed marks
                in_doubt_key = key
                table.commit(writes, version, deletes=hits)
                landed.add(key)
                in_doubt_key = None
        except Exception as e:         # noqa: BLE001 — poison, don't tear
            keep = set(landed)
            if in_doubt_key is not None:
                keep.add(in_doubt_key)
            self._poison_torn_commit(tx, col_tables, keep, version, e)
        # indexation is maintenance, not part of commit atomicity: run it
        # only once every table's apply landed, and never let it poison a
        # fully-committed transaction (the next commit/indexate retries)
        for (table, _writes, _handles) in col_tables.values():
            try:
                table.indexate()
            except Exception:          # noqa: BLE001 — best-effort
                pass
        # read watermark advances only once every shard's apply landed
        # (lock-free readers must never see a torn cross-table commit)
        coord.publish(version.plan_step)
        if self.engine.catalog.store is not None:
            self.engine.catalog.store.save_state(version.plan_step)
        self.engine.coordinator.unpin_snapshot(tx.tx_id)
        self.tx = None

    def _poison_torn_commit(self, tx: Transaction, col_tables: dict,
                            keep: set, version,
                            cause: Exception) -> None:
        """A multi-table apply failed partway. The r5 `finally` published
        the half-applied step and left the tx open — readers saw a torn
        cross-table commit forever and a retry double-applied. Instead:
        force-abort everything not yet applied (`keep` holds the landed
        tables — stamped versions cannot be recalled — plus the table
        whose apply call was in flight: its stamps/durable record may
        have landed, so rolling it back could destroy committed data),
        publish the step so the read watermark never wedges behind it,
        clear the session's tx, and surface a distinct internal error
        naming what did (or may have) landed."""
        applied = sorted({t.name for t, _ops in tx.row_writes
                          if id(t) in keep}
                         | {ent[0].name for k, ent in col_tables.items()
                            if k in keep})
        for table, _ops in tx.row_writes:
            if id(table) in keep:
                continue
            try:
                table.rollback_tx(tx.tx_id)
            except Exception:          # noqa: BLE001 — best-effort abort
                pass
        for key, (table, writes, handles) in col_tables.items():
            if key in keep:
                continue
            try:
                table.rollback_deletes(handles)
            except Exception:          # noqa: BLE001
                pass
            try:
                table.rollback(writes)
            except Exception:          # noqa: BLE001
                pass
        coord = self.engine.coordinator
        coord.publish(version.plan_step)
        coord.unpin_snapshot(tx.tx_id)
        self.tx = None
        if not keep:
            # nothing landed and nothing is in doubt: every write was
            # cleanly force-aborted, so the safe-retry contract of a
            # plain TxAborted still holds — don't escalate to the
            # must-not-retry torn error
            raise TxAborted(
                f"commit failed before any write landed "
                f"({type(cause).__name__}: {cause}); transaction "
                "force-aborted cleanly — safe to retry") from cause
        raise TxCommitTorn(
            f"internal: multi-table commit torn at plan step "
            f"{version.plan_step} ({type(cause).__name__}: {cause}); "
            f"applied (or in-doubt) tables: {applied or 'none'}; "
            "everything else force-aborted") from cause

    def rollback(self) -> None:
        tx = self._require_tx()
        self._abort(tx)

    def _abort(self, tx: Transaction) -> None:
        for table, _ops in tx.row_writes:
            table.rollback_tx(tx.tx_id)
        for table, handles in tx.col_deletes:
            table.rollback_deletes(handles)
        for table, writes in tx.col_writes:
            table.rollback(writes)
        self.engine.coordinator.unpin_snapshot(tx.tx_id)
        self.tx = None

    def _require_tx(self) -> Transaction:
        if self.tx is None:
            raise TxAborted("no open transaction")
        return self.tx

    # -- engine integration ------------------------------------------------

    @property
    def snapshot(self) -> Optional[Snapshot]:
        return self.tx.snapshot if self.tx is not None else None
