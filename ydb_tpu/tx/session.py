"""Interactive transactions — BEGIN / COMMIT / ROLLBACK with optimistic
locks.

The reference's session actor holds per-session tx state
(`ydb/core/kqp/session_actor/kqp_session_actor.cpp`), acquires optimistic
locks during reads (`ydb/core/tx/locks/`), and commits through the
coordinator plan-step protocol with lock validation at commit time.

v0 semantics (snapshot isolation + table-granular optimistic locks):

  * BEGIN captures the coordinator's read snapshot; every statement in the
    tx reads at that snapshot PLUS the tx's own uncommitted writes
    (`Snapshot.tx_view`);
  * writes stage against storage tagged with the tx id — row tables get
    unstamped version-chain entries, column tables uncommitted insert-table
    writes — invisible to every other session;
  * each table READ records (uid, data_version-at-snapshot) in the lock
    set and validates TABLE-granular at commit (any foreign bump since
    BEGIN → TxAborted); own staged writes bump data_version, so the
    lock remembers how many bumps were self-inflicted;
  * tables only ever WRITTEN validate finer (the row/range-lock
    refinement of `ydb/core/tx/locks/`): row-store blind writes take
    pk-granular write locks — commit aborts only when a foreign commit
    newer than the snapshot touched one of OUR keys — and column-store
    blind inserts are commuting appends (no conflict possible without a
    read);
  * COMMIT validates every lock, then takes one coordinator plan step
    and stamps all staged writes at it — atomically visible, since
    readers order by plan step;
  * ROLLBACK (or abort) removes every staged write.

Reads stay table-granular (no predicate locks), which keeps the
protocol sound: serializable over row tables, snapshot-write isolation
over column tables.
"""

from __future__ import annotations

from typing import Optional

from ydb_tpu.storage.mvcc import Snapshot


class TxAborted(Exception):
    """Optimistic lock broken: a conflicting commit landed since BEGIN."""


class Transaction:
    def __init__(self, tx_id: int, snapshot: Snapshot,
                 begin_versions: dict):
        self.tx_id = tx_id
        self.snapshot = Snapshot(snapshot.plan_step, snapshot.tx_id,
                                 tx_view=tx_id)
        # data_version of every table AS OF BEGIN — the lock baseline
        # (first-touch versions would miss commits landing between BEGIN
        # and the first read, which the tx's snapshot cannot see)
        self.begin_versions = begin_versions
        # uid -> [table, baseline version, self bumps since]
        self.locks: dict = {}
        # READ-locked tables validate table-granular; tables only ever
        # WRITTEN validate at pk granularity (row stores) or commute
        # (column inserts are pure appends) — concurrent blind upserts
        # to disjoint keys stop aborting spuriously (the row/range-lock
        # refinement of `ydb/core/tx/locks/`, point-write granularity)
        self.read_locked: set = set()
        self.write_pks: dict = {}      # uid -> set of pk tuples
        self.row_writes: list = []     # (table, ops) in apply order
        self.col_writes: list = []     # (table, [(shard, wid)])
        self.col_deletes: list = []    # (table, [delete-mark handles])

    def lock(self, table, read: bool = True) -> None:
        if read:
            self.read_locked.add(table.uid)
        if table.uid not in self.locks:
            seen = self.begin_versions.get(table.uid, table.data_version)
            self.locks[table.uid] = [table, seen, 0]

    def note_self_bump(self, table, n: int = 1,
                       write_pks=None) -> None:
        self.lock(table, read=False)
        self.locks[table.uid][2] += n
        if write_pks is not None:
            self.write_pks.setdefault(table.uid, set()).update(write_pks)

    def validate(self) -> None:
        for uid, (table, seen, self_bumps) in self.locks.items():
            if uid in self.read_locked:
                if table.data_version - self_bumps != seen:
                    raise TxAborted(
                        f"optimistic lock broken on table {table.name!r}")
                continue
            # write-only: point conflicts on the touched keys only
            pks = self.write_pks.get(uid)
            check = getattr(table, "max_committed_step", None)
            if pks and check is not None \
                    and check(pks) > self.snapshot.plan_step:
                raise TxAborted(
                    f"write-write conflict on table {table.name!r}")
            # write-only column-table appends commute: no check


class Session:
    """One interactive session over a shared engine (the session-actor
    analog). Sessions share catalog/executor/coordinator; each holds at
    most one open transaction."""

    def __init__(self, engine):
        import threading
        self.engine = engine
        self.tx: Optional[Transaction] = None
        # one statement at a time per session: a client pipelining e.g.
        # SELECT and COMMIT on the same session must not race on self.tx
        # (the reference rejects with SESSION_BUSY; here the second
        # statement queues). The engine-wide default session skips this —
        # anonymous autocommit reads are the concurrent path.
        self._mu = threading.RLock()

    # -- statement entry ---------------------------------------------------

    def execute(self, sql: str):
        return self.engine.execute(sql, session=self)

    def query(self, sql: str):
        return self.engine.execute(sql, session=self).to_pandas()

    # -- tx control --------------------------------------------------------

    def begin(self) -> None:
        if self.tx is not None:
            raise TxAborted("transaction already open")
        coord = self.engine.coordinator
        begin_versions = {t.uid: t.data_version
                          for t in self.engine.catalog.tables.values()}
        self.tx = Transaction(coord.begin_tx(), coord.read_snapshot(),
                              begin_versions)
        coord.pin_snapshot(self.tx.tx_id, self.tx.snapshot.plan_step)

    def commit(self) -> None:
        tx = self._require_tx()
        try:
            tx.validate()
        except TxAborted:
            self._abort(tx)
            raise
        coord = self.engine.coordinator
        version = coord.propose(tx.tx_id)
        try:
            for table, ops in tx.row_writes:
                table.stamp_tx(tx.tx_id, version, ops_for_wal=ops)
            # group column writes + delete marks PER TABLE: one commit call
            # carries both through one intent-journal record (an UPDATE's
            # deletes and re-inserts must survive a crash together)
            col_tables: dict = {}
            for table, writes in tx.col_writes:
                ent = col_tables.setdefault(id(table), [table, [], []])
                ent[1].extend(writes)
            for table, handles in tx.col_deletes:
                ent = col_tables.setdefault(id(table), [table, [], []])
                ent[2].extend(handles)
            for (table, writes, handles) in col_tables.values():
                hits = [(shard, portion, mark.rows)
                        for (shard, portion, mark) in handles]
                for (_shard, portion, mark) in handles:
                    portion.drop_delete(mark)  # replaced by committed marks
                table.commit(writes, version, deletes=hits)
                table.indexate()
        finally:
            # read watermark advances only once every shard's apply landed
            # (lock-free readers must never see a torn cross-table commit)
            coord.publish(version.plan_step)
        if self.engine.catalog.store is not None:
            self.engine.catalog.store.save_state(version.plan_step)
        self.engine.coordinator.unpin_snapshot(tx.tx_id)
        self.tx = None

    def rollback(self) -> None:
        tx = self._require_tx()
        self._abort(tx)

    def _abort(self, tx: Transaction) -> None:
        for table, _ops in tx.row_writes:
            table.rollback_tx(tx.tx_id)
        for table, handles in tx.col_deletes:
            table.rollback_deletes(handles)
        for table, writes in tx.col_writes:
            table.rollback(writes)
        self.engine.coordinator.unpin_snapshot(tx.tx_id)
        self.tx = None

    def _require_tx(self) -> Transaction:
        if self.tx is None:
            raise TxAborted("no open transaction")
        return self.tx

    # -- engine integration ------------------------------------------------

    @property
    def snapshot(self) -> Optional[Snapshot]:
        return self.tx.snapshot if self.tx is not None else None
