"""Transaction coordinator — plan-step allocation and commit ordering.

The reference's coordinator tablet (`ydb/core/tx/coordinator/
coordinator_impl.h:209`, `coordinator__plan_step.cpp`) assigns global plan
steps that order distributed transactions across shards; the mediator
(`ydb/core/tx/mediator/`) fans each step out to per-shard execute queues,
and TimeCast (`time_cast/time_cast.h:70`) tells shards the safe watermark
for MVCC reads.

In-process v0: one Coordinator owns the monotonic (plan_step, tx_id)
space. `propose` is the plan-step grant; because all shards live in this
process, mediator fan-out degenerates to the caller applying the commit
synchronously — the protocol boundary (propose → stamped version →
per-shard apply) is kept so a networked mediator can slot in.
"""

from __future__ import annotations

from ydb_tpu.storage.mvcc import Snapshot, WriteVersion


class Coordinator:
    def __init__(self, start_step: int = 1):
        self._plan_step = max(1, start_step)
        self._next_tx = 1
        self._pinned: dict[int, int] = {}   # open tx id -> snapshot step

    def begin_tx(self) -> int:
        """Allocate a transaction id (the TxProxy tx-allocator analog)."""
        tx = self._next_tx
        self._next_tx += 1
        return tx

    def propose(self, tx_id: int = 0) -> WriteVersion:
        """Grant the next plan step to a committing transaction."""
        self._plan_step += 1
        return WriteVersion(self._plan_step, tx_id)

    def read_snapshot(self) -> Snapshot:
        """Safe MVCC read watermark (the TimeCast analog): everything
        planned so far is visible, nothing in flight is."""
        return Snapshot(self._plan_step, 2 ** 62)

    # -- pinned snapshots (open interactive txs) --------------------------

    def pin_snapshot(self, tx_id: int, plan_step: int) -> None:
        self._pinned[tx_id] = plan_step

    def unpin_snapshot(self, tx_id: int) -> None:
        self._pinned.pop(tx_id, None)

    def safe_watermark(self) -> int:
        """Highest plan step no pinned snapshot is behind — background
        maintenance (compaction re-stamps merged portions) must not touch
        versions newer than this, or pinned readers lose rows."""
        if self._pinned:
            return min(self._pinned.values())
        return self._plan_step

    @property
    def last_plan_step(self) -> int:
        return self._plan_step
