"""Transaction coordinator — plan-step allocation and commit ordering.

The reference's coordinator tablet (`ydb/core/tx/coordinator/
coordinator_impl.h:209`, `coordinator__plan_step.cpp`) assigns global plan
steps that order distributed transactions across shards; the mediator
(`ydb/core/tx/mediator/`) fans each step out to per-shard execute queues,
and TimeCast (`time_cast/time_cast.h:70`) tells shards the safe watermark
for MVCC reads.

In-process v0: one Coordinator owns the monotonic (plan_step, tx_id)
space. `propose` is the plan-step grant; because all shards live in this
process, mediator fan-out degenerates to the caller applying the commit
synchronously — the protocol boundary (propose → stamped version →
per-shard apply) is kept so a networked mediator can slot in.
"""

from __future__ import annotations

from ydb_tpu.storage.mvcc import Snapshot, WriteVersion


class Coordinator:
    def __init__(self, start_step: int = 1):
        import threading
        self._mu = threading.Lock()
        self._plan_step = max(1, start_step)
        # read watermark: the highest plan step whose commit has finished
        # APPLYING (stamps + delete marks in memory). propose() grants a
        # step but does not publish it — lock-free readers snapshotting
        # mid-commit must not observe a torn multi-shard apply (partial
        # inserts, or an UPDATE's re-inserts without its delete marks).
        self._published = self._plan_step
        self._proposed: set[int] = set()    # granted, not yet published
        self._next_tx = 1
        self._pinned: dict[int, int] = {}   # open tx id -> snapshot step

    def begin_tx(self) -> int:
        """Allocate a transaction id (the TxProxy tx-allocator analog)."""
        with self._mu:
            tx = self._next_tx
            self._next_tx += 1
            return tx

    def propose(self, tx_id: int = 0) -> WriteVersion:
        """Grant the next plan step to a committing transaction. The step
        becomes readable only after `publish(step)` — callers must publish
        once the commit's in-memory apply completes (or aborts)."""
        with self._mu:
            self._plan_step += 1
            self._proposed.add(self._plan_step)
            return WriteVersion(self._plan_step, tx_id)

    def publish(self, plan_step: int) -> None:
        """Mark a granted plan step fully applied; advances the read
        watermark past every contiguous applied step (the mediator's
        step-complete acknowledgement, `coordinator__plan_step.cpp`)."""
        with self._mu:
            self._proposed.discard(plan_step)
            self._published = (min(self._proposed) - 1) if self._proposed \
                else self._plan_step

    def read_snapshot(self) -> Snapshot:
        """Safe MVCC read watermark (the TimeCast analog): everything
        published so far is visible, nothing mid-apply is."""
        with self._mu:
            return Snapshot(self._published, 2 ** 62)

    # -- pinned snapshots (open interactive txs) --------------------------

    def pin_snapshot(self, tx_id: int, plan_step: int) -> None:
        with self._mu:
            self._pinned[tx_id] = plan_step

    def unpin_snapshot(self, tx_id: int) -> None:
        with self._mu:
            self._pinned.pop(tx_id, None)

    def safe_watermark(self) -> int:
        """Highest plan step no pinned snapshot is behind — background
        maintenance (compaction re-stamps merged portions) must not touch
        versions newer than this, or pinned readers lose rows. Bounded by
        the published watermark: restamping into a mid-apply step would
        outrun every current reader's snapshot."""
        with self._mu:
            if self._pinned:
                return min(min(self._pinned.values()), self._published)
            return self._published

    @property
    def last_plan_step(self) -> int:
        return self._plan_step
