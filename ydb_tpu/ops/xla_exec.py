"""XLA lowering of SSA programs — the TPU data plane.

Each (program, input-signature, capacity-bucket) pair compiles once to a
single fused XLA computation via ``jax.jit`` and is cached — the analog of
the reference's MiniKQL pattern cache (compile-once, run-per-block,
`ydb/library/yql/minikql/computation/mkql_computation_pattern_cache.h:56`)
with XLA playing the role of the LLVM codegen path
(`ydb/library/yql/minikql/codegen/`).

Design constraints honored for the TPU:
  * static shapes only — blocks are padded to power-of-two capacity
    buckets; the true row count rides as a traced scalar and every
    reduction masks by ``iota < length``;
  * no data-dependent control flow — filters keep selection masks
    (`TColumnFilter` semantics) instead of gathering;
  * GroupBy avoids scatter ops: global aggregates are plain masked
    reductions; bounded key domains use a chunked one-hot 2-D reduction
    (an MXU/VPU-friendly "aggregation as reduction over buckets");
    unbounded domains sort (keys + row-id only — wide multi-operand
    sorts explode XLA compile time) and aggregate with cumulative-sum
    differences at segment boundaries;
  * f64 accumulation for SQL sum semantics (TPU emulates f64; precision
    verified against the numpy oracle in tests).

Measured platform note (tunneled single-chip TPU, see PERF.md): after the
first device→host readout in a process, every dispatch pays a large fixed
latency and each *scatter* op (`segment_sum`, `.at[].set/add`) pays ~70-100ms
extra, while gathers / sorts / cumsums / reductions stay at base cost. The
operator designs here (and the whole-query fusion in
`ydb_tpu/ops/fused.py`) exist to keep a query at one dispatch with zero
scatters in the steady state.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.dtypes import DType, Kind
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import ir
from ydb_tpu.ops.device import DeviceBlock, bucket_capacity, to_device, to_host
from ydb_tpu.ops.kernels import KERNELS


# --------------------------------------------------------------------------
# traced helpers
# --------------------------------------------------------------------------


def _sort_operand(x):
    """A lax.sort-comparable operand for a key column, in its natural domain.

    No bitcast tricks: the TPU x64 emulation pass cannot rewrite
    f64<->s64 bitcasts, and ``lax.sort`` already provides a total order for
    float and unsigned operands natively."""
    if x.dtype in (jnp.float64, jnp.float32, jnp.uint64):
        return x
    if x.dtype == jnp.bool_:
        return x.astype(jnp.int32)
    return x.astype(jnp.int64)


def _zero_like_operand(x):
    return jnp.zeros((), x.dtype)


def _eval(expr, env, params, cap):
    if isinstance(expr, ir.Col):
        return env[expr.name]
    if isinstance(expr, ir.Const):
        return jnp.full((cap,), expr.value, dtype=expr.dtype.np), None
    if isinstance(expr, ir.Param):
        val = params[expr.name]
        if expr.is_array:
            return val, None
        return jnp.full((cap,), val, dtype=expr.dtype.np), None
    if isinstance(expr, ir.Call):
        k = KERNELS[expr.op]
        args = [_eval(a, env, params, cap) for a in expr.args]
        extra = expr.extra_dict()
        if k.null_mode == "custom":
            return k.impl_nv(jnp, args, extra)
        data = k.impl(jnp, [a[0] for a in args], extra)
        valid = None
        for _, v in args:
            if v is not None:
                valid = v if valid is None else (valid & v)
        return data, valid
    raise TypeError(f"bad expr {expr!r}")


_F64_MIN, _F64_MAX = -np.inf, np.inf


def _sentinel(dtype, for_min: bool):
    if np.issubdtype(dtype, np.floating):
        return np.array(np.inf if for_min else -np.inf, dtype=dtype)
    info = np.iinfo(dtype)
    return np.array(info.max if for_min else info.min, dtype=dtype)


_SMALL_DOMAIN_BUCKETS = 1 << 9     # one-hot 2-D reduction path bound
_CHUNK_W = 64                      # buckets per one-hot chunk
_SCATTER_MAX_BUCKETS = 1 << 16    # medium-domain single-scatter path bound


def _acc_dtype(d):
    if np.issubdtype(np.dtype(d.dtype), np.floating):
        return jnp.float64
    if d.dtype == jnp.uint64:
        return jnp.uint64
    return jnp.int64


def _groupby_global(cmd: ir.GroupBy, env, active, iota):
    """Keyless GROUP BY: plain masked reductions — one output row, no sort,
    no scatter (the BlockCombineAll analog, `mkql_block_agg.cpp`)."""
    new_env = {}
    for a in cmd.aggs:
        if a.func == "count_all":
            data = jnp.sum(active.astype(jnp.uint64))
            new_env[a.out] = (data[None], None)
            continue
        d, v = env[a.arg]
        m = active if v is None else (active & v)
        if a.func == "count":
            new_env[a.out] = (jnp.sum(m.astype(jnp.uint64))[None], None)
            continue
        any_valid = jnp.any(m)[None]
        if a.func == "sum":
            data = jnp.sum(jnp.where(m, d, 0).astype(_acc_dtype(d)))[None]
            new_env[a.out] = (data, any_valid)
        elif a.func in ("min", "max"):
            sent = _sentinel(np.dtype(d.dtype), a.func == "min")
            red = jnp.min if a.func == "min" else jnp.max
            data = red(jnp.where(m, d, sent))[None]
            data = jnp.where(any_valid, data, jnp.zeros((), d.dtype))
            new_env[a.out] = (data, any_valid)
        elif a.func == "some":
            firstpos = jnp.min(jnp.where(m, iota, len(iota)))
            data = d[jnp.clip(firstpos, 0, len(iota) - 1)][None]
            new_env[a.out] = (data, any_valid)
        else:
            raise ValueError(a.func)
    return new_env, jnp.int32(1)


def _bucket_ids(cmd: ir.GroupBy, env, cap):
    """Mixed-radix bucket id per row for bounded key domains (+1 slot per
    key for NULL)."""
    kid = jnp.zeros((cap,), jnp.int32)
    stride = 1
    strides = []
    for kname, dom in zip(cmd.keys, cmd.key_domains):
        d, v = env[kname]
        code = d.astype(jnp.int32) + 1          # -1 (null string code) → 0
        if v is not None:
            code = jnp.where(v, code, 0)        # SQL: one NULL group
        code = jnp.clip(code, 0, dom)
        kid = kid + code * stride
        strides.append(stride)
        stride *= dom + 1
    return kid, stride, strides


def _groupby_small_domain(cmd: ir.GroupBy, env, schema: Schema, sel,
                          length, cap):
    """Bounded-domain aggregation as a chunked one-hot 2-D reduction — the
    BlockCombineHashed analog (`mkql_block_agg.cpp`) built entirely from
    elementwise ops + axis-0 reductions (XLA fuses the one-hot expansion
    into the reduction; nothing materializes, nothing scatters)."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = (iota < length) if sel is None else ((iota < length) & sel)
    kid, nbuckets, strides = _bucket_ids(cmd, env, cap)

    chunks: dict[str, list] = {a.out: [] for a in cmd.aggs}
    valid_chunks: dict[str, list] = {}
    present_chunks = []
    for c0 in range(0, nbuckets, _CHUNK_W):
        w = min(_CHUNK_W, nbuckets - c0)
        ids = c0 + jnp.arange(w, dtype=jnp.int32)
        oh = (kid[:, None] == ids[None, :]) & active[:, None]
        present_chunks.append(jnp.any(oh, axis=0))
        for a in cmd.aggs:
            if a.func == "count_all":
                chunks[a.out].append(jnp.sum(oh.astype(jnp.uint64), axis=0))
                continue
            d, v = env[a.arg]
            m = oh if v is None else (oh & v[:, None])
            if a.func == "count":
                chunks[a.out].append(jnp.sum(m.astype(jnp.uint64), axis=0))
                continue
            any_valid = jnp.any(m, axis=0)
            valid_chunks.setdefault(a.out, []).append(any_valid)
            if a.func == "sum":
                acc = jnp.where(m, d[:, None], 0).astype(_acc_dtype(d))
                chunks[a.out].append(jnp.sum(acc, axis=0))
            elif a.func in ("min", "max"):
                sent = _sentinel(np.dtype(d.dtype), a.func == "min")
                red = jnp.min if a.func == "min" else jnp.max
                data = red(jnp.where(m, d[:, None], sent), axis=0)
                chunks[a.out].append(
                    jnp.where(any_valid, data, jnp.zeros((), d.dtype)))
            elif a.func == "some":
                firstpos = jnp.min(jnp.where(m, iota[:, None], cap), axis=0)
                chunks[a.out].append(d[jnp.clip(firstpos, 0, cap - 1)])
            else:
                raise ValueError(a.func)

    new_env = {}
    for a in cmd.aggs:
        data = jnp.concatenate(chunks[a.out])
        v = valid_chunks.get(a.out)
        new_env[a.out] = (data, jnp.concatenate(v) if v is not None else None)
    present = jnp.concatenate(present_chunks)
    return _emit_bucket_groups(cmd, env, schema, new_env, present, nbuckets,
                               strides)


def _emit_bucket_groups(cmd: ir.GroupBy, env, schema: Schema, new_env,
                        present, nbuckets, strides):
    """Shared bounded-domain epilogue: rebuild key columns from bucket ids,
    then compact non-empty buckets to the front of a SMALL capacity bucket
    (compress sorts; doing it over the scan capacity would cost a full
    cap-sized argsort for a handful of groups)."""
    bucket_ids = jnp.arange(nbuckets, dtype=jnp.int32)
    for kname, dom, st in zip(cmd.keys, cmd.key_domains, strides):
        code = (bucket_ids // st) % (dom + 1) - 1
        d, _v = env[kname]
        kd = code.astype(jnp.int32).astype(d.dtype)
        kv = code >= 0
        dt = schema.dtype(kname)
        new_env[kname] = (kd, kv if dt.nullable else None)

    out_cap = bucket_capacity(nbuckets, minimum=128)
    pad = out_cap - nbuckets
    padded = {}
    for name, (d, v) in new_env.items():
        dp = jnp.pad(d, (0, pad)) if pad > 0 else d[:out_cap]
        vp = None
        if v is not None:
            vp = jnp.pad(v, (0, pad)) if pad > 0 else v[:out_cap]
        padded[name] = (dp, vp)
    present_p = jnp.pad(present, (0, pad)) if pad > 0 else present[:out_cap]
    return compress(padded, jnp.int32(nbuckets), present_p, out_cap)


def _groupby_medium_domain(cmd: ir.GroupBy, env, schema: Schema, sel,
                           length, cap):
    """Bounded domains too wide for the one-hot path: one scatter-reduce
    per aggregate into a bucket array (each scatter pays the platform's
    post-readout scatter tax exactly once per aggregate)."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = (iota < length) if sel is None else ((iota < length) & sel)
    kid, nbuckets, strides = _bucket_ids(cmd, env, cap)
    seg_safe = jnp.where(active, kid, nbuckets)
    nseg = nbuckets + 1                         # +1 garbage bucket

    new_env = {}
    for a in cmd.aggs:
        if a.func == "count_all":
            data = jax.ops.segment_sum(active.astype(jnp.uint64), seg_safe,
                                       nseg)
            new_env[a.out] = (data[:nbuckets], None)
            continue
        d, v = env[a.arg]
        m = active if v is None else (active & v)
        if a.func == "count":
            data = jax.ops.segment_sum(m.astype(jnp.uint64), seg_safe, nseg)
            new_env[a.out] = (data[:nbuckets], None)
            continue
        cnt = jax.ops.segment_sum(m.astype(jnp.int32), seg_safe, nseg)
        any_valid = (cnt > 0)[:nbuckets]
        if a.func == "sum":
            acc = jnp.where(m, d, 0).astype(_acc_dtype(d))
            data = jax.ops.segment_sum(acc, seg_safe, nseg)[:nbuckets]
            new_env[a.out] = (data, any_valid)
        elif a.func in ("min", "max"):
            sent = _sentinel(np.dtype(d.dtype), a.func == "min")
            masked = jnp.where(m, d, sent)
            fn = jax.ops.segment_min if a.func == "min" else jax.ops.segment_max
            data = fn(masked, seg_safe, nseg)[:nbuckets]
            data = jnp.where(any_valid, data, jnp.zeros((), d.dtype))
            new_env[a.out] = (data, any_valid)
        elif a.func == "some":
            pos = jnp.where(m, iota, cap)
            firstpos = jax.ops.segment_min(pos, seg_safe, nseg)[:nbuckets]
            data = d[jnp.clip(firstpos, 0, cap - 1)]
            new_env[a.out] = (data, any_valid)
        else:
            raise ValueError(a.func)

    present = jax.ops.segment_sum(active.astype(jnp.int32), seg_safe,
                                  nseg)[:nbuckets] > 0
    return _emit_bucket_groups(cmd, env, schema, new_env, present, nbuckets,
                               strides)


def _trace_group_by_sorted(cmd: ir.GroupBy, env, schema: Schema, sel,
                           length, cap):
    """Unbounded-domain aggregation: sort (keys + row-id only), segment
    boundaries from key changes, sums/counts via cumulative-sum differences
    at segment endpoints, min/max via one scatter-reduce per aggregate.

    The sort carries only key encodings and the row permutation — carrying
    value columns through a wide multi-operand `lax.sort` explodes XLA
    compile time on TPU (minutes at 1M+ rows); values are gathered by the
    permutation instead.

    Precision note: a segment sum is csum[end] − csum[start] + v[start];
    for a tiny group inside a huge total the cancellation costs ~(total /
    group_sum)·1e-16 relative error — acceptable for SQL doubles and the
    test oracles' 1e-6 tolerances."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    row_mask = iota < length
    active = row_mask if sel is None else (row_mask & sel)

    inactive = (~active).astype(jnp.int32)
    sort_keys = [inactive]
    for kname in cmd.keys:
        d, v = env[kname]
        enc = _sort_operand(d)
        if v is not None:
            enc = jnp.where(v, enc, _zero_like_operand(enc))
            sort_keys.append(v.astype(jnp.int32))
        else:
            sort_keys.append(jnp.ones((cap,), jnp.int32))
        sort_keys.append(enc)
    # iota as the last key → deterministic total order, and the sort output
    # IS the permutation (no carried operands)
    out = jax.lax.sort(sort_keys + [iota], num_keys=len(sort_keys) + 1)
    inactive_s = out[0]
    keyparts_s = out[1:-1]
    perm = out[-1]

    env_s = {}

    def sorted_col(name):
        got = env_s.get(name)
        if got is None:
            d, v = env[name]
            got = (d[perm], v[perm] if v is not None else None)
            env_s[name] = got
        return got

    active_s = inactive_s == 0
    changed = jnp.zeros((cap,), jnp.bool_)
    for kp in keyparts_s:
        prev = jnp.concatenate([kp[:1], kp[:-1]])
        neq = kp != prev
        if np.issubdtype(np.dtype(kp.dtype), np.floating):
            # NaN != NaN would split every NaN row into its own group;
            # lax.sort places NaNs adjacently, so treat them as equal
            neq = neq & ~(jnp.isnan(kp) & jnp.isnan(prev))
        changed = changed | neq
    boundary = active_s & ((iota == 0) | changed)
    ngroups = jnp.sum(boundary.astype(jnp.int32))
    nactive = jnp.sum(active_s.astype(jnp.int32))

    # compact segment-start row indices to the front: starts[i] = sorted-row
    # index where group i begins
    starts = jnp.argsort(jnp.where(boundary, iota, jnp.int32(cap))
                         ).astype(jnp.int32)
    gi = jnp.arange(cap, dtype=jnp.int32)
    next_start = jnp.concatenate([starts[1:], jnp.full((1,), cap, jnp.int32)])
    ends = jnp.where(gi + 1 < ngroups, next_start - 1, nactive - 1)
    ends = jnp.clip(ends, 0, cap - 1)
    live = gi < ngroups

    new_env = {}
    for kname in cmd.keys:
        d, v = sorted_col(kname)
        kd = d[starts]
        dt = schema.dtype(kname)
        if dt.nullable:
            kv = (v[starts] if v is not None else jnp.ones((cap,), jnp.bool_))
            new_env[kname] = (kd, kv & live)
        else:
            new_env[kname] = (kd, None)

    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_safe = jnp.where(active_s, seg, cap)

    def csum_diff(per_row):
        """Per-group sum of a sorted per-row array via cumsum endpoints."""
        c = jnp.cumsum(per_row)
        first = per_row[starts]
        return c[ends] - c[starts] + first

    for a in cmd.aggs:
        if a.func == "count_all":
            data = csum_diff(active_s.astype(jnp.uint64))
            new_env[a.out] = (jnp.where(live, data, 0), None)
            continue
        d, v = sorted_col(a.arg)
        m = active_s if v is None else (active_s & v)
        if a.func == "count":
            data = csum_diff(m.astype(jnp.uint64))
            new_env[a.out] = (jnp.where(live, data, 0), None)
            continue
        cnt = csum_diff(m.astype(jnp.int64))
        any_valid = (cnt > 0) & live
        if a.func == "sum":
            acc = jnp.where(m, d, 0).astype(_acc_dtype(d))
            new_env[a.out] = (csum_diff(acc), any_valid)
        elif a.func in ("min", "max"):
            sent = _sentinel(np.dtype(d.dtype), a.func == "min")
            masked = jnp.where(m, d, sent)
            init = jnp.full((cap + 1,), sent, d.dtype)
            upd = (init.at[seg_safe].min(masked, mode="drop")
                   if a.func == "min"
                   else init.at[seg_safe].max(masked, mode="drop"))
            data = jnp.where(any_valid, upd[:cap], jnp.zeros((), d.dtype))
            new_env[a.out] = (data, any_valid)
        elif a.func == "some":
            # first valid value in the segment: rows are key-then-row-id
            # sorted, so scan for the first m-true position per segment
            pos = jnp.where(m, iota, cap)
            init = jnp.full((cap + 1,), cap, jnp.int32)
            firstpos = init.at[seg_safe].min(pos, mode="drop")[:cap]
            data = d[jnp.clip(firstpos, 0, cap - 1)]
            new_env[a.out] = (data, any_valid)
        else:
            raise ValueError(a.func)
    return new_env, ngroups.astype(jnp.int32)


def _trace_group_by(cmd: ir.GroupBy, env, schema: Schema, sel, length, cap):
    """GroupBy dispatch: keyless → plain reductions; small bounded domains →
    one-hot 2-D reduction; medium bounded → scatter-reduce; unbounded →
    sort-based. Returns (new_env, new_length)."""
    if not cmd.keys:
        iota = jnp.arange(cap, dtype=jnp.int32)
        active = (iota < length) if sel is None else ((iota < length) & sel)
        return _groupby_global(cmd, env, active, iota)
    if cmd.key_domains and all(d > 0 for d in cmd.key_domains):
        nb = 1
        for d in cmd.key_domains:
            nb *= d + 1
        if nb <= _SMALL_DOMAIN_BUCKETS:
            return _groupby_small_domain(cmd, env, schema, sel, length, cap)
        if nb + 1 <= _SCATTER_MAX_BUCKETS:
            return _groupby_medium_domain(cmd, env, schema, sel, length, cap)
    return _trace_group_by_sorted(cmd, env, schema, sel, length, cap)


def _trace_program(program: ir.Program, in_schema_cols, cap, env, length,
                   params, sel=None):
    """env: name -> (data, valid|None); returns (env, length, sel, schema).
    `sel` seeds the selection mask (fused pipelines thread it between
    programs instead of compressing)."""
    schema = Schema(list(in_schema_cols))
    for cmd in program.commands:
        if isinstance(cmd, ir.Assign):
            data, valid = _eval(cmd.expr, env, params, cap)
            env[cmd.name] = (data, valid)
            dt = ir.infer_expr(cmd.expr, schema)
            schema = Schema([c for c in schema.columns if c.name != cmd.name]
                            + [Column(cmd.name, dt)])
        elif isinstance(cmd, ir.Filter):
            data, valid = _eval(cmd.pred, env, params, cap)
            mask = data if valid is None else (data & valid)
            sel = mask if sel is None else (sel & mask)
        elif isinstance(cmd, ir.GroupBy):
            env, length = _trace_group_by(cmd, env, schema, sel, length, cap)
            # the scatter path shrinks the working capacity to a small
            # bucket; subsequent commands trace at the new size
            if env:
                cap = next(iter(env.values()))[0].shape[0]
            schema = ir.infer_schema(ir.Program([cmd]), schema)
            sel = None
        elif isinstance(cmd, ir.Projection):
            schema = schema.select(list(cmd.names))
            env = {nm: env[nm] for nm in cmd.names}
        else:
            raise TypeError(f"bad command {cmd!r}")
    return env, length, sel, schema


def compress(env, length, sel, cap):
    """BlockCompress: compact selected rows to the front (stable).

    Analog of `mkql_block_compress.cpp`. Sort by (dropped, position)."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = (iota < length) if sel is None else ((iota < length) & sel)
    keys = jnp.where(active, iota, jnp.int32(cap))
    order = jnp.argsort(keys)
    new_len = jnp.sum(active.astype(jnp.int32))
    new_env = {}
    for name, (d, v) in env.items():
        new_env[name] = (d[order], v[order] if v is not None else None)
    return new_env, new_len


# --------------------------------------------------------------------------
# compiled-program cache
# --------------------------------------------------------------------------


class ProgramCache:
    """(program fp, signature, capacity) -> jitted fn. Pattern-cache
    analog; entries draw on the process-wide live-executable budget
    (`ops/exec_cache.py`)."""

    def __init__(self):
        from ydb_tpu.ops.exec_cache import ExecCache
        self._cache = ExecCache("program")
        self.hits = 0
        self.misses = 0

    def get(self, program: ir.Program, sig, cap, param_names):
        key = (program.fingerprint(), sig, cap, param_names)
        fn = self._cache.get(key)
        if fn is None:
            self.misses += 1
            fn = self._build(program, sig, cap)
            self._cache[key] = fn
        else:
            self.hits += 1
        return fn

    @staticmethod
    def _build(program: ir.Program, sig, cap):
        in_cols = [Column(name, DType(Kind(kind), nullable))
                   for (name, kind, nullable) in sig]

        @partial(jax.jit, static_argnames=())
        def fn(arrays, valids, length, params):
            env = {}
            for c in in_cols:
                env[c.name] = (arrays[c.name], valids.get(c.name))
            env, length, sel, schema = _trace_program(
                program, in_cols, cap, env, length, params)
            if sel is not None:  # statically known: no Filter → already compact
                out_cap = next(iter(env.values()))[0].shape[0] if env else cap
                env, length = compress(env, length, sel, out_cap)
            out_d = {nm: env[nm][0] for nm in schema.names}
            out_v = {nm: env[nm][1] for nm in schema.names if env[nm][1] is not None}
            return out_d, out_v, length

        return fn


_GLOBAL_CACHE = ProgramCache()


@partial(jax.jit, static_argnames=("names",))
def _compress_jit(arrays, valids, length, sel, names):
    env = {n: (arrays[n], valids.get(n)) for n in names}
    cap = arrays[names[0]].shape[0]
    env, new_len = compress(env, length, sel, cap)
    out_d = {n: env[n][0] for n in names}
    out_v = {n: env[n][1] for n in names if env[n][1] is not None}
    return out_d, out_v, new_len


def compress_block(dblock: DeviceBlock, sel) -> DeviceBlock:
    """Apply a selection mask, compacting survivors to the block front."""
    names = tuple(dblock.schema.names)
    out_d, out_v, new_len = _compress_jit(
        dblock.arrays, dblock.valids, dblock.length, sel, names)
    return DeviceBlock(dblock.schema, out_d, out_v, new_len, dblock.capacity,
                       dict(dblock.dictionaries))


def run_on_device(program: ir.Program, dblock: DeviceBlock,
                  params: Optional[dict] = None,
                  cache: Optional[ProgramCache] = None) -> DeviceBlock:
    """Run a compiled program over a device-resident block."""
    cache = cache or _GLOBAL_CACHE
    params = params or {}
    dev_params = {k: (jnp.asarray(v) if isinstance(v, np.ndarray) else v)
                  for k, v in params.items()}
    fn = cache.get(program, dblock.sig(), dblock.capacity,
                   tuple(sorted(params.keys())))
    out_d, out_v, length = fn(dblock.arrays, dblock.valids, dblock.length,
                              dev_params)
    out_schema = ir.infer_schema(program, dblock.schema)
    dicts = {n: d for n, d in dblock.dictionaries.items() if out_schema.has(n)}
    out_cap = (next(iter(out_d.values())).shape[0] if out_d
               else dblock.capacity)
    return DeviceBlock(out_schema, out_d, out_v, length, out_cap, dicts)


def run_program(program: ir.Program, block: HostBlock,
                params: Optional[dict] = None,
                cache: Optional[ProgramCache] = None) -> HostBlock:
    """Host-convenience entry: pad → device → compiled program → HostBlock."""
    return to_host(run_on_device(program, to_device(block), params, cache))
