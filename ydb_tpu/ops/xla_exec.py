"""XLA lowering of SSA programs — the TPU data plane.

Each (program, input-signature, capacity-bucket) pair compiles once to a
single fused XLA computation via ``jax.jit`` and is cached — the analog of
the reference's MiniKQL pattern cache (compile-once, run-per-block,
`ydb/library/yql/minikql/computation/mkql_computation_pattern_cache.h:56`)
with XLA playing the role of the LLVM codegen path
(`ydb/library/yql/minikql/codegen/`).

Design constraints honored for the TPU:
  * static shapes only — blocks are padded to power-of-two capacity
    buckets; the true row count rides as a traced scalar and every
    reduction masks by ``iota < length``;
  * no data-dependent control flow — filters keep selection masks
    (`TColumnFilter` semantics) instead of gathering;
  * GroupBy avoids scatter ops: global aggregates are plain masked
    reductions; bounded key domains use a chunked one-hot 2-D reduction
    (an MXU/VPU-friendly "aggregation as reduction over buckets");
    unbounded domains sort (keys + row-id only — wide multi-operand
    sorts explode XLA compile time) and aggregate with cumulative-sum
    differences at segment boundaries;
  * f64 accumulation for SQL sum semantics (TPU emulates f64; precision
    verified against the numpy oracle in tests).

Measured platform note (tunneled single-chip TPU, see PERF.md): after the
first device→host readout in a process, every dispatch pays a large fixed
latency and each *scatter* op (`segment_sum`, `.at[].set/add`) pays ~70-100ms
extra, while gathers / sorts / cumsums / reductions stay at base cost. The
operator designs here (and the whole-query fusion in
`ydb_tpu/ops/fused.py`) exist to keep a query at one dispatch with zero
scatters in the steady state.
"""

from __future__ import annotations

import os
import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.dtypes import DType, Kind
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import ir
from ydb_tpu.ops.device import DeviceBlock, bucket_capacity, to_device, to_host
from ydb_tpu.ops.kernels import KERNELS


# --------------------------------------------------------------------------
# traced helpers
# --------------------------------------------------------------------------


def _sort_operand(x):
    """A lax.sort-comparable operand for a key column, in its natural domain.

    No bitcast tricks: the TPU x64 emulation pass cannot rewrite
    f64<->s64 bitcasts, and ``lax.sort`` already provides a total order for
    float and unsigned operands natively."""
    if x.dtype in (jnp.float64, jnp.float32, jnp.uint64):
        return x
    if x.dtype == jnp.bool_:
        return x.astype(jnp.int32)
    return x.astype(jnp.int64)


def _zero_like_operand(x):
    return jnp.zeros((), x.dtype)


def _eval(expr, env, params, cap):
    if isinstance(expr, ir.Col):
        return env[expr.name]
    if isinstance(expr, ir.Const):
        return jnp.full((cap,), expr.value, dtype=expr.dtype.np), None
    if isinstance(expr, ir.Param):
        val = params[expr.name]
        if expr.is_array:
            return val, None
        return jnp.full((cap,), val, dtype=expr.dtype.np), None
    if isinstance(expr, ir.Call):
        k = KERNELS[expr.op]
        args = [_eval(a, env, params, cap) for a in expr.args]
        extra = expr.extra_dict()
        if k.null_mode == "custom":
            return k.impl_nv(jnp, args, extra)
        data = k.impl(jnp, [a[0] for a in args], extra)
        valid = None
        for _, v in args:
            if v is not None:
                valid = v if valid is None else (valid & v)
        return data, valid
    raise TypeError(f"bad expr {expr!r}")


_F64_MIN, _F64_MAX = -np.inf, np.inf


def _sentinel(dtype, for_min: bool):
    if np.issubdtype(dtype, np.floating):
        return np.array(np.inf if for_min else -np.inf, dtype=dtype)
    info = np.iinfo(dtype)
    return np.array(info.max if for_min else info.min, dtype=dtype)


_SMALL_DOMAIN_BUCKETS = 1 << 9     # one-hot 2-D reduction path bound
_CHUNK_W = 64                      # buckets per one-hot chunk
_SCATTER_MAX_BUCKETS = 1 << 16    # medium-domain single-scatter path bound


# --------------------------------------------------------------------------
# sorted group-by tuning + trace-time instrumentation
# --------------------------------------------------------------------------


def groupby_tuning() -> tuple:  # lint: tuning-provider
    """(tile_rows, batch_cap, legacy, bounds) resolved from the environment.

    * YDB_TPU_GROUPBY_TILE_ROWS — value-column gathers inside the sorted
      group-by split into tiles of at most this many rows (default 4M:
      the largest size at which 2-D gathers compile on the platform's
      remote TPU compiler — PERF.md round-5/8; tiny values force many
      tiles for tests);
    * YDB_TPU_GATHER_BATCH_CAP — per-dtype batched (multi-column 2-D)
      gathers are emitted only while a tile is at most this many rows;
      0 disables batching entirely (per-column gathers, byte-identical
      results);
    * YDB_TPU_GROUPBY_LEGACY — any non-empty value other than "0" routes
      to the pre-round-8 early-materializing lowering (A/B lever for the
      CI gather-budget gate).

    * YDB_TPU_BOUNDS — the bounds-lattice lever (`query/bounds.py`):
      plans carry structurally different GroupBys (carry keys,
      out_bounds) per setting, and the lever riding here puts it in
      every compiled-program cache key by construction.

    * YDB_TPU_LATE_MAT — the late-materialization lever
      (`late_mat_enabled`): fused traces thread row-id vectors instead
      of payload columns and may carry a bound-sized `ir.Compact`;
      riding here keys every compiled program on the lever, so a flip
      recompiles instead of serving a deferral-shaped trace.

    The tuple is a component of every compiled-program cache key
    (ProgramCache, fused/tile/finalize/dist-agg keys), so flipping a knob
    recompiles instead of serving a trace built under other settings."""
    from ydb_tpu.query.bounds import bounds_enabled

    def _int(name: str, default: int) -> int:
        try:
            return int(os.environ.get(name, "") or default)
        except ValueError:
            return default
    tile_rows = max(_int("YDB_TPU_GROUPBY_TILE_ROWS", 1 << 22), 8)
    batch_cap = max(_int("YDB_TPU_GATHER_BATCH_CAP", 1 << 22), 0)
    legacy = os.environ.get("YDB_TPU_GROUPBY_LEGACY", "") not in ("", "0")
    return (tile_rows, batch_cap, legacy, bounds_enabled(),
            late_mat_enabled())


def late_mat_enabled() -> bool:  # lint: tuning-provider
    """YDB_TPU_LATE_MAT — default ON. The late-materialization lever:
    the fused path carries compact row-id vectors instead of payload
    columns through the byte-heavy middle of a plan (probe gathers
    defer to their first reference or to a bound-sized tail gather) and
    compacts intermediates to ladder-quantized bounds (`ir.Compact`).
    `=0` restores the eager-gather path byte-equal (the A/B lever for
    `scripts/latemat_gate.py`); it rides every affected compiled-program
    cache key via `groupby_tuning`."""
    return os.environ.get("YDB_TPU_LATE_MAT", "") not in ("0",)


class _TraceStats(threading.local):
    """Per-thread accumulator of trace-time group-by/sort op counts —
    the engine snapshots it per statement into QueryStats (EXPLAIN
    ANALYZE); the same increments also land on the process /counters
    registry under groupby/* and sort/*. Counts accrue at TRACE time:
    a compile-cache hit re-runs no tracing, so deltas are only visible
    for freshly compiled shapes (exactly what the CI gate wants)."""

    def __init__(self):
        self.stats: dict = {}


_TRACE = _TraceStats()


def groupby_trace_reset() -> None:
    _TRACE.stats = {}


def groupby_trace_snapshot() -> dict:
    return dict(_TRACE.stats)


def groupby_trace_mark() -> dict:
    """Opaque marker for a delta window (`groupby_trace_delta`). The
    engine brackets each statement with mark/delta instead of
    reset/snapshot: the thread-local is never cleared mid-statement, so
    a NESTED statement on the same thread (the DQ router's merge stage
    re-enters `engine.query`) cannot wipe the outer statement's window —
    its traces simply fold into the outer delta."""
    return dict(_TRACE.stats)


def groupby_trace_fold(delta: dict) -> None:
    """Fold a trace delta captured on ANOTHER thread into this thread's
    window. The compile-ahead lane builds (traces) fused programs on a
    background worker, so the build-time gauges land in that thread's
    accumulator; the statement that consumes the warmed entry folds the
    parked delta here so its EXPLAIN ANALYZE / QueryStats window reports
    the build it triggered — without this, a warmed statement looks like
    it traced nothing."""
    st = _TRACE.stats
    for k, v in delta.items():
        if k.endswith("_max"):
            if v > st.get(k, -1):
                st[k] = v
        else:
            st[k] = st.get(k, 0) + v


def groupby_trace_delta(mark: dict) -> dict:
    """Trace activity since `mark`: counters subtract; `*_max` high
    watermarks report their current value only if raised inside the
    window (a statement that traced nothing yields {})."""
    out = {}
    for k, v in _TRACE.stats.items():
        if k.endswith("_max"):
            if v > mark.get(k, -1):
                out[k] = v
        else:
            d = v - mark.get(k, 0)
            if d:
                out[k] = d
    return out


def _t_inc(name: str, by: int = 1, ns: str = "groupby") -> None:
    from ydb_tpu.utils.metrics import GLOBAL
    _TRACE.stats[name] = _TRACE.stats.get(name, 0) + by
    # lint: allow-counters(groupby/* + sort/* trace names, all registered)
    GLOBAL.inc(f"{ns}/{name}", by)


def _t_max(name: str, value: int, ns: str = "groupby") -> None:
    from ydb_tpu.utils.metrics import GLOBAL
    if value > _TRACE.stats.get(name, -1):
        _TRACE.stats[name] = value
    # lint: allow-counters(groupby/* + sort/* trace names, all registered)
    GLOBAL.set_max(f"{ns}/{name}", value)


def _b_inc(name: str, by: int = 1) -> None:
    """Bounds-lattice trace counter: lands on /counters under bounds/*
    and in the per-statement trace window under a `bounds_` prefix (the
    engine splits the delta into stats.groupby vs stats.bounds)."""
    from ydb_tpu.utils.metrics import GLOBAL
    key = "bounds_" + name
    _TRACE.stats[key] = _TRACE.stats.get(key, 0) + by
    # lint: allow-counters(bounds/* trace names, all registered)
    GLOBAL.inc(f"bounds/{name}", by)


def _count_gather(rows: int, tile_budget: int, value: bool = False,
                  batched: bool = False, ops: int = 1) -> None:
    """Record `ops` traced gather ops of `rows` output rows each.

    `groupby/gather_ops` counts only gathers ABOVE the tile-row budget —
    the ~30 ms full-capacity ops the tiled/late-materialized lowering
    exists to eliminate (each such op on the measured platform costs the
    same as a whole tile's batch). `gather_ops_total` counts everything."""
    _t_inc("gather_ops_total", ops)
    if rows > tile_budget:
        _t_inc("gather_ops", ops)
    if batched:
        _t_inc("batched_gathers", ops)
    if value:
        _t_max("value_gather_rows_max", rows)


def record_sort(rows: int, operands: int) -> None:
    """Called from every multi-operand device sort lowering (group-by and
    ORDER BY alike): high-watermark of rows and operand count — the two
    axes of the lax.sort compile cliff (PERF.md)."""
    _t_max("rows_max", rows, ns="sort")
    _t_max("operands_max", operands, ns="sort")


def _acc_dtype(d):
    if np.issubdtype(np.dtype(d.dtype), np.floating):
        return jnp.float64
    if d.dtype == jnp.uint64:
        return jnp.uint64
    return jnp.int64


def _groupby_global(cmd: ir.GroupBy, env, active, iota):
    """Keyless GROUP BY: plain masked reductions — one output row, no sort,
    no scatter (the BlockCombineAll analog, `mkql_block_agg.cpp`)."""
    new_env = {}
    for a in cmd.aggs:
        if a.func == "count_all":
            data = jnp.sum(active.astype(jnp.uint64))
            new_env[a.out] = (data[None], None)
            continue
        d, v = env[a.arg]
        m = active if v is None else (active & v)
        if a.func == "count":
            new_env[a.out] = (jnp.sum(m.astype(jnp.uint64))[None], None)
            continue
        any_valid = jnp.any(m)[None]
        if a.func == "sum":
            data = jnp.sum(jnp.where(m, d, 0).astype(_acc_dtype(d)))[None]
            new_env[a.out] = (data, any_valid)
        elif a.func in ("min", "max"):
            sent = _sentinel(np.dtype(d.dtype), a.func == "min")
            red = jnp.min if a.func == "min" else jnp.max
            data = red(jnp.where(m, d, sent))[None]
            data = jnp.where(any_valid, data, jnp.zeros((), d.dtype))
            new_env[a.out] = (data, any_valid)
        elif a.func == "some":
            firstpos = jnp.min(jnp.where(m, iota, len(iota)))
            data = d[jnp.clip(firstpos, 0, len(iota) - 1)][None]
            new_env[a.out] = (data, any_valid)
        else:
            raise ValueError(a.func)
    return new_env, jnp.int32(1)


def _bucket_ids(cmd: ir.GroupBy, env, cap):
    """Mixed-radix bucket id per row for bounded key domains (+1 slot per
    key for NULL)."""
    kid = jnp.zeros((cap,), jnp.int32)
    stride = 1
    strides = []
    for kname, dom in zip(cmd.keys, cmd.key_domains):
        d, v = env[kname]
        code = d.astype(jnp.int32) + 1          # -1 (null string code) → 0
        if v is not None:
            code = jnp.where(v, code, 0)        # SQL: one NULL group
        code = jnp.clip(code, 0, dom)
        kid = kid + code * stride
        strides.append(stride)
        stride *= dom + 1
    return kid, stride, strides


def _groupby_small_domain(cmd: ir.GroupBy, env, schema: Schema, sel,
                          length, cap):
    """Bounded-domain aggregation as a chunked one-hot 2-D reduction — the
    BlockCombineHashed analog (`mkql_block_agg.cpp`) built entirely from
    elementwise ops + axis-0 reductions (XLA fuses the one-hot expansion
    into the reduction; nothing materializes, nothing scatters)."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = (iota < length) if sel is None else ((iota < length) & sel)
    kid, nbuckets, strides = _bucket_ids(cmd, env, cap)

    chunks: dict[str, list] = {a.out: [] for a in cmd.aggs}
    valid_chunks: dict[str, list] = {}
    present_chunks = []
    first_chunks = []                  # leader row per bucket (carry keys)
    for c0 in range(0, nbuckets, _CHUNK_W):
        w = min(_CHUNK_W, nbuckets - c0)
        ids = c0 + jnp.arange(w, dtype=jnp.int32)
        oh = (kid[:, None] == ids[None, :]) & active[:, None]
        present_chunks.append(jnp.any(oh, axis=0))
        if cmd.carry_keys:
            first_chunks.append(
                jnp.min(jnp.where(oh, iota[:, None], cap), axis=0))
        for a in cmd.aggs:
            if a.func == "count_all":
                chunks[a.out].append(jnp.sum(oh.astype(jnp.uint64), axis=0))
                continue
            d, v = env[a.arg]
            m = oh if v is None else (oh & v[:, None])
            if a.func == "count":
                chunks[a.out].append(jnp.sum(m.astype(jnp.uint64), axis=0))
                continue
            any_valid = jnp.any(m, axis=0)
            valid_chunks.setdefault(a.out, []).append(any_valid)
            if a.func == "sum":
                acc = jnp.where(m, d[:, None], 0).astype(_acc_dtype(d))
                chunks[a.out].append(jnp.sum(acc, axis=0))
            elif a.func in ("min", "max"):
                sent = _sentinel(np.dtype(d.dtype), a.func == "min")
                red = jnp.min if a.func == "min" else jnp.max
                data = red(jnp.where(m, d[:, None], sent), axis=0)
                chunks[a.out].append(
                    jnp.where(any_valid, data, jnp.zeros((), d.dtype)))
            elif a.func == "some":
                firstpos = jnp.min(jnp.where(m, iota[:, None], cap), axis=0)
                chunks[a.out].append(d[jnp.clip(firstpos, 0, cap - 1)])
            else:
                raise ValueError(a.func)

    new_env = {}
    for a in cmd.aggs:
        data = jnp.concatenate(chunks[a.out])
        v = valid_chunks.get(a.out)
        new_env[a.out] = (data, jnp.concatenate(v) if v is not None else None)
    present = jnp.concatenate(present_chunks)
    firstpos = jnp.concatenate(first_chunks) if first_chunks else None
    return _emit_bucket_groups(cmd, env, schema, new_env, present, nbuckets,
                               strides, cap, firstpos)


def _emit_bucket_groups(cmd: ir.GroupBy, env, schema: Schema, new_env,
                        present, nbuckets, strides, cap, firstpos=None):
    """Shared bounded-domain epilogue: rebuild key columns from bucket ids,
    then compact non-empty buckets to the front of a SMALL capacity bucket
    (compress sorts; doing it over the scan capacity would cost a full
    cap-sized argsort for a handful of groups). `firstpos`: leader row id
    per bucket, required when the command carries functionally-determined
    keys (their per-group value gathers from the leader row)."""
    _b_inc("proven_rows", bucket_capacity(nbuckets, minimum=128))
    _b_inc("capacity_rows", cap)
    _b_inc("bounded_groupbys")
    if cmd.carry_keys:
        _b_inc("carried_keys", len(cmd.carry_keys))
    bucket_ids = jnp.arange(nbuckets, dtype=jnp.int32)
    for kname, dom, st in zip(cmd.keys, cmd.key_domains, strides):
        code = (bucket_ids // st) % (dom + 1) - 1
        d, _v = env[kname]
        kd = code.astype(jnp.int32).astype(d.dtype)
        kv = code >= 0
        dt = schema.dtype(kname)
        new_env[kname] = (kd, kv if dt.nullable else None)
    for kname in cmd.carry_keys:
        d, v = env[kname]
        safe = jnp.clip(firstpos, 0, cap - 1)
        kd = d[safe]
        dt = schema.dtype(kname)
        if dt.nullable:
            kv = (v[safe] if v is not None
                  else jnp.ones((nbuckets,), jnp.bool_))
            new_env[kname] = (kd, kv & present)
        else:
            new_env[kname] = (kd, None)

    out_cap = bucket_capacity(nbuckets, minimum=128)
    pad = out_cap - nbuckets
    padded = {}
    for name, (d, v) in new_env.items():
        dp = jnp.pad(d, (0, pad)) if pad > 0 else d[:out_cap]
        vp = None
        if v is not None:
            vp = jnp.pad(v, (0, pad)) if pad > 0 else v[:out_cap]
        padded[name] = (dp, vp)
    present_p = jnp.pad(present, (0, pad)) if pad > 0 else present[:out_cap]
    return compress(padded, jnp.int32(nbuckets), present_p, out_cap)


def _groupby_medium_domain(cmd: ir.GroupBy, env, schema: Schema, sel,
                           length, cap):
    """Bounded domains too wide for the one-hot path: one scatter-reduce
    per aggregate into a bucket array (each scatter pays the platform's
    post-readout scatter tax exactly once per aggregate)."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = (iota < length) if sel is None else ((iota < length) & sel)
    kid, nbuckets, strides = _bucket_ids(cmd, env, cap)
    seg_safe = jnp.where(active, kid, nbuckets)
    nseg = nbuckets + 1                         # +1 garbage bucket

    new_env = {}
    for a in cmd.aggs:
        if a.func == "count_all":
            data = jax.ops.segment_sum(active.astype(jnp.uint64), seg_safe,
                                       nseg)
            new_env[a.out] = (data[:nbuckets], None)
            continue
        d, v = env[a.arg]
        m = active if v is None else (active & v)
        if a.func == "count":
            data = jax.ops.segment_sum(m.astype(jnp.uint64), seg_safe, nseg)
            new_env[a.out] = (data[:nbuckets], None)
            continue
        cnt = jax.ops.segment_sum(m.astype(jnp.int32), seg_safe, nseg)
        any_valid = (cnt > 0)[:nbuckets]
        if a.func == "sum":
            acc = jnp.where(m, d, 0).astype(_acc_dtype(d))
            data = jax.ops.segment_sum(acc, seg_safe, nseg)[:nbuckets]
            new_env[a.out] = (data, any_valid)
        elif a.func in ("min", "max"):
            sent = _sentinel(np.dtype(d.dtype), a.func == "min")
            masked = jnp.where(m, d, sent)
            fn = jax.ops.segment_min if a.func == "min" else jax.ops.segment_max
            data = fn(masked, seg_safe, nseg)[:nbuckets]
            data = jnp.where(any_valid, data, jnp.zeros((), d.dtype))
            new_env[a.out] = (data, any_valid)
        elif a.func == "some":
            pos = jnp.where(m, iota, cap)
            firstpos = jax.ops.segment_min(pos, seg_safe, nseg)[:nbuckets]
            data = d[jnp.clip(firstpos, 0, cap - 1)]
            new_env[a.out] = (data, any_valid)
        else:
            raise ValueError(a.func)

    present = jax.ops.segment_sum(active.astype(jnp.int32), seg_safe,
                                  nseg)[:nbuckets] > 0
    firstpos = None
    if cmd.carry_keys:
        pos = jnp.where(active, iota, cap)
        firstpos = jax.ops.segment_min(pos, seg_safe, nseg)[:nbuckets]
    return _emit_bucket_groups(cmd, env, schema, new_env, present, nbuckets,
                               strides, cap, firstpos)


def _gather_sorted(cols: dict, perm, cap: int, tiles: int, tile_budget: int,
                   batch_cap: int) -> dict:
    """Materialize env columns in key-sorted order: the ONLY place value
    columns are gathered at row-level granularity on the sorted path.

    Tiled: the permutation splits into `tiles` static slices so no single
    gather op exceeds cap/tiles rows — below the platform's ~4M 2-D-gather
    compiler wedge (PERF.md round-5), which also re-unlocks the reverted
    per-dtype BATCHED gather: all requested columns of one dtype fold into
    one (m, tile) gather per tile (measured cost of a 2-8 column 2-D
    gather equals ONE column's). `batch_cap` gates the batch by tile rows;
    0 disables it (per-column gathers — byte-identical results)."""
    T = cap // tiles
    by_dt: dict = {}
    for name, arr in cols.items():
        by_dt.setdefault(str(arr.dtype), []).append(name)
    out = {}
    for _dt, names in by_dt.items():
        arrs = [cols[n] for n in names]
        m = len(arrs)
        if batch_cap > 0 and m > 1 and T <= batch_cap:
            stacked = jnp.stack(arrs)                    # (m, cap)
            pieces = [stacked[:, perm[p * T:(p + 1) * T]]
                      for p in range(tiles)]             # (m, T) each
            _count_gather(T, tile_budget, value=True, batched=True,
                          ops=tiles)
            full = jnp.concatenate(pieces, axis=1) if tiles > 1 \
                else pieces[0]
            for i, n in enumerate(names):
                out[n] = full[i]
        else:
            for n, arr in zip(names, arrs):
                pieces = [arr[perm[p * T:(p + 1) * T]]
                          for p in range(tiles)]
                _count_gather(T, tile_budget, value=True, ops=tiles)
                out[n] = jnp.concatenate(pieces) if tiles > 1 else pieces[0]
    return out


def _csum_diffs(per_rows: list, starts, ends, oc: int, tile_budget: int,
                batch_cap: int) -> list:
    """Per-group sums of sorted per-row arrays via cumulative-sum
    endpoints, evaluated at OUTPUT capacity: diff = c[end] − c[start] +
    v[start]. The cumsums stay 1-D (cheap on the platform; only 2-D ones
    wedge); the endpoint gathers batch per accumulation dtype — one
    (m, oc) gather triple instead of 3 gathers per aggregate. `batch_cap`
    gates the batch by oc exactly as `_gather_sorted` gates by tile rows:
    with no proven out_bound oc == scan capacity, and an (m, cap) 2-D
    gather is the ~4M compiler-wedge shape this module exists to avoid."""
    out: list = [None] * len(per_rows)
    groups: dict = {}
    for i, pr in enumerate(per_rows):
        groups.setdefault(str(pr.dtype), []).append(i)
    for _dt, idxs in groups.items():
        csums = [jnp.cumsum(per_rows[i]) for i in idxs]
        if batch_cap > 0 and len(idxs) > 1 and oc <= batch_cap:
            cs = jnp.stack(csums)                        # (m, cap)
            fs = jnp.stack([per_rows[i] for i in idxs])
            ce, cst, f0 = cs[:, ends], cs[:, starts], fs[:, starts]
            _count_gather(oc, tile_budget, batched=True, ops=3)
            for k, i in enumerate(idxs):
                out[i] = ce[k] - cst[k] + f0[k]
        else:
            for c, i in zip(csums, idxs):
                out[i] = c[ends] - c[starts] + per_rows[i][starts]
                _count_gather(oc, tile_budget, ops=3)
    return out


def _segment_scan(vals, boundary, kind: str):
    """Running min/max within key segments of a sorted block: an
    associative scan over (value, segment-start flag) pairs — log-depth
    elementwise, NO scatter (the legacy path paid one ~70-100 ms
    scatter-reduce per min/max aggregate, the platform's most taxed op
    class). Read at segment END positions it yields the whole-segment
    reduction."""
    combine = jnp.minimum if kind == "min" else jnp.maximum

    def op(a, b):
        av, ab = a
        bv, bb = b
        return (jnp.where(bb, bv, combine(av, bv)), ab | bb)

    out, _flags = jax.lax.associative_scan(op, (vals, boundary))
    return out


def _trace_group_by_sorted(cmd: ir.GroupBy, env, schema: Schema, sel,
                           length, cap):
    """Unbounded-domain aggregation, round-8 shape: ONE key sort, then a
    pre-aggregate → tile → LATE-MATERIALIZE pipeline, still inside one
    dispatch (the WideCombiner workhorse, `mkql_wide_combine.cpp`, in the
    partition-then-combine decomposition of DrJAX, arxiv 2403.07128):

      * the sort carries only key encodings + the row permutation (wide
        multi-operand sorts explode XLA compile time — PERF.md);
      * per-row value materialization (the former 15-20 sequential ~30 ms
        full-capacity gathers) happens tiled at ≤ YDB_TPU_GROUPBY_TILE_ROWS
        rows per op and per-dtype batched (`_gather_sorted`), and ONLY for
        columns that truly need sorted per-row values (sum/min/max data,
        nullable-arg validity);
      * everything per-GROUP — key values, csum endpoints, min/max scan
        reads, `some` values — gathers at OUTPUT capacity: ngroups slots,
        statically bounded by `cmd.out_bound` when the planner/executor
        can prove one (key-domain products, inner-join build cardinality),
        the scan capacity otherwise;
      * min/max/some use a segmented associative scan (`_segment_scan`)
        instead of scatter-reduces — the sorted path is now scatter-FREE.

    `cmd.out_bound` is a PROVEN upper bound on ngroups: an understated
    value would silently drop groups, so only guaranteed sources may set
    it. Precision of csum diffs is unchanged from the legacy path (see
    `_trace_group_by_sorted_legacy`)."""
    tile_budget, batch_cap, legacy, _bounds, _lm = groupby_tuning()
    if legacy:
        return _trace_group_by_sorted_legacy(cmd, env, schema, sel, length,
                                             cap)
    tiles = 1
    while cap // tiles > tile_budget and cap % (tiles * 2) == 0 \
            and cap // tiles > 1:
        tiles *= 2
    _t_inc("traces")
    _t_inc("tiles", tiles)
    _t_max("sort_rows_max", cap)

    iota = jnp.arange(cap, dtype=jnp.int32)
    row_mask = iota < length
    active = row_mask if sel is None else (row_mask & sel)

    inactive = (~active).astype(jnp.int32)
    sort_keys = [inactive]
    for kname in cmd.keys:
        d, v = env[kname]
        enc = _sort_operand(d)
        if v is not None:
            # nullable keys carry a validity operand so NULLs form one
            # group; non-nullable keys contribute only their encoding —
            # a constant all-ones operand sorts nothing, and each
            # operand at scan capacity is real wall time (PERF round-16)
            enc = jnp.where(v, enc, _zero_like_operand(enc))
            sort_keys.append(v.astype(jnp.int32))
        sort_keys.append(enc)
    record_sort(cap, len(sort_keys) + 1)
    # iota as the last key → deterministic total order, and the sort output
    # IS the permutation (no carried operands)
    out = jax.lax.sort(sort_keys + [iota], num_keys=len(sort_keys) + 1)
    inactive_s = out[0]
    keyparts_s = out[1:-1]
    perm = out[-1]

    active_s = inactive_s == 0
    changed = jnp.zeros((cap,), jnp.bool_)
    for kp in keyparts_s:
        prev = jnp.concatenate([kp[:1], kp[:-1]])
        neq = kp != prev
        if np.issubdtype(np.dtype(kp.dtype), np.floating):
            # NaN != NaN would split every NaN row into its own group;
            # lax.sort places NaNs adjacently, so treat them as equal
            neq = neq & ~(jnp.isnan(kp) & jnp.isnan(prev))
        changed = changed | neq
    boundary = active_s & ((iota == 0) | changed)
    ngroups = jnp.sum(boundary.astype(jnp.int32))
    nactive = jnp.sum(active_s.astype(jnp.int32))

    # output capacity: the late-materialization granularity. Everything
    # per-group below gathers at `oc` slots, not scan capacity.
    oc = cap
    if cmd.out_bound:
        oc = min(bucket_capacity(max(int(cmd.out_bound), 1), minimum=128),
                 cap)

    # compact segment-start row indices to the front: starts[i] = sorted-row
    # index where group i begins (argsort = 2-operand sort)
    record_sort(cap, 2)
    starts = jnp.argsort(jnp.where(boundary, iota, jnp.int32(cap))
                         ).astype(jnp.int32)[:oc]
    gi = jnp.arange(oc, dtype=jnp.int32)
    next_start = jnp.concatenate([starts[1:], jnp.full((1,), cap, jnp.int32)])
    # group i ends at the next group's start − 1; the LAST live group ends
    # at nactive − 1. ngroups ≤ oc is guaranteed (out_bound contract), so
    # slicing starts to oc cannot orphan a live group's end.
    ends = jnp.where(gi + 1 < ngroups, next_start - 1, nactive - 1)
    ends = jnp.clip(ends, 0, cap - 1)
    live = gi < ngroups

    # group-leader original row ids: ONE oc-sized gather shared by every
    # late-materialized column (keys, CARRIED keys, `some` values)
    lead = perm[jnp.clip(starts, 0, cap - 1)]
    _count_gather(oc, tile_budget)

    # bounds-lattice gauges: per-group allocation (oc) vs the scan
    # capacity it replaced, and how many grouping columns the carry
    # rewrite kept OUT of the sort identity
    _b_inc("proven_rows", oc)
    _b_inc("capacity_rows", cap)
    if cmd.out_bound:
        _b_inc("bounded_groupbys")
    if cmd.carry_keys:
        _b_inc("carried_keys", len(cmd.carry_keys))

    new_env = {}
    # carried keys materialize EXACTLY like keys — value at the group
    # leader row — their per-group constancy is the carry contract
    for kname in list(cmd.keys) + list(cmd.carry_keys):
        d, v = env[kname]
        kd = d[lead]
        _count_gather(oc, tile_budget)
        dt = schema.dtype(kname)
        if dt.nullable:
            if v is not None:
                kv = v[lead]
                _count_gather(oc, tile_budget)
            else:
                kv = jnp.ones((oc,), jnp.bool_)
            new_env[kname] = (kd, kv & live)
        else:
            new_env[kname] = (kd, None)

    # ---- sorted per-row materialization: only what aggregation truly
    # needs (sum/min/max data; validity of nullable args)
    need_data, need_valid = [], []
    for a in cmd.aggs:
        if a.func == "count_all":
            continue
        if env[a.arg][1] is not None:
            need_valid.append(a.arg)
        if a.func in ("sum", "min", "max"):
            need_data.append(a.arg)
    data_s = _gather_sorted(
        {n: env[n][0] for n in dict.fromkeys(need_data)}, perm, cap, tiles,
        tile_budget, batch_cap)
    valid_s = _gather_sorted(
        {n: env[n][1] for n in dict.fromkeys(need_valid)}, perm, cap, tiles,
        tile_budget, batch_cap)

    # ---- phase 1: register every cumulative-sum job so endpoint gathers
    # batch per dtype across aggregates
    jobs: list = []

    def job(per_row) -> int:
        jobs.append(per_row)
        return len(jobs) - 1

    agg_plan = []
    for a in cmd.aggs:
        if a.func == "count_all":
            agg_plan.append(("count", a, job(active_s.astype(jnp.uint64)),
                             None, None))
            continue
        v = valid_s.get(a.arg)
        m = active_s if v is None else (active_s & v)
        if a.func == "count":
            agg_plan.append(("count", a, job(m.astype(jnp.uint64)), None,
                             None))
            continue
        cnt_j = job(m.astype(jnp.int64))
        if a.func == "sum":
            d = data_s[a.arg]
            acc = jnp.where(m, d, 0).astype(_acc_dtype(d))
            agg_plan.append(("sum", a, job(acc), cnt_j, None))
        elif a.func in ("min", "max", "some"):
            agg_plan.append((a.func, a, None, cnt_j, m))
        else:
            raise ValueError(a.func)

    diffs = _csum_diffs(jobs, starts, ends, oc, tile_budget, batch_cap)

    # ---- phase 2: assemble per-group outputs at oc capacity
    for (kind, a, data_j, cnt_j, m) in agg_plan:
        if kind == "count":
            new_env[a.out] = (jnp.where(live, diffs[data_j], 0), None)
            continue
        cnt = diffs[cnt_j]
        any_valid = (cnt > 0) & live
        if kind == "sum":
            new_env[a.out] = (diffs[data_j], any_valid)
        elif kind in ("min", "max"):
            d = data_s[a.arg]
            sent = _sentinel(np.dtype(d.dtype), kind == "min")
            masked = jnp.where(m, d, sent)
            data = _segment_scan(masked, boundary, kind)[ends]
            _count_gather(oc, tile_budget)
            data = jnp.where(any_valid, data, jnp.zeros((), d.dtype))
            new_env[a.out] = (data, any_valid)
        else:  # some: first valid value — late-materialized at oc
            pos = jnp.where(m, iota, cap)
            firstpos = _segment_scan(pos, boundary, "min")[ends]
            _count_gather(oc, tile_budget)
            rowid = perm[jnp.clip(firstpos, 0, cap - 1)]
            _count_gather(oc, tile_budget)
            data = env[a.arg][0][rowid]
            _count_gather(oc, tile_budget)
            new_env[a.out] = (data, any_valid)
    return new_env, ngroups.astype(jnp.int32)


def _trace_group_by_sorted_legacy(cmd: ir.GroupBy, env, schema: Schema, sel,
                                  length, cap):
    """Pre-round-8 sorted aggregation (YDB_TPU_GROUPBY_LEGACY=1): sort
    (keys + row-id only), EARLY value materialization (every key and
    aggregate column gathered at scan capacity), sums/counts via
    cumulative-sum differences, min/max via one scatter-reduce per
    aggregate. Kept as the A/B baseline for the CI gather-budget gate
    and the byte-equality differential tests.

    Precision note: a segment sum is csum[end] − csum[start] + v[start];
    for a tiny group inside a huge total the cancellation costs ~(total /
    group_sum)·1e-16 relative error — acceptable for SQL doubles and the
    test oracles' 1e-6 tolerances."""
    tile_budget, _batch_cap, _legacy, _bounds, _lm = groupby_tuning()
    _t_inc("traces")
    _t_inc("tiles", 1)
    _t_max("sort_rows_max", cap)
    record_sort(cap, 2 * len(cmd.keys) + 2)
    iota = jnp.arange(cap, dtype=jnp.int32)
    row_mask = iota < length
    active = row_mask if sel is None else (row_mask & sel)

    inactive = (~active).astype(jnp.int32)
    sort_keys = [inactive]
    for kname in cmd.keys:
        d, v = env[kname]
        enc = _sort_operand(d)
        if v is not None:
            enc = jnp.where(v, enc, _zero_like_operand(enc))
            sort_keys.append(v.astype(jnp.int32))
        else:
            sort_keys.append(jnp.ones((cap,), jnp.int32))
        sort_keys.append(enc)
    # iota as the last key → deterministic total order, and the sort output
    # IS the permutation (no carried operands)
    out = jax.lax.sort(sort_keys + [iota], num_keys=len(sort_keys) + 1)
    inactive_s = out[0]
    keyparts_s = out[1:-1]
    perm = out[-1]

    env_s = {}

    def sorted_col(name):
        got = env_s.get(name)
        if got is None:
            d, v = env[name]
            _count_gather(cap, tile_budget, value=True,
                          ops=1 if v is None else 2)
            got = (d[perm], v[perm] if v is not None else None)
            env_s[name] = got
        return got

    active_s = inactive_s == 0
    changed = jnp.zeros((cap,), jnp.bool_)
    for kp in keyparts_s:
        prev = jnp.concatenate([kp[:1], kp[:-1]])
        neq = kp != prev
        if np.issubdtype(np.dtype(kp.dtype), np.floating):
            # NaN != NaN would split every NaN row into its own group;
            # lax.sort places NaNs adjacently, so treat them as equal
            neq = neq & ~(jnp.isnan(kp) & jnp.isnan(prev))
        changed = changed | neq
    boundary = active_s & ((iota == 0) | changed)
    ngroups = jnp.sum(boundary.astype(jnp.int32))
    nactive = jnp.sum(active_s.astype(jnp.int32))

    # compact segment-start row indices to the front: starts[i] = sorted-row
    # index where group i begins
    record_sort(cap, 2)
    starts = jnp.argsort(jnp.where(boundary, iota, jnp.int32(cap))
                         ).astype(jnp.int32)
    gi = jnp.arange(cap, dtype=jnp.int32)
    next_start = jnp.concatenate([starts[1:], jnp.full((1,), cap, jnp.int32)])
    ends = jnp.where(gi + 1 < ngroups, next_start - 1, nactive - 1)
    ends = jnp.clip(ends, 0, cap - 1)
    live = gi < ngroups

    new_env = {}
    for kname in list(cmd.keys) + list(cmd.carry_keys):
        d, v = sorted_col(kname)
        kd = d[starts]
        _count_gather(cap, tile_budget)
        dt = schema.dtype(kname)
        if dt.nullable:
            if v is not None:
                kv = v[starts]
                _count_gather(cap, tile_budget)
            else:
                kv = jnp.ones((cap,), jnp.bool_)
            new_env[kname] = (kd, kv & live)
        else:
            new_env[kname] = (kd, None)

    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_safe = jnp.where(active_s, seg, cap)

    def csum_diff(per_row):
        """Per-group sum of a sorted per-row array via cumsum endpoints."""
        c = jnp.cumsum(per_row)
        first = per_row[starts]
        _count_gather(cap, tile_budget, ops=3)
        return c[ends] - c[starts] + first

    for a in cmd.aggs:
        if a.func == "count_all":
            data = csum_diff(active_s.astype(jnp.uint64))
            new_env[a.out] = (jnp.where(live, data, 0), None)
            continue
        d, v = sorted_col(a.arg)
        m = active_s if v is None else (active_s & v)
        if a.func == "count":
            data = csum_diff(m.astype(jnp.uint64))
            new_env[a.out] = (jnp.where(live, data, 0), None)
            continue
        cnt = csum_diff(m.astype(jnp.int64))
        any_valid = (cnt > 0) & live
        if a.func == "sum":
            acc = jnp.where(m, d, 0).astype(_acc_dtype(d))
            new_env[a.out] = (csum_diff(acc), any_valid)
        elif a.func in ("min", "max"):
            sent = _sentinel(np.dtype(d.dtype), a.func == "min")
            masked = jnp.where(m, d, sent)
            init = jnp.full((cap + 1,), sent, d.dtype)
            _t_inc("scatter_ops")
            upd = (init.at[seg_safe].min(masked, mode="drop")
                   if a.func == "min"
                   else init.at[seg_safe].max(masked, mode="drop"))
            data = jnp.where(any_valid, upd[:cap], jnp.zeros((), d.dtype))
            new_env[a.out] = (data, any_valid)
        elif a.func == "some":
            # first valid value in the segment: rows are key-then-row-id
            # sorted, so scan for the first m-true position per segment
            pos = jnp.where(m, iota, cap)
            init = jnp.full((cap + 1,), cap, jnp.int32)
            _t_inc("scatter_ops")
            firstpos = init.at[seg_safe].min(pos, mode="drop")[:cap]
            data = d[jnp.clip(firstpos, 0, cap - 1)]
            _count_gather(cap, tile_budget)
            new_env[a.out] = (data, any_valid)
        else:
            raise ValueError(a.func)
    return new_env, ngroups.astype(jnp.int32)


def _trace_group_by(cmd: ir.GroupBy, env, schema: Schema, sel, length, cap):
    """GroupBy dispatch: keyless → plain reductions; small bounded domains →
    one-hot 2-D reduction; medium bounded → scatter-reduce; unbounded →
    sort-based. Returns (new_env, new_length)."""
    if not cmd.keys:
        iota = jnp.arange(cap, dtype=jnp.int32)
        active = (iota < length) if sel is None else ((iota < length) & sel)
        return _groupby_global(cmd, env, active, iota)
    if cmd.key_domains and all(d > 0 for d in cmd.key_domains):
        nb = 1
        for d in cmd.key_domains:
            nb *= d + 1
        if nb <= _SMALL_DOMAIN_BUCKETS:
            return _groupby_small_domain(cmd, env, schema, sel, length, cap)
        if nb + 1 <= _SCATTER_MAX_BUCKETS:
            return _groupby_medium_domain(cmd, env, schema, sel, length, cap)
    return _trace_group_by_sorted(cmd, env, schema, sel, length, cap)


def _trace_program(program: ir.Program, in_schema_cols, cap, env, length,
                   params, sel=None, aux=None, passthrough=()):
    """env: name -> (data, valid|None); returns (env, length, sel, schema).
    `sel` seeds the selection mask (fused pipelines thread it between
    programs instead of compressing).

    `aux`: out-of-band scalar box filled by `ir.Compact` (live count +
    overflow flag — the executor's loud-rerun input; scalars cannot ride
    the row-shaped env). `passthrough`: helper column names (the fused
    late-materialization row-id vectors) that survive Projections and
    whose projected names may be ABSENT from env (deferred columns stay
    deferred through a projection); callers that pass no passthrough
    keep the strict behavior."""
    schema = Schema(list(in_schema_cols))
    for cmd in program.commands:
        if isinstance(cmd, ir.Assign):
            data, valid = _eval(cmd.expr, env, params, cap)
            env[cmd.name] = (data, valid)
            dt = ir.infer_expr(cmd.expr, schema)
            schema = Schema([c for c in schema.columns if c.name != cmd.name]
                            + [Column(cmd.name, dt)])
        elif isinstance(cmd, ir.Filter):
            data, valid = _eval(cmd.pred, env, params, cap)
            mask = data if valid is None else (data & valid)
            sel = mask if sel is None else (sel & mask)
        elif isinstance(cmd, ir.GroupBy):
            env, length = _trace_group_by(cmd, env, schema, sel, length, cap)
            # the scatter path shrinks the working capacity to a small
            # bucket; subsequent commands trace at the new size
            if env:
                cap = next(iter(env.values()))[0].shape[0]
            schema = ir.infer_schema(ir.Program([cmd]), schema)
            sel = None
        elif isinstance(cmd, ir.Projection):
            schema = schema.select(list(cmd.names))
            if passthrough:
                new_env = {nm: env[nm] for nm in cmd.names if nm in env}
                for h in passthrough:
                    if h in env:
                        new_env[h] = env[h]
                env = new_env
            else:
                env = {nm: env[nm] for nm in cmd.names}
        elif isinstance(cmd, ir.Compact):
            env, length, sel, live, ovf = compact_env(env, length, sel,
                                                      cap, cmd.cap)
            cap = cmd.cap
            if aux is not None:
                aux["compact_live"] = live
                aux["compact_ovf"] = ovf
        else:
            raise TypeError(f"bad command {cmd!r}")
    return env, length, sel, schema


def compress(env, length, sel, cap):
    """BlockCompress: compact selected rows to the front (stable).

    Analog of `mkql_block_compress.cpp`. Sort by (dropped, position)."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = (iota < length) if sel is None else ((iota < length) & sel)
    keys = jnp.where(active, iota, jnp.int32(cap))
    order = jnp.argsort(keys)
    new_len = jnp.sum(active.astype(jnp.int32))
    new_env = {}
    for name, (d, v) in env.items():
        new_env[name] = (d[order], v[order] if v is not None else None)
    return new_env, new_len


def compact_env(env, length, sel, cap, new_cap: int):
    """`ir.Compact` lowering: stable-compress selected rows to the front
    of a `new_cap`-sized buffer — downstream operators compile at the
    small shape. O(cap) prefix-sum + dropping scatter, NOT an argsort:
    each live row's target slot is its rank among live rows
    (`cumsum - 1`), dropped/overflow rows scatter out of bounds
    (`mode="drop"`), so the compact costs one pass over the wide
    capacity instead of a sort of it. Returns (env', length', sel',
    live, overflow): `live` is the true selected count and
    `overflow = live > new_cap` — the host-side loud-rerun signal; rows
    beyond `new_cap` ARE dropped from env', so a result produced under
    overflow must be discarded, never served."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = (iota < length) if sel is None else ((iota < length) & sel)
    rank = jnp.cumsum(active.astype(jnp.int32)) - 1
    tgt = jnp.where(active, rank, jnp.int32(new_cap))   # inactive → OOB
    live = jnp.sum(active.astype(jnp.int32))
    ovf = live > jnp.int32(new_cap)

    def _scatter(a):
        return jnp.zeros((new_cap,), a.dtype).at[tgt].set(a, mode="drop")

    new_env = {}
    for name, (d, v) in env.items():
        new_env[name] = (_scatter(d),
                         _scatter(v) if v is not None else None)
    new_len = jnp.minimum(live, jnp.int32(new_cap))
    new_sel = jnp.arange(new_cap, dtype=jnp.int32) < new_len
    return new_env, new_len, new_sel, live, ovf


# --------------------------------------------------------------------------
# compiled-program cache
# --------------------------------------------------------------------------


class ProgramCache:
    """(program fp, signature, capacity) -> jitted fn. Pattern-cache
    analog; entries draw on the process-wide live-executable budget
    (`ops/exec_cache.py`)."""

    def __init__(self):
        from ydb_tpu.ops.exec_cache import ExecCache
        from ydb_tpu.utils import progstats
        self._cache = ExecCache("program")
        # eviction surfaces in the program inventory: the entry persists
        # in `.sys/compiled_programs` marked `evicted`, and a re-compile
        # of the key counts a MISS that re-records compile_ms
        self._cache.on_evict = \
            lambda key: progstats.mark_evicted("program", key)
        self.hits = 0
        self.misses = 0

    def get(self, program: ir.Program, sig, cap, param_names):
        # groupby tuning is part of the identity: a program traced under
        # one tile/batch setting must not serve another (tests flip the
        # env knobs in-process)
        key = (program.fingerprint(), sig, cap, param_names,
               groupby_tuning())
        # observability levers cannot stale a program: they choose how
        # the identical trace is dispatched/recorded, not what it computes
        # lint: allow-cache-key(progstats/memledger/critpath observe only)
        fn = self._cache.get(key)
        if fn is None:
            self.misses += 1
            fn = self._timed_fill(key, self._build(program, sig, cap))
        else:
            self.hits += 1
            from ydb_tpu.utils import progstats
            progstats.record_hit(getattr(fn, "key_id", None))
        return fn

    def _timed_fill(self, key, built):
        """Cache-fill wrapper: jax.jit compiles lazily on the FIRST
        invocation, so the fill stores a thin shim that times that call
        (trace + XLA compile + first run) and records it as this
        program's compile_ms; later calls pay one flag check. With the
        program observatory on (`utils/progstats`, the default), the
        first call compiles via the explicit AOT path instead —
        lower().compile(), ONE trace + ONE compile like the lazy path —
        capturing the executable's cost/memory analysis, and steady-
        state calls dispatch through the AOT handle. The shim delegates
        `clear_cache` to whichever target holds the executable so
        ExecCache eviction releases it (a bare closure would silently
        defeat the release-on-evict lifecycle), and it never overwrites
        the cache entry — an overwrite would spuriously release."""
        import threading as _threading
        import time as _time

        from ydb_tpu.utils import progstats
        timed = [False]
        target = [built]               # swapped to the AOT handle once
        mu = _threading.Lock()

        def shim(*a, **kw):
            if timed[0]:
                return target[0](*a, **kw)
            with mu:
                first = not timed[0]
                timed[0] = True
            if not first:
                # lost the first-call race: don't double-count compiles
                return target[0](*a, **kw)
            from ydb_tpu.utils.metrics import GLOBAL
            t0 = _time.perf_counter()
            if progstats.enabled():
                target[0] = progstats.capture("program", key, built, a)
            out = target[0](*a, **kw)
            ms = (_time.perf_counter() - t0) * 1000.0
            GLOBAL.inc("program_cache/compiles")
            GLOBAL.inc("program_cache/compile_ms", ms)
            return out

        def _clear():
            t = target[0]
            cc = getattr(t, "clear_cache", None)
            if callable(cc):
                cc()                   # the handle clears built too
            if t is not built:
                built.clear_cache()

        shim.clear_cache = _clear
        # the inventory id rides the shim so a later cache HIT can be
        # attributed without re-hashing the key
        shim.key_id = progstats.key_id("program", key) \
            if progstats.enabled() else None
        self._cache[key] = shim
        return shim

    @staticmethod
    def _build(program: ir.Program, sig, cap):
        in_cols = [Column(name, DType(Kind(kind), nullable))
                   for (name, kind, nullable) in sig]

        @partial(jax.jit, static_argnames=())
        def fn(arrays, valids, length, params):
            env = {}
            for c in in_cols:
                env[c.name] = (arrays[c.name], valids.get(c.name))
            env, length, sel, schema = _trace_program(
                program, in_cols, cap, env, length, params)
            if sel is not None:  # statically known: no Filter → already compact
                out_cap = next(iter(env.values()))[0].shape[0] if env else cap
                env, length = compress(env, length, sel, out_cap)
            out_d = {nm: env[nm][0] for nm in schema.names}
            out_v = {nm: env[nm][1] for nm in schema.names if env[nm][1] is not None}
            return out_d, out_v, length

        return fn


_GLOBAL_CACHE = ProgramCache()


@partial(jax.jit, static_argnames=("names",))
def _compress_jit(arrays, valids, length, sel, names):
    env = {n: (arrays[n], valids.get(n)) for n in names}
    cap = arrays[names[0]].shape[0]
    env, new_len = compress(env, length, sel, cap)
    out_d = {n: env[n][0] for n in names}
    out_v = {n: env[n][1] for n in names if env[n][1] is not None}
    return out_d, out_v, new_len


def compress_block(dblock: DeviceBlock, sel) -> DeviceBlock:
    """Apply a selection mask, compacting survivors to the block front."""
    names = tuple(dblock.schema.names)
    out_d, out_v, new_len = _compress_jit(
        dblock.arrays, dblock.valids, dblock.length, sel, names)
    return DeviceBlock(dblock.schema, out_d, out_v, new_len, dblock.capacity,
                       dict(dblock.dictionaries))


def run_on_device(program: ir.Program, dblock: DeviceBlock,
                  params: Optional[dict] = None,
                  cache: Optional[ProgramCache] = None) -> DeviceBlock:
    """Run a compiled program over a device-resident block."""
    cache = cache or _GLOBAL_CACHE
    params = params or {}
    dev_params = {k: (jnp.asarray(v) if isinstance(v, np.ndarray) else v)
                  for k, v in params.items()}
    fn = cache.get(program, dblock.sig(), dblock.capacity,
                   tuple(sorted(params.keys())))
    out_d, out_v, length = fn(dblock.arrays, dblock.valids, dblock.length,
                              dev_params)
    out_schema = ir.infer_schema(program, dblock.schema)
    dicts = {n: d for n, d in dblock.dictionaries.items() if out_schema.has(n)}
    out_cap = (next(iter(out_d.values())).shape[0] if out_d
               else dblock.capacity)
    return DeviceBlock(out_schema, out_d, out_v, length, out_cap, dicts)


def run_program(program: ir.Program, block: HostBlock,
                params: Optional[dict] = None,
                cache: Optional[ProgramCache] = None) -> HostBlock:
    """Host-convenience entry: pad → device → compiled program → HostBlock."""
    return to_host(run_on_device(program, to_device(block), params, cache))
