"""XLA lowering of SSA programs — the TPU data plane.

Each (program, input-signature, capacity-bucket) pair compiles once to a
single fused XLA computation via ``jax.jit`` and is cached — the analog of
the reference's MiniKQL pattern cache (compile-once, run-per-block,
`ydb/library/yql/minikql/computation/mkql_computation_pattern_cache.h:56`)
with XLA playing the role of the LLVM codegen path
(`ydb/library/yql/minikql/codegen/`).

Design constraints honored for the TPU:
  * static shapes only — blocks are padded to power-of-two capacity
    buckets; the true row count rides as a traced scalar and every
    reduction masks by ``iota < length``;
  * no data-dependent control flow — filters keep selection masks
    (`TColumnFilter` semantics) instead of gathering;
  * GroupBy is a sort-based segmented aggregation: ``lax.sort`` over
    bit-monotone key encodings, segment ids from key-change boundaries,
    ``segment_sum/min/max`` — all MXU/VPU-friendly with static tiles;
  * f64 accumulation for SQL sum semantics (TPU emulates f64; precision
    verified against the numpy oracle in tests).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.dtypes import DType, Kind
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import ir
from ydb_tpu.ops.device import DeviceBlock, bucket_capacity, to_device, to_host
from ydb_tpu.ops.kernels import KERNELS


# --------------------------------------------------------------------------
# traced helpers
# --------------------------------------------------------------------------


def _sort_operand(x):
    """A lax.sort-comparable operand for a key column, in its natural domain.

    No bitcast tricks: the TPU x64 emulation pass cannot rewrite
    f64<->s64 bitcasts, and ``lax.sort`` already provides a total order for
    float and unsigned operands natively."""
    if x.dtype in (jnp.float64, jnp.float32, jnp.uint64):
        return x
    if x.dtype == jnp.bool_:
        return x.astype(jnp.int32)
    return x.astype(jnp.int64)


def _zero_like_operand(x):
    return jnp.zeros((), x.dtype)


def _eval(expr, env, params, cap):
    if isinstance(expr, ir.Col):
        return env[expr.name]
    if isinstance(expr, ir.Const):
        return jnp.full((cap,), expr.value, dtype=expr.dtype.np), None
    if isinstance(expr, ir.Param):
        val = params[expr.name]
        if expr.is_array:
            return val, None
        return jnp.full((cap,), val, dtype=expr.dtype.np), None
    if isinstance(expr, ir.Call):
        k = KERNELS[expr.op]
        args = [_eval(a, env, params, cap) for a in expr.args]
        extra = expr.extra_dict()
        if k.null_mode == "custom":
            return k.impl_nv(jnp, args, extra)
        data = k.impl(jnp, [a[0] for a in args], extra)
        valid = None
        for _, v in args:
            if v is not None:
                valid = v if valid is None else (valid & v)
        return data, valid
    raise TypeError(f"bad expr {expr!r}")


_F64_MIN, _F64_MAX = -np.inf, np.inf


def _sentinel(dtype, for_min: bool):
    if np.issubdtype(dtype, np.floating):
        return np.array(np.inf if for_min else -np.inf, dtype=dtype)
    info = np.iinfo(dtype)
    return np.array(info.max if for_min else info.min, dtype=dtype)


_SCATTER_MAX_BUCKETS = 1 << 16


def _agg_over_segments(cmd: ir.GroupBy, env, active, seg_safe, nseg, iota):
    """Shared aggregate emission: env values segmented by `seg_safe` into
    `nseg` buckets; rows where ~active must carry seg_safe == nseg-1 (a
    garbage bucket the caller drops or overwrites)."""
    new_env = {}
    for a in cmd.aggs:
        if a.func == "count_all":
            data = jax.ops.segment_sum(active.astype(jnp.uint64), seg_safe, nseg)
            new_env[a.out] = (data, None)
            continue
        d, v = env[a.arg]
        m = active if v is None else (active & v)
        if a.func == "count":
            data = jax.ops.segment_sum(m.astype(jnp.uint64), seg_safe, nseg)
            new_env[a.out] = (data, None)
            continue
        any_valid = jax.ops.segment_max(m.astype(jnp.int32), seg_safe, nseg) > 0
        if a.func == "sum":
            if np.issubdtype(np.dtype(d.dtype), np.floating):
                acc = jnp.where(m, d, 0).astype(jnp.float64)
            elif d.dtype == jnp.uint64:
                acc = jnp.where(m, d, 0).astype(jnp.uint64)
            else:
                acc = jnp.where(m, d, 0).astype(jnp.int64)
            data = jax.ops.segment_sum(acc, seg_safe, nseg)
            new_env[a.out] = (data, any_valid)
        elif a.func in ("min", "max"):
            sent = _sentinel(np.dtype(d.dtype), a.func == "min")
            masked = jnp.where(m, d, sent)
            fn = jax.ops.segment_min if a.func == "min" else jax.ops.segment_max
            data = fn(masked, seg_safe, nseg)
            data = jnp.where(any_valid, data, jnp.zeros((), d.dtype))
            new_env[a.out] = (data, any_valid)
        elif a.func == "some":
            pos = jnp.where(m, iota, len(iota))
            firstpos = jax.ops.segment_min(pos, seg_safe, nseg)
            safe = jnp.clip(firstpos, 0, len(iota) - 1)
            data = d[safe]
            new_env[a.out] = (data, any_valid)
        else:
            raise ValueError(a.func)
    return new_env


def _trace_group_by_scatter(cmd: ir.GroupBy, env, schema: Schema, sel,
                            length, cap):
    """Direct-indexed aggregation for statically bounded key domains — the
    BlockCombineHashed analog (`mkql_block_agg.cpp`): bucket id is the mixed
    radix of the key codes (+1 slot for NULL), no sort. Buckets live in the
    leading K slots of the cap-sized block; non-empty buckets are compacted
    to the front."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = (iota < length) if sel is None else ((iota < length) & sel)

    kid = jnp.zeros((cap,), jnp.int32)
    stride = 1
    strides = []
    for kname, dom in zip(cmd.keys, cmd.key_domains):
        d, v = env[kname]
        code = d.astype(jnp.int32) + 1          # -1 (null string code) → 0
        if v is not None:
            code = jnp.where(v, code, 0)        # SQL: one NULL group
        code = jnp.clip(code, 0, dom)
        kid = kid + code * stride
        strides.append(stride)
        stride *= dom + 1
    nbuckets = stride
    nseg = nbuckets + 1                         # +1 garbage bucket
    seg_safe = jnp.where(active, kid, nbuckets)

    new_env = _agg_over_segments(cmd, env, active, seg_safe, nseg, iota)
    present = jax.ops.segment_sum(active.astype(jnp.int32), seg_safe, nseg) > 0
    present = present.at[nbuckets].set(False)

    # rebuild key columns from bucket ids
    bucket_ids = jnp.arange(nseg, dtype=jnp.int32)
    for kname, dom, st in zip(cmd.keys, cmd.key_domains, strides):
        code = (bucket_ids // st) % (dom + 1) - 1
        d, _v = env[kname]
        kd = code.astype(jnp.int32).astype(d.dtype)
        kv = code >= 0
        dt = schema.dtype(kname)
        new_env[kname] = (kd, kv if dt.nullable else None)

    # compact non-empty buckets to the front of a SMALL capacity bucket
    # (compress sorts; doing it over the original cap would cost a full
    # cap-sized argsort for a handful of groups)
    out_cap = bucket_capacity(nseg, minimum=128)
    pad = out_cap - nseg
    padded = {}
    for name, (d, v) in new_env.items():
        dp = jnp.pad(d, (0, pad)) if pad > 0 else d[:out_cap]
        vp = None
        if v is not None:
            vp = jnp.pad(v, (0, pad)) if pad > 0 else v[:out_cap]
        padded[name] = (dp, vp)
    present_p = jnp.pad(present, (0, pad)) if pad > 0 else present[:out_cap]
    out_env, ngroups = compress(padded, jnp.int32(nseg), present_p, out_cap)
    return out_env, ngroups


def _trace_group_by(cmd: ir.GroupBy, env, schema: Schema, sel, length, cap):
    """Sort-based segmented aggregation. Returns (new_env, new_length)."""
    if cmd.keys and cmd.key_domains and all(d > 0 for d in cmd.key_domains):
        nb = 1
        for d in cmd.key_domains:
            nb *= d + 1
        if nb + 1 <= min(cap, _SCATTER_MAX_BUCKETS):
            return _trace_group_by_scatter(cmd, env, schema, sel, length, cap)
    iota = jnp.arange(cap, dtype=jnp.int32)
    row_mask = iota < length
    active = row_mask if sel is None else (row_mask & sel)

    # sort operands: [inactive][per-key: validbit, enc] + carried values
    inactive = (~active).astype(jnp.int32)
    sort_keys = [inactive]
    for kname in cmd.keys:
        d, v = env[kname]
        enc = _sort_operand(d)
        if v is not None:
            enc = jnp.where(v, enc, _zero_like_operand(enc))
            sort_keys.append(v.astype(jnp.int32))
        else:
            sort_keys.append(jnp.ones((cap,), jnp.int32))
        sort_keys.append(enc)

    carried_names: list[str] = []
    carried: list = []

    def carry(name):
        if name in carried_names:
            return
        d, v = env[name]
        carried_names.append(name)
        carried.append(d)
        carried.append(v if v is not None else jnp.ones((cap,), jnp.bool_))

    for kname in cmd.keys:
        carry(kname)
    for a in cmd.aggs:
        if a.arg is not None:
            carry(a.arg)

    nk = len(sort_keys)
    out = jax.lax.sort(sort_keys + carried, num_keys=nk)
    inactive_s = out[0]
    keyparts_s = out[1:nk]
    carried_s = out[nk:]
    env_s = {}
    for i, name in enumerate(carried_names):
        env_s[name] = (carried_s[2 * i], carried_s[2 * i + 1])

    active_s = inactive_s == 0
    if cmd.keys:
        changed = jnp.zeros((cap,), jnp.bool_)
        for kp in keyparts_s:
            prev = jnp.concatenate([kp[:1], kp[:-1]])
            neq = kp != prev
            if np.issubdtype(np.dtype(kp.dtype), np.floating):
                # NaN != NaN would split every NaN row into its own group;
                # lax.sort places NaNs adjacently, so treat them as equal
                neq = neq & ~(jnp.isnan(kp) & jnp.isnan(prev))
            changed = changed | neq
        first_row = iota == 0
        boundary = active_s & (first_row | changed)
        seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
        ngroups = jnp.sum(boundary.astype(jnp.int32))
    else:
        boundary = active_s & (jnp.cumsum(active_s.astype(jnp.int32)) == 1)
        seg = jnp.zeros((cap,), jnp.int32)
        ngroups = jnp.int32(1)  # global agg always yields one row

    seg_safe = jnp.where(active_s, seg, cap - 1)

    new_env = {}
    # emit group keys: scatter first-row-of-segment values
    scatter_idx = jnp.where(boundary, seg, cap)  # cap = dropped
    for kname in cmd.keys:
        d, v = env_s[kname]
        kd = jnp.zeros((cap,), d.dtype).at[scatter_idx].set(d, mode="drop")
        kv = jnp.zeros((cap,), jnp.bool_).at[scatter_idx].set(v, mode="drop")
        dt = schema.dtype(kname)
        new_env[kname] = (kd, kv if dt.nullable else None)

    new_env.update(_agg_over_segments(cmd, env_s, active_s, seg_safe, cap,
                                      iota))
    return new_env, ngroups.astype(jnp.int32)


def _trace_program(program: ir.Program, in_schema_cols, cap, env, length, params):
    """env: name -> (data, valid|None); returns (env, length, sel)."""
    schema = Schema(list(in_schema_cols))
    sel = None
    for cmd in program.commands:
        if isinstance(cmd, ir.Assign):
            data, valid = _eval(cmd.expr, env, params, cap)
            env[cmd.name] = (data, valid)
            dt = ir.infer_expr(cmd.expr, schema)
            schema = Schema([c for c in schema.columns if c.name != cmd.name]
                            + [Column(cmd.name, dt)])
        elif isinstance(cmd, ir.Filter):
            data, valid = _eval(cmd.pred, env, params, cap)
            mask = data if valid is None else (data & valid)
            sel = mask if sel is None else (sel & mask)
        elif isinstance(cmd, ir.GroupBy):
            env, length = _trace_group_by(cmd, env, schema, sel, length, cap)
            # the scatter path shrinks the working capacity to a small
            # bucket; subsequent commands trace at the new size
            if env:
                cap = next(iter(env.values()))[0].shape[0]
            schema = ir.infer_schema(ir.Program([cmd]), schema)
            sel = None
        elif isinstance(cmd, ir.Projection):
            schema = schema.select(list(cmd.names))
            env = {nm: env[nm] for nm in cmd.names}
        else:
            raise TypeError(f"bad command {cmd!r}")
    return env, length, sel, schema


def compress(env, length, sel, cap):
    """BlockCompress: compact selected rows to the front (stable).

    Analog of `mkql_block_compress.cpp`. Sort by (dropped, position)."""
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = (iota < length) if sel is None else ((iota < length) & sel)
    keys = jnp.where(active, iota, jnp.int32(cap))
    order = jnp.argsort(keys)
    new_len = jnp.sum(active.astype(jnp.int32))
    new_env = {}
    for name, (d, v) in env.items():
        new_env[name] = (d[order], v[order] if v is not None else None)
    return new_env, new_len


# --------------------------------------------------------------------------
# compiled-program cache
# --------------------------------------------------------------------------


class ProgramCache:
    """(program fp, signature, capacity) -> jitted fn. Pattern-cache analog."""

    def __init__(self):
        self._cache: dict = {}
        self.hits = 0
        self.misses = 0

    def get(self, program: ir.Program, sig, cap, param_names):
        key = (program.fingerprint(), sig, cap, param_names)
        fn = self._cache.get(key)
        if fn is None:
            self.misses += 1
            fn = self._build(program, sig, cap)
            self._cache[key] = fn
        else:
            self.hits += 1
        return fn

    @staticmethod
    def _build(program: ir.Program, sig, cap):
        in_cols = [Column(name, DType(Kind(kind), nullable))
                   for (name, kind, nullable) in sig]

        @partial(jax.jit, static_argnames=())
        def fn(arrays, valids, length, params):
            env = {}
            for c in in_cols:
                env[c.name] = (arrays[c.name], valids.get(c.name))
            env, length, sel, schema = _trace_program(
                program, in_cols, cap, env, length, params)
            if sel is not None:  # statically known: no Filter → already compact
                out_cap = next(iter(env.values()))[0].shape[0] if env else cap
                env, length = compress(env, length, sel, out_cap)
            out_d = {nm: env[nm][0] for nm in schema.names}
            out_v = {nm: env[nm][1] for nm in schema.names if env[nm][1] is not None}
            return out_d, out_v, length

        return fn


_GLOBAL_CACHE = ProgramCache()


@partial(jax.jit, static_argnames=("names",))
def _compress_jit(arrays, valids, length, sel, names):
    env = {n: (arrays[n], valids.get(n)) for n in names}
    cap = arrays[names[0]].shape[0]
    env, new_len = compress(env, length, sel, cap)
    out_d = {n: env[n][0] for n in names}
    out_v = {n: env[n][1] for n in names if env[n][1] is not None}
    return out_d, out_v, new_len


def compress_block(dblock: DeviceBlock, sel) -> DeviceBlock:
    """Apply a selection mask, compacting survivors to the block front."""
    names = tuple(dblock.schema.names)
    out_d, out_v, new_len = _compress_jit(
        dblock.arrays, dblock.valids, dblock.length, sel, names)
    return DeviceBlock(dblock.schema, out_d, out_v, new_len, dblock.capacity,
                       dict(dblock.dictionaries))


def run_on_device(program: ir.Program, dblock: DeviceBlock,
                  params: Optional[dict] = None,
                  cache: Optional[ProgramCache] = None) -> DeviceBlock:
    """Run a compiled program over a device-resident block."""
    cache = cache or _GLOBAL_CACHE
    params = params or {}
    dev_params = {k: (jnp.asarray(v) if isinstance(v, np.ndarray) else v)
                  for k, v in params.items()}
    fn = cache.get(program, dblock.sig(), dblock.capacity,
                   tuple(sorted(params.keys())))
    out_d, out_v, length = fn(dblock.arrays, dblock.valids, dblock.length,
                              dev_params)
    out_schema = ir.infer_schema(program, dblock.schema)
    dicts = {n: d for n, d in dblock.dictionaries.items() if out_schema.has(n)}
    out_cap = (next(iter(out_d.values())).shape[0] if out_d
               else dblock.capacity)
    return DeviceBlock(out_schema, out_d, out_v, length, out_cap, dicts)


def run_program(program: ir.Program, block: HostBlock,
                params: Optional[dict] = None,
                cache: Optional[ProgramCache] = None) -> HostBlock:
    """Host-convenience entry: pad → device → compiled program → HostBlock."""
    return to_host(run_on_device(program, to_device(block), params, cache))
