"""Device-resident blocks.

A ``DeviceBlock`` keeps a block's columns on the accelerator between
operators of the same stage — the analog of MiniKQL block values flowing
between Block* computation nodes without leaving the engine
(`mkql_computation_node_holders.h:577` TArrowBlock). Host round-trips happen
only at channel boundaries (serialization) or result egress.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.dictionary import Dictionary
from ydb_tpu.core.schema import Schema


def bucket_capacity(n: int, minimum: int = 8192) -> int:
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


@dataclass
class DeviceBlock:
    schema: Schema
    arrays: dict                      # name -> jnp array (len = capacity)
    valids: dict                      # name -> jnp bool array (subset of names)
    length: object                    # traced/concrete scalar int32
    capacity: int
    dictionaries: dict = field(default_factory=dict)  # name -> Dictionary

    def sig(self) -> tuple:
        return tuple((c.name, c.dtype.kind.value, c.name in self.valids)
                     for c in self.schema)


def to_device(block: HostBlock, capacity: Optional[int] = None,
              device=None) -> DeviceBlock:
    """Upload a host block, optionally committed to a specific device
    (row-partition placement on a mesh: jit'd programs follow committed
    inputs, so per-portion work lands on the portion's device)."""
    import jax

    cap = capacity or bucket_capacity(max(block.length, 1))
    put = (lambda x: jax.device_put(x, device)) if device is not None \
        else jnp.asarray
    arrays, valids, dicts = {}, {}, {}
    pad = cap - block.length
    for c in block.schema:
        cd = block.columns[c.name]
        data = np.pad(cd.data, (0, pad)) if pad else cd.data
        arrays[c.name] = put(data)
        if cd.valid is not None:
            v = np.pad(cd.valid, (0, pad)) if pad else cd.valid
            valids[c.name] = put(v)
        if cd.dictionary is not None:
            dicts[c.name] = cd.dictionary
    length = put(np.int32(block.length)) if device is not None \
        else jnp.int32(block.length)
    # resource ledger: the upload's padded bytes (capacity bucket) vs the
    # block's live rows — shape arithmetic only, never a sync
    from ydb_tpu.utils import memledger
    memledger.record_padded_buffers("device_block", "upload",
                                    block.length, cap, arrays, valids)
    return DeviceBlock(block.schema, arrays, valids, length, cap, dicts)


def host_column(data, valid, dtype, dictionary) -> ColumnData:
    """Host materialization convention shared by every device→host path
    (`to_host`, the fused unpack): restore the schema dtype, collapse
    all-valid masks to None, reattach the dictionary."""
    # lint: transfer-ok(inputs already landed by the caller's batched device_get)
    d = np.asarray(data).astype(dtype.np)
    v = valid
    if v is not None:
        # lint: transfer-ok(inputs already landed by the caller's batched device_get)
        v = np.asarray(v)
        if v.all():
            v = None
    return ColumnData(d, v, dictionary)


def to_host(dblock: DeviceBlock) -> HostBlock:
    import jax

    n = int(dblock.length)
    # one batched device→host transfer for all columns (each np.asarray on
    # a device array is a separate blocking round-trip — expensive on a
    # tunneled TPU)
    sliced = {name: a[:n] for name, a in dblock.arrays.items()}
    vsliced = {name: v[:n] for name, v in dblock.valids.items()}
    # lint: transfer-ok(result egress — the one batched client-boundary readback)
    host_a, host_v = jax.device_get((sliced, vsliced))
    from ydb_tpu.utils import memledger
    memledger.record_transfer("ops/device.py::to_host",
                              memledger.deep_nbytes((host_a, host_v)),
                              boundary=True)
    cols = {}
    for c in dblock.schema:
        cols[c.name] = host_column(host_a[c.name], host_v.get(c.name),
                                   c.dtype, dblock.dictionaries.get(c.name))
    return HostBlock(dblock.schema, cols, n)


class DeviceStageBlock(HostBlock):
    """A stage-boundary block whose columns still live on the
    accelerator: the device-resident spine's unit of flow between DQ
    stages.

    It IS a ``HostBlock`` to every consumer that only looks at
    ``schema``/``length`` or calls the block protocol — but ``columns``
    is a lazy property that materializes host arrays ONCE (one batched
    ``to_host`` readback, honestly counted as a boundary transfer) the
    first time a host-only path touches it. Stage plumbing that stays
    device-resident (the planned ICI exchange, the device landing in
    the channel table, the fused scan fast path) reads ``.device``
    directly and never triggers that readback; ``to_pandas`` therefore
    survives only where a consumer genuinely leaves the device plane —
    the client-result boundary.

    ``length`` is host-known (stamped at capture from the fused
    program's length scalar), so shape planning — segment sizing, the
    count exchange, channel stats — never syncs."""

    def __init__(self, device: DeviceBlock, length: int):
        # deliberately NOT the dataclass __init__: `columns` is a
        # read-only property here, not a field
        self.schema = device.schema
        self.device = device
        self.length = int(length)
        self._cols = None

    @property
    def columns(self) -> dict:
        if self._cols is None:
            self._cols = to_host(
                DeviceBlock(self.device.schema, self.device.arrays,
                            self.device.valids, self.length,
                            self.device.capacity,
                            self.device.dictionaries)).columns
        return self._cols

    @property
    def materialized(self) -> bool:
        """True once a host path has forced the readback."""
        return self._cols is not None

    def live_nbytes(self) -> int:
        """Live payload bytes (length x schema itemsizes + masks) —
        shape arithmetic only, never a device sync."""
        n = 0
        for c in self.schema:
            n += self.length * int(np.dtype(c.dtype.np).itemsize)
            if c.name in self.device.valids:
                n += self.length
        return n

    def project(self, output: list) -> "DeviceStageBlock":
        """Device-side mirror of the executor's `_project_output`
        (rename + duplicate-label suffixing) — array references move,
        no bytes do."""
        from ydb_tpu.core.schema import Column

        arrays, valids, dicts = {}, {}, {}
        schema_cols = []
        used = set()
        for (internal, label) in output:
            lbl = label
            k = 2
            while lbl in used:
                lbl = f"{label}_{k}"
                k += 1
            used.add(lbl)
            arrays[lbl] = self.device.arrays[internal]
            if internal in self.device.valids:
                valids[lbl] = self.device.valids[internal]
            if internal in self.device.dictionaries:
                dicts[lbl] = self.device.dictionaries[internal]
            schema_cols.append(Column(lbl, self.schema.dtype(internal)))
        dev = DeviceBlock(Schema(schema_cols), arrays, valids,
                          self.device.length, self.device.capacity, dicts)
        return DeviceStageBlock(dev, self.length)


class DeviceResultFuture:
    """Handle to a dispatched device computation whose device→host
    readout is deferred until the result is actually consumed.

    The dispatch cliff (PERF.md) makes overlap the whole game: a
    dispatch is ~async and cheap, but every blocking readout costs a
    full link round trip — so a query pipeline that dispatches query
    N+1 while query N drains D2H turns N × (dispatch + readout) into
    ~max(compute) + one readout. The future is the seam: the executor
    dispatches the fused program WITHOUT `block_until_ready`, wraps the
    single-pytree `jax.device_get` (plus host-side unpack) in `fetch`,
    and the engine resolves it in its lock-free readout phase.

    `result()` runs `fetch` exactly once (thread-safe) and caches the
    block — or the exception, which re-raises on every later call.
    """

    __slots__ = ("_fetch", "_value", "_exc", "_done", "_mu")

    def __init__(self, fetch):
        import threading
        self._fetch = fetch            # () -> HostBlock
        self._value = None
        self._exc = None
        self._done = False
        self._mu = threading.Lock()

    @classmethod
    def completed(cls, block) -> "DeviceResultFuture":
        """Wrap an already-materialized result (host-lane / distributed
        paths) so every executor path speaks one readout protocol."""
        fut = cls(None)
        fut._value = block
        fut._done = True
        return fut

    def done(self) -> bool:
        return self._done

    def result(self):
        with self._mu:
            if not self._done:
                # only Exception is cached as the computation's outcome;
                # control-flow BaseExceptions (KeyboardInterrupt,
                # SystemExit) propagate WITHOUT poisoning the future —
                # _done stays False so a later result() can refetch
                try:
                    self._value = self._fetch()
                except Exception as e:       # noqa: BLE001 — re-raised
                    self._exc = e
                self._done = True
                self._fetch = None           # drop device refs promptly
            if self._exc is not None:
                raise self._exc
            return self._value

    def map(self, fn) -> "DeviceResultFuture":
        """Chain a host-side transform onto the readout (projection,
        offset slicing) without forcing it now."""
        return DeviceResultFuture(lambda: fn(self.result()))


def to_host_async(dblock: DeviceBlock) -> DeviceResultFuture:
    """`to_host` as a future: the device program stays in flight (jax
    async dispatch) and the single pytree `device_get` runs when the
    result is consumed."""
    return DeviceResultFuture(lambda: to_host(dblock))
