"""Device-resident blocks.

A ``DeviceBlock`` keeps a block's columns on the accelerator between
operators of the same stage — the analog of MiniKQL block values flowing
between Block* computation nodes without leaving the engine
(`mkql_computation_node_holders.h:577` TArrowBlock). Host round-trips happen
only at channel boundaries (serialization) or result egress.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.dictionary import Dictionary
from ydb_tpu.core.schema import Schema


def bucket_capacity(n: int, minimum: int = 8192) -> int:
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


@dataclass
class DeviceBlock:
    schema: Schema
    arrays: dict                      # name -> jnp array (len = capacity)
    valids: dict                      # name -> jnp bool array (subset of names)
    length: object                    # traced/concrete scalar int32
    capacity: int
    dictionaries: dict = field(default_factory=dict)  # name -> Dictionary

    def sig(self) -> tuple:
        return tuple((c.name, c.dtype.kind.value, c.name in self.valids)
                     for c in self.schema)


def to_device(block: HostBlock, capacity: Optional[int] = None,
              device=None) -> DeviceBlock:
    """Upload a host block, optionally committed to a specific device
    (row-partition placement on a mesh: jit'd programs follow committed
    inputs, so per-portion work lands on the portion's device)."""
    import jax

    cap = capacity or bucket_capacity(max(block.length, 1))
    put = (lambda x: jax.device_put(x, device)) if device is not None \
        else jnp.asarray
    arrays, valids, dicts = {}, {}, {}
    pad = cap - block.length
    for c in block.schema:
        cd = block.columns[c.name]
        data = np.pad(cd.data, (0, pad)) if pad else cd.data
        arrays[c.name] = put(data)
        if cd.valid is not None:
            v = np.pad(cd.valid, (0, pad)) if pad else cd.valid
            valids[c.name] = put(v)
        if cd.dictionary is not None:
            dicts[c.name] = cd.dictionary
    length = put(np.int32(block.length)) if device is not None \
        else jnp.int32(block.length)
    return DeviceBlock(block.schema, arrays, valids, length, cap, dicts)


def host_column(data, valid, dtype, dictionary) -> ColumnData:
    """Host materialization convention shared by every device→host path
    (`to_host`, the fused unpack): restore the schema dtype, collapse
    all-valid masks to None, reattach the dictionary."""
    d = np.asarray(data).astype(dtype.np)
    v = valid
    if v is not None:
        v = np.asarray(v)
        if v.all():
            v = None
    return ColumnData(d, v, dictionary)


def to_host(dblock: DeviceBlock) -> HostBlock:
    import jax

    n = int(dblock.length)
    # one batched device→host transfer for all columns (each np.asarray on
    # a device array is a separate blocking round-trip — expensive on a
    # tunneled TPU)
    sliced = {name: a[:n] for name, a in dblock.arrays.items()}
    vsliced = {name: v[:n] for name, v in dblock.valids.items()}
    host_a, host_v = jax.device_get((sliced, vsliced))
    cols = {}
    for c in dblock.schema:
        cols[c.name] = host_column(host_a[c.name], host_v.get(c.name),
                                   c.dtype, dblock.dictionaries.get(c.name))
    return HostBlock(dblock.schema, cols, n)
