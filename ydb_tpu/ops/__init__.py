from ydb_tpu.ops.ir import (
    Agg, Assign, Call, Col, Const, Filter, GroupBy, Param, Program, Projection,
)

__all__ = [
    "Agg", "Assign", "Call", "Col", "Const", "Filter", "GroupBy", "Param",
    "Program", "Projection",
]
