"""Columnar SSA program IR.

The device-executable program shape mirrors the reference's ColumnShard
pushdown program (`ydb/core/protos/ssa.proto:19-209`): an ordered list of
commands over named columns —

  * ``Assign``     — bind a new named column to an expression
    (constant / parameter / kernel call over existing columns),
  * ``Filter``     — intersect the block's selection mask with a predicate,
  * ``GroupBy``    — hash/sort aggregate by key columns,
  * ``Projection`` — restrict to a set of columns.

It is also the per-stage compute IR (the analog of serialized MiniKQL
programs in DQ task specs, `ydb/library/yql/dq/proto/dq_tasks.proto:186`);
every program has two lowerings: a numpy oracle (`ops/numpy_exec.py`) and the
XLA lowering (`ops/xla_exec.py`). Programs are structurally fingerprinted for
the jit pattern cache (analog of
`mkql_computation_pattern_cache.h:56`).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Optional, Union

from ydb_tpu.core.dtypes import BOOL, DType, FLOAT64, INT64, Kind, UINT64, common_numeric
from ydb_tpu.core.schema import Column, Schema

# --------------------------------------------------------------------------
# Expressions
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Col:
    name: str


@dataclass(frozen=True)
class Const:
    value: Any
    dtype: DType


@dataclass(frozen=True)
class Param:
    """Runtime-bound input (scalar or array), e.g. a dictionary LUT.

    Analog of the SSA program's parameters schema
    (`ssa.proto:201` TOlapProgram.Parameters).
    """
    name: str
    dtype: DType
    is_array: bool = False


@dataclass(frozen=True)
class Call:
    op: str
    args: tuple                      # tuple[Expr, ...]
    extra: tuple = ()                # sorted tuple of (key, value) pairs

    def extra_dict(self) -> dict:
        return dict(self.extra)


Expr = Union[Col, Const, Param, Call]


def call(op: str, *args: Expr, **extra) -> Call:
    return Call(op, tuple(args), tuple(sorted(extra.items())))


# --------------------------------------------------------------------------
# Commands
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Assign:
    name: str
    expr: Expr


@dataclass(frozen=True)
class Filter:
    pred: Expr


@dataclass(frozen=True)
class Agg:
    out: str
    func: str                        # count | count_all | sum | min | max | some
    arg: Optional[str] = None        # column name; None only for count_all


@dataclass(frozen=True)
class GroupBy:
    keys: tuple                      # tuple[str, ...] (may be empty: global agg)
    aggs: tuple                      # tuple[Agg, ...]
    # static per-key domain sizes (codes in [-1, domain)); 0 = unbounded.
    # When every key is bounded and the product is small, the XLA lowering
    # uses direct-indexed scatter aggregation (BlockCombineHashed analog)
    # instead of sort-based segmentation. Part of the structural
    # fingerprint, so dictionary growth recompiles.
    key_domains: tuple = ()
    # PROVEN static upper bound on the number of groups (0 = unbounded).
    # The sorted lowering late-materializes per-group outputs at a bucket
    # of this size instead of scan capacity, so per-group gathers run at
    # output cardinality. An UNDERSTATED bound silently drops groups —
    # only guaranteed sources may set it: the planner's key-domain
    # products (dictionary/bool domains snapshot at plan time) and the
    # executor's inner-join build cardinality (ngroups ≤ build rows when
    # every key is the probe key or a unique build's payload). Part of
    # the structural fingerprint.
    out_bound: int = 0
    # CARRIED keys: grouping columns PROVEN functionally determined by
    # `keys` (the executor's bounds rewrite: a unique-keyed build's
    # payload is a function of its join key; dataset-verified
    # determinants within one build's payload). They do not participate
    # in the sort / bucket identity — their per-group value materializes
    # from the group leader row, exactly like key late-materialization.
    # A FALSE dependency silently merges groups, so only runtime-verified
    # sources may populate this. Part of the structural fingerprint.
    carry_keys: tuple = ()


@dataclass(frozen=True)
class Projection:
    names: tuple                     # tuple[str, ...]


@dataclass(frozen=True)
class Compact:
    """Shrink the working capacity to `cap` rows: selected rows compact
    to the front (stable, like BlockCompress) and the block is SLICED to
    the ladder-quantized `cap` (`progstore/buckets.bucket_segment`) —
    downstream commands compile and run at the small shape instead of
    scan capacity.

    `cap` is SIZING-quality (an estimate or a lattice bound), never a
    correctness input: the lowering emits the live count and an overflow
    flag out-of-band (`_trace_program`'s aux box) and the executor
    re-runs the un-compacted program LOUDLY when live > cap — truncation
    is detected, never silent. `bound` records the pre-quantized bound
    the planner/executor derived (documentation + structural identity).
    Part of the structural fingerprint, so a re-sized compact recompiles.
    """
    cap: int
    bound: int = 0


Command = Union[Assign, Filter, GroupBy, Projection, Compact]


@dataclass
class Program:
    commands: list = field(default_factory=list)

    def assign(self, name: str, expr: Expr) -> "Program":
        self.commands.append(Assign(name, expr))
        return self

    def filter(self, pred: Expr) -> "Program":
        self.commands.append(Filter(pred))
        return self

    def group_by(self, keys: list[str], aggs: list[Agg],
                 key_domains: tuple = (), out_bound: int = 0,
                 carry_keys: tuple = ()) -> "Program":
        self.commands.append(GroupBy(tuple(keys), tuple(aggs),
                                     tuple(key_domains), out_bound,
                                     tuple(carry_keys)))
        return self

    def project(self, names: list[str]) -> "Program":
        self.commands.append(Projection(tuple(names)))
        return self

    def compact(self, cap: int, bound: int = 0) -> "Program":
        self.commands.append(Compact(cap, bound))
        return self

    # -- structural identity (jit pattern-cache key) ----------------------

    def fingerprint(self) -> str:
        h = hashlib.sha256(repr(self.commands).encode())
        return h.hexdigest()[:24]

    def __repr__(self) -> str:
        return f"Program({self.commands!r})"


# --------------------------------------------------------------------------
# Type inference
# --------------------------------------------------------------------------

AGG_FUNCS = ("count", "count_all", "sum", "min", "max", "some")


def infer_expr(expr: Expr, schema: Schema) -> DType:
    from ydb_tpu.ops.kernels import KERNELS  # late import: registry below IR

    if isinstance(expr, Col):
        return schema.dtype(expr.name)
    if isinstance(expr, (Const, Param)):
        return expr.dtype
    if isinstance(expr, Call):
        k = KERNELS[expr.op]
        arg_types = [infer_expr(a, schema) for a in expr.args]
        return k.result_dtype(arg_types, expr.extra_dict())
    raise TypeError(f"bad expr {expr!r}")


def agg_result_dtype(func: str, arg_dtype: Optional[DType]) -> DType:
    if func in ("count", "count_all"):
        return DType(Kind.UINT64, nullable=False)
    assert arg_dtype is not None
    if func == "sum":
        if arg_dtype.is_float:
            return FLOAT64
        if arg_dtype.kind in (Kind.UINT8, Kind.UINT16, Kind.UINT32, Kind.UINT64):
            return UINT64
        return INT64
    return arg_dtype  # min/max/some


def infer_schema(program: Program, schema: Schema) -> Schema:
    """Output schema of a program over an input schema (also validates)."""
    cur = Schema(list(schema.columns))
    for cmd in program.commands:
        if isinstance(cmd, Assign):
            dt = infer_expr(cmd.expr, cur)
            cols = [c for c in cur.columns if c.name != cmd.name]
            cur = Schema(cols + [Column(cmd.name, dt)])
        elif isinstance(cmd, Filter):
            dt = infer_expr(cmd.pred, cur)
            if dt.kind is not Kind.BOOL:
                raise TypeError(f"filter predicate must be bool, got {dt}")
        elif isinstance(cmd, GroupBy):
            cols = [cur.col(k) for k in cmd.keys]
            cols += [cur.col(k) for k in cmd.carry_keys]
            for a in cmd.aggs:
                if a.func not in AGG_FUNCS:
                    raise ValueError(f"unknown aggregate {a.func}")
                arg_dt = cur.dtype(a.arg) if a.arg is not None else None
                cols.append(Column(a.out, agg_result_dtype(a.func, arg_dt)))
            cur = Schema(cols)
        elif isinstance(cmd, Projection):
            cur = cur.select(list(cmd.names))
        elif isinstance(cmd, Compact):
            pass                         # capacity change only — schema holds
        else:
            raise TypeError(f"bad command {cmd!r}")
    return cur


def expr_columns(expr: Expr, out: Optional[set] = None) -> set:
    """Set of input column names referenced by an expression."""
    if out is None:
        out = set()
    if isinstance(expr, Col):
        out.add(expr.name)
    elif isinstance(expr, Call):
        for a in expr.args:
            expr_columns(a, out)
    return out


def program_params(program: Program) -> list[Param]:
    """All Params referenced anywhere in the program, in first-use order."""
    seen: dict[str, Param] = {}

    def walk(e: Expr):
        if isinstance(e, Param):
            seen.setdefault(e.name, e)
        elif isinstance(e, Call):
            for a in e.args:
                walk(a)

    for cmd in program.commands:
        if isinstance(cmd, Assign):
            walk(cmd.expr)
        elif isinstance(cmd, Filter):
            walk(cmd.pred)
    return list(seen.values())
