"""CPU oracle lowering of SSA programs.

Executes a ``Program`` over a ``HostBlock`` with plain numpy. This is the
correctness reference every XLA kernel is differentially tested against —
the role Arrow compute plays for the reference's ColumnShard program
(`ydb/core/formats/arrow/program.cpp` TProgramStep::Apply).

Selection-vector semantics mirror the reference's ``TColumnFilter``
(`ydb/core/formats/arrow/arrow_filter.h`): filters accumulate a boolean
mask; rows are only physically compacted at block egress or before a
GroupBy.
"""

from __future__ import annotations

# lint: allow-file-host-sync(CPU oracle lane — operates on host numpy only, never device values)

from typing import Optional

import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.dtypes import Kind
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import ir
from ydb_tpu.ops.kernels import KERNELS


def _eval(expr, cols: dict, schema: Schema, params: dict, n: int):
    """Evaluate an expression → (data, valid) over full-length arrays."""
    if isinstance(expr, ir.Col):
        cd = cols[expr.name]
        return cd[0], cd[1]
    if isinstance(expr, ir.Const):
        return np.full(n, expr.value, dtype=expr.dtype.np), None
    if isinstance(expr, ir.Param):
        val = params[expr.name]
        if expr.is_array:
            return np.asarray(val, dtype=expr.dtype.np), None
        return np.full(n, val, dtype=expr.dtype.np), None
    if isinstance(expr, ir.Call):
        k = KERNELS[expr.op]
        args = [_eval(a, cols, schema, params, n) for a in expr.args]
        extra = expr.extra_dict()
        if k.null_mode == "custom":
            return k.impl_nv(np, args, extra)
        data = k.impl(np, [a[0] for a in args], extra)
        valid = None
        for _, v in args:
            if v is not None:
                valid = v if valid is None else (valid & v)
        return data, valid
    raise TypeError(f"bad expr {expr!r}")


def canonical_key_pair(d, v):
    """Canonical (physical int64, validity int64) encoding of ONE
    group-key column — the grouping equality itself: all NULLs form one
    value, -0.0 == 0.0, all NaNs equal. Shared by the group-by oracle
    below and the bounds lattice's functional-dependency verification
    (`query/bounds.dataset_distinct`), which must count distinct tuples
    under exactly the equality grouping uses — a drift between the two
    would let a "verified" dependency silently merge groups."""
    if v is not None:  # SQL: all NULL keys form one group
        d = np.where(v, d, np.zeros((), d.dtype))
    if np.issubdtype(d.dtype, np.floating):
        d = np.where(d == 0, np.zeros((), d.dtype), d)
        d = np.where(np.isnan(d), np.full((), np.nan, d.dtype), d)
        phys = d.astype(np.float64).view(np.uint64)
    else:
        phys = d
    valid = (v if v is not None else np.ones(len(d), bool)).astype(np.int64)
    return np.ascontiguousarray(phys.astype(np.int64)), valid


def _group_by(cmd: ir.GroupBy, cols: dict, schema: Schema, sel):
    n = None
    for d, _ in cols.values():
        n = len(d)
        break
    idx = np.nonzero(sel)[0] if sel is not None else np.arange(n)

    # -- key codes: np.unique over a (rows, nkeys*2) matrix incl. validity --
    if cmd.keys:
        mats = []
        for kname in cmd.keys:
            d, v = cols[kname]
            phys, valid = canonical_key_pair(
                d[idx], v[idx] if v is not None else None)
            mats.append(phys)
            mats.append(valid)
        mat = np.stack(mats, axis=1) if mats else np.zeros((len(idx), 0), np.int64)
        uniq, inverse = np.unique(mat, axis=0, return_inverse=True)
        inverse = np.asarray(inverse).reshape(-1)
        ngroups = len(uniq)
        first = np.full(ngroups, len(idx), dtype=np.int64)
        np.minimum.at(first, inverse, np.arange(len(idx)))
    else:
        ngroups = 1
        inverse = np.zeros(len(idx), dtype=np.int64)
        first = np.zeros(1, dtype=np.int64)

    out_cols: dict[str, tuple] = {}
    # carried keys (functionally determined by `keys`) take the group
    # leader's value, exactly like the device lowerings
    for kname in list(cmd.keys) + list(cmd.carry_keys):
        d, v = cols[kname]
        dk, vk = d[idx], (v[idx] if v is not None else None)
        out_cols[kname] = (dk[first], vk[first] if vk is not None else None)

    for a in cmd.aggs:
        if a.func == "count_all":
            data = np.bincount(inverse, minlength=ngroups).astype(np.uint64)
            out_cols[a.out] = (data, None)
            continue
        d, v = cols[a.arg]
        dk = d[idx]
        vk = v[idx] if v is not None else np.ones(len(idx), bool)
        if a.func == "count":
            data = np.bincount(inverse, weights=vk.astype(np.float64),
                               minlength=ngroups).astype(np.uint64)
            out_cols[a.out] = (data, None)
            continue
        any_valid = np.zeros(ngroups, dtype=bool)
        np.logical_or.at(any_valid, inverse, vk)
        if a.func == "sum":
            acc_dt = np.float64 if np.issubdtype(dk.dtype, np.floating) else np.int64
            acc = np.zeros(ngroups, dtype=acc_dt)
            np.add.at(acc, inverse, np.where(vk, dk, 0).astype(acc_dt))
            out_cols[a.out] = (acc, any_valid if not np.all(any_valid) else None)
        elif a.func in ("min", "max"):
            if np.issubdtype(dk.dtype, np.floating):
                sentinel = np.inf if a.func == "min" else -np.inf
            else:
                info = np.iinfo(dk.dtype)
                sentinel = info.max if a.func == "min" else info.min
            acc = np.full(ngroups, sentinel, dtype=dk.dtype)
            op = np.minimum if a.func == "min" else np.maximum
            op.at(acc, inverse, np.where(vk, dk, sentinel).astype(dk.dtype))
            out_cols[a.out] = (acc, any_valid if not np.all(any_valid) else None)
        elif a.func == "some":
            acc = np.zeros(ngroups, dtype=dk.dtype)
            pos = np.full(ngroups, len(idx), dtype=np.int64)
            valid_pos = np.where(vk, np.arange(len(idx)), len(idx))
            np.minimum.at(pos, inverse, valid_pos)
            ok = pos < len(idx)
            acc[ok] = dk[pos[ok]]
            out_cols[a.out] = (acc, any_valid if not np.all(any_valid) else None)
        else:
            raise ValueError(a.func)
    return out_cols, ngroups


def run_program(program: ir.Program, block: HostBlock,
                params: Optional[dict] = None) -> HostBlock:
    params = params or {}
    schema = Schema(list(block.schema.columns))
    cols = {c.name: (block.columns[c.name].data, block.columns[c.name].valid)
            for c in schema}
    dicts = {c.name: block.columns[c.name].dictionary for c in schema}
    sel = None
    n = block.length

    for cmd in program.commands:
        if isinstance(cmd, ir.Assign):
            data, valid = _eval(cmd.expr, cols, schema, params, n)
            if np.isscalar(data) or (hasattr(data, "shape") and data.shape == ()):
                data = np.full(n, data)
            dt = ir.infer_expr(cmd.expr, schema)
            cols[cmd.name] = (np.asarray(data, dtype=dt.np), valid)
            schema = Schema([c for c in schema.columns if c.name != cmd.name]
                            + [Column(cmd.name, dt)])
            if isinstance(cmd.expr, ir.Col):
                dicts[cmd.name] = dicts.get(cmd.expr.name)
        elif isinstance(cmd, ir.Filter):
            data, valid = _eval(cmd.pred, cols, schema, params, n)
            mask = data if valid is None else (data & valid)
            sel = mask if sel is None else (sel & mask)
        elif isinstance(cmd, ir.GroupBy):
            out_cols, ngroups = _group_by(cmd, cols, schema, sel)
            schema = ir.infer_schema(ir.Program([cmd]), schema)
            cols = {name: out_cols[name] for name in schema.names}
            sel = None
            n = ngroups
        elif isinstance(cmd, ir.Projection):
            schema = schema.select(list(cmd.names))
            cols = {nm: cols[nm] for nm in cmd.names}
        elif isinstance(cmd, ir.Compact):
            # the oracle is unpadded: compact just materializes the
            # selection. `cap` is a device-sizing hint — truncating here
            # would bake a forged bound into the truth the differential
            # tests compare against, so the oracle never truncates.
            if sel is not None:
                idx = np.nonzero(sel)[0]
                cols = {nm: (d[idx], v[idx] if v is not None else None)
                        for nm, (d, v) in cols.items()}
                n = len(idx)
                sel = None
        else:
            raise TypeError(f"bad command {cmd!r}")

    if sel is not None:
        idx = np.nonzero(sel)[0]
        cols = {nm: (d[idx], v[idx] if v is not None else None)
                for nm, (d, v) in cols.items()}
        n = len(idx)

    out = {}
    for c in schema:
        d, v = cols[c.name]
        out[c.name] = ColumnData(np.asarray(d, dtype=c.dtype.np), v, dicts.get(c.name))
    return HostBlock(schema, out, n)
