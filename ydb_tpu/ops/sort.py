"""Device sort / top-k block operators.

Analogs of WideTopSort/WideSort/WideTop (`mkql_block_top.cpp`,
`mkql_wide_top_sort.cpp`): multi-key sort via ``lax.sort`` over bit-monotone
encodings (descending keys flip their encoding), carrying row indices, then
a static-width head take for LIMIT.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu.ops.device import DeviceBlock
from ydb_tpu.ops.xla_exec import _sort_operand, _zero_like_operand, record_sort


def sort_env(arrays, valids, length, sel, keys: tuple, names: tuple):
    """Traceable sort body (callable from fused jitted pipelines);
    keys: tuple of (col_name, ascending, nulls_first)."""
    return _sort_impl(arrays, valids, length, sel, keys, names)


@partial(jax.jit, static_argnames=("keys", "names"))
def _sort_block(arrays, valids, length, sel, keys: tuple, names: tuple):
    return _sort_impl(arrays, valids, length, sel, keys, names)


def _sort_impl(arrays, valids, length, sel, keys: tuple, names: tuple):
    """keys: tuple of (col_name, ascending, nulls_first).

    Sorts key encodings + a row-id only (carrying whole rows through a wide
    multi-operand ``lax.sort`` explodes XLA compile time on TPU); row values
    follow by permutation gathers, which XLA fuses."""
    first = arrays[names[0]]
    cap = first.shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = iota < length
    if sel is not None:
        active = active & sel

    sort_ops = [(~active).astype(jnp.int32)]  # dropped rows go last
    for (name, asc, nulls_first) in keys:
        d = arrays[name]
        v = valids.get(name)
        enc = _sort_operand(d)
        if not asc:
            if enc.dtype in (jnp.float64, jnp.float32):
                enc = -enc
            else:
                enc = ~enc  # bitwise not: reverses order, no int64-min overflow
        if v is not None:
            nullrank = (~v).astype(jnp.int32) if not nulls_first else v.astype(jnp.int32)
            sort_ops.append(nullrank)
            enc = jnp.where(v, enc, _zero_like_operand(enc))
        sort_ops.append(enc)

    # iota as the final key → deterministic (stable) order; the sorted iota
    # IS the permutation
    record_sort(cap, len(sort_ops) + 1)   # sort/rows_max + operands_max
    out = jax.lax.sort(sort_ops + [iota], num_keys=len(sort_ops) + 1)
    perm = out[-1]
    new_arrays, new_valids = {}, {}
    for name in names:
        new_arrays[name] = arrays[name][perm]
        if name in valids:
            new_valids[name] = valids[name][perm]
    new_len = jnp.sum(active.astype(jnp.int32))
    return new_arrays, new_valids, new_len


def sort_block(dblock: DeviceBlock, keys: list[tuple], sel=None,
               limit=None) -> DeviceBlock:
    """keys: [(name, ascending, nulls_first)]; limit caps the result length."""
    names = tuple(dblock.schema.names)
    arrays, valids, length = _sort_block(
        dblock.arrays, dblock.valids, dblock.length, sel,
        tuple(keys), names)
    if limit is not None:
        length = jnp.minimum(length, jnp.int32(limit))
    return DeviceBlock(dblock.schema, arrays, valids, length,
                       dblock.capacity, dict(dblock.dictionaries))
