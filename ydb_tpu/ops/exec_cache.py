"""Bounded LRU for compiled-executable references.

Accumulating live XLA executables in one process eventually wedges or
segfaults this platform's compile service (and grows the XLA CPU
client's executable table without bound in tests) — round 4 routed
around it by manually clearing every cache between queries. The real
fix is a lifecycle: every compiled-program cache in the engine
(whole-query fused programs, finalize programs, distributed agg/shuffle
programs, the per-stage ProgramCache) shares ONE live-executable budget,
LRU-evicted, so a long-lived server holds a bounded working set no
matter how many distinct query shapes pass through. The analog of the
reference's computation pattern cache with its size limit
(`mkql_computation_pattern_cache.h:56` — MaxPatternsSize/MaxCompiledSize).

Eviction drops the last engine-side reference to a jitted callable; its
underlying executables are freed when Python GC runs. A shared global
budget (`GLOBAL_BUDGET`) spans every cache created in the process.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

__all__ = ["ExecCache", "GLOBAL_BUDGET", "live_executables"]


class _Budget:
    """Process-wide live-executable budget shared by all ExecCaches."""

    def __init__(self, max_entries: int):
        import weakref
        self.max_entries = max_entries
        self._mu = threading.RLock()
        # weak refs: an engine's caches must die with the engine — a
        # strong registry would pin every dead executor's executables
        # and grow the scan with each engine ever created
        self._caches: list = []
        self._weakref = weakref.ref

    def register(self, cache: "ExecCache") -> None:
        with self._mu:
            self._caches.append(self._weakref(cache))

    def _live(self) -> list:
        alive = []
        dead = False
        for ref in self._caches:
            c = ref()
            if c is None:
                dead = True
            else:
                alive.append(c)
        if dead:
            self._caches = [self._weakref(c) for c in alive]
        return alive

    def total(self) -> int:
        with self._mu:
            return sum(len(c) for c in self._live())

    def evict_to_fit(self, incoming: int = 1) -> None:
        """Evict globally-LRU entries until `incoming` new ones fit."""
        with self._mu:
            caches = self._live()
            while sum(len(c) for c in caches) + incoming \
                    > self.max_entries:
                victim = None
                oldest = None
                for c in caches:
                    t = c._oldest_tick()
                    if t is not None and (oldest is None or t < oldest):
                        oldest, victim = t, c
                if victim is None:
                    return
                victim._evict_one()


GLOBAL_BUDGET = _Budget(int(os.environ.get(
    "YDB_TPU_EXEC_CACHE_ENTRIES", 160)))

_tick_mu = threading.Lock()
_tick = [0]


def _next_tick() -> int:
    with _tick_mu:
        _tick[0] += 1
        return _tick[0]


def live_executables() -> int:
    return GLOBAL_BUDGET.total()


class ExecCache:
    """One named compiled-program cache drawing on the global budget.

    dict-like for the common get/put shape; every entry counts as one
    live executable against GLOBAL_BUDGET regardless of which cache
    holds it, and recency is global (a hot fused program keeps its slot
    while a cold distributed shape from another cache is evicted)."""

    def __init__(self, name: str, budget: _Budget = None):
        self.name = name
        self._budget = budget or GLOBAL_BUDGET
        self._entries: OrderedDict = OrderedDict()   # key -> (value, tick)
        self._mu = threading.RLock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._budget.register(self)

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def get(self, key, default=None):
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return default
            self._entries[key] = (ent[0], _next_tick())
            self._entries.move_to_end(key)
            self.hits += 1
            return ent[0]

    def __contains__(self, key) -> bool:
        with self._mu:
            return key in self._entries

    def __getitem__(self, key):
        v = self.get(key, _MISSING)
        if v is _MISSING:
            raise KeyError(key)
        return v

    def __setitem__(self, key, value) -> None:
        self._budget.evict_to_fit(1)
        with self._mu:
            self._entries[key] = (value, _next_tick())
            self._entries.move_to_end(key)

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()

    # -- budget hooks ------------------------------------------------------

    def _oldest_tick(self):
        with self._mu:
            if not self._entries:
                return None
            first = next(iter(self._entries.values()))
            return first[1]

    def _evict_one(self) -> None:
        with self._mu:
            if self._entries:
                self._entries.popitem(last=False)
                self.evictions += 1


class _Missing:
    pass


_MISSING = _Missing()
