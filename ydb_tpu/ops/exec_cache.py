"""Bounded LRU for compiled-executable references.

Accumulating live XLA executables in one process eventually wedges or
segfaults this platform's compile service (and grows the XLA CPU
client's executable table without bound in tests) — round 4 routed
around it by manually clearing every cache between queries. The real
fix is a lifecycle: every compiled-program cache in the engine
(whole-query fused programs, finalize programs, distributed agg/shuffle
programs, the per-stage ProgramCache) shares ONE live-executable budget,
LRU-evicted, so a long-lived server holds a bounded working set no
matter how many distinct query shapes pass through. The analog of the
reference's computation pattern cache with its size limit
(`mkql_computation_pattern_cache.h:56` — MaxPatternsSize/MaxCompiledSize).

Eviction RELEASES the executable, not just the reference: a jitted
callable's compiled executables live in its own `jax.jit` cache, which a
dropped Python reference only frees after the garbage collector breaks
the closure↔cache reference cycles — under allocation pressure that lag
was long enough for "evicted" executables to pile up live and SIGSEGV
the platform (the r5 full-suite crash). `_release` therefore calls
`clear_cache()` on evicted/overwritten/cleared entries (recursing into
tuple entries and one level of object attributes for the composite
distributed-path entries), and the budget runs a periodic `gc.collect()`
every `YDB_TPU_EXEC_CACHE_GC` releases (default 16) so the cycle-bound
remainder actually dies. A shared global budget (`GLOBAL_BUDGET`) spans
every cache created in the process.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

__all__ = ["ExecCache", "GLOBAL_BUDGET", "live_executables",
           "release_executable"]


class _Budget:
    """Process-wide live-executable budget shared by all ExecCaches."""

    def __init__(self, max_entries: int):
        import weakref
        self.max_entries = max_entries
        self._mu = threading.RLock()
        # weak refs: an engine's caches must die with the engine — a
        # strong registry would pin every dead executor's executables
        # and grow the scan with each engine ever created
        self._caches: list = []
        self._weakref = weakref.ref

    def register(self, cache: "ExecCache") -> None:
        with self._mu:
            self._caches.append(self._weakref(cache))

    def _live(self) -> list:
        alive = []
        dead = False
        for ref in self._caches:
            c = ref()
            if c is None:
                dead = True
            else:
                alive.append(c)
        if dead:
            self._caches = [self._weakref(c) for c in alive]
        return alive

    def total(self) -> int:
        with self._mu:
            return sum(len(c) for c in self._live())

    def evict_to_fit(self, incoming: int = 1) -> None:
        """Evict globally-LRU entries until `incoming` new ones fit.
        Victims are popped under the budget lock but RELEASED after it:
        release runs a periodic full gc.collect(), which must not stall
        every other thread's compile-cache insert."""
        with self._mu:
            dropped = self._evict_to_fit_locked(incoming)
        _release_dropped(dropped)

    def _evict_to_fit_locked(self, incoming: int) -> list:
        """Returns [(cache, key, value)] victims for the caller to
        release (and to report to the cache's eviction hook) outside
        the locks."""
        dropped = []
        caches = self._live()
        while sum(len(c) for c in caches) + incoming \
                > self.max_entries:
            victim = None
            oldest = None
            for c in caches:
                t = c._oldest_tick()
                if t is not None and (oldest is None or t < oldest):
                    oldest, victim = t, c
            if victim is None:
                break
            kv = victim._pop_oldest()
            if kv is not _MISSING:
                dropped.append((victim, kv[0], kv[1]))
        return dropped


def _release_dropped(dropped: list) -> None:
    """Release evicted executables and fire each owning cache's
    `on_evict(key)` hook (outside every lock — the hook feeds the
    program inventory, `utils/progstats.mark_evicted`, and
    observability must neither deadlock nor fail an insert)."""
    for (cache, key, v) in dropped:
        release_executable(v)
        hook = cache.on_evict
        if hook is not None:
            try:
                hook(key)
            except Exception:            # noqa: BLE001 — observability
                pass


GLOBAL_BUDGET = _Budget(int(os.environ.get(
    "YDB_TPU_EXEC_CACHE_ENTRIES", 160)))

_GC_EVERY = max(1, int(os.environ.get("YDB_TPU_EXEC_CACHE_GC", 16)))
_gc_mu = threading.Lock()
_released_since_gc = [0]


def release_executable(value) -> None:
    """Free a cached entry's compiled executables deterministically:
    `clear_cache()` on jitted callables (tuple entries and one level of
    object attributes covered — the finalize/dist-agg/shuffle-join caches
    store composites), then a periodic gc to break the closure cycles
    that would otherwise keep the remainder alive."""
    import gc

    def _clear(v, depth: int) -> None:
        cc = getattr(v, "clear_cache", None)
        if callable(cc):
            try:
                cc()
            except Exception:                # noqa: BLE001 — best effort
                pass
            return
        if isinstance(v, (tuple, list)):
            for x in v:
                _clear(x, depth)
            return
        if depth > 0 and hasattr(v, "__dict__"):
            for x in vars(v).values():
                _clear(x, depth - 1)

    _clear(value, 1)
    with _gc_mu:
        _released_since_gc[0] += 1
        run_gc = _released_since_gc[0] >= _GC_EVERY
        if run_gc:
            _released_since_gc[0] = 0
    if run_gc:
        gc.collect()

_tick_mu = threading.Lock()
_tick = [0]


def _next_tick() -> int:
    with _tick_mu:
        _tick[0] += 1
        return _tick[0]


def live_executables() -> int:
    return GLOBAL_BUDGET.total()


class ExecCache:
    """One named compiled-program cache drawing on the global budget.

    dict-like for the common get/put shape; every entry counts as one
    live executable against GLOBAL_BUDGET regardless of which cache
    holds it, and recency is global (a hot fused program keeps its slot
    while a cold distributed shape from another cache is evicted)."""

    def __init__(self, name: str, budget: _Budget = None):
        self.name = name
        self._budget = budget or GLOBAL_BUDGET
        self._entries: OrderedDict = OrderedDict()   # key -> (value, tick)
        self._mu = threading.RLock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.released = 0
        # optional eviction hook `fn(key)`, fired AFTER the victim's
        # executable is released, outside every lock — the program
        # inventory (`utils/progstats`) marks the entry `evicted` here
        self.on_evict = None
        self._budget.register(self)

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def get(self, key, default=None):
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return default
            self._entries[key] = (ent[0], _next_tick())
            self._entries.move_to_end(key)
            self.hits += 1
            return ent[0]

    def __contains__(self, key) -> bool:
        with self._mu:
            return key in self._entries

    def __getitem__(self, key):
        v = self.get(key, _MISSING)
        if v is _MISSING:
            raise KeyError(key)
        return v

    def __setitem__(self, key, value) -> None:
        # check + evict + insert are one atomic step under the budget
        # lock (budget._mu -> cache._mu everywhere, get() takes only the
        # cache lock): two concurrent misses for the same key must not
        # each evict an unrelated entry for one net insert, and an
        # eviction between the check and the insert must not land the
        # entry without a reservation. Overwrites skip eviction — they
        # replace in place without growing the cache.
        dropped = []
        with self._budget._mu:
            with self._mu:
                is_new = key not in self._entries
            if is_new:
                dropped = self._budget._evict_to_fit_locked(1)
            with self._mu:
                old = self._entries.get(key)
                self._entries[key] = (value, _next_tick())
                self._entries.move_to_end(key)
                if old is not None and old[0] is not value:
                    self.released += 1
        _release_dropped(dropped)
        if old is not None and old[0] is not value:
            # an overwritten entry's executable must release like an
            # evicted one — a recompile for the same key otherwise leaks
            # the prior executable until (if ever) gc notices
            release_executable(old[0])

    def clear(self) -> None:
        with self._mu:
            dropped = [v for (v, _t) in self._entries.values()]
            self._entries.clear()
        for v in dropped:
            self.released += 1
            release_executable(v)

    # -- budget hooks ------------------------------------------------------

    def _oldest_tick(self):
        with self._mu:
            if not self._entries:
                return None
            first = next(iter(self._entries.values()))
            return first[1]

    def _pop_oldest(self):
        """Pop the LRU entry, returning its (key, value) for the budget
        to release — and report to `on_evict` — outside the locks
        (or _MISSING when empty)."""
        with self._mu:
            if not self._entries:
                return _MISSING
            k, (victim, _t) = self._entries.popitem(last=False)
            self.evictions += 1
            self.released += 1
            return (k, victim)


class _Missing:
    pass


_MISSING = _Missing()
