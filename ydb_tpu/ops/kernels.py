"""Scalar kernel registry — the block-function vocabulary.

Each kernel has one abstract semantics and two lowerings selected by the
array namespace (`numpy` for the CPU oracle, `jax.numpy` for the XLA path) —
the analog of the reference's dual scalar/block kernel surface
(`ydb/library/yql/minikql/invoke_builtins/` exposed as Arrow kernels via
`mkql_block_impl.h:33` and the ColumnShard custom registry
`ydb/core/formats/arrow/custom_registry.cpp:95`).

Null semantics:
  * ``propagate`` — result row is null iff any argument row is null
    (arithmetic, comparisons, math, casts, date extraction);
  * ``kleene``    — SQL three-valued AND/OR;
  * ``custom``    — kernel computes its own validity (coalesce, if,
    is_null, dictionary LUT gathers).

Values are (data, valid) pairs; ``valid is None`` means all-valid. Kernels
never branch on data-dependent Python conditions, so both lowerings trace
under ``jax.jit``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from ydb_tpu.core.dtypes import (
    BOOL, DType, FLOAT64, INT32, Kind, common_numeric,
)


@dataclass
class Kernel:
    name: str
    result_dtype: Callable       # (arg_dtypes, extra) -> DType
    impl: Callable               # (xp, datas, extra) -> data           [propagate]
    null_mode: str = "propagate"  # propagate | kleene_and | kleene_or | custom
    impl_nv: Optional[Callable] = None  # (xp, (data, valid) pairs, extra) -> (data, valid)


KERNELS: dict[str, Kernel] = {}


def _reg(name, result_dtype, impl=None, null_mode="propagate", impl_nv=None):
    KERNELS[name] = Kernel(name, result_dtype, impl, null_mode, impl_nv)


# -- dtype rules -----------------------------------------------------------

def _rt_common(ts, extra):
    out = ts[0]
    for t in ts[1:]:
        out = common_numeric(out, t)
    return out


def _rt_bool(ts, extra):
    return DType(Kind.BOOL, any(t.nullable for t in ts))


def _rt_float(ts, extra):
    return DType(Kind.FLOAT64, any(t.nullable for t in ts))


def _rt_same(ts, extra):
    return ts[0]


def _rt_div(ts, extra):
    if all(t.is_integer for t in ts):
        return DType(Kind.FLOAT64, any(t.nullable for t in ts))
    return _rt_common(ts, extra)


def _rt_cast(ts, extra):
    return DType(Kind(extra["to"]), ts[0].nullable)


def _rt_i32(ts, extra):
    return DType(Kind.INT32, ts[0].nullable)


# -- arithmetic ------------------------------------------------------------

_reg("add", _rt_common, lambda xp, a, e: a[0] + a[1])
_reg("sub", _rt_common, lambda xp, a, e: a[0] - a[1])
_reg("mul", _rt_common, lambda xp, a, e: a[0] * a[1])
_reg("div", _rt_div, lambda xp, a, e: _safe_div(xp, a[0], a[1]))
_reg("idiv", _rt_common, lambda xp, a, e: a[0] // xp.where(a[1] == 0, 1, a[1]))
_reg("mod", _rt_common, lambda xp, a, e: a[0] % xp.where(a[1] == 0, 1, a[1]))
_reg("neg", _rt_same, lambda xp, a, e: -a[0])
_reg("abs", _rt_same, lambda xp, a, e: xp.abs(a[0]))


def _safe_div(xp, a, b):
    # lint: transfer-ok(np.asarray only on the xp-is-np host lane — dtype probe, never a device value)
    num = a.astype(np.float64) if np.issubdtype(np.asarray(a).dtype if xp is np else a.dtype, np.integer) else a
    den = b.astype(num.dtype) if hasattr(b, "dtype") else b
    zero = den == 0
    return xp.where(zero, xp.zeros_like(num), num) / xp.where(zero, xp.ones_like(den), den)


# -- comparison ------------------------------------------------------------

_reg("eq", _rt_bool, lambda xp, a, e: a[0] == a[1])
_reg("ne", _rt_bool, lambda xp, a, e: a[0] != a[1])
_reg("lt", _rt_bool, lambda xp, a, e: a[0] < a[1])
_reg("le", _rt_bool, lambda xp, a, e: a[0] <= a[1])
_reg("gt", _rt_bool, lambda xp, a, e: a[0] > a[1])
_reg("ge", _rt_bool, lambda xp, a, e: a[0] >= a[1])


# -- boolean (Kleene) ------------------------------------------------------

def _and_nv(xp, args, extra):
    (da, va), (db, vb) = args
    if va is None and vb is None:
        return da & db, None
    ta = va if va is not None else _ones(xp, da)
    tb = vb if vb is not None else _ones(xp, db)
    # Kleene: false dominates null; null-as-true in data, masked by validity
    data = (da | ~ta) & (db | ~tb)
    valid = (ta & tb) | (ta & ~da) | (tb & ~db)
    return data, valid


def _or_nv(xp, args, extra):
    (da, va), (db, vb) = args
    data = da | db
    if va is None and vb is None:
        return data, None
    ta = va if va is not None else _ones(xp, da)
    tb = vb if vb is not None else _ones(xp, db)
    valid = (ta & tb) | (ta & da) | (tb & db)
    return (da & ta) | (db & tb), valid


def _ones(xp, like):
    return xp.ones(like.shape, dtype=bool) if hasattr(like, "shape") else True


def _zeros(xp, like):
    return xp.zeros(like.shape, dtype=bool) if hasattr(like, "shape") else False


_reg("and", _rt_bool, null_mode="custom", impl_nv=_and_nv)
_reg("or", _rt_bool, null_mode="custom", impl_nv=_or_nv)
_reg("not", _rt_bool, lambda xp, a, e: ~a[0])
_reg("xor", _rt_bool, lambda xp, a, e: a[0] ^ a[1])


# -- conditionals / null handling -----------------------------------------

def _if_nv(xp, args, extra):
    (dc, vc), (dt, vt), (df, vf) = args
    cond = dc if vc is None else (dc & vc)
    data = xp.where(cond, dt, df)
    if vt is None and vf is None:
        return data, None
    tt = vt if vt is not None else _ones(xp, data)
    tf = vf if vf is not None else _ones(xp, data)
    return data, xp.where(cond, tt, tf)


def _coalesce_nv(xp, args, extra):
    (da, va), (db, vb) = args
    if va is None:
        return da, None
    data = xp.where(va, da, db)
    valid = None if vb is None else (va | vb)
    return data, valid


def _is_null_nv(xp, args, extra):
    (da, va) = args[0]
    if va is None:
        return _zeros(xp, da) if not hasattr(da, "shape") else xp.zeros(da.shape, dtype=bool), None
    return ~va, None


def _is_not_null_nv(xp, args, extra):
    data, valid = _is_null_nv(xp, args, extra)
    return ~data, None


def _rt_if(ts, extra):
    t = common_numeric(ts[1], ts[2]) if (ts[1].is_numeric and ts[2].is_numeric) else ts[1]
    return t.with_nullable(ts[1].nullable or ts[2].nullable)


def _typed_null_nv(xp, args, extra):
    """All-null column with the dtype/shape of the argument (CASE w/o ELSE)."""
    (da, _va) = args[0]
    return da, xp.zeros(da.shape, dtype=bool)


_reg("if", _rt_if, null_mode="custom", impl_nv=_if_nv)
_reg("typed_null", lambda ts, e: ts[0].with_nullable(True),
     null_mode="custom", impl_nv=_typed_null_nv)
_reg("coalesce", lambda ts, e: ts[0].with_nullable(ts[1].nullable),
     null_mode="custom", impl_nv=_coalesce_nv)
_reg("is_null", lambda ts, e: DType(Kind.BOOL, False), null_mode="custom", impl_nv=_is_null_nv)
_reg("is_not_null", lambda ts, e: DType(Kind.BOOL, False), null_mode="custom", impl_nv=_is_not_null_nv)


# -- math ------------------------------------------------------------------

_reg("floor", _rt_same, lambda xp, a, e: xp.floor(a[0]))
_reg("ceil", _rt_same, lambda xp, a, e: xp.ceil(a[0]))
_reg("round", _rt_same, lambda xp, a, e: xp.sign(a[0]) * xp.floor(xp.abs(a[0]) + 0.5))
_reg("sqrt", _rt_float, lambda xp, a, e: xp.sqrt(xp.maximum(a[0], 0)))
_reg("exp", _rt_float, lambda xp, a, e: xp.exp(a[0]))
_reg("ln", _rt_float, lambda xp, a, e: xp.log(xp.maximum(a[0], 1e-300)))
_reg("pow", _rt_float, lambda xp, a, e: xp.power(a[0], a[1]))


# -- cast ------------------------------------------------------------------

def _cast_impl(xp, a, e):
    from ydb_tpu.core.dtypes import DType as _DT
    target = _DT(Kind(e["to"])).np
    return a[0].astype(target)


_reg("cast", _rt_cast, _cast_impl)


# -- date extraction (civil-from-days, branch-free) ------------------------
# Algorithm: Howard Hinnant's civil_from_days; pure integer ops → jittable.

def _civil(xp, days):
    z = days.astype(np.int64) + 719468
    era = xp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = xp.where(mp < 10, mp + 3, mp - 9)
    y = xp.where(m <= 2, y + 1, y)
    return y, m, d


_reg("year", _rt_i32, lambda xp, a, e: _civil(xp, a[0])[0].astype(np.int32))
_reg("month", _rt_i32, lambda xp, a, e: _civil(xp, a[0])[1].astype(np.int32))
_reg("day_of_month", _rt_i32, lambda xp, a, e: _civil(xp, a[0])[2].astype(np.int32))

# time-of-day extraction over unix-seconds int64 (TIMESTAMP storage is
# seconds; the reference's datetime2 UDF module is the analog surface)
_reg("hour_of_day", _rt_i32,
     lambda xp, a, e: ((a[0] // 3600) % 24).astype(np.int32))
_reg("minute_of_hour", _rt_i32,
     lambda xp, a, e: ((a[0] // 60) % 60).astype(np.int32))
_reg("second_of_minute", _rt_i32,
     lambda xp, a, e: (a[0] % 60).astype(np.int32))


# -- dictionary-coded string ops ------------------------------------------

def _take_lut_nv(xp, args, extra):
    """lut[code] gather; code<0 (null string) → null result.

    The LUT is a runtime Param computed host-side over the column dictionary
    (see core/dictionary.py) — this is how LIKE/substr/eq on strings run on
    the device without touching bytes.

    `null_neg`: the LUT VALUES are themselves dictionary codes where a
    negative entry means "the transform produced NULL for this input"
    (derived-string lane: regexp_extract with no match, split_part out of
    range) — the result validity must reflect it, or COUNT/IS NULL see a
    phantom value."""
    (codes, vc), (lut, _) = args
    safe = xp.clip(codes, 0, lut.shape[0] - 1) if hasattr(lut, "shape") else codes
    data = lut[safe]
    nul = codes < 0
    valid = ~nul if vc is None else (vc & ~nul)
    if extra.get("null_neg"):
        valid = valid & (data >= 0)
    return data, valid


def _rt_take_lut(ts, extra):
    return DType(ts[1].kind, True)


_reg("take_lut", _rt_take_lut, null_mode="custom", impl_nv=_take_lut_nv)


# -- hashing (for shuffles / joins) ---------------------------------------

from ydb_tpu.utils.hashing import hash_combine as _hc, splitmix64 as _sm64


def _rt_u64(ts, extra):
    return DType(Kind.UINT64, ts[0].nullable)


_reg("hash64", _rt_u64, lambda xp, a, e: _sm64(xp, a[0]))


def _hash_combine(xp, a, e):
    h = a[0]
    for x in a[1:]:
        h = _hc(xp, h, x)
    return h


_reg("hash_combine", _rt_u64, _hash_combine)
