"""Aggregation-state spill: HBM → host-DRAM partitioned merge.

The analog of the reference WideCombiner's state machine
(`ydb/library/yql/minikql/comp_nodes/mkql_wide_combine.cpp:338-600`,
InMemory → Spilling → ProcessSpilled): when the partial group-by states
of a query exceed the device merge budget, each partial block is
hash-partitioned BY GROUP KEY on the device (one sort dispatch), read
out to host DRAM, and the merge group-by then runs per partition —
partitions hold disjoint key sets, so per-partition merges compose into
the global result without ever holding all states in HBM at once.

TPU shape of the idea: the reference spills hash-table buckets to disk
and re-reads them; here the "bucket" is a key-hash partition of a
padded columnar block, the spill medium is host DRAM (125GB vs 16GB
HBM on this platform), and the partition step is a single fused
sort-by-partition dispatch instead of per-row bucket appends.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.utils.hashing import hash_combine, splitmix64

# fixed hash slot for NULL keys: every all-NULL key lands in one partition
_NULL_SENTINEL = -0x61C8864680B583EB


@partial(jax.jit, static_argnames=("names", "key_names", "nparts"))
def _partition_sort(arrays, valids, length, names: tuple, key_names: tuple,
                    nparts: int):
    """Sort a block's rows by key-hash partition id; returns the sorted
    columns plus per-partition row counts (one dispatch, one transfer
    when the caller fetches). Float keys hash on their int truncation —
    partitioning only needs same-key → same-partition, not injectivity."""
    cap = arrays[names[0]].shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = iota < length
    h = None
    for k in key_names:
        enc = arrays[k].astype(jnp.int64)
        v = valids.get(k)
        if v is not None:
            enc = jnp.where(v, enc, jnp.int64(_NULL_SENTINEL))
        x = splitmix64(jnp, enc)
        h = x if h is None else hash_combine(jnp, h, x)
    part = (h % jnp.uint64(nparts)).astype(jnp.int32)
    pkey = jnp.where(active, part, jnp.int32(nparts))
    # iota as the second key → stable order, and the output IS the
    # permutation (no carried operands — wide sorts explode compile time)
    _, perm = jax.lax.sort([pkey, iota], num_keys=2)
    counts = jnp.sum((pkey[:, None]
                      == jnp.arange(nparts, dtype=jnp.int32)[None, :]),
                     axis=0, dtype=jnp.int32)
    out_arrays = {n: a[perm] for n, a in arrays.items()}
    out_valids = {n: v[perm] for n, v in valids.items()}
    return out_arrays, out_valids, counts


class PartitionStore:
    """Host-DRAM store of key-hash partitions of partial-agg blocks.

    feed() spills one device block; partition(p) returns the
    host-concatenated rows of partition p across every fed block."""

    def __init__(self, schema, key_names: list, nparts: int,
                 dictionaries: dict | None = None):
        self.schema = schema
        self.key_names = tuple(key_names)
        self.nparts = nparts
        self.dictionaries = dict(dictionaries or {})
        # partition -> list of {name: np array}, {name: np bool array}
        self._parts: list = [[] for _ in range(nparts)]
        self.spilled_rows = 0
        self.spilled_bytes = 0

    def feed(self, dblock) -> None:
        names = tuple(dblock.schema.names)
        arrays, valids, counts = _partition_sort(
            dblock.arrays, dblock.valids, dblock.length, names,
            self.key_names, self.nparts)
        h_arrays, h_valids, h_counts = jax.device_get(
            (arrays, valids, counts))
        self.dictionaries.update(dblock.dictionaries)
        bounds = np.cumsum(h_counts)
        total = int(bounds[-1])
        self.spilled_rows += total
        lo = 0
        for p in range(self.nparts):
            hi = int(bounds[p])
            if hi > lo:
                piece_a = {n: a[lo:hi] for n, a in h_arrays.items()}
                piece_v = {n: v[lo:hi] for n, v in h_valids.items()}
                self._parts[p].append((piece_a, piece_v))
                self.spilled_bytes += sum(a.nbytes for a in piece_a.values())
                self.spilled_bytes += sum(v.nbytes for v in piece_v.values())
            lo = hi

    def partition(self, p: int) -> HostBlock:
        pieces = self._parts[p]
        cols = {}
        if not pieces:
            for c in self.schema.columns:
                cols[c.name] = ColumnData(np.zeros(0, dtype=c.dtype.np),
                                          None, self.dictionaries.get(c.name))
            return HostBlock(self.schema, cols, 0)
        n = sum(len(next(iter(a.values()))) for (a, _v) in pieces)
        for c in self.schema.columns:
            data = np.concatenate([a[c.name] for (a, _v) in pieces])
            valid = None
            if any(c.name in v for (_a, v) in pieces):
                valid = np.concatenate(
                    [v.get(c.name, np.ones(len(next(iter(a.values()))),
                                           np.bool_))
                     for (a, v) in pieces])
            cols[c.name] = ColumnData(data, valid,
                                      self.dictionaries.get(c.name))
        self._parts[p] = []          # release as soon as merged
        return HostBlock(self.schema, cols, n)


def host_sort_limit(block: HostBlock, sort: list, limit, offset,
                    dictionaries: dict | None = None) -> HostBlock:
    """Host-side ORDER BY + LIMIT/OFFSET over a merged result (the spill
    path's final pass — per-partition results are each sorted on device
    or small enough that a host lexsort is cheap). String keys order by
    dictionary value rank; NULLs honor nulls_first."""
    dicts = dict(dictionaries or {})
    if sort:
        keys = []
        for sk in reversed(sort):       # lexsort: last key is primary
            cd = block.columns[sk.name]
            data = cd.data
            dic = dicts.get(sk.name) or cd.dictionary
            if dic is not None and block.schema.dtype(sk.name).is_string:
                ranks = dic.sort_ranks().astype(np.int64)
                safe = np.clip(data.astype(np.int64), 0, len(ranks) - 1)
                data = ranks[safe]
            k = data.astype(np.float64) \
                if np.issubdtype(data.dtype, np.floating) \
                else data.astype(np.int64)
            if not sk.ascending:
                k = -k.astype(np.float64) if k.dtype == np.float64 else -k
            if cd.valid is not None:
                nullk = np.where(cd.valid, 0, -1 if sk.nulls_first else 1)
                keys.append(k)
                keys.append(nullk)       # appended after → higher priority
            else:
                keys.append(k)
        order = np.lexsort(tuple(keys))
        block = block.take(order)
    lo = offset or 0
    hi = block.length if limit is None else min(lo + limit, block.length)
    if lo or hi < block.length:
        block = block.slice(lo, hi)
    return block
