"""Broadcast hash-join — TPU-native MapJoin + duplicate-key expansion.

The reference's broadcast join (`mkql_map_join.cpp` MapJoinCore) builds a
host hash table and probes row-by-row; GraceJoin (`mkql_grace_join.cpp`)
handles duplicate keys by bucket partitioning. The TPU-native design
replaces both probes with fully vectorized binary search over a *sorted*
build side:

  * build (host, once per build table): sort build keys, keep the
    permutation — O(n log n) on small dimension tables;
  * unique-key probe (device, per block): ``jnp.searchsorted`` (vectorized
    binary search, log2(n) gathers) + one equality check + payload gathers;
  * duplicate-key probe (``probe_expand``): left/right searchsorted give
    each probe row its matching build range [lo, hi); an exclusive
    prefix-sum over the counts lays out the expanded output; one
    host sync picks the output capacity bucket; a second program maps each
    output slot back to (probe row, build row) with two searchsorted-style
    gathers. This is the TPU analog of GraceJoin's duplicate handling —
    expansion instead of per-bucket nested loops.

Join kinds: inner, left, left_semi, left_anti (the kinds KQP plans emit for
broadcast joins), plus mark (match-flag attach, unique builds only).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu.core.block import ColumnData, HostBlock
from ydb_tpu.core.dtypes import DType, Kind
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops.device import DeviceBlock, bucket_capacity


def _host_key(block: HostBlock, name: str) -> tuple[np.ndarray, Optional[np.ndarray]]:
    """Key in its search domain: float keys stay float64, the rest int64.

    (No IEEE bitcast encodings: the TPU x64 emulation pass cannot rewrite
    f64<->s64 bitcasts, and searchsorted compares floats natively.)"""
    cd = block.columns[name]
    d = cd.data
    if np.issubdtype(d.dtype, np.floating):
        return d.astype(np.float64), cd.valid
    return d.astype(np.int64), cd.valid


_LUT_SPAN_BUDGET = 1 << 26         # max direct-address entries (256MB int32)
_FD_BUDGET = 1 << 28               # max host bytes retained for FD checks


@dataclass
class BuildTable:
    """Sorted build side, resident on device.

    When the key is integral with a bounded span, a direct-address lookup
    table maps (key - lut_base) → sorted build row (-1 = absent), so a probe
    is ONE fused gather instead of a binary search (`jnp.searchsorted`
    lowers to a serializing scan loop on this platform — see PERF.md).
    With duplicate keys the LUT holds the FIRST sorted row of the key
    run (existence checks — semi/anti/mark — stay LUT-probeable)."""
    keys_sorted: object            # jnp int64 (padded with INT64_MAX)
    n: int                         # real build rows
    payload: dict                  # name -> jnp array (sorted by key)
    payload_valid: dict            # name -> jnp bool
    schema: Schema                 # payload schema
    dictionaries: dict
    unique: bool
    lut: object = None             # jnp int32 (span,) or None
    lut_base: int = 0              # key value of lut[0]
    # NOT IN: the build side contained a NULL key — x NOT IN S is then
    # never TRUE for any x (NULL or FALSE), so a not_in anti probe must
    # select nothing. Set by the executor's anti-null check.
    anti_has_null: bool = False
    # bounds lattice: the HOST build block (post null-key drop), retained
    # so the executor's carry rewrite can VERIFY functional dependencies
    # between payload columns by measured distinct counts
    # (`Executor._fd_determinant`). None above the retention budget.
    fd_block: object = None
    fd_memo: object = None         # {cols tuple: distinct count} cache


def build(block: HostBlock, key: str, payload_names: list[str],
          keep_fd: bool = False) -> BuildTable:
    enc, valid = _host_key(block, key)
    if valid is not None:
        # null build keys never match; drop them
        keep = np.nonzero(valid)[0]
        block = block.take(keep)
        enc = enc[keep]
    order = np.argsort(enc, kind="stable")
    enc = enc[order]
    unique = bool(np.all(np.diff(enc) != 0)) if len(enc) > 1 else True
    cap = bucket_capacity(max(len(enc), 1), minimum=128)
    sentinel = np.inf if enc.dtype == np.float64 else np.iinfo(np.int64).max
    keys_pad = np.full(cap, sentinel, dtype=enc.dtype)
    keys_pad[:len(enc)] = enc

    lut = None
    lut_base = 0
    if enc.dtype != np.float64 and len(enc):
        lo, hi = int(enc[0]), int(enc[-1])
        span = hi - lo + 1
        # density cap 64x: a filtered 1.6M-row build over a 15M-key span
        # (TPC-H q3/q18 shapes) is a 60MB LUT — far cheaper than losing
        # whole-query fusion; the absolute budget still bounds HBM
        if 0 < span <= max(1 << 12, min(_LUT_SPAN_BUDGET, 64 * len(enc))):
            span_cap = bucket_capacity(span, minimum=1024)
            lut_np = np.full(span_cap, -1, np.int32)
            offs = (enc - lo).astype(np.int64)
            # first sorted row of each key run wins (reversed assignment:
            # numpy keeps the last write, which is the run's first row)
            lut_np[offs[::-1]] = np.arange(len(enc) - 1, -1, -1,
                                           dtype=np.int32)
            lut = jnp.asarray(lut_np)
            lut_base = lo

    payload, payload_valid, dicts = {}, {}, {}
    for name in payload_names:
        cd = block.columns[name]
        d = cd.data[order]
        pad = np.zeros(cap - len(d), dtype=d.dtype)
        payload[name] = jnp.asarray(np.concatenate([d, pad]))
        if cd.valid is not None:
            v = np.concatenate([cd.valid[order], np.zeros(cap - len(d), np.bool_)])
            payload_valid[name] = jnp.asarray(v)
        if cd.dictionary is not None:
            dicts[name] = cd.dictionary
    # retain the host block for measured functional-dependency checks
    # (the carry rewrite's dataset verification) — host RAM is the cheap
    # side of this platform, but only the consumer's exact shape pins it:
    # the caller passes keep_fd when the consuming pipeline carries a
    # multi-key group-by (`Executor._prepare_builds`), and the FD lane
    # only ever reads unique-keyed builds with the lattice ON; anything
    # else (and any build past the budget) just skips the FD lane and
    # keeps every key in the sort identity
    from ydb_tpu.query.bounds import bounds_enabled
    fd_block = block if keep_fd and unique and bounds_enabled() \
        and block.length \
        and sum(cd.data.nbytes
                for cd in block.columns.values()) <= _FD_BUDGET \
        else None
    return BuildTable(jnp.asarray(keys_pad), len(enc), payload, payload_valid,
                      block.schema.select(payload_names), dicts, unique,
                      lut, lut_base, fd_block=fd_block)


def place(table: BuildTable, device) -> BuildTable:
    """Replicate a build table onto a specific device (the broadcast leg of
    MapJoin on a mesh: every device probes its own copy)."""
    put = lambda x: jax.device_put(x, device)  # noqa: E731
    return BuildTable(
        put(table.keys_sorted), table.n,
        {k: put(v) for k, v in table.payload.items()},
        {k: put(v) for k, v in table.payload_valid.items()},
        table.schema, table.dictionaries, table.unique,
        None if table.lut is None else put(table.lut), table.lut_base,
        table.anti_has_null)


@dataclass
class PartitionedBuild:
    """GraceJoin-style hash-partitioned build side (`mkql_grace_join.cpp`):
    the build rows are split host-side by key hash into partitions small
    enough for the device budget; the probe side routes each row to its
    key's partition, so every partition joins independently. Partitions
    stay in host DRAM until probed — the HBM→host spill discipline of
    SURVEY §5.7 (the reference spills buckets to disk)."""
    tables: list                   # [BuildTable] per partition
    n_partitions: int
    key: str


def build_partitioned(block: HostBlock, key: str, payload_names: list[str],
                      budget_bytes: int) -> PartitionedBuild:
    """Partition a too-big build side by key hash (splitmix64, matching
    the device-side routing in the probe)."""
    from ydb_tpu.utils.hashing import splitmix64

    row_bytes = max(1, sum(block.columns[n].data.itemsize
                           for n in payload_names) + 8)
    total = row_bytes * max(block.length, 1)
    nparts = 1
    while total / nparts > budget_bytes:
        nparts *= 2
    enc, _valid = _host_key(block, key)
    h = splitmix64(np, enc.astype(np.int64))
    part = (h % np.uint64(nparts)).astype(np.int64)
    tables = []
    for p in range(nparts):
        idx = np.nonzero(part == p)[0]
        tables.append(build(block.take(idx), key, payload_names))
    return PartitionedBuild(tables, nparts, key)


def bsearch_traced(keys_sorted, enc):
    """Branchless lower_bound as log2(cap) UNROLLED gathers — the fused
    replacement for `jnp.searchsorted`, which lowers to a serializing
    scan loop on this platform (~4s for 6M probes, PERF.md). keys_sorted
    must be padded to a power-of-two capacity with a +inf/INT64_MAX
    sentinel (what `build()` produces)."""
    cap = keys_sorted.shape[0]
    assert cap & (cap - 1) == 0, "bsearch needs a pow2-padded build"
    pos = jnp.zeros(enc.shape, jnp.int32)
    step = cap >> 1
    while step:
        kv = keys_sorted[pos + (step - 1)]
        pos = jnp.where(kv < enc, pos + step, pos)
        step >>= 1
    return pos


def probe_lut_traced(env: dict, sel, bt_arrays: dict, meta: dict):
    """Build-probe inside a fused query trace (`ops/fused.py`): a
    direct-address LUT gather when the build has one, an unrolled
    binary search otherwise (sparse key spans, float keys).

    env: {name: (data, valid|None)}; sel: bool selection mask — REQUIRED,
    and must already include the row-activity mask (`iota < length`; the
    fused pipeline threads it instead of compressing, so there is no
    separate length here); bt_arrays: traced build inputs {lut, lut_base,
    n, keys, payload.<name>, pvalid.<name>}; meta (static): probe_key,
    kind, payload_names (post-rename), src_names, mark_col, not_in,
    bsearch.

    Returns (env', sel'). Selection semantics match `_probe`: matched rows
    selected for inner/semi, unmatched for anti, all for left/mark."""
    if sel is None:
        raise ValueError("probe_lut_traced needs the row-activity mask")
    d, v = env[meta["probe_key"]]
    active = sel
    matchable = active if v is None else (active & v)
    kind = meta["kind"]

    if meta.get("bsearch"):
        keys = bt_arrays["keys"]
        enc = _probe_enc(d)
        pos = bsearch_traced(keys, enc)
        idx = jnp.clip(pos, 0, keys.shape[0] - 1)
        found = (keys[idx] == enc) & matchable \
            & (idx < bt_arrays["n"])
    else:
        if np.issubdtype(np.dtype(d.dtype), np.floating):
            # LUTs address integer keys; truncating a float probe would
            # mis-match (10.5 → 10) — floats must take the bsearch path
            raise TypeError("LUT probe requires an integral probe key")
        enc = d.astype(jnp.int64)
        lut = bt_arrays["lut"]
        span = lut.shape[0]
        off = enc - bt_arrays["lut_base"]
        inb = (off >= 0) & (off < span)
        idx = lut[jnp.clip(off, 0, span - 1).astype(jnp.int32)]
        found = inb & (idx >= 0) & matchable

    pcap = next(iter(bt_arrays["payload"].values())).shape[0] \
        if bt_arrays["payload"] else d.shape[0]
    safe = jnp.clip(idx, 0, pcap - 1)
    # late materialization: thread the (build row-id, match) pair instead
    # of gathering payload widths at probe capacity — the fused body
    # gathers from `payload[...]` at the first reference (post-compact)
    # or at the bound-sized tail (`ops/fused.py`). Selection semantics
    # are computed identically either way.
    late = bool(meta.get("late")) and kind in ("inner", "left")
    out_sel, gathered, gathered_valid = _select_and_gather(
        found, safe, active, v, bt_arrays["n"], kind, meta["not_in"],
        bt_arrays["payload"], bt_arrays["pvalid"],
        () if late else meta["src_names"])

    if kind == "left_anti" and meta["not_in"]:
        # a NULL in the build set makes NOT IN never-true for every row
        out_sel = out_sel & ~bt_arrays["has_null"]

    env2 = dict(env)
    if late:
        env2[meta["row_col"]] = (safe.astype(jnp.int32), None)
        env2[meta["found_col"]] = (found, None)
    else:
        for src, out in zip(meta["src_names"], meta["payload_names"]):
            if src in gathered:
                env2[out] = (gathered[src], gathered_valid[src])
    if kind == "mark":
        env2[meta["mark_col"] or "__mark"] = (found, None)
    return env2, out_sel


def _probe_enc(d):
    if d.dtype in (jnp.float64, jnp.float32):
        return d.astype(jnp.float64)
    return d.astype(jnp.int64)


@partial(jax.jit, static_argnames=("probe_key", "kind", "payload_names",
                                   "not_in"))
def _probe(probe_arrays, probe_valids, length, sel, n_build,
           keys_sorted, payload, payload_valid,
           probe_key, kind: str, payload_names: tuple, not_in: bool = False):
    cap = probe_arrays[probe_key].shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    row_mask = iota < length
    active = row_mask if sel is None else (row_mask & sel)

    d = probe_arrays[probe_key]
    enc = _probe_enc(d)
    v = probe_valids.get(probe_key)
    # NULL probe keys never match but must survive LEFT / LEFT ANTI joins
    matchable = active if v is None else (active & v)

    padded = keys_sorted.shape[0]
    pos = jnp.searchsorted(keys_sorted, enc).astype(jnp.int32)
    safe = jnp.clip(pos, 0, padded - 1)
    # `safe < n_build` guards against probe keys equal to the padding
    # sentinel (INT64_MAX / +inf) matching padding slots
    found = (keys_sorted[safe] == enc) & matchable & (safe < n_build)
    out_sel, gathered, gathered_valid = _select_and_gather(
        found, safe, active, v, n_build, kind, not_in, payload,
        payload_valid, payload_names)
    return out_sel, gathered, gathered_valid, found


def _select_and_gather(found, safe, active, v, n_build, kind: str,
                       not_in: bool, payload, payload_valid,
                       payload_names: tuple):
    """Shared post-match join logic (selection semantics + payload
    gathers) for the searchsorted (`_probe`) and LUT
    (`probe_lut_traced`) probes — the NOT IN three-valued rule and
    null-extension behavior live only here."""
    out_sel = found if kind in ("inner", "left_semi") else (
        (~found) & active if kind == "left_anti" else active)
    if kind == "left_anti" and not_in and v is not None:
        # x NOT IN S: NULL when x is NULL and S non-empty (row excluded),
        # TRUE when S is empty (row kept regardless of x)
        out_sel = out_sel & (v | (n_build == 0))

    gathered, gathered_valid = {}, {}
    if kind in ("inner", "left", "mark"):
        for name in payload_names:
            gathered[name] = payload[name][safe]
            pv = payload_valid.get(name)
            gathered_valid[name] = found if pv is None else (found & pv[safe])
    return out_sel, gathered, gathered_valid


def probe(dblock: DeviceBlock, table: BuildTable, probe_key: str,
          kind: str = "inner", sel=None,
          rename: Optional[dict] = None,
          mark_col: Optional[str] = None,
          not_in: bool = False) -> tuple[DeviceBlock, object]:
    """Probe a device block against a build table.

    Returns (new DeviceBlock with payload columns appended, new selection
    mask). The caller decides when to compress.

    kind "mark" keeps every active row, attaches payloads (null where
    unmatched) and a bool `mark_col` column holding the match flag — the
    building block for semi/anti joins that need post-join verification
    (composite hash keys, NOT IN null checks).
    """
    if not table.unique and kind in ("inner", "left", "mark"):
        raise ValueError(
            "broadcast MapJoin requires unique build keys for inner/left "
            "joins; duplicate keys need the partitioned GraceJoin path")
    rename = rename or {}
    names = tuple(table.schema.names)
    out_sel, gathered, gathered_valid, found = _probe(
        dblock.arrays, dblock.valids, dblock.length, sel, jnp.int32(table.n),
        table.keys_sorted, table.payload, table.payload_valid,
        probe_key, kind, names, not_in)
    if kind == "left_anti" and not_in and table.anti_has_null:
        # NULL in the build set: NOT IN is never TRUE (host-static — the
        # flag is known at build time, no traced input needed here)
        out_sel = jnp.zeros_like(out_sel)

    arrays = dict(dblock.arrays)
    valids = dict(dblock.valids)
    dicts = dict(dblock.dictionaries)
    cols = list(dblock.schema.columns)
    if kind in ("inner", "left", "mark"):
        for name in names:
            out_name = rename.get(name, name)
            arrays[out_name] = gathered[name]
            valids[out_name] = gathered_valid[name]
            dt = table.schema.dtype(name).with_nullable(True)
            cols = [c for c in cols if c.name != out_name] + [Column(out_name, dt)]
            if name in table.dictionaries:
                dicts[out_name] = table.dictionaries[name]
    if kind == "mark":
        name = mark_col or "__mark"
        arrays[name] = found
        cols = [c for c in cols if c.name != name] + [
            Column(name, DType(Kind.BOOL, nullable=False))]
    schema = Schema(cols)
    out = DeviceBlock(schema, arrays, valids, dblock.length, dblock.capacity, dicts)
    return out, out_sel


# -- duplicate-key (expanding) probe ---------------------------------------

@partial(jax.jit, static_argnames=("probe_key", "left"))
def _expand_counts(probe_arrays, probe_valids, length, n_build, keys_sorted,
                   probe_key, left: bool):
    cap = probe_arrays[probe_key].shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    active = iota < length
    enc = _probe_enc(probe_arrays[probe_key])
    v = probe_valids.get(probe_key)
    matchable = active if v is None else (active & v)

    lo = jnp.searchsorted(keys_sorted, enc, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(keys_sorted, enc, side="right").astype(jnp.int32)
    # sentinel padding (+inf / INT64_MAX) must not count as matches
    lo = jnp.minimum(lo, n_build)
    hi = jnp.minimum(hi, n_build)
    mcounts = jnp.where(matchable, hi - lo, 0)
    counts = jnp.where(active, jnp.maximum(mcounts, 1), 0) if left \
        else mcounts
    offsets = jnp.cumsum(counts) - counts          # exclusive prefix sum
    total = jnp.sum(counts)
    return lo, mcounts, counts, offsets, total


@partial(jax.jit, static_argnames=("kind", "payload_names", "out_cap"))
def _expand_gather(probe_arrays, probe_valids, lo, mcounts, offsets, total,
                   payload, payload_valid, kind: str, payload_names: tuple,
                   out_cap: int):
    cap = lo.shape[0]
    j = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, cap - 1)
    k = j - offsets[row]
    padded = next(iter(payload.values())).shape[0] if payload else cap
    bidx = jnp.clip(lo[row] + k, 0, padded - 1)
    live = j < total
    found = (mcounts[row] > 0) & live

    out_arrays = {n: a[row] for n, a in probe_arrays.items()}
    out_valids = {n: v[row] for n, v in probe_valids.items()}
    for n in payload_names:
        out_arrays[n] = payload[n][bidx]
        pv = payload_valid.get(n)
        out_valids[n] = found if pv is None else (found & pv[bidx])
    return out_arrays, out_valids


def probe_expand(dblock: DeviceBlock, table: BuildTable, probe_key: str,
                 kind: str = "inner",
                 rename: Optional[dict] = None) -> DeviceBlock:
    """Join a device block against a build table with duplicate keys.

    Returns a NEW compacted DeviceBlock whose capacity is the bucket for
    the expanded row count (inner: one output row per probe×build match;
    left: additionally one null-extended row per unmatched probe row).
    One device→host sync decides the capacity bucket.
    """
    assert kind in ("inner", "left"), kind
    rename = rename or {}
    lo, mcounts, counts, offsets, total = _expand_counts(
        dblock.arrays, dblock.valids, dblock.length, jnp.int32(table.n),
        table.keys_sorted, probe_key, kind == "left")
    n_out = int(total)                     # sync point (capacity decision)
    out_cap = bucket_capacity(max(n_out, 1), minimum=128)
    names = tuple(table.schema.names)
    payload = {rename.get(n, n): table.payload[n] for n in names}
    payload_valid = {rename.get(n, n): v for n, v in
                     table.payload_valid.items()}
    out_names = tuple(rename.get(n, n) for n in names)
    out_arrays, out_valids = _expand_gather(
        dblock.arrays, dblock.valids, lo, mcounts, offsets, total,
        payload, payload_valid, kind, out_names, out_cap)

    dicts = dict(dblock.dictionaries)
    cols = [c for c in dblock.schema.columns if c.name not in out_names]
    for n in names:
        out_name = rename.get(n, n)
        dt = table.schema.dtype(n).with_nullable(True)
        cols.append(Column(out_name, dt))
        if n in table.dictionaries:
            dicts[out_name] = table.dictionaries[n]
    return DeviceBlock(Schema(cols), out_arrays, out_valids,
                       jnp.int32(n_out), out_cap, dicts)
