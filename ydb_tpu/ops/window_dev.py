"""Device-side window functions.

The r4 engine evaluated every window spec in a pandas host lane
(`query/window.py`) — honest but single-core, and the host-lane guard
simply REFUSED large frames. This module evaluates the common specs as
ONE scatter-free jitted program over the whole frame, the TPU-native
shape of the reference's block window kernels (`mkql_block_top.cpp`,
peephole window rewrites `yql_opt_peephole_physical.cpp:5810`):

  * one `lax.sort` per distinct (PARTITION BY, ORDER BY) clause —
    partition keys hash-combined into ONE u64 operand (equality only),
    order keys encoded into order-preserving operands, the row id riding
    along as the permutation (never value columns: sort operand count is
    the compile-time cliff, PERF.md);
  * partition/order boundaries by adjacent comparison; segment starts /
    ends via cummax over flipped/unflipped iotas;
  * row_number / rank / dense_rank from boundary cumsums;
  * running and whole-partition SUM/COUNT/AVG from prefix sums against
    the segment-start prefix (NULLs excluded via a parallel validity
    cumsum);
  * running MIN/MAX as a segmented prefix scan (`lax.associative_scan`
    with a reset-at-boundary combiner);
  * ROWS BETWEEN frames for sum/count/avg from the same prefix sums at
    clipped offsets;
  * LEAD/LAG as clipped in-segment gathers;
  * results return to source row order through one inverse permutation
    (argsort of the sort permutation — a 2-operand sort) and ONE
    device→host transfer for all outputs.

Unsupported shapes (float partition keys, bounded min/max frames,
exotic funcs) decline → the caller keeps the pandas lane.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu.utils.hashing import hash_combine, splitmix64

DEVICE_FUNCS = {"row_number", "rank", "dense_rank", "sum", "min", "max",
                "count", "avg", "lead", "lag"}

_I64MAX = np.iinfo(np.int64).max


# ---------------------------------------------------------------------------
# host-side spec compilation: which specs can run on device, key encodings
# ---------------------------------------------------------------------------


def _sort_group_key(spec) -> tuple:
    return (tuple(spec["part"]), tuple(spec["order"]), tuple(spec["asc"]))


def spec_supported(spec, block) -> bool:
    fn = spec["func"]
    if fn not in DEVICE_FUNCS:
        return False
    frame = spec.get("frame")
    if frame is not None:
        if fn in ("min", "max"):
            return False              # bounded sliding min/max: host lane
        if fn in ("row_number", "rank", "dense_rank", "lead", "lag"):
            return False              # frame is meaningless / unsupported
        _tag, lo, hi = frame
        for b in (lo, hi):
            if not isinstance(b, (int, tuple)):
                return False
    if fn in ("lead", "lag"):
        # arg 0 = value, optional arg 1 = offset literal (inner select
        # materializes it as a column; constant columns only). The
        # 3-arg DEFAULT form stays on the host lane.
        if not spec["args"] or len(spec["args"]) > 2:
            return False
    for name in spec["part"]:
        cd = block.columns[name]
        if np.issubdtype(cd.data.dtype, np.floating):
            return False              # no f64 bitcast on this platform
    return True


def _encode_part_host(block, names):
    """Partition keys → (arrays to hash, validity ints). Equality-only."""
    out = []
    for n in names:
        cd = block.columns[n]
        out.append((cd.data.astype(np.int64),
                    None if cd.valid is None
                    else cd.valid.astype(np.int64)))
    return out


def _encode_order_host(block, name, ascending):
    """One order key → an order-preserving f64/i64 array with NULLs
    mapped last (pandas na_position='last' parity)."""
    cd = block.columns[name]
    d = cd.data
    if cd.dictionary is not None:
        ranks = cd.dictionary.sort_ranks()
        d = ranks[np.clip(d, 0, None)].astype(np.int64)
        d = np.where(cd.data < 0, 0, d)
    if np.issubdtype(d.dtype, np.floating):
        enc = d.astype(np.float64)
        if not ascending:
            enc = -enc
        if cd.valid is not None:
            enc = np.where(cd.valid, enc, np.inf)
        enc = np.where(np.isnan(enc), np.inf, enc)
        return enc
    enc = d.astype(np.int64)
    if not ascending:
        enc = -enc
    if cd.valid is not None:
        enc = np.where(cd.valid, enc, _I64MAX)
    return enc


# ---------------------------------------------------------------------------
# traced helpers
# ---------------------------------------------------------------------------


def _seg_starts(boundary, iota):
    """Index of each row's segment start (boundary[0] must be True)."""
    return jax.lax.cummax(jnp.where(boundary, iota, 0))


def _seg_ends(boundary, iota, n):
    """Index of each row's segment END (inclusive). boundary marks
    segment STARTS; a start at i+1 means i is an end."""
    nxt = jnp.concatenate([boundary[1:], jnp.ones((1,), bool)])
    rev = jnp.flip(jnp.where(nxt, iota, n - 1))
    return jnp.flip(jax.lax.cummin(rev))


def _segmented_scan_minmax(v, boundary, is_min):
    """Running min/max with reset at segment boundaries."""
    def combine(a, b):
        ab, av = a
        bb, bv = b
        merged = jnp.where(bb, bv,
                           jnp.minimum(av, bv) if is_min
                           else jnp.maximum(av, bv))
        return (ab | bb, merged)
    _b, out = jax.lax.associative_scan(combine, (boundary, v))
    return out


def _prefix(v):
    """Exclusive prefix sums of shape (n+1,): P[i] = sum(v[:i])."""
    return jnp.concatenate([jnp.zeros((1,), v.dtype), jnp.cumsum(v)])


def _build_window_fn(struct):
    """Trace one jitted program computing every spec in `struct`:
    {"groups": [{"n_part_ops": int, "n_order": int,
                 "specs": [{"func","frame","has_arg","arg_float",
                            "offset","alias"}]}], "cap": int}"""

    @jax.jit
    def fn(inputs):
        L = inputs["length"]
        cap = inputs["iota"].shape[0]
        iota = inputs["iota"]
        active = iota < L
        outs = {}
        for gi, grp in enumerate(struct["groups"]):
            # --- one sort per clause group
            phash = jnp.zeros(cap, jnp.uint64)
            for pi in range(grp["n_part_ops"]):
                phash = hash_combine(
                    jnp, phash,
                    splitmix64(jnp, inputs[f"g{gi}p{pi}"]))
            # padded rows sort to the back as their own partition
            phash = jnp.where(active, phash >> jnp.uint64(1),
                              jnp.uint64(np.uint64(2**64 - 1)))
            operands = [phash]
            for oi in range(grp["n_order"]):
                operands.append(inputs[f"g{gi}o{oi}"])
            operands.append(iota)
            sorted_ops = jax.lax.sort(tuple(operands),
                                      num_keys=len(operands) - 1)
            perm = sorted_ops[-1]
            s_hash = sorted_ops[0]
            # --- boundaries
            first = jnp.zeros(cap, bool).at[0].set(True)  # static index
            b_part = jnp.concatenate(
                [jnp.ones((1,), bool), s_hash[1:] != s_hash[:-1]])
            b_order = b_part
            for oi in range(grp["n_order"]):
                so = sorted_ops[1 + oi]
                b_order = b_order | jnp.concatenate(
                    [jnp.ones((1,), bool), so[1:] != so[:-1]])
            del first
            seg_start = _seg_starts(b_part, iota)
            seg_end = _seg_ends(b_part, iota, cap)
            inv = jax.lax.sort((perm, iota), num_keys=1)[1]

            def unsort(x):
                return x[inv]

            # dense-rank prefix over order boundaries (shared)
            corder = jnp.cumsum(b_order.astype(jnp.int64))

            for si, spec in enumerate(grp["specs"]):
                fnname = spec["func"]
                if fnname == "row_number":
                    out = iota - seg_start + 1
                    outs[spec["alias"]] = (unsort(out), None)
                    continue
                if fnname == "rank":
                    grp_start = jax.lax.cummax(
                        jnp.where(b_order, iota, 0))
                    out = grp_start - seg_start + 1
                    outs[spec["alias"]] = (unsort(out), None)
                    continue
                if fnname == "dense_rank":
                    out = corder - corder[seg_start] + 1
                    outs[spec["alias"]] = (unsort(out), None)
                    continue
                if fnname in ("lead", "lag"):
                    v = inputs[f"g{gi}s{si}a"][perm]
                    valid_in = inputs.get(f"g{gi}s{si}av")
                    sv = valid_in[perm] if valid_in is not None else None
                    off = spec["offset"]
                    tgt = iota + off if fnname == "lead" else iota - off
                    inside = (tgt >= seg_start) & (tgt <= seg_end) \
                        & (tgt >= 0) & (tgt < cap)
                    tgt_c = jnp.clip(tgt, 0, cap - 1)
                    out = v[tgt_c]
                    ov = inside if sv is None else (inside & sv[tgt_c])
                    outs[spec["alias"]] = (unsort(out), unsort(ov))
                    continue
                # aggregates --------------------------------------------
                has_arg = spec["has_arg"]
                if has_arg:
                    v = inputs[f"g{gi}s{si}a"][perm]
                    valid_in = inputs.get(f"g{gi}s{si}av")
                    sv = valid_in[perm] if valid_in is not None \
                        else jnp.ones(cap, bool)
                else:                     # count(*)
                    v = jnp.ones(cap, jnp.int64)
                    sv = jnp.ones(cap, bool)
                sv = sv & (perm < L)
                filled = jnp.where(sv, v, jnp.zeros((), v.dtype))
                frame = spec["frame"]
                if fnname in ("min", "max"):
                    ident = jnp.array(
                        np.inf if fnname == "min" else -np.inf, v.dtype) \
                        if jnp.issubdtype(v.dtype, jnp.floating) else \
                        jnp.array(_I64MAX if fnname == "min"
                                  else -_I64MAX - 1, v.dtype)
                    vm = jnp.where(sv, v, ident)
                    if spec["running"]:
                        out = _segmented_scan_minmax(vm, b_part,
                                                     fnname == "min")
                        nn = jnp.cumsum(sv.astype(jnp.int64))
                        nnrun = nn - nn[seg_start] \
                            + sv[seg_start].astype(jnp.int64)
                        ov = nnrun > 0
                    else:
                        run = _segmented_scan_minmax(vm, b_part,
                                                     fnname == "min")
                        out = run[seg_end]
                        nn = jnp.cumsum(sv.astype(jnp.int64))
                        tot = nn[seg_end] - nn[seg_start] \
                            + sv[seg_start].astype(jnp.int64)
                        ov = tot > 0
                    outs[spec["alias"]] = (unsort(out), unsort(ov))
                    continue
                cs = _prefix(filled)
                cn = _prefix(sv.astype(jnp.int64))
                if frame is not None:
                    _tag, lo, hi = frame
                    lo_unb = not isinstance(lo, int)
                    hi_unb = not isinstance(hi, int)
                    start = seg_start if lo_unb \
                        else jnp.clip(iota + lo, seg_start, seg_end + 1)
                    end1 = seg_end + 1 if hi_unb \
                        else jnp.clip(iota + hi + 1, seg_start,
                                      seg_end + 1)
                    start = jnp.minimum(start, end1)
                elif spec["running"]:
                    start, end1 = seg_start, iota + 1
                else:
                    start, end1 = seg_start, seg_end + 1
                ssum = cs[end1] - cs[start]
                scnt = cn[end1] - cn[start]
                if fnname == "count":
                    outs[spec["alias"]] = (unsort(scnt), None)
                elif fnname == "sum":
                    outs[spec["alias"]] = (unsort(ssum),
                                           unsort(scnt > 0))
                else:                     # avg
                    a = ssum.astype(jnp.float64) / jnp.maximum(scnt, 1)
                    outs[spec["alias"]] = (unsort(a), unsort(scnt > 0))
        return outs

    return fn


_FN_CACHE = None


def _fn_cache():
    global _FN_CACHE
    if _FN_CACHE is None:
        from ydb_tpu.ops.exec_cache import ExecCache
        _FN_CACHE = ExecCache("window")
    return _FN_CACHE


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def compute_windows_device(block, outer):
    """Evaluate every window spec of `outer` on device. Returns
    {alias: (np values, np valid|None)} or None when any spec (or key
    encoding) requires the host lane."""
    from ydb_tpu.ops.device import bucket_capacity

    specs = [p for k, p in outer if k == "win"]
    if not specs or block.length == 0:
        return None
    for s in specs:
        if not spec_supported(s, block):
            return None

    # group by sort clause; build the static structure + input arrays
    groups: dict = {}
    order = []
    for s in specs:
        k = _sort_group_key(s)
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(s)

    L = block.length
    cap = bucket_capacity(max(L, 1))
    pad = cap - L

    def up(a, fill=0):
        if pad:
            a = np.concatenate(
                [a, np.full(pad, fill, dtype=a.dtype)])
        return jnp.asarray(a)

    inputs = {"length": jnp.int64(L),
              "iota": jnp.arange(cap, dtype=jnp.int64)}
    struct = {"groups": [], "cap": cap}
    for gi, k in enumerate(order):
        part, onames, asc = k
        gspecs = groups[k]
        pi = 0
        for name in part:
            for arr in _encode_part_host(block, [name])[0]:
                if arr is None:
                    continue
                inputs[f"g{gi}p{pi}"] = up(arr)
                pi += 1
        for oi, name in enumerate(onames):
            enc = _encode_order_host(block, name, asc[oi])
            inputs[f"g{gi}o{oi}"] = up(
                enc, fill=np.inf if enc.dtype == np.float64 else _I64MAX)
        sspecs = []
        for si, s in enumerate(gspecs):
            fn = s["func"]
            has_arg = bool(s["args"]) and not (
                fn == "count" and not s["args"])
            offset = 1
            if fn in ("lead", "lag") and len(s["args"]) > 1:
                off_cd = block.columns[s["args"][1]]
                offset = int(off_cd.data[0])
                if not (off_cd.data[:L] == off_cd.data[0]).all():
                    return None       # non-constant offset: host lane
            if has_arg:
                cd = block.columns[s["args"][0]]
                if cd.dictionary is not None and fn in (
                        "sum", "avg", "min", "max", "count"):
                    return None       # string aggregates: host lane
                d = cd.data
                if d.dtype == np.bool_:
                    d = d.astype(np.int64)
                inputs[f"g{gi}s{si}a"] = up(d)
                if cd.valid is not None:
                    inputs[f"g{gi}s{si}av"] = up(
                        cd.valid, fill=False)
            sspecs.append({
                "func": fn, "frame": s.get("frame"),
                "has_arg": has_arg,
                "running": bool(s["order"]),
                "offset": offset, "alias": s["alias"],
                "dict": (block.columns[s["args"][0]].dictionary
                         if has_arg and fn in ("lead", "lag") else None),
            })
        struct["groups"].append({
            "n_part_ops": pi, "n_order": len(onames), "specs": sspecs})

    skey = (cap, repr([(g["n_part_ops"], g["n_order"],
                        [(s["func"], s["frame"], s["has_arg"],
                          s["running"], s["offset"], s["alias"])
                         for s in g["specs"]])
                       for g in struct["groups"]]),
            tuple(sorted((k, str(v.dtype)) for k, v in inputs.items()
                         if hasattr(v, "dtype"))))
    cache = _fn_cache()
    fn = cache.get(skey)
    if fn is None:
        fn = _build_window_fn(struct)
        cache[skey] = fn
    dev = fn(inputs)
    host = jax.device_get(dev)

    out = {}
    dicts = {s2["alias"]: s2["dict"]
             for g in struct["groups"] for s2 in g["specs"]}
    for alias, (vals, valid) in host.items():
        out[alias] = (np.asarray(vals)[:L],
                      None if valid is None else np.asarray(valid)[:L],
                      dicts.get(alias))
    return out
