"""Device-side window functions.

The r4 engine evaluated every window spec in a pandas host lane
(`query/window.py`) — honest but single-core, and the host-lane guard
simply REFUSED large frames. This module evaluates the common specs as
ONE scatter-free jitted program over the whole frame, the TPU-native
shape of the reference's block window kernels (`mkql_block_top.cpp`,
peephole window rewrites `yql_opt_peephole_physical.cpp:5810`):

  * one `lax.sort` per distinct (PARTITION BY, ORDER BY) clause —
    partition keys hash-combined into ONE u64 operand (equality only),
    order keys encoded into order-preserving operands, the row id riding
    along as the permutation (never value columns: sort operand count is
    the compile-time cliff, PERF.md);
  * partition/order boundaries by adjacent comparison; segment starts /
    ends via cummax over flipped/unflipped iotas;
  * row_number / rank / dense_rank from boundary cumsums;
  * running and whole-partition SUM/COUNT/AVG from prefix sums against
    the segment-start prefix (NULLs excluded via a parallel validity
    cumsum);
  * running MIN/MAX as a segmented prefix scan (`lax.associative_scan`
    with a reset-at-boundary combiner);
  * ROWS BETWEEN frames for sum/count/avg from the same prefix sums at
    clipped offsets;
  * LEAD/LAG as clipped in-segment gathers;
  * results return to source row order through one inverse permutation
    (argsort of the sort permutation — a 2-operand sort) and ONE
    device→host transfer for all outputs.

Unsupported shapes (float partition keys, bounded min/max frames,
exotic funcs) decline → the caller keeps the pandas lane.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu.utils.hashing import hash_combine, splitmix64

DEVICE_FUNCS = {"row_number", "rank", "dense_rank", "sum", "min", "max",
                "count", "avg", "lead", "lag"}

_I64MAX = np.iinfo(np.int64).max


# ---------------------------------------------------------------------------
# host-side spec compilation: which specs can run on device, key encodings
# ---------------------------------------------------------------------------


def _sort_group_key(spec) -> tuple:
    return (tuple(spec["part"]), tuple(spec["order"]), tuple(spec["asc"]))


def spec_supported(spec, block) -> bool:
    fn = spec["func"]
    if fn not in DEVICE_FUNCS:
        return False
    frame = spec.get("frame")
    if frame is not None:
        if fn in ("min", "max"):
            return False              # bounded sliding min/max: host lane
        if fn in ("row_number", "rank", "dense_rank", "lead", "lag"):
            return False              # frame is meaningless / unsupported
        _tag, lo, hi = frame
        for b in (lo, hi):
            if not isinstance(b, (int, tuple)):
                return False
    if fn in ("lead", "lag"):
        # arg 0 = value, optional arg 1 = offset literal (inner select
        # materializes it as a column; constant columns only). The
        # 3-arg DEFAULT form stays on the host lane.
        if not spec["args"] or len(spec["args"]) > 2:
            return False
    for name in spec["part"]:
        cd = block.columns[name]
        if np.issubdtype(cd.data.dtype, np.floating):
            return False              # no f64 bitcast on this platform
    return True


def _encode_part_host(block, names):
    """Partition keys → (arrays to hash, validity ints). Equality-only."""
    out = []
    for n in names:
        cd = block.columns[n]
        out.append((cd.data.astype(np.int64),
                    None if cd.valid is None
                    else cd.valid.astype(np.int64)))
    return out


def _final_key_ok(cd) -> bool:
    d = cd.data
    return (cd.dictionary is not None
            or np.issubdtype(d.dtype, np.floating)
            or np.issubdtype(d.dtype, np.integer)
            or d.dtype == np.bool_)


def _encode_final_key(cd, ascending):
    """Final ORDER BY key → order-preserving operand with the ENGINE's
    NULL placement (YQL null-smallest: first when ascending, last when
    descending — matching `apply_order_limit`'s defaults)."""
    d = cd.data
    if cd.dictionary is not None:
        ranks = cd.dictionary.sort_ranks()
        enc = ranks[np.clip(d, 0, None)].astype(np.int64)
        enc = np.where(d < 0, 0, enc)
        valid = (d >= 0) if cd.valid is None else (cd.valid & (d >= 0))
    else:
        valid = cd.valid
        if np.issubdtype(d.dtype, np.floating):
            enc = d.astype(np.float64)
            if not ascending:
                enc = -enc
            if valid is not None:
                enc = np.where(valid, enc,
                               -np.inf if ascending else np.inf)
            return np.where(np.isnan(enc),
                            -np.inf if ascending else np.inf, enc)
        elif np.issubdtype(d.dtype, np.integer) or d.dtype == np.bool_:
            enc = d.astype(np.int64)
        else:
            return None
    # INT64_MIN cannot negate (wraps to itself) and collides with the
    # ascending NULL sentinel — decline such rows to the host tail
    if len(enc) and int(enc.min()) == np.iinfo(np.int64).min:
        return None
    enc = enc if ascending else -enc
    if valid is not None:
        sent = np.iinfo(np.int64).min if ascending else _I64MAX
        enc = np.where(valid, enc, sent)
    return enc


def _encode_order_host(block, name, ascending):
    """One order key → an order-preserving f64/i64 array with NULLs
    mapped last (pandas na_position='last' parity)."""
    cd = block.columns[name]
    d = cd.data
    if cd.dictionary is not None:
        ranks = cd.dictionary.sort_ranks()
        d = ranks[np.clip(d, 0, None)].astype(np.int64)
        d = np.where(cd.data < 0, 0, d)
    if np.issubdtype(d.dtype, np.floating):
        enc = d.astype(np.float64)
        if not ascending:
            enc = -enc
        if cd.valid is not None:
            enc = np.where(cd.valid, enc, np.inf)
        enc = np.where(np.isnan(enc), np.inf, enc)
        return enc
    enc = d.astype(np.int64)
    if not ascending:
        enc = -enc
    if cd.valid is not None:
        enc = np.where(cd.valid, enc, _I64MAX)
    return enc


# ---------------------------------------------------------------------------
# traced helpers
# ---------------------------------------------------------------------------


def _seg_starts(boundary, iota):
    """Index of each row's segment start (boundary[0] must be True)."""
    return jax.lax.cummax(jnp.where(boundary, iota, 0))


def _seg_ends(boundary, iota, n):
    """Index of each row's segment END (inclusive). boundary marks
    segment STARTS; a start at i+1 means i is an end."""
    nxt = jnp.concatenate([boundary[1:], jnp.ones((1,), bool)])
    rev = jnp.flip(jnp.where(nxt, iota, n - 1))
    return jnp.flip(jax.lax.cummin(rev))


def _segmented_scan_minmax(v, boundary, is_min):
    """Running min/max with reset at segment boundaries."""
    def combine(a, b):
        ab, av = a
        bb, bv = b
        merged = jnp.where(bb, bv,
                           jnp.minimum(av, bv) if is_min
                           else jnp.maximum(av, bv))
        return (ab | bb, merged)
    _b, out = jax.lax.associative_scan(combine, (boundary, v))
    return out


def _prefix(v):
    """Exclusive prefix sums of shape (n+1,): P[i] = sum(v[:i])."""
    return jnp.concatenate([jnp.zeros((1,), v.dtype), jnp.cumsum(v)])


def _build_window_fn(struct):
    """Trace one jitted program computing every spec in `struct`:
    {"groups": [{"n_part_ops": int, "n_order": int,
                 "specs": [{"func","frame","has_arg","arg_float",
                            "offset","alias"}]}], "cap": int}"""

    @jax.jit
    def fn(inputs):
        L = inputs["length"]
        cap = inputs["iota"].shape[0]
        iota = inputs["iota"]
        active = iota < L
        outs = {}
        for gi, grp in enumerate(struct["groups"]):
            # --- one sort per clause group
            #
            # PARTITION BY keys are hash-combined into ONE u64 sort
            # operand — a deliberate correctness/compile-time tradeoff:
            # two DISTINCT partitions whose combined splitmix64 hashes
            # collide in the surviving 63 bits (the top bit is the
            # padding sentinel) would silently merge, corrupting every
            # windowed value in both. The per-pair probability is 2^-63
            # (~1e-19; even 1M partitions give ~5e7 pairs ≈ 5e-12 per
            # query), while the alternative — one sort operand per key
            # column — rides the lax.sort compile cliff (operand count
            # is the compile-time driver: 6M×8 operands ≈ 218 s,
            # PERF.md). A second independent hash operand would square
            # the collision odds at +1 operand; revisit if this lane
            # ever feeds billing-grade aggregation instead of analytics.
            phash = jnp.zeros(cap, jnp.uint64)
            for pi in range(grp["n_part_ops"]):
                phash = hash_combine(
                    jnp, phash,
                    splitmix64(jnp, inputs[f"g{gi}p{pi}"]))
            # padded rows sort to the back as their own partition
            phash = jnp.where(active, phash >> jnp.uint64(1),
                              jnp.uint64(np.uint64(2**64 - 1)))
            operands = [phash]
            for oi in range(grp["n_order"]):
                operands.append(inputs[f"g{gi}o{oi}"])
            operands.append(iota)
            sorted_ops = jax.lax.sort(tuple(operands),
                                      num_keys=len(operands) - 1)
            perm = sorted_ops[-1]
            s_hash = sorted_ops[0]
            # --- boundaries
            first = jnp.zeros(cap, bool).at[0].set(True)  # static index
            b_part = jnp.concatenate(
                [jnp.ones((1,), bool), s_hash[1:] != s_hash[:-1]])
            b_order = b_part
            for oi in range(grp["n_order"]):
                so = sorted_ops[1 + oi]
                b_order = b_order | jnp.concatenate(
                    [jnp.ones((1,), bool), so[1:] != so[:-1]])
            del first
            seg_start = _seg_starts(b_part, iota)
            seg_end = _seg_ends(b_part, iota, cap)
            inv = jax.lax.sort((perm, iota), num_keys=1)[1]

            def unsort(x):
                return x[inv]

            # dense-rank prefix over order boundaries (shared)
            corder = jnp.cumsum(b_order.astype(jnp.int64))

            for si, spec in enumerate(grp["specs"]):
                fnname = spec["func"]
                if fnname == "row_number":
                    out = iota - seg_start + 1
                    outs[spec["alias"]] = (unsort(out), None)
                    continue
                if fnname == "rank":
                    grp_start = jax.lax.cummax(
                        jnp.where(b_order, iota, 0))
                    out = grp_start - seg_start + 1
                    outs[spec["alias"]] = (unsort(out), None)
                    continue
                if fnname == "dense_rank":
                    out = corder - corder[seg_start] + 1
                    outs[spec["alias"]] = (unsort(out), None)
                    continue
                if fnname in ("lead", "lag"):
                    v = inputs[f"g{gi}s{si}a"][perm]
                    valid_in = inputs.get(f"g{gi}s{si}av")
                    sv = valid_in[perm] if valid_in is not None else None
                    off = spec["offset"]
                    tgt = iota + off if fnname == "lead" else iota - off
                    inside = (tgt >= seg_start) & (tgt <= seg_end) \
                        & (tgt >= 0) & (tgt < cap)
                    tgt_c = jnp.clip(tgt, 0, cap - 1)
                    out = v[tgt_c]
                    ov = inside if sv is None else (inside & sv[tgt_c])
                    outs[spec["alias"]] = (unsort(out), unsort(ov))
                    continue
                # aggregates --------------------------------------------
                has_arg = spec["has_arg"]
                if has_arg:
                    v = inputs[f"g{gi}s{si}a"][perm]
                    valid_in = inputs.get(f"g{gi}s{si}av")
                    sv = valid_in[perm] if valid_in is not None \
                        else jnp.ones(cap, bool)
                else:                     # count(*)
                    v = jnp.ones(cap, jnp.int64)
                    sv = jnp.ones(cap, bool)
                sv = sv & (perm < L)
                filled = jnp.where(sv, v, jnp.zeros((), v.dtype))
                frame = spec["frame"]
                if fnname in ("min", "max"):
                    ident = jnp.array(
                        np.inf if fnname == "min" else -np.inf, v.dtype) \
                        if jnp.issubdtype(v.dtype, jnp.floating) else \
                        jnp.array(_I64MAX if fnname == "min"
                                  else -_I64MAX - 1, v.dtype)
                    vm = jnp.where(sv, v, ident)
                    if spec["running"]:
                        out = _segmented_scan_minmax(vm, b_part,
                                                     fnname == "min")
                        nn = jnp.cumsum(sv.astype(jnp.int64))
                        nnrun = nn - nn[seg_start] \
                            + sv[seg_start].astype(jnp.int64)
                        ov = nnrun > 0
                    else:
                        run = _segmented_scan_minmax(vm, b_part,
                                                     fnname == "min")
                        out = run[seg_end]
                        nn = jnp.cumsum(sv.astype(jnp.int64))
                        tot = nn[seg_end] - nn[seg_start] \
                            + sv[seg_start].astype(jnp.int64)
                        ov = tot > 0
                    outs[spec["alias"]] = (unsort(out), unsort(ov))
                    continue
                cs = _prefix(filled)
                cn = _prefix(sv.astype(jnp.int64))
                if frame is not None:
                    _tag, lo, hi = frame
                    lo_unb = not isinstance(lo, int)
                    hi_unb = not isinstance(hi, int)
                    start = seg_start if lo_unb \
                        else jnp.clip(iota + lo, seg_start, seg_end + 1)
                    end1 = seg_end + 1 if hi_unb \
                        else jnp.clip(iota + hi + 1, seg_start,
                                      seg_end + 1)
                    start = jnp.minimum(start, end1)
                elif spec["running"]:
                    start, end1 = seg_start, iota + 1
                else:
                    start, end1 = seg_start, seg_end + 1
                ssum = cs[end1] - cs[start]
                scnt = cn[end1] - cn[start]
                if fnname == "count":
                    outs[spec["alias"]] = (unsort(scnt), None)
                elif fnname == "sum":
                    outs[spec["alias"]] = (unsort(ssum),
                                           unsort(scnt > 0))
                else:                     # avg
                    a = ssum.astype(jnp.float64) / jnp.maximum(scnt, 1)
                    outs[spec["alias"]] = (unsort(a), unsort(scnt > 0))

        fin = struct.get("final")
        if fin is None:
            return outs
        # final ORDER BY + LIMIT device-side: one more sort (keys +
        # row id), then every output leaves sliced to K rows
        ops_l = [jnp.where(active, jnp.int64(0), jnp.int64(1))]
        for key_spec in fin["keys"]:
            src, name, asc = key_spec[0], key_spec[1], key_spec[2]
            if src == "col":
                ops_l.append(inputs[name])
                continue
            if src == "winstr":
                # string window output: sort by lexicographic rank LUT;
                # NULL (code < 0 or invalid) takes the engine's
                # null-smallest placement
                v, vv = outs[name]
                ranks = inputs[key_spec[3]]
                code = v.astype(jnp.int64)
                enc = ranks[jnp.clip(code, 0, ranks.shape[0] - 1)]
                invalid = code < 0
                if vv is not None:
                    invalid = invalid | ~vv
                enc = enc if asc else -enc
                sent = jnp.int64(np.iinfo(np.int64).min) if asc \
                    else jnp.int64(_I64MAX)
                ops_l.append(jnp.where(invalid, sent, enc))
                continue
            v, vv = outs[name]
            enc = v.astype(jnp.int64) if v.dtype == jnp.bool_ else v
            if jnp.issubdtype(enc.dtype, jnp.floating):
                enc = enc if asc else -enc
                if vv is not None:
                    enc = jnp.where(vv, enc,
                                    -jnp.inf if asc else jnp.inf)
            else:
                enc = enc.astype(jnp.int64)
                enc = enc if asc else -enc
                if vv is not None:
                    sent = jnp.int64(np.iinfo(np.int64).min) if asc \
                        else jnp.int64(_I64MAX)
                    enc = jnp.where(vv, enc, sent)
            ops_l.append(enc)
        ops_l.append(iota)
        sout = jax.lax.sort(tuple(ops_l), num_keys=len(ops_l) - 1)
        perm_f = sout[-1][:fin["K"]]
        n_out = jnp.minimum(L, jnp.int64(fin["K"]))
        final_outs = {}
        for alias, (v, vv) in outs.items():
            final_outs[alias] = (v[perm_f],
                                 None if vv is None else vv[perm_f])
        for name in fin["pass_cols"]:
            v = inputs[f"out_{name}"][perm_f]
            vvin = inputs.get(f"outv_{name}")
            final_outs[name] = (v, None if vvin is None
                                else vvin[perm_f])
        return final_outs, n_out

    return fn


_FN_CACHE = None


def _fn_cache():
    global _FN_CACHE
    if _FN_CACHE is None:
        from ydb_tpu.ops.exec_cache import ExecCache
        _FN_CACHE = ExecCache("window")
    return _FN_CACHE


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def compute_windows_device(block, outer, final_sort=None, limit=None,
                           offset=0):
    """Evaluate every window spec of `outer` on device. Returns
    {alias: (np values, np valid|None)} or None when any spec (or key
    encoding) requires the host lane.

    `final_sort`/`limit`: when given ([(name, ascending, win_output?)],
    row limit), the program ALSO sorts the full result by those keys and
    slices to offset+limit rows device-side before transfer — the
    output egress is then O(limit) instead of O(rows) for EVERY column
    (the D2H link is the dominant window cost post-readout, PERF.md r5).
    Returns ({alias_or_col: (values, valid|None, dict|None)}, n_rows)
    in that mode, covering passthrough columns too."""
    from ydb_tpu.ops.device import bucket_capacity

    specs = [p for k, p in outer if k == "win"]
    if not specs or block.length == 0:
        return None
    for s in specs:
        if not spec_supported(s, block):
            return None

    # pre-validate the final-sort keys BEFORE any encoding/upload work:
    # an ineligible key must cost a cheap decline, not a fully-prepared
    # program thrown away (review r5)
    win_aliases_pre = {s["alias"] for s in specs}
    if final_sort is not None and limit is not None:
        for (name, _asc) in final_sort:
            if name in win_aliases_pre:
                continue
            cd = block.columns.get(name)
            if cd is None or not _final_key_ok(cd):
                return None
    else:
        final_sort = None             # offset/limit without both: plain

    # group by sort clause; build the static structure + input arrays
    groups: dict = {}
    order = []
    for s in specs:
        k = _sort_group_key(s)
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(s)

    L = block.length
    cap = bucket_capacity(max(L, 1))
    pad = cap - L

    def up(a, fill=0):
        if pad:
            a = np.concatenate(
                [a, np.full(pad, fill, dtype=a.dtype)])
        return jnp.asarray(a)

    inputs = {"length": jnp.int64(L),
              "iota": jnp.arange(cap, dtype=jnp.int64)}
    struct = {"groups": [], "cap": cap}
    for gi, k in enumerate(order):
        part, onames, asc = k
        gspecs = groups[k]
        pi = 0
        for name in part:
            for arr in _encode_part_host(block, [name])[0]:
                if arr is None:
                    continue
                inputs[f"g{gi}p{pi}"] = up(arr)
                pi += 1
        for oi, name in enumerate(onames):
            enc = _encode_order_host(block, name, asc[oi])
            inputs[f"g{gi}o{oi}"] = up(
                enc, fill=np.inf if enc.dtype == np.float64 else _I64MAX)
        sspecs = []
        for si, s in enumerate(gspecs):
            fn = s["func"]
            has_arg = bool(s["args"]) and not (
                fn == "count" and not s["args"])
            off_n = 1
            if fn in ("lead", "lag") and len(s["args"]) > 1:
                off_cd = block.columns[s["args"][1]]
                off_n = int(off_cd.data[0])
                if not (off_cd.data[:L] == off_cd.data[0]).all():
                    return None       # non-constant offset: host lane
            if has_arg:
                cd = block.columns[s["args"][0]]
                if cd.dictionary is not None and fn in (
                        "sum", "avg", "min", "max", "count"):
                    return None       # string aggregates: host lane
                d = cd.data
                if d.dtype == np.bool_:
                    d = d.astype(np.int64)
                inputs[f"g{gi}s{si}a"] = up(d)
                if cd.valid is not None:
                    inputs[f"g{gi}s{si}av"] = up(
                        cd.valid, fill=False)
            sspecs.append({
                "func": fn, "frame": s.get("frame"),
                "has_arg": has_arg,
                "running": bool(s["order"]),
                "offset": off_n, "alias": s["alias"],
                "dict": (block.columns[s["args"][0]].dictionary
                         if has_arg and fn in ("lead", "lag") else None),
            })
        struct["groups"].append({
            "n_part_ops": pi, "n_order": len(onames), "specs": sspecs})

    # final ORDER BY + LIMIT pushed into the program: passthrough
    # columns upload once, every output leaves the device sliced to
    # offset+limit rows
    win_aliases = {s["alias"] for s in specs}
    if final_sort is not None:
        K = min(int(offset) + int(limit), cap)
        dict_of_alias = {s2["alias"]: s2["dict"]
                         for g in struct["groups"] for s2 in g["specs"]}
        fkeys = []
        for fi, (name, ascending) in enumerate(final_sort):
            if name in win_aliases:
                dic = dict_of_alias.get(name)
                if dic is not None:
                    # string-valued window output (lead/lag of a dict
                    # column): sort by LEXICOGRAPHIC rank, not raw
                    # insertion-order codes — ranks upload as a LUT the
                    # program gathers through
                    ranks = dic.sort_ranks().astype(np.int64)
                    inputs[f"frank{fi}"] = jnp.asarray(
                        ranks if len(ranks) else np.zeros(1, np.int64))
                    fkeys.append(("winstr", name, ascending,
                                  f"frank{fi}"))
                else:
                    fkeys.append(("win", name, ascending))
            else:
                cd = block.columns.get(name)
                if cd is None:
                    return None
                enc = _encode_final_key(cd, ascending)
                if enc is None:
                    return None
                inputs[f"fs{fi}"] = up(
                    enc, fill=np.inf if enc.dtype == np.float64
                    else _I64MAX)
                fkeys.append(("col", f"fs{fi}", ascending))
        pass_cols = [p for k2, p in outer if k2 == "col"]
        pass_dicts = {}
        for name in pass_cols:
            cd = block.columns[name]
            d = cd.data
            inputs[f"out_{name}"] = up(d)
            if cd.valid is not None:
                inputs[f"outv_{name}"] = up(cd.valid, fill=False)
            if cd.dictionary is not None:
                pass_dicts[name] = cd.dictionary
        struct["final"] = {"keys": fkeys, "K": K,
                           "pass_cols": list(pass_cols)}

    skey = (cap, repr([(g["n_part_ops"], g["n_order"],
                        [(s["func"], s["frame"], s["has_arg"],
                          s["running"], s["offset"], s["alias"])
                         for s in g["specs"]])
                       for g in struct["groups"]]),
            repr(struct.get("final")),
            tuple(sorted((k, str(v.dtype)) for k, v in inputs.items()
                         if hasattr(v, "dtype"))))
    cache = _fn_cache()
    fn = cache.get(skey)
    if fn is None:
        fn = _build_window_fn(struct)
        cache[skey] = fn
    dicts = {s2["alias"]: s2["dict"]
             for g in struct["groups"] for s2 in g["specs"]}
    if struct.get("final") is not None:
        dev, n_dev = fn(inputs)
        host, n = jax.device_get((dev, n_dev))
        n = int(n)
        dicts.update(pass_dicts)
        out = {}
        # device_get above already landed host ndarrays — slice directly
        for name, (vals, valid) in host.items():
            out[name] = (vals[:n],
                         None if valid is None else valid[:n],
                         dicts.get(name))
        return out, n
    dev = fn(inputs)
    host = jax.device_get(dev)

    out = {}
    for alias, (vals, valid) in host.items():
        out[alias] = (vals[:L],
                      None if valid is None else valid[:L],
                      dicts.get(alias))
    return out
