"""Whole-query fused execution — ONE XLA dispatch per query.

The reference streams blocks through a chain of separately-scheduled
operators (scan actor → block comp nodes → channels,
`dq_compute_actor_impl.h:295`). On this TPU platform every dispatch after
the first device→host readout pays a large fixed tunnel latency (PERF.md),
so the fused path compiles the ENTIRE single-node query — scan over all
portions, pushdown filters, broadcast-join probes, aggregation, HAVING,
output expressions, ORDER BY, LIMIT — into one `jax.jit` program:

  * scan sources arrive as stacked (K, CAP) "superblocks" per column
    (`DeviceColumnCache.superblock`), flattened to one K·CAP row vector
    with a per-row activity mask (no data-dependent shapes);
  * filters thread a selection mask between programs (`TColumnFilter`
    semantics) — nothing compresses until after aggregation;
  * joins probe via direct-address LUTs (`ops/join.py:probe_lut_traced`) —
    one fused gather per probe, no binary-search loops;
  * GroupBy uses the scatter-free paths of `ops/xla_exec.py`.

A query therefore costs one dispatch + one result readout in the steady
state, versus O(portions × operators) dispatches for the unfused path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import ir
from ydb_tpu.ops.device import bucket_capacity
from ydb_tpu.ops.join import probe_lut_traced
from ydb_tpu.ops.sort import sort_env
from ydb_tpu.ops.xla_exec import _eval, _trace_program, compress

# the executor-lifted LIMIT+OFFSET device input (companion of the
# query/paramlift.py literal lift; defined here because the ops layer
# must not import the query layer at trace time)
LIMIT_PARAM = "__lim2"


def apply_join_schema(schema: Schema, payload_cols: list) -> Schema:
    """Schema effect of a join probe: payload columns replace any existing
    columns with the same names and append at the end (the single source
    of truth for fused schema threading)."""
    taken = {p.name for p in payload_cols}
    return Schema([c for c in schema.columns if c.name not in taken]
                  + list(payload_cols))


LM_POS = "__lmpos"                       # deferred-scan row-position column


def _prog_refs(prog: ir.Program) -> set:
    """Column names a program actually COMPUTES over (Assign exprs,
    Filter preds, GroupBy keys/carries/agg args). Projection names are
    deliberately excluded: projecting a deferred column keeps it
    deferred (`_trace_program` passthrough) rather than forcing a
    full-capacity gather."""
    refs: set = set()
    for cmd in prog.commands:
        if isinstance(cmd, ir.Assign):
            ir.expr_columns(cmd.expr, refs)
        elif isinstance(cmd, ir.Filter):
            ir.expr_columns(cmd.pred, refs)
        elif isinstance(cmd, ir.GroupBy):
            refs.update(cmd.keys)
            refs.update(cmd.carry_keys)
            refs.update(a.arg for a in cmd.aggs if a.arg is not None)
    return refs


def _fused_body(pipe, final_program: Optional[ir.Program],
                scan_cols: list, K: int, CAP: int,
                sb_valid_names: frozenset, join_metas: list,
                rank_assigns: list, sort_spec: tuple,
                limit: Optional[int], offset: Optional[int],
                keep: tuple, lift_limit: bool = False,
                late_scan: frozenset = frozenset(),
                compact_prog: Optional[ir.Program] = None):
    """Un-jitted trace body shared by the single-query fused program
    (`build_fused_fn`) and the multi-query batched lane
    (`build_fused_batched_fn`, which vmaps it over stacked params).

    `lift_limit`: LIMIT+OFFSET arrives as the `__lim2` device input
    (paramlift.LIMIT_PARAM) instead of a baked constant — the length
    clamp becomes runtime, while the output slice stays static at the
    limit's capacity bucket (identical to the baked path's bucket, so
    results are byte-equal); callers key the compiled program on the
    bucket, and every limit inside it shares one executable.

    Late materialization (`YDB_TPU_LATE_MAT`, `xla_exec.late_mat_enabled`):
    `late_scan` names scan columns that are NOT loaded into the row env
    up front — a single int32 row-position column (`__lmpos`) rides the
    pipeline instead, and each deferred column gathers from the
    superblock at its first compute reference or at the bound-sized
    tail. Joins whose meta carries `late` likewise thread a
    (build row-id, match) pair (`ops/join.probe_lut_traced`) in place of
    their payload widths. `compact_prog` (an `ir.Compact` wrapper built
    by the executor) shrinks the working capacity to a ladder-quantized
    bound after the joins, so deferred gathers and the partial group-by
    run at the small shape; its live/overflow scalars come back in the
    4th return element (the executor's loud-rerun input)."""
    lim2 = None if limit is None else limit + (offset or 0)
    layout_box: dict = {}

    def fn(sb, sbv, lengths, builds, params):
        cap0 = K * CAP
        cap = cap0
        aux: dict = {}
        env = {}
        deferred: dict = {}              # out name -> ("scan", src) |
        #                                  ("join", join_idx, src)
        for c in scan_cols:
            if c.name in late_scan:
                deferred[c.name] = ("scan", c.name)
            else:
                d = sb[c.name].reshape(cap0)
                v = sbv[c.name].reshape(cap0) \
                    if c.name in sb_valid_names else None
                env[c.name] = (d, v)
        if deferred:
            env[LM_POS] = (jnp.arange(cap0, dtype=jnp.int32), None)
        sel = (jnp.arange(CAP, dtype=jnp.int32)[None, :]
               < lengths[:, None]).reshape(cap0)
        length = jnp.int32(cap0)
        schema = Schema(list(scan_cols))

        def helper_names() -> tuple:
            return tuple(n for n in env if n.startswith("__lm"))

        def gc():
            # drop row-id helper columns whose deferrals are all
            # materialized — they must not ride sorts/compresses for free
            if not any(s[0] == "scan" for s in deferred.values()):
                env.pop(LM_POS, None)
            live_joins = {s[1] for s in deferred.values()
                          if s[0] == "join"}
            for j, m in enumerate(join_metas):
                if m.get("late") and j not in live_joins:
                    env.pop(m["row_col"], None)
                    env.pop(m["found_col"], None)

        def materialize(names):
            # the deferred gather: runs at the CURRENT capacity — after a
            # compact/limit slice that is the bound, not the scan
            for nm in names:
                src = deferred.pop(nm, None)
                if src is None:
                    continue
                if src[0] == "scan":
                    pos = env[LM_POS][0]
                    d = sb[src[1]].reshape(cap0)[pos]
                    v = (sbv[src[1]].reshape(cap0)[pos]
                         if src[1] in sb_valid_names else None)
                    env[nm] = (d, v)
                else:
                    _k, j, s = src
                    m = join_metas[j]
                    row = env[m["row_col"]][0]
                    ok = env[m["found_col"]][0]
                    pv = builds[j]["pvalid"].get(s)
                    d = builds[j]["payload"][s][row]
                    v = ok if pv is None else (ok & pv[row])
                    env[nm] = (d, v)
            gc()

        def run(prog):
            nonlocal env, length, sel, schema, cap
            materialize(sorted(_prog_refs(prog) & set(deferred)))
            env, length, sel, schema = _trace_program(
                prog, schema.columns, cap, env, length, params, sel=sel,
                aux=aux, passthrough=helper_names())
            if env:
                cap = next(iter(env.values()))[0].shape[0]
            elif sel is not None:
                # a column-free env (count(*) plans) still changes
                # capacity through a Compact — the mask carries it
                cap = sel.shape[0]
            # a GroupBy/Projection that dropped a deferred column from
            # the schema retires its deferral (it no longer exists)
            for nm in [n for n in deferred if not schema.has(n)]:
                del deferred[nm]
            gc()

        if pipe.pre_program is not None:
            run(pipe.pre_program)
        bi = 0
        for kind, step in pipe.steps:
            if kind == "join":
                meta = join_metas[bi]
                if meta["probe_key"] in deferred:
                    materialize([meta["probe_key"]])
                env, sel = probe_lut_traced(env, sel, builds[bi], meta)
                if meta.get("late") and meta["kind"] in ("inner", "left"):
                    for src, out in zip(meta["src_names"],
                                        meta["payload_names"]):
                        env.pop(out, None)   # replaced by this probe
                        deferred[out] = ("join", bi, src)
                bi += 1
                schema = apply_join_schema(schema, meta["payload_cols"])
            else:
                run(step)
        if compact_prog is not None:
            run(compact_prog)
        if pipe.partial is not None:
            run(pipe.partial)
        if final_program is not None:
            run(final_program)
        if sel is not None:
            env, length = compress(env, length, sel, cap)
            sel = None

        need: set = set()
        for a in rank_assigns:
            ir.expr_columns(a.expr, need)
        need.update(n for (n, _asc, _nf) in sort_spec)
        materialize(sorted(need & set(deferred)))
        for a in rank_assigns:
            env[a.name] = _eval(a.expr, env, params, cap)
        if sort_spec:
            arrays = {n: d for n, (d, _v) in env.items()}
            valids = {n: v for n, (d, v) in env.items() if v is not None}
            arrays2, valids2, length = sort_env(
                arrays, valids, length, None, sort_spec,
                tuple(arrays.keys()))
            env = {n: (arrays2[n], valids2.get(n)) for n in arrays2}
        if lim2 is not None:
            bound = params[LIMIT_PARAM] if lift_limit else jnp.int32(lim2)
            length = jnp.minimum(length, bound)
            out_cap = min(bucket_capacity(lim2, minimum=128), cap)
            env = {n: (d[:out_cap], v[:out_cap] if v is not None else None)
                   for n, (d, v) in env.items()}
        # the tail gather: whatever is still deferred materializes HERE,
        # at the post-limit capacity — a LIMIT-K plan gathers its payload
        # widths for K-bucket rows, not scan capacity
        want = [n for n in keep if n in env or n in deferred]
        if want:
            materialize([n for n in want if n in deferred])
        else:
            materialize(sorted(deferred))
            want = [n for n in env if not n.startswith("__lm")]
        out_names = [n for n in want if n in env]
        groups: dict = {}
        data_layout = []
        for n in out_names:
            d = env[n][0]
            key = str(d.dtype)
            groups.setdefault(key, []).append(d)
            data_layout.append((n, key, len(groups[key]) - 1))
        valid_names = [n for n in out_names if env[n][1] is not None]
        layout_box["data"] = data_layout
        layout_box["valids"] = valid_names
        data_stacks = {k: jnp.stack(v) for k, v in groups.items()}
        valid_stack = (jnp.stack([env[n][1] for n in valid_names])
                       if valid_names else None)
        return data_stacks, valid_stack, length, aux

    return fn, layout_box


def build_fused_fn(pipe, final_program: Optional[ir.Program],
                   scan_cols: list, K: int, CAP: int,
                   sb_valid_names: frozenset, join_metas: list,
                   rank_assigns: list, sort_spec: tuple,
                   limit: Optional[int], offset: Optional[int],
                   keep: tuple, lift_limit: bool = False,
                   late_scan: frozenset = frozenset(),
                   compact_prog: Optional[ir.Program] = None):
    """Compile the full single-node query pipeline into one jitted fn.

    scan_cols: [Column] of the flattened scan env (internal names).
    join_metas: per join step, the static meta dict for
    `probe_lut_traced` plus "payload_cols" ([Column] appended to the
    schema by the probe).

    Returns (fn, layout_box); fn(sb, sbv, lengths, builds, params) →
    (data_stacks {dtype: (k, cap)}, valid_stack (m, cap) | None, length,
    aux) — `aux` is empty unless `compact_prog` ran (then it carries the
    compact live count + overflow flag; the executor consumes it before
    any result use). Outputs are STACKED by dtype so the result crosses
    the link in a handful of transfers instead of one per column (each
    device→host round trip costs ~15 ms on this platform — PERF.md);
    `layout_box` is filled at trace time with
    {"data": [(name, dtype_str, row)], "valids": [name]} describing the
    stacking."""
    fn, layout_box = _fused_body(pipe, final_program, scan_cols, K, CAP,
                                 sb_valid_names, join_metas, rank_assigns,
                                 sort_spec, limit, offset, keep,
                                 lift_limit=lift_limit,
                                 late_scan=late_scan,
                                 compact_prog=compact_prog)
    return jax.jit(fn), layout_box


def build_fused_batched_fn(pipe, final_program: Optional[ir.Program],
                           scan_cols: list, K: int, CAP: int,
                           sb_valid_names: frozenset, join_metas: list,
                           rank_assigns: list, sort_spec: tuple,
                           limit: Optional[int], offset: Optional[int],
                           keep: tuple, param_axes: dict, axis_size: int,
                           lift_limit: bool = False,
                           late_scan: frozenset = frozenset()):
    """The multi-query batched dispatch program: ONE executable running
    `axis_size` same-shape queries as a vmap over their stacked lifted
    params (DrJAX's mapped-over-a-fixed-program composition, arxiv
    2403.07128). Scan superblock, build tables, and any param whose
    value is batch-invariant broadcast (in_axes None); only the
    per-member params carry the leading batch axis (`param_axes`:
    {name: 0 | None}). Outputs gain a leading batch axis; each client's
    result is its slice (`fetch_fused_batch`). Late materialization rides
    the vmapped trace unchanged (row-id gathers batch like any other op);
    the compact step stays single-query-only, so `aux` is always empty
    here."""
    fn, layout_box = _fused_body(pipe, final_program, scan_cols, K, CAP,
                                 sb_valid_names, join_metas, rank_assigns,
                                 sort_spec, limit, offset, keep,
                                 lift_limit=lift_limit, late_scan=late_scan)
    batched = jax.vmap(fn, in_axes=(None, None, None, None, param_axes),
                       axis_size=axis_size)
    return jax.jit(batched), layout_box


def _unpack_fused_host(host_stacks, host_valids, n: int, layout_box: dict,
                       out_schema: Schema, out_dicts: dict):
    """Host-side assembly of one query's result from already-transferred
    dtype-stacked arrays (shared by the single-query fetch and each
    member slice of a batched fetch)."""
    from ydb_tpu.core.block import HostBlock
    from ydb_tpu.ops.device import host_column

    valid_row = {nm: i for i, nm in enumerate(layout_box["valids"])}
    cols = {}
    out_cols = []
    for (name, dtype_key, row) in layout_box["data"]:
        if not out_schema.has(name):
            continue
        valid = (host_valids[valid_row[name]][:n]
                 if name in valid_row and host_valids is not None
                 else None)
        cols[name] = host_column(host_stacks[dtype_key][row][:n], valid,
                                 out_schema.dtype(name),
                                 out_dicts.get(name))
        out_cols.append(out_schema.col(name))
    return HostBlock(Schema(out_cols), cols, n)


def fetch_fused_result(data_stacks, valid_stack, length, layout_box: dict,
                       out_schema: Schema, out_dicts: dict):
    """Device→host readout of one fused dispatch: ONE `jax.device_get`
    for the whole result (length included) — per-column fetches pay a
    full link round trip each (PERF.md). Large row-level outputs sync
    the length first and slice device-side so padding doesn't cross the
    link. This is the deferred half of the device-result future: the
    dispatch returns immediately and this runs when the result is
    consumed, so concurrent queries overlap compute with D2H drains."""
    from ydb_tpu.utils import memledger
    cap_out = (next(iter(data_stacks.values())).shape[1]
               if data_stacks else 0)
    padded_bytes = memledger.deep_nbytes((data_stacks, valid_stack))
    if cap_out > (1 << 16):
        n = int(length)
        m = max(n, 1)
        data_stacks = {k: v[:, :m] for k, v in data_stacks.items()}
        if valid_stack is not None:
            valid_stack = valid_stack[:, :m]
        # lint: transfer-ok(result egress — padding sliced off device-side first)
        host_stacks, host_valids = jax.device_get(
            (data_stacks, valid_stack))
    else:
        # lint: transfer-ok(result egress — the fused path's ONE pytree readback)
        host_stacks, host_valids, n = jax.device_get(
            (data_stacks, valid_stack, length))
        n = int(n)
    # capacity-sized outputs (group-by buckets, LIMIT buckets): the live
    # result rows vs the power-of-two output capacity the program wrote
    if cap_out:
        memledger.record_pad(
            "result_capacity", n, cap_out,
            int(padded_bytes * min(n, cap_out) / cap_out), padded_bytes)
    memledger.record_transfer(
        "ops/fused.py::fetch_fused_result",
        memledger.deep_nbytes((host_stacks, host_valids)), boundary=True)
    return _unpack_fused_host(host_stacks, host_valids, n, layout_box,
                              out_schema, out_dicts)


def capture_fused_device(data_stacks, valid_stack, length, layout_box: dict,
                         out_schema: Schema, out_dicts: dict):
    """Device-resident view of one fused dispatch: the stage-spine
    capture. Slices the dtype-stacked output rows back into per-column
    device arrays BY REFERENCE — zero transfers, zero copies — so a DQ
    stage can hand the result to the next stage (or the planned ICI
    exchange) without the host round-trip `fetch_fused_result` pays.
    `length` stays whatever scalar the caller holds (host int at the
    capture seam); padding above it is dead rows the consumer masks."""
    from ydb_tpu.ops.device import DeviceBlock

    valid_row = {nm: i for i, nm in enumerate(layout_box["valids"])}
    arrays, valids, dicts = {}, {}, {}
    out_cols = []
    for (name, dtype_key, row) in layout_box["data"]:
        if not out_schema.has(name):
            continue
        arrays[name] = data_stacks[dtype_key][row]
        if name in valid_row and valid_stack is not None:
            valids[name] = valid_stack[valid_row[name]]
        if out_dicts.get(name) is not None:
            dicts[name] = out_dicts[name]
        out_cols.append(out_schema.col(name))
    cap = int(next(iter(arrays.values())).shape[0]) if arrays else 0
    return DeviceBlock(Schema(out_cols), arrays, valids, length, cap,
                       dicts)


def fetch_fused_batch(data_stacks, valid_stack, lengths, layout_box: dict,
                      out_schema: Schema, out_dicts: dict,
                      member_rows: list):
    """Device→host readout of one BATCHED dispatch: still ONE
    `jax.device_get` — for the whole batch — then each member unpacks
    its slice host-side. `member_rows[i]` is member i's batch-axis row
    (identical-query dedup maps every member to row 0; padded rows are
    never read). Returns [HostBlock], one per member."""
    from ydb_tpu.utils import memledger
    # lint: transfer-ok(result egress — one readback for the whole batch)
    host_stacks, host_valids, ns = jax.device_get(
        (data_stacks, valid_stack, lengths))
    memledger.record_transfer(
        "ops/fused.py::fetch_fused_batch",
        memledger.deep_nbytes((host_stacks, host_valids)), boundary=True)
    out = []
    for b in member_rows:
        hs = {k: v[b] for k, v in host_stacks.items()}
        hv = host_valids[b] if host_valids is not None else None
        out.append(_unpack_fused_host(hs, hv, int(ns[b]), layout_box,
                                      out_schema, out_dicts))
    return out


def build_tile_fn(pipe, scan_cols: list, K: int, CAP: int,
                  sb_valid_names: frozenset, join_metas: list):
    """Fused scan→filter→join→partial-agg program for ONE tile of a scan
    too large for HBM (the streaming front half of `build_fused_fn`,
    stopping after `pipe.partial`). The reference streams blocks through
    its combiner the same way before the merge stage
    (`mkql_wide_combine.cpp` InMemory state); here a tile is K stacked
    sources in one dispatch and the partial stays device-resident for the
    finalize/merge stage.

    fn(sb, sbv, lengths, builds, params) → (data {name}, valids {name},
    length) — compressed (active rows at front), NOT transferred. Tiles
    stream and merge host-side, so the late-materialization deferral is
    stripped here (a row-id crossing a tile boundary would dangle)."""
    join_metas = [{**m, "late": False} for m in join_metas]

    @jax.jit
    def fn(sb, sbv, lengths, builds, params):
        cap = K * CAP
        env = {}
        for c in scan_cols:
            d = sb[c.name].reshape(cap)
            v = sbv[c.name].reshape(cap) if c.name in sb_valid_names else None
            env[c.name] = (d, v)
        sel = (jnp.arange(CAP, dtype=jnp.int32)[None, :]
               < lengths[:, None]).reshape(cap)
        length = jnp.int32(cap)
        schema = Schema(list(scan_cols))

        def run(prog, env, length, sel, schema, cap):
            env, length, sel, schema = _trace_program(
                prog, schema.columns, cap, env, length, params, sel=sel)
            if env:
                cap = next(iter(env.values()))[0].shape[0]
            return env, length, sel, schema, cap

        if pipe.pre_program is not None:
            env, length, sel, schema, cap = run(pipe.pre_program, env,
                                                length, sel, schema, cap)
        bi = 0
        for kind, step in pipe.steps:
            if kind == "join":
                meta = join_metas[bi]
                env, sel = probe_lut_traced(env, sel, builds[bi], meta)
                bi += 1
                schema = apply_join_schema(schema, meta["payload_cols"])
            else:
                env, length, sel, schema, cap = run(step, env, length, sel,
                                                    schema, cap)
        if pipe.partial is not None:
            env, length, sel, schema, cap = run(pipe.partial, env, length,
                                                sel, schema, cap)
        if sel is not None:
            env, length = compress(env, length, sel, cap)
        out_d = {n: d for n, (d, _v) in env.items()}
        out_v = {n: v for n, (d, v) in env.items() if v is not None}
        return out_d, out_v, length

    return fn


def tile_cache_key(pipe, scan_cols, K, CAP, sb_valid_names, builds_sig,
                   param_names):
    from ydb_tpu.ops.xla_exec import groupby_tuning
    progs = []
    if pipe.pre_program is not None:
        progs.append(pipe.pre_program.fingerprint())
    for kind, step in pipe.steps:
        if kind == "join":
            progs.append(("join", step.probe_key, step.kind,
                          tuple(step.payload), step.mark_col, step.not_in))
        else:
            progs.append(step.fingerprint())
    if pipe.partial is not None:
        progs.append(pipe.partial.fingerprint())
    return ("tile", tuple(progs),
            tuple((c.name, c.dtype.kind.value, c.dtype.nullable)
                  for c in scan_cols),
            K, CAP, tuple(sorted(sb_valid_names)), builds_sig,
            tuple(param_names), groupby_tuning())


def fused_cache_key(plan, scan_cols, K, CAP, sb_valid_names, builds_sig,
                    sort_spec, rank_assigns, param_names, lim_key=None,
                    compact_cap=None):
    # the plan signature carries the group-by tuning (tile rows / gather
    # batch cap / legacy flag): the cost gate for the tile count P runs
    # at trace time from (capacity, tuning), so a knob flip must compile
    # a fresh program rather than reuse one tiled differently.
    # `lim_key`: lifted-LIMIT plans key on the limit's capacity bucket
    # (("limB", bucket)) instead of the exact values — every LIMIT inside
    # one bucket shares one executable, the clamp rides in as __lim2
    from ydb_tpu.ops.xla_exec import groupby_tuning
    pipe = plan.pipeline
    progs = []
    if pipe.pre_program is not None:
        progs.append(pipe.pre_program.fingerprint())
    for kind, step in pipe.steps:
        if kind == "join":
            progs.append(("join", step.probe_key, step.kind,
                          tuple(step.payload), step.mark_col, step.not_in))
        else:
            progs.append(step.fingerprint())
    if pipe.partial is not None:
        progs.append(pipe.partial.fingerprint())
    if plan.final_program is not None:
        progs.append(plan.final_program.fingerprint())
    lim = (plan.limit, plan.offset) if lim_key is None else lim_key
    return (tuple(progs),
            tuple((c.name, c.dtype.kind.value, c.dtype.nullable)
                  for c in scan_cols),
            K, CAP, tuple(sorted(sb_valid_names)), builds_sig,
            sort_spec,
            ir.Program(rank_assigns).fingerprint() if rank_assigns else "",
            lim,
            tuple(n for (n, _lbl) in plan.output), tuple(param_names),
            # ladder-quantized compact capacity: a re-sized compact is a
            # different program; the late-mat LEVER itself rides inside
            # groupby_tuning(), so a flip can never reuse this trace
            ("compact", int(compact_cap or 0)),
            groupby_tuning())


def build_inputs_sig(bt) -> tuple:
    """Shape signature of a BuildTable's traced inputs. keys_sorted is
    ALWAYS traced (bsearch probes), so its capacity is always part of
    the signature."""
    return (bt.lut.shape[0] if bt.lut is not None else "bs",
            bt.keys_sorted.shape[0],
            next(iter(bt.payload.values())).shape[0] if bt.payload else 0,
            tuple(sorted(bt.payload)), tuple(sorted(bt.payload_valid)))


def build_traced_inputs(bt) -> dict:
    """The traced-input pytree for one BuildTable."""
    out = {
        "lut_base": jnp.int64(bt.lut_base),
        "n": jnp.int32(bt.n),
        "has_null": jnp.bool_(bt.anti_has_null),
        "keys": bt.keys_sorted,      # bsearch probes (sparse/float keys)
        "payload": dict(bt.payload),
        "pvalid": dict(bt.payload_valid),
    }
    if bt.lut is not None:           # pytree shape is part of the jit sig
        out["lut"] = bt.lut
    return out
