"""Differential tests: XLA lowering vs numpy oracle for SSA programs."""

import numpy as np
import pandas as pd
import pytest

from ydb_tpu.core import dtypes as dt
from ydb_tpu.core.block import HostBlock
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import ir
from ydb_tpu.ops.ir import Agg, Col, Const, Param, call
from ydb_tpu.ops import numpy_exec, xla_exec


def make_block(rng, n=5000, with_nulls=True):
    schema = Schema([
        Column("a", dt.INT64), Column("b", dt.FLOAT64),
        Column("c", dt.INT32), Column("k", dt.INT32),
        Column("d", dt.DATE32),
    ])
    arrays = {
        "a": rng.integers(-1000, 1000, n),
        "b": rng.normal(size=n) * 100,
        "c": rng.integers(0, 50, n).astype(np.int32),
        "k": rng.integers(0, 7, n).astype(np.int32),
        "d": rng.integers(8000, 12000, n).astype(np.int32),
    }
    valids = {}
    if with_nulls:
        valids["b"] = rng.random(n) > 0.1
        valids["a"] = rng.random(n) > 0.05
    return HostBlock.from_arrays(schema, arrays, valids)


def assert_blocks_equal(x: HostBlock, y: HostBlock, sort_by=None):
    dx, dy = x.to_pandas(), y.to_pandas()
    assert list(dx.columns) == list(dy.columns)
    assert len(dx) == len(dy)
    if sort_by:
        dx = dx.sort_values(sort_by).reset_index(drop=True)
        dy = dy.sort_values(sort_by).reset_index(drop=True)
    for col in dx.columns:
        a, b = dx[col].to_numpy(), dy[col].to_numpy()
        na, nb = pd.isna(a), pd.isna(b)
        assert (na == nb).all(), f"null mismatch in {col}"
        af = pd.to_numeric(pd.Series(a[~na]), errors="coerce").to_numpy(dtype=np.float64)
        bf = pd.to_numeric(pd.Series(b[~nb]), errors="coerce").to_numpy(dtype=np.float64)
        np.testing.assert_allclose(af, bf, rtol=1e-9, atol=1e-9, err_msg=col)


def run_both(program, block, params=None, sort_by=None):
    oracle = numpy_exec.run_program(program, block, params)
    device = xla_exec.run_program(program, block, params)
    assert_blocks_equal(oracle, device, sort_by=sort_by)
    return oracle, device


def test_assign_filter_arith(rng):
    b = make_block(rng)
    p = (ir.Program()
         .assign("e", call("mul", Col("a"), Const(2, dt.INT64)))
         .assign("f", call("add", Col("e"), call("abs", Col("b"))))
         .filter(call("gt", Col("f"), Const(0.0, dt.FLOAT64)))
         .project(["a", "e", "f"]))
    oracle, _ = run_both(p, b)
    assert oracle.length > 0


def test_filter_kleene_null_semantics(rng):
    b = make_block(rng)
    p = (ir.Program()
         .filter(call("or",
                      call("lt", Col("a"), Const(0, dt.INT64)),
                      call("gt", Col("b"), Const(50.0, dt.FLOAT64))))
         .project(["a", "b"]))
    run_both(p, b)


def test_global_agg(rng):
    b = make_block(rng)
    p = ir.Program().group_by([], [
        Agg("cnt", "count_all"),
        Agg("cnt_b", "count", "b"),
        Agg("s", "sum", "b"),
        Agg("mn", "min", "a"),
        Agg("mx", "max", "a"),
    ])
    oracle, _ = run_both(p, b)
    assert oracle.length == 1


def test_grouped_agg(rng):
    b = make_block(rng)
    p = (ir.Program()
         .group_by(["k"], [
             Agg("cnt", "count_all"),
             Agg("s", "sum", "b"),
             Agg("sa", "sum", "a"),
             Agg("mn", "min", "b"),
             Agg("mx", "max", "b"),
         ]))
    run_both(p, b, sort_by=["k"])


def test_multi_key_group_with_filter(rng):
    b = make_block(rng)
    p = (ir.Program()
         .filter(call("le", Col("d"), Const(11000, dt.DATE32)))
         .group_by(["k", "c"], [Agg("cnt", "count_all"), Agg("s", "sum", "b")]))
    run_both(p, b, sort_by=["k", "c"])


def test_group_by_nullable_key(rng):
    b = make_block(rng)
    p = ir.Program().group_by(["a"], [Agg("cnt", "count_all")])
    run_both(p, b, sort_by=["a"])


def test_date_extract(rng):
    b = make_block(rng)
    p = (ir.Program()
         .assign("y", call("year", Col("d")))
         .assign("m", call("month", Col("d")))
         .project(["d", "y", "m"]))
    oracle, _ = run_both(p, b)
    df = oracle.to_pandas()
    expect = pd.to_datetime(df["d"].astype(np.int64), unit="D")
    assert (df["y"].to_numpy() == expect.dt.year.to_numpy()).all()
    assert (df["m"].to_numpy() == expect.dt.month.to_numpy()).all()


def test_if_coalesce(rng):
    b = make_block(rng)
    p = (ir.Program()
         .assign("x", call("if",
                           call("ge", Col("a"), Const(0, dt.INT64)),
                           Col("b"), call("neg", Col("b"))))
         .assign("y", call("coalesce", Col("b"), Const(0.0, dt.FLOAT64)))
         .project(["x", "y"]))
    run_both(p, b)


def test_take_lut_param(rng):
    b = make_block(rng)
    lut = rng.random(50) > 0.5  # pretend: predicate over a 50-entry dictionary
    p = (ir.Program()
         .filter(call("take_lut", Col("c"), Param("lut0", dt.BOOL, is_array=True)))
         .group_by([], [Agg("cnt", "count_all")]))
    run_both(p, b, params={"lut0": lut})


def test_string_dictionary_roundtrip(rng):
    df = pd.DataFrame({
        "s": ["apple", "banana", None, "apple", "cherry"] * 100,
        "v": np.arange(500, dtype=np.float64),
    })
    b = HostBlock.from_pandas(df)
    assert b.schema.dtype("s").is_string
    d = b.columns["s"].dictionary
    lut = d.lut(lambda v: v.startswith("a"))
    p = (ir.Program()
         .filter(call("take_lut", Col("s"), Param("lut", dt.BOOL, is_array=True)))
         .group_by(["s"], [Agg("cnt", "count_all"), Agg("sv", "sum", "v")]))
    oracle, device = run_both(p, b, params={"lut": lut}, sort_by=["s"])
    out = oracle.to_pandas()
    assert set(out["s"]) == {"apple"}
    assert int(out["cnt"].iloc[0]) == 200


def test_program_cache_reuse(rng):
    cache = xla_exec.ProgramCache()
    p = ir.Program().filter(call("gt", Col("a"), Const(0, dt.INT64))).project(["a"])
    b1, b2 = make_block(rng, 3000), make_block(rng, 4000)
    xla_exec.run_program(p, b1, cache=cache)
    xla_exec.run_program(p, b2, cache=cache)  # same capacity bucket 8192
    assert cache.misses == 1 and cache.hits == 1
