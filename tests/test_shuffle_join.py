"""Distributed shuffle join: partitioned builds over the mesh.

VERDICT r3 item 3: stop replicating join builds to every device. The
build hash-partitions across mesh devices (no device holds the full
build — pinned by construction in `partition_build`) and probe rows
route to their key's owner via one ICI all_to_all
(`parallel/shuffle_join.py`, the `dq_opt_join.cpp` ShuffleJoin +
`dq_tasks_graph.h` stage-boundary analog).
"""

import numpy as np
import pandas as pd
import pytest

from ydb_tpu.parallel import make_mesh
from ydb_tpu.query import QueryEngine


@pytest.fixture(scope="module")
def eng():
    e = QueryEngine(block_rows=1 << 10, mesh=make_mesh(8))
    e.execute("create table fact (id Int64 not null, k Int64 not null, "
              "g Int64 not null, v Double not null, primary key (id))")
    e.execute("create table dim (k2 Int64 not null, w Double not null, "
              "primary key (k2))")
    n, m = 20_000, 4_000
    ids = np.arange(n)
    ks = (ids * 7) % m          # some dim keys never hit
    gs = ids % 11
    vs = ids * 0.5
    for lo in range(0, n, 5_000):
        rows = ",".join(f"({i},{k},{g},{v})" for i, k, g, v in
                        zip(ids[lo:lo+5_000], ks[lo:lo+5_000],
                            gs[lo:lo+5_000], vs[lo:lo+5_000]))
        e.execute(f"insert into fact (id, k, g, v) values {rows}")
    rows = ",".join(f"({k},{k * 1.5})" for k in range(0, m, 2))
    e.execute(f"insert into dim (k2, w) values {rows}")
    # force the partitioned path: every build is "too big to broadcast"
    e.executor.dist_broadcast_budget_bytes = 1
    e.fact = pd.DataFrame({"id": ids, "k": ks, "g": gs, "v": vs})
    e.dim = pd.DataFrame({"k2": np.arange(0, m, 2),
                          "w": np.arange(0, m, 2) * 1.5})
    return e


def test_shuffle_inner_join_agg(eng):
    got = eng.query(
        "select g, count(*) as n, sum(v + w) as s from fact, dim "
        "where k = k2 group by g order by g")
    assert eng.executor.last_path == "distributed-shuffle-join"
    j = eng.fact.merge(eng.dim, left_on="k", right_on="k2")
    w = j.assign(s=j.v + j.w).groupby("g").agg(
        n=("s", "size"), s=("s", "sum")).reset_index()
    assert list(got.g) == list(w.g)
    assert list(got.n) == list(w.n)
    np.testing.assert_allclose(got.s, w.s, rtol=1e-9)
    from ydb_tpu.utils.metrics import GLOBAL
    assert GLOBAL.snapshot().get("executor/shuffle_joins", 0) >= 1


def test_shuffle_semi_join_agg(eng):
    got = eng.query(
        "select g, sum(v) as s from fact where k in (select k2 from dim) "
        "group by g order by g")
    assert eng.executor.last_path == "distributed-shuffle-join"
    f = eng.fact[eng.fact.k.isin(eng.dim.k2)]
    w = f.groupby("g").v.sum().reset_index()
    assert list(got.g) == list(w.g)
    np.testing.assert_allclose(got.s, w.v, rtol=1e-9)


def test_shuffle_anti_join_agg(eng):
    got = eng.query(
        "select g, count(*) as n from fact "
        "where not exists (select * from dim where k2 = k) "
        "group by g order by g")
    assert eng.executor.last_path == "distributed-shuffle-join"
    f = eng.fact[~eng.fact.k.isin(eng.dim.k2)]
    w = f.groupby("g").size().reset_index(name="n")
    assert list(got.g) == list(w.g)
    assert list(got.n) == list(w.n)


def test_shuffle_join_global_agg(eng):
    got = eng.query("select sum(v * w) as s, count(*) as n "
                    "from fact, dim where k = k2")
    assert eng.executor.last_path == "distributed-shuffle-join"
    j = eng.fact.merge(eng.dim, left_on="k", right_on="k2")
    np.testing.assert_allclose(got.s[0], (j.v * j.w).sum(), rtol=1e-9)
    assert got.n[0] == len(j)


def test_no_device_holds_full_build(eng):
    """Pin the partitioning contract: each device's build partition is a
    strict subset (the point of the shuffle join)."""
    from ydb_tpu.parallel.shuffle_join import partition_build
    from ydb_tpu.core.block import HostBlock
    import ydb_tpu.core.dtypes as dt
    from ydb_tpu.core.schema import Column, Schema

    n = 10_000
    schema = Schema([Column("k", dt.DType(dt.Kind.INT64, False)),
                     Column("w", dt.DType(dt.Kind.FLOAT64, False))])
    hb = HostBlock.from_arrays(schema, {
        "k": np.arange(n, dtype=np.int64),
        "w": np.arange(n, dtype=np.float64)})
    arrays, pschema, dicts, bcap = partition_build(hb, "k", ["w"], 8)
    assert int(arrays["ns"].sum()) == n
    assert all(int(c) < n for c in arrays["ns"])      # strict subsets
    # partitions are disjoint by key hash
    seen = set()
    for p in range(8):
        ks = set(arrays["keys"][p][:arrays["ns"][p]].tolist())
        assert not (ks & seen)
        seen |= ks
    assert len(seen) == n
