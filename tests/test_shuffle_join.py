"""Distributed shuffle join: partitioned builds over the mesh.

VERDICT r3 item 3: stop replicating join builds to every device. The
build hash-partitions across mesh devices (no device holds the full
build — pinned by construction in `partition_build`) and probe rows
route to their key's owner via one ICI all_to_all
(`parallel/shuffle_join.py`, the `dq_opt_join.cpp` ShuffleJoin +
`dq_tasks_graph.h` stage-boundary analog).
"""

import numpy as np
import pandas as pd
import pytest

from ydb_tpu.parallel import make_mesh
from ydb_tpu.query import QueryEngine


@pytest.fixture(scope="module")
def eng():
    e = QueryEngine(block_rows=1 << 10, mesh=make_mesh(8))
    e.execute("create table fact (id Int64 not null, k Int64 not null, "
              "g Int64 not null, v Double not null, primary key (id))")
    e.execute("create table dim (k2 Int64 not null, w Double not null, "
              "primary key (k2))")
    n, m = 20_000, 4_000
    ids = np.arange(n)
    ks = (ids * 7) % m          # some dim keys never hit
    gs = ids % 11
    vs = ids * 0.5
    for lo in range(0, n, 5_000):
        rows = ",".join(f"({i},{k},{g},{v})" for i, k, g, v in
                        zip(ids[lo:lo+5_000], ks[lo:lo+5_000],
                            gs[lo:lo+5_000], vs[lo:lo+5_000]))
        e.execute(f"insert into fact (id, k, g, v) values {rows}")
    rows = ",".join(f"({k},{k * 1.5})" for k in range(0, m, 2))
    e.execute(f"insert into dim (k2, w) values {rows}")
    # force the partitioned path: every build is "too big to broadcast"
    e.executor.dist_broadcast_budget_bytes = 1
    e.fact = pd.DataFrame({"id": ids, "k": ks, "g": gs, "v": vs})
    e.dim = pd.DataFrame({"k2": np.arange(0, m, 2),
                          "w": np.arange(0, m, 2) * 1.5})
    return e


def test_shuffle_inner_join_agg(eng):
    got = eng.query(
        "select g, count(*) as n, sum(v + w) as s from fact, dim "
        "where k = k2 group by g order by g")
    assert eng.executor.last_path == "distributed-shuffle-join"
    j = eng.fact.merge(eng.dim, left_on="k", right_on="k2")
    w = j.assign(s=j.v + j.w).groupby("g").agg(
        n=("s", "size"), s=("s", "sum")).reset_index()
    assert list(got.g) == list(w.g)
    assert list(got.n) == list(w.n)
    np.testing.assert_allclose(got.s, w.s, rtol=1e-9)
    from ydb_tpu.utils.metrics import GLOBAL
    assert GLOBAL.snapshot().get("executor/shuffle_joins", 0) >= 1


def test_shuffle_semi_join_agg(eng):
    got = eng.query(
        "select g, sum(v) as s from fact where k in (select k2 from dim) "
        "group by g order by g")
    assert eng.executor.last_path == "distributed-shuffle-join"
    f = eng.fact[eng.fact.k.isin(eng.dim.k2)]
    w = f.groupby("g").v.sum().reset_index()
    assert list(got.g) == list(w.g)
    np.testing.assert_allclose(got.s, w.v, rtol=1e-9)


def test_shuffle_anti_join_agg(eng):
    got = eng.query(
        "select g, count(*) as n from fact "
        "where not exists (select * from dim where k2 = k) "
        "group by g order by g")
    assert eng.executor.last_path == "distributed-shuffle-join"
    f = eng.fact[~eng.fact.k.isin(eng.dim.k2)]
    w = f.groupby("g").size().reset_index(name="n")
    assert list(got.g) == list(w.g)
    assert list(got.n) == list(w.n)


def test_shuffle_join_global_agg(eng):
    got = eng.query("select sum(v * w) as s, count(*) as n "
                    "from fact, dim where k = k2")
    assert eng.executor.last_path == "distributed-shuffle-join"
    j = eng.fact.merge(eng.dim, left_on="k", right_on="k2")
    np.testing.assert_allclose(got.s[0], (j.v * j.w).sum(), rtol=1e-9)
    assert got.n[0] == len(j)


def test_no_device_holds_full_build(eng):
    """Pin the partitioning contract: each device's build partition is a
    strict subset (the point of the shuffle join)."""
    from ydb_tpu.parallel.shuffle_join import partition_build
    from ydb_tpu.core.block import HostBlock
    import ydb_tpu.core.dtypes as dt
    from ydb_tpu.core.schema import Column, Schema

    n = 10_000
    schema = Schema([Column("k", dt.DType(dt.Kind.INT64, False)),
                     Column("w", dt.DType(dt.Kind.FLOAT64, False))])
    hb = HostBlock.from_arrays(schema, {
        "k": np.arange(n, dtype=np.int64),
        "w": np.arange(n, dtype=np.float64)})
    arrays, pschema, dicts, bcap = partition_build(hb, "k", ["w"], 8)
    assert int(arrays["ns"].sum()) == n
    assert all(int(c) < n for c in arrays["ns"])      # strict subsets
    # partitions are disjoint by key hash
    seen = set()
    for p in range(8):
        ks = set(arrays["keys"][p][:arrays["ns"][p]].tolist())
        assert not (ks & seen)
        seen |= ks
    assert len(seen) == n


def test_shuffle_join_composite_key(eng):
    """VERDICT r4 #8: composite join keys exchange as combined 64-bit
    hashes — no full-build replication (the broadcast decline is gone)."""
    e = eng
    e.execute("create table cfact (id Int64 not null, a Int64 not null, "
              "b Int64 not null, v Double not null, primary key (id))")
    e.execute("create table cdim (a2 Int64 not null, b2 Int64 not null, "
              "w Double not null, primary key (a2, b2))")
    n = 8_000
    ids = np.arange(n)
    aa, bb = ids % 37, ids % 11
    rows = ",".join(f"({i},{a},{b},{i * 0.25})"
                    for i, a, b in zip(ids, aa, bb))
    e.execute(f"insert into cfact (id, a, b, v) values {rows}")
    pairs = {(a, b): (a * 100 + b) * 0.5
             for a in range(0, 37, 2) for b in range(11)}
    rows = ",".join(f"({a},{b},{w})" for (a, b), w in pairs.items())
    e.execute(f"insert into cdim (a2, b2, w) values {rows}")
    got = e.query("select count(*) as n, sum(v + w) as s from cfact, cdim "
                  "where a = a2 and b = b2")
    assert e.executor.last_path == "distributed-shuffle-join"
    f = pd.DataFrame({"a": aa, "b": bb, "v": ids * 0.25})
    d = pd.DataFrame([(a, b, w) for (a, b), w in pairs.items()],
                     columns=["a2", "b2", "w"])
    j = f.merge(d, left_on=["a", "b"], right_on=["a2", "b2"])
    assert int(got.n[0]) == len(j)
    np.testing.assert_allclose(got.s[0], (j.v + j.w).sum(), rtol=1e-9)


def test_shuffle_join_string_key(eng):
    """Dictionary-encoded join keys: build codes remap into the probe
    dictionary and exchange as ints."""
    e = eng
    e.execute("create table sfact (id Int64 not null, tag Utf8 not null, "
              "v Double not null, primary key (id))")
    e.execute("create table sdim (tag2 Utf8 not null, w Double not null, "
              "primary key (tag2))")
    n = 6_000
    ids = np.arange(n)
    tags = [f"t{i % 97}" for i in ids]
    rows = ",".join(f"({i},'{t}',{i * 0.5})" for i, t in zip(ids, tags))
    e.execute(f"insert into sfact (id, tag, v) values {rows}")
    # dim inserts in a DIFFERENT order → different dictionary codes
    dim = {f"t{k}": k * 2.0 for k in range(96, -1, -3)}
    rows = ",".join(f"('{t}',{w})" for t, w in dim.items())
    e.execute(f"insert into sdim (tag2, w) values {rows}")
    got = e.query("select count(*) as n, sum(v + w) as s "
                  "from sfact, sdim where tag = tag2")
    assert e.executor.last_path == "distributed-shuffle-join"
    f = pd.DataFrame({"tag": tags, "v": ids * 0.5})
    d = pd.DataFrame(list(dim.items()), columns=["tag2", "w"])
    j = f.merge(d, left_on="tag", right_on="tag2")
    assert int(got.n[0]) == len(j)
    np.testing.assert_allclose(got.s[0], (j.v + j.w).sum(), rtol=1e-9)


def test_shuffle_join_q9_shape(eng):
    """The q9 shape: multi-join pipeline whose LAST join is the big
    composite-keyed one — earlier dimension joins broadcast, the big
    build hash-partitions (oracle-checked)."""
    e = eng
    e.execute("create table q9f (id Int64 not null, pk Int64 not null, "
              "sk Int64 not null, g Int64 not null, v Double not null, "
              "primary key (id))")
    e.execute("create table q9d (sk2 Int64 not null, nm Utf8 not null, "
              "primary key (sk2))")
    e.execute("create table q9ps (pk2 Int64 not null, sk3 Int64 not null, "
              "cost Double not null, primary key (pk2, sk3))")
    n = 8_000
    ids = np.arange(n)
    pk, sk, g = ids % 53, ids % 13, ids % 5
    rows = ",".join(f"({i},{p},{s},{q},{i * 0.1})"
                    for i, p, s, q in zip(ids, pk, sk, g))
    e.execute(f"insert into q9f (id, pk, sk, g, v) values {rows}")
    rows = ",".join(f"({s},'n{s % 4}')" for s in range(13))
    e.execute(f"insert into q9d (sk2, nm) values {rows}")
    ps = {(p, s): p + s * 0.25 for p in range(53) for s in range(13)
          if (p + s) % 3 != 0}
    rows = ",".join(f"({p},{s},{c})" for (p, s), c in ps.items())
    e.execute(f"insert into q9ps (pk2, sk3, cost) values {rows}")
    got = e.query(
        "select nm, sum(v - cost) as profit from q9f, q9d, q9ps "
        "where sk = sk2 and pk = pk2 and sk = sk3 "
        "group by nm order by nm")
    assert e.executor.last_path == "distributed-shuffle-join"
    f = pd.DataFrame({"pk": pk, "sk": sk, "v": ids * 0.1})
    dd = pd.DataFrame({"sk2": np.arange(13),
                       "nm": [f"n{s % 4}" for s in range(13)]})
    pp = pd.DataFrame([(p, s, c) for (p, s), c in ps.items()],
                      columns=["pk2", "sk3", "cost"])
    j = f.merge(dd, left_on="sk", right_on="sk2") \
         .merge(pp, left_on=["pk", "sk"], right_on=["pk2", "sk3"])
    w = j.assign(profit=j.v - j.cost).groupby("nm", as_index=False) \
         .profit.sum()
    assert list(got.nm) == list(w.nm)
    np.testing.assert_allclose(got.profit, w.profit, rtol=1e-9)


def test_shuffle_join_string_key_unreferenced_dim_values(eng):
    """Build values ABSENT from the probe dictionary all remap to the
    shared -2 never-match code: they must be dropped pre-exchange, not
    trip the duplicate-key gate into a silent broadcast fallback."""
    e = eng
    e.execute("create table s2fact (id Int64 not null, tag Utf8 not null, "
              "v Double not null, primary key (id))")
    e.execute("create table s2dim (tag2 Utf8 not null, w Double not null, "
              "primary key (tag2))")
    n = 6_000
    ids = np.arange(n)
    tags = [f"t{i % 40}" for i in ids]        # fact uses only t0..t39
    rows = ",".join(f"({i},'{t}',{i * 0.5})" for i, t in zip(ids, tags))
    e.execute(f"insert into s2fact (id, tag, v) values {rows}")
    dim = {f"t{k}": k * 2.0 for k in range(120)}   # 80 values never probed
    rows = ",".join(f"('{t}',{w})" for t, w in dim.items())
    e.execute(f"insert into s2dim (tag2, w) values {rows}")
    got = e.query("select count(*) as n, sum(v + w) as s "
                  "from s2fact, s2dim where tag = tag2")
    assert e.executor.last_path == "distributed-shuffle-join"
    f = pd.DataFrame({"tag": tags, "v": ids * 0.5})
    d = pd.DataFrame(list(dim.items()), columns=["tag2", "w"])
    j = f.merge(d, left_on="tag", right_on="tag2")
    assert int(got.n[0]) == len(j)
    np.testing.assert_allclose(got.s[0], (j.v + j.w).sum(), rtol=1e-9)


def test_shuffle_join_tuning_flip_rebuilds(eng, monkeypatch):
    """Cache-key completeness (graftlint cache-key pass): the executor's
    ShuffleJoin cache keys on the group-by tuning tuple. The SAME SQL
    across a YDB_TPU_GROUPBY_TILE_ROWS flip must build a second
    ShuffleJoin (its traced partial/rest programs are tiled under the
    live knobs) and return the same answer — before the fix the flip
    reused the instance traced under the old settings."""
    sql = ("select g, sum(v * w) as s from fact join dim on k = k2 "
           "group by g order by g")
    monkeypatch.delenv("YDB_TPU_GROUPBY_TILE_ROWS", raising=False)
    out1 = eng.query(sql)
    n0 = len(eng.executor._shuffle_joins)
    assert n0 >= 1
    out_cached = eng.query(sql)
    assert len(eng.executor._shuffle_joins) == n0     # same tuning: hit

    monkeypatch.setenv("YDB_TPU_GROUPBY_TILE_ROWS", "256")
    out2 = eng.query(sql)
    assert len(eng.executor._shuffle_joins) == n0 + 1, \
        "tuning flip must build a fresh ShuffleJoin, not serve the stale one"
    for out in (out_cached, out2):
        assert list(out.g) == list(out1.g)
        np.testing.assert_allclose(out.s, out1.s, rtol=1e-9)
