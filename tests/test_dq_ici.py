"""DQ channel ICI plane (`ydb_tpu/dq/ici.py`) — differential suite.

Every test drives the REAL pluggable-plane path: LocalWorkers on the
virtual 8-device CPU mesh (conftest), `dq/lower.py` choosing
`plane="ici"` for worker-bound edges, the runner executing the
redistribution as a device collective, and the `YDB_TPU_DQ_PLANE=host`
lever as the byte-equal oracle. Quantization (`YDB_TPU_DQ_QUANT=1`)
differentials: SUM/AVG within declared tolerance, keys and
COUNT/MIN/MAX bit-exact, non-quantizable declared columns refused
loudly and shipped exact. Failure injection: a worker dying
mid-collective falls back to the host plane with the query still
completing.
"""

import numpy as np
import pandas as pd
import pytest

from ydb_tpu.cluster import ShardedCluster
from ydb_tpu.dq.lower import DqLowerError, DqTopology, lower_select
from ydb_tpu.dq.runner import DqTaskRunner, LocalWorker
from ydb_tpu.query import QueryEngine
from ydb_tpu.sql import parse
from ydb_tpu.utils.metrics import GLOBAL

NW = 2
ROWS = 140

# declared quantization tolerance: int8 per-block symmetric codes bound
# each value's error by maxabs/254 of its block; SUM/AVG over same-sign
# same-magnitude columns stay within ~1% — 2% is the declared contract
QUANT_RTOL = 2e-2


def _mk_engine(wid: int, nw: int = NW) -> QueryEngine:
    eng = QueryEngine(block_rows=1 << 12)
    eng.execute("create table t (id Int64 not null, k Int64 not null, "
                "v Double not null, tag Utf8 not null, nv Double, "
                "primary key (id))")
    eng.execute("create table u (uid Int64 not null, w Double not null, "
                "x Double not null, primary key (uid))")
    mine = [i for i in range(ROWS) if i % nw == wid]
    # v is DYADIC (i * 0.5): float sums are exact in any order, so the
    # host-vs-ICI comparisons below can demand byte-equality; nv carries
    # NULLs (object dtype through to_pandas — the mask codec lane)
    eng.execute(
        "insert into t (id, k, v, tag, nv) values "
        + ", ".join(f"({i}, {i % 7}, {i * 0.5}, 'tag{i % 3}', "
                    + ("null" if i % 5 == 0 else f"{i * 0.25}") + ")"
                    for i in mine))
    umine = [i for i in range(7) if i % nw == wid]
    if umine:
        # x magnitudes are HOMOGENEOUS (~10..12): per-block int8
        # quantization bounds error by block-maxabs/254 per value, so
        # the declared RELATIVE tolerance only holds when a block's
        # values share a magnitude — the aggregation-tolerant shape
        # the planner proof targets (prices, measures), not mixtures
        # spanning orders of magnitude
        eng.execute("insert into u (uid, w, x) values "
                    + ", ".join(f"({i}, {i}.0, {10.0 + i * 0.3})"
                                for i in umine))
    return eng


@pytest.fixture(scope="module")
def cluster():
    engines = [_mk_engine(i) for i in range(NW)]
    c = ShardedCluster([LocalWorker(e, name=f"icw{i}")
                        for i, e in enumerate(engines)],
                       merge_engine=engines[0])
    c.key_columns["t"] = ["id"]
    c.key_columns["u"] = ["uid"]
    return c


def _frames_equal(a: pd.DataFrame, b: pd.DataFrame, rtol=None,
                  loose_cols=()):
    assert list(a.columns) == list(b.columns)
    assert len(a) == len(b)
    for col in a.columns:
        x, y = a[col].to_numpy(), b[col].to_numpy()
        if col in loose_cols:
            np.testing.assert_allclose(x.astype(np.float64),
                                       y.astype(np.float64), rtol=rtol)
        elif x.dtype.kind == "f" or y.dtype.kind == "f":
            assert np.array_equal(x.astype(np.float64),
                                  y.astype(np.float64),
                                  equal_nan=True), col
        else:
            assert np.array_equal(x, y), col


JOIN_SQL = ("select k, count(*) as n, sum(w) as s, min(x) as mn, "
            "max(x) as mx from t, u where k = uid group by k order by k")


# -- lowering: plane selection ---------------------------------------------


def _cols(table):
    return {"t": ["id", "k", "v", "tag", "nv"],
            "u": ["uid", "w", "x"]}[table]


def _topo(ici_devices):
    return DqTopology(n_workers=2, key_columns={"t": ["id"],
                                                "u": ["uid"]},
                      ici_devices=ici_devices)


def test_lowering_picks_ici_for_mesh_colocated_edges():
    g = lower_select(parse(JOIN_SQL), _topo(ici_devices=8), _cols)
    planes = {ch.kind: ch.plane for ch in g.channels.values()}
    assert planes["hash_shuffle"] == "ici"     # worker-bound: device edge
    assert planes["union_all"] == "host"       # router-bound: collected
    assert "plane=ici" in g.explain()


def test_lowering_keeps_host_without_shared_mesh(monkeypatch):
    g = lower_select(parse(JOIN_SQL), _topo(ici_devices=0), _cols)
    assert all(ch.plane == "host" for ch in g.channels.values())
    # the force-host lever beats a capable mesh
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "host")
    g = lower_select(parse(JOIN_SQL), _topo(ici_devices=8), _cols)
    assert all(ch.plane == "host" for ch in g.channels.values())
    # force-ici on an incapable topology refuses instead of lying
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "ici")
    with pytest.raises(DqLowerError, match="device-colocated"):
        lower_select(parse(JOIN_SQL), _topo(ici_devices=0), _cols)


def test_lowering_proves_quant_tolerance():
    g = lower_select(parse(
        "select k, sum(w) as s, avg(x) as a, min(w) as mn from t, u "
        "where k = uid group by k"), _topo(8), _cols)
    by_key = {ch.key: ch for ch in g.channels.values()
              if ch.kind == "hash_shuffle"}
    # x only feeds AVG → tolerant; w feeds SUM and MIN → exact; the
    # join keys are never candidates
    assert by_key["uid"].quant_cols == ["x"]
    assert by_key["k"].quant_cols == []


# -- execution: ICI vs host plane differentials ----------------------------


def test_join_ici_byte_equal_to_host_plane(monkeypatch, cluster):
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "host")
    want = cluster.query(JOIN_SQL)
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
    b0 = GLOBAL.get("dq/ici_bytes")
    cb0 = GLOBAL.get("dq/channel_bytes")
    got = cluster.query(JOIN_SQL)
    _frames_equal(got, want)
    # the edge's bytes moved planes: device collective, zero npz frames
    assert GLOBAL.get("dq/ici_bytes") > b0
    assert GLOBAL.get("dq/channel_bytes") == cb0
    assert GLOBAL.get("dq/ici_frames") >= 2 * NW * NW


def test_string_and_nullable_columns_cross_ici(monkeypatch, cluster):
    """Dictionary (string) and masked (NULL-bearing numeric) codecs:
    shuffle edges whose payload is not plain numerics still match the
    host plane byte-for-byte."""
    sql = ("select tag, count(*) as n, sum(v) as s, sum(nv) as sn "
           "from t, u where k = uid group by tag order by tag")
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "host")
    want = cluster.query(sql)
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
    got = cluster.query(sql)
    # nv sums are NOT dyadic — still equal because every worker's rows
    # land in producer order on both planes
    _frames_equal(got, want)


def test_zero_row_and_skewed_shapes(monkeypatch, cluster):
    for sql in (
            # 0-row: no t row survives the filter
            "select k, count(*) as n, sum(w) as s from t, u "
            "where k = uid and v < -1 group by k order by k",
            # skew: one key → every exchanged row lands on ONE consumer
            "select k, count(*) as n, sum(w) as s from t, u "
            "where k = uid and k = 3 group by k order by k"):
        monkeypatch.setenv("YDB_TPU_DQ_PLANE", "host")
        want = cluster.query(sql)
        monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
        got = cluster.query(sql)
        _frames_equal(got, want)


# -- quantization differentials --------------------------------------------


def test_quant_tolerant_within_declared_tolerance(monkeypatch, cluster):
    sql = ("select k, count(*) as n, sum(x) as s, avg(x) as a from t, u "
           "where k = uid group by k order by k")
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
    monkeypatch.setenv("YDB_TPU_DQ_QUANT", "0")
    want = cluster.query(sql)
    monkeypatch.setenv("YDB_TPU_DQ_QUANT", "1")
    q0 = GLOBAL.get("dq/quant_bytes_saved")
    got = cluster.query(sql)
    # keys + COUNT bit-exact, SUM/AVG within the declared tolerance,
    # and the saving is measured, not assumed
    _frames_equal(got, want, rtol=QUANT_RTOL, loose_cols=("s", "a"))
    assert GLOBAL.get("dq/quant_bytes_saved") > q0


def test_quant_never_touches_keys_count_min_max(monkeypatch, cluster):
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
    monkeypatch.setenv("YDB_TPU_DQ_QUANT", "0")
    want = cluster.query(JOIN_SQL)
    monkeypatch.setenv("YDB_TPU_DQ_QUANT", "1")
    got = cluster.query(JOIN_SQL)
    # w feeds SUM only → may quantize… but min/max columns (x) and the
    # keys/count are bit-exact BY CONSTRUCTION (exact-context columns
    # never enter quant_cols)
    _frames_equal(got, want, rtol=QUANT_RTOL, loose_cols=("s",))
    for col in ("k", "n", "mn", "mx"):
        assert np.array_equal(got[col].to_numpy(), want[col].to_numpy())


def test_quant_refused_on_unquantizable_column(monkeypatch, cluster):
    """nv is NULL-bearing (object dtype on the wire): the planner may
    prove it tolerant, but the runtime codec is a masked lane — the
    quant request must be REFUSED (counted) and shipped exact, never
    silently lossy."""
    sql = ("select k, sum(nv) as sn from t, u where k = uid "
           "group by k order by k")
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
    monkeypatch.setenv("YDB_TPU_DQ_QUANT", "0")
    want = cluster.query(sql)
    monkeypatch.setenv("YDB_TPU_DQ_QUANT", "1")
    r0 = GLOBAL.get("dq/quant_refused")
    got = cluster.query(sql)
    assert GLOBAL.get("dq/quant_refused") > r0
    _frames_equal(got, want)         # exact: the refusal shipped verbatim


# -- failure: mid-collective worker death → host-plane fallback ------------


class _DieOnIciLand(LocalWorker):
    """Worker whose device plane 'dies' mid-collective: the first landed
    partition raises a transport error (the in-process analog of a chip
    dropping out of the mesh between the all_to_all and the barrier)."""

    def __init__(self, engine, name=""):
        super().__init__(engine, name=name)
        self.armed = True

    def ici_land(self, channel, df, nbytes, src="ici", seq=None):
        if self.armed:
            self.armed = False
            raise ConnectionError("worker lost mid-collective")
        return super().ici_land(channel, df, nbytes, src=src, seq=seq)


def test_mid_collective_death_falls_back_to_host(monkeypatch):
    engines = [_mk_engine(i) for i in range(NW)]
    workers = [_DieOnIciLand(engines[0], name="die0"),
               LocalWorker(engines[1], name="ok1")]
    c = ShardedCluster(workers, merge_engine=engines[0])
    c.key_columns["t"] = ["id"]
    c.key_columns["u"] = ["uid"]
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
    f0 = GLOBAL.get("dq/ici_fallbacks")
    got = c.query(JOIN_SQL)
    assert GLOBAL.get("dq/ici_fallbacks") > f0
    # the query still COMPLETED, correct, on the host plane
    oracle = ShardedCluster([LocalWorker(_mk_engine(0, nw=1))])
    oracle.key_columns["t"] = ["id"]
    oracle.key_columns["u"] = ["uid"]
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "host")
    want = oracle.query(JOIN_SQL)
    _frames_equal(got, want)


# -- broadcast edge + observability ----------------------------------------


def test_broadcast_edge_rides_ici():
    """Hand-built Broadcast edge on the ICI plane: all-gather lands
    EVERY producer's rows on every consumer."""
    from ydb_tpu.dq.graph import (BROADCAST, UNION_ALL, Channel, Stage,
                                  StageGraph)
    engines = [_mk_engine(i) for i in range(NW)]
    workers = [LocalWorker(e, name=f"bc{i}")
               for i, e in enumerate(engines)]
    ch = Channel(id="dqc_ici_b1", kind=BROADCAST, src_stage="s0",
                 dst_stage="s1", columns=["id", "v"],
                 table="__xj_dq_ici_bcast", plane="ici")
    out = Channel(id="dqc_ici_b2", kind=UNION_ALL, src_stage="s1")
    g = StageGraph(
        stages=[Stage(id="s0", sql="select id, v from t",
                      outputs=[ch.id]),
                Stage(id="s1",
                      sql=f"select count(*) as c, sum(v) as s "
                          f"from {ch.table}",
                      inputs=[ch.id], outputs=[out.id]),
                Stage(id="merge", inputs=[out.id], on="router",
                      merge_sel=None)],
        channels={ch.id: ch, out.id: out}, tag="icib")
    got = DqTaskRunner(workers, engines[0]).run(g)
    want_s = sum(i * 0.5 for i in range(ROWS))
    assert list(got.c) == [ROWS, ROWS]       # each worker saw every row
    assert list(got.s) == [want_s, want_s]


def test_plane_visible_in_explain_and_sysview(monkeypatch, cluster):
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
    plan = cluster.query(f"explain analyze {JOIN_SQL}")
    text = "\n".join(plan["plan"])
    assert "plane=ici" in text               # per-channel plane column
    assert "plane ici" in text               # per-task profile rows
    stats = cluster.query("select stage, plane, ici_bytes "
                          "from `.sys/dq_stage_stats` "
                          "where plane = 'ici'")
    assert len(stats) > 0
    assert (stats["ici_bytes"].to_numpy() > 0).all()


def test_graph_validate_rejects_ici_router_bound():
    from ydb_tpu.dq.graph import UNION_ALL, Channel, Stage, StageGraph
    ch = Channel(id="c1", kind=UNION_ALL, src_stage="s0", plane="ici")
    g = StageGraph(stages=[Stage(id="s0", sql="x", outputs=["c1"]),
                           Stage(id="merge", inputs=["c1"],
                                 on="router")],
                   channels={"c1": ch}, tag="v")
    with pytest.raises(ValueError, match="ICI-plane and router-bound"):
        g.validate()


def test_quantize_blocked_roundtrip_with_nan():
    import jax.numpy as jnp

    from ydb_tpu.parallel.collective import (dequantize_blocked,
                                             quantize_blocked)
    rng = np.random.default_rng(7)
    x = rng.normal(size=(4, 256)) * 13.0
    x[1, 3] = np.nan
    x[2, :] = 0.0                             # all-zero block: scale 1
    q, s = quantize_blocked(jnp.asarray(x))
    assert q.dtype == jnp.int8 and s.shape == (4, 2)
    back = np.asarray(dequantize_blocked(q, s, np.float64))
    assert np.isnan(back[1, 3]) and not np.isnan(back[1, 4])
    finite = ~np.isnan(x)
    # per-value error bounded by half a quant step of the block's scale
    np.testing.assert_allclose(back[finite], x[finite],
                               atol=float(np.nanmax(np.abs(x)) / 127))
    assert (back[2, :] == 0).all()
