"""PostgreSQL wire protocol front, exercised with a raw v3 client.

Mirrors the reference's pgwire surface (`ydb/core/local_pgwire/`,
`ydb/apps/pgwire`): SSL negotiation downgrade, startup handshake,
simple-query result sets in text format, DML command tags, transaction
status tracking in ReadyForQuery, and error responses. The test client
speaks the documented v3 framing directly (no client library in the
image) — which also pins our framing bytes exactly.
"""

import socket
import struct

import pytest

from ydb_tpu.query import QueryEngine
from ydb_tpu.server.pgwire import serve_pg


class PgClient:
    """Minimal protocol-v3 client (simple query flow only)."""

    def __init__(self, port: int, ssl_probe: bool = False):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        self.f = self.sock.makefile("rb")
        if ssl_probe:
            self.sock.sendall(struct.pack("!II", 8, 80877103))
            assert self.f.read(1) == b"N"      # server: no TLS, plaintext
        params = b"user\0tester\0database\0ydb\0\0"
        body = struct.pack("!I", 196608) + params
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)
        self.params = {}
        self.ready = self._drain_until_ready()

    def _read_msg(self):
        tag = self.f.read(1)
        (length,) = struct.unpack("!I", self.f.read(4))
        return tag, self.f.read(length - 4)

    def _drain_until_ready(self):
        msgs = []
        while True:
            tag, payload = self._read_msg()
            if tag == b"Z":
                self.status = payload
                return msgs
            if tag == b"S":
                k, v = payload.split(b"\0")[:2]
                self.params[k.decode()] = v.decode()
            msgs.append((tag, payload))

    def query(self, sql: str):
        body = sql.encode() + b"\0"
        self.sock.sendall(b"Q" + struct.pack("!I", len(body) + 4) + body)
        msgs = self._drain_until_ready()
        cols, rows, tag, err = [], [], None, None
        for t, payload in msgs:
            if t == b"T":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                for _ in range(n):
                    end = payload.index(b"\0", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
            elif t == b"D":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                row = []
                for _ in range(n):
                    (ln,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(row)
            elif t == b"C":
                tag = payload.rstrip(b"\0").decode()
            elif t == b"E":
                err = payload
        if err is not None:
            fields = {chr(p[0]): p[1:].decode()
                      for p in err.split(b"\0") if p}
            raise RuntimeError(fields.get("M", "pg error"))
        return cols, rows, tag

    # -- extended protocol --------------------------------------------------

    def _send(self, tag: bytes, body: bytes):
        self.sock.sendall(tag + struct.pack("!I", len(body) + 4) + body)

    def prepare(self, name: str, sql: str, oids=()):
        body = name.encode() + b"\0" + sql.encode() + b"\0"
        body += struct.pack("!H", len(oids))
        for o in oids:
            body += struct.pack("!I", o)
        self._send(b"P", body)

    def bind(self, portal: str, name: str, params):
        body = portal.encode() + b"\0" + name.encode() + b"\0"
        body += struct.pack("!H", 1) + struct.pack("!H", 0)  # all text
        body += struct.pack("!H", len(params))
        for p in params:
            if p is None:
                body += struct.pack("!i", -1)
            else:
                enc = str(p).encode()
                body += struct.pack("!i", len(enc)) + enc
        body += struct.pack("!H", 0)
        self._send(b"B", body)

    def execute_portal(self, portal: str = ""):
        self._send(b"D", b"P" + portal.encode() + b"\0")
        self._send(b"E", portal.encode() + b"\0" + struct.pack("!i", 0))
        self._send(b"S", b"")
        msgs = self._drain_until_ready()
        cols, rows, tag, err = [], [], None, None
        for t, payload in msgs:
            if t == b"T":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                for _ in range(n):
                    end = payload.index(b"\0", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
            elif t == b"D":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                row = []
                for _ in range(n):
                    (ln,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(row)
            elif t == b"C":
                tag = payload.rstrip(b"\0").decode()
            elif t == b"E":
                err = payload
        if err is not None:
            fields = {chr(p[0]): p[1:].decode()
                      for p in err.split(b"\0") if p}
            raise RuntimeError(fields.get("M", "pg error"))
        return cols, rows, tag

    def close(self):
        self.sock.sendall(b"X" + struct.pack("!I", 4))
        self.sock.close()


@pytest.fixture(scope="module")
def pg():
    eng = QueryEngine(block_rows=1 << 10)
    eng.execute("create table t (id Int64 not null, name Utf8, v Double, "
                "ok Bool not null, d Date not null, primary key (id))")
    eng.execute("insert into t (id, name, v, ok, d) values "
                "(1, 'alpha', 1.5, true, date '2020-05-17'), "
                "(2, null, null, false, date '2021-01-02')")
    server = serve_pg(eng, port=0)
    yield server
    server.stop()


def test_handshake_and_select(pg):
    c = PgClient(pg.port, ssl_probe=True)
    assert c.params["server_encoding"] == "UTF8"
    cols, rows, tag = c.query("select id, name, v, ok, d from t order by id")
    assert cols == ["id", "name", "v", "ok", "d"]
    assert rows[0] == ["1", "alpha", "1.5", "t", "2020-05-17"]
    assert rows[1][1] is None and rows[1][2] is None
    assert rows[1][3] == "f" and rows[1][4] == "2021-01-02"
    assert tag == "SELECT 2"
    c.close()


def test_dml_tags_and_tx_status(pg):
    c = PgClient(pg.port)
    c.query("create table rw (k Int64 not null, v Double, "
            "primary key (k)) with (store = row)")
    _c, _r, tag = c.query("insert into rw (k, v) values (3, 3.0), (4, 4.0)")
    assert tag == "INSERT 0 2"
    assert c.status == b"I"
    c.query("begin")
    assert c.status == b"T"                 # in transaction
    _c, _r, tag = c.query("update rw set v = 9.0 where k = 3")
    assert tag == "UPDATE 1"
    c.query("commit")
    assert c.status == b"I"
    _c, rows, _t = c.query("select v from rw where k = 3")
    assert rows == [["9.0"]]
    _c, _r, tag = c.query("delete from rw where k = 4")
    assert tag == "DELETE 1"
    c.query("drop table rw")
    c.close()


def test_error_response_keeps_connection(pg):
    c = PgClient(pg.port)
    with pytest.raises(RuntimeError, match="unknown table"):
        c.query("select * from missing")
    # the connection survives an error
    cols, rows, _t = c.query("select count(*) as n from t")
    assert cols == ["n"] and len(rows) == 1
    c.close()


def test_aggregate_through_pg(pg):
    c = PgClient(pg.port)
    _cols, rows, _tag = c.query(
        "select ok, count(*) as n from t group by ok order by ok")
    assert [r[0] for r in rows] == ["f", "t"]
    c.close()


def test_aborted_transaction_semantics(pg):
    """After an error inside an explicit tx: status 'E', statements are
    rejected with 25P02, and COMMIT answers ROLLBACK (nothing persists)."""
    c = PgClient(pg.port)
    c.query("create table ab (k Int64 not null, v Int64, "
            "primary key (k)) with (store = row)")
    c.query("begin")
    c.query("insert into ab (k, v) values (1, 1)")
    with pytest.raises(RuntimeError):
        c.query("select * from missing")
    assert c.status == b"E"                  # aborted-transaction state
    with pytest.raises(RuntimeError, match="aborted"):
        c.query("insert into ab (k, v) values (2, 2)")
    _c, _r, tag = c.query("commit")
    assert tag == "ROLLBACK"                 # commit of an aborted tx
    assert c.status == b"I"
    _c, rows, _t = c.query("select count(*) as n from ab")
    assert rows == [["0"]]                   # nothing persisted
    c.query("drop table ab")
    c.close()


def test_ddl_command_tags(pg):
    c = PgClient(pg.port)
    _c, _r, tag = c.query("create table dt (k Int64 not null, "
                          "primary key (k))")
    assert tag == "CREATE TABLE"
    _c, _r, tag = c.query("alter table dt add column x Int64")
    assert tag == "ALTER TABLE"
    _c, _r, tag = c.query("drop table dt")
    assert tag == "DROP TABLE"
    c.close()


def test_extended_protocol_typed_params(pg):
    """Parse/Bind/Execute with $n placeholders and typed TEXT params —
    the extended-protocol flow psycopg-style clients drive."""
    c = PgClient(pg.port)
    c.prepare("s1", "select id, name from t where id = $1 and ok = $2",
              oids=(20, 16))
    c.bind("", "s1", [1, "t"])
    cols, rows, tag = c.execute_portal("")
    assert cols == ["id", "name"]
    assert rows == [["1", "alpha"]]
    # rebind the SAME prepared statement with different params
    c.bind("", "s1", [2, "f"])
    _c, rows, _t = c.execute_portal("")
    assert rows == [["2", None]]
    c.close()


def test_extended_protocol_null_string_date(pg):
    c = PgClient(pg.port)
    c.prepare("s2", "select count(*) as n from t where name = $1")
    c.bind("", "s2", ["alpha"])
    _c, rows, _t = c.execute_portal("")
    assert rows == [["1"]]
    c.prepare("s3", "select count(*) as n from t where d < $1",
              oids=(1082,))
    c.bind("", "s3", ["2021-01-01"])
    _c, rows, _t = c.execute_portal("")
    assert rows == [["1"]]
    c.close()


def test_extended_protocol_dml_and_injection(pg):
    c = PgClient(pg.port)
    c.query("create table ep (k Int64 not null, s Utf8, "
            "primary key (k))")
    c.prepare("ins", "insert into ep (k, s) values ($1, $2)")
    c.bind("", "ins", [7, "it''s; drop table ep"])
    _c, _r, tag = c.execute_portal("")
    assert tag == "INSERT 0 1"
    # NULL parameter lands as SQL NULL
    c.bind("", "ins", [8, None])
    _c, _r, tag = c.execute_portal("")
    assert tag == "INSERT 0 1"
    _c, rows, _t = c.query("select count(*) as n from ep where s is null")
    assert rows == [["1"]]
    c.query("delete from ep where k = 8")
    _c, rows, _t = c.query("select s from ep where k = 7")
    assert rows == [["it''s; drop table ep"]]
    # malformed numeric param for an int oid refuses instead of splicing
    c.prepare("bad", "select * from ep where k = $1", oids=(20,))
    c.bind("", "bad", ["1; drop table ep"])
    with pytest.raises(RuntimeError):
        c.execute_portal("")
    _c, rows, _t = c.query("select count(*) as n from ep")
    assert rows == [["1"]]
    c.query("drop table ep")
    c.close()


def test_grpc_token_auth():
    pytest.importorskip("grpc")
    from ydb_tpu.server import Client, serve
    eng = QueryEngine(block_rows=1 << 10)
    eng.execute("create table au (k Int64 not null, primary key (k))")
    server, port = serve(eng, port=0, token="sekrit")
    try:
        bad = Client(f"127.0.0.1:{port}")
        with pytest.raises(RuntimeError, match="Unauthenticated"):
            bad.execute("select 1 as x")
        good = Client(f"127.0.0.1:{port}", token="sekrit")
        assert good.execute("select 1 as x")["rows"] == [[1]]
        assert good.ping()          # probes stay open
    finally:
        server.stop(0)


def test_describe_owns_row_description(pg):
    """ADVICE r4: per the v3 spec the RowDescription must ride the
    Describe reply (JDBC/psycopg decode result sets off it) and Execute
    must emit only DataRow/CommandComplete."""
    c = PgClient(pg.port)
    c.prepare("dsc", "select id, name from t order by id")
    c.bind("", "dsc", [])
    # one extended round: Describe(portal) + Execute + Sync. Exactly ONE
    # RowDescription (Describe's); Execute contributes DataRows + tag only.
    c._send(b"D", b"P\0")
    c._send(b"E", b"\0" + struct.pack("!i", 0))
    c._send(b"S", b"")
    msgs = c._drain_until_ready()
    tags = [t for t, _p in msgs]
    assert b"E" not in tags
    assert tags.count(b"T") == 1 and tags.count(b"D") == 2
    # the T precedes every DataRow (describe-then-execute ordering)
    assert tags.index(b"T") < tags.index(b"D")
    assert any(t == b"C" for t in tags)
    c.close()


def test_oid0_param_stays_string(pg):
    """ADVICE r4: an unspecified-type (oid 0) digit-string parameter
    compared against a STRING column must compare as the string, while
    the same shape against an int column coerces to the number."""
    c = PgClient(pg.port)
    c.query("create table p0 (k Int64 not null, s Utf8, primary key (k))")
    c.query("insert into p0 (k, s) values (123, '123'), (7, 'x')")
    c.prepare("bys", "select k from p0 where s = $1")     # no oids
    c.bind("", "bys", ["123"])
    _c, rows, _t = c.execute_portal("")
    assert rows == [["123"]]
    c.prepare("byk", "select s from p0 where k = $1")     # no oids
    c.bind("", "byk", ["7"])
    _c, rows, _t = c.execute_portal("")
    assert rows == [["x"]]
    c.query("drop table p0")
    c.close()


def test_matview_over_the_wire(pg):
    """Materialized-view DDL routes like any other DDL (command tags)
    and a view read serves rows through the simple-query flow."""
    c = PgClient(pg.port)
    c.query("create table mvsrc (k Int64 not null, v Int64, "
            "primary key (k)) with (store = row)")
    _c, _r, tag = c.query("create materialized view wv as "
                          "select count(*) as n, sum(v) as s from mvsrc")
    assert tag == "CREATE MATERIALIZED VIEW"
    c.query("insert into mvsrc (k, v) values (1, 10), (2, 32)")
    cols, rows, _tag = c.query("select * from wv")
    assert cols == ["n", "s"] and rows == [["2", "42"]]
    _c, _r, tag = c.query("drop materialized view wv")
    assert tag == "DROP MATERIALIZED VIEW"
    _c, _r, tag = c.query("drop table mvsrc")
    assert tag == "DROP TABLE"
    c.close()
