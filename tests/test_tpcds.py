"""TPC-DS query subset end-to-end vs pandas oracle (BASELINE config #4)."""

import pytest

from ydb_tpu.bench.tpcds_gen import load_tpcds
from ydb_tpu.query import QueryEngine

from tests.tpcds_util import QUERIES, oracle
from tests.tpch_util import assert_frames_match


@pytest.fixture(scope="module")
def eng():
    e = QueryEngine(block_rows=1 << 13)
    e.raw = load_tpcds(e.catalog, sf=0.01, shards=2,
                       portion_rows=1 << 12)
    return e


@pytest.mark.parametrize("name", list(QUERIES))
def test_tpcds_query(eng, name):
    got = eng.query(QUERIES[name])
    want = oracle(name, eng.raw)
    want.columns = list(got.columns)
    assert_frames_match(got, want, ordered=True, rtol=1e-9)
