"""GraceJoin: hash-partitioned builds with a host-DRAM spill budget.

The analog of `mkql_grace_join_ut.cpp`: build sides above the device
budget partition by key hash; every partition joins independently and the
union must equal the broadcast result — for unique and duplicate keys,
inner/left/semi/anti kinds, through real SQL.
"""

import numpy as np
import pandas as pd
import pytest

from ydb_tpu.query import QueryEngine


@pytest.fixture
def eng():
    e = QueryEngine(block_rows=1 << 12)
    # force the Grace path: any build over ~2KB partitions
    e.executor.grace_budget_bytes = 2048
    e.execute("""create table f (fid Int64 not null, k Int64 not null,
                 x Double not null, primary key (fid))""")
    e.execute("""create table d (did Int64 not null, k Int64 not null,
                 w Double not null, primary key (did))""")
    rng = np.random.default_rng(11)
    n_f, n_d = 3000, 900
    f = pd.DataFrame({"fid": np.arange(n_f),
                      "k": rng.integers(0, 400, n_f),
                      "x": rng.random(n_f).round(3)})
    # duplicate build keys: ~2.25 rows per key
    d = pd.DataFrame({"did": np.arange(n_d),
                      "k": rng.integers(0, 400, n_d),
                      "w": rng.random(n_d).round(3)})
    e.catalog.table("f").bulk_upsert(f, e._next_version())
    e.catalog.table("d").bulk_upsert(d, e._next_version())
    e.f, e.d = f, d
    return e


def _is_partitioned(e, sql):
    from ydb_tpu.ops.join import PartitionedBuild
    from ydb_tpu.sql import parse
    plan = e.planner.plan_select(parse(sql))
    steps = [s for k, s in plan.pipeline.steps if k == "join"]
    builds = [e.executor._prepare_join(s, dict(plan.params), e.snapshot())
              for s in steps]
    return any(isinstance(b, PartitionedBuild) for b in builds)


def test_inner_join_duplicate_keys_partitioned(eng):
    sql = ("select sum(f.x * d.w) as s, count(*) as n "
           "from f join d on f.k = d.k")
    assert _is_partitioned(eng, sql)
    got = eng.query(sql)
    m = eng.f.merge(eng.d, on="k")
    assert got.n[0] == len(m)
    np.testing.assert_allclose(got.s[0], (m.x * m.w).sum(), rtol=1e-9)


def test_group_by_after_partitioned_join(eng):
    sql = ("select f.k as k, count(*) as n, sum(d.w) as s from f "
           "join d on f.k = d.k group by f.k order by k")
    got = eng.query(sql)
    m = eng.f.merge(eng.d, on="k")
    want = m.groupby("k", as_index=False).agg(n=("w", "size"),
                                              s=("w", "sum"))
    np.testing.assert_array_equal(got.k, want.k)
    np.testing.assert_array_equal(got.n, want.n)
    np.testing.assert_allclose(got.s, want.s, rtol=1e-9)


def test_semi_and_anti_partitioned(eng):
    got = eng.query("select count(*) as n from f where f.k in "
                    "(select d.k from d)")
    keys = set(eng.d.k)
    assert got.n[0] == int(eng.f.k.isin(keys).sum())
    got = eng.query("select count(*) as n from f where not exists "
                    "(select 1 from d where d.k = f.k)")
    assert got.n[0] == int((~eng.f.k.isin(keys)).sum())


def test_partitioned_matches_broadcast(eng):
    sql = ("select f.k as k, sum(f.x) as sx, sum(d.w) as sw from f "
           "join d on f.k = d.k group by f.k order by k")
    got_grace = eng.query(sql)
    eng.executor.grace_budget_bytes = 1 << 29   # broadcast path
    eng._plan_cache.clear()
    got_bcast = eng.query(sql)
    pd.testing.assert_frame_equal(got_grace, got_bcast)
