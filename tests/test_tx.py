"""Interactive transactions: isolation, atomicity, optimistic locks,
randomized serializability.

The analog of the reference's KQP tx suites + the in-house serializability
checker (`ydb/core/kqp/ut/tx`, `ydb/tests/tools/ydb_serializable/`):
concurrent sessions interleave BEGIN/SELECT/UPSERT/COMMIT on shared
tables; committed history must equal some serial order.
"""

import numpy as np
import pytest

from ydb_tpu.query import QueryEngine, QueryError
from ydb_tpu.tx import TxAborted


@pytest.fixture
def eng():
    e = QueryEngine(block_rows=1 << 13)
    e.execute("""create table acct (id Int64 not null, bal Int64 not null,
                 primary key (id)) with (store = row)""")
    e.execute("insert into acct (id, bal) values (1, 100), (2, 100), (3, 100)")
    return e


def test_tx_atomic_commit(eng):
    s = eng.session()
    s.execute("begin")
    s.execute("update acct set bal = bal - 30 where id = 1")
    s.execute("update acct set bal = bal + 30 where id = 2")
    # other sessions see nothing until commit
    other = eng.query("select sum(bal) as t, min(bal) as lo from acct")
    assert other.t[0] == 300 and other.lo[0] == 100
    s.execute("commit")
    df = eng.query("select id, bal from acct order by id")
    assert list(df.bal) == [70, 130, 100]


def test_tx_rollback_discards(eng):
    s = eng.session()
    s.execute("begin")
    s.execute("update acct set bal = 0 where id = 1")
    s.execute("delete from acct where id = 2")
    assert list(s.query("select bal from acct order by id").bal) == [0, 100]
    s.execute("rollback")
    df = eng.query("select id, bal from acct order by id")
    assert list(df.id) == [1, 2, 3] and list(df.bal) == [100] * 3


def test_tx_reads_own_writes_and_snapshot(eng):
    s = eng.session()
    s.execute("begin")
    s.execute("upsert into acct (id, bal) values (4, 50)")
    assert s.query("select count(*) as n from acct").n[0] == 4
    # a commit by another session AFTER our BEGIN is invisible to us
    eng.execute("upsert into acct (id, bal) values (5, 77)")
    assert s.query("select count(*) as n from acct").n[0] == 4
    assert eng.query("select count(*) as n from acct").n[0] == 4  # 3 + id5
    s.execute("rollback")
    assert eng.query("select count(*) as n from acct").n[0] == 4


def test_tx_optimistic_lock_conflict(eng):
    s1, s2 = eng.session(), eng.session()
    s1.execute("begin")
    # s1 reads acct → lock
    assert s1.query("select bal from acct where id = 1").bal[0] == 100
    # s2 commits a write to acct behind s1's back
    s2.execute("update acct set bal = 999 where id = 3")
    s1.execute("update acct set bal = bal - 10 where id = 1")
    with pytest.raises(QueryError, match="optimistic lock"):
        s1.execute("commit")
    # aborted tx left nothing behind
    df = eng.query("select id, bal from acct order by id")
    assert list(df.bal) == [100, 100, 999]


def test_tx_no_conflict_on_unrelated_table(eng):
    eng.execute("""create table other (id Int64 not null, primary key (id))
                 with (store = row)""")
    s1 = eng.session()
    s1.execute("begin")
    s1.execute("update acct set bal = 1 where id = 1")
    eng.execute("insert into other (id) values (1)")   # unrelated commit
    s1.execute("commit")                               # must succeed
    assert eng.query("select bal from acct where id = 1").bal[0] == 1


def test_tx_column_table_insert(eng):
    eng.execute("create table log (id Int64 not null, primary key (id))")
    s = eng.session()
    s.execute("begin")
    s.execute("insert into log (id) values (1), (2)")
    assert s.query("select count(*) as n from log").n[0] == 2
    assert eng.query("select count(*) as n from log").n[0] == 0
    s.execute("commit")
    assert eng.query("select count(*) as n from log").n[0] == 2


def test_tx_column_table_rollback(eng):
    eng.execute("create table log (id Int64 not null, primary key (id))")
    s = eng.session()
    s.execute("begin")
    s.execute("insert into log (id) values (1)")
    s.execute("rollback")
    assert eng.query("select count(*) as n from log").n[0] == 0


def test_tx_ddl_rejected(eng):
    s = eng.session()
    s.execute("begin")
    with pytest.raises(QueryError, match="DDL"):
        s.execute("create table x (id Int64 not null, primary key (id))")
    s.execute("rollback")


def test_tx_durability(tmp_path):
    ddir = str(tmp_path / "d")
    e = QueryEngine(block_rows=1 << 13, data_dir=ddir)
    e.execute("""create table acct (id Int64 not null, bal Int64 not null,
                 primary key (id)) with (store = row)""")
    e.execute("insert into acct (id, bal) values (1, 100), (2, 100)")
    s = e.session()
    s.execute("begin")
    s.execute("update acct set bal = bal - 40 where id = 1")
    s.execute("update acct set bal = bal + 40 where id = 2")
    s.execute("commit")
    s2 = e.session()
    s2.execute("begin")
    s2.execute("update acct set bal = 0 where id = 1")
    s2.execute("rollback")
    e2 = QueryEngine(block_rows=1 << 13, data_dir=ddir)
    df = e2.query("select id, bal from acct order by id")
    assert list(df.bal) == [60, 140]


def test_randomized_serializability(eng):
    """Jepsen-style check (ydb_serializable analog): random interleaved
    transfer transactions; committed ones must form a serializable
    history. With table-granular optimistic locks every pair of committed
    txs conflicts, so the commit order IS the serial order — replaying
    committed transfers serially must reproduce the final state, and the
    total must be invariant throughout."""
    rng = np.random.default_rng(7)
    committed = []
    sessions = []
    for _ in range(60):
        if rng.random() < 0.4:
            # a fully sequential tx (no interleaving → always commits)
            s = eng.session()
            src, dst = rng.choice([1, 2, 3], 2, replace=False)
            amt = int(rng.integers(1, 20))
            s.execute("begin")
            s.execute(f"update acct set bal = bal - {amt} where id = {src}")
            s.execute(f"update acct set bal = bal + {amt} where id = {dst}")
            s.execute("commit")
            committed.append([(int(src), int(dst), amt)])
        elif sessions and rng.random() < 0.6:
            s, plan = sessions.pop(rng.integers(len(sessions)))
            try:
                for (src, dst, amt) in plan:
                    s.execute(f"update acct set bal = bal - {amt} "
                              f"where id = {src}")
                    s.execute(f"update acct set bal = bal + {amt} "
                              f"where id = {dst}")
                if rng.random() < 0.8:
                    s.execute("commit")
                    committed.append(plan)
                else:
                    s.execute("rollback")
            except QueryError:
                pass                        # optimistic abort
        else:
            s = eng.session()
            s.execute("begin")
            src, dst = rng.choice([1, 2, 3], 2, replace=False)
            amt = int(rng.integers(1, 20))
            sessions.append((s, [(int(src), int(dst), amt)]))
        # invariant: committed total never changes
        assert eng.query("select sum(bal) as t from acct").t[0] == 300
    for s, _plan in sessions:
        try:
            s.execute("rollback")
        except QueryError:
            pass
    # serial replay of the committed transfers reproduces the final state
    bal = {1: 100, 2: 100, 3: 100}
    for plan in committed:
        for (src, dst, amt) in plan:
            bal[src] -= amt
            bal[dst] += amt
    df = eng.query("select id, bal from acct order by id")
    assert list(df.bal) == [bal[1], bal[2], bal[3]]
    assert len(committed) > 5, "too few commits to be meaningful"


def test_atomic_insert_batch_failure(eng):
    """Regression (r3 review): a failing multi-row INSERT must leave
    nothing behind — in autocommit AND inside a transaction."""
    with pytest.raises(QueryError, match="duplicate"):
        eng.execute("insert into acct (id, bal) values (9, 1), (1, 2)")
    assert eng.query("select count(*) as n from acct").n[0] == 3
    s = eng.session()
    s.execute("begin")
    with pytest.raises(QueryError, match="duplicate"):
        s.execute("insert into acct (id, bal) values (8, 1), (8, 2)")
    s.execute("commit")
    assert eng.query("select count(*) as n from acct").n[0] == 3


def test_tx_staged_column_write_invalidates_plan_cache(eng):
    """Regression (r3 review): a tx-staged column INSERT grows shared
    dictionaries — the tx's own reads must not reuse a stale cached plan."""
    eng.execute("""create table c (id Int64 not null, s Utf8 not null,
                 primary key (id))""")
    eng.execute("insert into c (id, s) values (1, 'alpha'), (2, 'beta')")
    q = "select s, count(*) as n from c group by s order by s"
    assert list(eng.query(q).s) == ["alpha", "beta"]   # plan now cached
    s = eng.session()
    s.execute("begin")
    s.execute("insert into c (id, s) values (3, 'zeta')")
    df = s.query(q)
    assert list(df.s) == ["alpha", "beta", "zeta"]
    assert list(df.n) == [1, 1, 1]
    s.execute("rollback")
    assert list(eng.query(q).s) == ["alpha", "beta"]


def test_crashed_open_tx_writes_discarded_at_boot(tmp_path):
    """Regression (r3 review): column writes staged by a tx that never
    committed must be dropped at recovery, not resurrected as zombies."""
    ddir = str(tmp_path / "d")
    e = QueryEngine(block_rows=1 << 13, data_dir=ddir)
    e.execute("create table c (id Int64 not null, primary key (id))")
    e.execute("insert into c (id) values (1)")
    s = e.session()
    s.execute("begin")
    s.execute("insert into c (id) values (2)")
    # process "dies" here with the tx open (no commit/rollback)
    e2 = QueryEngine(block_rows=1 << 13, data_dir=ddir)
    assert e2.query("select count(*) as n from c").n[0] == 1
    t = e2.catalog.table("c")
    assert all(en.committed_version is not None
               for sh in t.shards for en in sh.inserts)
    # but a COMMITTED tx's writes must survive the same crash
    s2 = e2.session()
    s2.execute("begin")
    s2.execute("insert into c (id) values (3)")
    s2.execute("commit")
    e3 = QueryEngine(block_rows=1 << 13, data_dir=ddir)
    assert sorted(e3.query("select id from c").id) == [1, 3]


def test_plan_step_covers_wal_when_state_json_lags(tmp_path):
    """Regression (r3 review): recovery derives the plan-step watermark
    from replayed versions, not just state.json (which can lag a crash
    between wal_commit and save_state)."""
    import json, os
    ddir = str(tmp_path / "d")
    e = QueryEngine(block_rows=1 << 13, data_dir=ddir)
    e.execute("create table c (id Int64 not null, primary key (id))")
    e.execute("insert into c (id) values (1)")
    step = e._plan_step
    # simulate the crash window: state.json rolled back behind the WAL
    with open(os.path.join(ddir, "state.json"), "w") as f:
        json.dump({"last_plan_step": 1}, f)
    e2 = QueryEngine(block_rows=1 << 13, data_dir=ddir)
    assert e2._plan_step >= step
    assert e2.query("select count(*) as n from c").n[0] == 1


def test_insert_select_column_subset(eng):
    """Regression (r3 review): INSERT..SELECT with a column subset
    null-fills nullable columns instead of raising KeyError."""
    eng.execute("""create table src (k Int64 not null, primary key (k))""")
    eng.execute("insert into src (k) values (1), (2)")
    eng.execute("""create table dst (k Int64 not null, v Double,
                 primary key (k))""")
    eng.execute("insert into dst (k) select k from src")
    df = eng.query("select k, v from dst order by k")
    assert list(df.k) == [1, 2]
    assert df.v.isna().all()


def test_compaction_respects_pinned_snapshots(eng):
    """Regression (r3 review): background compaction re-stamps merged
    portions at a newer version — it must skip portions an open tx's
    pinned snapshot still needs, or the tx sees committed rows vanish."""
    eng.execute("""create table cc (id Int64 not null, primary key (id))
                 with (partitions = 1)""")
    for i in range(5):
        eng.execute(f"insert into cc (id) values ({i})")
    s = eng.session()
    s.execute("begin")
    assert s.query("select count(*) as n from cc").n[0] == 5
    # push the small-portion count past the compaction threshold while
    # the tx snapshot is pinned
    for i in range(5, 16):
        eng.execute(f"insert into cc (id) values ({i})")
    # the pinned snapshot must still see its 5 rows
    assert s.query("select count(*) as n from cc").n[0] == 5
    s.execute("rollback")   # (commit would abort: foreign writes landed)
    assert eng.query("select count(*) as n from cc").n[0] == 16
    # with the tx gone, compaction proceeds on the next indexation
    eng.execute("insert into cc (id) values (99)")
    assert len(eng.catalog.table("cc").shards[0].portions) < 17


def test_read_watermark_trails_apply(eng):
    """Regression (ADVICE r4, high): propose() must not advance the READ
    watermark before the commit finishes applying — a lock-free reader
    snapshotting mid-commit would see a torn multi-shard apply."""
    coord = eng.coordinator
    before = coord.read_snapshot().plan_step
    v = coord.propose(0)
    # mid-apply: the granted step is NOT readable yet
    assert coord.read_snapshot().plan_step == before
    assert coord.safe_watermark() <= before
    coord.publish(v.plan_step)
    assert coord.read_snapshot().plan_step == v.plan_step


def test_read_watermark_interleaved_publishes(eng):
    """Two in-flight commits: the watermark advances only past the
    contiguous published prefix (publishing the later step first must not
    expose the earlier, still-applying one)."""
    coord = eng.coordinator
    base = coord.read_snapshot().plan_step
    v1 = coord.propose(0)
    v2 = coord.propose(0)
    coord.publish(v2.plan_step)          # later step applies first
    assert coord.read_snapshot().plan_step == base   # v1 still applying
    coord.publish(v1.plan_step)
    assert coord.read_snapshot().plan_step == v2.plan_step


def test_blind_upserts_disjoint_keys_commit(eng):
    """pk-granular write locks (r5): two txs that only WRITE disjoint
    keys of a row table must BOTH commit — the r4 table-granular lock
    aborted the second spuriously."""
    eng.execute("create table wkv (id Int64 not null, v Int64 not null, "
                "primary key (id)) with (store = row)")
    s1, s2 = eng.session(), eng.session()
    s1.execute("begin")
    s2.execute("begin")
    s1.execute("upsert into wkv (id, v) values (1, 10), (2, 20)")
    s2.execute("upsert into wkv (id, v) values (3, 30), (4, 40)")
    s1.execute("commit")
    s2.execute("commit")                 # disjoint keys: no conflict
    df = eng.query("select count(*) as n, sum(v) as s from wkv")
    assert int(df.n[0]) == 4 and int(df.s[0]) == 100


def test_blind_upserts_same_key_conflict(eng):
    """...but the SAME key still conflicts (write-write, exactly one
    winner), and a reader tx still aborts on any foreign write."""
    eng.execute("create table wk2 (id Int64 not null, v Int64 not null, "
                "primary key (id)) with (store = row)")
    eng.execute("insert into wk2 (id, v) values (7, 0)")
    s1, s2 = eng.session(), eng.session()
    s1.execute("begin")
    s2.execute("begin")
    s1.execute("upsert into wk2 (id, v) values (7, 1)")
    s2.execute("upsert into wk2 (id, v) values (7, 2)")
    s1.execute("commit")
    with pytest.raises(QueryError, match="conflict|optimistic"):
        s2.execute("commit")
    assert int(eng.query("select v from wk2 where id = 7").v[0]) == 1
    # read+write tx stays table-granular: foreign write → abort
    s3 = eng.session()
    s3.execute("begin")
    s3.query("select count(*) as n from wk2")
    eng.execute("upsert into wk2 (id, v) values (99, 9)")
    s3.execute("upsert into wk2 (id, v) values (50, 5)")
    with pytest.raises(QueryError, match="optimistic"):
        s3.execute("commit")


def test_insert_select_self_reference_stays_table_granular(eng):
    """Review r5: INSERT ... SELECT reads its source — a tx doing the
    self-referencing form must still abort on a foreign write."""
    eng.execute("create table isr (id Int64 not null, v Int64 not null, "
                "primary key (id)) with (store = row)")
    eng.execute("insert into isr (id, v) values (1, 10), (2, 20)")
    s = eng.session()
    s.execute("begin")
    s.execute("insert into isr (id, v) select id + 100, v from isr")
    eng.execute("upsert into isr (id, v) values (2, 999)")   # foreign
    with pytest.raises(QueryError, match="optimistic"):
        s.execute("commit")
