"""Tests for broadcast join (searchsorted MapJoin) and device sort/top-k."""

import numpy as np
import pandas as pd

from ydb_tpu.core import dtypes as dt
from ydb_tpu.core.block import HostBlock
from ydb_tpu.core.schema import Column, Schema
from ydb_tpu.ops import join as mj
from ydb_tpu.ops.device import to_device, to_host
from ydb_tpu.ops.sort import sort_block
from ydb_tpu.ops.xla_exec import compress_block


def _dim_block(n=100):
    return HostBlock.from_pandas(pd.DataFrame({
        "pk": np.arange(n, dtype=np.int64) * 10,
        "name": [f"item{i}" for i in range(n)],
        "price": np.arange(n, dtype=np.float64) * 1.5,
    }))


def _fact_block(rng, n=5000, dim_n=100):
    keys = rng.integers(0, dim_n * 2, n) * 10  # half miss
    return HostBlock.from_pandas(pd.DataFrame({
        "fk": keys.astype(np.int64),
        "qty": rng.integers(1, 10, n).astype(np.int64),
    }))


def test_inner_join_matches_pandas(rng):
    dim, fact = _dim_block(), _fact_block(rng)
    table = mj.build(dim, "pk", ["name", "price"])
    assert table.unique
    out, sel = mj.probe(to_device(fact), table, "fk", kind="inner")
    res = to_host(compress_block(out, sel)).to_pandas()

    expect = fact.to_pandas().merge(
        dim.to_pandas(), left_on="fk", right_on="pk")[["fk", "qty", "name", "price"]]
    res_s = res.sort_values(["fk", "qty"]).reset_index(drop=True)
    exp_s = expect.sort_values(["fk", "qty"]).reset_index(drop=True)
    assert len(res_s) == len(exp_s)
    np.testing.assert_array_equal(res_s["fk"].to_numpy(), exp_s["fk"].to_numpy())
    np.testing.assert_allclose(
        res_s["price"].to_numpy(np.float64), exp_s["price"].to_numpy(np.float64))
    assert (res_s["name"] == exp_s["name"]).all()


def test_left_join_nulls(rng):
    dim, fact = _dim_block(), _fact_block(rng)
    table = mj.build(dim, "pk", ["price"])
    out, sel = mj.probe(to_device(fact), table, "fk", kind="left")
    res = to_host(compress_block(out, sel)).to_pandas()
    assert len(res) == fact.length
    missing = res["price"].isna()
    assert missing.any()
    assert (res.loc[missing, "fk"].to_numpy() >= 1000).all()


def test_semi_anti_join(rng):
    dim, fact = _dim_block(), _fact_block(rng)
    table = mj.build(dim, "pk", [])
    dfact = to_device(fact)
    _, sel_semi = mj.probe(dfact, table, "fk", kind="left_semi")
    _, sel_anti = mj.probe(dfact, table, "fk", kind="left_anti")
    n_semi = to_host(compress_block(dfact, sel_semi)).length
    n_anti = to_host(compress_block(dfact, sel_anti)).length
    assert n_semi + n_anti == fact.length
    assert n_semi == int((fact.columns["fk"].data < 1000).sum())


def test_sort_topk(rng):
    n = 3000
    b = HostBlock.from_pandas(pd.DataFrame({
        "x": rng.integers(0, 1000, n).astype(np.int64),
        "y": rng.normal(size=n),
    }))
    d = sort_block(to_device(b), [("x", False, False), ("y", True, False)], limit=50)
    res = to_host(d).to_pandas()
    exp = b.to_pandas().sort_values(["x", "y"], ascending=[False, True]).head(50)
    np.testing.assert_array_equal(res["x"].to_numpy(), exp["x"].to_numpy())
    np.testing.assert_allclose(res["y"].to_numpy(np.float64),
                               exp["y"].to_numpy(np.float64))


def test_sort_nulls_last(rng):
    b = HostBlock.from_pandas(pd.DataFrame({
        "x": [3.0, None, 1.0, 2.0, None],
    }))
    d = sort_block(to_device(b), [("x", True, False)])
    res = to_host(d).to_pandas()
    vals = res["x"].tolist()
    assert vals[:3] == [1.0, 2.0, 3.0]
    assert pd.isna(vals[3]) and pd.isna(vals[4])


def _dup_build_block(rng, n_keys=40, avg_dup=3):
    ks, names, prices = [], [], []
    i = 0
    for k in range(n_keys):
        for _ in range(int(rng.integers(0, avg_dup * 2 + 1))):  # 0..6 dups
            ks.append(k * 10)
            names.append(f"v{i}")
            prices.append(float(i) * 0.5)
            i += 1
    return HostBlock.from_pandas(pd.DataFrame({
        "pk": np.array(ks, dtype=np.int64),
        "name": names,
        "price": np.array(prices, dtype=np.float64),
    }))


def test_expand_inner_join_duplicates(rng):
    dim = _dup_build_block(rng)
    fact = _fact_block(rng, n=3000, dim_n=60)
    table = mj.build(dim, "pk", ["name", "price"])
    assert not table.unique
    out = mj.probe_expand(to_device(fact), table, "fk", kind="inner")
    res = to_host(out).to_pandas()
    expect = fact.to_pandas().merge(
        dim.to_pandas(), left_on="fk", right_on="pk")[
        ["fk", "qty", "name", "price"]]
    res_s = res.sort_values(["fk", "qty", "name"]).reset_index(drop=True)
    exp_s = expect.sort_values(["fk", "qty", "name"]).reset_index(drop=True)
    assert len(res_s) == len(exp_s)
    np.testing.assert_array_equal(res_s["fk"].to_numpy(),
                                  exp_s["fk"].to_numpy())
    np.testing.assert_allclose(res_s["price"].to_numpy(np.float64),
                               exp_s["price"].to_numpy(np.float64))
    assert (res_s["name"] == exp_s["name"]).all()


def test_expand_left_join_duplicates(rng):
    dim = _dup_build_block(rng)
    fact = _fact_block(rng, n=2000, dim_n=60)
    table = mj.build(dim, "pk", ["price"])
    out = mj.probe_expand(to_device(fact), table, "fk", kind="left")
    res = to_host(out).to_pandas()
    expect = fact.to_pandas().merge(
        dim.to_pandas()[["pk", "price"]], left_on="fk", right_on="pk",
        how="left")[["fk", "qty", "price"]]
    assert len(res) == len(expect)
    res_s = res.sort_values(["fk", "qty", "price"]).reset_index(drop=True)
    exp_s = expect.sort_values(["fk", "qty", "price"]).reset_index(drop=True)
    np.testing.assert_array_equal(res_s["fk"].to_numpy(),
                                  exp_s["fk"].to_numpy())
    got_p = res_s["price"].to_numpy(np.float64)
    want_p = exp_s["price"].to_numpy(np.float64)
    both_nan = np.isnan(got_p) & np.isnan(want_p)
    np.testing.assert_allclose(got_p[~both_nan], want_p[~both_nan])


def test_expand_join_null_probe_keys(rng):
    # NULL probe keys never match: dropped by inner, null-extended by left
    schema = Schema([Column("fk", dt.DType(dt.Kind.INT64, True)),
                     Column("qty", dt.DType(dt.Kind.INT64, False))])
    fk = np.array([0, 10, 10, 99], dtype=np.int64)
    valid = np.array([True, True, False, True])
    fact = HostBlock.from_arrays(
        schema, {"fk": fk, "qty": np.arange(4, dtype=np.int64)},
        valids={"fk": valid})
    dim = HostBlock.from_pandas(pd.DataFrame({
        "pk": np.array([10, 10], dtype=np.int64),
        "price": np.array([1.0, 2.0])}))
    table = mj.build(dim, "pk", ["price"])
    inner = to_host(mj.probe_expand(to_device(fact), table, "fk", "inner"))
    assert inner.length == 2 and list(inner.to_pandas().qty) == [1, 1]
    left = to_host(mj.probe_expand(to_device(fact), table, "fk", "left"))
    df = left.to_pandas().sort_values(["qty", "price"]).reset_index(drop=True)
    assert len(df) == 5  # rows 0,2,3 null-extended + two matches for row 1
