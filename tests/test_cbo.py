"""Cost-based join ordering v1: statistics + selectivity estimates.

VERDICT r3 item 5: join order was a PK-edge spanning tree ranked by RAW
table size. Now `query/stats.py` estimates post-predicate cardinality
(NDV from dictionaries/spans, range selectivity from portion min/max) and
the planner ranks fact choice and build attachment by it — EXPLAIN shows
the estimates.
"""

import numpy as np
import pandas as pd
import pytest

from ydb_tpu.query import QueryEngine
from ydb_tpu.query import stats as S


@pytest.fixture(scope="module")
def eng():
    e = QueryEngine(block_rows=1 << 12)
    # big: 60k rows, joins small on k; small: 3k rows
    e.execute("create table big (id Int64 not null, k Int64 not null, "
              "d Int32 not null, v Double not null, primary key (id))")
    e.execute("create table small (k2 Int64 not null, w Double not null, "
              "primary key (k2))")
    n = 60_000
    ids = np.arange(n)
    rows = ",".join(f"({i},{i % 3000},{i % 365},{i * 0.5})"
                    for i in ids)
    for lo in range(0, n, 15_000):
        chunk = ",".join(f"({i},{i % 3000},{i % 365},{i * 0.5})"
                         for i in ids[lo:lo + 15_000])
        e.execute(f"insert into big (id, k, d, v) values {chunk}")
    e.execute("insert into small (k2, w) values "
              + ",".join(f"({k},{k * 2.0})" for k in range(3000)))
    e.big = pd.DataFrame({"id": ids, "k": ids % 3000, "d": ids % 365,
                          "v": ids * 0.5})
    e.small = pd.DataFrame({"k2": np.arange(3000),
                            "w": np.arange(3000) * 2.0})
    return e


def test_stats_primitives(eng):
    t = eng.catalog.table("big")
    assert S.table_rows(t) == 60_000
    lo, hi = S.column_minmax(t, "d")
    assert (lo, hi) == (0, 364)
    # pk NDV = rows; int NDV bounded by span
    assert S.column_ndv(t, "id") == 60_000
    assert S.column_ndv(t, "d") == 365


def test_selectivity_shapes(eng):
    from ydb_tpu.sql import parse
    t = eng.catalog.table("big")

    def sel(pred_sql):
        stmt = parse(f"select id from big where {pred_sql}")
        return S.predicate_selectivity(stmt.where, "big", t)

    assert sel("d = 7") == pytest.approx(1 / 365)
    assert sel("d < 36") == pytest.approx(36 / 364, rel=0.1)
    assert sel("d between 10 and 45") == pytest.approx(35 / 364, rel=0.2)
    assert sel("d in (1, 2, 3)") == pytest.approx(3 / 365)


def test_filtered_big_becomes_build_side(eng):
    """A hard equality on the big table's pk collapses its estimate to ~1
    row — the small table must drive the scan, the filtered big table
    becomes the broadcast build despite 20x raw size."""
    plan_txt = eng.explain(
        "select count(*) as c from big, small "
        "where big.k = small.k2 and big.id = 17")
    first_scan = [ln for ln in plan_txt.splitlines() if "Scan" in ln][0]
    assert "Scan small" in first_scan, plan_txt
    assert "est_rows=1" in plan_txt
    # and the answer is right either way
    got = eng.query("select count(*) as c from big, small "
                    "where big.k = small.k2 and big.id = 17")
    assert got.c[0] == 1


def test_unfiltered_big_drives(eng):
    plan_txt = eng.explain(
        "select small.k2, sum(big.v) as s from big, small "
        "where big.k = small.k2 group by small.k2")
    first_scan = [ln for ln in plan_txt.splitlines() if "Scan" in ln][0]
    assert "Scan big" in first_scan, plan_txt
    got = eng.query("select sum(v) as s from big, small "
                    "where big.k = small.k2")
    np.testing.assert_allclose(got.s[0], eng.big.v.sum(), rtol=1e-9)


def test_explain_shows_estimates(eng):
    txt = eng.explain("select count(*) as c from big where d < 10")
    assert "est_rows=" in txt
