"""Executable-cache lifecycle: the process-wide live-executable budget
(`ops/exec_cache.py`) and the cross-query build cache
(`query/build_cache.py`).

The r4 suite segfaulted the XLA client by accumulating compiled
executables and worked around it by clearing every cache between
queries; these tests pin the real fix — one LRU budget over all
compiled-program caches — and the soak proves a long-lived engine holds
a flat working set across many distinct query shapes.
"""

import numpy as np
import pytest

from ydb_tpu.ops.exec_cache import ExecCache, _Budget


def test_lru_within_one_cache():
    b = _Budget(3)
    c = ExecCache("t", b)
    c["a"], c["b"], c["c"] = 1, 2, 3
    assert c.get("a") == 1             # refresh a
    c["d"] = 4                         # evicts b (globally oldest)
    assert "b" not in c and "a" in c and "c" in c and "d" in c
    assert c.evictions == 1


def test_budget_spans_caches_globally_lru():
    b = _Budget(3)
    c1, c2 = ExecCache("one", b), ExecCache("two", b)
    c1["x"] = 1
    c2["y"] = 2
    c1["z"] = 3
    c2["w"] = 4                        # evicts c1["x"] — oldest anywhere
    assert "x" not in c1 and "y" in c2 and "z" in c1 and "w" in c2
    assert b.total() == 3


def test_get_refresh_protects_across_caches():
    b = _Budget(2)
    c1, c2 = ExecCache("one", b), ExecCache("two", b)
    c1["x"] = 1
    c2["y"] = 2
    assert c1.get("x") == 1            # x newer than y now
    c1["z"] = 3                        # evicts y, not x
    assert "x" in c1 and "y" not in c2


def test_engine_soak_live_executables_bounded(monkeypatch):
    """Many distinct query shapes through ONE engine: the live-executable
    count stays under the global budget and results stay correct (the
    r4 segfault scenario, minus the segfault). Lifting pinned OFF so the
    distinct literals really are distinct executables — the storm-shares-
    one-program property has its own pin above."""
    from ydb_tpu.ops.exec_cache import GLOBAL_BUDGET, live_executables
    from ydb_tpu.query import QueryEngine

    monkeypatch.setenv("YDB_TPU_PARAM_LIFT", "0")
    eng = QueryEngine(block_rows=1 << 12)
    eng.execute("create table s (k Int64 not null, a Int64, b Double, "
                "c Int64, primary key (k))")
    rows = ", ".join(f"({i}, {i % 7}, {i * 0.5}, {i % 3})"
                     for i in range(200))
    eng.execute(f"insert into s (k, a, b, c) values {rows}")

    old_max = GLOBAL_BUDGET.max_entries
    GLOBAL_BUDGET.max_entries = 24
    try:
        # every distinct literal is a distinct program fingerprint →
        # a distinct compiled executable per query shape
        for i in range(60):
            n = eng.query(
                f"select count(*) as n from s where a = {i % 11} "
                f"and k >= {i}").n[0]
            expect = sum(1 for k in range(200)
                         if k % 7 == i % 11 and k >= i)
            assert n == expect, (i, n, expect)
            assert live_executables() <= 24
    finally:
        GLOBAL_BUDGET.max_entries = old_max


def test_eviction_releases_executables():
    """Evicted/overwritten/cleared entries must RELEASE their compiled
    executables (clear_cache), not just drop the reference — the
    lifecycle leak behind the r5 full-suite SIGSEGV."""
    class FakeExec:
        def __init__(self):
            self.cleared = 0

        def clear_cache(self):
            self.cleared += 1

    b = _Budget(2)
    c = ExecCache("t", b)
    e1, e2, e3, e4 = FakeExec(), FakeExec(), FakeExec(), FakeExec()
    c["a"], c["b"] = e1, e2
    c["c"] = e3                        # evicts e1
    assert e1.cleared == 1 and e2.cleared == 0
    c["c"] = e4                        # overwrite releases e3
    assert e3.cleared == 1
    assert c.released == 2
    c.clear()
    assert e2.cleared == 1 and e4.cleared == 1
    assert c.released == 4
    # composite entries (tuples, one level of object attrs) release too
    class Holder:
        def __init__(self, fn):
            self.fn = fn
    b2 = _Budget(1)
    c2 = ExecCache("t2", b2)
    inner1, inner2 = FakeExec(), FakeExec()
    c2["x"] = (inner1, Holder(inner2), "schema")
    c2["y"] = FakeExec()               # evicts the composite
    assert inner1.cleared == 1 and inner2.cleared == 1


def test_evicted_program_recompile_is_miss_not_hit(monkeypatch):
    """The eviction-accounting companion of the PR-4 spurious-evict fix
    (overwrite-in-place must NOT evict — pinned above in
    test_eviction_releases_executables): a real LRU eviction must
    surface in the program inventory (`prog/evicted`, the entry
    persisting marked `evicted`), and re-running the evicted shape must
    count a ProgramCache MISS that re-records compile_ms — never a
    hit against a released executable."""
    from ydb_tpu.ops.exec_cache import GLOBAL_BUDGET
    from ydb_tpu.ops.xla_exec import _GLOBAL_CACHE
    from ydb_tpu.query import QueryEngine
    from ydb_tpu.utils import progstats
    from ydb_tpu.utils.metrics import GLOBAL

    monkeypatch.setenv("YDB_TPU_PARAM_LIFT", "0")
    # the inventory is process-global; scope the state assertions below
    # to THIS test's programs, not leftovers from earlier suites
    progstats.reset_for_tests()
    eng = QueryEngine(block_rows=1 << 12)
    eng.execute("create table ev (k Int64 not null, a Int64, b Double, "
                "primary key (k))")
    eng.execute("insert into ev (k, a, b) values "
                + ", ".join(f"({i}, {i % 5}, {i * 0.5})"
                            for i in range(120)))
    # portioned path → per-stage ProgramCache programs
    eng.executor.enable_fused = False
    old_max = GLOBAL_BUDGET.max_entries
    GLOBAL_BUDGET.max_entries = 4
    try:
        base = "select count(*) as n from ev where a = 0"
        assert int(eng.query(base).n[0]) == 24
        ev0 = GLOBAL.get("prog/evicted")
        # flood with distinct literal shapes (lift off → distinct
        # programs) until the base query's programs are LRU victims
        for i in range(1, 9):
            eng.query(f"select count(*) as n from ev where a = {i % 5} "
                      f"and k >= {i * 7}")
        assert GLOBAL.get("prog/evicted") > ev0, \
            "LRU evictions must emit prog/evicted"
        evicted = [r for r in progstats.inventory_rows()
                   if r["kind"] == "program" and r["state"] == "evicted"]
        assert evicted, "evicted entries must persist in the inventory"
        h0, m0 = _GLOBAL_CACHE.hits, _GLOBAL_CACHE.misses
        assert int(eng.query(base).n[0]) == 24
        assert _GLOBAL_CACHE.misses > m0, \
            "re-running an evicted shape must MISS and recompile"
        # at least one program re-registered: compiles grew past 1 with
        # its eviction history kept
        recompiled = [r for r in progstats.inventory_rows()
                      if r["kind"] == "program" and r["compiles"] >= 2
                      and r["evictions"] >= 1]
        assert recompiled, "recompile must re-record in the inventory"
        assert all(r["state"] == "live" for r in recompiled)
    finally:
        GLOBAL_BUDGET.max_entries = old_max
        eng.executor.enable_fused = True


def test_literal_storm_compiles_one_program():
    """THE param-lifting regression pin (the PR-6 tentpole vs the Weak #3
    executable-accumulation class): a 64-query literal-varying
    point-lookup storm — every statement a distinct SQL text — compiles
    EXACTLY ONE fused program after warmup, the per-stage ProgramCache
    takes zero new misses, and the exec-cache footprint stays flat.
    Before lifting, every distinct literal was a distinct program
    fingerprint: 64 clients = 64 executables of cache pressure."""
    from ydb_tpu.ops.exec_cache import live_executables
    from ydb_tpu.ops.xla_exec import _GLOBAL_CACHE
    from ydb_tpu.query import QueryEngine

    eng = QueryEngine(block_rows=1 << 12)
    eng.execute("create table pt (k Int64 not null, a Int64, b Double, "
                "primary key (k))")
    eng.execute("insert into pt (k, a, b) values "
                + ", ".join(f"({i}, {i % 7}, {i * 0.5})"
                            for i in range(200)))
    warm = eng.query("select a, b from pt where k = 0")
    assert warm.a[0] == 0
    fused0 = len(eng.executor._fused_cache)
    prog_misses0 = _GLOBAL_CACHE.misses
    live0 = live_executables()
    for i in range(1, 64):
        df = eng.query(f"select a, b from pt where k = {i}")
        assert df.a[0] == i % 7 and abs(df.b[0] - i * 0.5) < 1e-9, i
    assert len(eng.executor._fused_cache) == fused0, \
        "literal variants must share ONE compiled fused program"
    assert _GLOBAL_CACHE.misses == prog_misses0
    assert live_executables() == live0, "exec-cache size must stay flat"
    # the lifted-LIMIT bucket shares too: limit 3 and limit 5 both live
    # inside the 128-row bucket → one executable, distinct results
    df3 = eng.query("select k from pt where a = 1 order by k limit 3")
    n1 = len(eng.executor._fused_cache)
    df5 = eng.query("select k from pt where a = 1 order by k limit 5")
    assert len(eng.executor._fused_cache) == n1
    assert list(df3.k) == [1, 8, 15] and list(df5.k) == [1, 8, 15, 22, 29]


@pytest.mark.slow
def test_soak_compile_twice_the_lru_cap_releases(monkeypatch):
    """Soak (marked slow): compile 2× the LRU cap of DISTINCT query
    shapes in ONE process — the live-executable count stays under the
    cap, evictions actually release (released counter tracks them), and
    results stay correct throughout. The full-suite-SIGSEGV scenario,
    run deliberately. Parameter lifting is pinned OFF: it would collapse
    the distinct literals into one shape and starve the eviction path
    this soak exists to exercise."""
    from ydb_tpu.ops.exec_cache import GLOBAL_BUDGET, live_executables
    from ydb_tpu.query import QueryEngine

    monkeypatch.setenv("YDB_TPU_PARAM_LIFT", "0")
    eng = QueryEngine(block_rows=1 << 12)
    eng.execute("create table soak (k Int64 not null, a Int64, b Double, "
                "primary key (k))")
    eng.execute("insert into soak (k, a, b) values "
                + ", ".join(f"({i}, {i % 13}, {i * 0.25})"
                            for i in range(300)))
    old_max = GLOBAL_BUDGET.max_entries
    cap = 40
    GLOBAL_BUDGET.max_entries = cap
    released_before = sum(
        c.released for ref in GLOBAL_BUDGET._caches
        if (c := ref()) is not None)
    try:
        for i in range(2 * cap):
            # distinct literals → distinct program fingerprints →
            # distinct compiled executables
            got = eng.query(
                f"select count(*) as n, sum(b) as s from soak "
                f"where a = {i % 13} and k >= {i * 3}")
            expect = [k for k in range(300)
                      if k % 13 == i % 13 and k >= i * 3]
            assert int(got.n[0]) == len(expect), i
            assert live_executables() <= cap, i
        released_after = sum(
            c.released for ref in GLOBAL_BUDGET._caches
            if (c := ref()) is not None)
        assert released_after > released_before
    finally:
        GLOBAL_BUDGET.max_entries = old_max


def test_build_cache_hit_and_invalidation():
    from ydb_tpu.query import QueryEngine

    eng = QueryEngine(block_rows=1 << 12)
    eng.execute("create table f (k Int64 not null, d Int64, v Double, "
                "primary key (k))")
    eng.execute("create table d (d Int64 not null, tag Utf8, "
                "primary key (d))")
    eng.execute("insert into d (d, tag) values (0, 'x'), (1, 'y')")
    eng.execute("insert into f (k, d, v) values "
                + ", ".join(f"({i}, {i % 2}, {i * 1.0})" for i in range(50)))
    sql = ("select tag, sum(v) as s from f join d on f.d = d.d "
           "group by tag order by tag")
    bc = eng.executor.build_cache
    df1 = eng.query(sql)
    m0, h0 = bc.misses, bc.hits
    df2 = eng.query(sql)
    assert bc.hits > h0, "second run must hit the build cache"
    assert list(df1.s) == list(df2.s)
    # a write to the BUILD table invalidates (src-id keying)
    eng.execute("insert into d (d, tag) values (2, 'z')")
    eng.execute("insert into f (k, d, v) values (100, 2, 10.0)")
    df3 = eng.query(sql)
    assert bc.misses > m0
    assert list(df3.tag) == ["x", "y", "z"]
    # pandas oracle for the final state
    import pandas as pd
    f = pd.DataFrame({"d": [i % 2 for i in range(50)] + [2],
                      "v": [i * 1.0 for i in range(50)] + [10.0]})
    dd = pd.DataFrame({"d": [0, 1, 2], "tag": ["x", "y", "z"]})
    want = (f.merge(dd, on="d").groupby("tag").v.sum()
            .reset_index().sort_values("tag"))
    assert np.allclose(df3.s.to_numpy(), want.v.to_numpy())


def test_build_cache_respects_probe_dictionary():
    """Two tables joining the same build over DIFFERENT probe
    dictionaries must not share the remapped entry."""
    from ydb_tpu.query import QueryEngine

    eng = QueryEngine(block_rows=1 << 12)
    for t in ("p1", "p2"):
        eng.execute(f"create table {t} (k Int64 not null, s Utf8, "
                    f"primary key (k))")
    eng.execute("create table dim (s Utf8 not null, w Int64, "
                "primary key (s))")
    eng.execute("insert into dim (s, w) values ('a', 1), ('b', 2)")
    eng.execute("insert into p1 (k, s) values (1, 'a'), (2, 'b')")
    # p2's dictionary encodes in a different order
    eng.execute("insert into p2 (k, s) values (1, 'b'), (2, 'a')")
    q = "select sum(w) as t from {p} join dim on {p}.s = dim.s where k = 1"
    assert eng.query(q.format(p="p1")).t[0] == 1
    assert eng.query(q.format(p="p2")).t[0] == 2
