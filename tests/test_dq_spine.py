"""Device-resident stage spine: planned redistribution differentials.

The planned exchange (`dq/ici.exchange_blocks`) sizes its collective
segments from an exchanged count matrix instead of the legacy 2x
power-of-two guess, and hands `DeviceStageBlock`s between stages by
reference. Every scenario here must be BYTE-equal to the host plane
(the escape hatch) and to the lever-off legacy exchange — the planned
path changes wire layout and padding, never values or row order.

Run on the virtual 8-device host mesh (conftest sets
xla_force_host_platform_device_count).
"""

import numpy as np
import pandas as pd
import pytest

from ydb_tpu.cluster import ShardedCluster
from ydb_tpu.cluster.router import ShardedCluster as _RouterCluster
from ydb_tpu.dq.graph import HASH_SHUFFLE
from ydb_tpu.dq.runner import LocalWorker
from ydb_tpu.query import QueryEngine
from ydb_tpu.utils.metrics import GLOBAL

NW = 2
ROWS = 140

JOIN_SQL = ("select k, count(*) as n, sum(w) as s, min(x) as mn, "
            "max(x) as mx from t, u where k = uid group by k order by k")


def _mk_engine(wid: int, nw: int = NW, keys=None) -> QueryEngine:
    """The test_dq_ici harness schema; `keys[i]` overrides row i's k so
    scenarios can steer the shuffle's bucket histogram."""
    eng = QueryEngine(block_rows=1 << 12)
    eng.execute("create table t (id Int64 not null, k Int64 not null, "
                "v Double not null, tag Utf8 not null, nv Double, "
                "primary key (id))")
    eng.execute("create table u (uid Int64 not null, w Double not null, "
                "x Double not null, primary key (uid))")
    mine = [i for i in range(ROWS) if i % nw == wid]
    kof = (lambda i: keys[i]) if keys is not None else (lambda i: i % 7)
    # v dyadic (i * 0.5): float sums exact in any order → byte-equality
    eng.execute(
        "insert into t (id, k, v, tag, nv) values "
        + ", ".join(f"({i}, {kof(i)}, {i * 0.5}, 'tag{i % 3}', "
                    + ("null" if i % 5 == 0 else f"{i * 0.25}") + ")"
                    for i in mine))
    umine = [i for i in range(7) if i % nw == wid]
    if umine:
        eng.execute("insert into u (uid, w, x) values "
                    + ", ".join(f"({i}, {i}.0, {10.0 + i * 0.3})"
                                for i in umine))
    return eng


def _mk_cluster(nw: int = NW, keys=None) -> ShardedCluster:
    engines = [_mk_engine(i, nw, keys=keys) for i in range(nw)]
    c = ShardedCluster([LocalWorker(e, name=f"sp{i}")
                        for i, e in enumerate(engines)],
                       merge_engine=engines[0])
    c.key_columns["t"] = ["id"]
    c.key_columns["u"] = ["uid"]
    return c


def _frames_equal(a: pd.DataFrame, b: pd.DataFrame):
    assert list(a.columns) == list(b.columns)
    assert len(a) == len(b)
    for col in a.columns:
        x, y = a[col].to_numpy(), b[col].to_numpy()
        if x.dtype.kind == "f" or y.dtype.kind == "f":
            assert np.array_equal(x.astype(np.float64),
                                  y.astype(np.float64),
                                  equal_nan=True), col
        else:
            assert np.array_equal(x, y), col


def _both_planes(monkeypatch, cluster, sql):
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "host")
    want = cluster.query(sql)
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
    got = cluster.query(sql)
    return got, want


# -- planned path: spine invariants ----------------------------------------


def test_planned_join_byte_equal_and_hostsync_free(monkeypatch):
    """The headline differential: planned segments from exchanged
    counts, device blocks by reference, zero in-plan to_pandas."""
    cluster = _mk_cluster()
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "host")
    want = cluster.query(JOIN_SQL)
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
    n0 = GLOBAL.get("hostsync/to_pandas_in_plan")
    h0 = GLOBAL.get("devlink/handoffs")
    got = cluster.query(JOIN_SQL)
    _frames_equal(got, want)
    assert GLOBAL.get("hostsync/to_pandas_in_plan") - n0 == 0
    assert GLOBAL.get("devlink/handoffs") - h0 > 0


def test_zero_row_buckets(monkeypatch):
    """Every t row carries ONE key → ndev-1 of each producer's buckets
    are empty and most consumers land zero rows. Empty segments must
    ship (zero-filled) without perturbing values or order."""
    cluster = _mk_cluster(keys=[5] * ROWS)
    got, want = _both_planes(monkeypatch, cluster, JOIN_SQL)
    assert len(want) == 1           # the scenario really is degenerate
    _frames_equal(got, want)


def test_heavy_skew_single_bucket(monkeypatch):
    """>90% of rows hash to one key: the count matrix is near-diagonal
    and the planned segment is sized by the hot pair, not 2x the global
    max — results still byte-equal."""
    keys = [3 if i % 10 else i % 7 for i in range(ROWS)]  # ~93% k=3
    cluster = _mk_cluster(keys=keys)
    got, want = _both_planes(monkeypatch, cluster, JOIN_SQL)
    _frames_equal(got, want)


def test_single_worker_degenerate(monkeypatch):
    """NW=1: no redistribution to plan — the plan collapses to local
    execution and still matches the forced-host answer."""
    cluster = _mk_cluster(nw=1)
    got, want = _both_planes(monkeypatch, cluster, JOIN_SQL)
    _frames_equal(got, want)


def test_forged_low_bound_overflow_rerun(monkeypatch):
    """An unsound out_bound (forged to 1 row) undercuts the measured
    counts: the exchange books dq/planned_overflow_reruns and re-sizes
    to full capacity — the answer is unchanged."""
    cluster = _mk_cluster()
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "host")
    want = cluster.query(JOIN_SQL)

    orig = _RouterCluster._lower

    def forged(self, stmt):
        g = orig(self, stmt)
        for ch in g.channels.values():
            if ch.kind == HASH_SHUFFLE:
                ch.out_bound = 1
        return g

    monkeypatch.setattr(_RouterCluster, "_lower", forged)
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
    r0 = GLOBAL.get("dq/planned_overflow_reruns")
    got = cluster.query(JOIN_SQL)
    assert GLOBAL.get("dq/planned_overflow_reruns") > r0
    _frames_equal(got, want)


def test_lever_off_restores_legacy_2x_path(monkeypatch):
    """YDB_TPU_DQ_PLANNED=0: the legacy 2x exchange still runs
    byte-equal — and books the in-plan pandas debt the planned path
    retired (the differential that proves the spine is the thing
    removing it)."""
    cluster = _mk_cluster()
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "host")
    want = cluster.query(JOIN_SQL)
    monkeypatch.setenv("YDB_TPU_DQ_PLANE", "auto")
    monkeypatch.setenv("YDB_TPU_DQ_PLANNED", "0")
    n0 = GLOBAL.get("hostsync/to_pandas_in_plan")
    got = cluster.query(JOIN_SQL)
    _frames_equal(got, want)
    assert GLOBAL.get("hostsync/to_pandas_in_plan") - n0 > 0


def test_strings_and_nulls_planned(monkeypatch):
    """Dictionary and masked columns across the planned exchange: the
    union-dictionary remap and validity planes survive by reference."""
    sql = ("select tag, count(*) as n, sum(v) as s, sum(nv) as sn "
           "from t, u where k = uid group by tag order by tag")
    cluster = _mk_cluster()
    got, want = _both_planes(monkeypatch, cluster, sql)
    _frames_equal(got, want)
