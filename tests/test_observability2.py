"""Tracing spans, audit log, and rate limiting.

Reference analogs: Wilson spans + OTLP uploader
(`ydb/library/actors/wilson/`), the audit sink (`ydb/core/audit`), and
the Kesus-backed quoter (`ydb/core/quoter/quoter_service.cpp`).
"""

import pytest

from ydb_tpu.query import QueryEngine
from ydb_tpu.query.engine import QueryError
from ydb_tpu.storage import blobfile as B
from ydb_tpu.utils.quota import Quoter, TokenBucket


@pytest.fixture()
def eng():
    e = QueryEngine(block_rows=1 << 10)
    e.execute("create table t (id Int64 not null, v Double, "
              "primary key (id))")
    e.execute("insert into t (id, v) values (1, 1.0), (2, 2.0)")
    return e


def test_span_tree_phases(eng):
    eng.query("select sum(v) as s from t")
    names = [s.name for s in eng.last_trace]
    assert names[0] == "statement"
    assert {"parse", "plan", "execute"} <= set(names)
    root = eng.last_trace[0]
    by_id = {s.span_id: s for s in eng.last_trace}
    for s in eng.last_trace[1:]:
        assert s.trace_id == root.trace_id
        assert s.parent_id in by_id          # a connected tree
    ex = next(s for s in eng.last_trace if s.name == "execute")
    kids = [s for s in eng.last_trace if s.parent_id == ex.span_id]
    assert kids, "executor sub-spans attach under execute"


def test_explain_analyze_includes_trace(eng):
    df = eng.query("explain analyze select count(*) as c from t")
    text = "\n".join(df["plan"])
    assert "-- trace:" in text and "device-dispatch" in text


def test_trace_export_to_topic(eng):
    eng.create_topic("traces")
    eng.trace_to_topic("traces")
    eng.query("select count(*) as c from t")
    msgs = eng.topic("traces").read("c", 0, limit=10)
    assert msgs
    spans = msgs[-1]["data"]["spans"]
    assert spans[0]["name"] == "statement"
    assert all(sp["trace_id"] == spans[0]["trace_id"] for sp in spans)


def test_audit_log(tmp_path):
    root = str(tmp_path / "s")
    eng = QueryEngine(block_rows=1 << 10, data_dir=root)
    eng.execute("create table a (k Int64 not null, primary key (k))")
    eng.execute("insert into a (k) values (1), (2)")
    eng.query("select * from a")              # SELECTs are not audited
    with pytest.raises(QueryError):
        eng.execute("insert into a (k) values (null)")
    recs = B.wal_replay(str(tmp_path / "s" / "audit.bin"))
    kinds = [(r["kind"], r["status"]) for r in recs]
    assert ("createtable", "ok") in kinds
    assert ("insert", "ok") in kinds
    assert ("insert", "error") in kinds
    assert all(r["kind"] != "select" for r in recs)
    ok_insert = next(r for r in recs
                     if r["kind"] == "insert" and r["status"] == "ok")
    assert ok_insert["rows"] == 2


def test_token_bucket_and_quoter():
    now = [0.0]
    b = TokenBucket(rate=2, burst=4, clock=lambda: now[0])
    assert all(b.try_acquire() for _ in range(4))   # burst drains
    assert not b.try_acquire()
    now[0] += 1.0                                   # +2 tokens
    assert b.try_acquire() and b.try_acquire()
    assert not b.try_acquire()
    q = Quoter(clock=lambda: now[0])
    assert q.acquire("anything")                    # unmetered = unlimited
    q.set_quota("queries", rate=1, burst=1)
    assert q.acquire("queries")
    assert not q.acquire("queries")
    q.drop_quota("queries")
    assert q.acquire("queries")


def test_engine_admission_throttle(eng):
    eng.quoter.set_quota("queries", rate=0.001, burst=2)
    eng.query("select 1 as x")
    eng.query("select 2 as x")
    with pytest.raises(QueryError, match="rate limit"):
        eng.query("select 3 as x")
    eng.quoter.drop_quota("queries")
    eng.query("select 4 as x")                      # recovered
