"""Config system (YAML + selector overrides + feature flags) and the
health endpoint.

Reference: `ydb/library/yaml_config` (selector/override resolution),
`ydb/core/base/feature_flags.h` (gates on real paths), and
`ydb/core/health_check/health_check.cpp` (aggregated health API).
"""

import pytest

from ydb_tpu.query import QueryEngine
from ydb_tpu.utils.config import Config


def test_config_defaults_and_flags():
    c = Config()
    assert c.block_rows == 1 << 20
    assert c.flag("enable_fused") and c.flag("enable_plan_cache")
    with pytest.raises(KeyError):
        c.flag("enable_warp_drive")


def test_config_selector_overrides():
    doc = {
        "block_rows": 4096,
        "feature_flags": {"enable_fused": True},
        "overrides": [
            {"selector": {"env": "test"},
             "config": {"block_rows": 1024,
                        "feature_flags": {"enable_fused": False}}},
            {"selector": {"env": "prod"},
             "config": {"block_rows": 1 << 21}},
        ],
    }
    base = Config.from_dict(doc)
    assert base.block_rows == 4096 and base.flag("enable_fused")
    test = Config.from_dict(doc, labels={"env": "test"})
    assert test.block_rows == 1024 and not test.flag("enable_fused")
    prod = Config.from_dict(doc, labels={"env": "prod"})
    assert prod.block_rows == 1 << 21 and prod.flag("enable_fused")


def test_config_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown config"):
        Config.from_dict({"block_rowz": 1})
    with pytest.raises(ValueError, match="unknown feature flags"):
        Config.from_dict({"feature_flags": {"nope": True}})


def test_config_yaml_roundtrip(tmp_path):
    p = tmp_path / "conf.yaml"
    p.write_text("block_rows: 2048\n"
                 "feature_flags:\n  enable_plan_cache: false\n")
    c = Config.load(str(p))
    assert c.block_rows == 2048 and not c.flag("enable_plan_cache")


def test_flags_gate_real_paths():
    c = Config.from_dict({
        "block_rows": 1024,
        "feature_flags": {"enable_fused": False,
                          "enable_plan_cache": False}})
    eng = QueryEngine(config=c)
    assert eng.executor.block_rows == 1024
    eng.execute("create table t (id Int64 not null, v Double, "
                "primary key (id))")
    eng.execute("insert into t (id, v) values (1, 1.0), (2, 2.0)")
    df = eng.query("select sum(v) as s from t")
    assert float(df.s[0]) == 3.0
    assert eng.executor.last_path == "portioned"   # fused disabled
    eng.query("select sum(v) as s from t")
    assert eng.plan_cache_hits == 0                # cache disabled


def test_health_endpoint():
    from ydb_tpu.server import Client, serve
    eng = QueryEngine(block_rows=1 << 10)
    eng.execute("create table t (id Int64 not null, primary key (id))")
    eng.create_topic("tp")
    server, port = serve(eng, port=0)
    try:
        c = Client(f"127.0.0.1:{port}")
        h = c.health()
        assert h["status"] == "GOOD"
        assert h["tables"] == 1 and h["topics"] == 1
        assert h["durable"] is False
        assert h["platform"] in ("cpu", "tpu", "axon")
        assert h["uptime_s"] >= 0
    finally:
        server.stop(0)
