"""Critical-path extraction, clock-aligned cross-worker timelines, and
the Perfetto/Chrome trace export.

The blocking-chain math is pinned on hand-built span DAGs (diamond,
hidden channel wait, compile→execute, retry); the integration legs run
real queries — local fused and 2-worker DQ — and check the surfaced
forms: `QueryStats.critical_path`, EXPLAIN ANALYZE `-- critical path:`
lines, `.sys/query_critical_path`, `crit/*` counters, `GET /trace/<id>`
and the `YDB_TPU_CRITPATH=0` off-lever (byte-equal, counters frozen).
"""

import json
import urllib.request

import pytest

from ydb_tpu.query import QueryEngine
from ydb_tpu.utils import chrometrace, critpath
from ydb_tpu.utils.metrics import GLOBAL
from ydb_tpu.utils.tracing import Span


def sp(name, sid, parent, start, dur, **attrs):
    return Span(name, 1, sid, parent, float(start), float(dur),
                attrs=dict(attrs))


# -- hand-built DAG math ----------------------------------------------------


def test_diamond_takes_the_longer_parallel_branch():
    spans = [
        sp("dq-query", 1, None, 0, 100),
        sp("task-exec", 2, 1, 0, 40),        # short branch — NOT on path
        sp("task-exec", 3, 1, 0, 70),        # long branch — on path
        sp("device-execute", 4, 1, 70, 30),  # tail
    ]
    cp = critpath.extract(spans)
    names = [s["span_id"] for s in cp["segments"]]
    assert 3 in names and 4 in names and 2 not in names
    assert cp["connected"]
    assert cp["coverage"] == pytest.approx(1.0, abs=0.01)
    assert cp["classes"]["host_lane"] == pytest.approx(70, abs=0.1)
    assert cp["classes"]["device_execute"] == pytest.approx(30, abs=0.1)


def test_fully_hidden_channel_wait_stays_off_the_path():
    spans = [
        sp("execute", 1, None, 0, 100),
        sp("device-execute", 2, 1, 0, 100),
        sp("input-wait", 3, 1, 20, 30),      # entirely under the execute
    ]
    cp = critpath.extract(spans)
    assert "channel_wait" not in cp["classes"]
    assert cp["classes"]["device_execute"] == pytest.approx(100, abs=0.1)


def test_serial_compile_then_execute_chain_splits_classes():
    spans = [
        sp("statement", 1, None, 0, 90),
        sp("device-dispatch", 2, 1, 0, 50, compile_ms=40.0),
        sp("device-execute", 3, 1, 50, 40),
    ]
    cp = critpath.extract(spans)
    assert cp["classes"]["compile"] == pytest.approx(40, abs=0.1)
    # 10ms dispatch tail + the 40ms execute
    assert cp["classes"]["device_execute"] == pytest.approx(50, abs=0.1)
    assert cp["connected"]


def test_zero_and_single_span_queries():
    empty = critpath.extract([])
    assert empty["segments"] == [] and empty["wall_ms"] == 0.0
    one = critpath.extract([sp("device-execute", 1, None, 5, 10)])
    assert len(one["segments"]) == 1
    assert one["classes"] == {"device_execute": 10.0}
    assert one["coverage"] == pytest.approx(1.0)
    assert one["dominant_class"] == "device_execute"


def test_failed_attempt_does_not_extend_the_path():
    spans = [
        sp("dq-stage", 1, None, 0, 100),
        sp("dq-task", 2, 1, 0, 40, state="failed", attempt=1),
        sp("task-exec", 3, 2, 5, 30),            # child of the failure
        sp("dq-task", 4, 1, 45, 50, state="finished", attempt=2),
        sp("task-exec", 5, 4, 47, 45),
    ]
    cp = critpath.extract(spans)
    ids = {s["span_id"] for s in cp["segments"]}
    assert 2 not in ids and 3 not in ids
    assert 5 in ids
    # the pre-retry window is honest scheduler gap, not failed work
    assert cp["classes"]["scheduler_gap"] > 0


def test_zero_duration_mid_window_span_terminates():
    """Regression: a 0-duration child strictly inside its parent's
    window (rounded-away sub-µs work, a 0ms input-wait on a full
    channel) must not be selectable as the blocking child — choosing it
    left the walk's cursor unchanged and spun extract() forever."""
    spans = [
        sp("statement", 1, None, 0, 10),
        sp("input-wait", 2, 1, 5, 0),            # zero duration, mid-window
        sp("plan", 3, 1, 9.9995, 0.0004),        # sub-EPS sliver at t
    ]
    cp = critpath.extract(spans)                 # must return, not hang
    assert cp["classes"]["host_lane"] == pytest.approx(10, abs=0.1)
    assert 2 not in {s["span_id"] for s in cp["segments"]}
    assert cp["connected"]


def test_forest_without_root_gets_virtual_root_and_gap():
    spans = [
        sp("parse", 1, None, 0, 10),
        sp("plan", 2, None, 20, 10),             # 10ms gap before it
    ]
    cp = critpath.extract(spans)
    assert cp["wall_ms"] == pytest.approx(30)
    assert cp["classes"]["host_lane"] == pytest.approx(20, abs=0.1)
    assert cp["classes"]["scheduler_gap"] == pytest.approx(10, abs=0.1)
    assert cp["connected"]


def test_memory_join_rides_along():
    cp = critpath.extract(
        [sp("device-execute", 1, None, 0, 10)],
        memory={"transfer_bytes": 1234, "transfers": 3,
                "waste_bytes": 999, "pad_efficiency": 0.5,
                "to_pandas_in_plan": 1})
    assert cp["memory"]["transfer_bytes"] == 1234
    assert cp["memory"]["pad_efficiency"] == 0.5
    assert any("host transfers" in ln
               for ln in critpath.render_lines(cp))


# -- engine integration -----------------------------------------------------


def mk_engine():
    e = QueryEngine(block_rows=1 << 13)
    e.execute("create table t (id Int64 not null, v Double not null, "
              "primary key (id))")
    e.execute("insert into t (id, v) values " + ", ".join(
        f"({i}, {i}.5)" for i in range(64)))
    return e


def test_local_query_stats_and_explain_lines():
    eng = mk_engine()
    eng.query("select sum(v) as s, count(*) as n from t")
    cp = eng.last_stats.critical_path
    assert cp and cp["classes"]
    assert cp["connected"]
    assert cp["coverage"] >= 0.9
    assert set(cp["classes"]) <= set(critpath.CLASSES)
    df = eng.query("explain analyze select sum(v) as s from t")
    text = "\n".join(df["plan"])
    assert "-- critical path:" in text and "%" in text


def test_sysview_and_counters():
    eng = mk_engine()
    before = GLOBAL.get("crit/extractions")
    eng.query("select sum(v) as s from t")
    assert GLOBAL.get("crit/extractions") > before
    got = eng.query("select sql, coverage, connected, dominant_class "
                    "from `.sys/query_critical_path`")
    assert len(got) > 0
    assert bool(got["connected"].to_numpy()[-1])
    c = eng.counters()
    assert c.get("crit/extractions", 0) > 0        # always-visible [viz]


def test_chrome_render_validates_for_local_query():
    eng = mk_engine()
    eng.query("select sum(v) as s from t")
    trace = chrometrace.render(eng.profiles[-1])
    assert chrometrace.validate(trace) == []
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert xs and all(e["ts"] >= 0 and e["dur"] >= 0 for e in xs)
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert "router" in names


def test_http_trace_endpoint_serves_and_404s():
    from ydb_tpu.server.http import serve_http
    eng = mk_engine()
    eng.query("select sum(v) as s from t")
    prof = eng.profiles[-1]
    front = serve_http(eng)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{front.port}/trace/"
                f"{prof['trace_id']}", timeout=10) as r:
            trace = json.loads(r.read())
        assert chrometrace.validate(trace) == []
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{front.port}/trace/424242",
                timeout=10)
        assert ei.value.code == 404
    finally:
        front.stop()


# -- DQ cluster: cross-worker timelines -------------------------------------


def mk_cluster(skew_ms: float = 0.0):
    from ydb_tpu.cluster import ShardedCluster
    from ydb_tpu.dq.runner import LocalWorker

    engines = []
    for wid in range(2):
        e = QueryEngine(block_rows=1 << 13)
        e.execute("create table t (id Int64 not null, k Int64 not null, "
                  "v Double not null, primary key (id))")
        mine = [i for i in range(120) if i % 2 == wid]
        e.execute("insert into t (id, k, v) values " + ", ".join(
            f"({i}, {i % 7}, {i}.5)" for i in mine))
        e.execute("create table u (uid Int64 not null, w Double not null, "
                  "primary key (uid))")
        mine_u = [i for i in range(7) if i % 2 == wid]
        if mine_u:
            e.execute("insert into u (uid, w) values " + ", ".join(
                f"({i}, {i}.0)" for i in mine_u))
        engines.append(e)
    if skew_ms:
        # inject clock skew via the WORKER's `_now` hook: every span
        # this worker records is stamped `skew_ms` ahead — exactly the
        # shape two OS worker processes with different process starts
        # (or drifting clocks) produce over the DqRunTask RPC
        t1 = engines[1].tracer
        real = t1._now
        t1._now = lambda: real() + skew_ms
    workers = [LocalWorker(engines[0], name="w0"),
               LocalWorker(engines[1], name="w1")]
    c = ShardedCluster(workers, merge_engine=engines[0])
    c.key_columns["t"] = ["id"]
    c.key_columns["u"] = ["uid"]
    return c, engines


SQL = "select count(*) as n, sum(w) as s from t, u where k = uid"


def _assert_gap_free(eng):
    """Worker spans must sit inside their dq-task attempt spans on the
    ROUTER timebase — the rebase is measured, not parent-snapped."""
    spans = eng.last_trace
    by_id = {s.span_id: s for s in spans}
    checked = 0
    for s in spans:
        if s.name != "task-exec":
            continue
        task = by_id.get(s.parent_id)
        if task is None or task.name != "dq-task":
            continue
        checked += 1
        assert task.start_ms - 150.0 <= s.start_ms, \
            (s.start_ms, task.start_ms)
        assert s.start_ms + s.dur_ms <= task.start_ms + task.dur_ms \
            + 150.0, (s, task)
    assert checked >= 2          # both workers contributed
    cp = eng.profiles[-1]["critical_path"]
    assert cp["connected"] and cp["coverage"] >= 0.9


def test_skewed_worker_clocks_still_assemble_gap_free():
    # +8s and -8s skew: without clock alignment the worker subtrees
    # would land seconds outside their attempt spans and the "timeline"
    # would have giant holes/overlaps
    for skew in (8000.0, -8000.0):
        c, engines = mk_cluster(skew_ms=skew)
        got = c.query(SQL)
        assert int(got.n[0]) > 0
        _assert_gap_free(engines[0])
        # the offset estimate is stamped on the trace and ~cancels the
        # injected skew (both tracers share one real clock here)
        offs = [s.attrs["clock_offset_ms"] for s in engines[0].last_trace
                if s.name == "dq-task"
                and "clock_offset_ms" in s.attrs]
        assert offs
        # tolerance is loose (first-sample error is ±call-overhead
        # asymmetry under GIL contention on a 1-core runner) but still
        # ~30x tighter than the injected skew it must cancel
        assert any(abs(o + skew) < 250.0 for o in offs)


def test_unskewed_cluster_assembles_gap_free_too():
    c, engines = mk_cluster()
    c.query(SQL)
    _assert_gap_free(engines[0])


def test_dq_chrome_trace_has_worker_tracks_and_flow_arrows():
    c, engines = mk_cluster()
    c.query(SQL)
    prof = engines[0].profiles[-1]
    trace = chrometrace.render(prof)
    assert chrometrace.validate(trace) == []
    assert chrometrace.flow_pairs(trace) >= 1
    lanes = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    assert {"local:w0", "local:w1"} <= lanes


def test_dq_critical_path_classes_cover_channel_and_host_lane():
    c, engines = mk_cluster()
    c.query(SQL)
    cp = engines[0].profiles[-1]["critical_path"]
    assert cp["connected"] and cp["coverage"] >= 0.9
    assert all(s["class"] in critpath.CLASSES for s in cp["segments"])
    # a DQ stage chain runs through the host to_pandas lane today —
    # the non-device share must be visible, not hidden in gaps
    assert cp["non_device_ms"] > 0
    assert cp["dominant_span"]


# -- OTLP-uploader schema stamp ---------------------------------------------


def test_trace_topic_export_is_version_stamped():
    eng = mk_engine()
    eng.create_topic("traces")
    eng.trace_to_topic("traces")
    eng.query("select sum(v) as s from t")
    msgs = eng.topic("traces").read("c", 0, limit=10)
    assert msgs
    data = msgs[-1]["data"]
    assert data["v"] == 2
    assert data["timebase"] == "router"
    assert data["spans"] and data["spans"][0]["name"] == "statement"


# -- the YDB_TPU_CRITPATH=0 lever -------------------------------------------


def test_critpath_off_is_byte_equal_and_frozen(monkeypatch):
    import numpy as np
    base = mk_engine()
    want = base.query("select sum(v) as s, count(*) as n from t")

    monkeypatch.setenv("YDB_TPU_CRITPATH", "0")
    before = {k: GLOBAL.get(k) for k in
              ("crit/extractions", "crit/non_device_ms")}
    quiet = mk_engine()
    got = quiet.query("select sum(v) as s, count(*) as n from t")
    assert list(got.columns) == list(want.columns)
    assert all(np.array_equal(got[c].to_numpy(), want[c].to_numpy())
               for c in want.columns)
    # extraction fully disabled: no stats, no profile field, no ring
    # rows, counters frozen
    assert quiet.last_stats.critical_path == {}
    assert "critical_path" not in quiet.profiles[-1]
    assert len(quiet.critpath_stats) == 0
    assert {k: GLOBAL.get(k) for k in before} == before
    # and the export endpoint refuses loudly instead of serving stale
    from ydb_tpu.server.http import serve_http
    front = serve_http(quiet)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{front.port}/trace/1", timeout=10)
        assert ei.value.code == 409
    finally:
        front.stop()


# -- graftlint: analysis-side modules ---------------------------------------


def test_host_sync_pass_treats_critpath_as_analysis_side():
    from ydb_tpu.analysis.core import Project
    from ydb_tpu.analysis.passes.host_sync import (
        ANALYSIS_SIDE, HostSyncPass,
    )
    assert "ydb_tpu/utils/critpath.py" in ANALYSIS_SIDE
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    project = Project.from_dir(repo)
    findings = HostSyncPass().check(project)
    assert not [f for f in findings if f.path in ANALYSIS_SIDE]


def test_registry_covers_crit_families():
    from ydb_tpu.utils.metrics import COUNTER_REGISTRY
    for name in ("crit/extractions", "crit/disconnected",
                 "crit/non_device_ms", "crit/coverage_pct", "crit/*"):
        assert name in COUNTER_REGISTRY
