"""Shard split/merge with virtual-bucket routing.

VERDICT r3 item 10: auto-split a hot/large shard with portions
redistributed (`schemeshard__table_stats.cpp` trigger, simplified onto
hash-bucket routing: 64 virtual buckets map to shards; a split reassigns
half the hot shard's buckets to a new shard and re-partitions its
portions by bucket).
"""

import numpy as np
import pytest

from ydb_tpu.query import QueryEngine
from ydb_tpu.utils.config import Config


def _fill(e, n, start=0):
    for lo in range(start, start + n, 5000):
        rows = ",".join(f"({i},{i * 2})"
                        for i in range(lo, min(lo + 5000, start + n)))
        e.execute(f"insert into t (id, v) values {rows}")


def test_auto_split_at_threshold():
    cfg = Config(shard_split_rows=8000)
    e = QueryEngine(block_rows=1 << 10, config=cfg)
    e.execute("create table t (id Int64 not null, v Int64 not null, "
              "primary key (id)) with (store = column)")
    _fill(e, 20_000)
    t = e.catalog.table("t")
    assert len(t.shards) >= 2, "never split"
    # every shard under control, rows conserved and redistributed
    sizes = [s.num_rows for s in t.shards]
    assert sum(sizes) == 20_000
    assert all(n > 0 for n in sizes), sizes
    # scans/plans see both shards
    assert int(e.query("select count(*) as c from t").c[0]) == 20_000
    assert int(e.query("select sum(v) as s from t").s[0]) \
        == sum(i * 2 for i in range(20_000))
    # new writes route by the updated bucket map
    _fill(e, 5000, start=20_000)
    assert int(e.query("select count(*) as c from t").c[0]) == 25_000
    from ydb_tpu.utils.metrics import GLOBAL
    assert GLOBAL.snapshot().get("engine/shard_splits", 0) >= 1


def test_split_survives_restart(tmp_path):
    d = str(tmp_path / "store")
    cfg = Config(shard_split_rows=6000)
    e = QueryEngine(block_rows=1 << 10, config=cfg, data_dir=d)
    e.execute("create table t (id Int64 not null, v Int64 not null, "
              "primary key (id)) with (store = column)")
    _fill(e, 15_000)
    t = e.catalog.table("t")
    nsh, buckets = len(t.shards), list(t.buckets)
    assert nsh >= 2

    e2 = QueryEngine(block_rows=1 << 10, data_dir=d)
    t2 = e2.catalog.table("t")
    assert len(t2.shards) == nsh
    assert list(t2.buckets) == buckets
    assert int(e2.query("select count(*) as c from t").c[0]) == 15_000
    assert int(e2.query("select sum(v) as s from t").s[0]) \
        == sum(i * 2 for i in range(15_000))
    # writes after recovery land in the right shards
    _fill(e2, 1000, start=15_000)
    assert int(e2.query("select count(*) as c from t").c[0]) == 16_000


def test_merge_last_shard():
    e = QueryEngine(block_rows=1 << 10)
    e.execute("create table t (id Int64 not null, v Int64 not null, "
              "primary key (id)) with (store = column)")
    _fill(e, 10_000)
    t = e.catalog.table("t")
    assert t.split_shard(0)
    assert len(t.shards) == 2
    assert t.merge_last_shard()
    assert len(t.shards) == 1
    assert set(t.buckets) == {0}
    assert int(e.query("select count(*) as c from t").c[0]) == 10_000
    _fill(e, 1000, start=10_000)
    assert int(e.query("select count(*) as c from t").c[0]) == 11_000


def test_split_preserves_snapshots():
    e = QueryEngine(block_rows=1 << 10)
    e.execute("create table t (id Int64 not null, v Int64 not null, "
              "primary key (id)) with (store = column)")
    _fill(e, 10_000)
    from ydb_tpu.sql import parse
    plan = e.planner.plan_select(parse("select count(*) as c from t"))
    old = e.snapshot()
    t = e.catalog.table("t")
    assert t.split_shard(0)
    _fill(e, 2000, start=10_000)
    # the pre-split snapshot still counts exactly the old rows
    blk = e.executor.execute(plan, old)
    assert int(blk.to_pandas().iloc[0, 0]) == 10_000
