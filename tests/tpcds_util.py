"""TPC-DS query subset + pandas oracles.

Standard TPC-DS query shapes (the reference templates live in
`ydb/library/benchmarks/queries/tpcds/`): star joins over store_sales
with date/item/store dimensions, grouped reports with LIMIT, and the
rank-over-partition window pattern of the q67 family.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

QUERIES = {
    # q3: brand report for one manufacturer in December
    "ds3": """
select d.d_year, i.i_brand_id, i.i_brand, sum(ss.ss_ext_sales_price) as sum_agg
from store_sales ss
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on i.i_item_sk = ss.ss_item_sk
where i.i_manufact_id = 28 and d.d_moy = 12
group by d.d_year, i.i_brand_id, i.i_brand
order by d.d_year, sum_agg desc, i.i_brand_id
limit 100""",
    # q42: category report for one year/month
    "ds42": """
select d.d_year, i.i_category_id, i.i_category, sum(ss.ss_ext_sales_price) as s
from store_sales ss
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on i.i_item_sk = ss.ss_item_sk
where d.d_moy = 11 and d.d_year = 2000
group by d.d_year, i.i_category_id, i.i_category
order by s desc, d.d_year, i.i_category_id, i.i_category
limit 100""",
    # q52: brand report for one year/month
    "ds52": """
select d.d_year, i.i_brand_id, i.i_brand, sum(ss.ss_ext_sales_price) as ext_price
from store_sales ss
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on i.i_item_sk = ss.ss_item_sk
where d.d_moy = 11 and d.d_year = 2000
group by d.d_year, i.i_brand_id, i.i_brand
order by d.d_year, ext_price desc, i.i_brand_id
limit 100""",
    # q55: brand revenue for one manager-month shape
    "ds55": """
select i.i_brand_id, i.i_brand, sum(ss.ss_ext_sales_price) as ext_price
from store_sales ss
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on i.i_item_sk = ss.ss_item_sk
where d.d_moy = 11 and d.d_year = 1999 and i.i_manufact_id < 40
group by i.i_brand_id, i.i_brand
order by ext_price desc, i.i_brand_id
limit 100""",
    # q67 family: rank categories' sales within state via a windowed CTE
    "ds67": """
with sales as (
  select s.s_state as s_state, i.i_category as i_category,
         sum(ss.ss_net_profit) as profit
  from store_sales ss
  join store s on s.s_store_sk = ss.ss_store_sk
  join item i on i.i_item_sk = ss.ss_item_sk
  group by s.s_state, i.i_category
)
select s_state, i_category, profit,
       rank() over (partition by s_state order by profit desc) as rk
from sales
order by s_state, rk, i_category""",
    # q7 family: average report over a category/year slice
    "ds7": """
select i.i_item_sk, avg(ss.ss_quantity) as agg1,
       avg(ss.ss_sales_price) as agg2, avg(ss.ss_ext_sales_price) as agg3
from store_sales ss
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on i.i_item_sk = ss.ss_item_sk
where d.d_year = 2001 and i.i_category = 'Books'
group by i.i_item_sk
order by i.i_item_sk
limit 100""",
    # q73 family: frequent buyers via a HAVING derived table joined back
    "ds73": """
select c.c_last_name, c.c_first_name, dj.cnt
from (
  select ss.ss_customer_sk as ss_customer_sk, count(*) as cnt
  from store_sales ss
  join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
  where d.d_year = 2000
  group by ss.ss_customer_sk
  having count(*) > 8
) as dj
join customer c on c.c_customer_sk = dj.ss_customer_sk
order by dj.cnt desc, c.c_last_name, c.c_first_name
limit 50""",
}


def _frames(raw):
    return {k: pd.DataFrame(v) for k, v in raw.items()}


def oracle(name: str, raw: dict) -> pd.DataFrame:
    f = _frames(raw)
    ss, d, i, s = f["store_sales"], f["date_dim"], f["item"], f["store"]
    j = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk") \
          .merge(i, left_on="ss_item_sk", right_on="i_item_sk")
    if name == "ds3":
        x = j[(j.i_manufact_id == 28) & (j.d_moy == 12)]
        g = x.groupby(["d_year", "i_brand_id", "i_brand"],
                      as_index=False).ss_ext_sales_price.sum()
        g = g.rename(columns={"ss_ext_sales_price": "sum_agg"})
        return g.sort_values(["d_year", "sum_agg", "i_brand_id"],
                             ascending=[True, False, True],
                             kind="stable").head(100)
    if name in ("ds42", "ds52", "ds55"):
        if name == "ds55":
            x = j[(j.d_moy == 11) & (j.d_year == 1999)
                  & (j.i_manufact_id < 40)]
            g = x.groupby(["i_brand_id", "i_brand"],
                          as_index=False).ss_ext_sales_price.sum()
            return g.sort_values(["ss_ext_sales_price", "i_brand_id"],
                                 ascending=[False, True],
                                 kind="stable").head(100)
        x = j[(j.d_moy == 11) & (j.d_year == 2000)]
        if name == "ds42":
            g = x.groupby(["d_year", "i_category_id", "i_category"],
                          as_index=False).ss_ext_sales_price.sum()
            return g.sort_values(
                ["ss_ext_sales_price", "d_year", "i_category_id",
                 "i_category"], ascending=[False, True, True, True],
                kind="stable").head(100)[
                ["d_year", "i_category_id", "i_category",
                 "ss_ext_sales_price"]]
        g = x.groupby(["d_year", "i_brand_id", "i_brand"],
                      as_index=False).ss_ext_sales_price.sum()
        return g.sort_values(["d_year", "ss_ext_sales_price", "i_brand_id"],
                             ascending=[True, False, True],
                             kind="stable").head(100)
    if name == "ds67":
        js = ss.merge(s, left_on="ss_store_sk", right_on="s_store_sk") \
               .merge(i, left_on="ss_item_sk", right_on="i_item_sk")
        g = js.groupby(["s_state", "i_category"],
                       as_index=False).ss_net_profit.sum() \
              .rename(columns={"ss_net_profit": "profit"})
        g["rk"] = g.groupby("s_state").profit.rank(
            method="min", ascending=False).astype(np.int64)
        return g.sort_values(["s_state", "rk", "i_category"],
                             kind="stable")
    if name == "ds7":
        x = j[(j.d_year == 2001) & (j.i_category == "Books")]
        g = x.groupby("i_item_sk", as_index=False).agg(
            agg1=("ss_quantity", "mean"), agg2=("ss_sales_price", "mean"),
            agg3=("ss_ext_sales_price", "mean"))
        return g.sort_values("i_item_sk").head(100)
    if name == "ds73":
        c = f["customer"]
        x = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk")
        x = x[x.d_year == 2000]
        g = x.groupby("ss_customer_sk").size().reset_index(name="cnt")
        g = g[g.cnt > 8]
        m = g.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
        m = m.sort_values(["cnt", "c_last_name", "c_first_name"],
                          ascending=[False, True, True],
                          kind="stable").head(50)
        return m[["c_last_name", "c_first_name", "cnt"]]
    raise KeyError(name)

