"""TPC-DS query subset + pandas oracles.

Standard TPC-DS query shapes (the reference templates live in
`ydb/library/benchmarks/queries/tpcds/`): star joins over store_sales
with date/item/store dimensions, grouped reports with LIMIT, and the
rank-over-partition window pattern of the q67 family.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

QUERIES = {
    # q3: brand report for one manufacturer in December
    "ds3": """
select d.d_year, i.i_brand_id, i.i_brand, sum(ss.ss_ext_sales_price) as sum_agg
from store_sales ss
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on i.i_item_sk = ss.ss_item_sk
where i.i_manufact_id = 28 and d.d_moy = 12
group by d.d_year, i.i_brand_id, i.i_brand
order by d.d_year, sum_agg desc, i.i_brand_id
limit 100""",
    # q42: category report for one year/month
    "ds42": """
select d.d_year, i.i_category_id, i.i_category, sum(ss.ss_ext_sales_price) as s
from store_sales ss
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on i.i_item_sk = ss.ss_item_sk
where d.d_moy = 11 and d.d_year = 2000
group by d.d_year, i.i_category_id, i.i_category
order by s desc, d.d_year, i.i_category_id, i.i_category
limit 100""",
    # q52: brand report for one year/month
    "ds52": """
select d.d_year, i.i_brand_id, i.i_brand, sum(ss.ss_ext_sales_price) as ext_price
from store_sales ss
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on i.i_item_sk = ss.ss_item_sk
where d.d_moy = 11 and d.d_year = 2000
group by d.d_year, i.i_brand_id, i.i_brand
order by d.d_year, ext_price desc, i.i_brand_id
limit 100""",
    # q55: brand revenue for one manager-month shape
    "ds55": """
select i.i_brand_id, i.i_brand, sum(ss.ss_ext_sales_price) as ext_price
from store_sales ss
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on i.i_item_sk = ss.ss_item_sk
where d.d_moy = 11 and d.d_year = 1999 and i.i_manufact_id < 40
group by i.i_brand_id, i.i_brand
order by ext_price desc, i.i_brand_id
limit 100""",
    # q67 family: rank categories' sales within state via a windowed CTE
    "ds67": """
with sales as (
  select s.s_state as s_state, i.i_category as i_category,
         sum(ss.ss_net_profit) as profit
  from store_sales ss
  join store s on s.s_store_sk = ss.ss_store_sk
  join item i on i.i_item_sk = ss.ss_item_sk
  group by s.s_state, i.i_category
)
select s_state, i_category, profit,
       rank() over (partition by s_state order by profit desc) as rk
from sales
order by s_state, rk, i_category""",
    # q7: demographic/promotion average report (official form)
    "ds7": """
select i.i_item_id, avg(ss.ss_quantity) as agg1,
       avg(ss.ss_list_price) as agg2, avg(ss.ss_coupon_amt) as agg3,
       avg(ss.ss_sales_price) as agg4
from store_sales ss
join customer_demographics cd on cd.cd_demo_sk = ss.ss_cdemo_sk
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on i.i_item_sk = ss.ss_item_sk
join promotion p on p.p_promo_sk = ss.ss_promo_sk
where cd.cd_gender = 'M' and cd.cd_marital_status = 'S'
  and cd.cd_education_status = 'College'
  and (p.p_channel_email = 'N' or p.p_channel_event = 'N')
  and d.d_year = 2000
group by i.i_item_id
order by i.i_item_id
limit 100""",
    # q19: brand report where the customer's zip differs from the store's
    # (zip prefixes carried as ints; the reference compares substr(zip,1,5))
    "ds19": """
select i.i_brand_id, i.i_brand, i.i_manufact_id, i.i_manufact,
       sum(ss.ss_ext_sales_price) as ext_price
from store_sales ss
join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
join item i on i.i_item_sk = ss.ss_item_sk
join customer c on c.c_customer_sk = ss.ss_customer_sk
join customer_address ca on ca.ca_address_sk = c.c_current_addr_sk
join store s on s.s_store_sk = ss.ss_store_sk
where d.d_moy = 11 and d.d_year = 1999 and i.i_manager_id = 8
  and ca.ca_zip_num <> s.s_zip_num
group by i.i_brand_id, i.i_brand, i.i_manufact_id, i.i_manufact
order by ext_price desc, i.i_brand_id, i.i_manufact_id
limit 100""",
    # q33 family: per-manufacturer category sales across channels,
    # UNION ALL re-aggregated (two channels in this schema subset)
    "ds33": """
with ssr as (
  select i.i_manufact_id as i_manufact_id,
         sum(ss.ss_ext_sales_price) as total_sales
  from store_sales ss
  join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
  join item i on i.i_item_sk = ss.ss_item_sk
  where i.i_category = 'Electronics' and d.d_year = 1998 and d.d_moy = 5
  group by i.i_manufact_id),
wsr as (
  select i.i_manufact_id as i_manufact_id,
         sum(ws.ws_ext_sales_price) as total_sales
  from web_sales ws
  join date_dim d on d.d_date_sk = ws.ws_sold_date_sk
  join item i on i.i_item_sk = ws.ws_item_sk
  where i.i_category = 'Electronics' and d.d_year = 1998 and d.d_moy = 5
  group by i.i_manufact_id)
select i_manufact_id, sum(total_sales) as total_sales
from (select * from ssr union all select * from wsr) as tmp
group by i_manufact_id
order by total_sales desc, i_manufact_id
limit 100""",
    # q59 family: week-over-week per-store day-of-week sales ratios
    # (CASE-pivoted weekly CTE self-joined at a 52-week offset)
    "ds59": """
with wss as (
  select d.d_week_seq as d_week_seq, ss.ss_store_sk as ss_store_sk,
         sum(case when d.d_day_name = 'Sunday'
             then ss.ss_sales_price end) as sun_sales,
         sum(case when d.d_day_name = 'Monday'
             then ss.ss_sales_price end) as mon_sales,
         sum(case when d.d_day_name = 'Friday'
             then ss.ss_sales_price end) as fri_sales
  from store_sales ss
  join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
  group by d.d_week_seq, ss.ss_store_sk)
select s.s_store_name, y.d_week_seq,
       y.sun_sales / x.sun_sales as r1,
       y.mon_sales / x.mon_sales as r2,
       y.fri_sales / x.fri_sales as r3
from wss y
join wss x on y.ss_store_sk = x.ss_store_sk
join store s on s.s_store_sk = y.ss_store_sk
where y.d_week_seq >= 20 and y.d_week_seq <= 25
  and x.d_week_seq = y.d_week_seq + 52
order by s.s_store_name, y.d_week_seq
limit 100""",
    # q65: items selling at <=10% of their store's average revenue
    "ds65": """
with sc as (
  select ss.ss_store_sk as ss_store_sk, ss.ss_item_sk as ss_item_sk,
         sum(ss.ss_sales_price) as revenue
  from store_sales ss group by ss.ss_store_sk, ss.ss_item_sk),
sb as (
  select sc.ss_store_sk as ss_store_sk, avg(sc.revenue) as ave
  from sc group by sc.ss_store_sk)
select s.s_store_name, i.i_item_id, sc.revenue
from sb
join sc on sc.ss_store_sk = sb.ss_store_sk
join store s on s.s_store_sk = sc.ss_store_sk
join item i on i.i_item_sk = sc.ss_item_sk
where sc.revenue <= 0.1 * sb.ave
order by s.s_store_name, i.i_item_id
limit 100""",
    # q88 family: store-hour traffic slots as scalar subqueries
    "ds88": """
select
 (select count(*) from store_sales ss
   join household_demographics hd on hd.hd_demo_sk = ss.ss_hdemo_sk
   join time_dim t on t.t_time_sk = ss.ss_sold_time_sk
   join store s on s.s_store_sk = ss.ss_store_sk
   where t.t_hour = 8 and t.t_minute >= 30 and hd.hd_dep_count = 4
     and s.s_store_name = 'store_1') as h8_30,
 (select count(*) from store_sales ss
   join household_demographics hd on hd.hd_demo_sk = ss.ss_hdemo_sk
   join time_dim t on t.t_time_sk = ss.ss_sold_time_sk
   join store s on s.s_store_sk = ss.ss_store_sk
   where t.t_hour = 9 and t.t_minute < 30 and hd.hd_dep_count = 4
     and s.s_store_name = 'store_1') as h9_00,
 (select count(*) from store_sales ss
   join household_demographics hd on hd.hd_demo_sk = ss.ss_hdemo_sk
   join time_dim t on t.t_time_sk = ss.ss_sold_time_sk
   join store s on s.s_store_sk = ss.ss_store_sk
   where t.t_hour = 9 and t.t_minute >= 30 and hd.hd_dep_count = 4
     and s.s_store_name = 'store_1') as h9_30,
 (select count(*) from store_sales ss
   join household_demographics hd on hd.hd_demo_sk = ss.ss_hdemo_sk
   join time_dim t on t.t_time_sk = ss.ss_sold_time_sk
   join store s on s.s_store_sk = ss.ss_store_sk
   where t.t_hour = 10 and t.t_minute < 30 and hd.hd_dep_count = 4
     and s.s_store_name = 'store_1') as h10_00""",
    # q96: half-hour store traffic count
    "ds96": """
select count(*) as cnt
from store_sales ss
join household_demographics hd on hd.hd_demo_sk = ss.ss_hdemo_sk
join time_dim t on t.t_time_sk = ss.ss_sold_time_sk
join store s on s.s_store_sk = ss.ss_store_sk
where t.t_hour = 20 and t.t_minute >= 30 and hd.hd_dep_count = 7
  and s.s_store_name = 'store_2'""",
    # q98: revenue share of each item within its class (the official
    # windowed-ratio form: the window sits inside the ratio expression)
    "ds98": """
with rev as (
  select i.i_item_id as i_item_id, i.i_class as i_class,
         i.i_category as i_category,
         sum(ss.ss_ext_sales_price) as itemrevenue
  from store_sales ss
  join item i on i.i_item_sk = ss.ss_item_sk
  join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
  where i.i_category in ('Sports', 'Books', 'Home') and d.d_year = 1999
    and d.d_moy >= 2 and d.d_moy <= 3
  group by i.i_item_id, i.i_class, i.i_category)
select i_item_id, i_class, i_category, itemrevenue,
       itemrevenue * 100 / sum(itemrevenue)
           over (partition by i_class) as revenueratio
from rev
order by i_category, i_class, i_item_id, itemrevenue, revenueratio
limit 100""",
    # q73 family: frequent buyers via a HAVING derived table joined back
    "ds73": """
select c.c_last_name, c.c_first_name, dj.cnt
from (
  select ss.ss_customer_sk as ss_customer_sk, count(*) as cnt
  from store_sales ss
  join date_dim d on d.d_date_sk = ss.ss_sold_date_sk
  where d.d_year = 2000
  group by ss.ss_customer_sk
  having count(*) > 8
) as dj
join customer c on c.c_customer_sk = dj.ss_customer_sk
order by dj.cnt desc, c.c_last_name, c.c_first_name
limit 50""",
}


def _frames(raw):
    return {k: pd.DataFrame(v) for k, v in raw.items()}


def oracle(name: str, raw: dict) -> pd.DataFrame:
    f = _frames(raw)
    ss, d, i, s = f["store_sales"], f["date_dim"], f["item"], f["store"]
    j = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk") \
          .merge(i, left_on="ss_item_sk", right_on="i_item_sk")
    if name == "ds3":
        x = j[(j.i_manufact_id == 28) & (j.d_moy == 12)]
        g = x.groupby(["d_year", "i_brand_id", "i_brand"],
                      as_index=False).ss_ext_sales_price.sum()
        g = g.rename(columns={"ss_ext_sales_price": "sum_agg"})
        return g.sort_values(["d_year", "sum_agg", "i_brand_id"],
                             ascending=[True, False, True],
                             kind="stable").head(100)
    if name in ("ds42", "ds52", "ds55"):
        if name == "ds55":
            x = j[(j.d_moy == 11) & (j.d_year == 1999)
                  & (j.i_manufact_id < 40)]
            g = x.groupby(["i_brand_id", "i_brand"],
                          as_index=False).ss_ext_sales_price.sum()
            return g.sort_values(["ss_ext_sales_price", "i_brand_id"],
                                 ascending=[False, True],
                                 kind="stable").head(100)
        x = j[(j.d_moy == 11) & (j.d_year == 2000)]
        if name == "ds42":
            g = x.groupby(["d_year", "i_category_id", "i_category"],
                          as_index=False).ss_ext_sales_price.sum()
            return g.sort_values(
                ["ss_ext_sales_price", "d_year", "i_category_id",
                 "i_category"], ascending=[False, True, True, True],
                kind="stable").head(100)[
                ["d_year", "i_category_id", "i_category",
                 "ss_ext_sales_price"]]
        g = x.groupby(["d_year", "i_brand_id", "i_brand"],
                      as_index=False).ss_ext_sales_price.sum()
        return g.sort_values(["d_year", "ss_ext_sales_price", "i_brand_id"],
                             ascending=[True, False, True],
                             kind="stable").head(100)
    if name == "ds67":
        js = ss.merge(s, left_on="ss_store_sk", right_on="s_store_sk") \
               .merge(i, left_on="ss_item_sk", right_on="i_item_sk")
        g = js.groupby(["s_state", "i_category"],
                       as_index=False).ss_net_profit.sum() \
              .rename(columns={"ss_net_profit": "profit"})
        g["rk"] = g.groupby("s_state").profit.rank(
            method="min", ascending=False).astype(np.int64)
        return g.sort_values(["s_state", "rk", "i_category"],
                             kind="stable")
    if name == "ds7":
        cd, p = f["customer_demographics"], f["promotion"]
        x = j.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk") \
             .merge(p, left_on="ss_promo_sk", right_on="p_promo_sk")
        x = x[(x.cd_gender == "M") & (x.cd_marital_status == "S")
              & (x.cd_education_status == "College")
              & ((x.p_channel_email == "N") | (x.p_channel_event == "N"))
              & (x.d_year == 2000)]
        g = x.groupby("i_item_id", as_index=False).agg(
            agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
            agg3=("ss_coupon_amt", "mean"), agg4=("ss_sales_price", "mean"))
        return g.sort_values("i_item_id").head(100)
    if name == "ds19":
        c, ca = f["customer"], f["customer_address"]
        x = j.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk") \
             .merge(ca, left_on="c_current_addr_sk",
                    right_on="ca_address_sk") \
             .merge(s, left_on="ss_store_sk", right_on="s_store_sk")
        x = x[(x.d_moy == 11) & (x.d_year == 1999) & (x.i_manager_id == 8)
              & (x.ca_zip_num != x.s_zip_num)]
        g = x.groupby(["i_brand_id", "i_brand", "i_manufact_id",
                       "i_manufact"], as_index=False) \
             .ss_ext_sales_price.sum() \
             .rename(columns={"ss_ext_sales_price": "ext_price"})
        return g.sort_values(["ext_price", "i_brand_id", "i_manufact_id"],
                             ascending=[False, True, True],
                             kind="stable").head(100)[
            ["i_brand_id", "i_brand", "i_manufact_id", "i_manufact",
             "ext_price"]]
    if name == "ds33":
        ws = f["web_sales"]
        xs = j[(j.i_category == "Electronics") & (j.d_year == 1998)
               & (j.d_moy == 5)]
        ssr = xs.groupby("i_manufact_id", as_index=False) \
                .ss_ext_sales_price.sum() \
                .rename(columns={"ss_ext_sales_price": "total_sales"})
        xw = ws.merge(d, left_on="ws_sold_date_sk", right_on="d_date_sk") \
               .merge(i, left_on="ws_item_sk", right_on="i_item_sk")
        xw = xw[(xw.i_category == "Electronics") & (xw.d_year == 1998)
                & (xw.d_moy == 5)]
        wsr = xw.groupby("i_manufact_id", as_index=False) \
                .ws_ext_sales_price.sum() \
                .rename(columns={"ws_ext_sales_price": "total_sales"})
        u = pd.concat([ssr, wsr], ignore_index=True)
        g = u.groupby("i_manufact_id", as_index=False).total_sales.sum()
        return g.sort_values(["total_sales", "i_manufact_id"],
                             ascending=[False, True],
                             kind="stable").head(100)
    if name == "ds59":
        x = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk")
        def dow(day):
            v = x.ss_sales_price.where(x.d_day_name == day)
            return v
        x = x.assign(sun=dow("Sunday"), mon=dow("Monday"),
                     fri=dow("Friday"))
        wss = x.groupby(["d_week_seq", "ss_store_sk"], as_index=False) \
               .agg(sun_sales=("sun", "sum"), mon_sales=("mon", "sum"),
                    fri_sales=("fri", "sum"),
                    sun_n=("sun", "count"), mon_n=("mon", "count"),
                    fri_n=("fri", "count"))
        for col in ("sun", "mon", "fri"):
            wss[f"{col}_sales"] = wss[f"{col}_sales"] \
                .where(wss[f"{col}_n"] > 0)
        y = wss[(wss.d_week_seq >= 20) & (wss.d_week_seq <= 25)]
        xx = wss.copy()
        m = y.merge(xx, left_on=["ss_store_sk"], right_on=["ss_store_sk"],
                    suffixes=("_y", "_x"))
        m = m[m.d_week_seq_x == m.d_week_seq_y + 52]
        m = m.merge(s, left_on="ss_store_sk", right_on="s_store_sk")
        out = pd.DataFrame({
            "s_store_name": m.s_store_name,
            "d_week_seq": m.d_week_seq_y,
            "r1": m.sun_sales_y / m.sun_sales_x,
            "r2": m.mon_sales_y / m.mon_sales_x,
            "r3": m.fri_sales_y / m.fri_sales_x})
        return out.sort_values(["s_store_name", "d_week_seq"],
                               kind="stable").head(100)
    if name == "ds65":
        sc = ss.groupby(["ss_store_sk", "ss_item_sk"], as_index=False) \
               .ss_sales_price.sum() \
               .rename(columns={"ss_sales_price": "revenue"})
        sb = sc.groupby("ss_store_sk", as_index=False).revenue.mean() \
               .rename(columns={"revenue": "ave"})
        m = sc.merge(sb, on="ss_store_sk")
        m = m[m.revenue <= 0.1 * m.ave]
        m = m.merge(s, left_on="ss_store_sk", right_on="s_store_sk") \
             .merge(i, left_on="ss_item_sk", right_on="i_item_sk")
        return m.sort_values(["s_store_name", "i_item_id"],
                             kind="stable").head(100)[
            ["s_store_name", "i_item_id", "revenue"]]
    if name in ("ds88", "ds96"):
        hd, t = f["household_demographics"], f["time_dim"]
        x = ss.merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk") \
              .merge(t, left_on="ss_sold_time_sk", right_on="t_time_sk") \
              .merge(s, left_on="ss_store_sk", right_on="s_store_sk")
        if name == "ds96":
            n = len(x[(x.t_hour == 20) & (x.t_minute >= 30)
                      & (x.hd_dep_count == 7)
                      & (x.s_store_name == "store_2")])
            return pd.DataFrame({"cnt": [n]})
        base = x[(x.hd_dep_count == 4) & (x.s_store_name == "store_1")]
        def slot(h, half):
            mm = base[(base.t_hour == h)
                      & ((base.t_minute >= 30) if half
                         else (base.t_minute < 30))]
            return len(mm)
        return pd.DataFrame({"h8_30": [slot(8, True)],
                             "h9_00": [slot(9, False)],
                             "h9_30": [slot(9, True)],
                             "h10_00": [slot(10, False)]})
    if name == "ds98":
        x = j[j.i_category.isin(["Sports", "Books", "Home"])
              & (j.d_year == 1999) & (j.d_moy >= 2) & (j.d_moy <= 3)]
        g = x.groupby(["i_item_id", "i_class", "i_category"],
                      as_index=False).ss_ext_sales_price.sum() \
             .rename(columns={"ss_ext_sales_price": "itemrevenue"})
        g["classrevenue"] = g.groupby("i_class").itemrevenue \
                             .transform("sum")
        g["revenueratio"] = g.itemrevenue * 100 / g.classrevenue
        g = g.sort_values(["i_category", "i_class", "i_item_id",
                           "itemrevenue", "revenueratio"],
                          kind="stable").head(100)
        return g[["i_item_id", "i_class", "i_category", "itemrevenue",
                  "revenueratio"]]
    if name == "ds73":
        c = f["customer"]
        x = ss.merge(d, left_on="ss_sold_date_sk", right_on="d_date_sk")
        x = x[x.d_year == 2000]
        g = x.groupby("ss_customer_sk").size().reset_index(name="cnt")
        g = g[g.cnt > 8]
        m = g.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk")
        m = m.sort_values(["cnt", "c_last_name", "c_first_name"],
                          ascending=[False, True, True],
                          kind="stable").head(50)
        return m[["c_last_name", "c_first_name", "cnt"]]
    raise KeyError(name)

